"""Async load generator for the serving engine — the Locust/AsyncIO leg.

The reference claims "Benchmarking: Locust, AsyncIO" (``README.md:11,17``)
with ``locust``/``aiohttp`` pinned but unused (``requirements.txt:35-36``).
This is that capability, stdlib-only: an asyncio closed-loop (N concurrent
users, Locust's model) or open-loop (Poisson arrivals at a target QPS)
driver speaking HTTP/1.1 over raw asyncio streams, measuring what serving
benchmarks actually need:

* per-request latency and output token counts
* TTFT (time to first streamed token) and TPOT (per-token latency) when
  ``stream=True``
* aggregate request/output-token throughput + p50/p90/p99 percentiles

Report schema feeds ``scripts/benchmark_serving.py`` and the CSV/plot
tooling (the serving analog of ``results/training_metrics.csv``).
"""

from __future__ import annotations

import asyncio
import json
import random
import re
import time
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from dlti_tpu.benchmarks.traces import TraceEvent, read_trace, write_trace


@dataclass
class LoadGenConfig:
    host: str = "127.0.0.1"
    port: int = 8000
    num_requests: int = 64
    concurrency: int = 8            # closed-loop users
    qps: Optional[float] = None     # set => open-loop Poisson arrivals
    stream: bool = True             # measure TTFT via SSE
    max_tokens: int = 64
    temperature: float = 0.0
    prompt: str = "Write a function that reverses a linked list."
    prompts: Tuple[str, ...] = ()   # optional pool; falls back to `prompt`
    chat: bool = False
    timeout_s: float = 300.0
    seed: int = 0
    # After the run, scrape the server's /metrics and attach its ON-ENGINE
    # request-lifecycle histograms (TTFT/TPOT/queue time) to the report —
    # the engine's own view of the latencies this loadgen measures from
    # outside, so client-vs-server skew (network, HTTP framing, queueing
    # before admission) is visible in one report. Off by default: the
    # target may not expose dlti_* metrics.
    scrape_server_metrics: bool = False
    # After the run, scrape the server's /debug/vars time-series ring and
    # record the watchdog alert counters + the PEAK gateway queue depth
    # over the run (the ring sees the peak; a point-in-time scrape at run
    # end would not) — so chaos/regression runs fail loudly when the
    # server's own watchdog fired. Best-effort like the /metrics scrape.
    scrape_debug_vars: bool = True
    # Multi-tenant workload: > 0 spreads requests round-robin over
    # synthetic tenants "tenant-0".."tenant-N-1" via the X-Tenant header
    # (the admission gateway's per-tenant rate limits and fair dequeue
    # see N distinct principals). 0 = no tenant header.
    tenants: int = 0
    # Priority workload mix, "interactive:0.8,batch:0.2" — each request
    # draws its class from this distribution (seeded) and sends it in the
    # body. "" = no priority field (server default class).
    priority_mix: str = ""
    # Per-request queued-deadline (seconds) sent as body deadline_s when
    # > 0; a gateway sheds past-deadline queued requests with 503.
    deadline_s: float = 0.0
    # Recurring-session (chat-shaped) workload: sessions > 0 switches the
    # driver to N concurrent sessions of `turns` requests each. Every
    # session replays a shared system prompt plus its own GROWING history
    # (turn t's prompt is a strict extension of turn t-1's), sent with an
    # X-Session header so an affinity-routing gateway keeps the session
    # on one replica's warm prefix cache. `reuse_frac` is the fraction of
    # non-first turns that actually revisit the session; the rest issue
    # an unrelated cold prompt (one-off traffic mixed into the run).
    # num_requests is ignored in this mode (sessions * turns requests).
    sessions: int = 0
    turns: int = 4
    reuse_frac: float = 1.0
    # Multi-LoRA workload: > 0 tags each request with an X-Adapter header
    # drawn from N synthetic adapter names "adapter-0".."adapter-N-1"
    # (register them on the server first — scripts/serve.py --adapter or
    # POST /v1/adapters). adapter_mix picks the draw: "zipf" (weight
    # 1/(i+1) — the realistic skew that exercises pool eviction while the
    # hot adapters stay resident) or "uniform". 0 = no adapter header.
    adapters: int = 0
    adapter_mix: str = "zipf"
    # Mixed-interference workload: this fraction of requests (seeded draw)
    # carries a synthetic long prompt of ~long_prompt_tokens tokens instead
    # of the normal prompt — the disaggregation stressor. The report then
    # splits short-request decode TPOT by whether a long prefill was
    # concurrently in flight (the `interference` section) and adds
    # "long_prompt"/"short_prompt" per_class entries. 0.0 = off.
    long_prompt_frac: float = 0.0
    long_prompt_tokens: int = 512
    # Trace replay / capture (benchmarks.traces, dlti-trace/1 JSONL).
    # `trace` replays a recorded workload: each event fires at its
    # recorded arrival offset, and tenant / priority / session / adapter
    # / prompt+output lengths / deadline all come from the event
    # (num_requests, qps, tenants, priority_mix are ignored; concurrency
    # still caps in-flight). `record_trace` writes every request THIS
    # run submitted (any drive mode, replay included) back out as a
    # trace file, so live runs become replayable fixtures.
    trace: str = ""
    record_trace: str = ""


@dataclass
class RequestRecord:
    start: float
    end: float = 0.0
    first_token: Optional[float] = None
    output_tokens: int = 0
    ok: bool = False
    error: str = ""
    status: int = 0          # HTTP status (0 = transport failure)
    tenant: str = ""
    priority: str = ""
    # Recurring-session mode: which session (if any) and whether this
    # request replayed a warm, previously-sent prefix (turn >= 1 of a
    # session) vs a cold first-touch prompt.
    session: str = ""
    warm: bool = False
    # Mixed-interference mode: this request carried the synthetic long
    # prompt (its prefill is the interference source, not a victim).
    long: bool = False
    # Multi-LoRA mode: the adapter name this request was tagged with.
    adapter: str = ""
    # Server-side critical-path breakdown (the response's "phases"
    # object: gateway queue, engine queue, tier restore, prefill,
    # failover, decode — telemetry.ledger); empty when the server
    # predates it or the request failed.
    phases: dict = field(default_factory=dict)
    # Replica-lifecycle visibility (serving.lifecycle): how many times
    # this request's KV was live-migrated between replicas mid-flight,
    # and how many failover/preempt resubmissions it survived. 0 when
    # the server predates the fields or the fleet stayed healthy.
    migrations: int = 0
    retries: int = 0
    # Distributed-trace context (telemetry.distributed_trace): the id
    # minted at admission and carried across every process this request
    # touched; joins this client-side record to the server's merged
    # /debug/trace?request_id= timeline. "" when the server predates it.
    trace_id: str = ""
    request_id: str = ""     # server-assigned id (the timeline's key)

    @property
    def shed(self) -> bool:
        """Load intentionally refused by the server (gateway 429 queue
        bound / rate limit, 503 drain or queued-deadline shed) — reported
        separately from real errors."""
        return self.status in (429, 503)

    @property
    def latency(self) -> float:
        return self.end - self.start

    @property
    def ttft(self) -> Optional[float]:
        return None if self.first_token is None else self.first_token - self.start


@dataclass
class LoadReport:
    num_requests: int
    num_ok: int
    duration_s: float
    requests_per_s: float
    output_tokens_per_s: float
    latency_p50_s: float
    latency_p90_s: float
    latency_p99_s: float
    ttft_p50_s: float = 0.0
    ttft_p90_s: float = 0.0
    ttft_p99_s: float = 0.0
    tpot_mean_ms: float = 0.0
    # Tail-of-the-tail latency (p99.9) — the SLO percentile a
    # self-healing fleet is judged on: a single quarantine/migration
    # event lands here long before it moves p99.
    ttft_p999_s: float = 0.0
    tpot_p999_ms: float = 0.0
    # Replica-lifecycle disturbance totals over the run: live KV
    # migrations and failover resubmissions the served requests
    # reported (RequestRecord.migrations / .retries).
    migrations_total: int = 0
    retries_total: int = 0
    # Gateway shed accounting: 429/503 refusals are deliberate
    # load-shedding, counted apart from num_ok and from real errors.
    num_shed: int = 0
    shed_rate: float = 0.0
    # Per-priority-class latency breakdown ({class: {count, ok, shed,
    # ttft_p50_s, ttft_p90_s, ttft_p99_s, tpot_mean_ms, latency_p50_s,
    # latency_p99_s}}); empty without a priority mix.
    per_class: dict = field(default_factory=dict)
    errors: List[str] = field(default_factory=list)
    # Server-side histogram summaries ({metric: {count, sum, mean}}) when
    # cfg.scrape_server_metrics is set; empty otherwise.
    server_histograms: dict = field(default_factory=dict)
    # Server watchdog verdict from the end-of-run /debug/vars scrape:
    # {rule: count} of alerts the SERVER's anomaly watchdog fired, and the
    # peak gateway queue depth its time-series ring observed. Empty/0 when
    # the scrape is off, the route is absent, or nothing fired.
    watchdog_alerts: dict = field(default_factory=dict)
    peak_queue_depth: float = 0.0
    # Recurring-session mode: cold (first-touch) vs warm (repeat-prefix)
    # TTFT percentiles — the tiered-prefix-cache headline — plus the
    # server's own prefix-cache hit rate scraped from /stats at run end
    # ((cached + restored) / (cached + restored + prefilled) tokens;
    # 0.0 when the scrape fails or the server runs without the cache).
    num_cold: int = 0
    num_warm: int = 0
    cold_ttft_p50_s: float = 0.0
    cold_ttft_p90_s: float = 0.0
    warm_ttft_p50_s: float = 0.0
    warm_ttft_p90_s: float = 0.0
    cache_hit_rate: float = 0.0
    # Multi-LoRA mode (cfg.adapters > 0): per-adapter latency breakdown
    # ({adapter: {count, ok, shed, ttft/latency percentiles, tpot}} — the
    # _class_summary schema), plus the server's own adapter-pool hit rate
    # scraped from /metrics at run end (pool_hits / (hits + misses); 0.0
    # when the scrape fails or the server runs without a pool).
    per_adapter: dict = field(default_factory=dict)
    adapter_pool_hit_rate: float = 0.0
    # Mixed-interference mode (long_prompt_frac > 0): decode-TPOT p99 of
    # SHORT requests split by whether a long prompt's prefill window
    # overlapped their decode window — the prefill→decode interference a
    # disaggregated server is supposed to remove. Empty when the mode is
    # off or one side has no samples.
    interference: dict = field(default_factory=dict)
    # Critical-path decomposition (goodput-ledger era): mean seconds per
    # server-reported phase (gateway queue, engine queue, tier restore,
    # prefill, failover, decode) over all ok requests, and the cold vs
    # warm split — the "warm TTFT is lower BECAUSE restore replaced
    # prefill" evidence, not just the headline percentiles. Empty when
    # the server doesn't report phases.
    phase_means: dict = field(default_factory=dict)
    cold_phases: dict = field(default_factory=dict)
    warm_phases: dict = field(default_factory=dict)
    # End-of-run HBM attribution scraped from the server's /debug/memory
    # (telemetry.memledger): source, bytes_in_use, peak/untracked bytes
    # and the per-owner map — "did this load level fit, and with how much
    # headroom" alongside the latency numbers. Empty when the scrape is
    # off, the route is absent, or the server's ledger is disabled.
    memory: dict = field(default_factory=dict)
    # Multi-process fleet federation cross-check (serving.fleet): when
    # the end-of-run /metrics scrape finds per-worker federated series
    # (dlti_fleet_w{i}_requests, ...), sum each counter across workers
    # and compare against the gateway-level dlti_<key> total — the two
    # are computed from the same per-worker snapshots, so any delta
    # means the federation lost or double-counted a worker (e.g. across
    # a respawn). {"per_worker": {id: {key: v}}, "checks": {key:
    # {per_worker_sum, fleet_total, delta}}, "consistent": bool, plus
    # the fleet liveness/respawn counters}. Empty against a
    # single-process server.
    fleet_federation: dict = field(default_factory=dict)
    # Speculative-decode economics scraped from /metrics at run end
    # (engine.SPEC_METRIC_NAMES): proposed/accepted draft-token totals,
    # paused slot-rounds, the cumulative acceptance rate, and the draft
    # length the adaptive ladder last dispatched. All zeros against a
    # server running without --speculative (the series are schema-stable
    # and always exposed); {} only when the scrape itself fails.
    spec: dict = field(default_factory=dict)
    # SLO cross-check (telemetry.slo via GET /debug/slo): the server's
    # per-(objective, class) compliance / error-budget / breaching state
    # at run end, the client's own compliance recomputed from this run's
    # records at the server-reported (bucket-snapped) thresholds, and
    # per-pair agreement deltas — the server's SLO engine audited from
    # outside. Empty when the scrape is off, the route is absent, or the
    # server runs without --slo.
    slo: dict = field(default_factory=dict)
    # Distributed-trace coverage (telemetry.distributed_trace): of a
    # bounded sample of ok requests, the fraction whose server-side
    # merged timeline (GET /debug/trace?request_id=) contains the
    # gateway, prefill AND decode legs — span federation audited
    # end-to-end from the client. 0.0 when the scrape is off, tracing is
    # disabled server-side, or the server predates trace ids.
    trace_coverage: float = 0.0
    # The raw per-request records, for programmatic callers (the fleet
    # trace drill samples a migrated request's id + client latency to
    # cross-check the server's /debug/trace timeline against). Excluded
    # from to_dict(): the JSON report stays a summary, not a request log.
    records: List[RequestRecord] = field(default_factory=list)

    def to_dict(self) -> dict:
        import dataclasses

        d = dataclasses.asdict(self)
        d.pop("records", None)
        return d


def _percentile(xs: List[float], p: float) -> float:
    """Linear interpolation between closest ranks (numpy's default
    method). Nearest-rank rounding is too coarse for tail percentiles at
    bench-sized sample counts — at n=100, p99 and p99.9 both snapped to
    the max sample, hiding a tail regression until it moved p90."""
    if not xs:
        return 0.0
    xs = sorted(xs)
    k = max(0.0, p / 100.0 * (len(xs) - 1))
    f = int(k)
    if f >= len(xs) - 1:
        return xs[-1]
    return xs[f] + (k - f) * (xs[f + 1] - xs[f])


async def _iter_body(reader, headers: dict, timeout_s: float):
    """Yield decoded body byte chunks, honoring Transfer-Encoding: chunked
    (RFC 9112 §7.1) so framing never corrupts the payload — against servers
    beyond the in-repo one (which uses Content-Length), chunk-size lines
    would otherwise interleave with the JSON/SSE bytes."""
    if "chunked" in headers.get("transfer-encoding", "").lower():
        while True:
            size_line = await asyncio.wait_for(reader.readline(), timeout_s)
            if not size_line:
                return  # truncated stream
            try:
                size = int(size_line.split(b";")[0].strip() or b"0", 16)
            except ValueError:
                return
            if size == 0:
                # Drain trailer section up to the blank line.
                while True:
                    t = await asyncio.wait_for(reader.readline(), timeout_s)
                    if t in (b"\r\n", b"\n", b""):
                        return
            data = await asyncio.wait_for(reader.readexactly(size), timeout_s)
            yield data
            await asyncio.wait_for(reader.readline(), timeout_s)  # CRLF
    elif "content-length" in headers:
        n = int(headers["content-length"])
        if n > 0:
            yield await asyncio.wait_for(reader.readexactly(n), timeout_s)
    else:
        while True:
            chunk = await asyncio.wait_for(reader.read(4096), timeout_s)
            if not chunk:
                return
            yield chunk


async def _http_post_sse(host: str, port: int, path: str, body: dict,
                         rec: RequestRecord, timeout_s: float,
                         extra_headers: Optional[dict] = None) -> None:
    """POST; if the response is SSE, count data chunks and stamp TTFT."""
    writer = None
    try:
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, port), timeout_s
        )
        payload = json.dumps(body).encode()
        extra = "".join(f"{k}: {v}\r\n"
                        for k, v in (extra_headers or {}).items())
        req = (f"POST {path} HTTP/1.1\r\nHost: {host}:{port}\r\n"
               f"Content-Type: application/json\r\n{extra}"
               f"Content-Length: {len(payload)}\r\nConnection: close\r\n\r\n"
               ).encode() + payload
        writer.write(req)
        await writer.drain()

        status_line = await asyncio.wait_for(reader.readline(), timeout_s)
        parts = status_line.split()
        if len(parts) < 2:
            rec.error = f"malformed/empty status line: {status_line[:80]!r}"
            return
        status = int(parts[1])
        rec.status = status
        headers = {}
        while True:
            line = await asyncio.wait_for(reader.readline(), timeout_s)
            if line in (b"\r\n", b"\n", b""):
                break
            k, _, v = line.decode().partition(":")
            headers[k.strip().lower()] = v.strip()

        if status != 200:
            raw = b"".join([c async for c in _iter_body(reader, headers, timeout_s)])
            rec.error = f"HTTP {status}: {raw[:200].decode(errors='replace')}"
            return

        if headers.get("content-type", "").startswith("text/event-stream"):
            # SSE: scan dechunked stream for `data:` lines.
            n_data = 0
            usage_tokens = 0
            buf = b""
            async for chunk in _iter_body(reader, headers, timeout_s):
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    s = line.strip()
                    if not s.startswith(b"data:"):
                        continue
                    data = s[5:].strip()
                    if data == b"[DONE]":
                        rec.ok = True
                        continue
                    try:
                        obj = json.loads(data)
                    except json.JSONDecodeError:
                        continue
                    choices = obj.get("choices") or [{}]
                    delta = choices[0].get("delta", {}).get("content") \
                        if "delta" in choices[0] else choices[0].get("text")
                    if delta:
                        if rec.first_token is None:
                            rec.first_token = time.monotonic()
                        n_data += 1
                    if obj.get("usage"):
                        usage_tokens = int(
                            obj["usage"].get("completion_tokens", 0))
                    if obj.get("phases"):
                        rec.phases = dict(obj["phases"])
                    if "migrations" in obj:
                        rec.migrations = int(obj.get("migrations") or 0)
                    if "retries" in obj:
                        rec.retries = int(obj.get("retries") or 0)
                    if obj.get("trace_id"):
                        rec.trace_id = str(obj["trace_id"])
                    if obj.get("id"):
                        rec.request_id = str(obj["id"])
            # Prefer the final chunk's usage (token-accurate; our server
            # always sends it — stream_options.include_usage semantics).
            # Fallback: SSE event count, the stream's visible progress
            # unit (!= tokens when multi-step decode batches per sync).
            rec.output_tokens = usage_tokens if usage_tokens else n_data
            rec.ok = rec.ok or n_data > 0 or usage_tokens > 0
        else:
            raw = b"".join([c async for c in _iter_body(reader, headers, timeout_s)])
            obj = json.loads(raw)
            usage = obj.get("usage", {})
            rec.output_tokens = int(usage.get("completion_tokens", 0))
            if obj.get("phases"):
                rec.phases = dict(obj["phases"])
            rec.migrations = int(obj.get("migrations") or 0)
            rec.retries = int(obj.get("retries") or 0)
            rec.trace_id = str(obj.get("trace_id") or "")
            rec.request_id = str(obj.get("id") or "")
            rec.ok = True
    except Exception as e:  # noqa: BLE001 — one request's failure is a
        # recorded data point, never a crash of the whole load test.
        rec.error = f"{type(e).__name__}: {e}"
    finally:
        rec.end = time.monotonic()
        if writer is not None:
            writer.close()
            try:
                await writer.wait_closed()
            except OSError:
                pass


async def _scrape_histograms(host: str, port: int,
                             timeout_s: float = 10.0) -> dict:
    """GET /metrics and fold Prometheus histogram series into
    ``{name: {count, sum, mean}}``. Best-effort: any failure (no route,
    refused connection, unparseable body) returns ``{}`` — scraping must
    never fail a load test."""
    try:
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, port), timeout_s)
        req = (f"GET /metrics HTTP/1.1\r\nHost: {host}:{port}\r\n"
               f"Connection: close\r\n\r\n").encode()
        writer.write(req)
        await writer.drain()
        status_line = await asyncio.wait_for(reader.readline(), timeout_s)
        if b" 200 " not in status_line and not status_line.endswith(b" 200\r\n"):
            return {}
        headers: dict = {}
        while True:
            line = await asyncio.wait_for(reader.readline(), timeout_s)
            if line in (b"\r\n", b"\n", b""):
                break
            k, _, v = line.decode().partition(":")
            headers[k.strip().lower()] = v.strip()
        raw = b"".join([c async for c in _iter_body(reader, headers, timeout_s)])
        writer.close()
    except Exception:
        return {}
    out: dict = {}
    for line in raw.decode(errors="replace").splitlines():
        if line.startswith("#") or not line.strip():
            continue
        name, _, value = line.partition(" ")
        base = None
        if name.endswith("_sum"):
            base, key = name[:-4], "sum"
        elif name.endswith("_count"):
            base, key = name[:-6], "count"
        if base is None:
            continue
        try:
            v = float(value)
        except ValueError:
            continue
        out.setdefault(base, {})[key] = v
    hists = {}
    for base, d in out.items():
        if "count" in d and "sum" in d:
            n = d["count"]
            hists[base] = {"count": int(n), "sum": round(d["sum"], 6),
                           "mean": round(d["sum"] / n, 6) if n else 0.0}
    return hists


async def _http_get_json(host: str, port: int, path: str,
                         timeout_s: float = 10.0) -> Optional[dict]:
    """GET a JSON route over raw asyncio streams; None on any failure
    (scrapes must never fail a load test)."""
    try:
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, port), timeout_s)
        req = (f"GET {path} HTTP/1.1\r\nHost: {host}:{port}\r\n"
               f"Connection: close\r\n\r\n").encode()
        writer.write(req)
        await writer.drain()
        status_line = await asyncio.wait_for(reader.readline(), timeout_s)
        if b" 200 " not in status_line and \
                not status_line.endswith(b" 200\r\n"):
            return None
        headers: dict = {}
        while True:
            line = await asyncio.wait_for(reader.readline(), timeout_s)
            if line in (b"\r\n", b"\n", b""):
                break
            k, _, v = line.decode().partition(":")
            headers[k.strip().lower()] = v.strip()
        raw = b"".join([c async for c in _iter_body(reader, headers,
                                                    timeout_s)])
        writer.close()
        return json.loads(raw)
    except Exception:
        return None


def _watchdog_report(debug_vars: Optional[dict]) -> Tuple[dict, float]:
    """-> ({rule: alert_count}, peak gateway queue depth) from a
    /debug/vars snapshot (the ring holds the run's history, so the peak
    is the true peak, not the end-of-run value)."""
    if not debug_vars:
        return {}, 0.0
    alerts: dict = {}
    prefix = "dlti_watchdog_alerts_total"
    for k, v in (debug_vars.get("latest") or {}).items():
        if not k.startswith(prefix) or not v:
            continue
        label = k[len(prefix):].strip("{}")  # e.g. rule="hung_step"
        rule = label.partition("=")[2].strip('"') or label or "total"
        alerts[rule] = alerts.get(rule, 0) + int(v)
    peak = 0.0
    for s in debug_vars.get("samples") or []:
        peak = max(peak, float(s.get("values", {})
                               .get("gateway_queue_depth", 0.0)))
    return alerts, peak


def _slo_client_compliance(server_slo: dict,
                           recs: List[RequestRecord]) -> dict:
    """Recompute the server's SLO compliance from this run's records,
    classifying at the server-reported (bucket-snapped) thresholds so
    both sides cut on the identical boundary. Only objectives the client
    can observe from outside are recomputed — ttft (first SSE token),
    tpot (per-token decode latency), availability (ok vs refused) —
    queue_delay and goodput are server-internal."""
    out: dict = {}
    for key, st in (server_slo.get("objectives") or {}).items():
        name = st.get("objective")
        cls = st.get("class", "all")
        pool = [r for r in recs
                if cls in ("all", "") or r.priority == cls]
        thr = st.get("threshold_s")
        good = total = 0
        if name == "ttft" and thr:
            vals = [r.ttft for r in pool if r.ok and r.ttft is not None]
            total = len(vals)
            good = sum(1 for v in vals if v <= thr)
        elif name == "tpot" and thr:
            vals = [(r.latency - r.ttft) / (r.output_tokens - 1)
                    for r in pool
                    if r.ok and r.ttft is not None and r.output_tokens > 1]
            total = len(vals)
            good = sum(1 for v in vals if v <= thr)
        elif name == "availability":
            done = [r for r in pool if r.status or r.error]
            total = len(done)
            good = sum(1 for r in done if r.ok)
        else:
            continue
        if total:
            out[key] = {"good": good, "total": total,
                        "compliance": round(good / total, 6)}
    return out


def _slo_report(server_slo: dict, recs: List[RequestRecord]) -> dict:
    """LoadReport.slo: the server's /debug/slo state, the client-side
    recomputation, and per-(objective, class) agreement deltas. The
    server is windowed — the cross-check is honest only when its SLO
    window covers the whole run (the drill harness arranges that)."""
    server: dict = {}
    for key, st in (server_slo.get("objectives") or {}).items():
        server[key] = {
            "compliance": st.get("compliance"),
            "error_budget_remaining": st.get("error_budget_remaining"),
            "breaching": bool(st.get("breaching", False)),
            "threshold_s": st.get("threshold_s"),
            "target": st.get("target"),
        }
    client = _slo_client_compliance(server_slo, recs)
    agreement: dict = {}
    for key, c in client.items():
        s = server.get(key, {})
        if s.get("compliance") is None:
            continue
        delta = abs(float(s["compliance"]) - c["compliance"])
        agreement[key] = {"server": s["compliance"],
                         "client": c["compliance"],
                         "delta": round(delta, 6)}
    return {
        "server": server,
        "client": client,
        "agreement": agreement,
        "max_delta": round(max((a["delta"] for a in agreement.values()),
                               default=0.0), 6),
        "breaching": list(server_slo.get("breaching") or []),
    }


def _trace_prompt(ev: TraceEvent, idx: int) -> str:
    """Synthetic prompt sized to ev.prompt_tokens tokens (exact under the
    byte tokenizer: one char per token). A per-event prefix keeps replayed
    prompts distinct so a prefix cache can't collapse the prefill work
    the trace's length distribution encodes."""
    filler = f"[trace {idx}] replayed workload payload segment text. "
    n = max(1, int(ev.prompt_tokens))
    return (filler * (n // len(filler) + 1))[:n]


def _body_prompt_tokens(body: dict) -> int:
    """~token count of a request body's prompt (exact under the byte
    tokenizer: one char per token)."""
    if "prompt" in body:
        return len(body["prompt"])
    return sum(len(m.get("content", ""))
               for m in body.get("messages") or [])


def parse_priority_mix(spec: str) -> List[Tuple[str, float]]:
    """"interactive:0.8,batch:0.2" -> [("interactive", 0.8), ...]."""
    out: List[Tuple[str, float]] = []
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        name, _, w = part.partition(":")
        try:
            weight = float(w) if w else 1.0
        except ValueError:
            raise ValueError(f"bad priority mix entry {part!r} "
                             f"(expected class:weight)")
        if weight < 0:
            raise ValueError(f"priority weight must be >= 0: {part!r}")
        out.append((name.strip(), weight))
    return out


def _long_prompt(cfg: LoadGenConfig, idx: int) -> str:
    """Synthetic long-document prompt sized to ~long_prompt_tokens tokens
    (exact under the byte tokenizer: one char per token). A per-request
    prefix keeps prompts distinct so a prefix cache can't collapse the
    prefill work the interference measurement depends on."""
    filler = f"[doc {idx}] long document segment under summarization. "
    n = max(1, cfg.long_prompt_tokens)
    return (filler * (n // len(filler) + 1))[:n]


def _draw_adapter(cfg: LoadGenConfig, rng: random.Random) -> str:
    """Draw an adapter name "adapter-i" under the configured mix: zipf
    weights 1/(i+1) (adapter-0 hottest), uniform weighs all equally."""
    if cfg.adapters <= 0:
        return ""
    if cfg.adapter_mix == "uniform":
        i = rng.randrange(cfg.adapters)
    elif cfg.adapter_mix == "zipf":
        weights = [1.0 / (k + 1) for k in range(cfg.adapters)]
        i = rng.choices(range(cfg.adapters), weights=weights)[0]
    else:
        raise ValueError(f"adapter_mix must be 'zipf' or 'uniform', "
                         f"got {cfg.adapter_mix!r}")
    return f"adapter-{i}"


def _build_body(cfg: LoadGenConfig, rng: random.Random, idx: int,
                mix: List[Tuple[str, float]],
                ) -> Tuple[str, dict, dict, str, str, bool, str]:
    """-> (path, body, extra_headers, tenant, priority, long, adapter)
    for request idx."""
    long = (cfg.long_prompt_frac > 0
            and rng.random() < cfg.long_prompt_frac)
    prompt = (_long_prompt(cfg, idx) if long
              else rng.choice(cfg.prompts) if cfg.prompts else cfg.prompt)
    if cfg.chat:
        path = "/v1/chat/completions"
        body = {"messages": [{"role": "user", "content": prompt}]}
    else:
        path = "/v1/completions"
        body = {"prompt": prompt}
    body.update({"max_tokens": cfg.max_tokens, "temperature": cfg.temperature,
                 "stream": cfg.stream})
    headers: dict = {}
    tenant = priority = ""
    if cfg.tenants > 0:
        tenant = f"tenant-{idx % cfg.tenants}"
        headers["X-Tenant"] = tenant
    if mix:
        priority = rng.choices([m[0] for m in mix],
                               weights=[m[1] for m in mix])[0]
        body["priority"] = priority
    if cfg.deadline_s and cfg.deadline_s > 0:
        body["deadline_s"] = cfg.deadline_s
    adapter = _draw_adapter(cfg, rng)
    if adapter:
        headers["X-Adapter"] = adapter
    return path, body, headers, tenant, priority, long, adapter


def _phase_means(recs: List[RequestRecord]) -> dict:
    """Mean seconds per server-reported critical-path phase over the
    records that carried one ({} when none did). "total_s"/"ttft_s" ride
    along so the breakdown can be sanity-checked against the client-side
    latency percentiles."""
    agg: dict = {}
    n = 0
    for r in recs:
        if not r.phases:
            continue
        n += 1
        for k, v in r.phases.items():
            if isinstance(v, (int, float)):
                agg[k] = agg.get(k, 0.0) + float(v)
    return {k: round(v / n, 4) for k, v in agg.items()} if n else {}


def _class_summary(recs: List[RequestRecord]) -> dict:
    ok = [r for r in recs if r.ok]
    lat = [r.latency for r in ok]
    ttfts = [r.ttft for r in ok if r.ttft is not None]
    tpots_ms = [
        (r.latency - r.ttft) / max(1, r.output_tokens - 1) * 1000
        for r in ok if r.ttft is not None and r.output_tokens > 1
    ]
    return {
        "count": len(recs),
        "ok": len(ok),
        "shed": sum(1 for r in recs if r.shed),
        "latency_p50_s": round(_percentile(lat, 50), 4),
        "latency_p99_s": round(_percentile(lat, 99), 4),
        "ttft_p50_s": round(_percentile(ttfts, 50), 4),
        "ttft_p90_s": round(_percentile(ttfts, 90), 4),
        "ttft_p99_s": round(_percentile(ttfts, 99), 4),
        "tpot_mean_ms": (round(sum(tpots_ms) / len(tpots_ms), 2)
                         if tpots_ms else 0.0),
        "tpot_p99_ms": round(_percentile(tpots_ms, 99), 2),
    }


def _interference_summary(recs: List[RequestRecord]) -> dict:
    """Decode-TPOT p99 of short requests, split by whether any long
    request's prefill window [start, first_token] overlapped their decode
    window [first_token, end]. The victim metric of prefill→decode
    interference: a colocated engine's long chunks steal decode steps
    from co-resident slots; a disaggregated one's don't."""
    longs = [r for r in recs if r.long and r.ok and r.first_token is not None]
    shorts = [r for r in recs if not r.long and r.ok
              and r.first_token is not None and r.output_tokens > 1]
    if not longs or not shorts:
        return {}
    windows = [(r.start, r.first_token) for r in longs]
    with_ms: List[float] = []
    without_ms: List[float] = []
    for r in shorts:
        tpot = (r.end - r.first_token) / (r.output_tokens - 1) * 1000
        overlapped = any(ws < r.end and we > r.first_token
                         for ws, we in windows)
        (with_ms if overlapped else without_ms).append(tpot)
    return {
        "num_long": len(longs),
        "num_with_long_prefill": len(with_ms),
        "num_without_long_prefill": len(without_ms),
        "tpot_p99_with_long_prefill_ms": round(_percentile(with_ms, 99), 2),
        "tpot_p99_without_long_prefill_ms":
            round(_percentile(without_ms, 99), 2),
        "tpot_p50_with_long_prefill_ms": round(_percentile(with_ms, 50), 2),
        "tpot_p50_without_long_prefill_ms":
            round(_percentile(without_ms, 50), 2),
    }


async def _scrape_adapter_hit_rate(cfg: LoadGenConfig) -> float:
    """Adapter-pool hit rate from the server's /metrics counters
    (dlti_adapter_pool_{hits,misses}_total): hits / (hits + misses).
    Best-effort like every scrape — 0.0 on any failure or a pool that
    never resolved an adapter."""
    try:
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(cfg.host, cfg.port), 10.0)
        req = (f"GET /metrics HTTP/1.1\r\nHost: {cfg.host}:{cfg.port}\r\n"
               f"Connection: close\r\n\r\n").encode()
        writer.write(req)
        await writer.drain()
        status_line = await asyncio.wait_for(reader.readline(), 10.0)
        if b" 200" not in status_line:
            return 0.0
        headers: dict = {}
        while True:
            line = await asyncio.wait_for(reader.readline(), 10.0)
            if line in (b"\r\n", b"\n", b""):
                break
            k, _, v = line.decode().partition(":")
            headers[k.strip().lower()] = v.strip()
        raw = b"".join([c async for c in _iter_body(reader, headers, 10.0)])
        writer.close()
    except Exception:
        return 0.0
    vals = {}
    for line in raw.decode(errors="replace").splitlines():
        name, _, value = line.partition(" ")
        if name in ("dlti_adapter_pool_hits_total",
                    "dlti_adapter_pool_misses_total"):
            try:
                vals[name] = float(value)
            except ValueError:
                pass
    hits = vals.get("dlti_adapter_pool_hits_total", 0.0)
    misses = vals.get("dlti_adapter_pool_misses_total", 0.0)
    return round(hits / (hits + misses), 4) if hits + misses else 0.0


# Per-worker counters the fleet supervisor federates (must mirror
# dlti_tpu.serving.fleet.WORKER_COUNTER_KEYS — pinned by the fleet tests;
# not imported so the loadgen stays usable against a remote server
# without pulling in the engine stack).
_FLEET_COUNTER_KEYS = ("requests", "generated_tokens", "prefill_tokens",
                       "preemptions", "decode_steps")
_FLEET_SERIES_RE = re.compile(r"^dlti_fleet_w(\d+)_([a-z_]+) (\S+)$")


def _fleet_federation_report(metrics_text: str) -> dict:
    """LoadReport.fleet_federation from a raw /metrics exposition: sum
    each per-worker federated counter (``dlti_fleet_w{i}_<key>``) across
    workers and check it equals the gateway-level ``dlti_<key>`` total.
    {} when the exposition carries no fleet series (single-process
    server)."""
    scalars: dict = {}
    per_worker: dict = {}
    for line in metrics_text.splitlines():
        if line.startswith("#") or not line.strip():
            continue
        m = _FLEET_SERIES_RE.match(line)
        if m:
            wid, key, val = int(m.group(1)), m.group(2), m.group(3)
            try:
                per_worker.setdefault(wid, {})[key] = float(val)
            except ValueError:
                pass
            continue
        name, _, value = line.partition(" ")
        try:
            scalars[name] = float(value)
        except ValueError:
            pass
    if not per_worker:
        return {}
    checks: dict = {}
    for key in _FLEET_COUNTER_KEYS:
        rows = [w[key] for w in per_worker.values() if key in w]
        if not rows or f"dlti_{key}" not in scalars:
            continue
        total = scalars[f"dlti_{key}"]
        checks[key] = {
            "per_worker_sum": sum(rows),
            "fleet_total": total,
            "delta": total - sum(rows),
        }
    return {
        "workers": sorted(per_worker),
        "workers_alive": scalars.get("dlti_fleet_workers_alive"),
        "respawns_total": scalars.get("dlti_fleet_respawns_total"),
        "per_worker": per_worker,
        "checks": checks,
        "max_abs_delta": max((abs(c["delta"]) for c in checks.values()),
                             default=0.0),
        "consistent": all(c["delta"] == 0 for c in checks.values()),
    }


async def _scrape_spec(cfg: LoadGenConfig) -> dict:
    """LoadReport.spec from the server's /metrics exposition: the five
    schema-stable speculative-decode series (dlti_spec_*_total counters
    plus the acceptance-rate / draft-length gauges), reported under
    short keys. Best-effort like every scrape — {} on any failure."""
    names = {
        "dlti_spec_proposed_total": "proposed",
        "dlti_spec_accepted_total": "accepted",
        "dlti_spec_paused_rounds_total": "paused_rounds",
        "dlti_spec_acceptance_rate": "acceptance_rate",
        "dlti_spec_draft_len": "draft_len",
    }
    try:
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(cfg.host, cfg.port), 10.0)
        req = (f"GET /metrics HTTP/1.1\r\nHost: {cfg.host}:{cfg.port}\r\n"
               f"Connection: close\r\n\r\n").encode()
        writer.write(req)
        await writer.drain()
        status_line = await asyncio.wait_for(reader.readline(), 10.0)
        if b" 200" not in status_line:
            return {}
        headers: dict = {}
        while True:
            line = await asyncio.wait_for(reader.readline(), 10.0)
            if line in (b"\r\n", b"\n", b""):
                break
            k, _, v = line.decode().partition(":")
            headers[k.strip().lower()] = v.strip()
        raw = b"".join([c async for c in _iter_body(reader, headers, 10.0)])
        writer.close()
    except Exception:
        return {}
    out: dict = {}
    for line in raw.decode(errors="replace").splitlines():
        name, _, value = line.partition(" ")
        if name in names:
            try:
                out[names[name]] = float(value)
            except ValueError:
                pass
    return out


async def _scrape_trace_coverage(cfg: LoadGenConfig,
                                 recs: List["RequestRecord"],
                                 sample: int = 16) -> float:
    """LoadReport.trace_coverage: fetch the merged server-side timeline
    (GET /debug/trace?request_id=) for a bounded sample of ok requests
    and count those whose span tree carries the gateway, prefill AND
    decode legs. Best-effort like every scrape: 0.0 when tracing is
    disabled, the route is absent, or no response carried an id."""
    cand = [r for r in recs if r.ok and r.request_id]
    if not cand:
        return 0.0
    # Newest first: the span ring evicts oldest, so sampling the tail
    # measures federation, not ring capacity.
    cand = cand[-sample:]
    need = {"gateway/queued", "request/prefill", "request/decode"}
    covered = 0
    for r in cand:
        tl = await _http_get_json(
            cfg.host, cfg.port, f"/debug/trace?request_id={r.request_id}")
        if tl and need <= set(tl.get("legs") or {}):
            covered += 1
    return round(covered / len(cand), 4)


async def _scrape_fleet_federation(cfg: LoadGenConfig) -> dict:
    """GET /metrics and run the fleet federation cross-check.
    Best-effort like every scrape: {} on any failure or against a
    server with no fleet series."""
    try:
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(cfg.host, cfg.port), 10.0)
        req = (f"GET /metrics HTTP/1.1\r\nHost: {cfg.host}:{cfg.port}\r\n"
               f"Connection: close\r\n\r\n").encode()
        writer.write(req)
        await writer.drain()
        status_line = await asyncio.wait_for(reader.readline(), 10.0)
        if b" 200" not in status_line:
            return {}
        headers: dict = {}
        while True:
            line = await asyncio.wait_for(reader.readline(), 10.0)
            if line in (b"\r\n", b"\n", b""):
                break
            k, _, v = line.decode().partition(":")
            headers[k.strip().lower()] = v.strip()
        raw = b"".join([c async for c in _iter_body(reader, headers, 10.0)])
        writer.close()
    except Exception:
        return {}
    return _fleet_federation_report(raw.decode(errors="replace"))


async def _scrape_cache_hit_rate(cfg: LoadGenConfig) -> float:
    """Prefix-cache hit rate from the server's own /stats counters:
    tokens served from cache (HBM hits + lower-tier restores) over all
    prompt tokens the engine handled. Best-effort like every scrape."""
    stats = await _http_get_json(cfg.host, cfg.port, "/stats")
    if not stats:
        return 0.0
    cached = float(stats.get("prefix_cached_tokens", 0) or 0)
    restored = float(stats.get("prefix_restored_tokens", 0) or 0)
    prefilled = float(stats.get("prefill_tokens", 0) or 0)
    total = cached + restored + prefilled
    return round((cached + restored) / total, 4) if total else 0.0


async def _run_async(cfg: LoadGenConfig) -> LoadReport:
    rng = random.Random(cfg.seed)
    mix = parse_priority_mix(cfg.priority_mix)
    records: List[RequestRecord] = []
    captured: List[TraceEvent] = []
    sem = asyncio.Semaphore(cfg.concurrency)

    def _capture(rec: RequestRecord, body: dict) -> None:
        # --record-trace: every submitted request (any drive mode)
        # becomes a trace event at its actual send offset.
        if not cfg.record_trace:
            return
        captured.append(TraceEvent(
            offset_s=max(0.0, rec.start - t0),
            prompt_tokens=_body_prompt_tokens(body),
            max_tokens=int(body.get("max_tokens", cfg.max_tokens)),
            tenant=rec.tenant, priority=rec.priority,
            session=rec.session, adapter=rec.adapter,
            deadline_s=float(body.get("deadline_s", 0.0) or 0.0)))

    async def one(idx: int) -> None:
        async with sem:
            path, body, headers, tenant, priority, long, adapter = \
                _build_body(cfg, rng, idx, mix)
            rec = RequestRecord(start=time.monotonic(), tenant=tenant,
                                priority=priority, long=long,
                                adapter=adapter)
            records.append(rec)
            _capture(rec, body)
            await _http_post_sse(cfg.host, cfg.port, path, body, rec,
                                 cfg.timeout_s, extra_headers=headers)

    async def replay_one(idx: int, ev: TraceEvent) -> None:
        async with sem:
            prompt = _trace_prompt(ev, idx)
            if cfg.chat:
                path = "/v1/chat/completions"
                body: dict = {"messages": [{"role": "user",
                                            "content": prompt}]}
            else:
                path = "/v1/completions"
                body = {"prompt": prompt}
            body.update({"max_tokens": ev.max_tokens or cfg.max_tokens,
                         "temperature": cfg.temperature,
                         "stream": cfg.stream})
            headers: dict = {}
            if ev.tenant:
                headers["X-Tenant"] = ev.tenant
            if ev.priority:
                body["priority"] = ev.priority
            if ev.session:
                headers["X-Session"] = ev.session
            if ev.adapter:
                headers["X-Adapter"] = ev.adapter
            if ev.deadline_s and ev.deadline_s > 0:
                body["deadline_s"] = ev.deadline_s
            rec = RequestRecord(start=time.monotonic(), tenant=ev.tenant,
                                priority=ev.priority, session=ev.session,
                                adapter=ev.adapter)
            records.append(rec)
            _capture(rec, body)
            await _http_post_sse(cfg.host, cfg.port, path, body, rec,
                                 cfg.timeout_s, extra_headers=headers)

    async def session_task(sidx: int) -> None:
        # One chat session: `turns` sequential requests replaying a shared
        # system prompt + this session's growing history. Turn t's prompt
        # strictly extends turn t-1's, so a prefix-caching server skips
        # everything but the new tail — the cold-vs-warm TTFT split below
        # is the measurement of exactly that.
        srng = random.Random(cfg.seed * 7919 + sidx)
        sess = f"sess-{sidx}"
        system = cfg.prompt
        history: List[str] = []
        for t in range(cfg.turns):
            reuse = t > 0 and srng.random() < cfg.reuse_frac
            if t == 0 or reuse:
                history.append(f"[turn {len(history)}] {sess} follow-up "
                               f"question {len(history)}")
                text = system + "\n" + "\n".join(history)
                headers = {"X-Session": sess}
                rec_sess, warm = sess, t > 0
            else:
                # Defecting turn: unrelated one-off traffic (cold), no
                # session header — the (1 - reuse_frac) noise floor.
                text = f"one-off {sess}-{t}: {system[::-1]}"
                headers = {}
                rec_sess, warm = "", False
            if cfg.tenants > 0:
                headers["X-Tenant"] = f"tenant-{sidx % cfg.tenants}"
            if cfg.chat:
                path = "/v1/chat/completions"
                body = {"messages": [{"role": "user", "content": text}]}
            else:
                path = "/v1/completions"
                body = {"prompt": text}
            body.update({"max_tokens": cfg.max_tokens,
                         "temperature": cfg.temperature,
                         "stream": cfg.stream})
            if cfg.deadline_s and cfg.deadline_s > 0:
                body["deadline_s"] = cfg.deadline_s
            async with sem:
                rec = RequestRecord(start=time.monotonic(),
                                    tenant=headers.get("X-Tenant", ""),
                                    session=rec_sess, warm=warm)
                records.append(rec)
                await _http_post_sse(cfg.host, cfg.port, path, body, rec,
                                     cfg.timeout_s, extra_headers=headers)

    replay_events: List[TraceEvent] = []
    if cfg.trace:
        _, replay_events = read_trace(cfg.trace)

    t0 = time.monotonic()
    if cfg.trace:
        # Trace replay: fire each event at its recorded arrival offset —
        # sleep up to the offset, never ahead; a late event fires
        # immediately so offsets stay faithful under scheduler jitter.
        tasks = []
        for i, ev in enumerate(replay_events):
            delay = t0 + ev.offset_s - time.monotonic()
            if delay > 0:
                await asyncio.sleep(delay)
            tasks.append(asyncio.create_task(replay_one(i, ev)))
        await asyncio.gather(*tasks, return_exceptions=True)
    elif cfg.sessions > 0:
        # Recurring-session mode: sessions run concurrently, each one's
        # turns strictly in order (turn t+1 needs t's prefix resident).
        await asyncio.gather(
            *(session_task(i) for i in range(cfg.sessions)),
            return_exceptions=True)
    elif cfg.qps:
        # Open loop: Poisson arrivals; concurrency still caps in-flight.
        tasks = []
        for i in range(cfg.num_requests):
            tasks.append(asyncio.create_task(one(i)))
            await asyncio.sleep(rng.expovariate(cfg.qps))
        await asyncio.gather(*tasks, return_exceptions=True)
    else:
        # Closed loop: `concurrency` users issuing back-to-back requests.
        await asyncio.gather(*(one(i) for i in range(cfg.num_requests)),
                             return_exceptions=True)
    duration = time.monotonic() - t0
    if cfg.record_trace and captured:
        write_trace(cfg.record_trace, captured,
                    meta={"source": "loadgen", "seed": cfg.seed,
                          "mode": ("replay" if cfg.trace else
                                   "sessions" if cfg.sessions > 0 else
                                   "open" if cfg.qps else "closed")})
    server_hists = (await _scrape_histograms(cfg.host, cfg.port)
                    if cfg.scrape_server_metrics else {})
    watchdog_alerts, peak_queue = _watchdog_report(
        await _http_get_json(cfg.host, cfg.port, "/debug/vars")
        if cfg.scrape_debug_vars else None)
    # End-of-run memory map (telemetry.memledger via /debug/memory) —
    # best-effort like every other scrape; {} when absent/disabled.
    mem_snap = (await _http_get_json(cfg.host, cfg.port, "/debug/memory")
                if cfg.scrape_debug_vars else None)
    # End-of-run SLO state (telemetry.slo via /debug/slo) cross-checked
    # against this run's own records — best-effort like every scrape.
    slo_snap = (await _http_get_json(cfg.host, cfg.port, "/debug/slo")
                if cfg.scrape_debug_vars else None)
    # End-of-run fleet federation cross-check (serving.fleet) — rides
    # the same best-effort gate; {} against a single-process server.
    fleet_federation = (await _scrape_fleet_federation(cfg)
                        if cfg.scrape_debug_vars else {})
    # End-of-run speculative-decode economics (engine spec scalar
    # source) — same best-effort gate; all-zero values against a server
    # running without --speculative.
    spec = (await _scrape_spec(cfg) if cfg.scrape_debug_vars else {})
    # End-of-run distributed-trace audit: same best-effort gate; 0.0
    # against a server with tracing off or without trace ids.
    trace_coverage = (await _scrape_trace_coverage(cfg, records)
                      if cfg.scrape_debug_vars else 0.0)
    slo = (_slo_report(slo_snap, records)
           if slo_snap and slo_snap.get("objectives") else {})
    memory = {}
    if mem_snap:
        memory = {
            "source": mem_snap.get("source", ""),
            "bytes_in_use": mem_snap.get("bytes_in_use", 0),
            "peak_bytes": mem_snap.get("peak_bytes", 0),
            "untracked_bytes": mem_snap.get("untracked_bytes", 0),
            "headroom_bytes": mem_snap.get("headroom_bytes"),
            "owners": {o: d.get("bytes", 0) for o, d in
                       (mem_snap.get("owners") or {}).items()},
        }

    ok = [r for r in records if r.ok]
    shed = [r for r in records if r.shed]
    lat = [r.latency for r in ok]
    ttfts = [r.ttft for r in ok if r.ttft is not None]
    total_out = sum(r.output_tokens for r in ok)
    tpots_ms = [
        (r.latency - r.ttft) / max(1, r.output_tokens - 1) * 1000
        for r in ok if r.ttft is not None and r.output_tokens > 1
    ]
    per_class = {}
    if mix:
        for cls in {m[0] for m in mix}:
            per_class[cls] = _class_summary(
                [r for r in records if r.priority == cls])
    if cfg.long_prompt_frac > 0:
        per_class["long_prompt"] = _class_summary(
            [r for r in records if r.long])
        per_class["short_prompt"] = _class_summary(
            [r for r in records if not r.long])
    per_adapter = {}
    if cfg.adapters > 0:
        for name in sorted({r.adapter for r in records if r.adapter}):
            per_adapter[name] = _class_summary(
                [r for r in records if r.adapter == name])
    adapter_pool_hit_rate = (await _scrape_adapter_hit_rate(cfg)
                             if cfg.adapters > 0 else 0.0)
    cold = [r for r in ok if not r.warm]
    warm = [r for r in ok if r.warm]
    cold_ttfts = [r.ttft for r in cold if r.ttft is not None]
    warm_ttfts = [r.ttft for r in warm if r.ttft is not None]
    cache_hit_rate = (await _scrape_cache_hit_rate(cfg)
                      if cfg.sessions > 0 else 0.0)
    return LoadReport(
        num_requests=len(records),
        num_ok=len(ok),
        duration_s=round(duration, 3),
        requests_per_s=round(len(ok) / duration, 3) if duration else 0.0,
        output_tokens_per_s=round(total_out / duration, 1) if duration else 0.0,
        latency_p50_s=round(_percentile(lat, 50), 4),
        latency_p90_s=round(_percentile(lat, 90), 4),
        latency_p99_s=round(_percentile(lat, 99), 4),
        ttft_p50_s=round(_percentile(ttfts, 50), 4),
        ttft_p90_s=round(_percentile(ttfts, 90), 4),
        ttft_p99_s=round(_percentile(ttfts, 99), 4),
        tpot_mean_ms=round(sum(tpots_ms) / len(tpots_ms), 2) if tpots_ms else 0.0,
        ttft_p999_s=round(_percentile(ttfts, 99.9), 4),
        tpot_p999_ms=round(_percentile(tpots_ms, 99.9), 2),
        migrations_total=sum(r.migrations for r in records),
        retries_total=sum(r.retries for r in records),
        num_shed=len(shed),
        shed_rate=round(len(shed) / len(records), 4) if records else 0.0,
        per_class=per_class,
        # Shed refusals are deliberate back-pressure, not errors; keep the
        # error list for real failures so a bounded-queue burst doesn't
        # read as a broken server.
        errors=[r.error for r in records if r.error and not r.shed][:10],
        server_histograms=server_hists,
        watchdog_alerts=watchdog_alerts,
        peak_queue_depth=peak_queue,
        num_cold=len(cold),
        num_warm=len(warm),
        cold_ttft_p50_s=round(_percentile(cold_ttfts, 50), 4),
        cold_ttft_p90_s=round(_percentile(cold_ttfts, 90), 4),
        warm_ttft_p50_s=round(_percentile(warm_ttfts, 50), 4),
        warm_ttft_p90_s=round(_percentile(warm_ttfts, 90), 4),
        cache_hit_rate=cache_hit_rate,
        per_adapter=per_adapter,
        adapter_pool_hit_rate=adapter_pool_hit_rate,
        interference=(_interference_summary(records)
                      if cfg.long_prompt_frac > 0 else {}),
        phase_means=_phase_means(ok),
        cold_phases=_phase_means(cold),
        warm_phases=_phase_means(warm),
        memory=memory,
        slo=slo,
        fleet_federation=fleet_federation,
        spec=spec,
        trace_coverage=trace_coverage,
        records=records,
    )


def run_load_test(cfg: LoadGenConfig) -> LoadReport:
    """Blocking entry point (used by ``scripts/benchmark_serving.py``)."""
    return asyncio.run(_run_async(cfg))
