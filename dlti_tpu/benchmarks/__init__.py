"""Load-testing + benchmark reporting.

The reference pins ``locust==2.29.0`` and ``aiohttp==3.10.0``
(``requirements.txt:35-36``) and claims "Benchmarking: Locust, AsyncIO"
(``README.md:11,17``) but ships no benchmark code (SURVEY.md §0). This
package is that leg, dependency-free.
"""

from dlti_tpu.benchmarks.loadgen import LoadGenConfig, LoadReport, run_load_test  # noqa: F401
from dlti_tpu.benchmarks.traces import (  # noqa: F401
    TRACE_FORMAT,
    TraceEvent,
    read_trace,
    synthesize,
    trace_summary,
    write_trace,
)
