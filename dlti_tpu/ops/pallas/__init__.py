"""Pallas TPU kernels: flash attention (training), decode attention (serving)."""

from dlti_tpu.ops.pallas.flash_attention import flash_attention  # noqa: F401
