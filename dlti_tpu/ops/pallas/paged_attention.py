"""Paged decode attention for TPU, in Pallas.

The serving engine's decode hot op. The XLA fallback path
(``dlti_tpu.ops.kv_cache.paged_gather`` + ``reference_attention``)
materializes each sequence's whole logical KV window in HBM every step —
O(batch * max_len) extra traffic. This kernel instead walks the block table
and reads K/V blocks *in place* from the physical pool, one VMEM tile at a
time, with an online softmax — the TPU analog of vLLM's PagedAttention
CUDA kernel (the reference claims that engine via ``requirements.txt:18``
but ships no code; SURVEY.md §2b).

Design:

* Grid ``(batch, max_blocks_per_seq)``; TPU grids run sequentially
  minor-most-first, so the online-softmax running state ``(m, l, acc)``
  for one sequence lives in VMEM scratch across the block sweep.
* ``block_tables`` and ``seq_lens`` ride scalar prefetch
  (:class:`~jax.experimental.pallas.tpu.PrefetchScalarGridSpec`), so the
  K/V ``BlockSpec`` index maps can pick the *physical* block
  ``block_tables[b, j]`` for logical block ``j`` — the indirection happens
  in the pipeline, not as a gather. Each live block is fetched exactly
  once per sequence per step, with every KV head in the tile (full-dim
  trailing axes keep Mosaic's (8, 128) tiling rules satisfied).
* GQA for free: q arrives as ``(batch, kv_heads, heads_per_group, d)``
  and the per-block matmuls are batched over ``kv_heads``, so KV heads are
  never repeated.
* Blocks at or past ``seq_lens[b]`` are skipped (``pl.when``), and the
  tail block is masked by token position, so stale pool rows never
  contribute.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dlti_tpu.ops.pallas.flash_attention import out_struct

NEG_INF = -1e30


def _decode_kernel(seq_lens_ref, block_tables_ref, q_ref, k_ref, v_ref, *rest,
                   scale: float, block_size: int, window: int,
                   quantized: bool):
    if quantized:
        # int8 pools travel with (1, block_size, kv_heads) fp32 scale
        # tiles; the scales fold into the attention math per kv position
        # (s *= k_scale, p *= v_scale) — no dequantized K/V tile is ever
        # materialized. The scale tile's minor dim is kv_heads (< the
        # 128-lane Mosaic tile): Mosaic pads it, costing a few KB of
        # VMEM per block against the 64+ KB int8 payload — validated on
        # hardware (results/int8_kv_7b.json).
        ks_ref, vs_ref, o_ref, m_scratch, l_scratch, acc_scratch = rest
    else:
        (o_ref, m_scratch, l_scratch, acc_scratch), ks_ref, vs_ref = rest, None, None
    b = pl.program_id(0)
    j = pl.program_id(1)
    nb = pl.num_programs(1)
    seq_len = seq_lens_ref[b]

    @pl.when(j == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    run = j * block_size < seq_len
    if window:
        # Sliding window: skip blocks wholly below [seq_len - window, seq_len).
        run = jnp.logical_and(run, (j + 1) * block_size > seq_len - window)

    @pl.when(run)
    def _body():
        q = q_ref[0].astype(jnp.float32)                   # (kvh, hpg, d)
        k = jnp.swapaxes(k_ref[0].astype(jnp.float32), 0, 1)  # (kvh, bs, d)
        v = jnp.swapaxes(v_ref[0].astype(jnp.float32), 0, 1)  # (kvh, bs, d)
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale                                          # (kvh, hpg, bs)
        if ks_ref is not None:
            ks = jnp.swapaxes(ks_ref[0].astype(jnp.float32), 0, 1)
            s = s * ks[:, None, :]                         # (kvh, 1, bs)

        k_pos = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 2)
        valid = k_pos < seq_len
        if window:
            valid &= k_pos >= seq_len - window
        s = jnp.where(valid, s, NEG_INF)

        m_prev = m_scratch[:]                              # (kvh, hpg, 1)
        m_cur = jnp.max(s, axis=2, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new) * (s > NEG_INF / 2)
        alpha = jnp.exp(m_prev - m_new)
        l_scratch[:] = alpha * l_scratch[:] + jnp.sum(p, axis=2, keepdims=True)
        pv = p
        if vs_ref is not None:
            vs = jnp.swapaxes(vs_ref[0].astype(jnp.float32), 0, 1)
            pv = p * vs[:, None, :]                        # (kvh, 1, bs)
        acc_scratch[:] = acc_scratch[:] * alpha + jax.lax.dot_general(
            pv, v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        m_scratch[:] = m_new

    @pl.when(j == nb - 1)
    def _finalize():
        l = l_scratch[:]
        l = jnp.where(l == 0.0, 1.0, l)  # seq_len == 0 -> zero output
        o_ref[0] = (acc_scratch[:] / l).astype(o_ref.dtype)


def paged_decode_attention(
    q: jnp.ndarray,
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    block_tables: jnp.ndarray,
    seq_lens: jnp.ndarray,
    *,
    k_scale: jnp.ndarray | None = None,
    v_scale: jnp.ndarray | None = None,
    window: int | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """One-token-per-sequence attention over the paged KV pool.

    Args:
      q: ``(batch, 1, num_heads, head_dim)`` current-step queries.
      k_pool / v_pool: ``(num_blocks, block_size, kv_heads, head_dim)``.
      block_tables: ``(batch, max_blocks_per_seq)`` int32; entries for
        unallocated logical blocks may be any value (they are clamped and
        masked, never read into the result).
      seq_lens: ``(batch,)`` int32 — tokens valid per sequence *including*
        the current one (i.e. query position + 1).
      k_scale / v_scale: for int8 pools, the ``(num_blocks, block_size,
        kv_heads)`` fp32 per-row scales (``ops.kv_cache`` int8 layout);
        folded into the attention math in place — required iff the pools
        are int8.
      window: Mistral-style sliding window — only the last ``window``
        positions stay visible; whole blocks outside the band are skipped.

    Returns ``(batch, 1, num_heads, head_dim)``.
    """
    batch, s1, num_heads, head_dim = q.shape
    assert s1 == 1, f"decode kernel takes single-token queries, got s={s1}"
    num_blocks, block_size, kv_heads, _ = k_pool.shape
    hpg = num_heads // kv_heads
    max_blocks = block_tables.shape[1]
    scale = head_dim ** -0.5

    # (batch, kv_heads, hpg, d): group query heads with their KV head.
    qg = q[:, 0].reshape(batch, kv_heads, hpg, head_dim)
    # Physical ids must be in-range even for never-run grid steps: the
    # pipeline prefetches by index map before the kernel's pl.when gate.
    bt = jnp.clip(block_tables, 0, num_blocks - 1).astype(jnp.int32)
    seq_lens = seq_lens.astype(jnp.int32)

    grid = (batch, max_blocks)

    quantized = k_pool.dtype == jnp.int8
    if quantized and (k_scale is None or v_scale is None):
        raise ValueError("int8 KV pools require k_scale/v_scale")

    def q_map(b, j, seq_lens_ref, bt_ref):
        return (b, 0, 0, 0)

    def kv_map(b, j, seq_lens_ref, bt_ref):
        return (bt_ref[b, j], 0, 0, 0)

    def scale_map(b, j, seq_lens_ref, bt_ref):
        return (bt_ref[b, j], 0, 0)

    in_specs = [
        pl.BlockSpec((1, kv_heads, hpg, head_dim), q_map),
        pl.BlockSpec((1, block_size, kv_heads, head_dim), kv_map),
        pl.BlockSpec((1, block_size, kv_heads, head_dim), kv_map),
    ]
    operands = [qg, k_pool, v_pool]
    if quantized:
        in_specs += [pl.BlockSpec((1, block_size, kv_heads), scale_map),
                     pl.BlockSpec((1, block_size, kv_heads), scale_map)]
        operands += [k_scale, v_scale]

    kernel = functools.partial(_decode_kernel, scale=scale,
                               block_size=block_size, window=window or 0,
                               quantized=quantized)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, kv_heads, hpg, head_dim), q_map),
            scratch_shapes=[
                pltpu.VMEM((kv_heads, hpg, 1), jnp.float32),
                pltpu.VMEM((kv_heads, hpg, 1), jnp.float32),
                pltpu.VMEM((kv_heads, hpg, head_dim), jnp.float32),
            ],
        ),
        out_shape=out_struct((batch, kv_heads, hpg, head_dim), q.dtype, q),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=int(2 * 2 * batch * num_heads * max_blocks * block_size
                      * head_dim),
            bytes_accessed=int(
                (batch * max_blocks * block_size * kv_heads * head_dim * 2)
                * k_pool.dtype.itemsize + 2 * q.size * q.dtype.itemsize),
            transcendentals=batch * num_heads * max_blocks * block_size,
        ),
    )(seq_lens, bt, *operands)

    return out.reshape(batch, 1, num_heads, head_dim)
