"""Blockwise (flash) causal attention for TPU, in Pallas — fwd and bwd.

The hot op of the whole framework. Replaces the (seq, seq) score
materialization of ``reference_attention`` with an online-softmax sweep over
KV blocks held in VMEM — O(seq) memory, MXU-sized tiles, fp32 accumulators.
The reference repo inherits its fused attention from HF/torch CUDA kernels;
this is the TPU-native equivalent.

Layout: kernels operate on (batch*heads, seq, head_dim) with grids of
(bh, q_blocks, kv_blocks) (fwd, dq) or (bh, kv_blocks, q_blocks) (dk/dv).
TPU grids execute sequentially minor-most-first, so per-block running state
lives in VMEM scratch across the innermost sweep. Causal blocks outside the
(windowed) band are skipped via ``pl.when`` (no wasted MXU work), and the
band edges get elementwise iota masks.

Backward is the standard flash decomposition: the forward also emits the
per-row logsumexp L; the backward recomputes p = exp(qk*scale - L) per tile
(no (seq, seq) materialization), with
``D = rowsum(dO * O)``, ``dv += p^T dO``, ``ds = p * (dO v^T - D) * scale``,
``dq += ds k``, ``dk += ds^T q`` — two sweeps, O(seq) memory.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scratch, l_scratch, acc_scratch,
                *, scale: float, block_q: int, block_kv: int, causal: bool,
                window: int, seq_q: int, seq_kv: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    @pl.when(_band_run(qi, ki, block_q, block_kv, causal, window))
    def _body():
        q = q_ref[0].astype(jnp.float32)  # (block_q, d)
        k = k_ref[0].astype(jnp.float32)  # (block_kv, d)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # (block_q, block_kv)

        allowed = _band_mask(qi, ki, block_q, block_kv, s.shape, causal,
                             window, seq_q, seq_kv)
        if allowed is not None:
            s = jnp.where(allowed, s, NEG_INF)

        m_prev = m_scratch[:]  # (block_q, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # Rows with no causally-valid entry in this block have m_new ==
        # NEG_INF, making exp(s - m_new) == 1 for every *masked* entry —
        # explicitly zero them (hit when block_kv > block_q admits blocks
        # strictly above a row's diagonal).
        p = jnp.exp(s - m_new) * (s > NEG_INF / 2)  # (block_q, block_kv)
        alpha = jnp.exp(m_prev - m_new)  # (block_q, 1)
        l_new = alpha * l_scratch[:] + jnp.sum(p, axis=1, keepdims=True)
        acc_scratch[:] = acc_scratch[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_scratch[:] = m_new
        l_scratch[:] = l_new

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_scratch[:]
        safe_l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> zero output
        o_ref[0] = (acc_scratch[:] / safe_l).astype(o_ref.dtype)
        # Per-row logsumexp for the backward. Fully-masked rows get +BIG so
        # the backward's exp(s - L) is exactly 0 there.
        lse = jnp.where(l > 0.0, m_scratch[:] + jnp.log(safe_l), -NEG_INF)
        lse_ref[0] = lse


def _flash_fwd(q, k, v, *, scale, block_q, block_kv, causal, window, interpret):
    """q,k,v: (bh, seq, d) -> o: (bh, seq, d)."""
    bh, sq, d = q.shape
    skv = k.shape[1]
    block_q = min(block_q, sq)
    block_kv = min(block_kv, skv)
    grid = (bh, pl.cdiv(sq, block_q), pl.cdiv(skv, block_kv))

    kernel = functools.partial(
        _fwd_kernel, scale=scale, block_q=block_q, block_kv=block_kv,
        causal=causal, window=window, seq_q=sq, seq_kv=skv,
    )
    return pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32),  # logsumexp
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_kv, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_kv, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=int(2 * 2 * bh * sq * skv * d * (0.5 if causal else 1.0)),
            bytes_accessed=(q.size + k.size + v.size + q.size) * q.dtype.itemsize,
            transcendentals=bh * sq * skv,
        ),
    )(q, k, v)


def _band_mask(qi, ki, block_q, block_kv, shape, causal, window,
               seq_q, seq_kv):
    """Elementwise allowed-mask for the (qi, ki) tile.

    Combines the causal/sliding-window band with sequence bounds: Pallas
    does NOT zero tile padding on TPU, so rows >= seq_q / cols >= seq_kv
    hold garbage and must be masked in every kernel that *accumulates*
    across tiles (the whole backward; the non-causal forward). Returns
    None only when provably nothing needs masking.
    """
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, shape, 0)
    k_pos = ki * block_kv + jax.lax.broadcasted_iota(jnp.int32, shape, 1)
    padded = seq_q % block_q != 0 or seq_kv % block_kv != 0
    if not causal and not padded:
        return None
    allowed = None
    if causal:
        allowed = k_pos <= q_pos
        if window:
            allowed &= k_pos > q_pos - window
    if padded:
        bounds = (q_pos < seq_q) & (k_pos < seq_kv)
        allowed = bounds if allowed is None else (allowed & bounds)
    return allowed


def _band_run(qi, ki, block_q, block_kv, causal, window):
    """Whole-tile skip predicate (conservative w.r.t. :func:`_band_mask`)."""
    if not causal:
        return True
    run = ki * block_kv <= qi * block_q + (block_q - 1)
    if window:
        run = jnp.logical_and(
            run, ki * block_kv + (block_kv - 1) > qi * block_q - window)
    return run


def _load_bwd_tiles(q_ref, k_ref, v_ref, do_ref, qi, ki, block_q, block_kv,
                    seq_q, seq_kv):
    """Load backward tiles with padding rows/cols zeroed.

    Pallas does not zero tile padding on TPU; the backward *accumulates*
    across tiles, so garbage (potentially inf/NaN, which survives
    multiplication by zero) in rows >= seq_q / cols >= seq_kv must be
    cleared at load time.
    """
    q = q_ref[0].astype(jnp.float32)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    if seq_q % block_q != 0:
        rows = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, 1), 0)
        q = jnp.where(rows < seq_q, q, 0.0)
        do = jnp.where(rows < seq_q, do, 0.0)
    if seq_kv % block_kv != 0:
        cols = ki * block_kv + jax.lax.broadcasted_iota(
            jnp.int32, (block_kv, 1), 0)
        k = jnp.where(cols < seq_kv, k, 0.0)
        v = jnp.where(cols < seq_kv, v, 0.0)
    return q, k, v, do


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_scratch, *, scale, block_q, block_kv, causal, window,
               seq_q, seq_kv):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scratch[:] = jnp.zeros_like(dq_scratch)

    @pl.when(_band_run(qi, ki, block_q, block_kv, causal, window))
    def _body():
        q, k, v, do = _load_bwd_tiles(
            q_ref, k_ref, v_ref, do_ref, qi, ki, block_q, block_kv,
            seq_q, seq_kv)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        mask = _band_mask(qi, ki, block_q, block_kv, s.shape, causal, window,
                          seq_q, seq_kv)
        p = jnp.exp(s - lse_ref[0])                        # (bq, bk)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        # where() (not just p==0) so garbage lse/delta in padding rows can't
        # poison the product with 0 * inf = NaN.
        ds = p * (dp - delta_ref[0]) * scale               # (bq, bk)
        if mask is not None:
            ds = jnp.where(mask, ds, 0.0)
        dq_scratch[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scratch[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scratch, dv_scratch,
                *, scale, block_q, block_kv, causal, window, seq_q, seq_kv):
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scratch[:] = jnp.zeros_like(dk_scratch)
        dv_scratch[:] = jnp.zeros_like(dv_scratch)

    @pl.when(_band_run(qi, ki, block_q, block_kv, causal, window))
    def _body():
        q, k, v, do = _load_bwd_tiles(
            q_ref, k_ref, v_ref, do_ref, qi, ki, block_q, block_kv,
            seq_q, seq_kv)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        mask = _band_mask(qi, ki, block_q, block_kv, s.shape, causal, window,
                          seq_q, seq_kv)
        p = jnp.exp(s - lse_ref[0])                        # (bq, bk)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        dv_scratch[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0]) * scale
        if mask is not None:
            ds = jnp.where(mask, ds, 0.0)
        dk_scratch[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scratch[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scratch[:].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, *, scale, block_q, block_kv, causal,
               window, interpret):
    """q,k,v,o,do: (bh, s, d); lse: (bh, s, 1) -> (dq, dk, dv)."""
    bh, sq, d = q.shape
    skv = k.shape[1]
    block_q = min(block_q, sq)
    block_kv = min(block_kv, skv)
    nq = pl.cdiv(sq, block_q)
    nk = pl.cdiv(skv, block_kv)

    # D_i = rowsum(dO_i * O_i) — tiny elementwise pass, XLA-fused.
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)

    q_spec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    kv_spec = pl.BlockSpec((1, block_kv, d), lambda b, i, j: (b, j, 0))
    row_spec = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, block_q=block_q,
                          block_kv=block_kv, causal=causal, window=window,
                          seq_q=sq, seq_kv=skv),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        grid=(bh, nq, nk),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    # dk/dv sweep: grid transposed so kv blocks are outer, q inner.
    q_spec_t = pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0))
    kv_spec_t = pl.BlockSpec((1, block_kv, d), lambda b, j, i: (b, j, 0))
    row_spec_t = pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, block_q=block_q,
                          block_kv=block_kv, causal=causal, window=window,
                          seq_q=sq, seq_kv=skv),
        out_shape=(jax.ShapeDtypeStruct((bh, skv, d), k.dtype),
                   jax.ShapeDtypeStruct((bh, skv, d), v.dtype)),
        grid=(bh, nk, nq),
        in_specs=[q_spec_t, kv_spec_t, kv_spec_t, q_spec_t, row_spec_t,
                  row_spec_t],
        out_specs=(kv_spec_t, kv_spec_t),
        scratch_shapes=[pltpu.VMEM((block_kv, d), jnp.float32),
                        pltpu.VMEM((block_kv, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7)
)
def _flash_attention_core(q, k, v, causal, block_q, block_kv, window, interpret):
    """(b, s, h, d) attention with GQA via head repetition at the caller."""
    return _core_fwd(q, k, v, causal, block_q, block_kv, window, interpret)[0]


def _core_fwd(q, k, v, causal, block_q, block_kv, window, interpret):
    b, sq, h, d = q.shape
    scale = d ** -0.5
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, k.shape[1], d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, v.shape[1], d)
    o, lse = _flash_fwd(qt, kt, vt, scale=scale, block_q=block_q,
                        block_kv=block_kv, causal=causal, window=window,
                        interpret=interpret)
    out = o.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    return out, (qt, kt, vt, o, lse)


def _core_bwd(causal, block_q, block_kv, window, interpret, res, g):
    """Flash backward: tile-recomputed p from the saved logsumexp."""
    qt, kt, vt, o, lse = res
    bh, sq, d = qt.shape
    scale = d ** -0.5
    do = g.transpose(0, 2, 1, 3).reshape(bh, sq, d)
    dq, dk, dv = _flash_bwd(
        qt, kt, vt, o, lse, do, scale=scale, block_q=block_q,
        block_kv=block_kv, causal=causal, window=window, interpret=interpret)
    b = g.shape[0]
    h = g.shape[2]

    def unflat(x, s):
        return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)

    return unflat(dq, sq), unflat(dk, kt.shape[1]), unflat(dv, vt.shape[1])


_flash_attention_core.defvjp(_core_fwd, _core_bwd)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    segment_ids=None,
    block_q: int = 512,
    block_kv: int = 512,
    window: int | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Flash attention entry. q: (b, sq, h, d); k/v: (b, skv, h_kv, d).

    GQA is handled by repeating kv heads (the MXU cost is in the matmuls,
    which are unchanged). ``window`` enables Mistral-style sliding-window
    attention with whole-block skipping outside the band. Segment masking
    falls back to the reference implementation for now.
    """
    if segment_ids is not None:
        from dlti_tpu.ops.attention import reference_attention

        return reference_attention(q, k, v, causal=causal, segment_ids=segment_ids,
                                   window=window)

    h, h_kv = q.shape[2], k.shape[2]
    if h != h_kv:
        rep = h // h_kv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return _flash_attention_core(q, k, v, causal, block_q, block_kv,
                                 window or 0, interpret)
