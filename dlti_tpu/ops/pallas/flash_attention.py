"""Blockwise (flash) causal attention for TPU, in Pallas.

The hot op of the whole framework. Replaces the (seq, seq) score
materialization of ``reference_attention`` with an online-softmax sweep over
KV blocks held in VMEM — O(seq) memory, MXU-sized tiles, fp32 accumulators.
The reference repo inherits its fused attention from HF/torch CUDA kernels;
this is the TPU-native equivalent.

Layout: kernel operates on (batch*heads, seq, head_dim) with a grid of
(bh, q_blocks, kv_blocks). TPU grids execute sequentially minor-most-first,
so the (m, l, acc) running state for one q block lives in VMEM scratch
across the kv_block sweep. Causal blocks above the diagonal are skipped via
``pl.when`` (no wasted MXU work), and the diagonal block gets an elementwise
iota mask.

Backward: round-1 uses a recompute VJP through the XLA reference attention
(correct, O(seq^2) memory at the backward only); a Pallas backward kernel is
the planned follow-up for long-sequence training.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, m_scratch, l_scratch, acc_scratch,
                *, scale: float, block_q: int, block_kv: int, causal: bool,
                window: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    # Causal: process only kv blocks whose start <= q block's end; with a
    # sliding window, also skip blocks entirely below every query's window.
    run = True
    if causal:
        run = ki * block_kv <= qi * block_q + (block_q - 1)
        if window:
            run = jnp.logical_and(
                run, ki * block_kv + (block_kv - 1) > qi * block_q - window)

    @pl.when(run)
    def _body():
        q = q_ref[0].astype(jnp.float32)  # (block_q, d)
        k = k_ref[0].astype(jnp.float32)  # (block_kv, d)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # (block_q, block_kv)

        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 0
            )
            k_pos = ki * block_kv + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 1
            )
            allowed = k_pos <= q_pos
            if window:
                allowed &= k_pos > q_pos - window
            s = jnp.where(allowed, s, NEG_INF)

        m_prev = m_scratch[:]  # (block_q, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # Rows with no causally-valid entry in this block have m_new ==
        # NEG_INF, making exp(s - m_new) == 1 for every *masked* entry —
        # explicitly zero them (hit when block_kv > block_q admits blocks
        # strictly above a row's diagonal).
        p = jnp.exp(s - m_new) * (s > NEG_INF / 2)  # (block_q, block_kv)
        alpha = jnp.exp(m_prev - m_new)  # (block_q, 1)
        l_new = alpha * l_scratch[:] + jnp.sum(p, axis=1, keepdims=True)
        acc_scratch[:] = acc_scratch[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_scratch[:] = m_new
        l_scratch[:] = l_new

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_scratch[:]
        l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> zero output
        o_ref[0] = (acc_scratch[:] / l).astype(o_ref.dtype)


def _flash_fwd(q, k, v, *, scale, block_q, block_kv, causal, window, interpret):
    """q,k,v: (bh, seq, d) -> o: (bh, seq, d)."""
    bh, sq, d = q.shape
    skv = k.shape[1]
    block_q = min(block_q, sq)
    block_kv = min(block_kv, skv)
    grid = (bh, pl.cdiv(sq, block_q), pl.cdiv(skv, block_kv))

    kernel = functools.partial(
        _fwd_kernel, scale=scale, block_q=block_q, block_kv=block_kv,
        causal=causal, window=window,
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_kv, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_kv, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=int(2 * 2 * bh * sq * skv * d * (0.5 if causal else 1.0)),
            bytes_accessed=(q.size + k.size + v.size + q.size) * q.dtype.itemsize,
            transcendentals=bh * sq * skv,
        ),
    )(q, k, v)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7)
)
def _flash_attention_core(q, k, v, causal, block_q, block_kv, window, interpret):
    """(b, s, h, d) attention with GQA via head repetition at the caller."""
    b, sq, h, d = q.shape
    scale = d ** -0.5
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, k.shape[1], d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, v.shape[1], d)
    o = _flash_fwd(qt, kt, vt, scale=scale, block_q=block_q, block_kv=block_kv,
                   causal=causal, window=window, interpret=interpret)
    return o.reshape(b, h, sq, d).transpose(0, 2, 1, 3)


def _core_fwd(q, k, v, causal, block_q, block_kv, window, interpret):
    out = _flash_attention_core(q, k, v, causal, block_q, block_kv, window,
                                interpret)
    return out, (q, k, v)


def _core_bwd(causal, block_q, block_kv, window, interpret, res, g):
    """Recompute-based backward through the XLA reference implementation.

    Correct and XLA-fused; a Pallas flash backward replaces this for
    long-sequence training (tracked follow-up).
    """
    from dlti_tpu.ops.attention import reference_attention

    q, k, v = res

    def ref(q_, k_, v_):
        return reference_attention(q_, k_, v_, causal=causal,
                                   window=window or None)

    _, vjp = jax.vjp(ref, q, k, v)
    return vjp(g)


_flash_attention_core.defvjp(_core_fwd, _core_bwd)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    segment_ids=None,
    block_q: int = 512,
    block_kv: int = 512,
    window: int | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Flash attention entry. q: (b, sq, h, d); k/v: (b, skv, h_kv, d).

    GQA is handled by repeating kv heads (the MXU cost is in the matmuls,
    which are unchanged). ``window`` enables Mistral-style sliding-window
    attention with whole-block skipping outside the band. Segment masking
    falls back to the reference implementation for now.
    """
    if segment_ids is not None:
        from dlti_tpu.ops.attention import reference_attention

        return reference_attention(q, k, v, causal=causal, segment_ids=segment_ids,
                                   window=window)

    h, h_kv = q.shape[2], k.shape[2]
    if h != h_kv:
        rep = h // h_kv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return _flash_attention_core(q, k, v, causal, block_q, block_kv,
                                 window or 0, interpret)
