"""Blockwise (flash) causal attention for TPU, in Pallas — fwd and bwd.

The hot op of the whole framework. Replaces the (seq, seq) score
materialization of ``reference_attention`` with an online-softmax sweep over
KV blocks held in VMEM — O(seq) memory, MXU-sized tiles, fp32 accumulators.
The reference repo inherits its fused attention from HF/torch CUDA kernels
(``/root/reference/training/train_baseline.py:122-126`` loads the stock HF
Llama); this is the TPU-native equivalent.

Layout: the grid is (batch * kv_heads, q_blocks, kv_blocks) (fwd, dq) or
(batch * kv_heads, kv_blocks, q_blocks) (dk/dv). **GQA is native**: each
grid row processes all ``group = heads // kv_heads`` query heads of one kv
head together — q tiles are (group, block_q, d) against a single
(block_kv, d) K/V tile, so K/V are never repeated in HBM and the score
matmul keeps its MXU shape. TPU grids execute sequentially
minor-most-first, so per-block running state lives in VMEM scratch across
the innermost sweep.

**Packed sequences are native**: optional per-token segment ids mask
cross-document attention inside the kernel (id 0 = padding, matching
``reference_attention``), and whole (q, kv) tiles whose segment-id
intervals are disjoint are skipped before any MXU work — packed
long-context batches degrade toward block-diagonal cost instead of
O(seq²). Causal blocks outside the (windowed) band are likewise skipped
via ``pl.when``, and the band edges get elementwise iota masks.

Backward is the standard flash decomposition: the forward also emits the
per-row logsumexp L; the backward recomputes p = exp(qk*scale - L) per tile
(no (seq, seq) materialization), with
``D = rowsum(dO * O)``, ``dv += p^T dO``, ``ds = p * (dO v^T - D) * scale``,
``dq += ds k``, ``dk += ds^T q`` — two sweeps, O(seq) memory.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def out_struct(shape, dtype, like):
    """``jax.ShapeDtypeStruct`` carrying the varying-manual-axes (vma) of
    ``like``: inside a ``check_vma`` shard_map (e.g. the pipeline
    schedule's manual 'pipe' region) a pallas_call's out_shape must state
    how its outputs vary across manual axes, or tracing fails with
    "`vma` on `jax.ShapeDtypeStruct` must not be `None`". Outside any
    shard_map, vma is empty and this is a plain struct."""
    vma = getattr(jax.typeof(like), "vma", None)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _band_mask(qi, ki, block_q, block_kv, group, causal, window, seq_q,
               seq_kv):
    """Elementwise allowed-mask for the (qi, ki) tile.

    Shape (group*block_q, block_kv): the kernels flatten the GQA query
    group into the row dim (Mosaic's matmul lowering wants 2D operands),
    so row r is query position ``qi*block_q + r % block_q``. Combines the
    causal/sliding-window band with sequence bounds: Pallas does NOT zero
    tile padding on TPU, so rows >= seq_q / cols >= seq_kv hold garbage
    and must be masked in every kernel that *accumulates* across tiles
    (the whole backward; the non-causal forward). Returns None only when
    provably nothing needs masking.
    """
    shape = (group * block_q, block_kv)
    row = jax.lax.broadcasted_iota(jnp.int32, shape, 0)
    if group > 1:
        row = jax.lax.rem(row, block_q)
    q_pos = qi * block_q + row
    k_pos = ki * block_kv + jax.lax.broadcasted_iota(jnp.int32, shape, 1)
    padded = seq_q % block_q != 0 or seq_kv % block_kv != 0
    if not causal and not padded:
        return None
    allowed = None
    if causal:
        allowed = k_pos <= q_pos
        if window:
            allowed &= k_pos > q_pos - window
    if padded:
        bounds = (q_pos < seq_q) & (k_pos < seq_kv)
        allowed = bounds if allowed is None else (allowed & bounds)
    return allowed


def _tile_mask(qi, ki, block_q, block_kv, group, causal, window, seq_q,
               seq_kv, qseg_ref, kseg_ref):
    """Full allowed-mask: causal band ∧ bounds ∧ same-segment (id 0 = pad).
    Shape (group*block_q, block_kv) (see :func:`_band_mask`)."""
    allowed = _band_mask(qi, ki, block_q, block_kv, group, causal, window,
                         seq_q, seq_kv)
    if qseg_ref is not None:
        q_ids = qseg_ref[0]    # (block_q, 1)
        if group > 1:
            q_ids = jnp.broadcast_to(
                q_ids[None], (group, block_q, 1)).reshape(group * block_q, 1)
        kv_ids = kseg_ref[0]   # (1, block_kv)
        seg = (q_ids == kv_ids) & (kv_ids != 0)
        allowed = seg if allowed is None else (allowed & seg)
    return allowed


def _band_run(qi, ki, block_q, block_kv, causal, window):
    """Whole-tile skip predicate (conservative w.r.t. :func:`_band_mask`)."""
    if not causal:
        return True
    run = ki * block_kv <= qi * block_q + (block_q - 1)
    if window:
        run = jnp.logical_and(
            run, ki * block_kv + (block_kv - 1) > qi * block_q - window)
    return run


def _seg_run(qseg_ref, kseg_ref):
    """Dynamic whole-tile skip: if the q and kv tiles' segment-id intervals
    are disjoint, no pair can be equal and the tile contributes nothing.
    Garbage ids in tile padding only *widen* the intervals, so the skip
    stays conservative (a widened interval can only overlap more)."""
    q_ids = qseg_ref[0]
    kv_ids = kseg_ref[0]
    return jnp.logical_and(jnp.min(q_ids) <= jnp.max(kv_ids),
                           jnp.max(q_ids) >= jnp.min(kv_ids))


def _fwd_kernel(*refs, scale: float, block_q: int, block_kv: int,
                group: int, causal: bool, window: int, seq_q: int,
                seq_kv: int, has_segs: bool, window_blocks: int = 0):
    if has_segs:
        (q_ref, k_ref, v_ref, qseg_ref, kseg_ref, o_ref, lse_ref,
         m_scratch, l_scratch, acc_scratch) = refs
    else:
        (q_ref, k_ref, v_ref, o_ref, lse_ref,
         m_scratch, l_scratch, acc_scratch) = refs
        qseg_ref = kseg_ref = None
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    gbq = group * block_q
    # Windowed grid: the kv dimension enumerates only the window_blocks
    # blocks ending at q's diagonal block — blocks outside the band are
    # never visited (and never DMA'd). ki is the *virtual* kv-block index
    # the visit targets; negative values are clamped duplicate fetches of
    # block 0, fully masked and skipped below.
    if window_blocks:
        ki = ((qi + 1) * block_q - 1) // block_kv - (window_blocks - 1) + kj
    else:
        ki = kj

    @pl.when(kj == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    run = _band_run(qi, ki, block_q, block_kv, causal, window)
    if window_blocks:
        run = jnp.logical_and(run, ki >= 0)
    if has_segs:
        run = jnp.logical_and(run, _seg_run(qseg_ref, kseg_ref))

    @pl.when(run)
    def _body():
        # (group, block_q, d) -> (group*block_q, d): Mosaic's matmul wants
        # 2D operands, and the flattened form is one big MXU matmul.
        q = q_ref[0].astype(jnp.float32).reshape(gbq, -1)
        k = k_ref[0].astype(jnp.float32)  # (block_kv, d)
        v = v_ref[0].astype(jnp.float32)
        if seq_kv % block_kv != 0:
            # Zero OOB tile padding: Pallas leaves it garbage (NaN in
            # interpret mode) and the p @ v contraction sums over it —
            # 0 * NaN = NaN even though p is masked there.
            cols = ki * block_kv + jax.lax.broadcasted_iota(
                jnp.int32, (block_kv, 1), 0)
            k = jnp.where(cols < seq_kv, k, 0.0)
            v = jnp.where(cols < seq_kv, v, 0.0)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # (group*block_q, block_kv)

        allowed = _tile_mask(qi, ki, block_q, block_kv, group, causal,
                             window, seq_q, seq_kv, qseg_ref, kseg_ref)
        if allowed is not None:
            s = jnp.where(allowed, s, NEG_INF)

        m_prev = m_scratch[:]  # (group*block_q, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # Rows with no valid entry in this block have m_new == NEG_INF,
        # making exp(s - m_new) == 1 for every *masked* entry — explicitly
        # zero them (hit when block_kv > block_q admits blocks strictly
        # above a row's diagonal, or a fully-masked segment row).
        p = jnp.exp(s - m_new) * (s > NEG_INF / 2)
        alpha = jnp.exp(m_prev - m_new)  # (group*block_q, 1)
        l_new = alpha * l_scratch[:] + jnp.sum(p, axis=1, keepdims=True)
        acc_scratch[:] = acc_scratch[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_scratch[:] = m_new
        l_scratch[:] = l_new

    @pl.when(kj == pl.num_programs(2) - 1)
    def _finalize():
        l = l_scratch[:]
        safe_l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> zero output
        o_ref[0] = (acc_scratch[:] / safe_l).reshape(
            group, block_q, -1).astype(o_ref.dtype)
        # Per-row logsumexp for the backward. Fully-masked rows get +BIG so
        # the backward's exp(s - L) is exactly 0 there.
        lse = jnp.where(l > 0.0, m_scratch[:] + jnp.log(safe_l), -NEG_INF)
        lse_ref[0] = lse.reshape(group, block_q, 1)


def _window_kv_blocks(causal, window, block_q, block_kv, nk):
    """kv-block visits per q block under a sliding window (0 = full sweep).

    The band of q tile qi spans kv blocks
    [floor((qi*Bq - window + 1)/Bkv), floor(((qi+1)*Bq - 1)/Bkv)] — at
    most (Bq + window - 2)//Bkv + 1 blocks; +1 margin keeps the bound
    safe. Only worthwhile when it actually shrinks the sweep.
    """
    if not (causal and window):
        return 0
    w = (block_q + window - 2) // block_kv + 2
    return w if w < nk else 0


def _window_q_blocks(causal, window, block_q, block_kv, nq):
    """q-block visits per kv block for the dk/dv sweep (0 = full sweep)."""
    if not (causal and window):
        return 0
    w = (block_kv + window - 2) // block_q + 2
    return w if w < nq else 0


def _kv_block_index(qi, j, block_q, block_kv, window_blocks, nk):
    """Physical kv-block index for visit j of q tile qi (clamped for DMA;
    the kernel recomputes the unclamped value for masking)."""
    v = ((qi + 1) * block_q - 1) // block_kv - (window_blocks - 1) + j
    return jnp.clip(v, 0, nk - 1)


def _seg_specs(h_kv, block_q, block_kv, transposed=False, kv_index=None,
               q_index=None):
    """BlockSpecs for (b, sq, 1) q-segment and (b, 1, skv) kv-segment arrays.

    The (block_q, 1) / (1, block_kv) tile shapes let the kernel form the
    (block_q, block_kv) equality mask by broadcast — no lane<->sublane
    transposes on TPU. The grid's leading axis is batch*kv_heads; ``// h_kv``
    recovers the batch row. ``kv_index``/``q_index`` remap the minor grid
    dim for windowed sweeps.
    """
    if transposed:  # dkv grid: (bh, kv_block, q_visit)
        qix = q_index or (lambda ki, j: j)
        q_map = lambda b, jk, jq: (b // h_kv, qix(jk, jq), 0)
        kv_map = lambda b, jk, jq: (b // h_kv, 0, jk)
    else:
        kix = kv_index or (lambda i, j: j)
        q_map = lambda b, i, j: (b // h_kv, i, 0)
        kv_map = lambda b, i, j: (b // h_kv, 0, kix(i, j))
    return (pl.BlockSpec((1, block_q, 1), q_map),
            pl.BlockSpec((1, 1, block_kv), kv_map))


def _flash_fwd(q, k, v, q_seg, kv_seg, *, h_kv, scale, block_q, block_kv,
               causal, window, interpret):
    """q: (b*h_kv, group, sq, d); k/v: (b*h_kv, skv, d);
    q_seg: (b, sq, 1) / kv_seg: (b, 1, skv) or None -> (o, lse)."""
    bh, group, sq, d = q.shape
    skv = k.shape[1]
    block_q = min(block_q, sq)
    block_kv = min(block_kv, skv)
    nk = pl.cdiv(skv, block_kv)
    win_blocks = _window_kv_blocks(causal, window, block_q, block_kv, nk)
    grid = (bh, pl.cdiv(sq, block_q), win_blocks or nk)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, block_q=block_q, block_kv=block_kv,
        group=group, causal=causal, window=window, seq_q=sq, seq_kv=skv,
        has_segs=q_seg is not None, window_blocks=win_blocks,
    )
    q_spec = pl.BlockSpec((1, group, block_q, d), lambda b, i, j: (b, 0, i, 0))
    if win_blocks:
        kv_index = functools.partial(_kv_block_index, block_q=block_q,
                                     block_kv=block_kv,
                                     window_blocks=win_blocks, nk=nk)
        kv_spec = pl.BlockSpec((1, block_kv, d),
                               lambda b, i, j: (b, kv_index(i, j), 0))
    else:
        kv_index = None
        kv_spec = pl.BlockSpec((1, block_kv, d), lambda b, i, j: (b, j, 0))
    in_specs = [q_spec, kv_spec, kv_spec]
    inputs = [q, k, v]
    if q_seg is not None:
        qs_spec, ks_spec = _seg_specs(h_kv, block_q, block_kv,
                                      kv_index=kv_index)
        in_specs += [qs_spec, ks_spec]
        inputs += [q_seg, kv_seg]
    return pl.pallas_call(
        kernel,
        out_shape=(
            out_struct((bh, group, sq, d), q.dtype, q),
            out_struct((bh, group, sq, 1), jnp.float32, q),  # logsumexp
        ),
        grid=grid,
        in_specs=in_specs,
        out_specs=(
            q_spec,
            pl.BlockSpec((1, group, block_q, 1), lambda b, i, j: (b, 0, i, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((group * block_q, 1), jnp.float32),
            pltpu.VMEM((group * block_q, 1), jnp.float32),
            pltpu.VMEM((group * block_q, d), jnp.float32),
        ],
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            # Banded fraction: a windowed grid visits win_blocks kv blocks
            # per q tile instead of the causal triangle.
            flops=int(2 * 2 * bh * group * sq * d
                      * (min(win_blocks * block_kv, skv) if win_blocks
                         else skv * (0.5 if causal else 1.0))),
            bytes_accessed=(2 * q.size + k.size + v.size) * q.dtype.itemsize,
            transcendentals=int(bh * group * sq
                                * (min(win_blocks * block_kv, skv)
                                   if win_blocks else skv)),
        ),
    )(*inputs)


def _load_bwd_tiles(q_ref, k_ref, v_ref, do_ref, qi, ki, block_q, block_kv,
                    group, seq_q, seq_kv):
    """Load backward tiles (q/do flattened to (group*block_q, d)) with
    padding rows/cols zeroed.

    Pallas does not zero tile padding on TPU; the backward *accumulates*
    across tiles, so garbage (potentially inf/NaN, which survives
    multiplication by zero) in rows >= seq_q / cols >= seq_kv must be
    cleared at load time.
    """
    gbq = group * block_q
    q = q_ref[0].astype(jnp.float32).reshape(gbq, -1)
    k = k_ref[0].astype(jnp.float32)    # (block_kv, d)
    v = v_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32).reshape(gbq, -1)
    if seq_q % block_q != 0:
        rows = jax.lax.broadcasted_iota(jnp.int32, (gbq, 1), 0)
        if group > 1:
            rows = jax.lax.rem(rows, block_q)
        rows = qi * block_q + rows
        q = jnp.where(rows < seq_q, q, 0.0)
        do = jnp.where(rows < seq_q, do, 0.0)
    if seq_kv % block_kv != 0:
        cols = ki * block_kv + jax.lax.broadcasted_iota(
            jnp.int32, (block_kv, 1), 0)
        k = jnp.where(cols < seq_kv, k, 0.0)
        v = jnp.where(cols < seq_kv, v, 0.0)
    return q, k, v, do


def _dq_kernel(*refs, scale, block_q, block_kv, group, causal, window,
               seq_q, seq_kv, has_segs, window_blocks: int = 0):
    if has_segs:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qseg_ref, kseg_ref,
         dq_ref, dq_scratch) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dq_ref, dq_scratch) = refs
        qseg_ref = kseg_ref = None
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    gbq = group * block_q
    if window_blocks:  # see _fwd_kernel: virtual kv index of this visit
        ki = ((qi + 1) * block_q - 1) // block_kv - (window_blocks - 1) + kj
    else:
        ki = kj

    @pl.when(kj == 0)
    def _init():
        dq_scratch[:] = jnp.zeros_like(dq_scratch)

    run = _band_run(qi, ki, block_q, block_kv, causal, window)
    if window_blocks:
        run = jnp.logical_and(run, ki >= 0)
    if has_segs:
        run = jnp.logical_and(run, _seg_run(qseg_ref, kseg_ref))

    @pl.when(run)
    def _body():
        q, k, v, do = _load_bwd_tiles(
            q_ref, k_ref, v_ref, do_ref, qi, ki, block_q, block_kv, group,
            seq_q, seq_kv)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # (group*bq, bk)
        mask = _tile_mask(qi, ki, block_q, block_kv, group, causal, window,
                          seq_q, seq_kv, qseg_ref, kseg_ref)
        lse = lse_ref[0].reshape(gbq, 1)
        delta = delta_ref[0].reshape(gbq, 1)
        p = jnp.exp(s - lse)                               # (group*bq, bk)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        # where() (not just p==0) so garbage lse/delta in padding rows can't
        # poison the product with 0 * inf = NaN.
        ds = p * (dp - delta) * scale                      # (group*bq, bk)
        if mask is not None:
            ds = jnp.where(mask, ds, 0.0)
        dq_scratch[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(kj == pl.num_programs(2) - 1)
    def _finalize():
        dq_ref[0] = dq_scratch[:].reshape(
            group, block_q, -1).astype(dq_ref.dtype)


def _dkv_kernel(*refs, scale, block_q, block_kv, group, causal, window,
                seq_q, seq_kv, has_segs, window_q_blocks: int = 0):
    if has_segs:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qseg_ref, kseg_ref,
         dk_ref, dv_ref, dk_scratch, dv_scratch) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_scratch, dv_scratch) = refs
        qseg_ref = kseg_ref = None
    ki = pl.program_id(1)
    qj = pl.program_id(2)
    gbq = group * block_q
    if window_q_blocks:
        # Virtual q-block index of this visit: the band of kv block ki
        # starts at its own diagonal q block and extends window forward.
        qi = (ki * block_kv) // block_q + qj
    else:
        qi = qj

    @pl.when(qj == 0)
    def _init():
        dk_scratch[:] = jnp.zeros_like(dk_scratch)
        dv_scratch[:] = jnp.zeros_like(dv_scratch)

    run = _band_run(qi, ki, block_q, block_kv, causal, window)
    if window_q_blocks:
        # Clamped duplicate visits past the last real q block are masked.
        run = jnp.logical_and(run, qi * block_q < seq_q)
    if has_segs:
        run = jnp.logical_and(run, _seg_run(qseg_ref, kseg_ref))

    @pl.when(run)
    def _body():
        q, k, v, do = _load_bwd_tiles(
            q_ref, k_ref, v_ref, do_ref, qi, ki, block_q, block_kv, group,
            seq_q, seq_kv)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # (group*bq, bk)
        mask = _tile_mask(qi, ki, block_q, block_kv, group, causal, window,
                          seq_q, seq_kv, qseg_ref, kseg_ref)
        lse = lse_ref[0].reshape(gbq, 1)
        delta = delta_ref[0].reshape(gbq, 1)
        p = jnp.exp(s - lse)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        # Contract over all group*bq rows: one (bkv, group*bq) @
        # (group*bq, d) MXU matmul per tile sums the group contributions.
        dv_scratch[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        if mask is not None:
            ds = jnp.where(mask, ds, 0.0)
        dk_scratch[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qj == pl.num_programs(2) - 1)
    def _finalize():
        dk_ref[0] = dk_scratch[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scratch[:].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, q_seg, kv_seg, *, h_kv, scale, block_q,
               block_kv, causal, window, interpret):
    """q,o,do: (b*h_kv, group, s, d); k,v: (b*h_kv, s, d);
    lse: (b*h_kv, group, s, 1) -> (dq, dk, dv)."""
    bh, group, sq, d = q.shape
    skv = k.shape[1]
    block_q = min(block_q, sq)
    block_kv = min(block_kv, skv)
    nq = pl.cdiv(sq, block_q)
    nk = pl.cdiv(skv, block_kv)
    has_segs = q_seg is not None
    win_blocks = _window_kv_blocks(causal, window, block_q, block_kv, nk)
    win_q_blocks = _window_q_blocks(causal, window, block_q, block_kv, nq)

    # D_i = rowsum(dO_i * O_i) — tiny elementwise pass, XLA-fused.
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)

    q_spec = pl.BlockSpec((1, group, block_q, d), lambda b, i, j: (b, 0, i, 0))
    if win_blocks:
        kv_index = functools.partial(_kv_block_index, block_q=block_q,
                                     block_kv=block_kv,
                                     window_blocks=win_blocks, nk=nk)
        kv_spec = pl.BlockSpec((1, block_kv, d),
                               lambda b, i, j: (b, kv_index(i, j), 0))
    else:
        kv_index = None
        kv_spec = pl.BlockSpec((1, block_kv, d), lambda b, i, j: (b, j, 0))
    row_spec = pl.BlockSpec((1, group, block_q, 1), lambda b, i, j: (b, 0, i, 0))

    in_specs = [q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec]
    inputs = [q, k, v, do, lse, delta]
    if has_segs:
        qs_spec, ks_spec = _seg_specs(h_kv, block_q, block_kv,
                                      kv_index=kv_index)
        in_specs += [qs_spec, ks_spec]
        inputs += [q_seg, kv_seg]
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, block_q=block_q,
                          block_kv=block_kv, group=group, causal=causal,
                          window=window, seq_q=sq, seq_kv=skv,
                          has_segs=has_segs, window_blocks=win_blocks),
        out_shape=out_struct((bh, group, sq, d), q.dtype, q),
        grid=(bh, nq, win_blocks or nk),
        in_specs=in_specs,
        out_specs=q_spec,
        scratch_shapes=[pltpu.VMEM((group * block_q, d), jnp.float32)],
        interpret=interpret,
    )(*inputs)

    # dk/dv sweep: grid transposed so kv blocks are outer, q inner.
    if win_q_blocks:
        def q_index(jk, jq):
            return jnp.clip((jk * block_kv) // block_q + jq, 0, nq - 1)
    else:
        q_index = None
    qix = q_index or (lambda jk, jq: jq)
    q_spec_t = pl.BlockSpec((1, group, block_q, d),
                            lambda b, jk, jq: (b, 0, qix(jk, jq), 0))
    kv_spec_t = pl.BlockSpec((1, block_kv, d), lambda b, jk, jq: (b, jk, 0))
    row_spec_t = pl.BlockSpec((1, group, block_q, 1),
                              lambda b, jk, jq: (b, 0, qix(jk, jq), 0))
    in_specs_t = [q_spec_t, kv_spec_t, kv_spec_t, q_spec_t, row_spec_t,
                  row_spec_t]
    inputs_t = [q, k, v, do, lse, delta]
    if has_segs:
        qs_spec_t, ks_spec_t = _seg_specs(h_kv, block_q, block_kv,
                                          transposed=True, q_index=q_index)
        in_specs_t += [qs_spec_t, ks_spec_t]
        inputs_t += [q_seg, kv_seg]
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, block_q=block_q,
                          block_kv=block_kv, group=group, causal=causal,
                          window=window, seq_q=sq, seq_kv=skv,
                          has_segs=has_segs, window_q_blocks=win_q_blocks),
        out_shape=(out_struct((bh, skv, d), k.dtype, k),
                   out_struct((bh, skv, d), v.dtype, v)),
        grid=(bh, nk, win_q_blocks or nq),
        in_specs=in_specs_t,
        out_specs=(kv_spec_t, kv_spec_t),
        scratch_shapes=[pltpu.VMEM((block_kv, d), jnp.float32),
                        pltpu.VMEM((block_kv, d), jnp.float32)],
        interpret=interpret,
    )(*inputs_t)
    return dq, dk, dv


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8)
)
def _flash_attention_core(q, k, v, segment_ids, causal, block_q, block_kv,
                          window, interpret):
    """(b, s, h, d) attention; GQA and packing handled inside the kernels."""
    return _core_fwd(q, k, v, segment_ids, causal, block_q, block_kv,
                     window, interpret)[0]


def _split_heads(q, k, v):
    """(b, s, h, d) q -> (b*h_kv, group, s, d); k/v -> (b*h_kv, s, d).

    Query head ``kh * group + g`` reads kv head ``kh`` — the same layout
    ``repeat_kv`` produces, so results are bit-comparable with the
    reference path.
    """
    b, sq, h, d = q.shape
    h_kv = k.shape[2]
    group = h // h_kv
    qt = (q.transpose(0, 2, 1, 3)
          .reshape(b * h_kv, group, sq, d))
    kt = k.transpose(0, 2, 1, 3).reshape(b * h_kv, k.shape[1], d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h_kv, v.shape[1], d)
    return qt, kt, vt, h_kv, group


def _core_fwd(q, k, v, segment_ids, causal, block_q, block_kv, window,
              interpret):
    b, sq, h, d = q.shape
    scale = d ** -0.5
    qt, kt, vt, h_kv, group = _split_heads(q, k, v)
    if segment_ids is not None:
        if k.shape[1] != sq:
            raise ValueError(
                f"flash_attention segment masking requires self-attention "
                f"shapes (one segment_ids array for both sides); got "
                f"sq={sq}, skv={k.shape[1]}")
        seg = segment_ids.astype(jnp.int32)
        q_seg = seg[:, :, None]   # (b, sq, 1): block tile (block_q, 1)
        kv_seg = seg[:, None, :]  # (b, 1, skv): block tile (1, block_kv)
    else:
        q_seg = kv_seg = None
    o, lse = _flash_fwd(qt, kt, vt, q_seg, kv_seg, h_kv=h_kv, scale=scale,
                        block_q=block_q, block_kv=block_kv, causal=causal,
                        window=window, interpret=interpret)
    out = (o.reshape(b, h, sq, d).transpose(0, 2, 1, 3))
    return out, (qt, kt, vt, o, lse, q_seg, kv_seg)


def _core_bwd(causal, block_q, block_kv, window, interpret, res, g):
    """Flash backward: tile-recomputed p from the saved logsumexp."""
    qt, kt, vt, o, lse, q_seg, kv_seg = res
    bh, group, sq, d = qt.shape
    b = g.shape[0]
    h = g.shape[2]
    h_kv = bh // b
    scale = d ** -0.5
    do = g.transpose(0, 2, 1, 3).reshape(bh, group, sq, d)
    dq, dk, dv = _flash_bwd(
        qt, kt, vt, o, lse, do, q_seg, kv_seg, h_kv=h_kv, scale=scale,
        block_q=block_q, block_kv=block_kv, causal=causal, window=window,
        interpret=interpret)

    dq_out = dq.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    skv = kt.shape[1]
    dk_out = dk.reshape(b, h_kv, skv, d).transpose(0, 2, 1, 3)
    dv_out = dv.reshape(b, h_kv, skv, d).transpose(0, 2, 1, 3)
    dseg = (None if q_seg is None
            else np.zeros(g.shape[:1] + (sq,), jax.dtypes.float0))
    return dq_out, dk_out, dv_out, dseg


_flash_attention_core.defvjp(_core_fwd, _core_bwd)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    segment_ids=None,
    block_q: int = 512,
    block_kv: int = 512,
    window: int | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Flash attention entry. q: (b, sq, h, d); k/v: (b, skv, h_kv, d).

    GQA runs natively in the kernel (each kv head's query group shares its
    K/V tile — nothing is repeated in HBM). ``window`` enables
    Mistral-style sliding-window attention with whole-block skipping
    outside the band. ``segment_ids`` (b, s) enables packed-sequence
    masking with whole-block skipping of segment-disjoint tiles; id 0 is
    padding (such tokens attend to nothing and produce zero output).
    """
    return _flash_attention_core(q, k, v, segment_ids, causal, block_q,
                                 block_kv, window or 0, interpret)
