"""Attention ops.

The reference delegates attention to HF ``LlamaAttention`` CUDA paths; here
we provide:

* :func:`multi_head_attention` — XLA reference implementation (einsum-based,
  GQA-capable, causal + padding masks). XLA fuses this well on TPU and it is
  the numerically-trusted baseline for kernel tests.
* A Pallas flash-attention path (``dlti_tpu.ops.pallas.flash_attention``)
  selected via ``ModelConfig.attention_impl`` — blockwise, never materializes
  the (seq, seq) score matrix, keeps the MXU fed at long sequence lengths.

Dispatch policy ("auto"): flash on TPU when shapes are tile-aligned,
reference otherwise (CPU tests, tiny shapes).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def repeat_kv(x: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    """(b, s, kv_heads, d) -> (b, s, kv_heads * n_rep, d) for GQA."""
    if n_rep == 1:
        return x
    b, s, h, d = x.shape
    return jnp.broadcast_to(x[:, :, :, None, :], (b, s, h, n_rep, d)).reshape(b, s, h * n_rep, d)


def make_causal_mask(q_len: int, kv_len: int, dtype=jnp.float32,
                     window: int | None = None) -> jnp.ndarray:
    """Additive causal mask of shape (1, 1, q_len, kv_len).

    Supports q_len < kv_len (decode with cache): query i attends to
    kv positions <= (kv_len - q_len + i). ``window`` adds Mistral-style
    sliding-window locality: only the last ``window`` positions (query
    included) stay visible.
    """
    q_pos = jnp.arange(q_len)[:, None] + (kv_len - q_len)
    kv_pos = jnp.arange(kv_len)[None, :]
    allowed = kv_pos <= q_pos
    if window is not None:
        allowed &= kv_pos > q_pos - window
    return jnp.where(allowed, 0.0, jnp.finfo(dtype).min)[None, None, :, :].astype(dtype)


def reference_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    segment_ids: jnp.ndarray | None = None,
    kv_segment_ids: jnp.ndarray | None = None,
    q_positions: jnp.ndarray | None = None,
    kv_positions: jnp.ndarray | None = None,
    window: int | None = None,
    softmax_dtype=jnp.float32,
) -> jnp.ndarray:
    """Plain XLA attention. q: (b, sq, h, d); k/v: (b, skv, h_kv, d).

    Softmax is computed in float32 (TPU-friendly: bf16 matmuls on the MXU,
    fp32 VPU reductions). ``segment_ids`` enables packed-sequence masking:
    tokens attend only within their own segment; id 0 = padding.
    ``q_positions``/``kv_positions`` (b, s) give explicit token positions for
    causal masking — required for KV-cached decode where the cache capacity
    exceeds the written region (slot index == position by construction).
    ``window`` is Mistral-style sliding-window locality (needs ``causal``).
    """
    b, sq, num_heads, head_dim = q.shape
    num_kv = k.shape[2]
    k = repeat_kv(k, num_heads // num_kv)
    v = repeat_kv(v, num_heads // num_kv)

    scale = head_dim ** -0.5
    # (b, h, sq, skv) scores on the MXU in compute dtype, accumulated fp32.
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=softmax_dtype)
    scores = scores.astype(softmax_dtype) * scale

    skv = k.shape[1]
    if causal:
        if q_positions is not None:
            kv_pos = (kv_positions if kv_positions is not None
                      else jnp.broadcast_to(jnp.arange(skv)[None, :], (b, skv)))
            allowed = kv_pos[:, None, :] <= q_positions[:, :, None]
            if window is not None:
                allowed &= kv_pos[:, None, :] > q_positions[:, :, None] - window
            scores = scores + jnp.where(
                allowed, 0.0, jnp.finfo(softmax_dtype).min
            )[:, None, :, :].astype(softmax_dtype)
        else:
            scores = scores + make_causal_mask(sq, skv, softmax_dtype,
                                               window=window)
    if segment_ids is not None:
        kv_seg = kv_segment_ids if kv_segment_ids is not None else segment_ids
        same = (segment_ids[:, :, None] == kv_seg[:, None, :]) & (kv_seg[:, None, :] != 0)
        scores = jnp.where(same[:, None, :, :], scores, jnp.finfo(softmax_dtype).min)

    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v,
                     preferred_element_type=softmax_dtype)
    return out.astype(q.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "impl", "block_q", "block_kv", "window")
)
def multi_head_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    segment_ids: jnp.ndarray | None = None,
    impl: str = "auto",
    block_q: int = 512,
    block_kv: int = 512,
    window: int | None = None,
) -> jnp.ndarray:
    """Dispatching attention entry point used by the model.

    impl: "reference" | "flash" | "auto". "auto" picks flash on TPU for
    tile-aligned causal self-attention shapes — packed batches included
    (segment masking runs inside the kernel) — else reference. Sliding
    ``window`` works on both paths (flash skips whole blocks outside the
    band).
    """
    use_flash = False
    if impl == "flash":
        use_flash = True
    elif impl == "auto":
        on_tpu = jax.default_backend() == "tpu"
        sq, skv, hd = q.shape[1], k.shape[1], q.shape[3]
        aligned = sq % 128 == 0 and skv % 128 == 0 and hd % 128 == 0 and sq == skv
        use_flash = on_tpu and aligned and causal

    if use_flash:
        from dlti_tpu.ops.pallas.flash_attention import flash_attention

        # interpret ONLY on the cpu backend: impl="flash" then works —
        # slowly — on the CPU test harness, so flash-path compositions
        # (e.g. flash inside pipeline stages) are testable without a
        # chip. Gate on == "cpu", not != "tpu": this image's relay
        # backend is named "axon", and a != "tpu" check would silently
        # flip the hot kernel to interpret mode on the real chip.
        return flash_attention(
            q, k, v, causal=causal, segment_ids=segment_ids,
            block_q=block_q, block_kv=block_kv, window=window,
            interpret=jax.default_backend() == "cpu",
        )
    return reference_attention(q, k, v, causal=causal, segment_ids=segment_ids,
                               window=window)
