"""Paged KV cache — device-side ops.

The reference claims a vLLM serving leg ("PagedAttention, continuous
batching", ``README.md:10``; ``requirements.txt:18``) but ships no code.
This is the TPU-native equivalent of vLLM's block-based KV cache, designed
for XLA's static-shape model:

* One physical pool per layer: ``(num_blocks, block_size, kv_heads, head_dim)``
  living in HBM for the whole engine lifetime (no per-request allocation).
* A ``block_tables`` int32 array ``(batch, max_blocks_per_seq)`` maps each
  sequence's *logical* block ``i`` to a physical block id. Logical token
  position ``p`` lives at physical row ``block_tables[b, p // bs]`` offset
  ``p % bs``.
* Writes are flat scatters (``.at[...].set(mode="drop")``) — out-of-range
  slot ids (padding tokens) are dropped, so prefill and decode share one
  compiled update path.
* The XLA reference read path gathers a sequence's blocks back into a
  contiguous ``(batch, max_kv, kv_heads, head_dim)`` window; causal masking
  against explicit positions hides stale/unallocated slots (unwritten
  logical positions are always > the query position). The Pallas kernel
  (``dlti_tpu.ops.pallas.paged_attention``) reads blocks in place instead.

All functions are pure; the host-side block allocator lives in
``dlti_tpu.serving.block_manager``.
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp

# int8 KV: pools store symmetric per-token-per-kv-head int8 (absmax over
# head_dim -> one fp32 scale per written row), halving KV HBM vs bf16 —
# the pool is the serving engine's biggest allocation after the weights,
# so the freed memory goes straight into more decode slots. Quantization
# happens once at write (paged_update); consumers either dequantize after
# gather (XLA fallback / prefill / TP path) or fold the scales into the
# attention math in place (the Pallas decode kernel).


def init_paged_cache(
    num_layers: int,
    num_blocks: int,
    block_size: int,
    num_kv_heads: int,
    head_dim: int,
    dtype=jnp.bfloat16,
) -> List[dict]:
    """Allocate the physical block pools, one ``{"k", "v"}`` dict per layer.

    ``dtype="int8"`` (the string, or ``jnp.int8``) selects the quantized
    pool layout: int8 payloads plus ``{"k_scale", "v_scale"}`` fp32 arrays
    of shape ``(num_blocks, block_size, kv_heads)``.
    """
    shape = (num_blocks, block_size, num_kv_heads, head_dim)
    if dtype == "int8" or dtype == jnp.int8:
        sshape = (num_blocks, block_size, num_kv_heads)
        return [
            {"k": jnp.zeros(shape, jnp.int8),
             "v": jnp.zeros(shape, jnp.int8),
             "k_scale": jnp.zeros(sshape, jnp.float32),
             "v_scale": jnp.zeros(sshape, jnp.float32)}
            for _ in range(num_layers)
        ]
    return [
        {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
        for _ in range(num_layers)
    ]


def _quantize_rows(x: jnp.ndarray):
    """Per-(token, kv_head) symmetric int8 over the trailing head_dim."""
    x32 = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x32), axis=-1, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(x32 / scale), -127, 127).astype(jnp.int8)
    return q, scale[..., 0]


def slot_mapping(block_tables: jnp.ndarray, positions: jnp.ndarray,
                 block_size: int, num_blocks: int) -> jnp.ndarray:
    """Flat physical slot index for each (batch, seq) token.

    ``positions`` are logical token positions; negative positions (padding)
    map to an out-of-range slot so the scatter drops them.
    """
    blk = jnp.maximum(positions, 0) // block_size
    off = jnp.maximum(positions, 0) % block_size
    phys = jnp.take_along_axis(block_tables, blk, axis=1)
    slots = phys * block_size + off
    oob = num_blocks * block_size  # one past the end -> dropped by mode="drop"
    return jnp.where(positions >= 0, slots, oob)


def paged_update(layer_cache: dict, k_new: jnp.ndarray, v_new: jnp.ndarray,
                 slots: jnp.ndarray) -> dict:
    """Scatter new K/V rows into the physical pool.

    ``k_new``/``v_new``: (batch, s, kv_heads, head_dim); ``slots``: (batch, s)
    flat physical slot ids from :func:`slot_mapping`.
    """
    k_pool, v_pool = layer_cache["k"], layer_cache["v"]
    nb, bs, kvh, hd = k_pool.shape
    flat = slots.reshape(-1)
    out = dict(layer_cache)
    if k_pool.dtype == jnp.int8:
        kq, ks = _quantize_rows(k_new)
        vq, vs = _quantize_rows(v_new)
        out["k_scale"] = (layer_cache["k_scale"].reshape(nb * bs, kvh)
                          .at[flat].set(ks.reshape(-1, kvh), mode="drop")
                          .reshape(nb, bs, kvh))
        out["v_scale"] = (layer_cache["v_scale"].reshape(nb * bs, kvh)
                          .at[flat].set(vs.reshape(-1, kvh), mode="drop")
                          .reshape(nb, bs, kvh))
        k_new, v_new = kq, vq
    k_flat = k_pool.reshape(nb * bs, kvh, hd)
    v_flat = v_pool.reshape(nb * bs, kvh, hd)
    out["k"] = k_flat.at[flat].set(
        k_new.reshape(-1, kvh, hd).astype(k_pool.dtype),
        mode="drop").reshape(nb, bs, kvh, hd)
    out["v"] = v_flat.at[flat].set(
        v_new.reshape(-1, kvh, hd).astype(v_pool.dtype),
        mode="drop").reshape(nb, bs, kvh, hd)
    return out


def paged_gather(layer_cache: dict, block_tables: jnp.ndarray):
    """Gather each sequence's logical KV window from the pool.

    Returns (k, v) of shape (batch, max_blocks*block_size, kv_heads, head_dim)
    in logical order; garbage beyond a sequence's written length is masked by
    the caller's causal/position mask. int8 pools dequantize to the fp32
    product (scales are fp32) — callers cast to their compute dtype, so
    fp32 paths don't pay an extra bf16 rounding step on the way through.
    """
    k_pool, v_pool = layer_cache["k"], layer_cache["v"]
    nb, bs, kvh, hd = k_pool.shape
    b, max_blk = block_tables.shape
    k = k_pool[block_tables].reshape(b, max_blk * bs, kvh, hd)
    v = v_pool[block_tables].reshape(b, max_blk * bs, kvh, hd)
    if k_pool.dtype == jnp.int8:
        # Dequantize the gathered window (gather moves 1/2 the bytes of a
        # bf16 pool; the expansion happens on the small window).
        ks = layer_cache["k_scale"][block_tables].reshape(b, max_blk * bs, kvh, 1)
        vs = layer_cache["v_scale"][block_tables].reshape(b, max_blk * bs, kvh, 1)
        k = k.astype(jnp.float32) * ks
        v = v.astype(jnp.float32) * vs
    return k, v
