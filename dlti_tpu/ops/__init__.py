"""TPU-native compute ops: RoPE, attention (XLA reference + Pallas flash)."""

from dlti_tpu.ops.rope import apply_rope, rope_frequencies  # noqa: F401
from dlti_tpu.ops.attention import multi_head_attention  # noqa: F401
