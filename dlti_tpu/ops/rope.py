"""Rotary position embeddings (RoPE), Llama-style.

The reference gets RoPE implicitly through HF ``LlamaModel``
(``training/train_baseline.py:122-126`` loads ``meta-llama/Llama-2-7b-hf``);
here it is implemented directly. Uses the split-half rotation convention
(matching HF Llama), computed in float32 for numerical parity and cast back
to the compute dtype.
"""

from __future__ import annotations

import jax.numpy as jnp


def assert_rope_table_covers(table_len: int, needed_len: int,
                             context: str = "") -> None:
    """Trace-time guard for the table-sizing invariant.

    :func:`apply_rope` gathers with ``mode="clip"`` (no per-gather bounds
    check — see the comment there), so an under-sized cos/sin table no
    longer NaNs loudly: it silently clamps rotary angles (the r03 bug
    class, seq 512 > table 128). Call this wherever the maximum position
    is STATICALLY known (both arguments are Python ints at trace time —
    sequence lengths and table sizes are static under jit), so a future
    mis-sized caller fails at trace time instead of training on wrong
    rotations.
    """
    if table_len < needed_len:
        raise ValueError(
            f"RoPE table of length {table_len} cannot cover positions up "
            f"to {needed_len - 1}{' (' + context + ')' if context else ''}; "
            "apply_rope gathers with mode='clip' and would silently clamp "
            "rotary angles — size the table to >= max position + 1")


def rope_frequencies(head_dim: int, max_seq_len: int, theta: float = 10000.0) -> tuple:
    """Precompute cos/sin tables of shape ``(max_seq_len, head_dim // 2)``."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)  # (seq, head_dim//2)
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray,
               positions: jnp.ndarray) -> jnp.ndarray:
    """Rotate ``x`` of shape (batch, seq, heads, head_dim) by position.

    ``positions`` is (batch, seq) int32 — explicit so the same op serves
    packed sequences and KV-cached decode (where position != index).
    """
    orig_dtype = x.dtype
    half = x.shape[-1] // 2
    # Gather per-token tables: (batch, seq, half) -> broadcast over heads.
    # mode="clip", not the default "fill": positions are in-range by
    # construction (callers size the table to cover the actual sequence —
    # models/llama.py sizes it past max_seq_len), the NaN-fill bounds
    # check costs a lax.cond per gather, and that cond's branches type
    # differently under nested shard_map vma checking (PP x SP: the fill
    # branch is device-invariant while the gather branch varies over
    # 'pipe') — clip has no cond at all.
    cos_p = jnp.take(cos, positions, axis=0,
                     mode="clip")[:, :, None, :].astype(jnp.float32)
    sin_p = jnp.take(sin, positions, axis=0,
                     mode="clip")[:, :, None, :].astype(jnp.float32)
    x = x.astype(jnp.float32)
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate(
        [x1 * cos_p - x2 * sin_p, x2 * cos_p + x1 * sin_p], axis=-1
    )
    return rotated.astype(orig_dtype)
