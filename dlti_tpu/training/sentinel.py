"""Numeric fault tolerance: loss-spike/NaN sentinel, data quarantine, SDC.

PRs 4 and 6 made training survive *process* death; this module defends
against *numeric* death — the failure mode that doesn't crash anything
and therefore trains garbage until a human notices (the OPT-175B logbook
spent weeks in a manual "rewind and skip" loop; MegaScale automates the
detect→attribute→recover cycle). Three layers:

* **Detection** (:class:`NumericSentinel` + :class:`SpikeDetector`):
  every optimizer step's ``loss`` / ``grad_norm`` — already synced to the
  host in the compiled step's metrics, so detection adds ZERO extra
  device round-trips — is checked for nonfinite values and for spikes
  against a rolling-median window. Nonfinite steps additionally *skip
  the optimizer update inside the compiled step* (``training.step``
  gates on ``isfinite`` for bf16 exactly as the fp16 scaler always did),
  so a transient NaN costs one wasted batch, not a poisoned run.
* **Recovery** (:class:`DataSkipList` + the Trainer's rollback path): N
  consecutive anomalies restore the last digest-verified checkpoint
  (``checkpoint.restore_latest_verified``) and strike the data windows
  that fed the anomalous steps. A struck window is *replayed* once (a
  transient hardware hiccup passes the second time); a window that
  triggers rollback ``quarantine_after`` times is quarantined
  permanently — recorded in the ``train_meta.json`` sidecar and in a
  standalone ``sentinel_skiplist.json`` (crash-persistent between
  saves) — and the data feed skips it forever after, on this run and on
  every resume.
* **SDC detection** (:func:`replicated_param_digest` +
  :func:`attribute_suspects`): after an update, every data-parallel
  replica must hold bit-identical values for every cross-process
  *replicated* parameter leaf. Every ``sdc_check_interval`` steps each
  rank hashes its local copy and the digests are allgathered; a rank
  off the majority digest (ties break toward rank 0) is the suspect
  host — it writes a flight dump and exits with :data:`SDC_EXIT_CODE`
  so the elastic supervisor (PR 6) books it failed and reshapes the
  mesh around it, while healthy ranks exit clean for relaunch.

Metric names are a scrape contract (pinned in
``tests/test_bench_contract.py``): ``dlti_sentinel_*`` and ``dlti_sdc_*``.
"""

from __future__ import annotations

import collections
import hashlib
import json
import math
import os
import statistics
from typing import Any, Dict, Iterable, List, Optional, Tuple

from dlti_tpu.telemetry.registry import Counter
from dlti_tpu.utils import durable_io
from dlti_tpu.utils.logging import get_logger

# Name-stability contract (pinned in tests/test_bench_contract.py).
SENTINEL_METRIC_NAMES = (
    "dlti_sentinel_anomalies_total",
    "dlti_sentinel_skipped_updates_total",
    "dlti_sentinel_rollbacks_total",
    "dlti_sentinel_quarantined_windows_total",
)
SDC_METRIC_NAMES = (
    "dlti_sdc_probes_total",
    "dlti_sdc_mismatches_total",
)

anomalies_total = Counter(
    SENTINEL_METRIC_NAMES[0],
    help="anomalous optimizer steps, labeled by kind "
         "(nonfinite | loss_spike | grad_spike)")
skipped_updates_total = Counter(
    SENTINEL_METRIC_NAMES[1],
    help="optimizer updates skipped because grads/loss were nonfinite")
rollbacks_total = Counter(
    SENTINEL_METRIC_NAMES[2],
    help="automatic rollbacks to the last verified checkpoint")
quarantined_windows_total = Counter(
    SENTINEL_METRIC_NAMES[3],
    help="data windows permanently quarantined after repeated rollbacks")
sdc_probes_total = Counter(
    SDC_METRIC_NAMES[0],
    help="cross-rank parameter-digest integrity probes run")
sdc_mismatches_total = Counter(
    SDC_METRIC_NAMES[1],
    help="cross-rank digest mismatches (suspected silent data corruption)")

# Exit code of a rank that flagged ITSELF as the SDC suspect: distinctive
# (clear of shell/signal codes and the watchdog's 86) so the elastic
# supervisor's failure event attributes the eviction to corruption, not a
# crash. Healthy peers exit 0, so the supervisor books exactly one
# failed slot — the suspect host — and reshapes around it.
SDC_EXIT_CODE = 87

_ANOMALY_KINDS = ("nonfinite", "loss_spike", "grad_spike")


class SentinelGiveUp(RuntimeError):
    """The rollback budget is exhausted: anomalies persist through every
    automatic recovery the sentinel is allowed, so a human must look."""


# ----------------------------------------------------------------------
# Spike detection (host-side window math over already-synced metrics)
# ----------------------------------------------------------------------

class SpikeDetector:
    """Rolling-median spike detector for one scalar series.

    ``update(v)`` returns True when ``v`` exceeds ``factor`` x the median
    of the last ``window`` *normal* readings (and exceeds it by at least
    ``min_delta`` in absolute terms, so near-zero baselines don't turn
    noise into spikes). Cold start: nothing fires until ``min_samples``
    normal readings have been seen — the first steps of a run have no
    baseline to spike against. Re-arm semantics: a spiking value is NOT
    admitted into the window, so a burst of consecutive spikes keeps
    being judged against the pre-spike baseline instead of normalizing
    itself away; the window resumes growing from the first normal value
    after the burst. Nonfinite values are ignored (the nonfinite check
    is its own, stronger verdict).
    """

    def __init__(self, window: int = 32, min_samples: int = 8,
                 factor: float = 2.0, min_delta: float = 0.0):
        if window < 1 or min_samples < 1:
            raise ValueError("window and min_samples must be >= 1")
        self.window = window
        self.min_samples = min_samples
        self.factor = factor
        self.min_delta = min_delta
        self._values: collections.deque = collections.deque(maxlen=window)

    @property
    def ready(self) -> bool:
        return len(self._values) >= self.min_samples

    @property
    def median(self) -> float:
        return statistics.median(self._values) if self._values else 0.0

    def update(self, v: float) -> bool:
        v = float(v)
        if not math.isfinite(v):
            return False
        if self.ready:
            med = self.median
            if v > self.factor * med and (v - med) > self.min_delta:
                return True  # spike: keep it OUT of the baseline window
        self._values.append(v)
        return False

    def reset(self) -> None:
        self._values.clear()


# ----------------------------------------------------------------------
# The per-run sentinel: streak accounting + rollback escalation
# ----------------------------------------------------------------------

class NumericSentinel:
    """Per-step anomaly verdicts + the consecutive-anomaly streak that
    escalates to rollback. Pure host-side bookkeeping over metrics the
    compiled step already returns."""

    def __init__(self, cfg):
        self.cfg = cfg
        self.loss_spikes = SpikeDetector(
            window=cfg.window, min_samples=cfg.min_samples,
            factor=cfg.loss_spike_factor)
        self.grad_spikes = SpikeDetector(
            window=cfg.window, min_samples=cfg.min_samples,
            factor=cfg.grad_spike_factor)
        # (step, kind) of the current consecutive-anomaly streak.
        self.streak: List[Tuple[int, str]] = []
        self.rollbacks = 0
        self.counts: Dict[str, int] = {
            "nonfinite": 0, "loss_spike": 0, "grad_spike": 0,
            "skipped_updates": 0}

    def observe(self, step: int, loss: float, grad_norm: float,
                skipped_update: bool) -> dict:
        """One optimizer step's verdict. Returns ``{"kind": str,
        "rollback_due": bool, "streak": [(step, kind), ...]}`` — ``kind``
        is "" for a clean step."""
        kind = ""
        if not (math.isfinite(loss) and math.isfinite(grad_norm)):
            kind = "nonfinite"
        else:
            if self.loss_spikes.update(loss):
                kind = "loss_spike"
            if self.grad_spikes.update(grad_norm) and not kind:
                kind = "grad_spike"
        if skipped_update:
            self.counts["skipped_updates"] += 1
            skipped_updates_total.inc()
        if kind:
            self.counts[kind] += 1
            anomalies_total.labels(kind=kind).inc()
            self.streak.append((int(step), kind))
        else:
            self.streak.clear()
        due = (self.cfg.rollback_after > 0
               and len(self.streak) >= self.cfg.rollback_after)
        return {"kind": kind, "rollback_due": due,
                "streak": list(self.streak)}

    def note_rollback(self) -> None:
        self.rollbacks += 1
        rollbacks_total.inc()
        self.streak.clear()
        # The pre-anomaly baseline is still the best available estimate of
        # normal; keep the windows (the rolled-back steps were never
        # admitted — spikes stay out, and nonfinite values are ignored).

    def over_budget(self) -> bool:
        return self.rollbacks >= max(1, self.cfg.max_rollbacks)

    def scalars(self) -> dict:
        """Ring/steplog-friendly counter snapshot (the watchdog's
        loss_spike / nonfinite_step rules watch these keys)."""
        return {
            "sentinel_nonfinite_steps": self.counts["nonfinite"],
            "sentinel_loss_spikes": self.counts["loss_spike"],
            "sentinel_grad_spikes": self.counts["grad_spike"],
            "sentinel_skipped_updates": self.counts["skipped_updates"],
            "sentinel_rollbacks": self.rollbacks,
        }


# ----------------------------------------------------------------------
# Persistent data quarantine (the OPT "skip the bad shard" loop, durable)
# ----------------------------------------------------------------------

class DataSkipList:
    """Strike-counted skip-list of data windows, keyed by *global data
    position* (the index of the batch in the schedule: ``epoch *
    steps_per_epoch + step_in_epoch``) — NOT by optimizer step, which
    renumbers once windows are skipped.

    A window implicated in a rollback gets a strike and is *replayed*
    (transient numeric faults pass on the second try); at
    ``quarantine_after`` strikes it is quarantined permanently and the
    data feed skips it on this run and every resume. The list persists
    two ways: merged into every checkpoint's ``train_meta.json`` sidecar
    and written to ``sentinel_skiplist.json`` immediately at each
    rollback (rollbacks happen *between* saves, and losing the strikes
    to a crash would only cost an extra detect→rollback cycle — but not
    losing them is cheaper).
    """

    FILENAME = "sentinel_skiplist.json"

    def __init__(self, quarantine_after: int = 2):
        self.quarantine_after = max(1, int(quarantine_after))
        # pos -> {"strikes": int, "quarantined": bool, "last_step": int}
        self.windows: Dict[int, dict] = {}

    # -- strikes --------------------------------------------------------
    def strike(self, positions: Iterable[int], step: int) -> List[int]:
        """+1 strike for each implicated window; returns the positions
        this call pushed over the quarantine threshold."""
        newly = []
        for pos in sorted({int(p) for p in positions}):
            w = self.windows.setdefault(
                pos, {"strikes": 0, "quarantined": False, "last_step": 0})
            w["strikes"] += 1
            w["last_step"] = int(step)
            if not w["quarantined"] and w["strikes"] >= self.quarantine_after:
                w["quarantined"] = True
                newly.append(pos)
                quarantined_windows_total.inc()
        return newly

    def quarantined(self) -> set:
        return {p for p, w in self.windows.items() if w["quarantined"]}

    def __len__(self) -> int:
        return len(self.windows)

    # -- (de)serialization ---------------------------------------------
    def to_meta(self) -> List[dict]:
        return [{"pos": p, **w} for p, w in sorted(self.windows.items())]

    def merge_meta(self, entries: Optional[Iterable[dict]]) -> None:
        """Merge a sidecar/file skip-list into this one (max strikes win,
        quarantine is sticky) — resume unions every source it finds."""
        for e in entries or ():
            try:
                pos = int(e["pos"])
            except (KeyError, TypeError, ValueError):
                continue
            w = self.windows.setdefault(
                pos, {"strikes": 0, "quarantined": False, "last_step": 0})
            w["strikes"] = max(w["strikes"], int(e.get("strikes", 0)))
            w["quarantined"] = w["quarantined"] or bool(
                e.get("quarantined", False))
            w["last_step"] = max(w["last_step"], int(e.get("last_step", 0)))

    def save(self, directory: str) -> None:
        """Atomic write of the standalone skip-list file (rollbacks land
        between checkpoint saves; this survives a crash in that gap)."""
        path = os.path.join(directory, self.FILENAME)
        try:
            os.makedirs(directory, exist_ok=True)
        except OSError:
            get_logger().exception("sentinel skip-list write failed")
            return
        durable_io.write_json_atomic(
            path, {"format": 1, "windows": self.to_meta()},
            path_class="sentinel", indent=1, sort_keys=True)

    def load(self, directory: str) -> None:
        path = os.path.join(directory, self.FILENAME)
        try:
            with open(path) as f:
                self.merge_meta(json.load(f).get("windows", []))
        except (OSError, ValueError):
            pass


# ----------------------------------------------------------------------
# Cross-rank SDC probe (digest + allgather + attribution)
# ----------------------------------------------------------------------

def replicated_param_digest(params: Any) -> Tuple[bytes, int]:
    """SHA-256 over this process's local copy of every *fully replicated*
    param leaf (path + bytes, flatten order). Data-parallel replicas must
    hold bit-identical values for these after an update — sharded leaves
    (ZeRO-3 kernels, TP dims) legitimately differ per rank and are
    excluded; under FSDP the probe still covers the replicated small
    leaves (norm scales, LoRA factors below the FSDP size floor).
    Returns ``(digest, leaves_hashed)``."""
    import jax
    import numpy as np

    h = hashlib.sha256()
    n = 0
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        if not hasattr(leaf, "sharding") or not hasattr(leaf, "dtype"):
            continue
        if not getattr(leaf.sharding, "is_fully_replicated", False):
            continue
        try:
            local = np.asarray(leaf.addressable_data(0))
        except Exception:
            local = np.asarray(jax.device_get(leaf))
        h.update(jax.tree_util.keystr(path).encode())
        h.update(np.ascontiguousarray(local).tobytes())
        n += 1
    return h.digest(), n


def exchange_digests(digest: bytes) -> List[bytes]:
    """Allgather every rank's digest (one collective launch; the same
    budget-consciousness as the checkpoint store's consolidation)."""
    import jax
    import numpy as np

    if jax.process_count() <= 1:
        return [digest]
    from jax.experimental import multihost_utils

    local = np.frombuffer(digest, np.uint8)
    gathered = np.asarray(multihost_utils.process_allgather(local))
    gathered = gathered.reshape(jax.process_count(), -1)
    return [bytes(gathered[i]) for i in range(gathered.shape[0])]


def attribute_suspects(digests: List[bytes]) -> List[int]:
    """Ranks whose digest differs from the majority. Ties (including the
    2-rank split, where no majority exists) break toward rank 0's digest
    — the coordinator-as-reference heuristic; a corrupted rank 0 in a
    2-rank world is the documented blind spot (3+ ranks vote it out)."""
    if not digests:
        return []
    counts = collections.Counter(digests)
    top_n = counts.most_common(1)[0][1]
    top = {d for d, c in counts.items() if c == top_n}
    majority = digests[0] if digests[0] in top else counts.most_common(1)[0][0]
    return [i for i, d in enumerate(digests) if d != majority]


class SDCProbe:
    """Trainer-side wrapper: hash → allgather → attribute, with counters.
    ``check`` must be called by every rank at the same step (the training
    loop is step-synchronous, so a fixed cadence guarantees it)."""

    def __init__(self, interval: int):
        self.interval = max(0, int(interval))
        self.last_digest: Optional[bytes] = None
        self.mismatches = 0
        self.probes = 0

    def due(self, step_before: int, step_after: int) -> bool:
        if self.interval <= 0:
            return False
        return step_after // self.interval > step_before // self.interval

    def check(self, params: Any, step: int) -> dict:
        """Returns ``{"mismatch": bool, "suspects": [rank...], "rank":
        this_rank, "digests": [hex...], "leaves": n}``."""
        import jax

        digest, n = replicated_param_digest(params)
        self.last_digest = digest
        self.probes += 1
        sdc_probes_total.inc()
        if n == 0:
            get_logger().warning(
                "sdc probe at step %d found no cross-process replicated "
                "param leaves to hash (fully sharded layout?) — probe is "
                "a no-op for this configuration", step)
            return {"mismatch": False, "suspects": [],
                    "rank": jax.process_index(), "digests": [], "leaves": 0}
        digests = exchange_digests(digest)
        mismatch = len(set(digests)) > 1
        suspects: List[int] = []
        if mismatch:
            self.mismatches += 1
            sdc_mismatches_total.inc()
            suspects = attribute_suspects(digests)
        return {"mismatch": mismatch, "suspects": suspects,
                "rank": jax.process_index(),
                "digests": [d.hex()[:16] for d in digests], "leaves": n}

    def scalars(self) -> dict:
        return {"sdc_probes": self.probes, "sdc_mismatches": self.mismatches}
