"""Optimizer + LR schedule, mirroring the reference's DeepSpeed config.

Reference: AdamW lr=2e-4|3e-4, betas (0.9, 0.999), eps 1e-8, weight_decay 0
(``configs/ds_config_zero1.json:6-14``); WarmupLR 0 -> lr over warmup
(``configs/ds_config_zero1.json:16-23``); grad clip 1.0
(``configs/ds_config_zero1.json:44``).

The reference disables DeepSpeed's fused CUDA Adam
(``train_deepspeed_zero2.py:125-128``) and falls back to torch Adam; on TPU
the fused update comes for free — XLA fuses the optax adamw elementwise chain
into a handful of kernels.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

from dlti_tpu.config import OptimizerConfig


def _fp32_state(inner: optax.GradientTransformation) -> optax.GradientTransformation:
    """Keep optimizer state (Adam moments) in float32 for low-precision
    params.

    Gradients are always accumulated in fp32 (``training.step``), so
    moments initialized in a param's bf16/fp16 dtype silently promote to
    fp32 on the first update — a state-dtype morph that (a) poisons a
    ``lax.scan`` carry (steps_per_sync windows require dtype-invariant
    state) and (b) would lose second-moment precision if it ever stuck.
    Upcasting at init is the standard mixed-precision recipe (fp32 master
    optimizer state) and makes the state dtype stable from step 0.
    Only LoRA-less full fine-tunes are affected: LoRA factors are already
    fp32 master weights.
    """

    def init(params):
        return jax.tree_util.tree_map(
            lambda x: x.astype(jnp.float32)
            if getattr(x, "dtype", None) in (jnp.bfloat16, jnp.float16)
            else x,
            inner.init(params))

    return optax.GradientTransformation(init, inner.update)


def build_schedule(cfg: OptimizerConfig) -> optax.Schedule:
    if cfg.schedule == "warmup_constant":
        if cfg.warmup_steps <= 0:
            return optax.constant_schedule(cfg.learning_rate)
        # DeepSpeed WarmupLR: linear 0 -> lr, then constant.
        return optax.join_schedules(
            [
                optax.linear_schedule(0.0, cfg.learning_rate, max(cfg.warmup_steps, 1)),
                optax.constant_schedule(cfg.learning_rate),
            ],
            boundaries=[max(cfg.warmup_steps, 1)],
        )
    if cfg.schedule == "warmup_cosine":
        total = max(cfg.total_steps, cfg.warmup_steps + 1)
        return optax.warmup_cosine_decay_schedule(
            0.0, cfg.learning_rate, max(cfg.warmup_steps, 1), total
        )
    raise ValueError(f"unknown schedule {cfg.schedule!r}")


def build_optimizer(cfg: OptimizerConfig) -> optax.GradientTransformation:
    """Global-norm clip -> AdamW(schedule). Applied to the *trainable* subtree
    only (the step fn partitions LoRA vs frozen params before calling this),
    so optimizer state is allocated solely for trainable params."""
    return _fp32_state(optax.chain(
        optax.clip_by_global_norm(cfg.grad_clip),
        optax.adamw(
            learning_rate=build_schedule(cfg),
            b1=cfg.betas[0],
            b2=cfg.betas[1],
            eps=cfg.eps,
            weight_decay=cfg.weight_decay,
        ),
    ))
