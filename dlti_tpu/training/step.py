"""The jitted train step: loss, grad accumulation, optimizer update.

This is the in-tree replacement for what the reference outsources to HF
``Trainer`` + the DeepSpeed engine (``trainer.train()``,
``training/train_baseline.py:217``): forward, causal-LM loss with the
collator's semantics (labels = input_ids, ``mlm=False`` —
``train_baseline.py:195-198``), backward w.r.t. the trainable (LoRA) subset
only, gradient accumulation over microbatches (``lax.scan``, matching
``gradient_accumulation_steps`` — ``train_baseline.py:69-75``), global-norm
clip, AdamW update.

Design notes (TPU-first):

* Gradients are computed only for the trainable flat subset — backprop flows
  *through* frozen bf16 base kernels but never materializes their dW, the
  same work-skipping PEFT gets from ``requires_grad=False``.
* Grad accumulation is a ``lax.scan`` over the leading ``accum`` axis of the
  batch, accumulating fp32 grads; one compiled program per optimizer step,
  no host round-trips.
* Everything is shape-static; the same step function is jitted per-device or
  ``jit``-over-a-``Mesh`` with sharding constraints (see
  ``dlti_tpu.parallel``).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax

from dlti_tpu.training.state import TrainState, combine_params


def causal_lm_loss(
    logits: jnp.ndarray,
    input_ids: jnp.ndarray,
    loss_mask: Optional[jnp.ndarray] = None,
) -> tuple:
    """Next-token cross-entropy.

    Labels are the inputs shifted left (HF ``DataCollatorForLanguageModeling``
    with ``mlm=False`` shifts inside the model; semantics identical).
    Returns (sum_loss, num_tokens) so callers can weight across microbatches.
    """
    targets = input_ids[:, 1:]
    logits = logits[:, :-1, :]
    if loss_mask is None:
        mask = jnp.ones_like(targets, dtype=jnp.float32)
    else:
        mask = loss_mask[:, 1:].astype(jnp.float32)
    token_loss = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    return jnp.sum(token_loss * mask), jnp.sum(mask)


def chunked_causal_lm_loss(
    hidden: jnp.ndarray,
    lm_head: jnp.ndarray,
    input_ids: jnp.ndarray,
    loss_mask: Optional[jnp.ndarray] = None,
    chunk: int = 128,
) -> tuple:
    """:func:`causal_lm_loss` without ever materializing (B, S, V) logits.

    The LM-head matmul + softmax-CE run per sequence chunk inside a
    rematerialized ``lax.scan``: peak fp32 logit memory drops from
    S*vocab to chunk*vocab per example, and the backward recomputes each
    chunk's logits instead of storing them. Identical math to the
    unchunked loss up to summation order. At 7B/seq-512/vocab-32k this
    frees ~2 GB of what ``results/mfu_investigation_r03.json`` measured
    as the binding HBM constraint once the frozen base is int8.

    Not for sequence-parallel runs: the chunk reshape would regather a
    'sequence'-sharded activation.
    """
    x = hidden[:, :-1, :]
    targets = input_ids[:, 1:]
    if loss_mask is None:
        mask = jnp.ones_like(targets, dtype=jnp.float32)
    else:
        mask = loss_mask[:, 1:].astype(jnp.float32)
    b, s1, h = x.shape
    n = -(-s1 // chunk)
    pad = n * chunk - s1
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)))
        mask = jnp.pad(mask, ((0, 0), (0, pad)))
    xs = x.reshape(b, n, chunk, h).transpose(1, 0, 2, 3)
    ts = targets.reshape(b, n, chunk).transpose(1, 0, 2)
    ms = mask.reshape(b, n, chunk).transpose(1, 0, 2)

    def body(carry, inp):
        xc, tc, mc = inp
        logits = jnp.dot(xc, lm_head,
                         preferred_element_type=jnp.float32).astype(jnp.float32)
        tl = optax.softmax_cross_entropy_with_integer_labels(logits, tc)
        return (carry[0] + jnp.sum(tl * mc), carry[1] + jnp.sum(mc)), None

    (loss_sum, n_tok), _ = jax.lax.scan(
        jax.checkpoint(body), (jnp.float32(0.0), jnp.float32(0.0)),
        (xs, ts, ms))
    return loss_sum, n_tok


def apply_loss_scaler(scaler: dict, grad_norm, new_trainable, old_trainable,
                      new_opt_state, old_opt_state,
                      scale_window: int, min_scale: float, hysteresis: int):
    """Dynamic fp16 loss-scaler update (exact ds_config semantics:
    ``configs/ds_config_zero1.json:25-32``) — shared by the flat and
    pipelined train steps.

    On overflow (non-finite grad norm) the optimizer update is skipped
    (params/opt state keep old values) and the scale halves once the
    hysteresis budget is spent; after ``scale_window`` consecutive good
    steps the scale doubles. Returns
    ``(trainable, opt_state, new_scaler, metrics_extra)``.
    """
    finite = jnp.isfinite(grad_norm)
    new_trainable = jax.tree_util.tree_map(
        lambda new, old: jnp.where(finite, new, old),
        new_trainable, old_trainable)
    new_opt_state = jax.tree_util.tree_map(
        lambda new, old: jnp.where(finite, new, old)
        if hasattr(new, "shape") else new,
        new_opt_state, old_opt_state)

    # Overflow: absorb into hysteresis first, then halve the scale.
    hyst_after = jnp.where(finite, scaler["hysteresis_left"],
                           jnp.maximum(scaler["hysteresis_left"] - 1, 0))
    shrink = (~finite) & (scaler["hysteresis_left"] <= 1)
    scale_after = jnp.where(
        shrink, jnp.maximum(scaler["scale"] * 0.5, min_scale),
        scaler["scale"])
    good_after = jnp.where(finite, scaler["good_steps"] + 1, 0)
    # Growth: double after scale_window consecutive good steps.
    grow = good_after >= scale_window
    new_scaler = {
        "scale": jnp.where(grow, scale_after * 2.0, scale_after),
        "good_steps": jnp.where(grow, 0, good_after),
        # Any scale change re-arms the hysteresis budget.
        "hysteresis_left": jnp.where(
            shrink | grow, jnp.int32(hysteresis), hyst_after),
    }
    metrics_extra = {"loss_scale": new_scaler["scale"],
                     "overflow": (~finite).astype(jnp.float32)}
    return new_trainable, new_opt_state, new_scaler, metrics_extra


def guard_nonfinite_update(grad_norm, loss, new_trainable, old_trainable,
                           new_opt_state, old_opt_state):
    """bf16-path nonfinite gate: skip the optimizer update when the loss
    or grad norm is nonfinite, exactly as :func:`apply_loss_scaler` has
    always done for fp16 overflow — without it a single NaN batch writes
    NaN into every AdamW moment and the run is numerically dead from then
    on. Params/opt state keep their old values; the step counter still
    advances (the lr/rng schedule is a pure function of the step index,
    so skipping is rollback- and world-size-invariant). Returns
    ``(trainable, opt_state, metrics_extra)`` with the ``nonfinite`` /
    ``skipped_update`` flags the host-side sentinel
    (``dlti_tpu.training.sentinel``) reads from the already-synced
    metrics."""
    finite = jnp.isfinite(grad_norm) & jnp.isfinite(loss)
    new_trainable = jax.tree_util.tree_map(
        lambda new, old: jnp.where(finite, new, old),
        new_trainable, old_trainable)
    new_opt_state = jax.tree_util.tree_map(
        lambda new, old: jnp.where(finite, new, old)
        if hasattr(new, "shape") else new,
        new_opt_state, old_opt_state)
    bad = (~finite).astype(jnp.float32)
    return new_trainable, new_opt_state, {
        "nonfinite": bad, "skipped_update": bad}


def make_train_step(
    model,
    *,
    accum_steps: int = 1,
    sharding_constraint: Optional[Callable] = None,
    grad_constraint: Optional[Callable] = None,
    fp16_scale_window: int = 1000,
    fp16_min_scale: float = 1.0,
    fp16_hysteresis: int = 2,
    loss_chunk: int = 0,
) -> Callable:
    """Build ``train_step(state, batch, rng) -> (state, metrics)``.

    ``batch`` is a dict with ``input_ids`` (accum, micro_bs, seq) int32 and
    optional ``loss_mask`` of the same shape. ``sharding_constraint`` is an
    optional fn applied to per-microbatch inputs (inserted by the parallel
    layer to pin activations to the mesh). ``grad_constraint`` pins the
    accumulated grads to the optimizer-state sharding — the ZeRO-2
    reduce-scatter semantics (``configs/ds_config_zero1.json:40``).
    Host offload (``configs/ds_config_zero3.json:19-27``) is wired by the
    sharded-step wrapper (``make_sharded_train_step``), not here: when the
    runtime supports host-memory compute operands the frozen params enter
    the compiled program directly from pinned host memory (in-step
    streaming); otherwise the wrapper moves host-resident state to HBM at
    the step boundary and back after.

    When ``state.scaler`` is set (fp16 training), the loss is multiplied by
    the dynamic scale before backward, grads are unscaled, and non-finite
    grads skip the update and shrink the scale — DeepSpeed's dynamic loss
    scaler (``configs/ds_config_zero1.json:25-32``): halve on overflow once
    ``hysteresis`` overflows have been absorbed, double after
    ``fp16_scale_window`` consecutive good steps.
    """

    model_cfg = getattr(model, "cfg", None)
    moe_coef = (model_cfg.router_aux_loss_coef
                if model_cfg is not None and model_cfg.num_experts > 0 else 0.0)
    if loss_chunk and moe_coef:
        raise ValueError(
            "loss_chunk does not compose with MoE aux-loss collection; "
            "set train.loss_chunk=0 for MoE models")

    def microbatch_loss(trainable, frozen, micro, rng):
        params = combine_params(trainable, frozen)
        input_ids = micro["input_ids"]
        loss_mask = micro.get("loss_mask")
        if sharding_constraint is not None:
            input_ids = sharding_constraint(input_ids)
        apply_kwargs = dict(
            positions=micro.get("positions"),  # packed: per-doc RoPE restart
            segment_ids=micro.get("segment_ids"),  # packed: intra-doc attention
            deterministic=False,
            rngs={"dropout": rng},
        )
        if moe_coef and loss_mask is not None and micro.get("segment_ids") is None:
            # Keep padding tokens out of expert capacity/aux statistics.
            # Only for unpacked batches, where loss_mask IS the padding
            # mask; packed batches zero loss_mask at every document's
            # first (real!) token, and the model derives the correct
            # padding mask from segment_ids instead.
            apply_kwargs["token_mask"] = loss_mask
        if moe_coef:
            # MoE: collect the sown per-layer router load-balance losses
            # (dlti_tpu.models.moe.MoEMLP) alongside the LM loss.
            ((logits, _), variables) = model.apply(
                {"params": params}, input_ids,
                mutable=["intermediates"], **apply_kwargs,
            )
            from dlti_tpu.models.moe import collect_aux_loss

            aux = collect_aux_loss(variables.get("intermediates", {}))
        elif loss_chunk:  # MoE+loss_chunk rejected at build time above
            hidden, _ = model.apply({"params": params}, input_ids,
                                    return_hidden=True, **apply_kwargs)
            aux = 0.0
        else:
            logits, _ = model.apply({"params": params}, input_ids, **apply_kwargs)
            aux = 0.0
        if loss_chunk:
            loss_sum, n_tok = chunked_causal_lm_loss(
                hidden, model.head_matrix(params, hidden),
                input_ids, loss_mask, loss_chunk)
        else:
            loss_sum, n_tok = causal_lm_loss(logits, input_ids, loss_mask)
        # Weight the (per-microbatch mean) aux loss by tokens so the final
        # /n_tok gives ce_mean + coef * token-weighted-mean(aux). The
        # differentiated objective carries the aux term; reported metrics
        # keep CE and aux separate so logged losses stay comparable with
        # dense runs and the reference's pure-CE trajectory.
        objective = loss_sum + moe_coef * aux * n_tok
        return objective, (loss_sum, aux * n_tok, n_tok)

    def train_step(state: TrainState, batch: dict, rng: jax.Array):
        trainable, frozen = state.trainable_and_frozen()
        opt_state = state.opt_state
        loss_scale = (state.scaler["scale"] if state.scaler is not None
                      else jnp.float32(1.0))

        def accum_body(carry, micro_with_rng):
            # One fused fwd+bwd per microbatch via value_and_grad.
            grads_acc, loss_acc, aux_acc, tok_acc = carry
            micro, micro_rng = micro_with_rng

            def scaled_loss(trainable, frozen, micro, rng):
                objective, parts = microbatch_loss(trainable, frozen, micro, rng)
                return objective * loss_scale, parts

            (_, (loss_sum, aux_sum, n_tok)), grads = jax.value_and_grad(
                scaled_loss, argnums=0, has_aux=True
            )(trainable, frozen, micro, micro_rng)
            grads_acc = jax.tree_util.tree_map(
                lambda a, g: a + g.astype(jnp.float32), grads_acc, grads
            )
            return (grads_acc, loss_acc + loss_sum, aux_acc + aux_sum,
                    tok_acc + n_tok), None

        zero_grads = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), trainable
        )
        zero_carry = (zero_grads, jnp.float32(0.0), jnp.float32(0.0),
                      jnp.float32(0.0))
        rngs = jax.random.split(rng, accum_steps)
        if accum_steps == 1:
            micro = jax.tree_util.tree_map(lambda x: x[0], batch)
            (grads, loss_sum, aux_sum, n_tok), _ = accum_body(
                zero_carry, (micro, rngs[0])
            )
        else:
            (grads, loss_sum, aux_sum, n_tok), _ = jax.lax.scan(
                accum_body, zero_carry, (batch, rngs),
            )

        # Mean over all tokens in the global batch (matches HF Trainer's
        # token-mean loss under grad accumulation). Grads also unscale the
        # fp16 loss scale here (no-op at scale 1).
        n_tok = jnp.maximum(n_tok, 1.0)
        grads = jax.tree_util.tree_map(lambda g: g / (n_tok * loss_scale), grads)
        loss = loss_sum / n_tok
        if grad_constraint is not None:
            grads = grad_constraint(grads)

        updates, new_opt_state = state.tx.update(grads, opt_state, trainable)
        new_trainable = optax.apply_updates(trainable, updates)

        grad_norm = optax.global_norm(grads)
        metrics = {
            "loss": loss,  # pure token-mean CE (aux reported separately)
            "grad_norm": grad_norm,
            "num_tokens": n_tok,
        }
        if moe_coef:
            metrics["aux_loss"] = aux_sum / n_tok

        new_scaler = state.scaler
        if state.scaler is not None:
            new_trainable, new_opt_state, new_scaler, extra = \
                apply_loss_scaler(
                    state.scaler, grad_norm, new_trainable, trainable,
                    new_opt_state, opt_state, fp16_scale_window,
                    fp16_min_scale, fp16_hysteresis)
            metrics.update(extra)
            # Uniform sentinel schema with the bf16 path: an fp16
            # overflow IS a skipped nonfinite step.
            metrics["nonfinite"] = extra["overflow"]
            metrics["skipped_update"] = extra["overflow"]
        else:
            # bf16 path: same skip semantics, no scale to evolve.
            new_trainable, new_opt_state, extra = guard_nonfinite_update(
                grad_norm, loss, new_trainable, trainable,
                new_opt_state, opt_state)
            metrics.update(extra)

        new_params = combine_params(new_trainable, frozen)
        new_state = state.replace(
            step=state.step + 1, params=new_params, opt_state=new_opt_state,
            scaler=new_scaler,
        )
        return new_state, metrics

    return train_step


def make_multi_step(step_fn: Callable) -> Callable:
    """Scan K whole train steps into ONE compiled program.

    ``multi(state, batches, rngs)``: ``batches`` is a step-stacked batch
    pytree (leading axis K) and ``rngs`` a (K, ...) key array; returns the
    state after K steps plus step-stacked metrics. The training analog of
    the serving engine's multi-step decode: every compiled-program call
    pays a fixed dispatch/round-trip cost (~95 ms on this image's
    relay-attached chip — results/mfu_investigation_r03.json), and the
    scan amortizes it K-fold. The trajectory equals K separate calls when
    the caller pre-splits the same per-step rngs; a jitted ``step_fn`` is
    traced inline, keeping its sharding constraints.
    """

    def multi(state, batches, rngs):
        def body(st, inp):
            b, r = inp
            return step_fn(st, b, r)

        return jax.lax.scan(body, state, (batches, rngs))

    return jax.jit(multi, donate_argnums=(0,))


def make_eval_step(model, loss_chunk: int = 0) -> Callable:
    """Build ``eval_step(state, batch) -> metrics`` (no dropout, no update).

    ``loss_chunk`` mirrors the train step: a run whose HBM budget depends
    on never materializing full fp32 logits must not OOM at its first
    periodic eval.
    """

    def eval_step(state: TrainState, batch: dict):
        kwargs = dict(
            positions=batch.get("positions"),
            segment_ids=batch.get("segment_ids"),
            deterministic=True,
        )
        if loss_chunk:
            hidden, _ = model.apply(
                {"params": state.params}, batch["input_ids"],
                return_hidden=True, **kwargs)
            loss_sum, n_tok = chunked_causal_lm_loss(
                hidden, model.head_matrix(state.params, hidden),
                batch["input_ids"], batch.get("loss_mask"), loss_chunk)
        else:
            logits, _ = model.apply(
                {"params": state.params}, batch["input_ids"], **kwargs)
            loss_sum, n_tok = causal_lm_loss(
                logits, batch["input_ids"], batch.get("loss_mask")
            )
        return {"loss": loss_sum / jnp.maximum(n_tok, 1.0), "num_tokens": n_tok}

    return eval_step
