"""Elastic self-healing multi-process training supervisor.

``launcher.launch_local`` implements torchrun's sigkill_handler semantics:
first failure tears the whole job down (the reference's own recorded
2-GPU crash, ``train.ipynb:794-838``, left a human to restart from
scratch). This module is the MegaScale-style upgrade (Jiang et al., 2024:
fault tolerance is the dominant goodput lever at scale): a supervising
:class:`ElasticLauncher` that keeps a multi-process job making progress
through worker death with no human in the loop.

The recovery loop, per *generation* (a numbered rendezvous epoch):

1. Spawn one worker per surviving slot with the ``DLTI_*`` rendezvous env
   plus ``DLTI_GENERATION`` / ``DLTI_ELASTIC_DIR`` /
   ``DLTI_ELASTIC_NUM_SLOTS``; each generation rendezvouses on its own
   coordinator port, so a half-dead generation can never poison the next
   one's connect.
2. Watch worker exits and per-rank heartbeat files (the trainer writes
   one per step via :func:`beat`). A nonzero exit, a heartbeat older than
   the staleness deadline, or a rank-0 watchdog ``heartbeat_stale`` alert
   mirrored into the elastic dir marks that worker failed. The escalation
   ladder is *targeted*: SIGTERM the suspect (its flight recorder's
   preemption path gets a chance to dump + checkpoint), grace, SIGKILL —
   then tear down the stragglers (they are wedged in collectives the
   moment a peer dies) and reshape, never abort the whole job.
3. Charge the restart budget, back off exponentially, and relaunch the
   *survivors* as generation g+1. The workers re-derive their mesh from
   the shrunk world (``fit_parallel_to_devices`` +
   :func:`rescale_batch_schedule` keep the global batch schedule
   byte-identical) and resume from the last digest-verified checkpoint
   (``checkpoint.store.restore_latest_verified``).
4. Rejoin: while a failed slot waits out recovery, the supervisor watches
   the checkpoint dir; the next *committed* checkpoint boundary triggers
   a graceful drain (SIGTERM → the trainer's preemption checkpoint →
   clean exit) and a full-size relaunch — the returned host rejoins with
   at most one checkpoint interval of re-done work.

Whole-host chaos rides the same spec the in-process injector uses:
``DLTI_TRAIN_FAULT_INJECT=STEP:host-kill[:RANK]`` makes the *supervisor*
SIGKILL an entire worker once its heartbeats reach STEP (the in-process
injector ignores the ``host-kill`` mode — it is supervisor-owned).

Metric names are a scrape contract (pinned in
``tests/test_bench_contract.py``): ``dlti_elastic_restarts_total``,
``dlti_elastic_generation``, ``dlti_elastic_world_size``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import subprocess
import tempfile
import time
from typing import Callable, Dict, List, Optional, Sequence

from dlti_tpu.telemetry.registry import Counter, Gauge
from dlti_tpu.utils import durable_io
from dlti_tpu.utils.logging import get_logger

# -- rendezvous env extensions (on top of launcher's DLTI_* contract) ----
ENV_GENERATION = "DLTI_GENERATION"
ENV_ELASTIC_DIR = "DLTI_ELASTIC_DIR"
ENV_NUM_SLOTS = "DLTI_ELASTIC_NUM_SLOTS"

# Name-stability contract (pinned in tests/test_bench_contract.py).
ELASTIC_METRIC_NAMES = (
    "dlti_elastic_restarts_total",
    "dlti_elastic_generation",
    "dlti_elastic_world_size",
)

restarts_total = Counter(
    ELASTIC_METRIC_NAMES[0],
    help="worker-failure recoveries the elastic supervisor performed")
generation_gauge = Gauge(
    ELASTIC_METRIC_NAMES[1],
    help="current elastic rendezvous generation")
world_size_gauge = Gauge(
    ELASTIC_METRIC_NAMES[2],
    help="live worker count of the current generation")

_EVENTS_FILE = "elastic_events.jsonl"
_HB_MIN_INTERVAL_S = 0.05

# Stitched goodput ledger across restarts (telemetry.ledger): the
# supervisor merges per-generation worker ledgers and adds the buckets
# only it can see (restart downtime, shrunk-world degradation).
STITCHED_LEDGER_FILE = "ledger_stitched.json"


# ----------------------------------------------------------------------
# Worker-side helpers (called from the trainer / watchdog; every one is a
# no-op unless the elastic supervisor's env is present)
# ----------------------------------------------------------------------

def elastic_info() -> Optional[dict]:
    """The supervisor context this process runs under, or None."""
    d = os.environ.get(ENV_ELASTIC_DIR)
    if not d:
        return None
    return {
        "dir": d,
        "generation": int(os.environ.get(ENV_GENERATION, "0")),
        "rank": int(os.environ.get("DLTI_PROCESS_ID", "0")),
        "num_slots": int(os.environ.get(ENV_NUM_SLOTS, "0")),
    }


_last_beat = [0.0]


def beat(step: int) -> None:
    """Per-step heartbeat file for the supervisor (atomic write+rename;
    throttled; never raises — liveness reporting must not kill the
    thing whose liveness it reports)."""
    info = elastic_info()
    if info is None:
        return
    now = time.monotonic()
    if now - _last_beat[0] < _HB_MIN_INTERVAL_S:
        return
    _last_beat[0] = now
    path = os.path.join(
        info["dir"], f"hb_g{info['generation']}_r{info['rank']}.json")
    durable_io.write_json_atomic(
        path, {"step": int(step), "wall": time.time(),
               "generation": info["generation"],
               "rank": info["rank"], "pid": os.getpid()},
        path_class="elastic")


_last_ledger_save = [0.0]


def save_generation_ledger(ledger_dict: dict, step: Optional[int] = None,
                           force: bool = False) -> Optional[str]:
    """Persist this rank's goodput-ledger totals for the supervisor's
    cross-generation stitching (``ledger_g<G>_r<R>.json`` in the elastic
    dir; atomic write+rename; throttled like :func:`beat` because the
    trainer refreshes it per step — a worker that dies by SIGKILL never
    reaches its exit-path save, and the stitched ledger must still book
    that generation's rollback/replay time; never raises). No-op outside
    an elastic launch."""
    info = elastic_info()
    if info is None:
        return None
    now = time.monotonic()
    if not force and now - _last_ledger_save[0] < _HB_MIN_INTERVAL_S:
        return None
    _last_ledger_save[0] = now
    path = os.path.join(
        info["dir"], f"ledger_g{info['generation']}_r{info['rank']}.json")
    ok = durable_io.write_json_atomic(
        path, {**ledger_dict, "generation": info["generation"],
               "rank": info["rank"], "step": step, "wall": time.time()},
        path_class="elastic")
    return path if ok else None


def mirror_alert(alert: dict) -> None:
    """Mirror a watchdog alert into the elastic dir so the supervisor can
    act on rank-0's aggregated view (a ``heartbeat_stale`` alert names
    the straggling process ids — the supervisor's targeted-kill input).
    No-op outside an elastic launch; never raises."""
    info = elastic_info()
    if info is None:
        return
    path = os.path.join(
        info["dir"],
        f"watchdog_alerts_g{info['generation']}_r{info['rank']}.jsonl")
    durable_io.append_line(path, json.dumps(alert, default=str),
                           path_class="elastic")


# ----------------------------------------------------------------------
# Mesh / batch-schedule reshape (pure functions; the trainer entry point
# applies them via maybe_reshape_from_env)
# ----------------------------------------------------------------------

def rescale_batch_schedule(micro_batch_size: int, grad_accum_steps: int,
                           full_world: int, live_world: int,
                           ) -> tuple:
    """(micro_batch_size, grad_accum_steps) for a shrunk/regrown world
    that preserve the *global batch schedule*: the same
    ``micro_batch_size * grad_accum_steps`` rows feed the same optimizer
    step in the same order, redistributed between the batch and
    grad-accumulation dimensions. With token-uniform rows (packed or
    fixed-length) the loss/grad math is exactly the full-world math.

    ``micro_batch_size`` here is the configured FULL-world global
    microbatch; the returned one is the live-world global microbatch.
    """
    if full_world <= 0 or live_world <= 0:
        raise ValueError(
            f"world sizes must be positive, got full={full_world} "
            f"live={live_world}")
    rows_per_step = micro_batch_size * grad_accum_steps
    if (micro_batch_size * live_world) % full_world:
        raise ValueError(
            f"global micro_batch_size {micro_batch_size} cannot shrink by "
            f"{live_world}/{full_world}: per-slot rows are not integral")
    micro_live = micro_batch_size * live_world // full_world
    if micro_live == 0 or rows_per_step % micro_live:
        raise ValueError(
            f"rows/step {rows_per_step} is not divisible by the live "
            f"microbatch {micro_live} (world {full_world}->{live_world})")
    return micro_live, rows_per_step // micro_live


def maybe_reshape_from_env(cfg):
    """Reshape a built Config to the *live* world when this process runs
    under the elastic supervisor at less than full size.

    ``build_config`` already derives the mesh batch extent and the global
    microbatch from the live device count; what it cannot know is the
    FULL-world schedule the run must preserve across generations. This
    recomputes ``grad_accum_steps`` (more accumulation over fewer
    devices: same rows per optimizer step, same ``steps_per_epoch``, same
    per-step rng fold — so a shrunk generation resumes the exact batch
    schedule) and shrinks explicit mesh extents that no longer fit the
    surviving devices. Returns ``cfg`` unchanged outside an elastic
    launch or at full size."""
    info = elastic_info()
    if info is None or info["num_slots"] <= 1:
        return cfg
    import dataclasses as _dc

    import jax

    from dlti_tpu.parallel.mesh import fit_parallel_to_devices

    full = info["num_slots"]
    live = jax.process_count()
    generation_gauge.set(info["generation"])
    world_size_gauge.set(live)
    if live == full:
        return cfg
    if live > full:
        get_logger().warning(
            "elastic: live world %d exceeds configured slots %d; "
            "keeping the built config", live, full)
        return cfg
    par = fit_parallel_to_devices(cfg.parallel, jax.device_count())
    dp_old = max(1, cfg.parallel.data * cfg.parallel.fsdp)
    dp_live = max(1, par.data * par.fsdp)
    if (cfg.train.micro_batch_size * dp_live) % dp_old:
        raise ValueError(
            f"elastic reshape: micro_batch_size "
            f"{cfg.train.micro_batch_size} does not rescale from mesh "
            f"batch extent {dp_old} to {dp_live}")
    micro_live = cfg.train.micro_batch_size * dp_live // dp_old
    # grad-accum recompute against the FULL-world schedule (the contract
    # every generation must preserve): the full-world global microbatch is
    # the live one scaled back up by full/live.
    if (micro_live * full) % live:
        raise ValueError(
            f"elastic reshape: live microbatch {micro_live} does not scale "
            f"to an integral full-world microbatch (world {live}/{full})")
    micro_full = micro_live * full // live
    micro_check, accum_live = rescale_batch_schedule(
        micro_full, cfg.train.grad_accum_steps, full, live)
    assert micro_check == micro_live
    get_logger().warning(
        "elastic reshape: generation %d runs at world %d/%d — mesh "
        "data*fsdp %d->%d, micro_batch_size %d->%d, grad_accum %d->%d "
        "(global rows/step preserved: %d)",
        info["generation"], live, full, dp_old, dp_live,
        cfg.train.micro_batch_size, micro_live,
        cfg.train.grad_accum_steps, accum_live,
        micro_live * accum_live)
    return cfg.replace(
        parallel=par,
        train=_dc.replace(cfg.train, micro_batch_size=micro_live,
                          grad_accum_steps=accum_live))


# ----------------------------------------------------------------------
# Supervisor-side chaos: whole-host kills
# ----------------------------------------------------------------------

@dataclasses.dataclass
class HostKillSpec:
    """``STEP:host-kill[:RANK]`` — SIGKILL worker RANK (default 1) from
    the supervisor once its generation's heartbeats reach STEP. Fires at
    most once per supervisor lifetime (the restarted generations are the
    recovery under test, not fresh targets)."""

    step: int
    rank: int = 1
    fired: bool = False

    @classmethod
    def parse(cls, spec: Optional[str]) -> Optional["HostKillSpec"]:
        spec = (spec or "").strip() or os.environ.get(
            "DLTI_TRAIN_FAULT_INJECT", "").strip()
        if not spec:
            return None
        parts = spec.split(":")
        if len(parts) < 2 or parts[1] != "host-kill":
            return None  # in-process modes belong to training.chaos
        step = int(parts[0])
        rank = int(parts[2]) if len(parts) > 2 else 1
        if step < 1 or rank < 0:
            raise ValueError(f"bad host-kill spec {spec!r}")
        return cls(step=step, rank=rank)


# ----------------------------------------------------------------------
# The supervisor
# ----------------------------------------------------------------------

@dataclasses.dataclass
class _Worker:
    slot: int           # stable slot id (0..num_processes-1)
    rank: int           # generation-local contiguous rank
    proc: subprocess.Popen
    files: tuple = ()


@dataclasses.dataclass
class _Outcome:
    kind: str                     # "done" | "drain" | "failure"
    rc: int = 0
    failed_slots: tuple = ()


def latest_committed_step(ckpt_dir: Optional[str]) -> Optional[int]:
    """Newest committed checkpoint step, judged the way the store's
    atomic-finalize protocol allows without importing jax: a bare-integer
    dir containing its ``COMMIT`` marker (digest verification stays with
    the resuming worker)."""
    if not ckpt_dir:
        return None
    try:
        names = os.listdir(ckpt_dir)
    except OSError:
        return None
    steps = [int(n) for n in names
             if n.isdigit()
             and os.path.isfile(os.path.join(ckpt_dir, n, "COMMIT"))]
    return max(steps) if steps else None


class ElasticLauncher:
    """Supervising launcher: restart budget, backoff, generation-numbered
    rendezvous, reshape-on-failure, checkpoint-boundary rejoin.

    ``sleep``/``clock`` are injectable so the restart/backoff state
    machine is unit-testable with fake (non-JAX) workers in real time.
    """

    def __init__(self, command: Sequence[str], num_processes: int, *,
                 port: int = 29400, log_dir: Optional[str] = None,
                 restart_budget: int = 3, backoff_s: float = 1.0,
                 backoff_max_s: float = 30.0,
                 heartbeat_stale_s: float = 0.0,
                 startup_grace_s: float = 60.0,
                 rejoin: bool = True, ckpt_dir: Optional[str] = None,
                 min_world: int = 1, term_grace_s: float = 10.0,
                 poll_s: float = 0.2,
                 fault_spec: Optional[str] = None,
                 elastic_dir: Optional[str] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 clock: Callable[[], float] = time.monotonic):
        if num_processes < 1:
            raise ValueError("num_processes must be >= 1")
        if min_world < 1 or min_world > num_processes:
            raise ValueError(
                f"min_world {min_world} must be in [1, {num_processes}]")
        self.command = list(command)
        self.num_processes = num_processes
        self.port = port
        self.log_dir = log_dir
        self.restart_budget = restart_budget
        self.backoff_s = backoff_s
        self.backoff_max_s = backoff_max_s
        self.heartbeat_stale_s = heartbeat_stale_s
        self.startup_grace_s = startup_grace_s
        self.rejoin = rejoin
        self.ckpt_dir = ckpt_dir
        self.min_world = min_world
        self.term_grace_s = term_grace_s
        self.poll_s = poll_s
        self.fault = HostKillSpec.parse(fault_spec)
        self.elastic_dir = os.path.abspath(
            elastic_dir
            or (os.path.join(log_dir, "elastic") if log_dir else
                tempfile.mkdtemp(prefix="dlti-elastic-")))
        os.makedirs(self.elastic_dir, exist_ok=True)
        self.sleep = sleep
        self.clock = clock
        self.logger = get_logger()
        self.generation = 0
        self.restarts = 0
        # Per-alert-file consumed line counts (alerts are acted on once).
        self._alert_cursor: Dict[str, int] = {}

    # -- events ---------------------------------------------------------
    def _event(self, event: str, **data) -> None:
        rec = {"wall": time.time(), "event": event,
               "generation": self.generation, **data}
        durable_io.append_line(os.path.join(self.elastic_dir, _EVENTS_FILE),
                               json.dumps(rec, default=str),
                               path_class="elastic")
        self.logger.info("elastic[g%d]: %s %s", self.generation, event,
                         {k: v for k, v in data.items()})

    # -- heartbeat / alert file plumbing --------------------------------
    def _hb(self, rank: int) -> Optional[dict]:
        path = os.path.join(self.elastic_dir,
                            f"hb_g{self.generation}_r{rank}.json")
        try:
            with open(path) as f:
                hb = json.load(f)
            hb["_mtime"] = os.path.getmtime(path)
            return hb
        except (OSError, ValueError):
            return None

    def _observed_step(self, world_size: int) -> int:
        steps = [hb["step"] for r in range(world_size)
                 if (hb := self._hb(r)) is not None]
        return max(steps) if steps else -1

    def _stale_ranks_from_alerts(self, world_size: int) -> List[int]:
        """Ranks a worker-side watchdog ``heartbeat_stale`` alert named
        (rank 0 aggregates per-process heartbeats; the supervisor turns
        that view into a targeted kill). Each alert is consumed once."""
        stale: List[int] = []
        for r in range(world_size):
            path = os.path.join(
                self.elastic_dir,
                f"watchdog_alerts_g{self.generation}_r{r}.jsonl")
            try:
                with open(path) as f:
                    lines = f.readlines()
            except OSError:
                continue
            start = self._alert_cursor.get(path, 0)
            self._alert_cursor[path] = len(lines)
            for line in lines[start:]:
                try:
                    alert = json.loads(line)
                except ValueError:
                    continue
                if alert.get("rule") != "heartbeat_stale":
                    continue
                for proc in (alert.get("stale") or {}):
                    try:
                        stale.append(int(proc))
                    except (TypeError, ValueError):
                        continue
        return [r for r in sorted(set(stale)) if r < world_size]

    # -- process control ------------------------------------------------
    def _spawn(self, world: List[int]) -> List[_Worker]:
        from dlti_tpu.launcher import worker_env

        port = self.port + (self.generation % 64)
        coordinator = f"127.0.0.1:{port}"
        workers: List[_Worker] = []
        for rank, slot in enumerate(world):
            env = worker_env(coordinator, len(world), rank)
            env[ENV_GENERATION] = str(self.generation)
            env[ENV_ELASTIC_DIR] = self.elastic_dir
            env[ENV_NUM_SLOTS] = str(self.num_processes)
            stdout = stderr = None
            files: tuple = ()
            if self.log_dir:
                os.makedirs(self.log_dir, exist_ok=True)
                stdout = open(os.path.join(
                    self.log_dir, f"rank{rank}.g{self.generation}.out"), "wb")
                stderr = open(os.path.join(
                    self.log_dir, f"rank{rank}.g{self.generation}.err"), "wb")
                files = (stdout, stderr)
            proc = subprocess.Popen(self.command, env=env,
                                    stdout=stdout, stderr=stderr)
            workers.append(_Worker(slot=slot, rank=rank, proc=proc,
                                   files=files))
        generation_gauge.set(self.generation)
        world_size_gauge.set(len(world))
        self._event("spawn", world=list(world), world_size=len(world),
                    coordinator=coordinator,
                    ckpt_watermark=latest_committed_step(self.ckpt_dir))
        return workers

    def _signal_all(self, workers: List[_Worker], sig) -> None:
        for w in workers:
            if w.proc.poll() is None:
                try:
                    w.proc.send_signal(sig)
                except OSError:
                    pass

    def _teardown(self, workers: List[_Worker]) -> None:
        """SIGTERM survivors (preemption-checkpoint chance), grace, then
        SIGKILL — a peer-loss-wedged collective never exits on its own."""
        live = [w for w in workers if w.proc.poll() is None]
        if live:
            self._signal_all(live, signal.SIGTERM)
            deadline = self.clock() + self.term_grace_s
            while self.clock() < deadline and any(
                    w.proc.poll() is None for w in live):
                self.sleep(min(self.poll_s, 0.1))
            self._signal_all(live, signal.SIGKILL)
            for w in live:
                try:
                    w.proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass
        for w in workers:
            for f in w.files:
                f.close()

    def _kill_target(self, workers: List[_Worker], rank: int,
                     reason: str) -> None:
        """The targeted escalation ladder: SIGTERM (flight-recorder /
        preemption-checkpoint chance) → grace → SIGKILL, one rank only."""
        w = workers[rank]
        self._event(reason, rank=rank, slot=w.slot, pid=w.proc.pid)
        if w.proc.poll() is None:
            try:
                w.proc.send_signal(signal.SIGTERM)
            except OSError:
                pass
            deadline = self.clock() + self.term_grace_s
            while self.clock() < deadline and w.proc.poll() is None:
                self.sleep(min(self.poll_s, 0.1))
            if w.proc.poll() is None:
                try:
                    w.proc.kill()
                except OSError:
                    pass

    # -- one generation -------------------------------------------------
    def _run_generation(self, world: List[int],
                        rejoin_armed: bool) -> _Outcome:
        workers = self._spawn(world)
        spawn_t = self.clock()
        watermark = latest_committed_step(self.ckpt_dir)
        draining = False
        drain_deadline = None
        chaos_victim = None  # slot the supervisor itself host-killed
        try:
            while True:
                # Worker exits ------------------------------------------
                failed: List[_Worker] = []
                for w in workers:
                    rc = w.proc.poll()
                    if rc is not None and rc != 0 and not draining:
                        failed.append(w)
                if failed:
                    # Blame ONE root failure: when a worker dies, its
                    # peers crash too (wedged/aborted collectives), so
                    # several nonzero exits usually share one cause.
                    # Attribution order: the supervisor's own chaos
                    # victim, then signal deaths (SIGKILL/OOM — the
                    # "host vanished" signature) over clean nonzero
                    # exits (collective-error collateral), then first
                    # detected. A genuine multi-host loss self-corrects:
                    # the next generation fails again and shrinks again.
                    w = next(
                        (x for x in failed if x.slot == chaos_victim),
                        next((x for x in failed if x.proc.returncode < 0),
                             failed[0]))
                    self._event(
                        "failure", rank=w.rank, slot=w.slot,
                        rc=w.proc.returncode,
                        collateral=[{"slot": x.slot,
                                     "rc": x.proc.returncode}
                                    for x in failed if x is not w])
                    self._teardown(workers)
                    return _Outcome("failure", rc=w.proc.returncode,
                                    failed_slots=(w.slot,))
                if all(w.proc.poll() is not None for w in workers):
                    # All exited zero (nonzero handled above; during a
                    # drain the SIGTERM normally maps to rc 0 via the
                    # trainer's preemption path). Death BY our own
                    # SIGTERM (rc -15) is also a successful drain: it
                    # means the signal landed outside the trainer's
                    # handler window — before install or, commonly, after
                    # the epoch already finished and the handler was
                    # restored — and the relaunch resumes from the very
                    # checkpoint boundary that triggered the drain. Any
                    # other nonzero rc means the drain itself failed.
                    if draining:
                        bad = [w for w in workers
                               if w.proc.returncode
                               not in (0, -signal.SIGTERM)]
                        if bad:
                            self._event("drain_failed",
                                        rc=bad[0].proc.returncode)
                            return _Outcome("failure",
                                            rc=bad[0].proc.returncode)
                        self._event("drain_complete")
                        return _Outcome("drain")
                    self._event("done")
                    return _Outcome("done")
                if draining:
                    if self.clock() > drain_deadline:
                        self._event("drain_timeout")
                        self._teardown(workers)
                        return _Outcome("failure", rc=1)
                    self.sleep(self.poll_s)
                    continue

                # Supervisor-side chaos: whole-host kill ----------------
                if (self.fault is not None and not self.fault.fired
                        and self.fault.rank < len(workers)
                        and self._observed_step(len(workers))
                        >= self.fault.step):
                    self.fault.fired = True
                    w = workers[self.fault.rank]
                    chaos_victim = w.slot
                    self._event("host_kill", rank=w.rank, slot=w.slot,
                                step=self._observed_step(len(workers)))
                    if w.proc.poll() is None:
                        w.proc.kill()  # SIGKILL: the whole "host" vanishes
                    # next poll round books it as a failure

                # Staleness: per-rank heartbeat files -------------------
                if self.heartbeat_stale_s > 0:
                    now = time.time()
                    for w in workers:
                        if w.proc.poll() is not None:
                            continue
                        hb = self._hb(w.rank)
                        if hb is None:
                            # No beat yet: only the startup grace applies
                            # (cold jax compile must not read as death).
                            if (self.clock() - spawn_t
                                    > self.startup_grace_s):
                                self._stale_failure(workers, w)
                                return _Outcome(
                                    "failure", rc=1,
                                    failed_slots=(w.slot,))
                            continue
                        if now - hb["_mtime"] > self.heartbeat_stale_s:
                            self._stale_failure(workers, w)
                            return _Outcome("failure", rc=1,
                                            failed_slots=(w.slot,))

                # Rank-0 watchdog heartbeat_stale alerts ----------------
                for rank in self._stale_ranks_from_alerts(len(workers)):
                    w = workers[rank]
                    if w.proc.poll() is None:
                        self._stale_failure(workers, w,
                                            reason="watchdog_stale")
                        return _Outcome("failure", rc=1,
                                        failed_slots=(w.slot,))

                # Rejoin at the next checkpoint boundary ----------------
                if rejoin_armed and self.ckpt_dir:
                    cur = latest_committed_step(self.ckpt_dir)
                    if cur is not None and cur != watermark and (
                            watermark is None or cur > watermark):
                        self._event("rejoin_drain", checkpoint_step=cur)
                        self._signal_all(workers, signal.SIGTERM)
                        draining = True
                        drain_deadline = (self.clock()
                                          + self.term_grace_s + 60.0)
                        continue

                self.sleep(self.poll_s)
        finally:
            for w in workers:
                if w.proc.poll() is None:
                    w.proc.kill()
                for f in w.files:
                    f.close()

    def _stale_failure(self, workers: List[_Worker], w: _Worker,
                       reason: str = "stale") -> None:
        """Record + ladder-kill the straggler, then tear the rest down."""
        hb = self._hb(w.rank)
        incident = {
            "wall": time.time(), "reason": reason, "rank": w.rank,
            "slot": w.slot, "generation": self.generation,
            "heartbeat": hb and {k: hb[k] for k in hb if k != "_mtime"},
            "stale_s": (time.time() - hb["_mtime"]) if hb else None,
        }
        durable_io.write_json_atomic(
            os.path.join(self.elastic_dir,
                         f"supervisor_incident_g{self.generation}.json"),
            incident, path_class="elastic", indent=1)
        self._kill_target(workers, w.rank, reason)
        self._teardown(workers)

    # -- stitched goodput ledger ----------------------------------------
    def _write_stitched(self, timeline: List[dict]) -> None:
        """Merge per-generation worker ledgers with the supervisor's own
        timeline into ``ledger_stitched.json`` — the one place restart
        downtime and shrunk-world degradation are booked (workers cannot
        see either). Rewritten after every generation so a crashed
        supervisor still leaves the story so far. Never raises."""
        try:
            from dlti_tpu.telemetry.ledger import (
                load_generation_ledgers, stitch_ledgers,
            )

            stitched = stitch_ledgers(
                load_generation_ledgers(self.elastic_dir), timeline,
                self.num_processes)
            path = os.path.join(self.elastic_dir, STITCHED_LEDGER_FILE)
            durable_io.write_json_atomic(path, stitched,
                                         path_class="elastic", indent=1)
        except Exception:
            self.logger.debug("stitched-ledger write failed", exc_info=True)

    # -- the supervisor loop --------------------------------------------
    def run(self) -> int:
        slots = list(range(self.num_processes))
        world = list(slots)
        budget = self.restart_budget
        backoff = self.backoff_s
        pending_rejoin: List[int] = []
        timeline: List[dict] = []
        while True:
            gen_start = self.clock()
            outcome = self._run_generation(
                world, rejoin_armed=bool(pending_rejoin))
            timeline.append({
                "generation": self.generation,
                "world_size": len(world),
                "start": gen_start, "end": self.clock(),
                "outcome": outcome.kind,
            })
            self._write_stitched(timeline)
            if outcome.kind == "done":
                self._event("supervisor_exit", rc=0,
                            restarts=self.restarts)
                return 0
            if outcome.kind == "drain":
                # Graceful rejoin: the shrunk generation checkpointed at
                # the boundary and exited clean — relaunch at full size.
                world = sorted(set(world) | set(pending_rejoin))
                self._event("rejoin", world=list(world))
                pending_rejoin = []
                self.generation += 1
                continue
            # failure ---------------------------------------------------
            if budget <= 0:
                self._event("give_up", rc=outcome.rc,
                            restarts=self.restarts)
                return outcome.rc or 1
            budget -= 1
            self.restarts += 1
            restarts_total.inc()
            shrunk = [s for s in world if s not in outcome.failed_slots]
            if (self.rejoin and outcome.failed_slots
                    and len(shrunk) >= self.min_world):
                pending_rejoin = sorted(
                    set(pending_rejoin) | set(outcome.failed_slots))
                world = shrunk
            # else: relaunch at the same size (transient failure, or a
            # shrink would cross min_world)
            self._event("backoff", seconds=backoff, budget_left=budget,
                        next_world=list(world))
            self.sleep(backoff)
            backoff = min(backoff * 2, self.backoff_max_s)
            self.generation += 1
