"""Train state: full param tree + optimizer state over the trainable subset.

Unlike ``flax.training.train_state.TrainState``, params are kept as one tree
while the optimizer state covers only the *trainable* (LoRA) flat subset —
the structure that lets ZeRO-1/2 shard optimizer state over the data axis
while base params stay frozen (SURVEY.md §7 hard part #1).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from flax import struct, traverse_util


def _is_trainable_key(key: tuple, lora_enabled: bool) -> bool:
    if not lora_enabled:
        return True
    return key[-1] in ("lora_a", "lora_b")


def partition_params(params: dict, lora_enabled: bool) -> tuple:
    """Split a nested param dict into (trainable_flat, frozen_flat).

    Flat dicts keyed by path tuples are valid pytrees, so the trainable dict
    can be differentiated / optimized / sharded directly.
    """
    flat = traverse_util.flatten_dict(params)
    trainable = {k: v for k, v in flat.items() if _is_trainable_key(k, lora_enabled)}
    frozen = {k: v for k, v in flat.items() if not _is_trainable_key(k, lora_enabled)}
    return trainable, frozen


def combine_params(trainable_flat: dict, frozen_flat: dict) -> dict:
    return traverse_util.unflatten_dict({**frozen_flat, **trainable_flat})


class TrainState(struct.PyTreeNode):
    step: jnp.ndarray
    params: Any  # full nested param tree
    opt_state: Any  # optax state over the trainable flat subset
    tx: optax.GradientTransformation = struct.field(pytree_node=False)
    lora_enabled: bool = struct.field(pytree_node=False)
    # fp16 dynamic loss scaling state (None when training in bf16/fp32):
    # {scale: f32, good_steps: i32, hysteresis_left: i32} — the DeepSpeed
    # dynamic scaler's state (configs/ds_config_zero1.json:25-32).
    scaler: Any = None

    def trainable_and_frozen(self) -> tuple:
        return partition_params(self.params, self.lora_enabled)


def create_train_state(
    rng: jax.Array,
    model,
    tx: optax.GradientTransformation,
    example_batch_shape: tuple,
    lora_enabled: bool = True,
    init_fn: Callable | None = None,
    fp16_initial_scale: float | None = None,
    fp16_hysteresis: int = 2,
) -> TrainState:
    """Initialize params + optimizer state.

    ``example_batch_shape`` is (micro_batch, seq_len). ``init_fn`` overrides
    model.init for tests / loading pre-trained weights.
    ``fp16_initial_scale`` (e.g. 2**16) enables the dynamic loss scaler.
    """
    dummy = jnp.zeros(example_batch_shape, dtype=jnp.int32)
    if init_fn is None:
        variables = model.init(rng, dummy, deterministic=True)
        params = variables["params"]
    else:
        params = init_fn(rng, dummy)

    trainable, _ = partition_params(params, lora_enabled)
    if not trainable:
        raise ValueError("no trainable params found (LoRA enabled but no adapters grafted)")
    # Master copies of trainable params in fp32 (bf16 base stays bf16).
    opt_state = tx.init(trainable)
    scaler = None
    if fp16_initial_scale is not None:
        scaler = {
            "scale": jnp.array(fp16_initial_scale, jnp.float32),
            "good_steps": jnp.array(0, jnp.int32),
            "hysteresis_left": jnp.array(fp16_hysteresis, jnp.int32),
        }
    return TrainState(
        step=jnp.array(0, dtype=jnp.int32),
        params=params,
        opt_state=opt_state,
        tx=tx,
        lora_enabled=lora_enabled,
        scaler=scaler,
    )
