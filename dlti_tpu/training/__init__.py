"""Training runtime: optimizer, train state, step function, trainer loop."""

from dlti_tpu.training.optimizer import build_optimizer, build_schedule  # noqa: F401
from dlti_tpu.training.state import TrainState, create_train_state  # noqa: F401
from dlti_tpu.training.step import (  # noqa: F401
    causal_lm_loss,
    guard_nonfinite_update,
    make_multi_step,
    make_train_step,
)
from dlti_tpu.training.sentinel import (  # noqa: F401
    DataSkipList,
    NumericSentinel,
    SDC_EXIT_CODE,
    SentinelGiveUp,
    SpikeDetector,
)


def __getattr__(name):
    # Lazy re-export: trainer.py needs dlti_tpu.parallel, which imports
    # training.state (and hence this package) — an eager import here would
    # re-enter the half-initialized parallel package and cycle.
    if name == "Trainer":
        from dlti_tpu.training.trainer import Trainer

        return Trainer
    if name == "ElasticLauncher":
        from dlti_tpu.training.elastic import ElasticLauncher

        return ElasticLauncher
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
