"""Training runtime: optimizer, train state, step function, trainer loop."""

from dlti_tpu.training.optimizer import build_optimizer, build_schedule  # noqa: F401
from dlti_tpu.training.state import TrainState, create_train_state  # noqa: F401
from dlti_tpu.training.step import make_train_step, causal_lm_loss  # noqa: F401
