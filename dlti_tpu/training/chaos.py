"""Deterministic trainer-side fault injection.

The training analog of the gateway's ``DLTI_GATEWAY_FAULT_INJECT`` chaos
hook: kill or crash the trainer at an exact, reproducible point so chaos
tests (and operators running fire drills) can prove the
checkpoint/resume path recovers — without waiting for a real preemption.

Spec format (``--fault-inject-step`` / ``DLTI_TRAIN_FAULT_INJECT``)::

    STEP[:MODE]

where MODE is one of

* ``raise``     — raise :class:`TrainFault` after optimizer step STEP
                  completes (and its save, if due, has been issued).
                  Default.
* ``kill``      — ``SIGKILL`` the process at the same point: no finally
                  blocks, no atexit, no flushed saves — the honest
                  preemption/OOM-killer simulation.
* ``save-raise``— raise *inside* the save path at the first save with
                  step >= STEP, right after the async write is enqueued.
* ``save-kill`` — ``SIGKILL`` at that same point; with async saves the
                  writer thread dies mid-write, leaving the torn
                  ``.tmp-*`` staging dir the verified-resume scan must
                  quarantine.
* ``host-kill`` — SUPERVISOR-owned (``STEP:host-kill[:RANK]``): the
                  elastic launcher SIGKILLs an entire worker process
                  from outside once heartbeats reach STEP — the
                  whole-host death drill. The in-process injector
                  ignores it (``from_spec`` returns None), so the env
                  var can ride the launch env down to every worker.
"""

from __future__ import annotations

import os
import signal
from typing import Optional


class TrainFault(RuntimeError):
    """Raised by the fault injector (``raise`` / ``save-raise`` modes)."""


_MODES = ("raise", "kill", "save-raise", "save-kill")


class TrainFaultInjector:
    """Parsed ``STEP[:MODE]`` spec; fires at most once."""

    def __init__(self, step: int, mode: str):
        if step < 1:
            raise ValueError(f"fault-inject step must be >= 1, got {step}")
        if mode not in _MODES:
            raise ValueError(
                f"unknown fault-inject mode {mode!r}; expected one of "
                f"{_MODES}")
        self.step = step
        self.mode = mode
        self.fired = False
        # Forensics hook, called (mode, where, step) right before the
        # fault fires — even in the ``kill`` modes, where it is the ONLY
        # code that runs before SIGKILL. The trainer wires the flight
        # recorder here so a chaos kill leaves its black box behind (a
        # real external SIGKILL still leaves nothing; the *injected* one
        # is a drill, and drills should produce the evidence the
        # postmortem tooling is drilled on).
        self.pre_fire = None

    @classmethod
    def from_spec(cls, spec: Optional[str]) -> Optional["TrainFaultInjector"]:
        """Parse a spec string; empty/None falls back to the
        ``DLTI_TRAIN_FAULT_INJECT`` env var, then to no injector."""
        spec = (spec or "").strip() or os.environ.get(
            "DLTI_TRAIN_FAULT_INJECT", "").strip()
        if not spec:
            return None
        step_s, _, mode = spec.partition(":")
        if mode.partition(":")[0] == "host-kill":
            # Supervisor-side whole-host chaos
            # (dlti_tpu.training.elastic.HostKillSpec): not an in-process
            # fault — every worker sees the env var and must ignore it.
            return None
        try:
            step = int(step_s)
        except ValueError:
            raise ValueError(
                f"bad fault-inject spec {spec!r}; expected 'STEP[:MODE]' "
                f"with MODE in {_MODES}") from None
        return cls(step, mode or "raise")

    # ------------------------------------------------------------------
    def _fire(self, where: str, step: int) -> None:
        self.fired = True
        if self.pre_fire is not None:
            try:
                self.pre_fire(self.mode, where, step)
            except Exception:
                pass  # forensics must never save the process from chaos
        if self.mode.endswith("kill"):
            # No Python teardown at all — the process vanishes like a
            # preempted node. stdio is not flushed on purpose.
            os.kill(os.getpid(), signal.SIGKILL)
        raise TrainFault(
            f"injected fault ({self.mode}) {where} at step {step}")

    def maybe_fire_step(self, step: int) -> None:
        """Call at the end of each optimizer-step boundary."""
        if (not self.fired and self.mode in ("raise", "kill")
                and step >= self.step):
            self._fire("at step boundary", step)

    def maybe_fire_save(self, step: int) -> None:
        """Call right after a checkpoint save has been issued (async
        writes still in flight — that is the point)."""
        if (not self.fired and self.mode in ("save-raise", "save-kill")
                and step >= self.step):
            self._fire("mid-save", step)
