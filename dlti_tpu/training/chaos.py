"""Deterministic trainer-side fault injection.

The training analog of the gateway's ``DLTI_GATEWAY_FAULT_INJECT`` chaos
hook: kill or crash the trainer at an exact, reproducible point so chaos
tests (and operators running fire drills) can prove the
checkpoint/resume path recovers — without waiting for a real preemption.

Spec format (``--fault-inject-step`` / ``DLTI_TRAIN_FAULT_INJECT``)::

    STEP[:MODE]

where MODE is one of

* ``raise``     — raise :class:`TrainFault` after optimizer step STEP
                  completes (and its save, if due, has been issued).
                  Default.
* ``kill``      — ``SIGKILL`` the process at the same point: no finally
                  blocks, no atexit, no flushed saves — the honest
                  preemption/OOM-killer simulation.
* ``save-raise``— raise *inside* the save path at the first save with
                  step >= STEP, right after the async write is enqueued.
* ``save-kill`` — ``SIGKILL`` at that same point; with async saves the
                  writer thread dies mid-write, leaving the torn
                  ``.tmp-*`` staging dir the verified-resume scan must
                  quarantine.
* ``host-kill`` — SUPERVISOR-owned (``STEP:host-kill[:RANK]``): the
                  elastic launcher SIGKILLs an entire worker process
                  from outside once heartbeats reach STEP — the
                  whole-host death drill. The in-process injector
                  ignores it (``from_spec`` returns None), so the env
                  var can ride the launch env down to every worker.

Numeric chaos (the sentinel drills, ``dlti_tpu.training.sentinel``):

* ``nan-grad``    — poison ONE batch's loss mask with NaN right before
                    dispatch (fires once, at the batch feeding optimizer
                    step >= STEP): the loss and grads go nonfinite
                    through the real compiled step, the in-step gate
                    must skip the update, and the next batch is clean —
                    the transient-blowup simulation.
* ``poison-batch``— deterministically scramble the batch at *data
                    position* STEP (``rng(seed=pos).permutation`` of its
                    tokens) EVERY time that position is fed — keyed by
                    position, not optimizer step, so a rollback that
                    replays the window re-poisons it exactly like real
                    corrupt data, until the sentinel quarantines it.
* ``param-flip``  — ``STEP:param-flip[:RANK]``: flip one mantissa bit in
                    the first cross-process-replicated float param leaf
                    on rank RANK (default 1) at step boundary STEP — the
                    silent-data-corruption simulation the cross-rank
                    digest probe must catch and attribute.

Memory chaos (the OOM drill, ``dlti_tpu.telemetry.memledger``):

* ``hbm-squeeze`` — at step boundary STEP, inflate a balloon of live
                    device arrays (``DLTI_CHAOS_BALLOON_BYTES``, default
                    64 MiB) registered under the ledger's
                    ``chaos_balloon`` owner, then raise
                    :class:`SimulatedOOM` (a RESOURCE_EXHAUSTED-shaped
                    :class:`TrainFault`). The balloon stays live while
                    the fault unwinds, so the flight dump's
                    ``memory.json`` captures the squeezed state — the
                    deterministic CPU stand-in for a real HBM OOM.
"""

from __future__ import annotations

import os
import signal
from typing import Optional


class TrainFault(RuntimeError):
    """Raised by the fault injector (``raise`` / ``save-raise`` modes)."""


class SimulatedOOM(TrainFault):
    """``hbm-squeeze``'s fault: its message carries RESOURCE_EXHAUSTED so
    ``telemetry.memledger.is_oom_error`` classifies it exactly like a
    real XlaRuntimeError OOM — the whole forensics path downstream of
    the catch is the one a real OOM would take."""


_MODES = ("raise", "kill", "save-raise", "save-kill",
          "nan-grad", "poison-batch", "param-flip", "hbm-squeeze")

# hbm-squeeze balloon size (bytes); small enough for CI CPU hosts.
_BALLOON_BYTES_DEFAULT = 64 << 20


class TrainFaultInjector:
    """Parsed ``STEP[:MODE[:RANK]]`` spec; fires at most once — except
    ``poison-batch``, which (like the real corrupt shard it simulates)
    re-fires every time its data position is fed."""

    def __init__(self, step: int, mode: str, rank: int = 1):
        if step < 1:
            raise ValueError(f"fault-inject step must be >= 1, got {step}")
        if mode not in _MODES:
            raise ValueError(
                f"unknown fault-inject mode {mode!r}; expected one of "
                f"{_MODES}")
        self.step = step
        self.mode = mode
        self.rank = rank  # param-flip only: which process corrupts
        self.fired = False
        # Forensics hook, called (mode, where, step) right before the
        # fault fires — even in the ``kill`` modes, where it is the ONLY
        # code that runs before SIGKILL. The trainer wires the flight
        # recorder here so a chaos kill leaves its black box behind (a
        # real external SIGKILL still leaves nothing; the *injected* one
        # is a drill, and drills should produce the evidence the
        # postmortem tooling is drilled on).
        self.pre_fire = None

    @classmethod
    def from_spec(cls, spec: Optional[str]) -> Optional["TrainFaultInjector"]:
        """Parse a spec string; empty/None falls back to the
        ``DLTI_TRAIN_FAULT_INJECT`` env var, then to no injector."""
        spec = (spec or "").strip() or os.environ.get(
            "DLTI_TRAIN_FAULT_INJECT", "").strip()
        if not spec:
            return None
        step_s, _, rest = spec.partition(":")
        mode, _, rank_s = rest.partition(":")
        if mode == "host-kill":
            # Supervisor-side whole-host chaos
            # (dlti_tpu.training.elastic.HostKillSpec): not an in-process
            # fault — every worker sees the env var and must ignore it.
            return None
        try:
            step = int(step_s)
            rank = int(rank_s) if rank_s else 1
        except ValueError:
            raise ValueError(
                f"bad fault-inject spec {spec!r}; expected "
                f"'STEP[:MODE[:RANK]]' with MODE in {_MODES}") from None
        if rank_s and mode != "param-flip":
            raise ValueError(
                f"fault-inject spec {spec!r}: only param-flip takes a "
                f"RANK field")
        return cls(step, mode or "raise", rank=rank)

    # ------------------------------------------------------------------
    def _fire(self, where: str, step: int) -> None:
        self.fired = True
        if self.pre_fire is not None:
            try:
                self.pre_fire(self.mode, where, step)
            except Exception:
                pass  # forensics must never save the process from chaos
        if self.mode.endswith("kill"):
            # No Python teardown at all — the process vanishes like a
            # preempted node. stdio is not flushed on purpose.
            os.kill(os.getpid(), signal.SIGKILL)
        raise TrainFault(
            f"injected fault ({self.mode}) {where} at step {step}")

    def maybe_fire_step(self, step: int) -> None:
        """Call at the end of each optimizer-step boundary."""
        if (not self.fired and self.mode in ("raise", "kill")
                and step >= self.step):
            self._fire("at step boundary", step)
        if (not self.fired and self.mode == "hbm-squeeze"
                and step >= self.step):
            self.fired = True
            from dlti_tpu.telemetry import memledger as _ml

            # Inflate BEFORE raising: the balloon's arrays are live while
            # the fault unwinds, so the dump's memory.json shows the
            # chaos_balloon owner holding the squeezed bytes.
            nbytes = int(os.environ.get(
                "DLTI_CHAOS_BALLOON_BYTES", _BALLOON_BYTES_DEFAULT))
            balloon = _ml.MemoryBalloon(ledger=_ml.get_ledger())
            try:
                balloon.inflate(nbytes)
            except Exception:
                pass  # a balloon that itself OOMs still squeezed enough
            if self.pre_fire is not None:
                try:
                    self.pre_fire(self.mode, "HBM squeezed (balloon "
                                  f"{balloon.nbytes} bytes)", step)
                except Exception:
                    pass
            raise SimulatedOOM(
                f"RESOURCE_EXHAUSTED: injected HBM squeeze at step {step} "
                f"(balloon {balloon.nbytes} bytes)")

    def maybe_fire_save(self, step: int) -> None:
        """Call right after a checkpoint save has been issued (async
        writes still in flight — that is the point)."""
        if (not self.fired and self.mode in ("save-raise", "save-kill")
                and step >= self.step):
            self._fire("mid-save", step)

    # -- numeric chaos (sentinel drills) --------------------------------
    def maybe_corrupt_batch(self, pos: int, step: int,
                            host_batch: dict) -> Optional[dict]:
        """Called by the trainer with each fetched batch's *data
        position* and the optimizer step it will execute as, BEFORE
        device placement. Returns a corrupted copy to feed instead, or
        None (feed the original). Never mutates ``host_batch`` — the
        dataset may own those arrays."""
        import numpy as np

        if self.mode == "nan-grad" and not self.fired and step >= self.step:
            self.fired = True
            if self.pre_fire is not None:
                try:
                    self.pre_fire(self.mode, "batch poisoned (NaN mask)",
                                  step)
                except Exception:
                    pass
            out = dict(host_batch)
            mask = np.asarray(out.get(
                "loss_mask", np.ones_like(out["input_ids"])),
                dtype=np.float32).copy()
            # NaN on every real token: the masked loss sum, n_tok, and
            # every grad go nonfinite through the genuine compiled step.
            mask[mask != 0] = np.nan
            out["loss_mask"] = mask
            return out
        if self.mode == "poison-batch" and pos == self.step:
            # Keyed by DATA POSITION and re-firing: after a rollback the
            # replayed window is poisoned again, exactly like the corrupt
            # shard it simulates; once quarantined it is never fed, so
            # this stops firing. Deterministic per position.
            self.fired = True  # informational; the gate is `pos ==`
            out = dict(host_batch)
            ids = np.asarray(out["input_ids"])
            rng = np.random.default_rng(0x5EED + pos)
            out["input_ids"] = rng.permutation(
                ids.reshape(-1)).reshape(ids.shape).astype(ids.dtype)
            return out
        return None

    def maybe_corrupt_state(self, step: int, state):
        """Called at each optimizer-step boundary with the live train
        state. ``param-flip`` (on the configured rank only) returns a
        state whose first cross-process-replicated float param leaf has
        one mantissa bit flipped — a bit-exact SDC simulation the digest
        probe must attribute; other ranks/modes return None."""
        if self.mode != "param-flip" or self.fired or step < self.step:
            return None
        if os.environ.get("DLTI_GENERATION", "0") != "0":
            # Elastic relaunch: the spec rides the env into every
            # generation, but the flip simulates ONE corruption event —
            # the restarted generations are the recovery under test
            # (same rationale as elastic.HostKillSpec firing once).
            return None
        self.fired = True
        import jax
        import numpy as np

        if jax.process_index() != self.rank:
            return None
        if self.pre_fire is not None:
            try:
                self.pre_fire(self.mode, f"param bit flipped on rank "
                              f"{self.rank}", step)
            except Exception:
                pass
        leaves, treedef = jax.tree_util.tree_flatten(state.params)
        target = None
        for i, leaf in enumerate(leaves):
            if (hasattr(leaf, "dtype") and hasattr(leaf, "sharding")
                    and jax.numpy.issubdtype(leaf.dtype, jax.numpy.inexact)
                    and getattr(leaf.sharding, "is_fully_replicated",
                                False)
                    and leaf.size > 0):
                target = i
                break
        if target is None:
            return None
        leaf = leaves[target]
        try:
            host = np.array(leaf.addressable_data(0))
        except Exception:
            host = np.array(jax.device_get(leaf))
        flat = host.reshape(-1)
        bits = flat.view(np.dtype(f"u{flat.dtype.itemsize}"))
        bits[0] ^= 1  # lowest mantissa bit: silent, tiny, bit-exact
        # make_array_from_callback (not device_put): each process builds
        # its local shards without the multi-process broadcast path's
        # cross-rank equality collectives — per-rank divergence is the
        # POINT here. The product is transfer-created, so launder before
        # it can be donated into the next compiled step (see
        # checkpoint.store._launder).
        if jax.process_count() > 1:
            new_leaf = jax.make_array_from_callback(
                host.shape, leaf.sharding, lambda idx: host[idx])
        else:
            new_leaf = jax.device_put(host, leaf.sharding)
        from dlti_tpu.checkpoint.store import _launder

        leaves[target] = _launder([new_leaf])[0]
        return state.replace(
            params=jax.tree_util.tree_unflatten(treedef, leaves))
