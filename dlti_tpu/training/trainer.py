"""The training loop — in-tree replacement for HF ``Trainer`` + DeepSpeed.

One class drives what the reference spreads across four scripts
(``training/train_baseline.py`` / ``train_deepspeed_zero{1,2,3}.py``):

* build mesh + shard state per the configured ZeRO stage / TP / SP
* iterate epochs of per-host sharded batches
* per-``logging_steps`` loss/throughput logging (``train_baseline.py:184``)
* step- or epoch-based checkpointing with rotation
  (``train_deepspeed_zero1.py:243-245``: save_steps=100, keep 3)
* scan-latest-and-resume (``train_deepspeed_zero1.py:267-279``)
* final metrics in the reference CSV schema + tokens/sec/chip + MFU
  (``train_baseline.py:239-259``)
"""

from __future__ import annotations

import os
import sys
import time
from typing import Iterable, Optional

import jax
import numpy as np

from dlti_tpu.config import Config
from dlti_tpu.models import LlamaForCausalLM, count_params
# Submodule imports (not the package) so that `dlti_tpu.parallel` ->
# `training.state` -> `dlti_tpu.training` (which re-exports Trainer) does
# not cycle back into the half-initialized parallel package.
from dlti_tpu.parallel.mesh import build_mesh
from dlti_tpu.parallel.sharding import make_sharded_train_step, shard_train_state
from dlti_tpu.telemetry import (
    AnomalyWatchdog, FlightRecorder, GoodputLedger, Heartbeat,
    StepLogWriter, TimeSeriesSampler, build_slo_tracker, configure_tracer,
    get_recorder, get_tracer, install_recorder, schedule_lr,
)
from dlti_tpu.telemetry.ledger import (
    goodput_fraction_gauge, goodput_mfu_gauge, goodput_seconds_total,
)
from dlti_tpu.telemetry import memledger as memledger_mod
from dlti_tpu.telemetry.memledger import (
    MemoryLedger, executable_memory_analysis, is_oom_error,
)
from dlti_tpu.training.optimizer import build_optimizer
from dlti_tpu.training.state import TrainState, create_train_state
from dlti_tpu.training.step import make_train_step
from dlti_tpu.utils import durable_io
from dlti_tpu.utils.experiment import experiment_name_from_config
from dlti_tpu.utils.logging import StepTimer, get_logger, is_main_process
from dlti_tpu.utils.metrics import (
    MetricsRecord,
    compute_mfu,
    detect_chip_peak_flops,
    device_peak_memory,
    print_metrics_summary,
    save_training_metrics,
)


def _batch_compatible(a: dict, b: dict) -> bool:
    """Same keys/shapes/dtypes — stackable into one steps_per_sync window.

    Metadata-only checks (``np.shape`` / ``.dtype`` attributes): no copy,
    so device-resident batch leaves never round-trip to host here."""
    if a.keys() != b.keys():
        return False
    return all(np.shape(a[k]) == np.shape(b[k])
               and getattr(a[k], "dtype", None) == getattr(b[k], "dtype", None)
               for k in a)


def _validate_pipeline_config(cfg: Config) -> None:
    """Reject strategy combinations the GPipe path does not implement —
    loudly, at construction, instead of silently mis-sharding (VERDICT r02
    weak #2: PP must be reachable from the production Trainer)."""
    par = cfg.parallel
    illegal = []
    # The whole ZeRO family composes as of r05. ZeRO-1: optimizer state
    # shards over 'data'; the update runs under GSPMD outside the
    # pipeline's shard_map. ZeRO-2: grads additionally pinned to the
    # optimizer-state layout after the pipe step's value_and_grad
    # (reduce-scatter over 'data' instead of all-reduce). ZeRO-3:
    # stacked leaves shard over 'fsdp' on a non-layer dim
    # (pipeline_param_shardings), 'fsdp' rides GSPMD as an auto axis
    # inside the pipe shard_map (per-tick all-gather at use, grads
    # pinned to the reduce-scatter layout) — the same mechanism that
    # carried PP x TP.
    # 'tensor', 'data', 'expert', and 'sequence' all compose:
    # stage-internal TP, batch-row DP, and expert parallelism ride as
    # GSPMD auto axes inside the pipeline's shard_map; SP does too —
    # under pipe, ring_attention DELEGATES to reference_attention and
    # GSPMD partitions it over the auto 'sequence' axis (all-gather SP;
    # a nested manual ring computes wrong grads or fails verification
    # on this jax — see ring_attention's delegation comment). pipe x
    # tensor x data is full 3D; fsdp (ZeRO-3), expert, and sequence
    # extend it.
    if par.sequence > 1 and cfg.train.loss_chunk:
        # Mirror the flat-path rejection (make_sharded_train_step): the
        # chunk reshape would regather the 'sequence'-sharded hidden.
        illegal.append(f"sequence={par.sequence} with train.loss_chunk "
                       "(the chunk reshape regathers the sequence-"
                       "sharded activations; set loss_chunk=0)")
    if par.fsdp > 1 and int(par.zero_stage) != 3:
        illegal.append(f"fsdp={par.fsdp} without zero_stage=3 (the fsdp "
                       "axis only carries ZeRO-3 param sharding)")
    # Host offload composes (r05) in boundary-transfer mode — the flat
    # path's fallback semantics: offloaded leaves (optimizer moments
    # and/or the frozen base) rest in pinned host memory between steps
    # and cross at step boundaries (_build_step). In-step per-layer
    # STREAMING stays flat-only (pinned_host operands cannot enter the
    # pipe shard_map stage-sharded). offload_params needs LoRA: it
    # offloads the frozen base, and a full fine-tune has none.
    if par.offload_params and not cfg.lora.enabled:
        illegal.append("offload_params without LoRA (it offloads the "
                       "frozen base params; a full fine-tune has none)")
    # fp16 dynamic loss scaling composes: the pipelined step scales the
    # loss, unscales grads, and evolves TrainState.scaler via the same
    # apply_loss_scaler helper the flat step uses.
    # quantize_frozen_base composes: the stage body dequantizes int8
    # leaves like the unpipelined block, and pipeline_forward dequantizes
    # embed/head on the fly (quantized kernels TP-shard too via the shared
    # quant-path normalization in parallel.sharding).
    # loss_chunk composes: pipeline_forward returns hidden states and the
    # pipelined loss applies the head per sequence chunk
    # (pipeline_head_matrix + chunked_causal_lm_loss).
    # MoE composes: the stage scan collects each layer's sown router
    # aux loss (edge ticks masked so fill/drain recomputes don't
    # double-count), psum'd over 'pipe'; EP composes too (see above).
    # Packed sequences compose: segment ids ride each microbatch through
    # the stages (pipeline_forward segment_ids), per-doc positions included.
    # Every named remat policy composes as of r05 (the scanned stage body
    # passes cfg.remat_policy through the flat path's policy table), and
    # remat_stride does too: layers scan in GROUPS of stride with every
    # stride-th block keeping its activations (pipeline_forward); a
    # non-dividing stride warns in make_pipeline_train_step and falls
    # back to full remat.
    import jax as _jax

    if _jax.process_count() > 1:
        # Multi-host PP composes when the batch-row axes (data x fsdp)
        # span the processes: rows then shard across hosts and
        # make_global_batch assembles a consistent global array, with
        # the pipe/tensor/expert axes process-local (mesh order is
        # data-major). Without that, batch rows would be REPLICATED
        # across hosts while each host feeds its own different shard —
        # silent divergence. Proven by the 2-process 'pipe' leg in
        # tests/test_distributed.py (data=4 x pipe=2 over 2 processes).
        rows = par.data * par.fsdp
        if rows % _jax.process_count() != 0:
            illegal.append(
                f"multi-host meshes with batch-row extent data*fsdp={rows} "
                f"not divisible by process_count={_jax.process_count()} "
                "(batch rows must shard across hosts; a host-replicated "
                "batch would silently differ per host)")
    if illegal:
        raise ValueError(
            "pipeline parallelism (parallel.pipe="
            f"{par.pipe}) does not compose with: {', '.join(illegal)}. "
            "Legal: pipe x tensor x data x fsdp x sequence x expert "
            "(GPipe stages, stage-internal TP, batch-row DP, ZeRO-1/2/3, "
            "GSPMD-partitioned SP, expert parallelism) with "
            "bf16-or-int8-base LoRA or full fine-tune, dense or MoE "
            "models, packed or padded batches, fp16 scaler, loss_chunk, "
            "any named remat policy — single-host, or multi-host when "
            "data*fsdp divides by process_count (batch rows shard across "
            "hosts, pipe stages process-local)")
    if cfg.train.grad_accum_steps < 1:
        raise ValueError("grad_accum_steps must be >= 1 under pipe")


class Trainer:
    def __init__(self, cfg: Config, model: Optional[LlamaForCausalLM] = None,
                 base_params: Optional[dict] = None):
        self.cfg = cfg
        self.logger = get_logger()
        # Pretrained base weights (e.g. from models.load_hf_checkpoint) to
        # overlay onto the initialized tree — the from_pretrained analog.
        self.base_params = base_params
        self.tx = build_optimizer(cfg.optimizer)
        if cfg.parallel.pipe > 1:
            _validate_pipeline_config(cfg)
        self.mesh = None
        if cfg.parallel.num_devices > 1:
            self.mesh = build_mesh(cfg.parallel)
        # The model needs the mesh for sequence parallelism: with
        # parallel.sequence > 1 attention runs the ring schedule
        # (dlti_tpu.parallel.ring_attention) over the 'sequence' axis.
        self.model = model or LlamaForCausalLM(
            cfg.model, cfg.lora if cfg.lora.enabled else None, self.mesh
        )
        self._step_fn = None
        self._ckpt_mgr = None
        # Preemption flag: set by SIGTERM (cluster eviction) or
        # request_stop(); honored at the next step boundary.
        self._stop_requested = False
        # Chaos injector (dlti_tpu.training.chaos); (re)parsed per train().
        self._fault = None
        self._last_eval_loss = float("nan")
        # Host-side span tracer (telemetry.tracer): per-step phase spans
        # (batch fetch, host→device, dispatch, device sync, eval, save).
        # Disabled by default; cfg.telemetry.trace_dir enables it in
        # train() — span sites cost one attribute read while disabled.
        self._tracer = get_tracer()
        # Flight-recorder context hook (telemetry.flightrecorder): a
        # dict-merge no-op until train() installs a recorder; methods
        # outside the loop (_run_eval, _maybe_save) call it too.
        self._fnote = lambda **kw: None
        # Goodput ledger (telemetry.ledger): train() replaces this with a
        # live phase clock when cfg.telemetry.goodput_ledger is on; the
        # disabled placeholder keeps every enter() site a one-attribute-
        # read no-op (methods outside the loop transition through it too).
        self._ledger = GoodputLedger(enabled=False)

    # ------------------------------------------------------------------
    def init_state(self, rng: Optional[jax.Array] = None) -> TrainState:
        rng = rng if rng is not None else jax.random.PRNGKey(self.cfg.train.seed)
        state = create_train_state(
            rng,
            self.model,
            self.tx,
            (self.cfg.train.micro_batch_size, self.cfg.data.max_seq_len),
            lora_enabled=self.cfg.lora.enabled,
            fp16_initial_scale=(
                float(2 ** self.cfg.train.fp16_initial_scale_power)
                if self.cfg.train.fp16 else None),
            fp16_hysteresis=self.cfg.train.fp16_hysteresis,
        )
        if self.base_params is not None:
            from dlti_tpu.models import graft_base_params

            state = state.replace(
                params=graft_base_params(state.params, self.base_params))
        if self.cfg.train.quantize_frozen_base:
            if self.cfg.train.quantize_frozen_base != "int8":
                raise ValueError(
                    f"unknown quantize_frozen_base="
                    f"{self.cfg.train.quantize_frozen_base!r} (only 'int8')")
            if not self.cfg.lora.enabled:
                raise ValueError(
                    "quantize_frozen_base requires LoRA: it compresses the "
                    "frozen base params, and a full fine-tune has none")
            from dlti_tpu.models.quantization import quantize_params_int8

            # donate=True retires each bf16 source as its int8 twin lands,
            # so quantizing a 7B tree never holds both copies in HBM.
            state = state.replace(
                params=quantize_params_int8(state.params, donate=True))
        if self.mesh is not None and self.cfg.parallel.pipe > 1:
            # Pipeline layout: layers_{i} subtrees stack with a leading
            # layer dim, sharded over 'pipe'; embed/norm/head + optimizer
            # state replicate (they are a few percent of params/FLOPs).
            from jax.sharding import NamedSharding, PartitionSpec as P

            from dlti_tpu.parallel.pipeline import (
                pipeline_param_shardings, to_pipeline_state,
            )

            state = to_pipeline_state(state, self.cfg.model.num_layers)
            repl = NamedSharding(self.mesh, P())
            # opt_state_shardings is shape-based, so it applies to the
            # stacked trainable tree unchanged: ZeRO-1/2 x PP shard Adam
            # moments over 'data', ZeRO-3 x PP over 'fsdp' (the update
            # runs under GSPMD outside the pipeline's shard_map); stage
            # NONE (or a size-1 axis) falls out replicated.
            from dlti_tpu.parallel.sharding import opt_state_shardings

            param_sh = pipeline_param_shardings(state.params, self.mesh)
            if self.cfg.parallel.offload_params:
                # PP x param host-offload (boundary-transfer mode, the
                # flat path's fallback semantics): FROZEN base leaves
                # rest in pinned host memory between steps; trainable
                # (LoRA) leaves stay device-resident. _build_step moves
                # the frozen tree HBM-ward per step and splices the
                # still-valid host copies back after.
                from dlti_tpu.parallel.sharding import _host_memory_kind
                from dlti_tpu.training.state import (
                    combine_params, partition_params,
                )

                kind = _host_memory_kind(self.mesh)
                if kind is not None:
                    trainable_sh, frozen_sh = partition_params(
                        param_sh, self.cfg.lora.enabled)
                    frozen_sh = jax.tree_util.tree_map(
                        lambda s: NamedSharding(self.mesh, s.spec,
                                                memory_kind=kind),
                        frozen_sh)
                    param_sh = combine_params(trainable_sh, frozen_sh)
            from dlti_tpu.parallel.sharding import (
                launder_transfer_created, place_on_mesh,
            )

            # place_on_mesh, not device_put: multi-process placement of a
            # replicated-init state assembles local shards instead of
            # broadcasting every value; the launder makes the products
            # safe to donate (see sharding.place_on_mesh /
            # launder_transfer_created).
            state = launder_transfer_created(state.replace(
                params=jax.tree_util.tree_map(
                    place_on_mesh, state.params, param_sh),
                opt_state=jax.tree_util.tree_map(
                    place_on_mesh, state.opt_state,
                    opt_state_shardings(state.opt_state, self.cfg,
                                        self.mesh)),
                step=place_on_mesh(state.step, repl),
            ))
        elif self.mesh is not None:
            state = shard_train_state(state, self.cfg, self.mesh)
        return state

    def _build_step(self, state: TrainState):
        if self.mesh is not None and self.cfg.parallel.pipe > 1:
            from dlti_tpu.parallel.pipeline import make_pipeline_train_step

            accum = self.cfg.train.grad_accum_steps
            pipe = self.cfg.parallel.pipe
            if accum < 4 * pipe and is_main_process():
                self.logger.warning(
                    "GPipe bubble: grad_accum_steps=%d microbatches over "
                    "pipe=%d stages idles %.0f%% of ticks; use >= %d "
                    "microbatches for >80%% utilization",
                    accum, pipe, 100 * (pipe - 1) / (accum + pipe - 1),
                    4 * pipe)
            pipe_step = make_pipeline_train_step(
                self.cfg, self.tx, self.mesh, num_microbatches=accum)

            def step_fn(state, batch, rng):
                # (accum, micro_bs, seq) -> (accum*micro_bs, seq): grad
                # accumulation happens through the microbatch schedule.
                # Packed batches ride along: segment_ids/positions flatten
                # the same way and pipeline_forward masks per microbatch.
                flat = {k: v.reshape((-1,) + v.shape[2:])
                        for k, v in batch.items()}
                return pipe_step(state, flat, rng)

            if (self.cfg.parallel.offload_optimizer
                    or self.cfg.parallel.offload_params):
                # PP x host offload (boundary-transfer mode, the flat
                # path's fallback semantics): one shared wrapper — it
                # derives shardings from the PLACED state and is a no-op
                # when nothing actually rests in host memory (backend
                # without pinned_host).
                from dlti_tpu.parallel.sharding import wrap_boundary_offload

                step_fn = wrap_boundary_offload(
                    step_fn, state, self.mesh, self.cfg.lora.enabled)

            return step_fn
        if self.mesh is not None:
            return make_sharded_train_step(
                self.model, state, self.cfg, self.mesh,
                accum_steps=self.cfg.train.grad_accum_steps,
            )
        return jax.jit(
            make_train_step(
                self.model, accum_steps=self.cfg.train.grad_accum_steps,
                fp16_scale_window=self.cfg.train.fp16_scale_window,
                fp16_min_scale=self.cfg.train.fp16_min_scale,
                fp16_hysteresis=self.cfg.train.fp16_hysteresis,
                loss_chunk=self.cfg.train.loss_chunk,
            ),
            donate_argnums=(0,),
        )

    # ------------------------------------------------------------------
    def train(
        self,
        batches_per_epoch: Iterable[dict] | None = None,
        dataset=None,
        eval_dataset=None,
        state: Optional[TrainState] = None,
        resume: Optional[bool] = None,
    ) -> tuple:
        """Run the configured number of epochs. Returns (state, MetricsRecord).

        ``dataset`` (a :class:`~dlti_tpu.data.TokenBatchDataset`) enables
        epoch re-iteration and exact resume of the data schedule;
        ``batches_per_epoch`` is a simpler single-epoch iterable for custom
        loops (resume restores weights but not batch order).
        """
        cfg = self.cfg
        # Goodput ledger: the phase clock starts before state init so
        # compile/init time books as "startup" — every second of train()
        # lands in exactly one bucket (conservation is tier-1-tested).
        ledger = self._ledger = GoodputLedger(
            enabled=cfg.telemetry.goodput_ledger)
        state = state or self.init_state()
        resume = cfg.checkpoint.resume if resume is None else resume

        # Memory ledger (telemetry.memledger): owners registered as
        # callables through a one-slot box because the functional state
        # rebinds every step (donated buffers delete; the ledger skips
        # deleted arrays, and the box is refreshed at every bookkeep /
        # restore / rollback so snapshots track the live state).
        memledger = self._memledger = MemoryLedger(
            enabled=cfg.telemetry.memory_ledger,
            capacity_bytes=cfg.telemetry.hbm_budget_bytes)
        memledger_mod.install(memledger)
        mem_state = {"state": state}
        memledger.register("params", lambda: mem_state["state"].params)
        memledger.register("optimizer_state",
                           lambda: mem_state["state"].opt_state)
        memledger.register(
            "prefetch_buffers",
            lambda: (self._prefetcher.buffered_batches()
                     if getattr(self, "_prefetcher", None) is not None
                     else None))

        # Preemption-aware checkpointing (SURVEY.md §5.3): the reference's
        # only resilience is frequent periodic saves; here SIGTERM (the
        # cluster-eviction signal) triggers one final checkpoint at the
        # next step boundary — or, with steps_per_sync > 1, the next
        # window boundary (a filling window is dropped; an in-flight
        # scanned program finishes first) — so resume loses at most one
        # dispatch unit instead of up to save_steps.
        import signal as _signal

        self._stop_requested = False  # a reused Trainer trains again
        self._last_eval_loss = float("nan")
        prev_handler = None
        sigterm_installed = False
        try:
            prev_handler = _signal.signal(
                _signal.SIGTERM, lambda *_: self.request_stop())
            sigterm_installed = True
        except ValueError:
            pass  # not the main thread (e.g. embedded in a server)

        # Deterministic chaos hook (dlti_tpu.training.chaos): fresh per
        # train() call so a resumed run re-reads the spec/env.
        from dlti_tpu.training.chaos import TrainFaultInjector

        self._fault = TrainFaultInjector.from_spec(cfg.train.fault_inject_step)

        start_step = 0
        resume_meta = None
        self._rollback_due = None
        self._sdc_evict = False
        if resume and cfg.checkpoint.save_strategy != "no":
            from dlti_tpu.checkpoint import restore_latest_verified

            # Verified resume: digest-checks newest-first, quarantining
            # incomplete/corrupt checkpoints (kill mid-save, bit rot) and
            # falling back to the newest good one instead of crashing.
            ledger.enter("checkpoint_restore")
            restored = restore_latest_verified(cfg.checkpoint.output_dir,
                                               state)
            ledger.enter("startup")
            if restored is not None:
                state, step, resume_meta = restored
                mem_state["state"] = state
                start_step = int(step)
                self.logger.info(
                    "resumed from verified checkpoint step %d", start_step)
                if resume_meta and resume_meta.get("seed", cfg.train.seed) \
                        != cfg.train.seed:
                    self.logger.warning(
                        "checkpoint was saved with train.seed=%s but this "
                        "run uses %s — the resumed loss trajectory will "
                        "not match the original run's",
                        resume_meta.get("seed"), cfg.train.seed)

        step_fn = self._build_step(state)
        sync_k = max(1, int(cfg.train.steps_per_sync))
        multi_fn = None
        if sync_k > 1:
            if cfg.parallel.offload_optimizer or cfg.parallel.offload_params:
                raise ValueError(
                    "train.steps_per_sync > 1 does not compose with host "
                    "offload: the offload fallback moves state between host "
                    "and HBM at host-level step boundaries, which a scanned "
                    "window has none of; set steps_per_sync=1")
            if jax.process_count() > 1:
                raise ValueError(
                    "train.steps_per_sync > 1 is single-host only: "
                    "per-window global-batch assembly is not implemented "
                    "for multi-host meshes")
            from dlti_tpu.training.step import make_multi_step

            multi_fn = make_multi_step(step_fn)
        # Per-step rng keys are folded from a fixed base by *global step
        # index* (not a split chain): step N uses fold_in(base, N) whether
        # the run reached N directly or resumed into it, which is what
        # makes a mid-epoch resume's loss trajectory bit-identical to the
        # uninterrupted run's — a split chain would desynchronize on
        # resume (and on preemption-dropped window batches).
        rng_base = jax.random.PRNGKey(cfg.train.seed + 1)
        timer = StepTimer(warmup_steps=2)

        trainable, total = count_params(state.params)
        if is_main_process():
            self.logger.info(
                "trainable params: %s / %s (%.4f%%)",
                f"{trainable:,}", f"{total:,}", 100 * trainable / total,
            )

        tokens_per_step = (
            cfg.train.micro_batch_size * cfg.train.grad_accum_steps * cfg.data.max_seq_len
        )

        # -- unified telemetry (dlti_tpu.telemetry) ---------------------
        tcfg = cfg.telemetry
        if tcfg.trace_dir:
            self._tracer = configure_tracer(enabled=True,
                                            capacity=tcfg.trace_capacity)
        tracer = self._tracer
        steplog = None
        if tcfg.step_log_path and is_main_process():
            steplog = StepLogWriter(tcfg.step_log_path, run_meta={
                "experiment": experiment_name_from_config(cfg),
                "num_gpus": cfg.parallel.num_devices,
                "zero_stage": int(cfg.parallel.zero_stage),
                "strategy": self._strategy(),
            })
        heartbeat = None
        if tcfg.heartbeat_interval_steps > 0:
            heartbeat = Heartbeat()

        # -- self-monitoring: time-series ring + watchdog + black box ---
        # (telemetry.timeseries / .watchdog / .flightrecorder): the ring
        # samples the live training scalars below; the watchdog's
        # hung-step rule is fed by notify_step in bookkeep; the flight
        # recorder dumps on fatal exceptions, preemption stops, watchdog
        # escalation, and the chaos injector's pre-fire hook.
        wcfg, fcfg = tcfg.watchdog, tcfg.flight_recorder
        sampler = None
        watchdog = None
        flight = None
        self._live = {"train_step": start_step}
        # Sentinel handles for _train_scalars (populated after resume).
        self._sentinel = None
        self._skiplist = None
        self._sdc_probe = None

        # Elastic supervision (dlti_tpu.training.elastic): when launched
        # by the ElasticLauncher, report per-step liveness via heartbeat
        # files (the supervisor's staleness + chaos-trigger input) and
        # expose the generation/world gauges.
        from dlti_tpu.training import elastic as _elastic

        einfo = _elastic.elastic_info()
        if einfo is not None:
            _elastic.generation_gauge.set(einfo["generation"])
            _elastic.world_size_gauge.set(jax.process_count())
            self._live["elastic_generation"] = einfo["generation"]
            self._live["elastic_world_size"] = jax.process_count()
            self._live["elastic_restarts"] = _elastic.restarts_total.value
            _elastic.beat(start_step)  # liveness before the first step

        def _train_scalars():
            from dlti_tpu.checkpoint.store import (
                corrupt_skipped, last_verified_step, save_retries,
            )

            d = dict(self._live)
            d["ckpt_save_retries"] = save_retries.value
            d["ckpt_corrupt_skipped"] = corrupt_skipped.value
            d["ckpt_last_verified_step"] = last_verified_step.value
            d["trace_dropped_events"] = tracer.dropped_events
            # Sentinel/SDC counters (set once the sentinel initializes a
            # few lines below the sampler start): the watchdog's
            # loss_spike / nonfinite_step / sdc_mismatch rules watch
            # these ring series.
            if self._sentinel is not None:
                d.update(self._sentinel.scalars())
                d["sentinel_quarantined_windows"] = len(
                    self._skiplist.quarantined())
            if self._sdc_probe is not None:
                d.update(self._sdc_probe.scalars())
            # Goodput ledger: per-bucket seconds + the derived fraction
            # ride the ring (the watchdog's goodput_collapse rule, the
            # /dashboard sparkline, and every flight dump read these).
            if ledger.enabled:
                d.update(ledger.scalars())
            # Memory ledger: hbm_* series (the hbm_pressure rule, the
            # dashboard's "where the memory lives" panel, flight dumps).
            if memledger.enabled:
                d.update(memledger.scalars())
            if heartbeat is not None and heartbeat.last_seen:
                # Straggler lag on /debug/vars (the gauge twin lives in
                # Heartbeat.register; this is the ring-series form).
                d["heartbeat_lag"] = heartbeat.lag()
            # Durable-writer health: disk free/error/degraded series (the
            # watchdog's disk_pressure rule and flight dumps read these).
            d.update(durable_io.scalars())
            return d

        if wcfg.enabled or fcfg.enabled:
            sampler = TimeSeriesSampler(interval_s=wcfg.interval_s)
            sampler.add_source(_train_scalars)
        if fcfg.enabled and (is_main_process() or einfo is not None):
            # Every rank records under an elastic supervisor: per-rank
            # black boxes (tagged -gG-rR) are what postmortem --all
            # renders into one incident, and the SDC probe's suspect rank
            # must be able to dump before it evicts itself.
            if not tracer.enabled:
                # The black box needs a span tail even without a
                # --trace-dir export: recording is cheap (ring appends),
                # missing evidence is not.
                self._tracer = tracer = configure_tracer(
                    enabled=True, capacity=tcfg.trace_capacity)
            flight = FlightRecorder(
                fcfg.dir, tracer=tracer, sampler=sampler, config=cfg,
                max_spans=fcfg.max_spans,
                timeseries_tail=fcfg.timeseries_tail, keep=fcfg.keep)
            flight.add_metrics_source(_train_scalars)
            if memledger.enabled:
                # Every dump carries memory.json — the full ownership map
                # at death, the OOM postmortem's primary evidence.
                flight.add_memory_source(memledger.to_dict)
            flight.note(role="training", phase="init", step=start_step,
                        last_completed_step=start_step,
                        experiment=experiment_name_from_config(cfg))
            install_recorder(flight)
            self._fnote = flight.note
            if self._fault is not None:
                # Chaos forensics: the injected fault's last act is
                # writing the black box — even for N:kill, where the
                # pre-fire hook is the only code that runs before
                # SIGKILL. The drill exists to produce the evidence.
                self._fault.pre_fire = \
                    lambda mode, where, step: flight.dump(
                        reason=f"chaos_{mode}", force=True,
                        extra={"where": where, "injected_at_step": step})
        # Training-side SLO tracker: the goodput-fraction objective over
        # the ledger's own SLI (telemetry.slo) — burn-rate state rides
        # the ring, the watchdog's slo_burn rule, and slo.json in every
        # flight dump.
        slo_tracker = None
        if ledger.enabled and getattr(tcfg, "slo", None) is not None:
            slo_tracker = build_slo_tracker(
                tcfg.slo, goodput_fn=ledger.goodput_fraction)
        if slo_tracker is not None:
            if sampler is not None:
                sampler.add_source(slo_tracker.scalars)
            if flight is not None:
                flight.add_slo_source(slo_tracker.to_dict)
        if wcfg.enabled:
            watchdog = AnomalyWatchdog(wcfg, sampler, heartbeat=heartbeat,
                                       tracer=tracer, slo=slo_tracker)
            if flight is not None:
                flight.add_context_source(
                    lambda: {"watchdog_alerts": list(watchdog.alerts)})
        if sampler is not None:
            sampler.start()
        if watchdog is not None:
            watchdog.start()
        fnote = self._fnote

        # Constants for the per-step MFU/throughput fields (same terms
        # _final_metrics uses for the run-level record). The ledger needs
        # them too: its MFU gauge is the /metrics twin of the steplog's.
        peak_flops = (detect_chip_peak_flops()
                      if (steplog is not None or ledger.enabled) else 0.0)
        n_for_flops = (cfg.model.num_active_params()
                       if cfg.model.num_experts > 0 else total)

        losses: list = []
        global_step = start_step
        samples_seen = 0
        t_start = time.time()

        # Resume the *data schedule* too, not just the weights: skip the
        # epochs/steps already consumed so no batch is trained twice (the
        # reference delegates this to HF Trainer's resume machinery).
        # The DATA CURSOR is tracked separately from the optimizer step:
        # they are equal until the sentinel quarantines a data window,
        # after which the cursor leads the step by the windows skipped
        # (the sidecar records both, so resume replays exactly).
        spe = dataset.steps_per_epoch() if dataset is not None else 0
        data_cursor = start_step
        if resume_meta and resume_meta.get("data_pos") is not None:
            data_cursor = int(resume_meta["data_pos"])
        start_epoch, skip_steps = 0, 0
        if data_cursor > 0 and dataset is not None:
            if spe > 0:
                start_epoch = min(data_cursor // spe, cfg.train.num_epochs)
                skip_steps = data_cursor % spe
            if resume_meta and resume_meta.get("dataset"):
                # The sidecar records the data cursor the checkpoint was
                # saved at; a mismatch means the resumed run is feeding a
                # different schedule than the original (exact replay off).
                saved = resume_meta["dataset"]
                if saved.get("steps_per_epoch") not in (None, 0, spe):
                    self.logger.warning(
                        "checkpoint sidecar recorded steps_per_epoch=%s "
                        "but this dataset yields %s — mid-epoch resume "
                        "will replay a different batch schedule",
                        saved.get("steps_per_epoch"), spe)
                cur_shuffle = getattr(dataset, "shuffle_seed", None)
                if saved.get("shuffle_seed", cur_shuffle) != cur_shuffle:
                    self.logger.warning(
                        "checkpoint sidecar recorded shuffle_seed=%s but "
                        "this dataset uses %s — batch order will differ "
                        "from the original run",
                        saved.get("shuffle_seed"), cur_shuffle)

        # Mutable resume point: rollback rewinds it mid-run.
        resume_point = {"epoch": start_epoch, "skip": skip_steps}
        # fetch: next data position the loop will consume; committed:
        # position after the last EXECUTED batch (what the sidecar
        # records — prefetched/dropped batches replay on resume).
        cursor = {"fetch": data_cursor, "committed": data_cursor}

        def epoch_batches(epoch):
            if dataset is not None:
                return dataset.epoch(
                    epoch,
                    skip_steps=resume_point["skip"]
                    if epoch == resume_point["epoch"] else 0)
            return batches_per_epoch

        # -- numeric-fault sentinel (dlti_tpu.training.sentinel) --------
        # Detection is pure host math over the metrics the compiled step
        # already syncs; rollback needs a dataset (exact replay) and a
        # checkpoint store to restore from.
        from dlti_tpu.training import sentinel as sentinel_mod

        scfg = cfg.train.sentinel
        sentinel = None
        skiplist = None
        sdc_probe = None
        if scfg.enabled:
            sentinel = sentinel_mod.NumericSentinel(scfg)
            skiplist = sentinel_mod.DataSkipList(scfg.quarantine_after)
            if cfg.checkpoint.save_strategy != "no":
                skiplist.load(cfg.checkpoint.output_dir)
            if resume_meta:
                skiplist.merge_meta(resume_meta.get("skip_list"))
            if skiplist.quarantined() and is_main_process():
                self.logger.warning(
                    "sentinel: honoring persistent skip-list — %d data "
                    "window(s) quarantined: %s", len(skiplist.quarantined()),
                    sorted(skiplist.quarantined()))
            if scfg.sdc_check_interval > 0 and jax.process_count() > 1:
                sdc_probe = sentinel_mod.SDCProbe(scfg.sdc_check_interval)
        self._sentinel, self._skiplist, self._sdc_probe = \
            sentinel, skiplist, sdc_probe
        rollback_allowed = (sentinel is not None and dataset is not None
                            and cfg.checkpoint.save_strategy != "no"
                            and scfg.rollback_after > 0)
        # step -> data position of the batch that fed it (bounded; the
        # rollback path looks up the anomalous streak's windows here).
        step_pos: dict = {}
        skipped_windows = 0

        # -- background batch prefetch (dlti_tpu.data.prefetch) ---------
        # Gather/pack runs on a worker thread, double-buffered
        # cfg.data.prefetch_depth deep; where the step's input sharding is
        # known host-side the worker also issues the device_put ahead of
        # need (an async dispatch — the transfer overlaps the in-flight
        # step). Batch ORDER is untouched (one worker, FIFO queue), so the
        # loss trajectory is bit-identical to the inline path.
        # Only dataset-driven epochs prefetch: a custom batches_per_epoch
        # iterable may be a side-effecting generator whose *laziness* is
        # load-bearing (e.g. requesting a stop at yield time), and eager
        # consumption would reorder those effects against the step loop.
        prefetch_depth = (max(0, int(cfg.data.prefetch_depth))
                          if dataset is not None else 0)
        prefetch_place = None
        if prefetch_depth > 0 and multi_fn is None:
            if self.mesh is None:
                # Single-device jit: plain default-device placement.
                prefetch_place = jax.device_put
            elif (jax.process_count() == 1 and cfg.parallel.pipe == 1
                  and not (cfg.parallel.offload_optimizer
                           or cfg.parallel.offload_params)):
                # Flat sharded path: place with the step's own batch
                # sharding (make_sharded_train_step's in_shardings), so
                # dispatch finds the operands already resident. Pipe and
                # offload steps keep host batches (their wrappers reshape
                # or move operands themselves); multi-host keeps
                # make_global_batch on the step thread.
                from jax.sharding import NamedSharding

                from dlti_tpu.parallel.sharding import batch_pspec

                _b_sh = NamedSharding(self.mesh, batch_pspec(cfg))
                prefetch_place = lambda b: {  # noqa: E731
                    k: jax.device_put(v, _b_sh) for k, v in b.items()}
        # steps_per_sync windows stack HOST batches (exec_window), so the
        # worker prefetches the gather only — placement would be a wasted
        # second transfer. Window mode still benefits: the gather/pack for
        # batch N+1 overlaps the scanned window N.
        self._prefetcher = None

        def make_batch_iter(epoch):
            src = epoch_batches(epoch)
            if prefetch_depth > 0:
                from dlti_tpu.data.prefetch import HostPrefetcher

                self._prefetcher = HostPrefetcher(
                    src, depth=prefetch_depth, place_fn=prefetch_place,
                    tracer=tracer)
                return iter(self._prefetcher)
            return iter(src)

        def close_prefetcher():
            if self._prefetcher is not None:
                self._prefetcher.close()
                self._prefetcher = None

        eval_fn = None
        if eval_dataset is not None and cfg.train.eval_steps:
            if cfg.parallel.pipe > 1:
                from dlti_tpu.parallel.pipeline import make_pipeline_eval_step

                # Packed eval batches are fine: make_pipeline_eval_step
                # passes segment_ids/positions through pipeline_forward.
                eval_fn = make_pipeline_eval_step(cfg, self.mesh)
                params_dev_sh = getattr(step_fn,
                                        "params_dev_shardings", None)
                if params_dev_sh is not None:
                    # PP x offload_params: eval feeds params into the
                    # same pipe shard_map, which cannot take pinned_host
                    # stage-sharded operands. Tag the shardings for
                    # _run_eval, which transfers the frozen tree
                    # HBM-ward ONCE per eval pass (not per batch — a 7B
                    # base x 50 eval batches would be hundreds of GB of
                    # needless DMA) and releases the copy after.
                    inner_eval = eval_fn

                    def eval_fn(state, batch, _inner=inner_eval):
                        return _inner(state, batch)

                    eval_fn.params_dev_shardings = params_dev_sh
            else:
                from dlti_tpu.training.step import make_eval_step

                eval_fn = jax.jit(make_eval_step(
                    self.model, loss_chunk=self.cfg.train.loss_chunk))

        # Profiler window state: "pending" -> "active" -> "done" (at most
        # one trace per run; ">=" so a resume past the start step still
        # captures the next profile_num_steps steps).
        profile_state = "pending"
        profile_stop_at = None

        recorder = None
        if cfg.train.record_replay_dir and is_main_process():
            from dlti_tpu.utils.debug import StepRecorder

            recorder = StepRecorder(cfg.train.record_replay_dir,
                                    keep=cfg.train.record_replay_keep,
                                    every_steps=cfg.train.record_replay_every)
        # steps_per_sync window of (host_batch, global_batch, step_rng)
        # pending dispatch; always empty when multi_fn is None.
        window: list = []

        # In a steps_per_sync run the standalone per-step executable only
        # compiles when a drain first needs it (full windows trace step_fn
        # inline); that first call's compile time must not pollute the
        # step-time samples.
        step_fn_warm = {"done": multi_fn is None}

        # Activation-peak estimate: fold the compiled step's
        # memory_analysis() (temp/argument/output bytes — the transient
        # HBM a between-steps snapshot can never see) into the memory
        # ledger, once. Opt-in via env: the jit wrapper exposes no handle
        # to its cached executable, so this lowers+compiles a second time
        # — free on the tiny CI models that assert on it, not on a 7B run.
        mem_act = {"due": (memledger.enabled and os.environ.get(
            "DLTI_HBM_ANALYZE_STEP", "0") != "0")}

        def fold_step_memory_analysis(state, gb, r):
            mem_act["due"] = False
            try:
                info = executable_memory_analysis(
                    step_fn.lower(state, gb, r).compile())
            except Exception:
                return
            memledger.note_activation_peak(info)

        def exec_steps(state, items):
            """Classic path: one compiled call + host sync per step."""
            executed = []
            for hb, gb, r, pos in items:
                if mem_act["due"]:
                    fold_step_memory_analysis(state, gb, r)
                warm = step_fn_warm["done"]
                if warm:
                    timer.start()
                fnote(phase="step_dispatch")
                ledger.enter("step_compute")
                with tracer.span("train/step_dispatch", cat="train"):
                    state, m = step_fn(state, gb, r)
                fnote(phase="device_sync")
                ledger.enter("device_sync")
                with tracer.span("train/device_sync", cat="train"):
                    m = jax.device_get(m)  # blocks: true step time
                if warm:
                    timer.stop()
                else:
                    step_fn_warm["done"] = True
                executed.append((hb, r, m, pos))
            return state, executed

        def exec_window(state):
            """One scanned program runs the whole window; sync once.

            Stacks the *host* batches: multi-host runs are rejected for
            steps_per_sync > 1, and single-process ``make_global_batch``
            is a pass-through, so the host batch IS the step input — the
            stack never round-trips device arrays."""
            import jax.numpy as jnp

            k = len(window)
            stacked = {key: np.stack([it[0][key] for it in window])
                       for key in window[0][0]}
            rngs = jnp.stack([it[2] for it in window])
            with timer.measure(steps=k):
                fnote(phase="step_dispatch")
                ledger.enter("step_compute")
                with tracer.span("train/step_dispatch", cat="train",
                                 window=k):
                    state, mstack = multi_fn(state, stacked, rngs)
                fnote(phase="device_sync")
                ledger.enter("device_sync")
                with tracer.span("train/device_sync", cat="train"):
                    mstack = jax.device_get(mstack)
            executed = [(window[i][0], window[i][2],
                         {key: v[i] for key, v in mstack.items()},
                         window[i][3])
                        for i in range(k)]
            window.clear()
            return state, executed

        def drain_window(state):
            """Run pending window items through the per-step path (epoch
            tail or a max_steps-capped short window — the scanned program
            is shape-specialized to full windows), capped to the
            remaining step budget."""
            items = list(window)
            window.clear()
            if cfg.train.max_steps:
                items = items[:max(0, cfg.train.max_steps - global_step)]
            if not items:
                return state, []
            return exec_steps(state, items)

        def sidecar_meta():
            """Full-state sidecar saved next to the arrays: the data
            cursor + rng schedule that make a resumed run replay the
            exact batch/rng sequence (prefetched-but-unexecuted batches
            are dropped on every exit path, so the cursor IS the step)."""
            committed = cursor["committed"]
            return {
                "format": 1,
                "step": global_step,
                # Data cursor: equals the step until the sentinel skips
                # quarantined windows, after which it leads the step.
                "data_pos": committed,
                "epoch": (committed // spe) if spe else 0,
                "step_in_epoch": (committed % spe) if spe else 0,
                "samples_seen": samples_seen,
                "seed": cfg.train.seed,
                "rng_schedule": "fold_in_v1",
                # Persistent data quarantine (dlti_tpu.training.sentinel):
                # strike-counted windows; quarantined ones are skipped on
                # this run and every resume.
                "skip_list": skiplist.to_meta() if skiplist is not None
                else [],
                "dataset": {
                    "kind": type(dataset).__name__ if dataset is not None
                    else None,
                    "steps_per_epoch": spe,
                    "shuffle_seed": getattr(dataset, "shuffle_seed", None),
                    "packed": bool(getattr(dataset, "pack",
                                           getattr(dataset, "packed",
                                                   False))),
                },
                "prefetch_depth": prefetch_depth,
                "fp16": bool(cfg.train.fp16),
            }

        def bookkeep(state, executed):
            """Per-step records for a batch of executed steps, then
            window-boundary eval/save (cadence-crossing aware, so
            eval_steps/save_steps need not divide steps_per_sync)."""
            nonlocal global_step, samples_seen
            step_before = global_step
            window_anomalous = False
            # Memory ledger: follow the state rebind, then one snapshot
            # per bookkeep (not per step — live_arrays walks aren't free)
            # feeding the window's steplog records and the /metrics
            # gauges.
            mem_state["state"] = state
            mem_scalars = memledger.scalars() if memledger.enabled else {}
            # Goodput bookkeeping: host-side accounting books to "other";
            # the deltas accrued since the previous bookkeep feed the
            # steplog's per-phase fields and the /metrics counter (a
            # checkpoint issued below lands in the NEXT bookkeep's
            # deltas). Replay ends once the run passes its pre-rollback
            # high-water step — from here on, progress is fresh.
            ledger.enter("other")
            deltas = ledger.take_deltas()
            n_exec = max(1, len(executed))
            if (ledger.replay_until is not None
                    and global_step + len(executed)
                    >= ledger.replay_until):
                ledger.end_replay()
            for k, v in deltas.items():
                goodput_seconds_total.labels(bucket=k).inc(v)
            for hb, r, m, pos in executed:
                global_step += 1
                samples_seen += (cfg.train.micro_batch_size
                                 * cfg.train.grad_accum_steps)
                losses.append(float(m["loss"]))
                cursor["committed"] = pos + 1
                step_pos[global_step] = pos
                verdict = None
                if sentinel is not None:
                    # Anomaly verdict over the metrics this already-paid
                    # host sync delivered: nonfinite, loss/grad spikes vs
                    # the rolling window, streak accounting.
                    verdict = sentinel.observe(
                        global_step, float(m["loss"]),
                        float(m["grad_norm"]),
                        bool(float(m.get("skipped_update", 0.0))))
                    if verdict["kind"]:
                        window_anomalous = True
                        self.logger.warning(
                            "sentinel: %s anomaly at step %d (loss %.4g, "
                            "grad_norm %.4g, data window %d, streak %d)",
                            verdict["kind"], global_step, float(m["loss"]),
                            float(m["grad_norm"]), pos,
                            len(verdict["streak"]))
                        fnote(sentinel_last_anomaly={
                            "step": global_step, "kind": verdict["kind"],
                            "data_pos": pos})
                    if (verdict["rollback_due"] and rollback_allowed
                            and self._rollback_due is None):
                        self._rollback_due = {
                            "streak": verdict["streak"],
                            "positions": [step_pos[s]
                                          for s, _ in verdict["streak"]
                                          if s in step_pos]}
                if recorder is not None:
                    # Record the pre-assembly host-local batch: the
                    # global array's shards span other hosts' devices
                    # and cannot be fetched here.
                    recorder.record(global_step, hb, r, m)
                if steplog is not None:
                    # Per-step JSONL telemetry (rank-0): the MegaScale-
                    # style in-framework stream. Window-executed steps
                    # share the window's per-step time.
                    dt = timer.last_step_seconds
                    tok_s_chip = (tokens_per_step / dt
                                  / max(jax.device_count(), 1)
                                  if dt > 0 else 0.0)
                    peak_gb, peak_src = device_peak_memory()
                    steplog.log_step(
                        global_step,
                        loss=losses[-1],
                        grad_norm=float(m["grad_norm"]),
                        lr=schedule_lr(cfg.optimizer, global_step),
                        tokens_per_second_per_chip=round(tok_s_chip, 2),
                        mfu_percent=round(compute_mfu(
                            tok_s_chip, n_for_flops, peak_flops,
                            trainable_params=trainable), 4),
                        peak_memory_gb=round(peak_gb, 4),
                        peak_memory_source=peak_src,
                        step_time_s=round(dt, 6),
                        anomaly=(verdict or {}).get("kind", ""),
                        skipped_update=int(bool(float(
                            m.get("skipped_update", 0.0)))),
                        rollbacks_total=(sentinel.rollbacks
                                         if sentinel is not None else 0),
                        # Goodput-ledger per-phase fields (steplog
                        # schema): the window's accrual split evenly
                        # across its records; 0.0 when the ledger is off.
                        data_wait_s=round(
                            deltas.get("data_wait", 0.0) / n_exec, 6),
                        sync_s=round(
                            deltas.get("device_sync", 0.0) / n_exec, 6),
                        ckpt_s=round(
                            (deltas.get("checkpoint_save", 0.0)
                             + deltas.get("checkpoint_restore", 0.0))
                            / n_exec, 6),
                        rollback_s=round(
                            (deltas.get("rollback", 0.0)
                             + deltas.get("replay", 0.0)) / n_exec, 6),
                        # Memory-ledger per-step fields (steplog schema):
                        # headroom is -1 when capacity is unknown (CPU
                        # without a budget); both 0 when the ledger is
                        # off.
                        hbm_bytes_in_use=int(
                            mem_scalars.get("hbm_bytes_in_use", 0)),
                        hbm_headroom_bytes=int(
                            mem_scalars.get(
                                "hbm_headroom_bytes",
                                -1 if memledger.enabled else 0)),
                    )
                if global_step % cfg.train.logging_steps == 0 and is_main_process():
                    self.logger.info(
                        "step %d | loss %.4f | grad_norm %.3f | %.2f steps/s | %.0f tok/s/chip",
                        global_step, losses[-1], float(m["grad_norm"]),
                        timer.steps_per_second,
                        timer.steps_per_second * tokens_per_step
                        / max(jax.device_count(), 1),
                    )
            # Self-monitoring bookkeeping: refresh the sampled scalars,
            # feed the hung-step heartbeat, and stamp the flight context
            # with the last completed step (what a postmortem names).
            dt = timer.last_step_seconds
            self._live.update(
                train_step=global_step,
                train_step_time_s=dt,
                train_tokens_per_s=(tokens_per_step / dt if dt > 0 else 0.0),
                samples_seen=samples_seen)
            if ledger.enabled:
                # Goodput fraction + MFU as /metrics gauges (module-level
                # like the ckpt-store counters) and a /debug/vars series.
                goodput_fraction_gauge.set(ledger.goodput_fraction())
                if peak_flops and dt > 0:
                    mfu_now = compute_mfu(
                        tokens_per_step / dt / max(jax.device_count(), 1),
                        n_for_flops, peak_flops,
                        trainable_params=trainable)
                    self._live["train_mfu_percent"] = round(mfu_now, 4)
                    goodput_mfu_gauge.set(round(mfu_now, 4))
            if losses:
                self._live["train_loss"] = losses[-1]
            if watchdog is not None:
                watchdog.notify_step(global_step)
            if einfo is not None:
                # Per-step liveness file for the elastic supervisor
                # (independent per process — unlike the collective
                # Heartbeat below, it keeps reporting when a peer dies).
                _elastic.beat(global_step)
                if ledger.enabled:
                    # Refresh this generation's ledger file (throttled):
                    # a SIGKILLed worker never reaches its exit-path
                    # save, and the supervisor's stitched ledger must
                    # still book the generation's rollback/replay time.
                    _elastic.save_generation_ledger(ledger.to_dict(),
                                                    step=global_step)
            fnote(step=global_step, last_completed_step=global_step,
                  phase="between_steps")
            if len(step_pos) > 4096:
                for s in sorted(step_pos)[:-2048]:
                    del step_pos[s]
            # Cross-rank SDC probe — BEFORE the collective heartbeat/
            # eval/save below: on a mismatch the suspect rank exits and
            # the survivors must stop without entering another
            # collective (which would wedge on the dead peer).
            if sdc_probe is not None and sdc_probe.due(step_before,
                                                       global_step):
                fnote(phase="sdc_probe")
                ledger.enter("sdc_probe")
                with tracer.span("train/sdc_probe", cat="train",
                                 step=global_step):
                    res = sdc_probe.check(state.params, global_step)
                ledger.enter("other")
                if res["mismatch"]:
                    suspect_self = res["rank"] in res["suspects"]
                    alert = {
                        "wall": time.time(), "rule": "sdc_mismatch",
                        "message": (
                            f"cross-rank param digest mismatch at step "
                            f"{global_step}: suspect rank(s) "
                            f"{res['suspects']} (digests {res['digests']})"),
                        "step": global_step, "suspects": res["suspects"],
                        "rank": res["rank"]}
                    self.logger.error("sentinel: %s", alert["message"])
                    from dlti_tpu.training.elastic import mirror_alert

                    try:
                        mirror_alert(alert)
                    except Exception:
                        pass
                    if flight is not None:
                        flight.dump(reason="sdc_mismatch", force=True,
                                    extra={"alert": alert,
                                           "suspect_self": suspect_self})
                    if suspect_self:
                        # This host's replicated params diverged from the
                        # fleet: its memory/compute is untrustworthy. The
                        # black box is written; exit with the distinctive
                        # code so the elastic supervisor books THIS slot
                        # failed, reshapes the survivors, and rejoins the
                        # slot later with checkpoint-fresh params.
                        self.logger.error(
                            "sentinel: this rank (%d) is the SDC suspect; "
                            "exiting %d for supervisor eviction",
                            res["rank"], sentinel_mod.SDC_EXIT_CODE)
                        os._exit(sentinel_mod.SDC_EXIT_CODE)
                    # Healthy ranks: stop cleanly with NO further
                    # collectives (no final save — its consolidation
                    # would hang on the evicted peer); the relaunched
                    # generation resumes from the last verified step.
                    self._sdc_evict = True
                    self._stop_requested = True
                    return
            if heartbeat is not None and (
                    global_step // tcfg.heartbeat_interval_steps
                    > step_before // tcfg.heartbeat_interval_steps):
                # COLLECTIVE on multi-host meshes: every process reaches
                # this boundary at the same global_step (the loop is
                # step-synchronous), so the allgather lines up.
                heartbeat.beat(global_step)
                if is_main_process():
                    report = heartbeat.straggler_report()
                    if report:
                        self.logger.warning("heartbeat: %s", report)
            if (eval_fn is not None and cfg.train.eval_steps
                    and (global_step // cfg.train.eval_steps
                         > step_before // cfg.train.eval_steps)):
                self._run_eval(eval_fn, state, eval_dataset, global_step)
            if window_anomalous:
                # Never checkpoint a state produced by an anomalous step:
                # a spike's update is exactly what rollback exists to
                # discard, and saving it would make it the resume target.
                ck = self.cfg.checkpoint
                if (ck.save_strategy == "steps"
                        and global_step // ck.save_steps
                        > step_before // ck.save_steps):
                    self.logger.warning(
                        "sentinel: save suppressed at step %d (anomalous "
                        "window)", global_step)
            else:
                self._maybe_save(state, global_step, epoch_end=False,
                                 crossed_from=step_before,
                                 meta=sidecar_meta())
            if self._fault is not None:
                # Step-boundary chaos: fires after the step booked (and
                # its save, if due, was issued) — the crash point real
                # preemptions hit.
                self._fault.maybe_fire_step(global_step)

        def do_rollback(state, epoch):
            """Automatic numeric-fault recovery: restore the last
            digest-verified checkpoint, strike the data windows that fed
            the anomalous streak (quarantining repeat offenders), rewind
            the step counter and data cursor, and let the epoch loop
            re-enter at the restored position. The lr/rng schedule is a
            pure function of the step index, so the replayed steps are
            bit-identical to a run that never went anomalous."""
            nonlocal global_step
            info = self._rollback_due
            self._rollback_due = None
            if sentinel.over_budget():
                raise sentinel_mod.SentinelGiveUp(
                    f"sentinel rollback budget exhausted "
                    f"({sentinel.rollbacks} rollbacks, anomalies persist); "
                    f"a human must look at the data/hardware")
            from dlti_tpu.checkpoint import (
                restore_latest_verified, wait_for_saves)

            ckdir = cfg.checkpoint.output_dir
            pre_rollback_step = global_step
            ledger.enter("rollback")
            wait_for_saves(ckdir)
            fnote(phase="sentinel_rollback")
            with tracer.span("train/sentinel_rollback", cat="train",
                             step=global_step):
                restored = restore_latest_verified(ckdir, state)
            sentinel.note_rollback()
            if restored is None:
                self.logger.error(
                    "sentinel: rollback wanted after %d consecutive "
                    "anomalies but no verified checkpoint exists; "
                    "continuing in place (streak reset)",
                    len(info["streak"]))
                return state, epoch
            new_state, step, meta = restored
            mem_state["state"] = new_state
            ck_cursor = int((meta or {}).get("data_pos", step))
            # Strike ONLY the windows that fed anomalous steps — the
            # innocent windows since the checkpoint replay untouched.
            positions = sorted({p for p in info["positions"]
                                if p >= ck_cursor})
            newly_q = skiplist.strike(positions, step=global_step)
            if cfg.checkpoint.save_strategy != "no":
                skiplist.save(ckdir)
            if flight is not None:
                flight.dump(reason="sentinel_rollback", force=True, extra={
                    "streak": info["streak"], "restored_step": int(step),
                    "struck_windows": positions, "quarantined": newly_q,
                    "rollbacks": sentinel.rollbacks})
            self.logger.warning(
                "sentinel: ROLLBACK #%d after %d consecutive anomalies "
                "(last: %s) — restored verified step %d, struck data "
                "window(s) %s%s", sentinel.rollbacks, len(info["streak"]),
                info["streak"][-1][1], step, positions,
                f"; QUARANTINED {newly_q}" if newly_q else
                " (replaying once)")
            global_step = int(step)
            # Until the run passes its pre-rollback high-water step, the
            # re-executed steps are replay — recovery cost, not fresh
            # progress (the ledger reclasses their step buckets).
            ledger.begin_replay(pre_rollback_step)
            cursor["committed"] = ck_cursor
            cursor["fetch"] = ck_cursor
            step_pos.clear()
            self._live["train_step"] = global_step
            # A re-reached save boundary must re-save (no committed dir
            # newer than the restore target can exist — it would have
            # been the restore target).
            self._last_save_step = None
            if einfo is not None:
                # The rollback booking must reach the supervisor's
                # stitched ledger even if this worker is killed mid-replay.
                _elastic.save_generation_ledger(ledger.to_dict(),
                                                step=global_step, force=True)
            if dataset is not None and spe:
                new_epoch = min(ck_cursor // spe, cfg.train.num_epochs)
                resume_point["epoch"] = new_epoch
                resume_point["skip"] = ck_cursor % spe
                return new_state, new_epoch
            return new_state, epoch

        _EPOCH_END = object()  # sentinel: a batch is never this object
        try:
            epoch = start_epoch
            while epoch < cfg.train.num_epochs:
                batch_iter = make_batch_iter(epoch)
                if dataset is not None and spe:
                    cursor["fetch"] = epoch * spe + (
                        resume_point["skip"]
                        if epoch == resume_point["epoch"] else 0)
                while True:
                    # Manual iteration so the data-pipeline wait is its
                    # own trace span (the phase MegaScale singles out:
                    # input stalls masquerade as slow steps otherwise).
                    # Under prefetch this span measures the *stall* only —
                    # the gather itself runs in the worker's
                    # train/prefetch spans.
                    fnote(phase="batch_fetch")
                    ledger.enter("data_wait")
                    with tracer.span("train/batch_fetch", cat="train"):
                        batch = next(batch_iter, _EPOCH_END)
                    if batch is _EPOCH_END:
                        break
                    # Data position of THIS batch in the global schedule
                    # (epoch * steps_per_epoch + index): the key the
                    # sentinel's quarantine list is kept in — optimizer
                    # steps renumber once windows are skipped, positions
                    # never do.
                    pos = cursor["fetch"]
                    cursor["fetch"] += 1
                    if skiplist is not None and pos in skiplist.quarantined():
                        skipped_windows += 1
                        self._live["sentinel_windows_skipped"] = \
                            skipped_windows
                        if is_main_process():
                            self.logger.warning(
                                "sentinel: skipping quarantined data "
                                "window %d", pos)
                        continue
                    # A pending window always has len < take <= remaining
                    # step budget (it drains the moment it reaches take),
                    # so this check never skips queued-but-unrun steps.
                    if cfg.train.max_steps and global_step >= cfg.train.max_steps:
                        break
                    if cfg.train.profile_dir and is_main_process():
                        if (profile_state == "pending"
                                and global_step >= cfg.train.profile_start_step):
                            jax.profiler.start_trace(cfg.train.profile_dir)
                            profile_state = "active"
                            profile_stop_at = (global_step
                                               + cfg.train.profile_num_steps)
                        elif (profile_state == "active"
                              and global_step >= profile_stop_at):
                            jax.profiler.stop_trace()
                            profile_state = "done"
                            self.logger.info("profiler trace -> %s",
                                             cfg.train.profile_dir)
                    if self._prefetcher is not None:
                        # (host numpy batch, worker-placed batch); placed
                        # is the host batch itself when placement stayed
                        # on the step thread (windows, multi-host, pipe).
                        host_batch, batch = batch
                    else:
                        host_batch = batch
                    if self._fault is not None:
                        # Numeric chaos (nan-grad / poison-batch): corrupt
                        # the HOST batch before placement so the fault
                        # flows through the genuine compiled step.
                        corrupted = self._fault.maybe_corrupt_batch(
                            pos, global_step + len(window) + 1, host_batch)
                        if corrupted is not None:
                            host_batch = corrupted
                            batch = corrupted
                    if self.mesh is not None:
                        from dlti_tpu.parallel.sharding import make_global_batch

                        ledger.enter("host_to_device")
                        with tracer.span("train/host_to_device",
                                         cat="train"):
                            # Single-process: pass-through (worker-placed
                            # batches arrive here already device-resident).
                            batch = make_global_batch(batch, cfg, self.mesh)
                    # This batch executes as optimizer step global_step +
                    # len(window) + 1 (window always empty on the plain
                    # path); folding by that index keeps the schedule
                    # stateless — resumable and drop-safe.
                    step_rng = jax.random.fold_in(
                        rng_base, global_step + len(window) + 1)
                    if multi_fn is None:
                        state, executed = exec_steps(
                            state, [(host_batch, batch, step_rng, pos)])
                    else:
                        if window and not _batch_compatible(
                                window[0][0], host_batch):
                            # Custom batches_per_epoch iterables may change
                            # shape mid-stream (e.g. a ragged drop_last
                            # tail): drain the pending window per-step and
                            # start a new one — matching what the per-step
                            # jit would do (recompile), instead of a stack
                            # error.
                            state, executed = drain_window(state)
                            if executed:
                                bookkeep(state, executed)
                        window.append((host_batch, batch, step_rng, pos))
                        take = sync_k
                        if cfg.train.max_steps:
                            take = min(take,
                                       cfg.train.max_steps - global_step)
                        if len(window) < take:
                            if self._stop_requested:
                                # Preemption while the window fills: drop
                                # the queued batches (never counted, so
                                # resume replays them) and checkpoint now
                                # instead of up to K-1 batches later.
                                break
                            continue
                        if len(window) == sync_k:
                            state, executed = exec_window(state)
                        else:  # max_steps-capped short window
                            state, executed = drain_window(state)
                    bookkeep(state, executed)
                    if self._fault is not None:
                        # param-flip chaos: corrupt a replicated leaf in
                        # the LIVE state at the step boundary (rank-gated)
                        # — the SDC probe's drill input.
                        flipped = self._fault.maybe_corrupt_state(
                            global_step, state)
                        if flipped is not None:
                            state = flipped
                    if self._rollback_due is not None:
                        break
                    if self._stop_requested:
                        break
                # Epoch over (or preempted / max_steps / rollback): stop
                # the worker and drop its buffered batches — they were
                # never counted, so resume/rollback replays them.
                close_prefetcher()
                if (window and not self._stop_requested
                        and self._rollback_due is None):
                    # Epoch tail shorter than the window. On preemption the
                    # pending window is dropped instead — those steps never
                    # counted, so resume replays them.
                    state, executed = drain_window(state)
                    if executed:
                        bookkeep(state, executed)
                if self._rollback_due is not None:
                    window.clear()
                    state, epoch = do_rollback(state, epoch)
                    continue  # re-enter at the restored data position
                self._maybe_save(state, global_step, epoch_end=True,
                                 meta=sidecar_meta())
                if cfg.train.max_steps and global_step >= cfg.train.max_steps:
                    break
                if self._stop_requested:
                    break
                epoch += 1
            if (self._stop_requested and not self._sdc_evict
                    and cfg.checkpoint.save_strategy != "no"):
                from dlti_tpu.checkpoint import (
                    save_train_state, wait_for_saves)

                # _maybe_save may have just written this very step (e.g. the
                # stop landed on a save_steps boundary or at epoch end);
                # settle any in-flight async save first. The already-saved
                # check is the trainer's own marker, NOT latest_step(): a
                # filesystem probe races the (rank-0-only) async writer on
                # multi-process meshes, and a rank-dependent answer would
                # send only some ranks into the collective consolidation
                # below — a deadlock, not a redundant write.
                wait_for_saves(cfg.checkpoint.output_dir)
                if getattr(self, "_last_save_step", None) != global_step:
                    save_train_state(
                        cfg.checkpoint.output_dir, global_step, state,
                        keep=cfg.checkpoint.save_total_limit,
                        async_save=False, train_meta=sidecar_meta(),
                        retries=cfg.checkpoint.save_retries,
                        retry_backoff_s=cfg.checkpoint.save_retry_backoff_s)
                    self.logger.info(
                        "preemption checkpoint written at step %d", global_step)
        finally:
            ledger.enter("shutdown")
            close_prefetcher()  # a mid-epoch exception must not leak the worker
            if flight is not None:
                # The black box goes down with the ship: a fatal
                # exception (or a preemption stop) dumps before any
                # cleanup rewrites state. dump() never raises and
                # throttles duplicates (the chaos pre-fire hook may have
                # dumped milliseconds ago), so the original exception is
                # never masked.
                exc = sys.exc_info()[1]
                if exc is not None:
                    # An OOM death is filed as such: the dump's
                    # memory.json (add_memory_source above) is what
                    # postmortem.py renders as "where the memory went".
                    flight.dump(reason="oom" if is_oom_error(exc)
                                else "fatal_exception", exc=exc)
                elif self._stop_requested and not self._sdc_evict:
                    # (an SDC eviction already dumped its own black box)
                    flight.dump(reason="preemption_stop")
            if watchdog is not None:
                watchdog.stop()
            if sampler is not None:
                sampler.stop()
            if flight is not None:
                if get_recorder() is flight:
                    install_recorder(None)
                self._fnote = lambda **kw: None
            if memledger_mod.get_ledger() is memledger:
                memledger_mod.install(None)
            if sigterm_installed:
                # signal.signal reports a non-Python-installed previous
                # handler as None; SIG_DFL is the closest restorable state.
                _signal.signal(_signal.SIGTERM,
                               prev_handler if prev_handler is not None
                               else _signal.SIG_DFL)
            if profile_state == "active":  # run ended inside the trace window
                jax.profiler.stop_trace()
            if cfg.checkpoint.save_strategy != "no":
                # Settle in-flight async saves on EVERY exit path —
                # exception and normal return alike — so a training crash
                # cannot strand a half-written "latest" checkpoint (write
                # failures are logged by the store, never raised here,
                # which keeps an original exception unmasked).
                from dlti_tpu.checkpoint import wait_for_saves

                try:
                    wait_for_saves(cfg.checkpoint.output_dir)
                except Exception:
                    self.logger.exception(
                        "settling in-flight checkpoint saves failed")
            if ledger.enabled:
                # Settle the goodput accounting on EVERY exit path: flush
                # the residual deltas into the /metrics counter, set the
                # final fraction, and (under an elastic supervisor) save
                # this generation's ledger for cross-restart stitching.
                for k, v in ledger.take_deltas().items():
                    goodput_seconds_total.labels(bucket=k).inc(v)
                goodput_fraction_gauge.set(ledger.goodput_fraction())
                if einfo is not None:
                    _elastic.save_generation_ledger(
                        ledger.to_dict(), step=global_step, force=True)

        wall = time.time() - t_start
        record = self._final_metrics(
            losses, wall, samples_seen, tokens_per_step, global_step - start_step,
            trainable, total, timer,
        )
        if steplog is not None:
            # The final record is the full MetricsRecord dict, which keeps
            # the JSONL stream a superset of the reference CSV schema.
            steplog.log_final(record)
            steplog.close()
        if tcfg.trace_dir and is_main_process():
            trace_path = tracer.export(os.path.join(
                tcfg.trace_dir,
                f"trace_train_steps_{start_step}-{global_step}.json"))
            self.logger.info(
                "telemetry trace -> %s (open in https://ui.perfetto.dev)",
                trace_path)
        if ledger.enabled and is_main_process():
            totals = ledger.totals()
            top = sorted(totals.items(), key=lambda kv: -kv[1])[:6]
            self.logger.info(
                "goodput: %.1f%% productive over %.1fs booked — %s",
                100 * ledger.goodput_fraction(totals), sum(totals.values()),
                ", ".join(f"{k} {v:.1f}s" for k, v in top))
        if is_main_process():
            print_metrics_summary(record)
            save_training_metrics(record, csv_path=cfg.train.metrics_csv)
        return state, record

    # ------------------------------------------------------------------
    def request_stop(self) -> None:
        """Ask the training loop to checkpoint and exit at the next step
        boundary (what the SIGTERM handler calls on preemption)."""
        self._stop_requested = True

    def _run_eval(self, eval_fn, state, eval_dataset, step: int) -> float:
        dev_sh = getattr(eval_fn, "params_dev_shardings", None)
        if dev_sh is not None:
            # PP x offload_params: one host->HBM transfer of the frozen
            # tree covers the WHOLE eval pass; the device copy goes out
            # of scope (and frees) when this returns.
            state = state.replace(
                params=jax.device_put(state.params, dev_sh))
        losses, toks = [], 0.0
        self._fnote(phase="eval")
        self._ledger.enter("eval")
        with self._tracer.span("train/eval", cat="train", step=step):
            for batch in eval_dataset.epoch(0):
                flat = {
                    k: v.reshape((-1,) + v.shape[2:]) for k, v in batch.items()
                }  # eval ignores the accum dim
                m = jax.device_get(eval_fn(state, flat))
                losses.append(float(m["loss"]) * float(m["num_tokens"]))
                toks += float(m["num_tokens"])
        self._ledger.enter("other")
        eval_loss = sum(losses) / toks if toks else float("nan")
        if toks and is_main_process():
            self.logger.info("eval @ step %d | loss %.4f", step, eval_loss)
        self._last_eval_loss = eval_loss
        return eval_loss

    def _maybe_save(self, state: TrainState, step: int, epoch_end: bool,
                    crossed_from: Optional[int] = None,
                    meta: Optional[dict] = None) -> None:
        cfg = self.cfg.checkpoint
        if cfg.save_strategy == "no":
            return
        if crossed_from is None:
            steps_due = step % cfg.save_steps == 0
        else:
            # A steps_per_sync window advanced (crossed_from, step]; save
            # when it crossed a save_steps boundary, at the window-end
            # state (mid-window states are never materialized on host).
            steps_due = (step // cfg.save_steps
                         > crossed_from // cfg.save_steps)
        due = (
            (cfg.save_strategy == "steps" and steps_due and step > 0)
            or (cfg.save_strategy == "epoch" and epoch_end)
        )
        if not due:
            return
        if getattr(self, "_last_save_step", None) == step:
            # Already saved this step (a save_steps boundary that is also
            # the epoch end books two due saves). The store dedups the
            # *write*, but on a multi-process mesh the state consolidation
            # is a collective launch — skip it symmetrically on every
            # rank, not just where the writer lives.
            return
        self._last_save_step = step
        from dlti_tpu.checkpoint import save_train_state

        self._fnote(phase="checkpoint_save")
        self._ledger.enter("checkpoint_save")
        with self._tracer.span("train/checkpoint_save", cat="train",
                               step=step):
            save_train_state(
                cfg.output_dir, step, state,
                keep=cfg.save_total_limit, async_save=cfg.async_save,
                train_meta=meta, retries=cfg.save_retries,
                retry_backoff_s=cfg.save_retry_backoff_s,
            )
        self._ledger.enter("other")
        if self._fault is not None:
            # Mid-save chaos: with async_save the write is in flight right
            # now — a save-kill here is the honest torn-checkpoint case.
            self._fault.maybe_fire_save(step)

    def _strategy(self) -> str:
        """Strategy label for the reference CSV / telemetry stream."""
        par = self.cfg.parallel
        if par.pipe > 1:
            return f"pipe{par.pipe}"
        if int(par.zero_stage) == 0:
            return "baseline"
        return f"zero{int(par.zero_stage)}"

    def _final_metrics(
        self, losses, wall, samples_seen, tokens_per_step, steps, trainable, total, timer,
    ) -> MetricsRecord:
        cfg = self.cfg
        final_loss = losses[-1] if losses else float("nan")
        sps = samples_seen / wall if wall > 0 else 0.0
        tok_s_chip = (
            timer.steps_per_second * tokens_per_step / max(jax.device_count(), 1)
        )
        peak_flops = detect_chip_peak_flops()
        # MoE: FLOPs/token follow the k *routed* experts, not all E.
        n_for_flops = (cfg.model.num_active_params()
                       if cfg.model.num_experts > 0 else total)
        mfu = compute_mfu(tok_s_chip, n_for_flops, peak_flops,
                          trainable_params=trainable)
        peak_gb, peak_src = device_peak_memory()
        return MetricsRecord(
            experiment=experiment_name_from_config(cfg),
            num_gpus=cfg.parallel.num_devices,
            zero_stage=int(cfg.parallel.zero_stage),
            strategy=self._strategy(),
            training_time_hours=wall / 3600.0,
            samples_per_second=sps,
            peak_memory_gb=peak_gb,
            final_loss=final_loss,
            tokens_per_second_per_chip=tok_s_chip,
            mfu_percent=mfu,
            peak_memory_source=peak_src,
            eval_loss=getattr(self, "_last_eval_loss", float("nan")),
        )
