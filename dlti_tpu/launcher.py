"""Multi-process / multi-host launcher — the torchrun / `deepspeed` analog.

The reference never launches processes itself; it leans on ``torchrun``
(``train_deepspeed_zero1.py:10-12``: sets LOCAL_RANK/WORLD_SIZE) and the
``deepspeed`` CLI (``train.ipynb:640-653``: spawns N ranks with
``--master_addr=127.0.0.1 --master_port=29500``), with SLURM claimed but
absent (``README.md:18``). This module is the in-tree replacement:

* :func:`launch_local` — spawn N local worker processes, each with the
  ``DLTI_*`` rendezvous env (coordinator address, world size, process id);
  on the first failure the rest are terminated and the worst return code is
  returned (the semantics of torchrun's sigkill_handler, visible in the
  reference's recorded crash, ``train.ipynb:826-838``).
* :func:`slurm_env` — derive the same rendezvous env from ``SLURM_*``
  variables so one ``srun`` task per host self-configures.
* :func:`maybe_initialize_from_env` — called by entry points
  (``scripts/train.py``); a no-op unless the launcher env is present, in
  which case it runs :func:`jax.distributed.initialize` before backend use.

Rendezvous env contract (the LOCAL_RANK/WORLD_SIZE/MASTER_ADDR analog):

==========================  =================================================
``DLTI_COORDINATOR``        ``host:port`` of process 0
``DLTI_NUM_PROCESSES``      world size
``DLTI_PROCESS_ID``         this process's id (0-based)
==========================  =================================================

Elastic supervision (``--elastic``) hands off to
:class:`dlti_tpu.training.elastic.ElasticLauncher`, which extends the
contract with ``DLTI_GENERATION`` (the rendezvous generation),
``DLTI_ELASTIC_DIR`` (heartbeat/event dir), and
``DLTI_ELASTIC_NUM_SLOTS`` (the full-size world the batch schedule is
defined against) — see that module for the recovery loop.
"""

from __future__ import annotations

import os
import re
import signal
import subprocess
import sys
from typing import Dict, List, Optional, Sequence

ENV_COORDINATOR = "DLTI_COORDINATOR"
ENV_NUM_PROCESSES = "DLTI_NUM_PROCESSES"
ENV_PROCESS_ID = "DLTI_PROCESS_ID"

DEFAULT_PORT = 29400


def worker_env(coordinator: str, num_processes: int, process_id: int,
               base: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    env = dict(os.environ if base is None else base)
    env[ENV_COORDINATOR] = coordinator
    env[ENV_NUM_PROCESSES] = str(num_processes)
    env[ENV_PROCESS_ID] = str(process_id)
    return env


def launch_local(command: Sequence[str], num_processes: int,
                 port: int = DEFAULT_PORT,
                 log_dir: Optional[str] = None) -> int:
    """Spawn ``num_processes`` copies of ``command`` on this host.

    Process i gets ``DLTI_PROCESS_ID=i``; all share a localhost coordinator.
    Output is interleaved to our stdout/stderr unless ``log_dir`` is given
    (then ``rank{i}.out``/``.err`` per process — the ``logs/*.out``/``.err``
    layout the reference's ``.gitignore:36-37`` implies).

    Returns the worst return code; terminates stragglers once any worker
    fails so a crashed rank can't hang the job.
    """
    coordinator = f"127.0.0.1:{port}"
    procs: List[subprocess.Popen] = []
    files = []
    try:
        for i in range(num_processes):
            env = worker_env(coordinator, num_processes, i)
            stdout = stderr = None
            if log_dir:
                os.makedirs(log_dir, exist_ok=True)
                stdout = open(os.path.join(log_dir, f"rank{i}.out"), "wb")
                stderr = open(os.path.join(log_dir, f"rank{i}.err"), "wb")
                files += [stdout, stderr]
            procs.append(subprocess.Popen(list(command), env=env,
                                          stdout=stdout, stderr=stderr))
        rcs = [None] * num_processes
        first_bad_rc = None
        while any(rc is None for rc in rcs) and first_bad_rc is None:
            for i, p in enumerate(procs):
                if rcs[i] is None:
                    try:
                        rcs[i] = p.wait(timeout=0.25)
                    except subprocess.TimeoutExpired:
                        continue
                    if rcs[i] != 0 and first_bad_rc is None:
                        first_bad_rc = rcs[i]
        if first_bad_rc is not None:
            for i, p in enumerate(procs):
                if rcs[i] is None:
                    p.send_signal(signal.SIGTERM)
            for i, p in enumerate(procs):
                if rcs[i] is None:
                    try:
                        rcs[i] = p.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        p.kill()
                        rcs[i] = p.wait()
            # The rc that *triggered* teardown, not the -15s from our own
            # SIGTERMs — and never max(), which masks signal codes (-11)
            # behind a clean 0 from an already-finished rank.
            return first_bad_rc
        return next((rc for rc in rcs if rc), 0)
    finally:
        for p in procs:
            if p.poll() is None:  # spawn-loop exception / interrupt: no orphans
                p.kill()
        for f in files:
            f.close()


def first_slurm_node(nodelist: str) -> str:
    """First hostname of a SLURM nodelist, without needing ``scontrol``.

    Handles plain lists (``a,b``), compressed ranges
    (``tpu-host[003-006,009]`` -> ``tpu-host003``), and mixes of both
    (``alpha,tpu[01-04]`` -> ``alpha``): the first entry ends at the first
    top-level comma (commas inside ``[...]`` don't split entries).
    """
    depth = 0
    head = nodelist
    for i, ch in enumerate(nodelist):
        if ch == "[":
            depth += 1
        elif ch == "]":
            depth -= 1
        elif ch == "," and depth == 0:
            head = nodelist[:i]
            break
    m = re.match(r"^([^\[]+)\[([^\]\-,]+)", head)
    if m:
        return m.group(1) + m.group(2)
    return head


def slurm_env(environ: Optional[Dict[str, str]] = None,
              port: int = DEFAULT_PORT) -> Dict[str, str]:
    """Map ``SLURM_*`` vars to the ``DLTI_*`` rendezvous contract.

    Raises KeyError outside a SLURM allocation.
    """
    e = os.environ if environ is None else environ
    nodelist = e.get("SLURM_JOB_NODELIST") or e["SLURM_NODELIST"]
    coordinator = f"{first_slurm_node(nodelist)}:{port}"
    num = int(e.get("SLURM_NTASKS") or e["SLURM_NNODES"])
    pid = int(e.get("SLURM_PROCID") or e["SLURM_NODEID"])
    return worker_env(coordinator, num, pid, base=dict(e))


def maybe_initialize_from_env() -> bool:
    """Initialize jax.distributed from the launcher env; no-op without it.

    Entry points call this exactly once, before any jax backend use. Returns
    True if multi-process init ran.

    The connect retries with capped exponential backoff
    (``DLTI_CONNECT_RETRIES`` / ``DLTI_CONNECT_BACKOFF_S``, defaults 3 /
    1.0s, cap 10s): workers race rank-0 to the rendezvous and a cold
    coordinator — rank 0 still importing jax, or an elastic relaunch
    whose previous generation's port is mid-teardown — must read as
    "not up yet", not as a fatal error.
    """
    num = int(os.environ.get(ENV_NUM_PROCESSES, "1"))
    if num <= 1:
        return False
    from dlti_tpu.parallel.mesh import initialize_multihost

    coordinator = os.environ[ENV_COORDINATOR]
    process_id = int(os.environ[ENV_PROCESS_ID])
    retries = int(os.environ.get("DLTI_CONNECT_RETRIES", "3"))
    backoff = float(os.environ.get("DLTI_CONNECT_BACKOFF_S", "1.0"))
    attempt = 0
    while True:
        try:
            initialize_multihost(
                coordinator_address=coordinator,
                num_processes=num,
                process_id=process_id,
            )
            return True
        except Exception:
            attempt += 1
            if attempt > retries:
                raise
            import logging
            import time

            # A failed connect can leave the client half-initialized;
            # shut it down so the retry starts clean.
            try:
                import jax

                jax.distributed.shutdown()
            except Exception:
                pass
            delay = min(backoff * (2 ** (attempt - 1)), 10.0)
            logging.getLogger("dlti").warning(
                "jax.distributed.initialize(%s) failed (attempt %d/%d); "
                "retrying in %.1fs", coordinator, attempt, retries + 1,
                delay)
            time.sleep(delay)


def main(argv: Optional[Sequence[str]] = None) -> int:
    """CLI: ``launch.py [--num-processes N | --coordinator-from-slurm] -- cmd...``"""
    import argparse

    p = argparse.ArgumentParser(
        description="Process launcher (torchrun/deepspeed-CLI analog)")
    p.add_argument("--num-processes", type=int, default=0,
                   help="spawn N local worker processes")
    p.add_argument("--coordinator-from-slurm", action="store_true",
                   help="derive rendezvous from SLURM_* env and exec the "
                        "command in-place (one srun task per host)")
    p.add_argument("--port", type=int, default=DEFAULT_PORT)
    p.add_argument("--log-dir", default=None)
    # Elastic supervision (dlti_tpu.training.elastic.ElasticLauncher):
    # instead of kill-all-on-first-failure, recover worker death with a
    # restart budget, exponential backoff, and generation-numbered
    # rendezvous — shrink the world to the survivors, resume from the
    # last verified checkpoint, and rejoin at the next checkpoint
    # boundary.
    p.add_argument("--elastic", action="store_true",
                   help="supervise workers elastically (restart budget + "
                        "backoff + reshape-on-failure + rejoin) instead "
                        "of kill-all-on-first-failure")
    p.add_argument("--restart-budget", type=int, default=3,
                   help="worker-failure recoveries before giving up")
    p.add_argument("--backoff", type=float, default=1.0,
                   help="initial restart backoff seconds (doubles per "
                        "restart, capped at --backoff-max)")
    p.add_argument("--backoff-max", type=float, default=30.0)
    p.add_argument("--heartbeat-stale-s", type=float, default=0.0,
                   help="supervisor-side staleness deadline for per-rank "
                        "heartbeat files (0 = exits only)")
    p.add_argument("--startup-grace", type=float, default=60.0,
                   help="seconds before a never-beaten worker can be "
                        "declared stale (covers cold jax compiles)")
    p.add_argument("--no-rejoin", action="store_true",
                   help="do not grow back to full size at the next "
                        "checkpoint boundary after a shrink")
    p.add_argument("--ckpt-dir", default=None,
                   help="checkpoint dir to watch for rejoin boundaries "
                        "(the trainer's --output-dir)")
    p.add_argument("--min-world", type=int, default=1,
                   help="smallest world the supervisor may shrink to")
    p.add_argument("--term-grace", type=float, default=10.0,
                   help="SIGTERM->SIGKILL grace seconds in teardown")
    p.add_argument("--elastic-dir", default=None,
                   help="rendezvous/heartbeat dir (default: under "
                        "--log-dir, else a temp dir)")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="-- command to run")
    args = p.parse_args(argv)
    cmd = args.command
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        p.error("no command given (use: launch.py ... -- python scripts/train.py ...)")
    if args.coordinator_from_slurm:
        env = slurm_env(port=args.port)
        os.execvpe(cmd[0], list(cmd), env)  # never returns
    if args.num_processes <= 0:
        p.error("--num-processes N or --coordinator-from-slurm required")
    if args.elastic:
        from dlti_tpu.training.elastic import ElasticLauncher

        return ElasticLauncher(
            cmd, args.num_processes, port=args.port, log_dir=args.log_dir,
            restart_budget=args.restart_budget, backoff_s=args.backoff,
            backoff_max_s=args.backoff_max,
            heartbeat_stale_s=args.heartbeat_stale_s,
            startup_grace_s=args.startup_grace,
            rejoin=not args.no_rejoin, ckpt_dir=args.ckpt_dir,
            min_world=args.min_world, term_grace_s=args.term_grace,
            elastic_dir=args.elastic_dir,
        ).run()
    return launch_local(cmd, args.num_processes, port=args.port,
                        log_dir=args.log_dir)


if __name__ == "__main__":
    sys.exit(main())
