"""Single typed config tree for the whole framework.

Replaces the reference's two stitched-together config systems — per-script
argparse with drifting defaults (``training/train_baseline.py:27-89``,
``train_deepspeed_zero2.py:37-120``) and DeepSpeed JSON files with ``"auto"``
placeholders (``configs/ds_config_zero1.json``) — with one dataclass tree plus
per-strategy presets (see :func:`preset`).

Defaults mirror the reference where the reference has them:

* LoRA r=16, alpha=2*r, dropout=0.05, on q/k/v/o, bias none
  (``training/train_baseline.py:131-140``)
* AdamW betas (0.9, 0.999), eps 1e-8, weight decay 0
  (``configs/ds_config_zero1.json:6-14``)
* WarmupLR 0 -> lr over warmup steps (``configs/ds_config_zero1.json:16-23``)
* grad clip 1.0 (``configs/ds_config_zero1.json:44``)
* max_seq_len 512 truncation (``training/train_baseline.py:155``)
* lr 2e-4, grad-accum 16, micro-batch 1 (``training/train_baseline.py:60-75``)
"""

from __future__ import annotations

import dataclasses
import enum
import json
from dataclasses import dataclass, field
from typing import Any, Optional


class ZeROStage(enum.IntEnum):
    """ZeRO stage, kept as a first-class concept for reference parity.

    On TPU these are sharding presets over the mesh, not an engine:

    * ``NONE``  — pure replicated data parallelism (reference baseline).
    * ``ZERO1`` — optimizer state sharded over the data axis
      (``configs/ds_config_zero1.json:35``).
    * ``ZERO2`` — + gradients reduce-scattered to shards
      (``configs/ds_config_zero2.json:27``).
    * ``ZERO3`` — + parameters sharded (FSDP) with optional host offload
      (``configs/ds_config_zero3.json:17-27``).
    """

    NONE = 0
    ZERO1 = 1
    ZERO2 = 2
    ZERO3 = 3


@dataclass(frozen=True)
class ModelConfig:
    """Llama-family architecture hyperparameters."""

    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32  # < num_heads => GQA
    head_dim: Optional[int] = None  # default hidden_size // num_heads
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-5
    tie_embeddings: bool = False
    # Family knobs beyond Llama-2 (the reference is HF AutoModel-generic,
    # ``training/train_baseline.py:122``, so sibling families must load):
    attention_bias: bool = False        # Qwen2: bias on q/k/v (never o)
    sliding_window: Optional[int] = None  # Mistral: local attention window
    mlp_activation: str = "silu"        # "silu" | "gelu_tanh" | "gelu_exact"
    rmsnorm_offset: bool = False        # Gemma: normalize with (1 + weight)
    embedding_scale: bool = False       # Gemma: embed * sqrt(hidden_size)
    # Mixture of Experts (Mixtral family): 0 experts = dense MLP. When > 0
    # every block's MLP is a top-k routed expert layer
    # (dlti_tpu.models.moe.MoEMLP) with GShard capacity dispatch.
    num_experts: int = 0
    num_experts_per_tok: int = 2
    moe_capacity_factor: float = 1.25
    router_aux_loss_coef: float = 0.02
    dtype: str = "bfloat16"  # compute dtype (MXU-friendly)
    param_dtype: str = "bfloat16"  # storage dtype of (frozen) base params
    remat: bool = True  # jax.checkpoint each block (grad-ckpt parity)
    remat_policy: str = "nothing_saveable"  # or "dots_with_no_batch_dims_saveable"
    # Selective remat: every remat_stride-th block skips jax.checkpoint and
    # keeps its activations (1 = remat every block, the DeepSpeed
    # gradient-checkpointing default). Spends HBM headroom to cut the
    # recompute forward: stride k removes 1/k of it.
    remat_stride: int = 1
    attention_impl: str = "auto"  # "auto" | "reference" | "flash"
    flash_block_q: int = 512
    flash_block_kv: int = 512
    # Packed batches: an upper bound on any packed document's token count
    # (0 = unknown). Intra-document attention can never span further back
    # than the document's own length, so combined with segment masking a
    # window of this size is *exact* — and lets the flash kernel run its
    # banded sweep (O(seq x bound) FLOPs and DMA) instead of the causal
    # triangle. scripts/train.py sets it from the measured corpus when
    # packing. Ignored for unpacked batches.
    packed_attention_window: int = 0
    # Serving decode over the paged cache: "auto" uses the Pallas in-place
    # block-table kernel on TPU and the XLA gather path elsewhere;
    # "kernel" forces the kernel (interpreted off-TPU, for tests);
    # "gather" forces the XLA path.
    paged_attention_impl: str = "auto"

    @property
    def resolved_head_dim(self) -> int:
        return self.head_dim if self.head_dim is not None else self.hidden_size // self.num_heads

    def num_params(self, include_lm_head: bool = True) -> int:
        """Analytic parameter count (for MFU and reporting)."""
        return self._count_params(include_lm_head, active_only=False)

    def num_active_params(self, include_lm_head: bool = True) -> int:
        """Params touched per token — equals :meth:`num_params` for dense
        models; for MoE, k routed experts instead of all E (the count that
        drives FLOPs/token and MFU)."""
        return self._count_params(include_lm_head, active_only=True)

    def _count_params(self, include_lm_head: bool, active_only: bool) -> int:
        h, m, v = self.hidden_size, self.intermediate_size, self.vocab_size
        hd = self.resolved_head_dim
        q = h * self.num_heads * hd
        kv = 2 * h * self.num_kv_heads * hd
        o = self.num_heads * hd * h
        attn = q + kv + o
        if self.attention_bias:
            attn += (self.num_heads + 2 * self.num_kv_heads) * hd
        if self.num_experts > 0:
            n_ffn = (self.num_experts_per_tok if active_only
                     else self.num_experts)
            mlp = n_ffn * 3 * h * m + h * self.num_experts  # experts + router
        else:
            mlp = 3 * h * m
        norms = 2 * h
        per_layer = attn + mlp + norms
        total = v * h + self.num_layers * per_layer + h  # embed + layers + final norm
        if include_lm_head and not self.tie_embeddings:
            total += h * v
        return total


@dataclass(frozen=True)
class LoRAConfig:
    """LoRA adapter config.

    Matches the reference graft: r=16, alpha=32, dropout 0.05, q/k/v/o
    projections, no bias (``training/train_baseline.py:131-140``).
    """

    enabled: bool = True
    r: int = 16
    alpha: int = 32
    dropout: float = 0.05
    target_modules: tuple = ("q_proj", "k_proj", "v_proj", "o_proj")

    @property
    def scaling(self) -> float:
        return self.alpha / self.r


@dataclass(frozen=True)
class OptimizerConfig:
    """AdamW + WarmupLR, mirroring ``configs/ds_config_zero1.json:6-23,44``."""

    learning_rate: float = 2e-4
    betas: tuple = (0.9, 0.999)
    eps: float = 1e-8
    weight_decay: float = 0.0
    warmup_steps: int = 100
    grad_clip: float = 1.0
    schedule: str = "warmup_constant"  # or "warmup_cosine"
    total_steps: int = 0  # used by cosine schedule; 0 = constant after warmup


@dataclass(frozen=True)
class ParallelConfig:
    """Mesh shape + strategy.

    Axes: ``data`` (DP / ZeRO), ``fsdp`` (param sharding, ZeRO-3), ``tensor``
    (TP over ICI, for serving and large models), ``sequence`` (context /
    ring-attention parallelism for long sequences).
    """

    zero_stage: ZeROStage = ZeROStage.NONE
    data: int = 1
    fsdp: int = 1
    tensor: int = 1
    sequence: int = 1
    # Pipeline parallelism: the layer stack is split into `pipe` stages and
    # microbatches flow through a GPipe schedule (dlti_tpu.parallel.pipeline).
    pipe: int = 1
    # Expert parallelism: MoE expert weights and buffers shard over this
    # axis (all-to-all dispatch inserted by GSPMD).
    expert: int = 1
    # ZeRO-3 host offload parity (configs/ds_config_zero3.json:19-27).
    # offload_optimizer places optimizer state in pinned host memory (wired
    # in opt_state_shardings); offload_params places the frozen base params
    # in pinned host memory — streamed into the compiled step as host
    # operands when the runtime supports it, else moved at step boundaries
    # (make_sharded_train_step).
    offload_optimizer: bool = False
    offload_params: bool = False

    @property
    def num_devices(self) -> int:
        return (self.data * self.fsdp * self.tensor * self.sequence
                * self.pipe * self.expert)

    @property
    def dp_like_size(self) -> int:
        """Total batch-sharding degree (data * fsdp axes both carry batch)."""
        return self.data * self.fsdp


@dataclass(frozen=True)
class DataConfig:
    """Data pipeline config (reference: ``scripts/prepare_dataset.py``)."""

    dataset_path: str = "./data/glaive_code_full"
    dataset_name: str = "glaiveai/glaive-code-assistant"
    tokenizer: str = "meta-llama/Llama-2-7b-hf"
    max_seq_len: int = 512  # reference truncation (train_baseline.py:155)
    pack_sequences: bool = False  # reference does not pack; packing is a perf option
    num_samples: Optional[int] = None
    shuffle_seed: int = 0
    # Background batch prefetch depth (dlti_tpu.data.prefetch): the
    # Trainer runs batch gather/pack and the ahead-of-need device_put on a
    # worker thread, double-buffered this many batches deep, so the device
    # never waits on host batch prep. Batch order (and so the loss
    # trajectory) is bit-identical to the synchronous path. 0 = off
    # (legacy inline fetch).
    prefetch_depth: int = 2


@dataclass(frozen=True)
class CheckpointConfig:
    """Checkpoint / resume policy.

    Reference policies: baseline per-epoch keep-2 (``train_baseline.py:188-189``),
    ZeRO-1/2 per-100-steps keep-3 (``train_deepspeed_zero1.py:243-245``),
    ZeRO-3 per-epoch keep-2 (``train_deepspeed_zero3.py:234-236``).
    """

    output_dir: str = "./checkpoints/run"
    save_strategy: str = "steps"  # "steps" | "epoch" | "no"
    save_steps: int = 100
    save_total_limit: int = 3
    # Scan-latest-and-resume (train_deepspeed_zero1.py:267-279) — since the
    # crash-consistency pass, "latest" means latest *verified*: checkpoints
    # failing digest verification are quarantined and resume falls back to
    # the newest good one (dlti_tpu.checkpoint.store).
    resume: bool = True
    async_save: bool = True
    # Bounded retry/backoff for transient checkpoint-write failures (a
    # failed save is logged loudly but never kills the training run).
    save_retries: int = 3
    save_retry_backoff_s: float = 0.2


@dataclass(frozen=True)
class SentinelConfig:
    """Numeric-fault sentinel (``dlti_tpu.training.sentinel``): per-step
    nonfinite/spike detection over the compiled step's own metrics (no
    extra host syncs), automatic rollback to the last verified checkpoint
    with strike-counted data quarantine, and a periodic cross-rank
    parameter-digest probe that attributes silent data corruption to a
    suspect host for the elastic supervisor to evict."""

    # Host-side detection (spike windows, anomaly streaks, steplog
    # fields). The in-step nonfinite update gate is always compiled in —
    # it is a correctness fix, not an option.
    enabled: bool = True
    # Rolling-median spike window and its cold-start sample floor.
    window: int = 32
    min_samples: int = 8
    # Spike thresholds: latest > factor x rolling median (loss moves
    # slowly; grad norms are noisy, hence the wider factor).
    loss_spike_factor: float = 2.0
    grad_spike_factor: float = 10.0
    # Consecutive anomalous steps before automatic rollback to the last
    # verified checkpoint (0 = never roll back; detection still runs).
    rollback_after: int = 3
    # Total rollbacks allowed per run; exceeding raises SentinelGiveUp
    # (anomalies that survive every recovery need a human).
    max_rollbacks: int = 8
    # Strikes (rollbacks implicating a window) before that data window is
    # quarantined permanently; below that it is replayed (transient
    # faults pass on the second try).
    quarantine_after: int = 2
    # Cross-rank param-digest probe cadence in optimizer steps (0 = off;
    # multi-process runs only).
    sdc_check_interval: int = 0


@dataclass(frozen=True)
class TrainConfig:
    """Training loop knobs (reference: ``TrainingArguments`` uses across scripts)."""

    num_epochs: int = 1
    max_steps: int = 0  # 0 = derive from epochs * steps_per_epoch
    # GLOBAL microbatch per forward/backward (summed over all data-parallel
    # devices and hosts; must be divisible by data*fsdp mesh extent). The
    # reference's per-device bs=1 on N GPUs corresponds to micro_batch_size=N
    # here (train_baseline.py:64-68).
    micro_batch_size: int = 1
    grad_accum_steps: int = 16  # train_baseline.py:69-75
    logging_steps: int = 10  # train_baseline.py:184
    seed: int = 42
    eval_steps: int = 0  # 0 = no eval
    # Reference metrics contract: append one row per run
    # (training/utils.py:51-69 -> results/training_metrics.csv).
    metrics_csv: str = "results/training_metrics.csv"
    # fp16 dynamic loss scaling — parity with the reference's DeepSpeed fp16
    # block (configs/ds_config_zero1.json:25-32: loss_scale 0 = dynamic,
    # initial 2^16, window 1000, hysteresis 2, min_loss_scale 1). bf16 (the
    # TPU default) needs none of this; enable only for fp16 parity runs
    # (pair with ModelConfig dtype="float16").
    fp16: bool = False
    fp16_initial_scale_power: int = 16
    # Weight-only quantization of the *frozen* base params during LoRA
    # training ("" = off, "int8" = symmetric per-channel int8 — the QLoRA
    # idea, TPU-style). Grads flow only to the LoRA factors, so the base
    # may rest compressed: a 7B bf16 base is ~13.5 GB of a 16 GB chip,
    # int8 is ~6.8 GB — the freed HBM buys back remat recompute
    # (activation saving), the measured MFU ceiling at bf16
    # (results/mfu_investigation_r02.json). Requires lora.enabled.
    quantize_frozen_base: str = ""
    # Sequence-chunked cross-entropy (0 = off): compute the LM-head matmul
    # + softmax-CE loss_chunk positions at a time inside a rematerialized
    # scan, so (B, S, vocab) fp32 logits are never whole in HBM — at
    # 7B/seq-512 that is ~2 GB of the post-int8 memory headroom
    # (results/mfu_investigation_r03.json). Not for sequence-parallel or
    # MoE runs.
    loss_chunk: int = 0
    # Optimizer steps per host sync (1 = classic loop): with K > 1 the
    # Trainer scans K whole train steps into ONE compiled program
    # (lax.scan over stacked batches) and syncs metrics once per window —
    # the training analog of the serving engine's steps_per_sync
    # multi-step decode. Recovers per-call dispatch/relay overhead
    # (~95 ms/step on this image's remote chip: 3,880 -> 4,729 tok/s at
    # 7B, results/mfu_investigation_r03.json). Trajectory is identical to
    # K=1 (same per-step rng schedule); logging/metrics stay per-step;
    # eval/checkpoints land at window boundaries, and so do profiler
    # start/stop — a profile_num_steps < K trace captures a whole K-step
    # window (profile at steps_per_sync=1 for per-step traces). Not with
    # host offload (its step-boundary transfers are host-side) or
    # multi-host runs.
    steps_per_sync: int = 1
    fp16_scale_window: int = 1000
    fp16_hysteresis: int = 2
    fp16_min_scale: float = 1.0
    # jax.profiler trace capture (view in XProf/TensorBoard): writes a
    # trace of steps [profile_start_step, profile_start_step +
    # profile_num_steps) to profile_dir. Empty dir = no profiling.
    # The upgrade over the reference's wall_clock_breakdown:false
    # (configs/ds_config_zero1.json:48) — per-op device timelines.
    profile_dir: str = ""
    profile_start_step: int = 10
    profile_num_steps: int = 3
    # Deterministic-replay forensics (SURVEY.md §5.2 sanitizer analog):
    # persist a ring of (batch, rng, metrics) records so any recent step
    # can be re-executed bit-for-bit against a checkpoint
    # (dlti_tpu.utils.debug.replay_step). Empty dir = off.
    record_replay_dir: str = ""
    record_replay_every: int = 100
    record_replay_keep: int = 8
    # Deterministic trainer-side chaos hook ("STEP[:MODE]", MODE in raise |
    # kill | save-raise | save-kill — dlti_tpu.training.chaos), mirroring
    # the gateway's DLTI_GATEWAY_FAULT_INJECT. Also settable via env
    # DLTI_TRAIN_FAULT_INJECT. Chaos tests and fire drills use it to kill
    # the trainer at an exact step (or mid-async-save) and prove the
    # verified-resume path recovers. "" = off. The additional
    # "STEP:host-kill[:RANK]" mode is SUPERVISOR-owned (the elastic
    # launcher SIGKILLs a whole worker process from outside —
    # dlti_tpu.training.elastic.HostKillSpec); the in-process injector
    # ignores it. Numeric chaos modes (dlti_tpu.training.sentinel
    # drills): "STEP:nan-grad" poisons one batch's loss mask with NaN
    # (transient nonfinite step), "POS:poison-batch" deterministically
    # scrambles the batch at data position POS every time it is fed
    # (re-fires after rollback — the bad-data simulation), and
    # "STEP:param-flip[:RANK]" flips one mantissa bit in a replicated
    # param leaf on rank RANK (the silent-data-corruption simulation the
    # SDC probe must catch). Memory chaos: "STEP:hbm-squeeze" inflates a
    # balloon of device arrays (DLTI_CHAOS_BALLOON_BYTES, default 64 MiB)
    # and raises a RESOURCE_EXHAUSTED-shaped fault, driving the OOM
    # forensics path (flight dump with memory.json) deterministically on
    # CPU.
    fault_inject_step: str = ""
    # Numeric-fault sentinel (dlti_tpu.training.sentinel): see the
    # block's own docstring.
    sentinel: SentinelConfig = field(default_factory=SentinelConfig)


@dataclass(frozen=True)
class WatchdogConfig:
    """Anomaly watchdog (``dlti_tpu.telemetry.watchdog``): a rule engine
    over the in-process time-series ring. Disabled by default; alerts are
    structured events (JSONL log + ``dlti_watchdog_alerts_total{rule=}``
    counter + tracer instants) with a configurable escalation."""

    enabled: bool = False
    # Seconds between rule evaluations (also the time-series sampling
    # cadence the entry points use when the watchdog is on).
    interval_s: float = 1.0
    # Escalation on alert: "log" (record only), "dump" (also write a
    # flight record), "abort" (dump, SIGTERM self for the preemption
    # checkpoint, then hard-exit 86 — for CI chaos runs).
    action: str = "log"
    # JSONL alert event log ("" = alerts go to the logger/counter only).
    alert_log_path: str = ""
    # hung_step: no step completion within max(hung_step_min_s,
    # hung_step_factor x rolling-median step time) of the previous one.
    hung_step_factor: float = 10.0
    hung_step_min_s: float = 30.0
    # throughput_collapse: latest reading below floor_frac x rolling
    # median over at least min_samples ring samples. throughput_series
    # overrides the auto-watched set (train tok/s gauge + serving
    # generated_tokens rate).
    throughput_floor_frac: float = 0.25
    throughput_min_samples: int = 6
    throughput_series: str = ""
    # queue_buildup: gateway queue depth at/above this for 3 consecutive
    # samples (0 = rule off).
    queue_depth_limit: int = 0
    # shed_buildup: gateway sheds+rejections per second over the recent
    # window (0 = rule off).
    shed_rate_limit: float = 0.0
    # heartbeat_stale: a process heartbeat older than this (0 = rule off).
    heartbeat_stale_s: float = 0.0
    # ckpt_retry_storm: save retries accrued across the ring window.
    ckpt_retry_limit: int = 3
    # goodput_collapse: the goodput ledger's productive fraction (the
    # `goodput_fraction` ring series, telemetry.ledger) below
    # goodput_floor_frac x its rolling median over at least
    # goodput_min_samples samples (0 floor = rule off).
    goodput_floor_frac: float = 0.5
    goodput_min_samples: int = 8
    # hbm_pressure: the memory ledger's headroom fraction (the
    # `hbm_headroom_frac` ring series, telemetry.memledger — only
    # published when HBM capacity is known) dropped below this absolute
    # floor (0 = rule off).
    hbm_headroom_floor_frac: float = 0.0
    # disk_pressure: fires when free bytes on the persistence filesystem
    # (the `disk_free_bytes` ring series, utils.durable_io) drop below
    # this floor (0 = free-bytes check off; write-error growth and
    # degraded path classes always fire the rule).
    disk_free_floor_bytes: int = 0
    # replica_flap: fires when the serving replica-lifecycle flap
    # breaker evicts a replica (the flaps counter grew across the
    # watchdog window). 0 = rule off.
    replica_flap_limit: int = 1
    # slo_burn: fires when an SLO tracker reports a (objective, class)
    # burning through its error budget on a fast+slow window pair
    # (telemetry.slo). 0 = rule off even when a tracker is wired.
    slo_burn_limit: int = 1
    # canary_regression: fires when the deployment controller rolls a
    # candidate back (the dlti_deploy_rollbacks_total ring series grew
    # across the watchdog window) — a training run is producing
    # checkpoints the canary gates reject. 0 = rule off.
    canary_regression_limit: int = 1


@dataclass(frozen=True)
class SLOConfig:
    """Declarative SLO engine (``dlti_tpu.telemetry.slo``): objectives
    over existing SLIs, rolling error budgets per (objective, tenant
    class), multi-window multi-burn-rate alerts. Off by default; a zero
    threshold/target disables that objective family individually."""

    enabled: bool = False
    # Rolling error-budget window. An hour by default; drills shrink it
    # to seconds.
    window_s: float = 3600.0
    # Burn-rate alert tiers, "factor:long_s:short_s" comma-separated: a
    # tier fires when the burn rate exceeds factor over BOTH windows.
    burn_tiers: str = "14:60:5,6:300:30"
    # Latency objectives over the request-lifecycle histograms; the
    # threshold snaps to the nearest histogram bucket bound at/below it
    # (server and client then classify with the identical cut). 0 = off.
    ttft_threshold_s: float = 0.0
    ttft_target: float = 0.99
    tpot_threshold_s: float = 0.0
    tpot_target: float = 0.99
    queue_threshold_s: float = 0.0
    queue_target: float = 0.99
    # Admission availability per tenant class (admitted − shed over
    # admitted + rejected, from the gateway's counters). 0 = off.
    availability_target: float = 0.0
    # Training goodput: wall time counts as good while the ledger's
    # goodput fraction sits at/above the floor. 0 floor = off.
    goodput_floor: float = 0.0
    goodput_target: float = 0.99


@dataclass(frozen=True)
class FlightRecorderConfig:
    """Flight recorder (``dlti_tpu.telemetry.flightrecorder``): on fatal
    exception, SIGTERM, replica death, chaos fault, or watchdog
    escalation, dump a ``flight-*/`` black box (span tail, metrics
    snapshot, time-series tail, live context, config fingerprint) that
    ``scripts/postmortem.py`` renders. Enabled by setting ``dir``."""

    dir: str = ""  # "" = recorder off
    max_spans: int = 4096       # tracer events kept in spans.json
    timeseries_tail: int = 240  # ring samples kept in timeseries.json
    keep: int = 8               # dump dirs retained (oldest deleted)

    @property
    def enabled(self) -> bool:
        return bool(self.dir)


@dataclass(frozen=True)
class TelemetryConfig:
    """Unified telemetry layer (``dlti_tpu.telemetry``): span tracing,
    per-step JSONL stream, multi-host heartbeat. All off by default — the
    tracer's disabled path is one attribute read per span site."""

    # Directory for Chrome-trace JSON exports (Perfetto-viewable) of the
    # host-side span tracer: per-step trainer phases (batch fetch,
    # host→device, dispatch, sync, eval, save) and per-request engine
    # lifecycle spans. "" = tracer disabled.
    trace_dir: str = ""
    # Span ring-buffer capacity (events kept; oldest dropped beyond it).
    trace_capacity: int = 65536
    # Per-step JSONL telemetry stream (rank-0): step, loss, grad_norm, lr,
    # tokens/s/chip, MFU, HBM peak — a superset of the reference CSV
    # columns (telemetry.steplog). "" = off.
    step_log_path: str = ""
    # Multi-host heartbeat cadence in optimizer steps (0 = off): every
    # process reports its step (collective on multi-host meshes) and rank
    # 0 logs straggler lag.
    heartbeat_interval_steps: int = 0
    # Goodput ledger (telemetry.ledger): book every wall-clock second of
    # the run to one bucket (step compute, data wait, device sync, ckpt
    # save/restore, rollback + replay, SDC probe, ...) and derive the
    # goodput fraction + per-phase steplog fields. On by default — a
    # transition is ~a clock read; False reduces every site to one
    # attribute read (the tracer's disabled-path contract).
    goodput_ledger: bool = True
    # Memory ledger (telemetry.memledger): attribute device bytes to
    # named owners (params, optimizer state, KV pool, ...), reconcile
    # against jax.live_arrays()/memory_stats(), and feed the
    # hbm_* steplog fields, /debug/memory, and memory.json OOM
    # forensics. On by default; False reduces every site to one
    # attribute read.
    memory_ledger: bool = True
    # HBM capacity budget in bytes for headroom accounting (0 =
    # auto-detect from device memory_stats(); stays unknown on CPU,
    # where headroom-dependent features simply stay off).
    hbm_budget_bytes: int = 0
    # Self-monitoring: anomaly watchdog rules + flight-recorder black box
    # (see the blocks' own docstrings). Both off by default.
    watchdog: WatchdogConfig = field(default_factory=WatchdogConfig)
    flight_recorder: FlightRecorderConfig = field(
        default_factory=FlightRecorderConfig)
    # Declarative SLOs + error-budget burn alerting (telemetry.slo; see
    # the block's own docstring). Off by default.
    slo: SLOConfig = field(default_factory=SLOConfig)


@dataclass(frozen=True)
class GatewayConfig:
    """Admission gateway (``dlti_tpu.serving.gateway``): the scheduling
    front-end between the HTTP layer and the engine(s). Disabled by default
    — the server then admits directly into the engine, byte-for-byte the
    legacy behavior."""

    enabled: bool = False
    # Bounded admission queue: overflow is rejected with HTTP 429 +
    # Retry-After instead of growing without limit. 0 queued tokens = no
    # token bound (request-count bound still applies).
    max_queued_requests: int = 256
    max_queued_tokens: int = 0
    # Per-tenant token-bucket rate limiting (requests/s, sustained). 0 =
    # off. Burst is the bucket capacity; 0 derives max(1, 2*rps).
    rate_limit_rps: float = 0.0
    rate_limit_burst: float = 0.0
    # Weighted fair dequeue across tenants: "tenantA:4,tenantB:1" gives
    # tenantA 4x tenantB's dequeue share under contention. Unlisted
    # tenants weigh 1.
    tenant_weights: str = ""
    default_tenant: str = "default"
    # Tenant → LoRA adapter routing for multi-LoRA serving
    # (dlti_tpu.serving.adapters): "tenantA:ad1,tenantB:ad2" decodes
    # tenantA's requests under registered adapter ad1 unless the request
    # carries its own X-Adapter header. Unlisted tenants use the shared
    # base ("" = no mapping).
    adapter_map: str = ""
    # Retry-After value (seconds) for queue-bound rejections (rate-limit
    # rejections compute their own from the bucket deficit).
    retry_after_s: float = 1.0
    # Replica failover: how many times one request may be resubmitted onto
    # a surviving replica after its replica's step() faulted.
    max_retries: int = 2
    # Cache-affinity routing (dlti_tpu.serving.replicas): route each
    # request to its sticky rendezvous-hash replica (key = X-Session
    # header, else a digest of the first affinity_prefix_tokens prompt
    # ids) so repeat sessions land on the replica whose prefix cache is
    # warm; spill least-loaded when the sticky target's backlog exceeds
    # its decode slots by more than affinity_spill_threshold.
    affinity: bool = False
    affinity_spill_threshold: int = 4
    affinity_prefix_tokens: int = 32
    # Graceful drain: seconds SIGTERM waits for in-flight requests before
    # the server exits anyway.
    drain_grace_s: float = 30.0
    # Deterministic chaos hook: "REPLICA:STEP[:MODE]" kills replica
    # REPLICA on its STEP-th step() call (1-based). MODE "raise"
    # (default) raises in place of a device fault; "nan-logits" poisons
    # the replica's params with NaN so the engine's REAL numeric output
    # guard (EngineConfig.guard_nonfinite) detects the garbage and trips
    # the same quarantine path; "preempt" simulates a planned preemption
    # notice — the replica drains via live KV migration to survivors and
    # enters the lifecycle quarantine (no fault dump). Also settable via
    # env DLTI_GATEWAY_FAULT_INJECT; tests and chaos runs use it to
    # exercise failover without a real device fault.
    fault_inject_step: str = ""


@dataclass(frozen=True)
class PrefixTierConfig:
    """Hierarchical prefix-cache tiering
    (``dlti_tpu.serving.prefix_tiers``): evicted HBM prefix blocks demote
    to a bounded host-RAM tier and from there to digest-verified block
    dirs on disk; a prefix match in a lower tier restores blocks with a
    host→device scatter instead of a re-prefill. All tiers off by
    default (eviction discards, the legacy behavior). Maps onto
    ``EngineConfig.prefix_{host_blocks,disk_dir,disk_blocks}`` (see
    ``scripts/serve.py``)."""

    host_blocks: int = 0     # host-RAM tier budget, in KV blocks (0 = off)
    disk_dir: str = ""       # disk-tier directory ("" = disk tier off)
    disk_blocks: int = 0     # disk-tier budget, in block dirs (0 = off)

    @property
    def enabled(self) -> bool:
        return self.host_blocks > 0 or (bool(self.disk_dir)
                                        and self.disk_blocks > 0)


@dataclass(frozen=True)
class DisaggConfig:
    """Prefill/decode disaggregation (``dlti_tpu.serving.disagg``): split
    the replica fleet into a prefill pool and a decode pool, migrating
    each finished prefill's paged-KV blocks to a decode replica over the
    tier-restore path. Off by default — colocated serving is untouched."""

    enabled: bool = False
    prefill_replicas: int = 1
    decode_replicas: int = 1
    # Per-decode-replica bound on staged handoff snapshots: a full queue
    # backpressures the prefill pool (finished prefills stay in their
    # slots, which shrinks gateway dispatch room) instead of growing
    # host memory without limit.
    handoff_queue_depth: int = 8
    # Staged snapshots older than this re-prefill on the decode side
    # instead of waiting for a slot (0 = wait indefinitely; the request's
    # own gateway deadline still cancels it).
    handoff_deadline_s: float = 0.0
    # Deterministic chaos hook: "POOL:REPLICA:STEP[:MODE]" with POOL in
    # ("prefill", "decode") — same STEP/MODE semantics as
    # GatewayConfig.fault_inject_step, scoped to one pool member.
    fault_inject_step: str = ""


@dataclass(frozen=True)
class ReplicaLifecycleConfig:
    """Serving replica self-healing (``dlti_tpu.serving.lifecycle``): a
    faulted replica is quarantined instead of permanently evicted, its
    engine rebuilt from known-good weights, then reinstated only after a
    passing canary probe — with exponential probation backoff and a flap
    breaker (repeated quarantine/reinstate cycles inside a window →
    permanent eviction + watchdog alert). Off by default: with healing
    disabled a faulted replica stays dead forever (the legacy
    behavior)."""

    enabled: bool = False
    # Probation before the first reinstate probe, and the exponential
    # backoff applied per failed probe (delay = initial * backoff**fails,
    # capped at max).
    probation_initial_s: float = 2.0
    probation_backoff: float = 2.0
    probation_max_s: float = 60.0
    # Canary probe: a short greedy generation on the rebuilt replica,
    # checked against a digest pinned at fleet construction (and
    # re-pinned on weight reload).
    canary_prompt_tokens: int = 8
    canary_max_tokens: int = 4
    # Flap breaker: more than flap_max_cycles quarantines within
    # flap_window_s seconds evicts the replica permanently.
    flap_window_s: float = 300.0
    flap_max_cycles: int = 3


@dataclass(frozen=True)
class FleetConfig:
    """Multi-process serving fleet (``dlti_tpu.serving.fleet``): a
    supervisor process spawns N engine worker processes and drives them
    over the TCP wire protocol (``serving.wire``). Off by default — the
    in-process engine/replica paths are untouched."""

    workers: int = 2
    host: str = "127.0.0.1"
    # Worker startup bound: spawn -> jax import -> model build -> warmup
    # -> port published. Generous because warmup compiles the decode
    # ladder (first boot, cold compilation cache).
    startup_timeout_s: float = 600.0
    # Per-RPC socket timeout. A step can include a first-use prefill
    # bucket compile, so this is a liveness bound, not a latency target.
    rpc_timeout_s: float = 300.0
    # Idle heartbeat: refresh a worker's health/metrics snapshot when its
    # last contact is older than this (piggybacked on the step loop).
    health_interval_s: float = 2.0
    # Respawn backoff after a worker death (exponential, capped) and the
    # total respawns allowed per worker (elastic-launcher pattern).
    respawn_backoff_s: float = 0.5
    respawn_backoff_max_s: float = 30.0
    restart_budget: int = 8
    term_grace_s: float = 5.0
    max_frame_bytes: int = 256 * 1024 * 1024


@dataclass(frozen=True)
class SpeculativeConfig:
    """Adaptive speculative decoding (``dlti_tpu.serving.engine``): the
    n-gram prompt-lookup draft path plus its per-slot adaptive
    controller (acceptance-gated cooldowns and the pow2 draft-length
    ladder). Field names mirror the ``EngineConfig`` ``spec_*`` fields;
    :meth:`engine_kwargs` is the plumbing that applies the block to an
    engine build (``scripts/serve.py`` flags override it per run). Off
    by default — ``mode="none"`` keeps decode byte-identical to an
    engine that never compiled a spec program."""

    mode: str = "none"                 # "none" | "ngram"
    num_draft_tokens: int = 4
    ngram_size: int = 2
    adaptive: bool = True
    min_acceptance: float = 0.25
    probe_window: int = 64
    cooldown: int = 32

    def engine_kwargs(self) -> dict:
        """EngineConfig constructor kwargs for this block."""
        return {
            "speculative": self.mode,
            "num_draft_tokens": self.num_draft_tokens,
            "ngram_size": self.ngram_size,
            "spec_adaptive": self.adaptive,
            "spec_min_acceptance": self.min_acceptance,
            "spec_probe_window": self.probe_window,
            "spec_cooldown": self.cooldown,
        }


@dataclass(frozen=True)
class DeployConfig:
    """Continuous delivery (``dlti_tpu.serving.deploy``): a deployment
    controller that watches a training run's checkpoint directory for
    newly committed verified steps, auto-exports candidate weights
    through the digest-verified ``save_pytree`` path, canaries each
    candidate on one shadow replica under mirrored live traffic, and
    promotes fleet-wide (rolling reload) or rolls back — no human in the
    loop. Off by default; an empty ``watch_dir`` also keeps it off."""

    enabled: bool = False
    # Training checkpoint directory to watch (the checkpoint-store layout
    # scripts/train.py --output-dir writes). "" = controller off.
    watch_dir: str = ""
    # Where candidate exports land (save_pytree dirs named step-N;
    # rejected ones quarantine under <export_dir>/_quarantine).
    # "" = "<watch_dir>/_deploy_exports".
    export_dir: str = ""
    # Seconds between checkpoint-dir polls (injectable-clock ticks).
    poll_interval_s: float = 5.0
    # Fraction of live client submissions mirrored onto the canary as
    # shadow requests (results never reach clients).
    canary_shadow_frac: float = 0.25
    # Shadow-pair samples required before the gates are judged, and the
    # wall-clock bound a canary may wait for them (a quiet fleet judges
    # on the pinned probe set alone after the wait).
    canary_min_requests: int = 8
    canary_max_wait_s: float = 120.0
    # Gate 1 — greedy logprob drift: max |mean logprob delta| across the
    # pinned probe set, candidate vs incumbent baseline.
    promote_max_logprob_drift: float = 0.25
    # Gate 2 — output-length distribution shift: relative mean-length
    # delta between shadow (candidate) and paired live (incumbent)
    # completions (0 = gate off).
    max_length_shift_frac: float = 0.5
    # Gate 3 — per-phase SLO compliance on shadow requests: thresholds in
    # seconds (0 = that phase's gate off) and the compliant fraction
    # required.
    slo_ttft_threshold_s: float = 0.0
    slo_tpot_threshold_s: float = 0.0
    slo_min_compliance: float = 0.95
    # Pinned probe set: deterministic greedy prompts replayed against
    # every candidate and compared to the incumbent baseline.
    probe_prompts: int = 4
    probe_prompt_tokens: int = 8
    probe_max_tokens: int = 4
    # Promotion backoff for flapping candidates: after a rollback the
    # next candidate is not considered for initial * factor**rollbacks
    # seconds (capped), so a training run spewing bad checkpoints cannot
    # thrash the fleet with canary churn.
    promote_backoff_s: float = 30.0
    promote_backoff_factor: float = 2.0
    promote_backoff_max_s: float = 600.0


@dataclass(frozen=True)
class ServingConfig:
    """Serving-side config block (engine sizing stays in
    ``serving.engine.EngineConfig``; this holds the layers above it)."""

    gateway: GatewayConfig = field(default_factory=GatewayConfig)
    prefix_tiers: PrefixTierConfig = field(default_factory=PrefixTierConfig)
    disagg: DisaggConfig = field(default_factory=DisaggConfig)
    lifecycle: ReplicaLifecycleConfig = field(
        default_factory=ReplicaLifecycleConfig)
    fleet: FleetConfig = field(default_factory=FleetConfig)
    speculative: SpeculativeConfig = field(default_factory=SpeculativeConfig)
    deploy: DeployConfig = field(default_factory=DeployConfig)


@dataclass(frozen=True)
class Config:
    """Root config."""

    model: ModelConfig = field(default_factory=ModelConfig)
    lora: LoRAConfig = field(default_factory=LoRAConfig)
    optimizer: OptimizerConfig = field(default_factory=OptimizerConfig)
    parallel: ParallelConfig = field(default_factory=ParallelConfig)
    data: DataConfig = field(default_factory=DataConfig)
    checkpoint: CheckpointConfig = field(default_factory=CheckpointConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    telemetry: TelemetryConfig = field(default_factory=TelemetryConfig)
    serving: ServingConfig = field(default_factory=ServingConfig)
    experiment_name: str = ""

    def replace(self, **kwargs: Any) -> "Config":
        return dataclasses.replace(self, **kwargs)

    # ------------------------------------------------------------------
    # Serialization (round-trips through JSON for checkpoint metadata)
    # ------------------------------------------------------------------
    def to_dict(self) -> dict:
        def _convert(obj: Any) -> Any:
            if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
                return {k: _convert(v) for k, v in dataclasses.asdict(obj).items()}
            if isinstance(obj, enum.Enum):
                return obj.value
            if isinstance(obj, tuple):
                return list(obj)
            return obj

        return _convert(self)

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)

    @classmethod
    def from_dict(cls, d: dict) -> "Config":
        def _build(dc_cls, sub: dict):
            fields = {f.name: f for f in dataclasses.fields(dc_cls)}
            kwargs = {}
            for k, v in sub.items():
                if k not in fields:
                    continue
                f = fields[k]
                if dataclasses.is_dataclass(f.type) or f.name in (
                    "model", "lora", "optimizer", "parallel", "data",
                    "checkpoint", "train", "telemetry", "serving", "gateway",
                    "watchdog", "flight_recorder", "prefix_tiers", "sentinel",
                    "disagg", "lifecycle", "slo", "fleet", "speculative",
                    "deploy",
                ):
                    sub_cls = {
                        "model": ModelConfig, "lora": LoRAConfig,
                        "optimizer": OptimizerConfig, "parallel": ParallelConfig,
                        "data": DataConfig, "checkpoint": CheckpointConfig,
                        "train": TrainConfig, "telemetry": TelemetryConfig,
                        "serving": ServingConfig, "gateway": GatewayConfig,
                        "watchdog": WatchdogConfig,
                        "flight_recorder": FlightRecorderConfig,
                        "prefix_tiers": PrefixTierConfig,
                        "sentinel": SentinelConfig,
                        "disagg": DisaggConfig,
                        "lifecycle": ReplicaLifecycleConfig,
                        "slo": SLOConfig,
                        "fleet": FleetConfig,
                        "speculative": SpeculativeConfig,
                        "deploy": DeployConfig,
                    }.get(f.name)
                    if sub_cls is not None and isinstance(v, dict):
                        kwargs[k] = _build(sub_cls, v)
                        continue
                if f.name == "zero_stage":
                    kwargs[k] = ZeROStage(v)
                elif isinstance(v, list):
                    kwargs[k] = tuple(v)
                else:
                    kwargs[k] = v
            return dc_cls(**kwargs)

        return _build(cls, d)

    @classmethod
    def from_json(cls, s: str) -> "Config":
        return cls.from_dict(json.loads(s))


# ----------------------------------------------------------------------
# Model size presets
# ----------------------------------------------------------------------

MODEL_PRESETS: dict = {
    # Test-scale model: tiny but structurally identical (GQA, SwiGLU, RoPE).
    "llama_tiny": ModelConfig(
        vocab_size=512, hidden_size=64, intermediate_size=128, num_layers=2,
        num_heads=4, num_kv_heads=2, max_seq_len=128, remat=False,
        dtype="float32", param_dtype="float32",
    ),
    # Small debug model (fits anywhere, exercises remat + bf16).
    "llama_debug": ModelConfig(
        vocab_size=4096, hidden_size=256, intermediate_size=512, num_layers=4,
        num_heads=8, num_kv_heads=4, max_seq_len=512,
    ),
    # ~374M config (32k untied vocab): the largest preset whose *full*
    # fine-tune (bf16 params + fp32 AdamW moments + fp32 grad
    # accumulators) fits one 16 GB chip — used for on-hardware
    # convergence runs.
    "llama_300m": ModelConfig(
        vocab_size=32000, hidden_size=1024, intermediate_size=2816,
        num_layers=24, num_heads=16, num_kv_heads=16, max_seq_len=2048,
    ),
    # ~1.1B TinyLlama-shaped config for single-chip benchmarking.
    "llama_1b": ModelConfig(
        vocab_size=32000, hidden_size=2048, intermediate_size=5632,
        num_layers=22, num_heads=32, num_kv_heads=4, max_seq_len=2048,
    ),
    # Llama-2-7B (the reference's model: meta-llama/Llama-2-7b-hf).
    "llama2_7b": ModelConfig(
        vocab_size=32000, hidden_size=4096, intermediate_size=11008,
        num_layers=32, num_heads=32, num_kv_heads=32, max_seq_len=4096,
    ),
    # Llama-2-13B (BASELINE.json config #4: full fine-tune, ZeRO-3 multi-host).
    "llama2_13b": ModelConfig(
        vocab_size=32000, hidden_size=5120, intermediate_size=13824,
        num_layers=40, num_heads=40, num_kv_heads=40, max_seq_len=4096,
    ),
    # Llama-3-8B-shaped (GQA + large vocab), for generality.
    "llama3_8b": ModelConfig(
        vocab_size=128256, hidden_size=4096, intermediate_size=14336,
        num_layers=32, num_heads=32, num_kv_heads=8, max_seq_len=8192,
        rope_theta=500000.0,
    ),
    # Mistral-7B-v0.1: GQA + sliding-window local attention.
    "mistral_7b": ModelConfig(
        vocab_size=32000, hidden_size=4096, intermediate_size=14336,
        num_layers=32, num_heads=32, num_kv_heads=8, max_seq_len=8192,
        sliding_window=4096,
    ),
    # Qwen2-7B: biased q/k/v projections, big vocab, long RoPE period.
    "qwen2_7b": ModelConfig(
        vocab_size=152064, hidden_size=3584, intermediate_size=18944,
        num_layers=28, num_heads=28, num_kv_heads=4, max_seq_len=32768,
        rope_theta=1000000.0, attention_bias=True,
    ),
    # Gemma-7B: MHA with wide heads, (1+w) RMSNorm, scaled + tied embeddings.
    "gemma_7b": ModelConfig(
        vocab_size=256000, hidden_size=3072, intermediate_size=24576,
        num_layers=28, num_heads=16, num_kv_heads=16, head_dim=256,
        max_seq_len=8192, rms_norm_eps=1e-6, tie_embeddings=True,
        mlp_activation="gelu_tanh", rmsnorm_offset=True, embedding_scale=True,
    ),
    # Mixtral-8x7B: sparse MoE (8 experts, top-2) on the Mistral base.
    "mixtral_8x7b": ModelConfig(
        vocab_size=32000, hidden_size=4096, intermediate_size=14336,
        num_layers=32, num_heads=32, num_kv_heads=8, max_seq_len=8192,
        rope_theta=1000000.0, num_experts=8, num_experts_per_tok=2,
    ),
    # Test-scale MoE (structurally Mixtral: GQA + top-2 of 4 experts).
    "mixtral_tiny": ModelConfig(
        vocab_size=512, hidden_size=64, intermediate_size=128, num_layers=2,
        num_heads=4, num_kv_heads=2, max_seq_len=128, remat=False,
        dtype="float32", param_dtype="float32", num_experts=4,
        num_experts_per_tok=2,
    ),
}


def preset(name: str, **overrides: Any) -> Config:
    """Build a :class:`Config` from a strategy preset name.

    Presets mirror the reference experiment matrix
    (``training/train.ipynb``): ``baseline`` and ``zero{1,2,3}_{N}dev``.

    >>> preset("baseline").parallel.zero_stage
    <ZeROStage.NONE: 0>
    >>> preset("zero3_8dev").parallel.fsdp
    8
    """
    model = overrides.pop("model", MODEL_PRESETS["llama2_7b"])
    if isinstance(model, str):
        model = MODEL_PRESETS[model]

    if name == "baseline":
        par = ParallelConfig(zero_stage=ZeROStage.NONE)
    else:
        import re

        m = re.fullmatch(r"zero([123])(?:_(\d+)dev)?", name)
        if not m:
            raise ValueError(
                f"unknown preset {name!r}; expected 'baseline' or 'zero{{1,2,3}}[_Ndev]'"
            )
        stage = ZeROStage(int(m.group(1)))
        n = int(m.group(2) or 1)
        if stage == ZeROStage.ZERO3:
            par = ParallelConfig(zero_stage=stage, fsdp=n)
        else:
            par = ParallelConfig(zero_stage=stage, data=n)
    return Config(model=model, parallel=par, experiment_name=name, **overrides)
