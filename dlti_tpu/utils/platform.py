"""JAX platform selection that survives this image's site hook.

Exporting ``JAX_PLATFORMS`` is normally enough to pick a backend, but a
site hook here re-forces the TPU relay plugin on jax import, so entry
points must also win the race via ``jax.config.update`` — which only works
before the backend initializes. Every CLI / dry-run entry point funnels
through these helpers instead of hand-rolling the dance.
"""

from __future__ import annotations

import os


def honor_platform_env() -> None:
    """Re-assert ``JAX_PLATFORMS`` from the environment (no-op if unset)
    and enable the persistent compilation cache.

    Call before any jax backend use in an entry point.
    """
    enable_compilation_cache()
    plat = os.environ.get("JAX_PLATFORMS")
    if not plat:
        return
    import jax

    try:
        jax.config.update("jax_platforms", plat)
    except Exception:
        pass  # backend already up; the env var had its chance


def enable_compilation_cache(subdir: str = "xla",
                             min_compile_secs: float = 5.0) -> None:
    """Point XLA's persistent compilation cache at a stable location.

    A 7B train-step compile costs minutes on the remote relay but replays
    from this cache in milliseconds across processes (measured), so every
    entry point enables it. Explicit ``JAX_COMPILATION_CACHE_DIR`` (or
    ``DLTI_NO_COMPILE_CACHE=1``) wins. The test suite uses its own
    ``subdir`` and a lower ``min_compile_secs`` (hundreds of sub-5s
    compiles dominate there; see tests/conftest.py).
    """
    if os.environ.get("DLTI_NO_COMPILE_CACHE", "").lower() in (
            "1", "true", "yes"):
        return
    cache_dir = os.environ.get(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "dlti_tpu", subdir))
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          min_compile_secs)
    except Exception:
        pass  # older jax without these knobs


def host_platform_env(n_devices: int, env: dict) -> dict:
    """Set the CPU-backend-with-``n_devices``-virtual-devices vars on ``env``.

    The single source of truth for the env half of the dance — used both for
    this process (:func:`force_host_platform`) and for child-process env
    dicts (orchestration subprocesses), which additionally rely on the child
    entry point calling :func:`honor_platform_env` to win the site-hook race.
    """
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        )
    env["JAX_PLATFORMS"] = "cpu"
    return env


def force_host_platform(n_devices: int) -> None:
    """Force the CPU backend with ``n_devices`` virtual devices.

    For mesh simulation (tests, dry runs). Must run before the backend
    initializes in this process; silently loses the race otherwise, after
    which the caller's device-count check reports the failure.
    """
    host_platform_env(n_devices, os.environ)
    honor_platform_env()
