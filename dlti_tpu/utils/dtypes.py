"""One dtype-name table for the whole framework."""

from __future__ import annotations

import jax.numpy as jnp

_DTYPES = {
    "float32": jnp.float32,
    "bfloat16": jnp.bfloat16,
    "float16": jnp.float16,
}


def resolve_dtype(name: str):
    try:
        return _DTYPES[name]
    except KeyError:
        raise ValueError(
            f"unknown dtype {name!r}; expected one of {sorted(_DTYPES)}"
        ) from None
