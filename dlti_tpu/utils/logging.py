"""Rank-0 logging + step timing.

Reference analogs: main-process gating via ``is_main_process``
(``train_deepspeed_zero1.py:123,126``) / ``local_rank <= 0``
(``train_deepspeed_zero3.py:128``); per-10-step logging
(``logging_steps=10``, ``train_baseline.py:184``).
"""

from __future__ import annotations

import logging
import sys
import time
from contextlib import contextmanager


def is_main_process() -> bool:
    import jax

    return jax.process_index() == 0


def get_logger(name: str = "dlti_tpu") -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stdout)
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
        )
        logger.addHandler(handler)
        logger.setLevel(logging.INFO if is_main_process() else logging.WARNING)
        logger.propagate = False
    return logger


class StepTimer:
    """Wall-clock per-step timing with warm-up discard — the in-tree
    equivalent of DeepSpeed's ``wall_clock_breakdown`` (always available,
    reference keeps it disabled — ``configs/ds_config_zero1.json:48``)."""

    def __init__(self, warmup_steps: int = 2):
        self.warmup_steps = warmup_steps
        self._times: list = []
        self._t0: float | None = None
        self._count = 0
        # Most recent measured per-step time, warm-up included (the
        # per-step telemetry stream wants every step's own time, not the
        # smoothed mean the throughput summary uses).
        self.last_step_seconds = 0.0

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self, steps: int = 1) -> None:
        """``steps`` > 1: the timed span covered that many train steps
        (a steps_per_sync window); record the per-step time. Warm-up is
        counted in *steps*, so a scanned window past the warm-up budget
        still records (else steps_per_sync=K with max_steps=2K would
        discard every window and report 0 tok/s)."""
        dt = time.perf_counter() - self._t0
        steps = max(steps, 1)
        warm = self._count < self.warmup_steps
        self._count += steps
        self.last_step_seconds = dt / steps
        if not warm:
            self._times.append(dt / steps)

    @contextmanager
    def measure(self, steps: int = 1):
        self.start()
        yield
        self.stop(steps)

    @property
    def mean_step_seconds(self) -> float:
        return sum(self._times) / len(self._times) if self._times else 0.0

    @property
    def steps_per_second(self) -> float:
        m = self.mean_step_seconds
        return 1.0 / m if m > 0 else 0.0


@contextmanager
def profile_trace(log_dir: str, enabled: bool = True):
    """Capture a ``jax.profiler`` trace (view in TensorBoard/XProf) —
    the tracing capability the reference lacks (SURVEY.md §5.1)."""
    import jax

    if not enabled:
        yield
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
