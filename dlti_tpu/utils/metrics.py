"""Metrics: reference CSV schema + TPU-native additions (tokens/sec/chip, MFU).

Reference schema (``training/train_baseline.py:246-255``, appended to
``results/training_metrics.csv`` by ``training/utils.py:51-69``):
``experiment, num_gpus, zero_stage, strategy, training_time_hours,
samples_per_second, peak_memory_gb, final_loss``.

We keep those columns byte-compatible (``num_gpus`` meaning "num chips") so
the reference's analysis workflow ports directly, and append
``tokens_per_second_per_chip`` and ``mfu_percent`` — the BASELINE.json north
star metrics.
"""

from __future__ import annotations

import csv
import dataclasses
import os
from dataclasses import dataclass
from typing import Optional

# The reference repo's CSV schema (``training/train_baseline.py:246-255``)
# — the byte-compatible column set MetricsRecord starts from. The parity
# contract: every metrics surface we add (the CSV extensions below, the
# telemetry per-step JSONL stream) must stay a SUPERSET of these columns
# so the reference's analysis workflow keeps porting directly (guarded by
# tests/test_telemetry.py).
REFERENCE_CSV_COLUMNS = (
    "experiment", "num_gpus", "zero_stage", "strategy",
    "training_time_hours", "samples_per_second", "peak_memory_gb",
    "final_loss",
)

# v5e: 197 TFLOP/s bf16 per chip; v5p: 459; v4: 275. Used for MFU.
# NOTE: ordered most-specific-first — the lookup scans in insertion order and
# e.g. "v5" is a substring of every v5p device_kind.
TPU_PEAK_FLOPS = {
    "v5p": 459e12,
    "v5e": 197e12,
    "v5litepod": 197e12,
    "v6e": 918e12,
    "v5": 197e12,
    "v4": 275e12,
    "cpu": 1e12,  # placeholder so CPU smoke runs produce finite MFU
}


@dataclass
class MetricsRecord:
    experiment: str
    num_gpus: int  # column name kept for reference CSV parity; = num chips
    zero_stage: int
    strategy: str
    training_time_hours: float
    samples_per_second: float
    peak_memory_gb: float
    final_loss: float
    tokens_per_second_per_chip: float = 0.0
    mfu_percent: float = 0.0
    # Where peak_memory_gb came from: "device" (PJRT memory stats — real
    # HBM) or "host_rss" (process VmHWM fallback) — two different
    # quantities that must not be read as one (see device_peak_memory).
    peak_memory_source: str = "none"
    # Held-out eval loss at the last eval (nan when eval never ran).
    eval_loss: float = float("nan")

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def training_flops_per_token(num_params: int, trainable_params: Optional[int] = None) -> float:
    """Approximate FLOPs/token for one train step.

    Full fine-tune: ~6N (fwd 2N + bwd 4N). LoRA: bwd skips dW for frozen
    params (~2N of the 4N), giving ~4N + small adapter terms.
    """
    if trainable_params is not None and trainable_params < 0.5 * num_params:
        return 4.0 * num_params
    return 6.0 * num_params


def compute_mfu(
    tokens_per_second_per_chip: float,
    num_params: int,
    chip_peak_flops: float,
    trainable_params: Optional[int] = None,
) -> float:
    """Model FLOPs Utilization in percent."""
    achieved = tokens_per_second_per_chip * training_flops_per_token(
        num_params, trainable_params
    )
    return 100.0 * achieved / chip_peak_flops


def detect_chip_peak_flops() -> float:
    """Best-effort peak-FLOPs lookup for the local accelerator."""
    import jax

    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "cpu").lower().replace(" ", "")
    for key, val in TPU_PEAK_FLOPS.items():
        if key in kind:
            return val
    return TPU_PEAK_FLOPS["cpu"]


def device_memory_stats() -> dict:
    """Per-device PJRT memory stats: ``{device_str: stats_dict}`` for every
    local device that reports them (CPU backends and some plugins return
    None — those devices are simply absent). The raw map behind
    :func:`device_peak_memory` and the memory ledger's reconciliation
    (``dlti_tpu.telemetry.memledger``)."""
    import jax

    out = {}
    for dev in jax.local_devices():
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        if stats:
            out[str(dev)] = dict(stats)
    return out


def device_peak_memory() -> tuple:
    """Peak memory as ``(gb, source)`` (the
    ``torch.cuda.max_memory_allocated`` analog, reference
    ``train_baseline.py:253``).

    Aggregates across ALL local devices — the per-process peak is the sum
    of each chip's ``peak_bytes_in_use`` (a megacore host drives 4+ chips;
    reading only device 0 under-reported by the chip count). ``source`` is
    ``"device"`` (PJRT memory stats — real HBM), ``"host_rss"`` (process
    VmHWM fallback for CPU-simulated runs and PJRT plugins that return no
    stats, like the remote relay), or ``"none"``. Device HBM and host RSS
    are different quantities; consumers of the CSV must be able to tell
    them apart, hence the explicit source.
    """
    try:
        total = 0
        for stats in device_memory_stats().values():
            total += stats.get("peak_bytes_in_use",
                               stats.get("bytes_in_use", 0)) or 0
        if total:
            return total / 1024**3, "device"
    except Exception:
        pass
    try:  # host fallback: peak resident set (VmHWM), linux procfs
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmHWM:"):
                    return int(line.split()[1]) / 1024**2, "host_rss"  # kB->GB
    except Exception:
        pass
    return 0.0, "none"


def save_training_metrics(metrics: MetricsRecord | dict,
                          csv_path: str = "results/training_metrics.csv") -> None:
    """Append a row; write header on first write (``training/utils.py:51-69``).

    Schema-tolerant: when the existing file's header differs (a column was
    added since it was written), the file is rewritten under the union of
    columns instead of appending misaligned rows.
    """
    row = metrics.to_dict() if isinstance(metrics, MetricsRecord) else dict(metrics)
    os.makedirs(os.path.dirname(csv_path) or ".", exist_ok=True)
    old_fields: list = []
    if os.path.isfile(csv_path):
        with open(csv_path, newline="") as f:
            old_fields = next(csv.reader(f), []) or []
    if old_fields and set(old_fields) == set(row):
        # Same columns (possibly reordered keys in a dict row): plain
        # append in the file's own column order.
        with open(csv_path, "a", newline="") as f:
            csv.DictWriter(f, fieldnames=old_fields).writerow(row)
        return
    if old_fields and old_fields != list(row.keys()):
        # Header changed (a column was added since the file was written):
        # rewrite under the union of columns — via a temp file + atomic
        # replace, so a preemption mid-rewrite can never destroy history.
        with open(csv_path, newline="") as f:
            old_rows = list(csv.DictReader(f))
        fields = old_fields + [k for k in row if k not in old_fields]
        tmp_path = csv_path + ".tmp"
        with open(tmp_path, "w", newline="") as f:
            writer = csv.DictWriter(f, fieldnames=fields, restval="")
            writer.writeheader()
            for r in old_rows:
                writer.writerow(r)
            writer.writerow(row)
        os.replace(tmp_path, csv_path)
        return
    with open(csv_path, "a", newline="") as f:
        writer = csv.DictWriter(f, fieldnames=list(row.keys()))
        if not old_fields:
            writer.writeheader()
        writer.writerow(row)


def print_metrics_summary(metrics: MetricsRecord | dict) -> None:
    """Formatted stdout dump (``training/utils.py:72-88``)."""
    row = metrics.to_dict() if isinstance(metrics, MetricsRecord) else dict(metrics)
    print("\n" + "=" * 60)
    print("TRAINING METRICS SUMMARY")
    print("=" * 60)
    for k, v in row.items():
        if isinstance(v, float):
            print(f"  {k:<28} {v:.4f}")
        else:
            print(f"  {k:<28} {v}")
    print("=" * 60 + "\n")
