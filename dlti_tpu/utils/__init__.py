"""Shared utilities: experiment naming, metrics, logging, profiling."""

from dlti_tpu.utils.experiment import (  # noqa: F401
    create_experiment_name,
    get_zero_stage_from_config,
)
from dlti_tpu.utils.metrics import (  # noqa: F401
    MetricsRecord,
    compute_mfu,
    print_metrics_summary,
    save_training_metrics,
)
