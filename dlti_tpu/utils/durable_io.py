"""Durable writer: every persistence path's single door to the disk.

PRs 4-13 built durability *protocols* (manifest+commit checkpoints,
digest-verified tiers, atomic heartbeat renames) on the assumption of a
healthy filesystem. This module is the layer underneath: every byte the
package persists — checkpoint staging files, adapter exports, prefix-tier
blocks, flight dumps, the step log, elastic heartbeats/ledgers, the
sentinel skip-list, watchdog alert logs — goes through one writer with
*classified* error handling (an AST guard, ``tests/test_durable_io_guard
.py``, keeps it that way):

* **Transient** (``EIO``, ``EAGAIN``, ``EINTR``, ``EBUSY``,
  ``ETIMEDOUT``, ``ESTALE`` — the flaky-NFS family): bounded retry with
  exponential backoff.
* **Reclaimable** (``ENOSPC``, ``EDQUOT``): run the registered reclaim
  callbacks — quota-evict ``_quarantine/`` wreckage, rotate old flight
  dumps, drop cold disk-tier blocks — then retry. Components register
  what they can afford to lose via :func:`register_reclaimer`; the
  per-path-class :func:`disk_ledger` records what each class wrote,
  dropped, and reclaimed.
* **Persistent** (anything else, or retries exhausted): degrade by the
  path class's criticality instead of crashing. ``checkpoint`` /
  ``adapter`` / ``prefix_tier`` / ``flight`` / ``fleet_runtime`` writes
  re-raise the final ``OSError`` so their callers run the protocol-level
  fallback (skip the save and alert; flip the tier memory-only; record
  ``dump_failed``; let the fleet supervisor's startup timeout respawn);
  telemetry-stream classes (``steplog``, ``elastic``, ``sentinel``,
  ``watchdog``) drop-and-count — a lost log line must never abort a
  training step.

Degradation is self-announcing: ``dlti_disk_write_errors_total`` and
``dlti_disk_degraded`` carry a ``path_class`` label, ``dlti_disk_free_
bytes`` tracks the filesystem, and the watchdog's ``disk_pressure`` rule
fires on any of them. Recovery is automatic — the first successful write
of a class clears its degraded flag.

Chaos: all raw file operations funnel through :func:`_raw_write_bytes` /
:func:`_raw_append_text` / :func:`_raw_replace`, which consult the
installed fault injector (:class:`dlti_tpu.checkpoint.chaos.FaultyIO`,
spec ``DLTI_IO_FAULT=PATH_GLOB:errno[:count|rate][:delay_s]``) before
touching the os — ENOSPC/EIO/slow-write/torn-write injection at the
file boundary without monkeypatching builtins.
"""

from __future__ import annotations

import errno
import json
import os
import shutil
import threading
import time
from typing import Callable, Dict, Optional

from dlti_tpu.telemetry.registry import Counter, Gauge
from dlti_tpu.utils.logging import get_logger

# Chaos-spec env var (parsed by dlti_tpu.checkpoint.chaos.FaultyIO; read
# lazily per operation so subprocess drills only need the env set).
IO_FAULT_ENV = "DLTI_IO_FAULT"

# Name-stability contract (pinned in tests/test_bench_contract.py).
DISK_METRIC_NAMES = (
    "dlti_disk_free_bytes",
    "dlti_disk_write_errors_total",
    "dlti_disk_degraded",
)

free_bytes_gauge = Gauge(
    DISK_METRIC_NAMES[0],
    help="free bytes on the filesystem of the last persistence write")
write_errors_total = Counter(
    DISK_METRIC_NAMES[1],
    help="persistence write errors, labeled by path_class")
degraded_gauge = Gauge(
    DISK_METRIC_NAMES[2],
    help="1 while a path class is degraded (skipping/dropping writes), "
         "labeled by path_class")

# Path classes, with the per-class policy: does a persistent failure
# re-raise (the caller owns a protocol-level fallback) or drop-and-count
# (telemetry streams — losing a line must never hurt the run)? The
# retry budget is per durable operation (the transient-errno family);
# callers with their own outer retry loops (the checkpoint writer) keep
# them on top.
#   class        raises  retries
_POLICY: Dict[str, tuple] = {
    "checkpoint":  (True, 3),
    "adapter":     (True, 2),
    "prefix_tier": (True, 1),
    "flight":      (True, 1),
    # Fleet worker port files: the supervisor polls for them, so a
    # persistent failure must surface in the worker (its process exits
    # and the supervisor's startup timeout takes over).
    "fleet_runtime": (True, 1),
    "steplog":     (False, 0),
    "elastic":     (False, 1),
    "sentinel":    (False, 1),
    "watchdog":    (False, 0),
}
PATH_CLASSES = tuple(_POLICY)

_TRANSIENT_ERRNOS = frozenset(
    e for e in (errno.EIO, errno.EAGAIN, errno.EINTR, errno.EBUSY,
                errno.ETIMEDOUT, getattr(errno, "ESTALE", None))
    if e is not None)
_RECLAIM_ERRNOS = frozenset(
    e for e in (errno.ENOSPC, getattr(errno, "EDQUOT", None))
    if e is not None)

_lock = threading.Lock()
_degraded: set = set()
_ledger: Dict[str, Dict[str, float]] = {}
_reclaimers: "Dict[str, Callable[[int], int]]" = {}
_last_free = [0.0, 0]          # [monotonic probe time, bytes]
_probe_dir = ["."]             # filesystem the free-bytes gauge tracks


def classify_errno(exc: BaseException) -> str:
    """``"transient"`` | ``"reclaim"`` | ``"persistent"`` for an OSError
    (anything that is not an OSError classifies persistent)."""
    code = getattr(exc, "errno", None)
    if code in _TRANSIENT_ERRNOS:
        return "transient"
    if code in _RECLAIM_ERRNOS:
        return "reclaim"
    return "persistent"


# ----------------------------------------------------------------------
# Fault injection hook (the os/file boundary FaultyIO patches)
# ----------------------------------------------------------------------

_injector = [None]             # explicit (test-installed) injector
_env_cache: list = ["", None]  # [spec string, parsed FaultyIO]


def set_fault_injector(inj) -> None:
    """Install (or clear, with None) an explicit fault injector. An
    explicit injector wins over the ``DLTI_IO_FAULT`` env spec."""
    _injector[0] = inj


def _active_injector():
    if _injector[0] is not None:
        return _injector[0]
    spec = os.environ.get(IO_FAULT_ENV, "")
    if not spec:
        return None
    if _env_cache[0] != spec:
        from dlti_tpu.checkpoint.chaos import FaultyIO

        _env_cache[0], _env_cache[1] = spec, FaultyIO.from_spec(spec)
    return _env_cache[1]


def _plan_fault(op: str, path: str):
    inj = _active_injector()
    if inj is None:
        return None
    try:
        return inj.plan(op, str(path))
    except Exception:
        # A broken injector must never break production writes.
        get_logger().exception("io fault injector failed; ignoring")
        return None


def _apply_fault(fault, path: str, data: Optional[bytes]) -> None:
    """Honor a planned fault: sleep, tear, or raise. A torn write leaves
    a half-written file behind (the on-disk wreckage a real power cut or
    full NFS buffer flush produces) before raising."""
    if fault is None:
        return
    if fault.delay_s:
        time.sleep(fault.delay_s)
    if fault.err is None:
        return  # pure slow-write
    if fault.kind == "torn" and data is not None:
        with open(path, "wb") as f:
            f.write(data[: max(1, len(data) // 2)])
    raise OSError(fault.err,
                  f"chaos: injected {errno.errorcode.get(fault.err, fault.err)}"
                  f" ({fault.kind})", str(path))


# ----------------------------------------------------------------------
# Raw ops — the only places in the covered modules that touch the file
# boundary for writes (the AST guard pins this).
# ----------------------------------------------------------------------

def _raw_write_bytes(path: str, data: bytes, fsync: bool) -> None:
    _apply_fault(_plan_fault("write", path), path, data)
    with open(path, "wb") as f:
        f.write(data)
        if fsync:
            f.flush()
            os.fsync(f.fileno())


def _raw_append_text(path: str, text: str) -> None:
    fault = _plan_fault("write", path)
    if fault is not None and fault.err is not None and fault.kind == "torn":
        if fault.delay_s:
            time.sleep(fault.delay_s)
        with open(path, "a") as f:
            f.write(text[: max(1, len(text) // 2)])
        raise OSError(fault.err, "chaos: torn append", str(path))
    _apply_fault(fault, path, None)
    with open(path, "a") as f:
        f.write(text)
        f.flush()


def _raw_replace(src: str, dst: str) -> None:
    _apply_fault(_plan_fault("replace", dst), dst, None)
    os.replace(src, dst)


# ----------------------------------------------------------------------
# Ledger / degrade bookkeeping
# ----------------------------------------------------------------------

def _class_entry(path_class: str) -> Dict[str, float]:
    return _ledger.setdefault(path_class, {
        "writes": 0, "bytes": 0, "errors": 0, "drops": 0,
        "reclaims": 0, "reclaimed_bytes": 0, "last_errno": 0})


def _note_write(path_class: str, nbytes: int) -> None:
    with _lock:
        e = _class_entry(path_class)
        e["writes"] += 1
        e["bytes"] += nbytes
        if path_class in _degraded:
            _degraded.discard(path_class)
            degraded_gauge.labels(path_class=path_class).set(0)
            get_logger().warning(
                "durable_io: path class %r recovered (write succeeded)",
                path_class)


def _note_error(path_class: str, exc: BaseException) -> None:
    write_errors_total.labels(path_class=path_class).inc()
    with _lock:
        e = _class_entry(path_class)
        e["errors"] += 1
        e["last_errno"] = getattr(exc, "errno", 0) or 0


def _note_drop(path_class: str) -> None:
    with _lock:
        _class_entry(path_class)["drops"] += 1


def _set_degraded(path_class: str) -> None:
    with _lock:
        newly = path_class not in _degraded
        _degraded.add(path_class)
    degraded_gauge.labels(path_class=path_class).set(1)
    if newly:
        get_logger().error(
            "durable_io: path class %r DEGRADED (persistent write "
            "failure); writes will be skipped/dropped per criticality "
            "until one succeeds", path_class)


def is_degraded(path_class: str) -> bool:
    with _lock:
        return path_class in _degraded


def degraded_classes() -> tuple:
    with _lock:
        return tuple(sorted(_degraded))


def disk_ledger() -> Dict[str, Dict[str, float]]:
    """Per-path-class budget ledger: writes/bytes persisted, errors,
    drops, reclaim passes and bytes they freed, last errno seen."""
    with _lock:
        return {k: dict(v) for k, v in _ledger.items()}


def reset_for_tests() -> None:
    """Zero the module's mutable state (ledger, degraded flags, injector,
    reclaimers) so chaos tests don't leak into each other."""
    with _lock:
        _ledger.clear()
        for c in _degraded:
            degraded_gauge.labels(path_class=c).set(0)
        _degraded.clear()
        _reclaimers.clear()
    _injector[0] = None
    _env_cache[0], _env_cache[1] = "", None


def probe_free_bytes(path: Optional[str] = None) -> int:
    """statvfs free bytes for ``path``'s filesystem (default: the last
    directory a durable write touched); updates the gauge."""
    target = path or _probe_dir[0]
    try:
        free = shutil.disk_usage(target).free
    except OSError:
        return _last_free[1]
    _last_free[0], _last_free[1] = time.monotonic(), free
    free_bytes_gauge.set(free)
    return free


def scalars() -> dict:
    """Sampler-ring snapshot (the trainer's ``_train_scalars`` merges
    this; the watchdog's ``disk_pressure`` rule reads the keys)."""
    if time.monotonic() - _last_free[0] > 5.0:
        probe_free_bytes()
    with _lock:
        errors = sum(e["errors"] for e in _ledger.values())
        drops = sum(e["drops"] for e in _ledger.values())
        degraded = len(_degraded)
    return {"disk_free_bytes": _last_free[1],
            "disk_write_errors": errors,
            "disk_write_drops": drops,
            "disk_degraded": degraded}


# ----------------------------------------------------------------------
# Reclaim registry (the ENOSPC escape hatch)
# ----------------------------------------------------------------------

def register_reclaimer(name: str, fn: Callable[[int], int]) -> None:
    """Register ``fn(bytes_needed) -> bytes_freed`` under ``name``
    (idempotent: re-registering a name replaces it). Components offer up
    what they can afford to lose: quarantined wreckage, old flight
    dumps, cold disk-tier blocks."""
    with _lock:
        _reclaimers[name] = fn


def unregister_reclaimer(name: str) -> None:
    with _lock:
        _reclaimers.pop(name, None)


def reclaim(bytes_needed: int, path_class: str = "") -> int:
    """Run reclaimers until ``bytes_needed`` is freed (or all ran).
    Returns bytes freed. Reclaimer exceptions are logged and skipped —
    reclaim is best-effort by definition."""
    with _lock:
        items = list(_reclaimers.items())
    freed = 0
    for name, fn in items:
        try:
            freed += max(0, int(fn(max(0, bytes_needed - freed))))
        except Exception:
            get_logger().exception("reclaimer %r failed", name)
        if bytes_needed > 0 and freed >= bytes_needed:
            break
    if path_class:
        with _lock:
            e = _class_entry(path_class)
            e["reclaims"] += 1
            e["reclaimed_bytes"] += freed
    get_logger().warning(
        "durable_io: reclaim pass freed %d bytes (%d reclaimers, wanted "
        "%d) for class %r", freed, len(items), bytes_needed, path_class)
    return freed


def dir_bytes(path: str) -> int:
    """Recursive byte count of ``path`` (file or directory)."""
    if os.path.isfile(path):
        try:
            return os.path.getsize(path)
        except OSError:
            return 0
    total = 0
    for root, _dirs, files in os.walk(path):
        for name in files:
            try:
                total += os.path.getsize(os.path.join(root, name))
            except OSError:
                pass
    return total


def sweep_oldest(directory: str, keep: int = 0,
                 bytes_needed: int = 0) -> int:
    """Delete oldest-mtime entries under ``directory`` until only
    ``keep`` remain (and, when ``bytes_needed`` > 0, stop early once
    enough is freed). Returns bytes freed."""
    if not os.path.isdir(directory):
        return 0
    try:
        entries = sorted(
            (os.path.join(directory, n) for n in os.listdir(directory)),
            key=lambda p: os.path.getmtime(p) if os.path.exists(p) else 0)
    except OSError:
        return 0
    freed = 0
    doomed = entries[:-keep] if keep > 0 else entries
    for path in doomed:
        size = dir_bytes(path)
        try:
            if os.path.isdir(path):
                shutil.rmtree(path, ignore_errors=True)
            else:
                os.remove(path)
        except OSError:
            continue
        freed += size
        if bytes_needed > 0 and freed >= bytes_needed:
            break
    return freed


def quarantine_reclaimer(root: str,
                         subdir: str = "_quarantine") -> Callable[[int], int]:
    """A reclaimer that quota-evicts ``root/subdir`` oldest-first —
    quarantined wreckage is forensics, and forensics lose to keeping the
    run writing."""
    qdir = os.path.join(os.path.abspath(root), subdir)

    def _sweep(bytes_needed: int) -> int:
        return sweep_oldest(qdir, keep=0, bytes_needed=bytes_needed)

    return _sweep


# ----------------------------------------------------------------------
# The durable operations
# ----------------------------------------------------------------------

def _attempt(op: Callable[[], None], path: str, path_class: str,
             nbytes: int, retries: Optional[int],
             backoff_s: float) -> bool:
    """Run ``op`` under the classified retry/reclaim/degrade policy.
    Returns True on success; False when a drop-class gave up; re-raises
    the final OSError for raising classes."""
    raises, default_retries = _POLICY[path_class]
    budget = default_retries if retries is None else retries
    d = os.path.dirname(path)
    if d:
        _probe_dir[0] = d
    attempt = 0
    reclaimed = False
    while True:
        try:
            op()
        except OSError as e:
            _note_error(path_class, e)
            kind = classify_errno(e)
            probe_free_bytes(d or ".")
            if kind == "reclaim" and not reclaimed:
                reclaimed = True
                if reclaim(max(nbytes, 1), path_class) > 0:
                    continue  # space came back: retry without burning budget
            if kind in ("transient", "reclaim") and attempt < budget:
                time.sleep(backoff_s * (2 ** attempt))
                attempt += 1
                continue
            _set_degraded(path_class)
            if raises:
                raise
            _note_drop(path_class)
            get_logger().warning(
                "durable_io: dropped %s write to %s (%s)", path_class,
                path, e)
            return False
        else:
            _note_write(path_class, nbytes)
            return True


def write_bytes(path: str, data: bytes, *, path_class: str,
                fsync: bool = False, retries: Optional[int] = None,
                backoff_s: float = 0.05) -> bool:
    """Durably write ``data`` to ``path`` (replacing it). Returns True on
    success; drop-class failures return False; raising classes re-raise
    the final OSError."""
    path = str(path)
    return _attempt(lambda: _raw_write_bytes(path, data, fsync),
                    path, path_class, len(data), retries, backoff_s)


def append_line(path: str, text: str, *, path_class: str,
                retries: Optional[int] = None,
                backoff_s: float = 0.05) -> bool:
    """Durably append ``text`` (newline added if missing) to ``path``."""
    path = str(path)
    line = text if text.endswith("\n") else text + "\n"
    return _attempt(lambda: _raw_append_text(path, line),
                    path, path_class, len(line), retries, backoff_s)


def replace(src: str, dst: str, *, path_class: str,
            retries: Optional[int] = None,
            backoff_s: float = 0.05) -> bool:
    """Durable ``os.replace`` (atomic rename; works for the staging-dir
    commits too)."""
    src, dst = str(src), str(dst)
    return _attempt(lambda: _raw_replace(src, dst),
                    dst, path_class, 0, retries, backoff_s)


def write_json_atomic(path: str, obj, *, path_class: str,
                      fsync: bool = False, indent: Optional[int] = None,
                      sort_keys: bool = False, default=None,
                      retries: Optional[int] = None) -> bool:
    """tmp-file + atomic-rename JSON write under the durable policy (the
    heartbeat/ledger/skip-list idiom, centralized). Returns True only
    when both the staging write and the rename landed."""
    path = str(path)
    data = json.dumps(obj, indent=indent, sort_keys=sort_keys,
                      default=default).encode()
    tmp = f"{path}.tmp-{os.getpid()}"
    try:
        if not write_bytes(tmp, data, path_class=path_class, fsync=fsync,
                           retries=retries):
            return False
        if not replace(tmp, path, path_class=path_class, retries=retries):
            return False
        return True
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


class LineWriter:
    """Append-mode line stream with drop-and-count semantics: a write
    failure counts a drop, closes the handle, and the next write reopens
    — the stream heals itself when the fault clears and never raises
    (the step log / heartbeat contract: telemetry must not abort the
    step it describes)."""

    def __init__(self, path: str, *, path_class: str):
        self.path = os.path.abspath(path)
        self.path_class = path_class
        self.dropped = 0
        self._f = None
        self._warned = False
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._reopen()

    def _reopen(self) -> bool:
        try:
            self._f = open(self.path, "a")
            return True
        except OSError as e:
            _note_error(self.path_class, e)
            self._f = None
            return False

    def write_line(self, text: str) -> bool:
        line = text if text.endswith("\n") else text + "\n"
        try:
            fault = _plan_fault("write", self.path)
            if fault is not None:
                if fault.delay_s:
                    time.sleep(fault.delay_s)
                if fault.err is not None:
                    if fault.kind == "torn" and self._f is not None:
                        self._f.write(line[: max(1, len(line) // 2)])
                        self._f.flush()
                    raise OSError(fault.err, "chaos: injected fault",
                                  self.path)
            if self._f is None and not self._reopen():
                raise OSError(errno.EIO, "stream unavailable", self.path)
            self._f.write(line)
            self._f.flush()
        except (OSError, ValueError) as e:
            if isinstance(e, OSError):
                _note_error(self.path_class, e)
            _note_drop(self.path_class)
            _set_degraded(self.path_class)
            self.dropped += 1
            if self._f is not None:
                try:
                    self._f.close()
                except Exception:
                    pass
                self._f = None
            if not self._warned or self.dropped % 100 == 0:
                self._warned = True
                get_logger().warning(
                    "durable_io: %s line dropped on %s (%s; %d dropped "
                    "so far)", self.path_class, self.path, e, self.dropped)
            return False
        _note_write(self.path_class, len(line))
        return True

    def close(self) -> None:
        if self._f is not None and not self._f.closed:
            try:
                self._f.close()
            except OSError:
                pass
        self._f = None

    @property
    def closed(self) -> bool:
        return self._f is None or self._f.closed
