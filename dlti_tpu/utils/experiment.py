"""Experiment naming + config introspection.

API-parity with the reference's ``training/utils.py``:

* :func:`create_experiment_name` — ``training/utils.py:11-33``
* :func:`get_zero_stage_from_config` — ``training/utils.py:36-48`` (extended:
  also accepts this framework's own JSON config files)
"""

from __future__ import annotations

import json
from typing import Optional, Union

from dlti_tpu.config import Config, ZeROStage


def create_experiment_name(num_devices: int, zero_stage: Union[int, ZeROStage, None]) -> str:
    """``(num_devices, zero_stage)`` -> experiment name.

    >>> create_experiment_name(1, None)
    'baseline'
    >>> create_experiment_name(1, 0)
    'baseline'
    >>> create_experiment_name(2, 1)
    'zero1_2dev'
    >>> create_experiment_name(4, 3)
    'zero3_4dev'
    """
    stage = int(zero_stage) if zero_stage is not None else 0
    if stage == 0:
        return "baseline"
    return f"zero{stage}_{num_devices}dev"


def get_zero_stage_from_config(config_path: str) -> Optional[int]:
    """Read the ZeRO stage out of a JSON config file.

    Accepts both DeepSpeed-style files (``{"zero_optimization": {"stage": N}}``,
    reference ``configs/ds_config_zero1.json:34``) and this framework's
    serialized :class:`~dlti_tpu.config.Config` (``parallel.zero_stage``).
    Returns None if the file has neither.
    """
    with open(config_path) as f:
        cfg = json.load(f)
    if "zero_optimization" in cfg:
        return cfg["zero_optimization"].get("stage")
    if "parallel" in cfg:
        return cfg["parallel"].get("zero_stage")
    return None


def experiment_name_from_config(cfg: Config) -> str:
    if cfg.experiment_name:
        return cfg.experiment_name
    if cfg.parallel.pipe > 1:
        # Pipeline runs must not masquerade as the single-device baseline
        # in the metrics CSV (zero_stage is 0 under pure pipe).
        return f"pipe{cfg.parallel.pipe}_{cfg.parallel.num_devices}dev"
    return create_experiment_name(cfg.parallel.num_devices, cfg.parallel.zero_stage)
