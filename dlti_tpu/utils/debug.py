"""Sharding assertions + deterministic step replay — the sanitizer analog.

The reference's only "sanitizer" is turning DDP's unused-parameter
detection *off* (``train_deepspeed_zero1.py:248``; SURVEY.md §5.2) — on a
GSPMD stack the failure modes worth guarding are different: a leaf
silently landing with the wrong PartitionSpec (GSPMD falls back to
all-gather instead of erroring), non-finite values creeping into a step,
and "it diverged at step 31k" reports with nothing to reproduce from.
This module covers all three:

* :func:`assert_tree_sharding` / :func:`sharding_mismatches` — walk a
  pytree and fail loudly (with param paths) when actual shardings drift
  from the intended specs.
* :func:`assert_all_finite` — pinpoints which leaves carry NaN/inf.
* :class:`StepRecorder` / :func:`replay_step` — capture (batch, rng,
  metrics) of live training steps into a ring of ``.npz`` files; replay
  re-executes a recorded batch through a step function and checks the
  metrics reproduce — the deterministic-seed replay SURVEY §5.2
  prescribes, usable for post-mortem forensics on any checkpoint.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, List, Optional, Tuple

import jax
import numpy as np


def _path_str(path: tuple) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif isinstance(p, tuple):
            parts.extend(str(q) for q in p)
        else:
            parts.append(str(p))
    return "/".join(parts)


def sharding_mismatches(tree: Any, expected: Any) -> List[Tuple[str, str, str]]:
    """Compare actual leaf shardings against expected NamedShardings.

    ``expected`` is a matching pytree of shardings (e.g. the output of
    ``param_shardings`` / ``state_shardings``). Returns
    ``(path, actual, expected)`` triples for every drifted leaf; memory
    kinds are compared too (a weight quietly falling back from
    pinned_host to device defeats offload without an error).
    """
    actual_flat = jax.tree_util.tree_leaves_with_path(tree)
    expected_flat = jax.tree_util.tree_leaves_with_path(expected)
    exp_by_path = {_path_str(p): s for p, s in expected_flat}
    bad = []
    for path, leaf in actual_flat:
        ps = _path_str(path)
        want = exp_by_path.get(ps)
        if want is None:
            continue
        got = getattr(leaf, "sharding", None)
        if got is None:
            continue
        same_spec = getattr(got, "spec", None) == getattr(want, "spec", None)
        same_kind = (getattr(got, "memory_kind", None)
                     == getattr(want, "memory_kind", None))
        if not (same_spec and same_kind):
            bad.append((ps, f"{got}", f"{want}"))
    return bad


def assert_tree_sharding(tree: Any, expected: Any, what: str = "tree") -> None:
    """Raise ``AssertionError`` naming every leaf whose sharding drifted."""
    bad = sharding_mismatches(tree, expected)
    if bad:
        lines = "\n".join(f"  {p}:\n    actual   {a}\n    expected {e}"
                          for p, a, e in bad[:20])
        more = f"\n  ... and {len(bad) - 20} more" if len(bad) > 20 else ""
        raise AssertionError(
            f"{len(bad)} leaves of {what} have drifted shardings:\n{lines}{more}")


def assert_all_finite(tree: Any, what: str = "tree") -> None:
    """Raise with the paths of every leaf containing NaN/inf."""
    bad = []
    for path, leaf in jax.tree_util.tree_leaves_with_path(tree):
        arr = np.asarray(jax.device_get(leaf))
        if arr.dtype.kind == "f" and not np.isfinite(arr).all():
            n_bad = int((~np.isfinite(arr)).sum())
            bad.append(f"  {_path_str(path)}: {n_bad}/{arr.size} non-finite")
    if bad:
        raise AssertionError(f"non-finite values in {what}:\n" + "\n".join(bad))


class StepRecorder:
    """Ring buffer of training-step inputs for deterministic replay.

    ``record(step, batch, rng, metrics)`` persists the *inputs* of a step
    (the host-side batch arrays and the folded rng key) plus the observed
    metrics. Keeps the newest ``keep`` records. Cheap: one .npz write of
    the already-host-resident batch per recorded step.
    """

    def __init__(self, directory: str, keep: int = 8,
                 every_steps: int = 1) -> None:
        self.directory = directory
        self.keep = max(1, keep)  # keep<=0 would disable rotation entirely
        self.every_steps = max(1, every_steps)
        os.makedirs(directory, exist_ok=True)

    def record(self, step: int, batch: dict, rng, metrics: dict) -> None:
        if step % self.every_steps != 0:
            return
        path = os.path.join(self.directory, f"step_{step:08d}.npz")
        payload = {f"batch.{k}": np.asarray(jax.device_get(v))
                   for k, v in batch.items()}
        payload["rng"] = np.asarray(jax.random.key_data(rng))
        payload["metrics_json"] = np.frombuffer(
            json.dumps({k: float(v) for k, v in metrics.items()}).encode(),
            dtype=np.uint8)
        np.savez(path, step=step, **payload)
        self._rotate()

    def _rotate(self) -> None:
        files = sorted(f for f in os.listdir(self.directory)
                       if f.startswith("step_") and f.endswith(".npz"))
        for f in files[:-self.keep]:
            os.remove(os.path.join(self.directory, f))

    @staticmethod
    def load(path: str) -> Tuple[int, dict, Any, dict]:
        """-> (step, batch, rng, recorded_metrics)."""
        data = np.load(path)
        batch = {k[len("batch."):]: data[k] for k in data.files
                 if k.startswith("batch.")}
        rng = jax.random.wrap_key_data(data["rng"])
        metrics = json.loads(bytes(data["metrics_json"]).decode())
        return int(data["step"]), batch, rng, metrics


def replay_step(
    record_path: str,
    step_fn: Callable,
    state,
    *,
    rtol: float = 0.0,
    compare: Optional[List[str]] = None,
) -> dict:
    """Re-execute a recorded step and check its metrics reproduce.

    ``step_fn(state, batch, rng) -> (state, metrics)`` must be the same
    step function (and ``state`` the same train state — restore the
    matching checkpoint first). With ``rtol=0`` this asserts bitwise
    determinism of the recorded metrics — XLA executions are
    deterministic given identical inputs, program, and topology, so any
    divergence means the inputs/program differ from the original run.
    Returns the replayed metrics.
    """
    step, batch, rng, recorded = StepRecorder.load(record_path)
    _, metrics = step_fn(state, batch, rng)
    metrics = {k: float(jax.device_get(v)) for k, v in metrics.items()}
    keys = compare if compare is not None else [
        k for k in ("loss", "grad_norm") if k in recorded and k in metrics]
    for k in keys:
        a, b = metrics[k], recorded[k]
        ok = (a == b) if rtol == 0.0 else abs(a - b) <= rtol * max(abs(b), 1e-12)
        if not ok:
            raise AssertionError(
                f"replay of step {step} diverged on {k!r}: replayed {a!r} "
                f"vs recorded {b!r} (rtol={rtol})")
    return metrics
