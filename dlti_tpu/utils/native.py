"""Loader for the optional native (C++) runtime library.

The reference outsources its native runtime to external wheels (torch/NCCL/
DeepSpeed ops — SURVEY.md §2b); ours is in-tree under ``native/`` and built
with ``make -C native``. Everything degrades gracefully to pure Python when
the library hasn't been built, so tests and CPU smoke runs never require a
toolchain.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _lib_path() -> str:
    root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(root, "native", "libdlti_runtime.so")


def load_native_runtime() -> Optional[ctypes.CDLL]:
    """Return the loaded native runtime, or None if unavailable.

    Set ``DLTI_DISABLE_NATIVE=1`` to force the pure-Python paths (used by
    tests to cover both implementations).
    """
    global _LIB, _TRIED
    if os.environ.get("DLTI_DISABLE_NATIVE") == "1":
        return None
    if _TRIED:
        return _LIB
    _TRIED = True
    path = _lib_path()
    if not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    # Allocator ABI.
    lib.dlti_allocator_create.argtypes = [ctypes.c_int32]
    lib.dlti_allocator_create.restype = ctypes.c_void_p
    lib.dlti_allocator_destroy.argtypes = [ctypes.c_void_p]
    lib.dlti_allocator_num_free.argtypes = [ctypes.c_void_p]
    lib.dlti_allocator_num_free.restype = ctypes.c_int32
    lib.dlti_allocator_allocate.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.POINTER(ctypes.c_int32)]
    lib.dlti_allocator_allocate.restype = ctypes.c_int32
    lib.dlti_allocator_free.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.POINTER(ctypes.c_int32)]
    # Guarded free (absent in older builds): 1 = freed, 0 = rejected
    # batch (out-of-range / double free); rejection frees nothing.
    if hasattr(lib, "dlti_allocator_free_checked"):
        lib.dlti_allocator_free_checked.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.POINTER(ctypes.c_int32)]
        lib.dlti_allocator_free_checked.restype = ctypes.c_int32
    # Packer ABI (absent in older builds of the library).
    if hasattr(lib, "dlti_pack_assign"):
        lib.dlti_pack_assign.argtypes = [
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32)]
        lib.dlti_pack_assign.restype = ctypes.c_int32
    _LIB = lib
    return _LIB
