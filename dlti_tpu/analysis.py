"""Training-run comparison: speedup/efficiency analysis + plots.

Capability parity with the reference's ``scripts/compare_training.py``
(SURVEY.md §3.5): consume the metrics CSV written by
:func:`dlti_tpu.utils.metrics.save_training_metrics` (same schema as the
reference's ``results/training_metrics.csv``), derive speedup and scaling
efficiency against the baseline row, print a comparison table and key
findings, and render a 2x2 panel figure (training time, speedup, peak
memory per chip, scaling efficiency vs ideal).

Derivations follow the reference's definitions
(``compare_training.py:46-47``):

* ``speedup = baseline_training_time / training_time``
* ``efficiency_percent = speedup / num_chips * 100``

with the same fallback when no ``baseline`` experiment row exists: the
first row becomes the comparison anchor (``compare_training.py:37-42``).
TPU-native additions: tokens/sec/chip and MFU columns ride along when
present.
"""

from __future__ import annotations

import os
from typing import Optional

import pandas as pd


def load_and_calculate(csv_path: str) -> pd.DataFrame:
    """Load the metrics CSV and add speedup/efficiency columns."""
    df = pd.read_csv(csv_path)
    if df.empty:
        raise ValueError(f"{csv_path} has no rows")

    base_rows = df[df["experiment"] == "baseline"]
    if len(base_rows):
        base_time = float(base_rows.iloc[0]["training_time_hours"])
    else:
        # No baseline recorded: anchor on the first row so relative numbers
        # are still meaningful (reference fallback, compare_training.py:37-42).
        base_time = float(df.iloc[0]["training_time_hours"])

    times = df["training_time_hours"].astype(float).replace(0.0, float("nan"))
    df["speedup"] = base_time / times
    df["efficiency_percent"] = df["speedup"] / df["num_gpus"].astype(float) * 100.0
    return df


def print_comparison_table(df: pd.DataFrame) -> None:
    cols = [c for c in (
        "experiment", "num_gpus", "zero_stage", "strategy",
        "training_time_hours", "samples_per_second", "peak_memory_gb",
        "final_loss", "speedup", "efficiency_percent",
        "tokens_per_second_per_chip", "mfu_percent",
    ) if c in df.columns]
    print("=" * 72)
    print("TRAINING COMPARISON")
    print("=" * 72)
    print(df[cols].round(3).to_string(index=False))


def print_key_findings(df: pd.DataFrame) -> None:
    base = df[df["experiment"] == "baseline"]
    anchor = base.iloc[0] if len(base) else df.iloc[0]
    print("\nKEY FINDINGS (vs %s)" % anchor["experiment"])
    print("-" * 72)
    for idx, row in df.iterrows():
        if idx == anchor.name:
            continue
        saved_h = float(anchor["training_time_hours"]) - float(row["training_time_hours"])
        dmem = float(row["peak_memory_gb"]) - float(anchor["peak_memory_gb"])
        print(
            f"{row['experiment']:>16}: {row['speedup']:.2f}x speedup, "
            f"{row['efficiency_percent']:.1f}% scaling efficiency, "
            f"{saved_h:.2f}h saved, {dmem:+.2f} GB peak memory/chip"
        )


def create_plots(df: pd.DataFrame, output_path: str = "results/plots/training_comparison.png") -> str:
    """2x2 panel: time, speedup, peak memory/chip, efficiency vs ideal."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, axes = plt.subplots(2, 2, figsize=(13, 9))
    names = df["experiment"].tolist()
    x = range(len(names))

    ax = axes[0][0]
    ax.bar(x, df["training_time_hours"], color="#4878cf")
    ax.set_title("Training time")
    ax.set_ylabel("hours")

    ax = axes[0][1]
    ax.bar(x, df["speedup"], color="#6acc65")
    ax.axhline(1.0, ls="--", c="gray", lw=1, label="baseline")
    ax.set_title("Speedup vs baseline")
    ax.set_ylabel("x")
    ax.legend()

    ax = axes[1][0]
    ax.bar(x, df["peak_memory_gb"], color="#d65f5f")
    ax.set_title("Peak memory per chip")
    ax.set_ylabel("GB")

    ax = axes[1][1]
    eff = df.sort_values("num_gpus")
    ax.plot(eff["num_gpus"], eff["efficiency_percent"], "o-", label="measured")
    ax.axhline(100.0, ls="--", c="gray", lw=1, label="ideal")
    ax.set_title("Scaling efficiency")
    ax.set_xlabel("chips")
    ax.set_ylabel("%")
    ax.legend()

    for ax in (axes[0][0], axes[0][1], axes[1][0]):
        ax.set_xticks(list(x))
        ax.set_xticklabels(names, rotation=30, ha="right", fontsize=8)

    fig.tight_layout()
    os.makedirs(os.path.dirname(output_path) or ".", exist_ok=True)
    fig.savefig(output_path, dpi=300)
    plt.close(fig)
    return output_path


def compare(csv_path: str, plot_path: Optional[str] = None) -> pd.DataFrame:
    """Full analysis flow: load -> table -> findings -> plots."""
    df = load_and_calculate(csv_path)
    print_comparison_table(df)
    print_key_findings(df)
    if plot_path is not None:
        out = create_plots(df, plot_path)
        print(f"\nplots -> {out}")
    return df
