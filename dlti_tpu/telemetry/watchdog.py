"""Anomaly watchdog: a rule engine over the time-series ring.

The system noticing its own anomalies (the ROADMAP's "heavy traffic from
millions of users" has no human watching a dashboard): a small set of
rules runs over the :class:`~dlti_tpu.telemetry.timeseries.TimeSeriesSampler`
ring plus two push-style signals (step completions from the trainer,
heartbeats from multi-host runs), and every firing becomes a structured
alert — JSONL event log, ``dlti_watchdog_alerts_total{rule=...}`` counter,
a ``watchdog/alert`` tracer instant — with a configurable escalation:

* ``log``   — the alert record is the whole response (default);
* ``dump``  — additionally trigger a flight-record dump
  (:mod:`~dlti_tpu.telemetry.flightrecorder`), throttled;
* ``abort`` — dump, then hard-exit the process with
  :data:`ABORT_EXIT_CODE` — for CI chaos runs where a hung step must fail
  the job rather than burn the runner's budget.

Rules (all edge-triggered — an alert fires on the condition's rising edge
and re-arms only when the condition clears, so a sustained anomaly is one
alert, not one per check interval):

* ``hung_step``           — no step completion within
  ``max(hung_step_min_s, hung_step_factor x rolling-median step time)``
  of the last one (MegaScale's straggler/hang localizer, in-framework).
* ``throughput_collapse`` — the latest throughput reading fell below
  ``throughput_floor_frac`` x the rolling median (training tok/s gauge
  and the serving ``generated_tokens`` counter rate are both watched).
* ``queue_buildup``       — gateway queue depth at/above
  ``queue_depth_limit`` for 3 consecutive samples.
* ``shed_buildup``        — gateway sheds+rejections accruing faster than
  ``shed_rate_limit`` per second over the recent window.
* ``heartbeat_stale``     — a process's heartbeat older than
  ``heartbeat_stale_s`` (multi-host straggler death).
* ``ckpt_retry_storm``    — ``ckpt_save_retries`` grew by at least
  ``ckpt_retry_limit`` across the ring window (storage going bad under
  the async writer's backoff).
* ``nonfinite_step`` / ``loss_spike`` / ``sdc_mismatch`` — the numeric
  sentinel's counters (``dlti_tpu.training.sentinel``) grew since the
  previous check: nonfinite loss/grads (update skipped in-step), a
  loss/grad-norm spike vs the rolling window, or a cross-rank parameter
  digest mismatch (suspected silent data corruption).
* ``goodput_collapse``      — the goodput ledger's productive fraction
  (``goodput_fraction`` in the ring, ``telemetry.ledger``) fell below
  ``goodput_floor_frac`` x its rolling median: the run still steps, but
  recovery work (rollbacks, restores, stalls) is eating the wall clock.
* ``hbm_pressure``          — the memory ledger's headroom fraction
  (``hbm_headroom_frac`` in the ring, ``telemetry.memledger``) dropped
  below ``hbm_headroom_floor_frac``: the next big allocation (a long
  prefill, a KV growth burst) is likely to OOM — alert (and dump the
  ownership map) while the process is still alive to tell the story.
* ``disk_pressure``         — the durable writer (``utils.durable_io``)
  is in trouble: free bytes under ``disk_free_floor_bytes``, write
  errors accruing since the last check, or a path class degraded
  (skipping/dropping writes). Fires while the run is still healthy
  enough to act — the checkpoint that *couldn't* be written is exactly
  the one a later incident will want.
* ``replica_flap``          — the serving replica-lifecycle flap breaker
  permanently evicted a replica (``serving.lifecycle``'s
  ``dlti_replica_lifecycle_flaps_total`` grew since the last check): a
  replica cycled live → quarantined → live too many times inside the
  flap window, so self-healing gave up on it — capacity is now down a
  replica until an operator intervenes.
* ``slo_burn``            — an SLO tracker (``telemetry.slo``, wired via
  the ``slo=`` constructor arg) reports an (objective, class) burning
  its error budget past a fast+slow window tier: the alert carries the
  burn rates and the budget still remaining, so it lands *before*
  exhaustion. Re-arms when that (objective, class) stops breaching.

The module-level :func:`log_event` appends structured non-alert events
(e.g. the flight recorder's ``dump_failed``) to the same JSONL event log
the alerts go to, so one file tells the whole incident story.
"""

from __future__ import annotations

import json
import os
import signal as _signal
import statistics
import threading
import time
from collections import deque
from typing import Callable, List, Optional

from dlti_tpu.telemetry.registry import Counter
from dlti_tpu.telemetry.timeseries import TimeSeriesSampler
from dlti_tpu.telemetry.tracer import get_tracer
from dlti_tpu.utils import durable_io
from dlti_tpu.utils.logging import get_logger

# Name-stability contract (pinned in tests/test_bench_contract.py).
WATCHDOG_METRIC_NAMES = ("dlti_watchdog_alerts_total",)

# Module-level counter, same pattern as the checkpoint store's metrics:
# trainer-side and server-side watchdogs share it; the server registry
# registers it for /metrics exposition.
alerts_total = Counter(
    WATCHDOG_METRIC_NAMES[0],
    help="watchdog alerts fired, labeled by rule")

RULES = ("hung_step", "throughput_collapse", "queue_buildup",
         "shed_buildup", "heartbeat_stale", "ckpt_retry_storm",
         "nonfinite_step", "loss_spike", "sdc_mismatch",
         "goodput_collapse", "hbm_pressure", "disk_pressure",
         "replica_flap", "slo_burn", "canary_regression")

# Sentinel-counter rules (rule, ring keys summed): fire when the summed
# counters grew since the previous check (edge: a sustained anomaly burst
# is one alert; the rule re-arms after a quiet check). The keys are the
# trainer's _train_scalars sentinel snapshot
# (dlti_tpu.training.sentinel.NumericSentinel.scalars / SDCProbe.scalars).
_SENTINEL_RULES = (
    ("nonfinite_step", ("sentinel_nonfinite_steps",)),
    ("loss_spike", ("sentinel_loss_spikes", "sentinel_grad_spikes")),
    ("sdc_mismatch", ("sdc_mismatches",)),
)

ACTIONS = ("log", "dump", "abort")

# Exit code of the `abort` escalation (CI chaos runs assert on it; chosen
# clear of shell/signal codes).
ABORT_EXIT_CODE = 86

# Throughput series the collapse rule auto-watches: (name, is_counter).
_THROUGHPUT_SERIES = (
    ("train_tokens_per_s", False),
    ("generated_tokens", True),
)

# Counter names the shed-buildup rule sums (registry stats_dict keys; the
# reject counter carries per-reason labels, hence the prefix match).
_SHED_KEY_PREFIXES = ("dlti_gateway_shed_total", "dlti_gateway_rejected_total")

_CKPT_RETRY_KEYS = ("ckpt_save_retries", "dlti_ckpt_save_retries")

# disk_pressure inputs: the trainer's scalar source exposes the bare
# names (durable_io.scalars); the serving registry exposes the dlti_*
# metrics, path_class-labeled — hence prefix sums for the labeled pair.
_DISK_FREE_KEYS = ("disk_free_bytes", "dlti_disk_free_bytes")
_DISK_ERROR_KEY_PREFIXES = ("disk_write_errors",
                            "dlti_disk_write_errors_total")
_DISK_DEGRADED_KEY_PREFIXES = ("disk_degraded", "dlti_disk_degraded")


# ----------------------------------------------------------------------
# Module-level event log: structured non-alert events (the flight
# recorder's dump_failed, future maintenance events) append to the same
# JSONL file the alerts go to. The trainer/server watchdog installs its
# alert_log_path here at construction.
# ----------------------------------------------------------------------
_EVENT_LOG_PATH = [""]


def set_event_log_path(path: Optional[str]) -> None:
    _EVENT_LOG_PATH[0] = path or ""


def log_event(record: dict) -> bool:
    """Append a structured event to the watchdog event log (best-effort,
    drop-and-count via the durable writer; False when unconfigured or
    the write was dropped)."""
    path = _EVENT_LOG_PATH[0]
    if not path:
        return False
    d = os.path.dirname(path)
    if d:
        try:
            os.makedirs(d, exist_ok=True)
        except OSError:
            return False
    return durable_io.append_line(path, json.dumps(record, default=str),
                                  path_class="watchdog")


class AnomalyWatchdog:
    """Rule engine over a sampler ring; see module docstring."""

    def __init__(self, cfg, sampler: TimeSeriesSampler, *,
                 heartbeat=None, tracer=None, slo=None,
                 on_dump: Optional[Callable[[dict], Optional[str]]] = None,
                 clock: Callable[[], float] = time.monotonic):
        if cfg.action not in ACTIONS:
            raise ValueError(f"watchdog action must be one of {ACTIONS}, "
                             f"got {cfg.action!r}")
        self.cfg = cfg
        self.sampler = sampler
        self.heartbeat = heartbeat
        # SLO tracker (telemetry.slo.SLOTracker) for the slo_burn rule;
        # None = rule dormant.
        self.slo = slo
        self.logger = get_logger()
        self._tracer = tracer if tracer is not None else get_tracer()
        self._on_dump = on_dump
        self._clock = clock
        self._lock = threading.Lock()
        # Step-completion signal (trainer pushes; serving runs without it).
        self._last_step: Optional[int] = None
        self._last_step_t: Optional[float] = None
        self._step_durations: deque = deque(maxlen=32)
        # Edge-trigger state: condition keys currently firing.
        self._active: set = set()
        # Sentinel-counter watermarks: value at the previous check, per
        # rule (first sighting initializes without firing, so a resumed
        # run's nonzero counters don't alert spuriously).
        self._watermarks: dict = {}
        self.alerts: deque = deque(maxlen=256)  # recent alerts (forensics)
        self._last_dump_t = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if getattr(cfg, "alert_log_path", ""):
            set_event_log_path(cfg.alert_log_path)

    # -- push signals ---------------------------------------------------
    def notify_step(self, step: int) -> None:
        """Step-completion heartbeat from the training loop (call once per
        optimizer step, AFTER it completed)."""
        now = self._clock()
        with self._lock:
            if self._last_step_t is not None:
                self._step_durations.append(max(1e-6, now - self._last_step_t))
            self._last_step = int(step)
            self._last_step_t = now
            self._active.discard("hung_step")  # progress re-arms the rule

    # -- rule evaluation ------------------------------------------------
    def hung_step_deadline_s(self) -> float:
        """The current hang deadline: ``k x rolling-median step time``,
        floored at ``hung_step_min_s`` so cold-start compiles and the
        first few (unmeasured) steps never false-positive."""
        with self._lock:
            durs = list(self._step_durations)
        med = statistics.median(durs) if durs else 0.0
        return max(self.cfg.hung_step_min_s, self.cfg.hung_step_factor * med)

    def check_now(self, now: Optional[float] = None) -> List[dict]:
        """Run every rule once; returns the alerts fired by this check
        (already emitted/escalated). The background thread calls this at
        ``interval_s``; tests call it directly."""
        now = self._clock() if now is None else now
        fired: List[dict] = []

        # hung_step ----------------------------------------------------
        with self._lock:
            last_t, last_step = self._last_step_t, self._last_step
        if last_t is not None:
            deadline = self.hung_step_deadline_s()
            stalled = now - last_t
            if stalled > deadline:
                a = self._fire("hung_step", "hung_step",
                               f"no step completed for {stalled:.1f}s "
                               f"(deadline {deadline:.1f}s, last step "
                               f"{last_step})",
                               last_step=last_step,
                               stalled_s=round(stalled, 3),
                               deadline_s=round(deadline, 3))
                if a:
                    fired.append(a)
            # (re-arming happens in notify_step, not on condition clear:
            # only real progress should silence a hang alert.)

        # throughput_collapse ------------------------------------------
        for name, is_counter in self._throughput_series():
            vals = self._throughput_points(name, is_counter)
            key = f"throughput_collapse:{name}"
            if len(vals) >= self.cfg.throughput_min_samples:
                med = statistics.median(vals[:-1])
                latest = vals[-1]
                floor = self.cfg.throughput_floor_frac * med
                if med > 0 and latest < floor:
                    a = self._fire("throughput_collapse", key,
                                   f"{name} collapsed to {latest:.2f} "
                                   f"(rolling median {med:.2f}, floor "
                                   f"{floor:.2f})",
                                   series=name, latest=round(latest, 4),
                                   median=round(med, 4))
                    if a:
                        fired.append(a)
                else:
                    self._active.discard(key)

        # queue_buildup ------------------------------------------------
        if self.cfg.queue_depth_limit > 0:
            pts = [v for _, v in
                   self.sampler.series("gateway_queue_depth")][-3:]
            if len(pts) == 3 and min(pts) >= self.cfg.queue_depth_limit:
                a = self._fire("queue_buildup", "queue_buildup",
                               f"gateway queue depth >= "
                               f"{self.cfg.queue_depth_limit} for 3 "
                               f"samples (latest {pts[-1]:.0f})",
                               depth=pts[-1])
                if a:
                    fired.append(a)
            elif pts and pts[-1] < self.cfg.queue_depth_limit:
                self._active.discard("queue_buildup")

        # shed_buildup -------------------------------------------------
        if self.cfg.shed_rate_limit > 0:
            latest = self.sampler.latest()
            keys = [k for k in (latest or {}).get("values", {})
                    if k.startswith(_SHED_KEY_PREFIXES)]
            rate = sum(self.sampler.rate(k, window_s=30.0) or 0.0
                       for k in keys)
            if rate > self.cfg.shed_rate_limit:
                a = self._fire("shed_buildup", "shed_buildup",
                               f"gateway shedding {rate:.2f} req/s "
                               f"(limit {self.cfg.shed_rate_limit:g})",
                               shed_per_s=round(rate, 3))
                if a:
                    fired.append(a)
            else:
                self._active.discard("shed_buildup")

        # heartbeat_stale ----------------------------------------------
        if self.cfg.heartbeat_stale_s > 0 and self.heartbeat is not None:
            wall = time.time()
            stale = {p: wall - t for p, (_, t)
                     in self.heartbeat.last_seen.items()
                     if wall - t > self.cfg.heartbeat_stale_s}
            if stale:
                a = self._fire("heartbeat_stale", "heartbeat_stale",
                               f"process(es) silent past "
                               f"{self.cfg.heartbeat_stale_s:g}s: " +
                               ", ".join(f"proc {p}: {s:.0f}s"
                                         for p, s in sorted(stale.items())),
                               stale={str(p): round(s, 1)
                                      for p, s in stale.items()})
                if a:
                    fired.append(a)
            else:
                self._active.discard("heartbeat_stale")

        # ckpt_retry_storm ---------------------------------------------
        if self.cfg.ckpt_retry_limit > 0:
            for key in _CKPT_RETRY_KEYS:
                pts = [v for _, v in self.sampler.series(key)]
                if len(pts) < 2:
                    continue
                grew = pts[-1] - pts[0]
                if grew >= self.cfg.ckpt_retry_limit:
                    a = self._fire("ckpt_retry_storm", "ckpt_retry_storm",
                                   f"checkpoint save retried {grew:.0f}x "
                                   f"within the ring window",
                                   retries=grew)
                    if a:
                        fired.append(a)
                else:
                    self._active.discard("ckpt_retry_storm")
                break

        # goodput_collapse ---------------------------------------------
        floor_frac = getattr(self.cfg, "goodput_floor_frac", 0.0)
        if floor_frac > 0:
            vals = [v for _, v in self.sampler.series("goodput_fraction")]
            min_n = max(2, getattr(self.cfg, "goodput_min_samples", 8))
            if len(vals) >= min_n:
                med = statistics.median(vals[:-1])
                latest = vals[-1]
                if med > 0 and latest < floor_frac * med:
                    a = self._fire("goodput_collapse", "goodput_collapse",
                                   f"goodput fraction collapsed to "
                                   f"{latest:.3f} (rolling median "
                                   f"{med:.3f}, floor "
                                   f"{floor_frac * med:.3f}) — recovery "
                                   f"work is eating the wall clock",
                                   latest=round(latest, 4),
                                   median=round(med, 4))
                    if a:
                        fired.append(a)
                else:
                    self._active.discard("goodput_collapse")

        # hbm_pressure -------------------------------------------------
        headroom_floor = getattr(self.cfg, "hbm_headroom_floor_frac", 0.0)
        if headroom_floor > 0:
            pts = [v for _, v in self.sampler.series("hbm_headroom_frac")]
            if pts:
                latest = pts[-1]
                if latest < headroom_floor:
                    a = self._fire("hbm_pressure", "hbm_pressure",
                                   f"HBM headroom down to "
                                   f"{latest * 100:.1f}% of capacity "
                                   f"(floor {headroom_floor * 100:g}%) — "
                                   f"the next large allocation may OOM",
                                   headroom_frac=round(latest, 4),
                                   floor_frac=headroom_floor)
                    if a:
                        fired.append(a)
                else:
                    self._active.discard("hbm_pressure")

        # disk_pressure ------------------------------------------------
        latest = (self.sampler.latest() or {}).get("values", {})
        free = next((float(latest[k]) for k in _DISK_FREE_KEYS
                     if k in latest), None)
        floor_bytes = getattr(self.cfg, "disk_free_floor_bytes", 0)
        if floor_bytes > 0 and free is not None:
            if free < floor_bytes:
                a = self._fire("disk_pressure", "disk_pressure:free",
                               f"free disk down to {free / 1e9:.2f} GB "
                               f"(floor {floor_bytes / 1e9:.2f} GB) — the "
                               f"next save may hit ENOSPC",
                               free_bytes=free, floor_bytes=floor_bytes)
                if a:
                    fired.append(a)
            else:
                self._active.discard("disk_pressure:free")
        err_keys = [k for k in latest
                    if k.startswith(_DISK_ERROR_KEY_PREFIXES)]
        if err_keys:
            errs = sum(float(latest[k]) for k in err_keys)
            prev = self._watermarks.get("disk_pressure:errors")
            self._watermarks["disk_pressure:errors"] = errs
            if prev is not None and errs > prev:
                a = self._fire("disk_pressure", "disk_pressure:errors",
                               f"persistence write errors grew "
                               f"{errs - prev:.0f} since last check "
                               f"(now {errs:.0f})",
                               grew=errs - prev, total=errs)
                if a:
                    fired.append(a)
            elif prev is not None:
                self._active.discard("disk_pressure:errors")
        deg_keys = [k for k in latest
                    if k.startswith(_DISK_DEGRADED_KEY_PREFIXES)]
        if deg_keys:
            degraded = sum(float(latest[k]) for k in deg_keys)
            if degraded > 0:
                a = self._fire("disk_pressure", "disk_pressure:degraded",
                               f"{degraded:.0f} path class(es) degraded — "
                               f"writes being skipped/dropped",
                               degraded=degraded)
                if a:
                    fired.append(a)
            else:
                self._active.discard("disk_pressure:degraded")

        # sentinel rules: nonfinite_step / loss_spike / sdc_mismatch ---
        for rule, keys in _SENTINEL_RULES:
            present = [k for k in keys if k in latest]
            if not present:
                continue
            total = sum(float(latest[k]) for k in present)
            prev = self._watermarks.get(rule)
            self._watermarks[rule] = total
            if prev is None:
                continue
            if total > prev:
                a = self._fire(rule, rule,
                               f"{rule}: sentinel counter(s) "
                               f"{'+'.join(present)} grew "
                               f"{total - prev:.0f} since last check "
                               f"(now {total:.0f})",
                               grew=total - prev, total=total)
                if a:
                    fired.append(a)
            else:
                self._active.discard(rule)

        # replica_flap: lifecycle flap breaker evicted a replica --------
        if getattr(self.cfg, "replica_flap_limit", 0) > 0:
            flap_keys = [k for k in latest
                         if k.startswith("dlti_replica_lifecycle_"
                                         "flaps_total")]
            if flap_keys:
                flaps = sum(float(latest[k]) for k in flap_keys)
                prev = self._watermarks.get("replica_flap")
                self._watermarks["replica_flap"] = flaps
                if prev is not None and flaps > prev:
                    a = self._fire(
                        "replica_flap", "replica_flap",
                        f"replica_flap: flap breaker permanently "
                        f"evicted a replica ({flaps - prev:.0f} new "
                        f"eviction(s), {flaps:.0f} total) — the fleet "
                        f"is down capacity until an operator acts",
                        grew=flaps - prev, total=flaps)
                    if a:
                        fired.append(a)
                elif prev is not None:
                    self._active.discard("replica_flap")

        # canary_regression: the deploy controller rolled a candidate
        # back — a training run shipped a checkpoint that failed live
        # canary gates, which a human should look at even though the
        # fleet protected itself.
        if getattr(self.cfg, "canary_regression_limit", 0) > 0:
            rb_keys = [k for k in latest
                       if k.startswith("dlti_deploy_rollbacks_total")]
            if rb_keys:
                rolls = sum(float(latest[k]) for k in rb_keys)
                prev = self._watermarks.get("canary_regression")
                self._watermarks["canary_regression"] = rolls
                if prev is not None and rolls > prev:
                    a = self._fire(
                        "canary_regression", "canary_regression",
                        f"canary_regression: deploy controller rolled "
                        f"back a candidate checkpoint "
                        f"({rolls - prev:.0f} new rollback(s), "
                        f"{rolls:.0f} total) — the incumbent still "
                        f"serves, but the training run is producing "
                        f"checkpoints that fail canary gates",
                        grew=rolls - prev, total=rolls)
                    if a:
                        fired.append(a)
                elif prev is not None:
                    self._active.discard("canary_regression")

        # slo_burn: an (objective, class) is burning its error budget --
        if self.slo is not None \
                and getattr(self.cfg, "slo_burn_limit", 1) > 0:
            try:
                burns = self.slo.active_burns(now)
            except Exception:
                burns = []
            burning_keys = set()
            for b in burns:
                key = f"slo_burn:{b['objective']}:{b['class']}"
                burning_keys.add(key)
                a = self._fire(
                    "slo_burn", key,
                    f"SLO {b['objective']} (class {b['class']}) burning "
                    f"{b['burn_long']:.1f}x over {b['long_s']:g}s / "
                    f"{b['burn_short']:.1f}x over {b['short_s']:g}s "
                    f"(tier {b['factor']:g}x) — "
                    f"{b['budget_remaining'] * 100:.1f}% of the error "
                    f"budget remains",
                    objective=b["objective"], cls=b["class"],
                    factor=b["factor"],
                    burn_long=b["burn_long"], burn_short=b["burn_short"],
                    budget_remaining=round(b["budget_remaining"], 4),
                    compliance=round(b["compliance"], 6))
                if a:
                    fired.append(a)
            # Re-arm every (objective, class) that stopped breaching.
            with self._lock:
                stale = [k for k in self._active
                         if k.startswith("slo_burn:")
                         and k not in burning_keys]
                for k in stale:
                    self._active.discard(k)
        return fired

    def _throughput_series(self):
        if self.cfg.throughput_series:
            # Explicit override: treated as a gauge series.
            return ((self.cfg.throughput_series, False),)
        return _THROUGHPUT_SERIES

    def _throughput_points(self, name: str, is_counter: bool) -> List[float]:
        pts = self.sampler.series(name)
        if not is_counter:
            return [v for _, v in pts]
        # Counter -> per-interval rates (consecutive deltas), clamped at 0.
        rates = []
        for (t0, v0), (t1, v1) in zip(pts, pts[1:]):
            if t1 > t0:
                rates.append(max(0.0, (v1 - v0) / (t1 - t0)))
        return rates

    # -- emission / escalation ------------------------------------------
    def _fire(self, rule: str, key: str, message: str,
              **data) -> Optional[dict]:
        """Emit iff ``key``'s condition is newly true (edge trigger)."""
        with self._lock:
            if key in self._active:
                return None
            self._active.add(key)
        alert = {"wall": time.time(), "rule": rule, "message": message,
                 "action": self.cfg.action, **data}
        self.alerts.append(alert)
        alerts_total.labels(rule=rule).inc()
        self._tracer.instant("watchdog/alert", cat="watchdog", rule=rule,
                             message=message)
        self.logger.warning("watchdog alert [%s]: %s", rule, message)
        if self.cfg.alert_log_path:
            try:
                d = os.path.dirname(self.cfg.alert_log_path)
                if d:
                    os.makedirs(d, exist_ok=True)
                durable_io.append_line(self.cfg.alert_log_path,
                                       json.dumps(alert),
                                       path_class="watchdog")
            except OSError:
                self.logger.exception("watchdog alert log write failed")
        try:
            # Elastic supervision: mirror the alert into the supervisor's
            # rendezvous dir (no-op outside an elastic launch). Rank 0's
            # heartbeat_stale alerts name the straggling processes — the
            # supervisor turns that aggregated view into a TARGETED kill
            # + mesh reshape instead of this process's own whole-job
            # log/dump/abort ladder.
            from dlti_tpu.training.elastic import mirror_alert

            mirror_alert(alert)
        except Exception:
            pass
        self._escalate(alert)
        return alert

    def _dump(self, alert: dict) -> None:
        now = self._clock()
        if now - self._last_dump_t < 30.0:  # dump-storm throttle
            return
        self._last_dump_t = now
        try:
            if self._on_dump is not None:
                self._on_dump(alert)
            else:
                from dlti_tpu.telemetry.flightrecorder import get_recorder

                rec = get_recorder()
                if rec is not None:
                    rec.dump(reason=f"watchdog:{alert['rule']}",
                             extra={"alert": alert})
        except Exception:
            self.logger.exception("watchdog flight-record dump failed")

    def _escalate(self, alert: dict) -> None:
        if self.cfg.action == "log":
            return
        self._dump(alert)
        if self.cfg.action == "abort":
            # CI chaos runs: fail the job NOW rather than hang to the
            # harness timeout. SIGTERM first gives the trainer its
            # preemption-checkpoint path; the hard exit backstops a
            # process too wedged to honor it.
            self.logger.error(
                "watchdog abort escalation [%s]; sending SIGTERM then "
                "exiting %d", alert["rule"], ABORT_EXIT_CODE)
            try:
                os.kill(os.getpid(), _signal.SIGTERM)
                time.sleep(min(10.0, 2 * self.cfg.hung_step_min_s))
            finally:
                os._exit(ABORT_EXIT_CODE)

    # -- counters for reports -------------------------------------------
    def alert_counts(self) -> dict:
        """{rule: count} over this watchdog's lifetime (for bench/loadgen
        result JSON)."""
        out: dict = {}
        for a in self.alerts:
            out[a["rule"]] = out.get(a["rule"], 0) + 1
        return out

    # -- lifecycle ------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="dlti-watchdog")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2 * self.cfg.interval_s + 1)

    def _run(self) -> None:
        while not self._stop.wait(self.cfg.interval_s):
            try:
                self.check_now()
            except Exception:
                # The watchdog must never kill the thing it watches.
                self.logger.exception("watchdog check failed")
