"""Low-overhead host-side span tracer with Chrome-trace-event export.

The host-side complement to ``jax.profiler`` (which sees device ops but not
the scheduler): spans cover the *host* phases of a training step (batch
fetch, host→device transfer, compiled-step dispatch, device sync, eval,
checkpoint save) and of a request's life in the serving engine (queued →
prefill → decode). Export is the Chrome trace-event JSON format
(``{"traceEvents": [...]}``), viewable in Perfetto (https://ui.perfetto.dev)
or ``chrome://tracing``.

Design constraints:

* **Near-zero cost when disabled.** ``span()`` on a disabled tracer is one
  attribute read + returning a shared no-op context manager — no dict, no
  clock read, no lock. This is what makes it safe to leave instrumentation
  in the engine's per-step path unconditionally (guarded by the overhead
  smoke in ``tests/test_telemetry.py``).
* **Bounded memory.** Events land in a ring buffer (``deque(maxlen=...)``);
  a long-lived server keeps the most recent ``capacity`` events and never
  grows. Export is a snapshot of the ring.
* **Thread-safe.** Handler threads, the engine stepper, and the trainer all
  append under one lock; ``ts`` comes from ``time.monotonic()`` so all
  threads share a clock.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Optional


class _NullSpan:
    """Shared no-op context manager returned by a disabled tracer."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tracer", "_name", "_cat", "_args", "_t0")

    def __init__(self, tracer: "SpanTracer", name: str, cat: str, args: dict):
        self._tracer = tracer
        self._name = name
        self._cat = cat
        self._args = args

    def __enter__(self):
        self._t0 = time.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb):
        self._tracer._complete_event(
            self._name, self._t0, time.monotonic(), self._cat,
            threading.get_ident(), self._args)
        return False


class SpanTracer:
    """Ring-buffered span tracer emitting Chrome trace events."""

    def __init__(self, capacity: int = 65536, enabled: bool = False):
        self.enabled = enabled
        self.capacity = capacity
        self._events: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._pid = os.getpid()
        # Events silently evicted by the ring since process start. The ring
        # overwriting oldest-first is the design — but forensics consumers
        # (flight-record dumps, /debug/trace) must be able to tell "this is
        # the whole story" from "this is the most recent window of a longer
        # one", so truncation is counted, never silent.
        self._dropped = 0
        # Total events ever appended — the cursor axis for events_since()
        # (fleet workers ship ring tails incrementally in step/health
        # replies; the cursor survives ring eviction because it counts
        # appends, not positions).
        self._total = 0
        # Optional human label for this process's Perfetto row; when set,
        # exports prepend a "ph":"M" process_name metadata event so a
        # merged multi-process timeline renders one named row per source
        # instead of collapsing everything into anonymous pids.
        self.process_label: Optional[str] = None

    # -- recording ------------------------------------------------------
    def span(self, name: str, cat: str = "host", **args):
        """Context manager timing a host phase. Disabled: a shared no-op."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, cat, args)

    def complete(self, name: str, start_s: float, end_s: float,
                 cat: str = "host", tid: Optional[int] = None,
                 **args) -> None:
        """Record an already-measured span (``time.monotonic`` seconds) —
        how request-lifecycle phases are emitted after the fact from the
        timestamps the engine keeps on each :class:`Request`."""
        if not self.enabled:
            return
        self._complete_event(name, start_s, end_s, cat,
                             tid if tid is not None else threading.get_ident(),
                             args)

    def instant(self, name: str, cat: str = "host",
                tid: Optional[int] = None, **args) -> None:
        if not self.enabled:
            return
        ev = {"ph": "i", "name": name, "cat": cat, "s": "t",
              "ts": time.monotonic() * 1e6, "pid": self._pid,
              "tid": (tid if tid is not None else threading.get_ident())
              & 0x7FFFFFFF}
        if args:
            ev["args"] = args
        self._append(ev)

    def _complete_event(self, name, start_s, end_s, cat, tid, args) -> None:
        ev = {"ph": "X", "name": name, "cat": cat,
              "ts": start_s * 1e6, "dur": max(0.0, (end_s - start_s) * 1e6),
              "pid": self._pid, "tid": tid & 0x7FFFFFFF}
        if args:
            ev["args"] = args
        self._append(ev)

    def _append(self, ev: dict) -> None:
        with self._lock:
            if (self._events.maxlen is not None
                    and len(self._events) == self._events.maxlen):
                self._dropped += 1
            self._events.append(ev)
            self._total += 1

    # -- inspection / export --------------------------------------------
    @property
    def dropped_events(self) -> int:
        """Events evicted by the ring since process start (monotonic —
        ``clear()`` does not reset it; it feeds a /metrics counter)."""
        with self._lock:
            return self._dropped

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def events(self) -> list:
        with self._lock:
            return list(self._events)

    @property
    def total_events(self) -> int:
        """Events ever appended (cursor axis for :meth:`events_since`)."""
        with self._lock:
            return self._total

    def events_since(self, cursor: int, limit: int = 512) -> tuple:
        """Incremental tail read: everything appended after ``cursor``
        (a previous return value; start at 0), oldest first, capped at
        ``limit`` per call. Returns ``(events, dropped, new_cursor)``
        where ``dropped`` counts events that were appended after the
        cursor but already evicted by the ring — shipped as a count so
        the consumer's truncation accounting stays honest."""
        with self._lock:
            unshipped = max(0, self._total - max(0, cursor))
            avail = len(self._events)
            dropped = max(0, unshipped - avail)
            take = min(unshipped - dropped, max(0, limit))
            start = avail - (unshipped - dropped)
            evs = [self._events[i] for i in range(start, start + take)]
            return evs, dropped, self._total - (unshipped - dropped - take)

    def metadata_events(self) -> list:
        """``"ph":"M"`` process_name metadata for this process's row
        (empty unless :attr:`process_label` is set)."""
        if not self.process_label:
            return []
        # ts is meaningless on metadata events but present so every
        # exported event satisfies the {ph, ts, name} schema consumers pin.
        return [{"ph": "M", "name": "process_name", "cat": "__meta",
                 "ts": 0.0, "pid": self._pid, "tid": 0,
                 "args": {"name": self.process_label}}]

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def to_dict(self) -> dict:
        # droppedEvents is an extra top-level key: Perfetto/chrome://tracing
        # ignore unknown keys, while forensics consumers (flight records,
        # /debug/trace readers) use it to see whether the window truncated.
        return {"traceEvents": self.metadata_events() + self.events(),
                "displayTimeUnit": "ms",
                "droppedEvents": self.dropped_events}

    def export(self, path: str) -> str:
        """Write the ring snapshot as Chrome-trace JSON; returns ``path``.
        Open the file in Perfetto (ui.perfetto.dev) or chrome://tracing."""
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_dict(), f)
        os.replace(tmp, path)
        return path


# ----------------------------------------------------------------------
# Process-global tracer: the engine, server, and trainer all record into
# one timeline so a combined trace shows scheduler + request interleaving.
# Disabled by default — entry points enable it from config/CLI flags.
# ----------------------------------------------------------------------
_GLOBAL = SpanTracer()


def get_tracer() -> SpanTracer:
    return _GLOBAL


def configure_tracer(enabled: Optional[bool] = None,
                     capacity: Optional[int] = None) -> SpanTracer:
    """Enable/resize the process-global tracer (idempotent)."""
    t = _GLOBAL
    if capacity is not None and capacity != t.capacity:
        with t._lock:
            t.capacity = capacity
            t._dropped += max(0, len(t._events) - capacity)
            t._events = deque(t._events, maxlen=capacity)
    if enabled is not None:
        t.enabled = enabled
    return t
