"""Declarative SLO engine: objectives, error budgets, burn-rate alerts.

The ledgers (PRs 1/5/9/10) measure everything — request-lifecycle
histograms, gateway admission counters, the goodput ledger — but nothing
declares what *good* looks like. This module closes that gap with the
standard SRE machinery, computed entirely from SLIs the fleet already
collects (**no new hot-path instrumentation**: every objective is a
closure over an existing histogram snapshot, counter family, or ledger
fraction):

* :class:`Objective` — one declared target over an existing SLI. Two
  kinds: ``events`` (a cumulative good/total counter pair, e.g. "TTFT
  ≤ 250 ms for 99% of requests", "99.9% of admissions succeed per
  class") and ``time`` (an instantaneous value integrated against a
  floor, e.g. "goodput fraction ≥ 0.85 for 99% of wall time").
* :class:`SLOTracker` — rolling windowed compliance and error-budget
  accounting per (objective, tenant-class), plus **multi-window
  multi-burn-rate** alerting: each tier is ``factor:long_s:short_s``
  (SRE-style fast+slow pairs — the long window proves the burn is
  sustained, the short window proves it is *still* happening, so a
  recovered incident stops paging immediately). Timescales are plain
  seconds, so a drill can shrink an "hour" to 30 s.
* Burn rate is ``(bad/total over window) / (1 - target)`` — 1.0 means
  the budget spends exactly at the sustainable rate, ``f`` means the
  window's budget is gone in ``1/f`` of the budget window.

Latency objectives snap their threshold to the largest histogram bucket
bound ≤ the requested threshold: the server classifies with cumulative
bucket counts and an external client (loadgen's ``LoadReport.slo``) can
classify raw samples with the *identical* cut, so the two views agree
exactly modulo requests in flight at scrape time.

Surfaces: ``dlti_slo_*`` gauges (pinned in ``SLO_METRIC_NAMES``),
``GET /debug/slo``, the ``slo_burn`` watchdog rule (via
:meth:`SLOTracker.active_burns`), a ``/dashboard`` ring via
:meth:`SLOTracker.scalars`, and ``slo.json`` in every flight dump (via
:meth:`SLOTracker.to_dict`).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from dlti_tpu.telemetry.registry import Gauge

# Name-stability contract (pinned in tests/test_bench_contract.py).
SLO_METRIC_NAMES = (
    "dlti_slo_compliance",
    "dlti_slo_error_budget_remaining",
    "dlti_slo_burn_rate",
)

compliance_gauge = Gauge(
    SLO_METRIC_NAMES[0],
    help="windowed SLI compliance per (objective, class), 0..1")
budget_remaining_gauge = Gauge(
    SLO_METRIC_NAMES[1],
    help="fraction of the error budget left in the rolling window, 0..1")
burn_rate_gauge = Gauge(
    SLO_METRIC_NAMES[2],
    help="error-budget burn rate per (objective, class, window); "
         "1.0 = spending exactly at the sustainable rate")

# Default multi-window multi-burn-rate tiers (factor:long_s:short_s).
# The classic SRE page/ticket split scaled to a 1 h budget window:
# 14x over 1 min (confirmed by 5 s) pages, 6x over 5 min tickets.
DEFAULT_BURN_TIERS = "14:60:5,6:300:30"


def parse_burn_tiers(spec: str) -> Tuple[Tuple[float, float, float], ...]:
    """``"14:60:5,6:300:30"`` → ``((14, 60, 5), (6, 300, 30))``.

    Each tier is ``factor:long_window_s:short_window_s``; a tier fires
    only when the burn rate exceeds ``factor`` over BOTH windows. Raises
    ``ValueError`` on malformed tiers (factor ≤ 0, short ≥ long)."""
    tiers: List[Tuple[float, float, float]] = []
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        bits = part.split(":")
        if len(bits) != 3:
            raise ValueError(f"burn tier {part!r}: want factor:long_s:short_s")
        factor, long_s, short_s = (float(b) for b in bits)
        if factor <= 0 or long_s <= 0 or short_s <= 0:
            raise ValueError(f"burn tier {part!r}: all fields must be > 0")
        if short_s >= long_s:
            raise ValueError(
                f"burn tier {part!r}: short window must be < long window")
        tiers.append((factor, long_s, short_s))
    return tuple(tiers)


def _fmt_window(w: float) -> str:
    return f"{format(w, 'g')}s"


@dataclass
class Objective:
    """One declared target over an existing SLI.

    ``events`` kind: ``counts_fn`` returns the cumulative ``(good,
    total)`` event counts since process start; the tracker differences
    them over its windows. ``time`` kind: ``value_fn`` returns the
    instantaneous SLI and the tracker integrates wall time, counting a
    second as *good* while the value sits at/above ``floor``.
    """

    name: str
    target: float                                    # e.g. 0.99
    cls: str = "all"                                 # tenant class label
    kind: str = "events"                             # "events" | "time"
    counts_fn: Optional[Callable[[], Tuple[float, float]]] = None
    value_fn: Optional[Callable[[], float]] = None
    floor: float = 0.0
    threshold_s: Optional[float] = None              # effective (snapped) cut
    description: str = ""

    def __post_init__(self):
        if not 0.0 < self.target < 1.0:
            raise ValueError(
                f"objective {self.name!r}: target must be in (0, 1), "
                f"got {self.target} (a target of exactly 1.0 has a zero "
                f"error budget — burn rate is undefined)")
        if self.kind == "events" and self.counts_fn is None:
            raise ValueError(f"objective {self.name!r}: events kind "
                             f"needs counts_fn")
        if self.kind == "time" and self.value_fn is None:
            raise ValueError(f"objective {self.name!r}: time kind "
                             f"needs value_fn")
        if self.kind not in ("events", "time"):
            raise ValueError(f"objective {self.name!r}: unknown kind "
                             f"{self.kind!r}")

    @property
    def key(self) -> str:
        return f"{self.name}/{self.cls}"


class _ObjectiveState:
    """Per-objective cumulative sample ring + time-kind integrator."""

    __slots__ = ("samples", "good_cum", "total_cum", "last_t", "last_value")

    def __init__(self):
        # (t, good_cum, total_cum); the first sample is the zero point —
        # history that predates the tracker never counts against it.
        self.samples: deque = deque()
        self.good_cum = 0.0     # time-kind integrators
        self.total_cum = 0.0
        self.last_t: Optional[float] = None
        self.last_value: Optional[float] = None


class SLOTracker:
    """Rolling error-budget accounting + burn-rate evaluation.

    Pull-driven and thread-safe: the time-series sampler pulls
    :meth:`scalars` every interval, the watchdog pulls
    :meth:`active_burns` every check, HTTP handlers pull
    :meth:`to_dict` — each pull re-evaluates against ``clock()``. No
    thread of its own, nothing on any hot path.
    """

    def __init__(self, objectives: Sequence[Objective] = (), *,
                 window_s: float = 3600.0,
                 burn_tiers: str = DEFAULT_BURN_TIERS,
                 clock: Callable[[], float] = time.monotonic):
        self.objectives: List[Objective] = list(objectives)
        self.window_s = max(1.0, float(window_s))
        self.tiers = parse_burn_tiers(burn_tiers) \
            if isinstance(burn_tiers, str) else tuple(burn_tiers)
        self.clock = clock
        self._lock = threading.Lock()
        self._states: Dict[str, _ObjectiveState] = {}
        self._last: Dict[str, dict] = {}
        horizon = self.window_s
        for _, long_s, _ in self.tiers:
            horizon = max(horizon, long_s)
        self._horizon = horizon * 1.25 + 10.0

    def add_objective(self, obj: Objective) -> None:
        with self._lock:
            self.objectives.append(obj)

    # -- evaluation -----------------------------------------------------
    def _sample(self, obj: Objective, st: _ObjectiveState,
                now: float) -> None:
        if obj.kind == "events":
            good, total = obj.counts_fn()
            st.samples.append((now, float(good), float(total)))
        else:
            value = float(obj.value_fn())
            if st.last_t is not None:
                dt = max(0.0, now - st.last_t)
                st.total_cum += dt
                # Left Riemann: the interval just elapsed is judged by
                # the value that held at its start.
                if (st.last_value or 0.0) >= obj.floor:
                    st.good_cum += dt
            st.last_t, st.last_value = now, value
            st.samples.append((now, st.good_cum, st.total_cum))
        while len(st.samples) > 2 and st.samples[1][0] < now - self._horizon:
            st.samples.popleft()

    @staticmethod
    def _windowed(st: _ObjectiveState, now: float,
                  window: float) -> Tuple[float, float]:
        """(good, total) deltas over the trailing window.

        Baseline = the latest sample at/older than the window edge; with
        no sample that old yet, the first sample is the zero point (a
        young tracker reports over its own lifetime, never over history
        it did not witness). Deltas clamp at 0 so a counter reset reads
        as quiet, not negative."""
        if not st.samples:
            return 0.0, 0.0
        edge = now - window
        base = st.samples[0]
        for s in st.samples:
            if s[0] <= edge:
                base = s
            else:
                break
        last = st.samples[-1]
        return (max(0.0, last[1] - base[1]), max(0.0, last[2] - base[2]))

    def _burn(self, obj: Objective, st: _ObjectiveState, now: float,
              window: float) -> float:
        good, total = self._windowed(st, now, window)
        if total <= 0:
            return 0.0
        bad_frac = (total - good) / total
        return bad_frac / max(1e-9, 1.0 - obj.target)

    def evaluate(self, now: Optional[float] = None) -> Dict[str, dict]:
        """Sample every objective, recompute windows, update gauges;
        returns ``{objective_key: state}`` (also kept for re-reads)."""
        with self._lock:
            now = self.clock() if now is None else now
            out: Dict[str, dict] = {}
            windows = sorted({w for _, long_s, short_s in self.tiers
                              for w in (long_s, short_s)})
            for obj in self.objectives:
                st = self._states.setdefault(obj.key, _ObjectiveState())
                self._sample(obj, st, now)
                good, total = self._windowed(st, now, self.window_s)
                bad = max(0.0, total - good)
                compliance = 1.0 if total <= 0 else good / total
                allowed = (1.0 - obj.target) * total
                if allowed <= 0:
                    remaining = 1.0 if bad <= 0 else 0.0
                else:
                    remaining = max(0.0, 1.0 - bad / allowed)
                burns = {_fmt_window(w): self._burn(obj, st, now, w)
                         for w in windows}
                burning = []
                for factor, long_s, short_s in self.tiers:
                    b_long = burns[_fmt_window(long_s)]
                    b_short = burns[_fmt_window(short_s)]
                    if b_long >= factor and b_short >= factor:
                        burning.append({
                            "factor": factor, "long_s": long_s,
                            "short_s": short_s, "burn_long": round(b_long, 3),
                            "burn_short": round(b_short, 3),
                        })
                state = {
                    "objective": obj.name, "class": obj.cls,
                    "kind": obj.kind, "target": obj.target,
                    "threshold_s": obj.threshold_s,
                    "description": obj.description,
                    "window_s": self.window_s,
                    "good": good, "bad": bad, "total": total,
                    "compliance": compliance,
                    "error_budget_remaining": remaining,
                    "burn_rates": burns,
                    "burning": burning,
                    "breaching": bool(burning),
                }
                out[obj.key] = state
                labels = {"objective": obj.name, "class": obj.cls}
                compliance_gauge.labels(**labels).set(compliance)
                budget_remaining_gauge.labels(**labels).set(remaining)
                for wname, b in burns.items():
                    burn_rate_gauge.labels(window=wname, **labels).set(b)
            self._last = out
            return out

    # -- consumers ------------------------------------------------------
    def active_burns(self, now: Optional[float] = None) -> List[dict]:
        """Currently-breaching (objective, class, tier) triples — the
        watchdog's ``slo_burn`` rule input. Re-evaluates first."""
        state = self.evaluate(now)
        out = []
        for key, s in state.items():
            for tier in s["burning"]:
                out.append({
                    "objective": s["objective"], "class": s["class"],
                    "budget_remaining": s["error_budget_remaining"],
                    "compliance": s["compliance"], **tier,
                })
        return out

    def scalars(self, now: Optional[float] = None) -> dict:
        """Flat numeric summary for the time-series ring / dashboard."""
        state = self.evaluate(now)
        if not state:
            return {"slo_objectives": 0}
        worst_burn = 0.0
        for s in state.values():
            for b in s["burn_rates"].values():
                worst_burn = max(worst_burn, b)
        return {
            "slo_objectives": len(state),
            "slo_breaching": sum(1 for s in state.values()
                                 if s["breaching"]),
            "slo_worst_burn_rate": round(worst_burn, 4),
            "slo_min_budget_remaining": round(
                min(s["error_budget_remaining"] for s in state.values()), 4),
            "slo_compliance": {k: round(s["compliance"], 6)
                               for k, s in state.items()},
            "slo_budget_remaining": {
                k: round(s["error_budget_remaining"], 4)
                for k, s in state.items()},
        }

    def to_dict(self, now: Optional[float] = None) -> dict:
        """The ``GET /debug/slo`` payload / flight-dump ``slo.json``."""
        state = self.evaluate(now)
        return {
            "window_s": self.window_s,
            "burn_tiers": [{"factor": f, "long_s": l, "short_s": s}
                           for f, l, s in self.tiers],
            "num_objectives": len(state),
            "breaching": sorted(k for k, s in state.items()
                                if s["breaching"]),
            "objectives": state,
        }


# ----------------------------------------------------------------------
# Objective builders over the SLIs the fleet already has.
# ----------------------------------------------------------------------

def snap_threshold(buckets: Sequence[float], threshold_s: float) -> float:
    """Largest histogram bucket bound ≤ the requested threshold (the
    smallest bound when the request undercuts them all): server-side
    cumulative bucket counts and client-side raw-sample cuts then
    classify with the identical boundary."""
    eligible = [b for b in buckets if b <= threshold_s]
    return eligible[-1] if eligible else buckets[0]


def histogram_objective(name: str, histogram, threshold_s: float,
                        target: float, cls: str = "all",
                        description: str = "") -> Objective:
    """Latency objective over a registry Histogram: good = observations
    ≤ the (bucket-snapped) threshold, total = all observations."""
    effective = snap_threshold(histogram.buckets, threshold_s)
    cut = histogram.buckets.index(effective)

    def counts() -> Tuple[float, float]:
        bucket_counts, _, total = histogram.snapshot()
        return float(sum(bucket_counts[:cut + 1])), float(total)

    return Objective(
        name=name, cls=cls, target=target, kind="events",
        counts_fn=counts, threshold_s=effective,
        description=description or
        f"{histogram.name} <= {format(effective, 'g')}s "
        f"for {target:.4g} of requests")


def _sum_counter_family(stats: dict, name: str, cls: str) -> float:
    """Sum every child of a labeled counter out of a ``stats_dict()``
    snapshot (keys are ``name`` or ``name{k="v",...}``), optionally
    restricted to one ``priority`` class."""
    total = 0.0
    for k, v in stats.items():
        if not k.startswith(name):
            continue
        rest = k[len(name):]
        if rest and not rest.startswith("{"):
            continue            # a different, longer metric name
        if cls != "all" and f'priority="{cls}"' not in rest:
            continue
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        total += v
    return total


def availability_objective(stats_fn: Callable[[], dict], target: float,
                           cls: str = "all") -> Objective:
    """Admission availability per tenant class from the gateway's
    counters: total = admitted + rejected, good = admitted − shed (an
    admitted-then-shed request broke its promise)."""

    def counts() -> Tuple[float, float]:
        stats = stats_fn()
        admitted = _sum_counter_family(
            stats, "dlti_gateway_admitted_total", cls)
        rejected = _sum_counter_family(
            stats, "dlti_gateway_rejected_total", cls)
        shed = _sum_counter_family(stats, "dlti_gateway_shed_total", cls)
        return max(0.0, admitted - shed), admitted + rejected

    return Objective(
        name="availability", cls=cls, target=target, kind="events",
        counts_fn=counts,
        description=f"admissions neither rejected nor shed "
                    f"for {target:.4g} of requests (class={cls})")


def goodput_objective(value_fn: Callable[[], float], floor: float,
                      target: float) -> Objective:
    """Training goodput-fraction objective: wall time counts as good
    while the ledger's instantaneous fraction sits at/above ``floor``."""
    return Objective(
        name="goodput", cls="all", target=target, kind="time",
        value_fn=value_fn, floor=floor,
        description=f"goodput_fraction >= {floor:.4g} "
                    f"for {target:.4g} of wall time")


def standard_objectives(cfg, *, telemetry=None,
                        stats_fn: Optional[Callable[[], dict]] = None,
                        goodput_fn: Optional[Callable[[], float]] = None,
                        classes: Sequence[str] = ()) -> List[Objective]:
    """The declarative config → objective list used by both entry points
    (serving wires telemetry + stats_fn; training wires goodput_fn). A
    zero threshold/target disables that objective family."""
    out: List[Objective] = []
    if telemetry is not None:
        for attr, label, threshold, target in (
                ("ttft", "ttft", cfg.ttft_threshold_s, cfg.ttft_target),
                ("tpot", "tpot", cfg.tpot_threshold_s, cfg.tpot_target),
                ("queue_time", "queue_delay",
                 cfg.queue_threshold_s, cfg.queue_target)):
            if threshold > 0 and target > 0:
                out.append(histogram_objective(
                    label, getattr(telemetry, attr), threshold, target))
    if stats_fn is not None and cfg.availability_target > 0:
        for cls in ("all",) + tuple(classes):
            out.append(availability_objective(
                stats_fn, cfg.availability_target, cls=cls))
    if goodput_fn is not None and cfg.goodput_floor > 0 \
            and cfg.goodput_target > 0:
        out.append(goodput_objective(goodput_fn, cfg.goodput_floor,
                                     cfg.goodput_target))
    return out


def build_tracker(cfg, **kwargs) -> Optional["SLOTracker"]:
    """``SLOConfig`` → tracker (None when disabled or no objective
    resolved — callers wire nothing rather than a dead engine)."""
    if not getattr(cfg, "enabled", False):
        return None
    objectives = standard_objectives(cfg, **{
        k: v for k, v in kwargs.items() if k != "clock"})
    if not objectives:
        return None
    return SLOTracker(objectives, window_s=cfg.window_s,
                      burn_tiers=cfg.burn_tiers or DEFAULT_BURN_TIERS,
                      clock=kwargs.get("clock", time.monotonic))
