"""Multi-host heartbeat: per-process last-seen step, aggregated at rank 0.

Straggler visibility for ``jax.distributed`` runs (the MegaScale
per-rank-instrumentation idea at its smallest useful size): every process
calls :meth:`Heartbeat.beat` at the same step cadence — it is a collective
(``process_allgather``) on multi-host meshes, so the call sites must be
step-synchronous, which the trainer's bookkeeping loop already is — and
rank 0 keeps a ``{process_index: (step, wall_time)}`` map it can expose as
labeled gauges (``dlti_heartbeat_last_step{process="N"}``) and turn into a
straggler report.

Single-process runs degrade to a local map update (no collective, no jax
import cost beyond the first call)."""

from __future__ import annotations

import time
from typing import Dict, Optional, Tuple

# Name-stability contract (pinned in tests/test_bench_contract.py).
HEARTBEAT_METRIC_NAMES = (
    "dlti_heartbeat_last_step",
    "dlti_heartbeat_lag_steps",
)


class Heartbeat:
    def __init__(self, registry=None):
        # process_index -> (last step, wall time it was reported)
        self.last_seen: Dict[int, Tuple[int, float]] = {}
        if registry is not None:
            self.register(registry)

    def register(self, registry) -> None:
        """Expose per-process last-seen steps + straggler lag as labeled
        gauges (``straggler_report`` was log-only before the lag gauge —
        dashboards could not plot which rank trails by how much)."""
        self._gauge = registry.gauge(
            HEARTBEAT_METRIC_NAMES[0],
            help="last training step each process reported (rank-0 view)")
        self._lag_gauge = registry.gauge(
            HEARTBEAT_METRIC_NAMES[1],
            help="steps each process trails the fleet head (0 = lockstep)")

    def beat(self, step: int) -> Dict[int, Tuple[int, float]]:
        """Report this process's step; COLLECTIVE on multi-host meshes
        (every process must call with the same cadence). Returns the
        rank-0 aggregated map (local map elsewhere)."""
        import jax

        now = time.time()
        if jax.process_count() == 1:
            self.last_seen[0] = (int(step), now)
        else:
            import numpy as np
            from jax.experimental import multihost_utils

            local = np.asarray([jax.process_index(), int(step)], np.int64)
            gathered = np.asarray(
                multihost_utils.process_allgather(local)).reshape(-1, 2)
            for proc, st in gathered:
                self.last_seen[int(proc)] = (int(st), now)
        gauge = getattr(self, "_gauge", None)
        if gauge is not None:
            for proc, (st, _) in self.last_seen.items():
                gauge.labels(process=str(proc)).set(st)
        lag_gauge = getattr(self, "_lag_gauge", None)
        if lag_gauge is not None:
            for proc, behind in self.lags().items():
                lag_gauge.labels(process=str(proc)).set(behind)
        return self.last_seen

    def lag(self) -> int:
        """Max step spread across processes (0 = all in lockstep)."""
        if not self.last_seen:
            return 0
        steps = [st for st, _ in self.last_seen.values()]
        return max(steps) - min(steps)

    def lags(self) -> Dict[int, int]:
        """Per-process steps behind the fleet head (0 for the head) —
        the gauge/``/debug/vars`` form of :meth:`straggler_report`."""
        if not self.last_seen:
            return {}
        head = max(st for st, _ in self.last_seen.values())
        return {p: head - st for p, (st, _) in self.last_seen.items()}

    def straggler_report(self) -> Optional[str]:
        """Human-readable lag summary, or None when in lockstep."""
        if self.lag() == 0:
            return None
        head = max(st for st, _ in self.last_seen.values())
        behind = {p: head - st for p, (st, _) in self.last_seen.items()
                  if st < head}
        parts = ", ".join(f"proc {p}: -{d}" for p, d in sorted(behind.items()))
        return f"stragglers behind step {head}: {parts}"
