"""Flight recorder: the black box dumped when something dies.

When a step hangs, a replica faults, or the process takes a fatal
exception, the evidence (recent spans, counters, the time-series tail,
what the run was *doing*) normally evaporates with the process. The
flight recorder makes that evidence survive: :meth:`FlightRecorder.dump`
atomically materializes a ``flight-<step|ts>/`` directory:

* ``context.json``    — why (reason, exception traceback, signal), when,
  and what was in flight: the live context dict components keep updated
  via :meth:`note` (current phase, last completed step, active request
  count, ...), plus the config fingerprint and recent watchdog alerts.
* ``spans.json``      — the last-N tracer events (Chrome-trace format,
  Perfetto-loadable as-is) with the ring's ``droppedEvents`` count, so a
  truncated window is self-announcing.
* ``metrics.json``    — a full snapshot of every registered metrics
  source at death.
* ``timeseries.json`` — the sampler ring tail (the minutes *leading up
  to* the event — the part a point-in-time snapshot can never give you).
* ``config.json``     — the full run config.
* ``memory.json``     — the memory ledger's full ownership map at death
  (per owner per device + untracked/residual reconciliation): the "where
  the memory went" evidence an OOM postmortem needs. Always present —
  ``{}`` when no memory source is wired.
* ``slo.json``        — the SLO tracker's full state at death (per-
  objective compliance, error budget remaining, burn rates per window,
  breaching tiers — ``telemetry.slo``): whether the process died *while
  already failing its users* reframes any incident. Always present —
  ``{}`` when no SLO source is wired.
* ``MANIFEST.json``   — per-file sizes + SHA-256, written last; the dump
  stages into a ``.tmp-`` dir and renames, so a dump directory that
  exists is complete (same discipline as the checkpoint store).

``scripts/postmortem.py`` renders a dump into a human-readable incident
summary. Wiring: the trainer's ``finally`` path, the serving stepper's
fault handler, :class:`~dlti_tpu.serving.replicas.ReplicatedEngine`
failover, the watchdog's ``dump``/``abort`` escalations, and the chaos
injectors' pre-fire hook (so even a ``--fault-inject-step N:kill``
SIGKILL leaves the black box behind).

A process-global recorder (:func:`install` / :func:`get_recorder`)
mirrors the tracer's pattern so far-apart components (engine fault path,
replica failover) can reach it without plumbing.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
import traceback
from typing import Callable, List, Optional

from dlti_tpu.telemetry.registry import Counter
from dlti_tpu.telemetry.tracer import SpanTracer, get_tracer
from dlti_tpu.utils import durable_io
from dlti_tpu.utils.logging import get_logger

# Name-stability contract (pinned in tests/test_bench_contract.py).
FLIGHT_METRIC_NAMES = ("dlti_flight_dumps_total",)

dumps_total = Counter(
    FLIGHT_METRIC_NAMES[0],
    help="flight-record dumps written, labeled by reason")

_PREFIX = "flight-"
_TMP = ".tmp-"
MANIFEST = "MANIFEST.json"
DUMP_FILES = ("context.json", "spans.json", "metrics.json",
              "timeseries.json", "config.json", "memory.json", "slo.json",
              "deploy.json")


def config_fingerprint(config) -> Optional[str]:
    """Stable digest of the run config (sorted-key JSON), so two dumps
    from 'the same' job are provably same-config or provably not."""
    if config is None:
        return None
    try:
        payload = config.to_json() if hasattr(config, "to_json") \
            else json.dumps(config, sort_keys=True, default=str)
        return hashlib.sha256(payload.encode()).hexdigest()[:16]
    except Exception:
        return None


class FlightRecorder:
    """Collects context continuously; writes the black box on demand."""

    def __init__(self, directory: str, *,
                 tracer: Optional[SpanTracer] = None,
                 sampler=None, config=None,
                 max_spans: int = 4096, timeseries_tail: int = 240,
                 keep: int = 8, min_interval_s: float = 5.0):
        self.directory = os.path.abspath(directory)
        self.tracer = tracer if tracer is not None else get_tracer()
        self.sampler = sampler
        self.config = config
        self.max_spans = max_spans
        self.timeseries_tail = timeseries_tail
        self.keep = keep
        self.min_interval_s = min_interval_s
        self.logger = get_logger()
        self._lock = threading.Lock()
        self._context: dict = {}
        self._metrics_sources: List[Callable[[], dict]] = []
        self._context_sources: List[Callable[[], dict]] = []
        self._memory_sources: List[Callable[[], dict]] = []
        self._slo_sources: List[Callable[[], dict]] = []
        self._deploy_sources: List[Callable[[], dict]] = []
        self._last_dump_t = 0.0
        self.last_dump_path: Optional[str] = None
        self.dump_failures = 0
        # Old dumps are the first thing to sacrifice under ENOSPC: any
        # durable write anywhere can rotate them down to the newest one.
        durable_io.register_reclaimer(
            f"flight-dumps:{self.directory}",
            lambda need: durable_io.sweep_oldest(
                self.directory, keep=1, bytes_needed=need))

    # -- live context ---------------------------------------------------
    def note(self, **kw) -> None:
        """Cheap context update (a dict merge under a lock): components
        call this as their state changes — ``note(phase="decode",
        step=123)`` — so a dump can say what was happening *at death*."""
        with self._lock:
            self._context.update(kw)

    def add_metrics_source(self, fn: Callable[[], dict]) -> None:
        """A callable snapshotted into ``metrics.json`` at dump time
        (e.g. ``registry.stats_dict`` or the trainer's live scalars)."""
        self._metrics_sources.append(fn)

    def add_context_source(self, fn: Callable[[], dict]) -> None:
        """A callable merged into ``context.json`` at dump time (e.g. the
        watchdog's recent-alerts tail)."""
        self._context_sources.append(fn)

    def add_memory_source(self, fn: Callable[[], dict]) -> None:
        """A callable snapshotted into ``memory.json`` at dump time
        (``MemoryLedger.to_dict`` — the full ownership map at death)."""
        self._memory_sources.append(fn)

    def add_slo_source(self, fn: Callable[[], dict]) -> None:
        """A callable snapshotted into ``slo.json`` at dump time
        (``SLOTracker.to_dict`` — compliance/budget/burn state at
        death)."""
        self._slo_sources.append(fn)

    def add_deploy_source(self, fn: Callable[[], dict]) -> None:
        """A callable snapshotted into ``deploy.json`` at dump time
        (``DeploymentController.to_dict`` — incumbent/candidate/refused
        state of the continuous-delivery pipeline at death)."""
        self._deploy_sources.append(fn)

    # -- the dump -------------------------------------------------------
    def dump(self, reason: str, exc: Optional[BaseException] = None,
             extra: Optional[dict] = None,
             force: bool = False) -> Optional[str]:
        """Write a complete ``flight-*/`` directory; returns its path.

        Never raises (a forensics failure must not mask the original
        fault) and throttles repeat dumps within ``min_interval_s``
        unless ``force`` — terminal paths (fatal exception, pre-kill
        chaos hook) pass ``force=True``.

        An ENOSPC is not silent: the recorder rotates its own oldest
        dumps (plus anything the durable writer's reclaimers free) and
        retries the write once; when it *still* can't land, a
        ``dump_failed`` event with the errno goes to the watchdog event
        log — a missing black box leaves a paper trail.
        """
        try:
            now = time.monotonic()
            with self._lock:
                if not force and now - self._last_dump_t < self.min_interval_s:
                    return None
                self._last_dump_t = now
                context = dict(self._context)
        except Exception:
            self.logger.exception("flight-record dump failed (reason=%s)",
                                  reason)
            return None
        last_err: Optional[BaseException] = None
        for retry in (False, True):
            try:
                if retry:
                    durable_io.sweep_oldest(self.directory, keep=1)
                return self._write(reason, exc, extra, context)
            except OSError as e:
                last_err = e
                if durable_io.classify_errno(e) != "reclaim":
                    break
            except Exception as e:
                last_err = e
                break
        self.dump_failures += 1
        code = getattr(last_err, "errno", None)
        self.logger.error("flight-record dump failed (reason=%s errno=%s): %s",
                          reason, code, last_err)
        self._log_dump_failed(reason, code, last_err)
        return None

    def _log_dump_failed(self, reason: str, code, err) -> None:
        """Record ``dump_failed`` in the watchdog event log (best-effort;
        lazy import — the watchdog imports us for its dump escalation)."""
        try:
            from dlti_tpu.telemetry import watchdog as _watchdog

            _watchdog.log_event({
                "event": "dump_failed", "reason": reason,
                "errno": code, "error": str(err),
                "directory": self.directory, "time": time.time(),
            })
        except Exception:
            pass

    def _write(self, reason, exc, extra, context) -> str:
        for fn in self._context_sources:
            try:
                context.update(fn())
            except Exception:
                context.setdefault("context_source_errors", 0)
                context["context_source_errors"] += 1
        metrics: dict = {}
        for fn in self._metrics_sources:
            try:
                metrics.update(fn())
            except Exception:
                metrics.setdefault("metrics_source_errors", 0)
                metrics["metrics_source_errors"] += 1
        # memory.json is ALWAYS written (verify_dump requires every
        # DUMP_FILES entry); {} when no ledger is wired. A snapshot
        # failure must not lose the dump — the OOM being dumped may be
        # exactly what makes allocation-side introspection fragile.
        memory: dict = {}
        for fn in self._memory_sources:
            try:
                memory.update(fn())
            except Exception:
                memory.setdefault("memory_source_errors", 0)
                memory["memory_source_errors"] += 1
        # Same contract for slo.json: always written, {} when unwired.
        slo: dict = {}
        for fn in self._slo_sources:
            try:
                slo.update(fn())
            except Exception:
                slo.setdefault("slo_source_errors", 0)
                slo["slo_source_errors"] += 1
        # And for deploy.json: always written, {} when no controller.
        deploy: dict = {}
        for fn in self._deploy_sources:
            try:
                deploy.update(fn())
            except Exception:
                deploy.setdefault("deploy_source_errors", 0)
                deploy["deploy_source_errors"] += 1

        label = (f"step{int(context['step']):08d}" if "step" in context
                 else time.strftime("%Y%m%dT%H%M%S"))
        # Multi-process / elastic runs: tag the dump dir with the rank and
        # rendezvous generation so concurrent per-rank dumps land in
        # distinct directories (two ranks dying at the same step must not
        # race one dir name) and a postmortem can line dumps up by
        # generation (scripts/postmortem.py --all).
        rank = os.environ.get("DLTI_PROCESS_ID")
        gen = os.environ.get("DLTI_GENERATION")
        if gen is not None:
            label += f"-g{int(gen)}"
        if rank is not None:
            label += f"-r{int(rank)}"
        os.makedirs(self.directory, exist_ok=True)
        final = self._unique_dir(f"{_PREFIX}{label}")
        tmp = os.path.join(self.directory,
                           f"{_TMP}{os.path.basename(final)}-{os.getpid()}")
        os.makedirs(tmp, exist_ok=True)

        # process_name metadata first so a dump's span tail self-labels
        # its Perfetto row even before postmortem --all re-pids it.
        events = (self.tracer.metadata_events()
                  + self.tracer.events()[-self.max_spans:])
        payloads = {
            "context.json": {
                "reason": reason,
                "wall_time": time.time(),
                "iso_time": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
                "pid": os.getpid(),
                "process_id": int(rank) if rank is not None else None,
                "generation": int(gen) if gen is not None else None,
                "config_fingerprint": config_fingerprint(self.config),
                "exception": ("".join(traceback.format_exception(
                    type(exc), exc, exc.__traceback__)).rstrip()
                    if exc is not None else None),
                "context": context,
                **(extra or {}),
            },
            "spans.json": {
                "traceEvents": events,
                "displayTimeUnit": "ms",
                "droppedEvents": self.tracer.dropped_events,
                "tracerEnabled": self.tracer.enabled,
            },
            "metrics.json": metrics,
            "timeseries.json": {
                "samples": (self.sampler.tail(self.timeseries_tail)
                            if self.sampler is not None else []),
            },
            "config.json": (self.config.to_dict()
                            if hasattr(self.config, "to_dict")
                            else (self.config or {})),
            "memory.json": memory,
            "slo.json": slo,
            "deploy.json": deploy,
        }
        manifest: dict = {"format": 1, "reason": reason,
                          "created": time.time(), "files": {}}
        try:
            for name, obj in payloads.items():
                path = os.path.join(tmp, name)
                data = json.dumps(obj, indent=1, default=str).encode()
                durable_io.write_bytes(path, data, path_class="flight")
                manifest["files"][name] = {
                    "bytes": len(data),
                    "sha256": hashlib.sha256(data).hexdigest(),
                }
            durable_io.write_bytes(
                os.path.join(tmp, MANIFEST),
                json.dumps(manifest, indent=1).encode(),
                path_class="flight")
            # atomic: a visible flight-* dir is complete
            durable_io.replace(tmp, final, path_class="flight")
        except BaseException:
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)
            raise
        dumps_total.labels(reason=reason.split(":")[0]).inc()
        self.last_dump_path = final
        self.logger.warning("flight record (%s) -> %s", reason, final)
        self._rotate()
        return final

    def _unique_dir(self, base: str) -> str:
        path = os.path.join(self.directory, base)
        n = 1
        while os.path.exists(path):
            path = os.path.join(self.directory, f"{base}-{n}")
            n += 1
        return path

    def _rotate(self) -> None:
        if self.keep <= 0:
            return
        import shutil

        dumps = sorted(
            (d for d in os.listdir(self.directory)
             if d.startswith(_PREFIX)
             and os.path.isdir(os.path.join(self.directory, d))),
            key=lambda d: os.path.getmtime(os.path.join(self.directory, d)))
        for d in dumps[:-self.keep]:
            shutil.rmtree(os.path.join(self.directory, d),
                          ignore_errors=True)


# ----------------------------------------------------------------------
# Verification / loading (postmortem CLI + tests)
# ----------------------------------------------------------------------

def verify_dump(path: str) -> List[str]:
    """Digest-check a dump against its manifest; returns problems
    (empty = complete and intact)."""
    problems: List[str] = []
    mpath = os.path.join(path, MANIFEST)
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return [f"manifest unreadable: {e}"]
    for name, meta in manifest.get("files", {}).items():
        fpath = os.path.join(path, name)
        try:
            with open(fpath, "rb") as f:
                data = f.read()
        except OSError:
            problems.append(f"missing file: {name}")
            continue
        if len(data) != meta["bytes"]:
            problems.append(f"size mismatch: {name}")
        elif hashlib.sha256(data).hexdigest() != meta["sha256"]:
            problems.append(f"digest mismatch: {name}")
    for name in DUMP_FILES:
        if name not in manifest.get("files", {}):
            problems.append(f"manifest missing entry: {name}")
    return problems


def load_dump(path: str) -> dict:
    """{filename: parsed JSON} for a dump directory."""
    out = {}
    for name in DUMP_FILES + (MANIFEST,):
        fpath = os.path.join(path, name)
        if os.path.exists(fpath):
            with open(fpath) as f:
                out[name] = json.load(f)
    return out


def list_dumps(directory: str) -> List[str]:
    """Committed flight dirs under ``directory``, oldest first."""
    directory = os.path.abspath(directory)
    if not os.path.isdir(directory):
        return []
    dumps = [os.path.join(directory, d) for d in os.listdir(directory)
             if d.startswith(_PREFIX)
             and os.path.isdir(os.path.join(directory, d))]
    return sorted(dumps, key=os.path.getmtime)


# ----------------------------------------------------------------------
# Process-global recorder (the tracer's pattern): far-apart components —
# engine fault path, replica failover, chaos hooks — reach the black box
# without explicit plumbing. None when no entry point installed one.
# ----------------------------------------------------------------------
_RECORDER: Optional[FlightRecorder] = None


def install(recorder: Optional[FlightRecorder]) -> Optional[FlightRecorder]:
    global _RECORDER
    _RECORDER = recorder
    return recorder


def get_recorder() -> Optional[FlightRecorder]:
    return _RECORDER
