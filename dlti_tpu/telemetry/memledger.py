"""HBM memory ledger: per-owner device-memory attribution with
conservation by construction.

The goodput ledger (``ledger.py``) answers "where did the time go"; this
module answers the second axis of the paper's experiment grid, "where
does the memory live". The reference repo's whole ZeRO-1/2/3 comparison
is a *memory* story — peak device bytes per sharding strategy — yet a
single ``peak_bytes_in_use`` scalar cannot say whether the bytes are
parameters, optimizer state, KV blocks, or a leak.

**Model.** Subsystems register named *owners* (``params``,
``optimizer_state``, ``kv_block_pool``, ``prefix_cache_hbm``,
``decode_state_cache``, ``prefetch_buffers``, ...) with their
pytree/array handles (or a zero-arg callable returning one, for handles
that are swapped out across steps). A :meth:`MemoryLedger.snapshot` sums
per-device ``nbytes`` over each owner's live arrays, reconciles against
``jax.live_arrays()`` (device arrays nobody claimed → ``untracked``) and
``device.memory_stats()`` (allocator overhead beyond array payloads →
``residual``), and emits a bucket map whose values **sum to
bytes-in-use exactly, by construction** — the same conservation property
the goodput ledger pins for seconds, here pinned for bytes
(``tests/test_memledger.py``). Compiled-executable ``memory_analysis()``
(temp/argument/output bytes) folds in as the activation-peak estimate —
the transient bytes a snapshot between steps can never see.

**CPU determinism.** The CPU backend exposes no ``memory_stats()``; the
ledger then takes bytes-in-use := live-array bytes (``source:
"live_arrays"``, residual 0) and capacity from the configured budget, so
conservation, headroom admission and the squeeze-chaos drill all run
deterministically under ``JAX_PLATFORMS=cpu`` tier-1 tests.

**Consumers.** The trainer and serving engine each hold one ledger and
feed: ``dlti_hbm_bytes{owner=}`` / ``dlti_hbm_{peak,headroom,untracked}_
bytes`` on /metrics, ``hbm_*`` series on /debug/vars + /dashboard,
``GET /debug/memory`` (full per-owner per-device map + top-K live
arrays), ``memory.json`` in every flight dump (OOM forensics — rendered
by ``scripts/postmortem.py`` as "where the memory went"), the watchdog's
``hbm_pressure`` rule, and the engine's headroom-aware admission (defer,
don't fault). :class:`MemoryBalloon` is the chaos ``hbm-squeeze``
injector that proves the defer path without a real OOM.

Cost contract (same as the goodput ledger): a *disabled* ledger's
``snapshot()``/``scalars()``/``headroom_bytes()`` are one attribute read
+ early return. Metric names are a scrape contract (pinned in
``tests/test_bench_contract.py``).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from dlti_tpu.telemetry.registry import Gauge

# Canonical owner names (a label catalog, not a closed set — any snake_case
# owner registers fine; these are the ones the Trainer and engine wire up
# and postmortem/dashboards know how to read).
MEMORY_OWNERS = (
    "params",
    "optimizer_state",
    "grad_buffers",
    "kv_block_pool",
    "prefix_cache_hbm",
    "decode_state_cache",
    "prefetch_buffers",
    "kv_handoff_staging",  # disagg: host-staged prefill→decode KV payloads
    "lora_adapters",      # multi-LoRA serving: the stacked A/B adapter pool
    "chaos_balloon",      # the hbm-squeeze injector, visible by design
)

# Reconciliation buckets appended after the owners; owners + these sum to
# bytes-in-use exactly (see snapshot()).
UNTRACKED_BUCKET = "untracked"    # live device arrays nobody registered
RESIDUAL_BUCKET = "residual"      # allocator bytes beyond array payloads

# Name-stability contracts (pinned in tests/test_bench_contract.py).
MEMLEDGER_METRIC_NAMES = (
    "dlti_hbm_bytes",             # per-owner gauge (owner label)
    "dlti_hbm_peak_bytes",
    "dlti_hbm_headroom_bytes",
    "dlti_hbm_untracked_bytes",
)

# Module-level metrics (the goodput-ledger pattern: the trainer / engine
# sampler refreshes them, the server registry registers them for
# /metrics).
hbm_bytes_gauge = Gauge(
    MEMLEDGER_METRIC_NAMES[0],
    help="device bytes attributed per registered owner (owner label)")
hbm_peak_gauge = Gauge(
    MEMLEDGER_METRIC_NAMES[1],
    help="peak observed device bytes in use")
hbm_headroom_gauge = Gauge(
    MEMLEDGER_METRIC_NAMES[2],
    help="capacity minus bytes in use (0 when capacity unknown)")
hbm_untracked_gauge = Gauge(
    MEMLEDGER_METRIC_NAMES[3],
    help="live device bytes owned by no registered owner")


# ----------------------------------------------------------------------
# Free helpers (usable without a ledger)
# ----------------------------------------------------------------------

def _is_jax_array(x: Any) -> bool:
    # Committed device arrays only: numpy leaves and python scalars in a
    # pytree hold host memory, not HBM.
    return hasattr(x, "nbytes") and hasattr(x, "addressable_shards") \
        and hasattr(x, "is_deleted")


def _device_key(dev: Any) -> str:
    return f"{getattr(dev, 'platform', 'dev')}:{getattr(dev, 'id', 0)}"


def _array_per_device(arr: Any) -> Dict[str, int]:
    """Per-device payload bytes of one array, summing shard ``nbytes``
    (a sharded array holds only its shard bytes on each device)."""
    out: Dict[str, int] = {}
    try:
        shards = arr.addressable_shards
    except Exception:
        shards = []
    if shards:
        for sh in shards:
            try:
                key = _device_key(sh.device)
                out[key] = out.get(key, 0) + int(sh.data.nbytes)
            except Exception:
                continue
        if out:
            return out
    try:  # unsharded / fallback: whole payload on the array's device
        devs = list(getattr(arr, "devices", lambda: [])()) or [None]
        key = _device_key(devs[0]) if devs[0] is not None else "dev:0"
        out[key] = int(arr.nbytes)
    except Exception:
        pass
    return out


def tree_nbytes(tree: Any) -> int:
    """Total device payload bytes of every live jax array in a pytree."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if _is_jax_array(leaf) and not leaf.is_deleted():
            total += sum(_array_per_device(leaf).values())
    return total


def is_oom_error(exc: BaseException) -> bool:
    """Is ``exc`` a device out-of-memory? Matches the PJRT/XLA
    RESOURCE_EXHAUSTED family plus plain host ``MemoryError`` — the guard
    the trainer step and engine admit/prefill/KV-growth paths use to
    decide a failure deserves a ``memory.json`` forensics dump."""
    if isinstance(exc, MemoryError):
        return True
    msg = f"{type(exc).__name__}: {exc}".lower()
    return ("resource_exhausted" in msg or "resource exhausted" in msg
            or "out of memory" in msg or "out_of_memory" in msg
            or "allocation failure" in msg)


def executable_memory_analysis(compiled: Any) -> Dict[str, int]:
    """Best-effort ``memory_analysis()`` of a compiled executable as a
    plain dict (bytes). Empty when the backend doesn't implement it (CPU
    commonly doesn't) — callers treat it as advisory."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return {}
    if ma is None:
        return {}
    out: Dict[str, int] = {}
    for field in ("temp_size_in_bytes", "argument_size_in_bytes",
                  "output_size_in_bytes", "alias_size_in_bytes",
                  "generated_code_size_in_bytes"):
        v = getattr(ma, field, None)
        if isinstance(v, int) and v >= 0:
            out[field] = v
    if out:
        # The transient high-water estimate: temps live alongside args
        # and outputs while the step runs.
        out["activation_peak_bytes"] = (
            out.get("temp_size_in_bytes", 0)
            + out.get("output_size_in_bytes", 0))
    return out


def device_bytes_in_use() -> Dict[str, Dict[str, int]]:
    """``memory_stats()`` across ALL local devices:
    ``{device: {bytes_in_use, peak_bytes_in_use, bytes_limit}}`` (missing
    keys omitted; empty dict when no backend reports stats — CPU)."""
    import jax

    out: Dict[str, Dict[str, int]] = {}
    for dev in jax.local_devices():
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        entry = {}
        for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            v = stats.get(k)
            if isinstance(v, int) and v >= 0:
                entry[k] = v
        if entry:
            out[_device_key(dev)] = entry
    return out


# ----------------------------------------------------------------------
# The ledger
# ----------------------------------------------------------------------

class MemoryLedger:
    """Per-owner device-memory attribution with exact conservation.

    Thread-safety: ``register``/``unregister`` happen at wiring time;
    ``snapshot``/``scalars`` may be called concurrently by the sampler
    thread and HTTP handlers, so the owner map and peak/activation state
    share one lock. Owner *handles* are read without copying — providers
    must return a stable pytree (the trainer's state object / the
    engine's cache), not build one per call.
    """

    def __init__(self, enabled: bool = True, capacity_bytes: int = 0):
        self.enabled = enabled
        # 0 = auto-detect from memory_stats().bytes_limit (sums across
        # local devices); a configured budget wins when detection finds
        # nothing (the CPU path).
        self.capacity_bytes = int(capacity_bytes)
        self._lock = threading.Lock()
        self._owners: Dict[str, Any] = {}
        # owner -> (parent_owner, bytes_fn): sub-owners carved out of a
        # parent's bytes (see register_carve).
        self._carves: Dict[str, Any] = {}
        self._peak = 0
        self._owner_peaks: Dict[str, int] = {}
        self._activation: Dict[str, int] = {}

    # -- wiring ---------------------------------------------------------
    def register(self, owner: str, handle: Any) -> None:
        """Attach ``handle`` (a pytree of jax arrays, or a zero-arg
        callable returning one) under ``owner``. Re-registering replaces
        — handles that are rebuilt (a fresh TrainState after restore)
        should register a callable so the ledger follows the swap."""
        if not self.enabled:
            return
        with self._lock:
            self._owners[owner] = handle

    def register_carve(self, owner: str, parent: str,
                       bytes_fn: Callable[[], int]) -> None:
        """Attribute a slice of ``parent``'s bytes to ``owner`` without
        double counting — for sub-tenants living *inside* another owner's
        arrays (prefix-cache blocks resident in the KV pool). At snapshot
        time ``min(bytes_fn(), parent bytes)`` moves from parent to
        owner, so conservation is untouched."""
        if not self.enabled:
            return
        with self._lock:
            self._carves[owner] = (parent, bytes_fn)

    def unregister(self, owner: str) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._owners.pop(owner, None)
            self._carves.pop(owner, None)
            self._owner_peaks.pop(owner, None)

    def owners(self) -> List[str]:
        with self._lock:
            return sorted(self._owners)

    def set_capacity(self, capacity_bytes: int) -> None:
        self.capacity_bytes = int(capacity_bytes)

    def note_activation_peak(self, info: Dict[str, int]) -> None:
        """Fold in a compiled step's :func:`executable_memory_analysis`
        (keeps the max per field across recompiles)."""
        if not self.enabled or not info:
            return
        with self._lock:
            for k, v in info.items():
                if isinstance(v, int):
                    self._activation[k] = max(self._activation.get(k, 0), v)

    # -- snapshot -------------------------------------------------------
    def _materialize(self) -> Dict[str, List[Any]]:
        """owner -> live jax arrays, deduped by identity across owners
        (first registration order wins — an aliased array is one
        allocation and must be counted once)."""
        with self._lock:
            items = list(self._owners.items())
        import jax

        seen: set = set()
        out: Dict[str, List[Any]] = {}
        for owner, handle in items:
            try:
                tree = handle() if callable(handle) else handle
            except Exception:
                tree = None
            arrs = []
            for leaf in jax.tree_util.tree_leaves(tree):
                if not _is_jax_array(leaf) or leaf.is_deleted():
                    continue
                if id(leaf) in seen:
                    continue
                seen.add(id(leaf))
                arrs.append(leaf)
            out[owner] = arrs
        return out

    def snapshot(self, top_k: int = 0) -> dict:
        """The full reconciliation. Returns a dict whose ``buckets``
        (owners + ``untracked`` + ``residual``) sum to ``bytes_in_use``
        **exactly** — integers, no rounding escape hatch:

        * per owner: payload bytes of its live registered arrays
          (per-device breakdown included),
        * ``untracked``: ``jax.live_arrays()`` members no owner claimed,
        * ``bytes_in_use``: summed ``memory_stats()`` across local
          devices (``source: "device"``) or, when no backend reports
          stats, tracked+untracked live bytes (``source:
          "live_arrays"``),
        * ``residual``: bytes_in_use − tracked − untracked (allocator
          overhead / fragmentation; 0 on the live_arrays path). A
          negative residual (stats lagging a just-freed array) is shaved
          off the largest bucket, mirroring ``request_breakdown``'s
          exact-conservation arithmetic for time.
        """
        if not self.enabled:
            return {}
        import jax

        per_owner_arrays = self._materialize()
        owners: Dict[str, dict] = {}
        tracked_ids: set = set()
        tracked_total = 0
        for owner, arrs in per_owner_arrays.items():
            per_dev: Dict[str, int] = {}
            for a in arrs:
                tracked_ids.add(id(a))
                for dev, b in _array_per_device(a).items():
                    per_dev[dev] = per_dev.get(dev, 0) + b
            total = sum(per_dev.values())
            tracked_total += total
            owners[owner] = {"bytes": total, "per_device": per_dev}

        with self._lock:
            carves = list(self._carves.items())
        for owner, (parent, bytes_fn) in carves:
            if parent not in owners:
                continue
            try:
                want = max(0, int(bytes_fn()))
            except Exception:
                want = 0
            moved = min(want, owners[parent]["bytes"])
            owners[parent]["bytes"] -= moved
            owners[owner] = {"bytes": moved, "per_device": {},
                             "carved_from": parent}

        untracked_total = 0
        untracked_arrays: List[Any] = []
        try:
            live = jax.live_arrays()
        except Exception:
            live = []
        for a in live:
            if not _is_jax_array(a) or a.is_deleted():
                continue
            if id(a) in tracked_ids:
                continue
            tracked_ids.add(id(a))  # live_arrays can alias-duplicate
            untracked_total += sum(_array_per_device(a).values())
            untracked_arrays.append(a)

        dev_stats = device_bytes_in_use()
        if dev_stats:
            source = "device"
            bytes_in_use = sum(s.get("bytes_in_use", 0)
                               for s in dev_stats.values())
            device_peak = sum(s.get("peak_bytes_in_use", 0)
                              for s in dev_stats.values())
            detected_cap = sum(s.get("bytes_limit", 0)
                               for s in dev_stats.values())
        else:
            source = "live_arrays"
            bytes_in_use = tracked_total + untracked_total
            device_peak = 0
            detected_cap = 0
        capacity = detected_cap or self.capacity_bytes

        buckets: Dict[str, int] = {o: d["bytes"] for o, d in owners.items()}
        buckets[UNTRACKED_BUCKET] = untracked_total
        residual = bytes_in_use - tracked_total - untracked_total
        buckets[RESIDUAL_BUCKET] = max(0, residual)
        if residual < 0 and buckets:
            # Conservation over raw fidelity: shave the overshoot off the
            # largest bucket so the emitted map sums to bytes_in_use.
            top = max(buckets, key=lambda k: buckets[k])
            buckets[top] = max(0, buckets[top] + residual)

        with self._lock:
            self._peak = max(self._peak, bytes_in_use, device_peak)
            peak = self._peak
            for o, d in owners.items():
                self._owner_peaks[o] = max(self._owner_peaks.get(o, 0),
                                           d["bytes"])
            owner_peaks = dict(self._owner_peaks)
            activation = dict(self._activation)

        snap = {
            "source": source,
            "bytes_in_use": bytes_in_use,
            "peak_bytes": peak,
            "capacity_bytes": capacity,
            "headroom_bytes": (max(0, capacity - bytes_in_use)
                               if capacity else None),
            "tracked_bytes": tracked_total,
            "untracked_bytes": untracked_total,
            "residual_bytes": max(0, residual),
            "owners": owners,
            "owner_peak_bytes": owner_peaks,
            "buckets": buckets,
            "activation_peak": activation,
            "device_stats": dev_stats,
            "num_live_arrays": len(live),
        }
        if top_k > 0:
            ranked = sorted(untracked_arrays,
                            key=lambda a: -int(a.nbytes))[:top_k]
            snap["top_untracked_arrays"] = [{
                "shape": list(getattr(a, "shape", ())),
                "dtype": str(getattr(a, "dtype", "?")),
                "nbytes": int(a.nbytes),
                "per_device": _array_per_device(a),
            } for a in ranked]
        return snap

    # -- reads ----------------------------------------------------------
    def headroom_bytes(self,
                       snap: Optional[dict] = None) -> Optional[int]:
        """Capacity minus bytes-in-use; None when disabled or capacity is
        unknown (callers must then skip headroom gating, not treat it as
        zero)."""
        if not self.enabled:
            return None
        if snap is None:
            snap = self.snapshot()
        return snap.get("headroom_bytes")

    def scalars(self) -> Dict[str, float]:
        """``hbm_*`` keys for the time-series ring / ``/debug/vars``
        (what the watchdog's hbm_pressure rule, the dashboard panel and
        the steplog fields consume) — and the refresh point for the
        module-level gauges, so /metrics stays current wherever the
        sampler runs."""
        if not self.enabled:
            return {}
        snap = self.snapshot()
        out: Dict[str, float] = {
            "hbm_bytes_in_use": snap["bytes_in_use"],
            "hbm_tracked_bytes": snap["tracked_bytes"],
            "hbm_untracked_bytes": snap["untracked_bytes"],
            "hbm_peak_bytes": snap["peak_bytes"],
        }
        for o, d in snap["owners"].items():
            out[f"hbm_owner_{o}_bytes"] = d["bytes"]
        headroom = snap.get("headroom_bytes")
        cap = snap.get("capacity_bytes", 0)
        if headroom is not None:
            out["hbm_headroom_bytes"] = headroom
            if cap:
                out["hbm_headroom_frac"] = round(headroom / cap, 6)
        hbm_peak_gauge.set(snap["peak_bytes"])
        hbm_untracked_gauge.set(snap["untracked_bytes"])
        hbm_headroom_gauge.set(headroom or 0)
        for o, d in snap["owners"].items():
            hbm_bytes_gauge.labels(owner=o).set(d["bytes"])
        return out

    def to_dict(self, top_k: int = 8) -> dict:
        """The ``GET /debug/memory`` / ``memory.json`` payload."""
        if not self.enabled:
            return {}
        snap = self.snapshot(top_k=top_k)
        snap["ts"] = time.time()
        return snap

    def save(self, path: str, **extra) -> Optional[str]:
        """Atomic JSON write of :meth:`to_dict` + ``extra``; never raises
        (accounting must not kill the run it accounts). None disabled."""
        if not self.enabled:
            return None
        try:
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            tmp = f"{path}.tmp-{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump({**self.to_dict(), **extra}, f)
            os.replace(tmp, path)
            return path
        except OSError:
            return None


# ----------------------------------------------------------------------
# Chaos: the hbm-squeeze balloon
# ----------------------------------------------------------------------

class MemoryBalloon:
    """A deterministic HBM squeeze: allocate ``n`` device bytes and
    register them with the ledger as ``chaos_balloon`` — the headroom
    shrinks by exactly what the ledger can see, so the defer-don't-fault
    admission path and the hbm_pressure watchdog rule are provable on
    CPU without a real OOM. ``deflate()`` releases the bytes and the
    owner entry."""

    OWNER = "chaos_balloon"

    def __init__(self, ledger: Optional[MemoryLedger] = None):
        self.ledger = ledger
        self._arrays: List[Any] = []

    @property
    def nbytes(self) -> int:
        return sum(int(a.nbytes) for a in self._arrays
                   if not a.is_deleted())

    def inflate(self, nbytes: int) -> int:
        """Allocate ~``nbytes`` more device memory (float32 zeros,
        materialized). Returns the balloon's new total size."""
        import jax
        import jax.numpy as jnp

        n = max(1, int(nbytes) // 4)
        arr = jax.block_until_ready(jnp.zeros((n,), dtype=jnp.float32))
        self._arrays.append(arr)
        if self.ledger is not None:
            self.ledger.register(self.OWNER, lambda: self._arrays)
        return self.nbytes

    def deflate(self) -> None:
        for a in self._arrays:
            try:
                a.delete()
            except Exception:
                pass
        self._arrays = []
        if self.ledger is not None:
            self.ledger.unregister(self.OWNER)


# ----------------------------------------------------------------------
# Process-global accessor (the flightrecorder pattern): chaos injectors
# and postmortem hooks reach the live ledger without plumbing.
# ----------------------------------------------------------------------

_current: Optional[MemoryLedger] = None


def install(ledger: Optional[MemoryLedger]) -> None:
    global _current
    _current = ledger


def get_ledger() -> Optional[MemoryLedger]:
    return _current
