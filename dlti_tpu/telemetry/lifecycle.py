"""Request-lifecycle telemetry for the serving engine.

vLLM treats request-lifecycle metrics (TTFT/TPOT, queue time, preemptions)
as a first-class engine surface; this is that surface for
:class:`~dlti_tpu.serving.engine.InferenceEngine`. One instance per engine
(or shared across replicas — histograms aggregate naturally) holds the
latency histograms and converts the timestamps the engine already keeps on
each :class:`Request` into Chrome-trace spans:

    submitted → admitted        ``request/queued``   (queue-time histogram)
    admitted  → first token     ``request/prefill``  (TTFT histogram, from
                                                      arrival)
    first tok → finished        ``request/decode``   (TPOT histogram)

When the admission gateway fronts the engine, its own phase precedes
these on the same timeline under the ``gateway`` category:
``gateway/enqueued`` (accepted), ``gateway/queued`` (admission wait,
complete-span), and the ``gateway/rejected`` / ``gateway/shed`` instants
for refusals and queued-deadline sheds (``serving.gateway``).

Spans are emitted *after the fact* from recorded timestamps
(:meth:`SpanTracer.complete`), so the engine's hot path only ever touches
monotonic-clock floats it already records. Each request's spans share a
``tid`` derived from its id, giving one Perfetto track per request.
"""

from __future__ import annotations

import time
import zlib
from typing import Optional

from dlti_tpu.telemetry.ledger import (
    CriticalPathTracker, note_readmitted, note_requeue,
)
from dlti_tpu.telemetry.registry import (
    Histogram, HOST_PREP_BUCKETS, LATENCY_BUCKETS, TPOT_BUCKETS,
)
from dlti_tpu.telemetry.tracer import SpanTracer, get_tracer


def _req_tid(request_id: str) -> int:
    # Stable per-request Perfetto track in a compact id range, offset past
    # plausible real thread ids' low bits colliding at 0.
    return 1_000_000 + (zlib.crc32(request_id.encode()) % 1_000_000)


class RequestTelemetry:
    """Histograms + lifecycle span emission for engine requests."""

    def __init__(self, tracer: Optional[SpanTracer] = None,
                 slow_k: int = 32):
        self.tracer = tracer if tracer is not None else get_tracer()
        # Critical-path attribution (telemetry.ledger): every finished
        # request's phase breakdown feeds dlti_request_phase_* and the
        # GET /debug/slow worst-K retention. Shared across replicas like
        # the histograms, so the fleet attributes into one place.
        self.critical_path = CriticalPathTracker(slow_k=slow_k)
        self.ttft = Histogram(
            "dlti_request_ttft_seconds", LATENCY_BUCKETS,
            help="time from request arrival to first generated token",
            stats_key="request_ttft_seconds")
        self.tpot = Histogram(
            "dlti_request_tpot_seconds", TPOT_BUCKETS,
            help="mean per-output-token latency after the first token",
            stats_key="request_tpot_seconds")
        self.queue_time = Histogram(
            "dlti_request_queue_time_seconds", LATENCY_BUCKETS,
            help="time from request arrival to slot admission",
            stats_key="request_queue_time_seconds")
        # Host-side prep per decode dispatch (batch assembly + state
        # sync): the term the device-resident decode-state cache holds
        # flat as max_seqs grows (serving.decode_state).
        self.host_prep = Histogram(
            "dlti_decode_host_prep_seconds", HOST_PREP_BUCKETS,
            help="host-side prep time per decode dispatch "
                 "(batch assembly + decode-state sync)",
            stats_key="decode_host_prep_seconds")

    def histograms(self):
        return (self.ttft, self.tpot, self.queue_time, self.host_prep)

    # -- lifecycle hooks (called by the engine) -------------------------
    # Requests flagged ``shadow`` (the deployment controller's mirrored
    # canary traffic, serving.deploy) never book into the client-facing
    # histograms or phase attribution: shadow results never reach a
    # client, so counting them would dilute the SLIs the SLO objectives
    # are computed from.
    def on_submitted(self, req) -> None:
        if getattr(req, "shadow", False):
            return
        self.tracer.instant("request/submitted", cat="request",
                            tid=_req_tid(req.request_id), id=req.request_id,
                            trace=getattr(req, "trace_id", ""))

    def on_admitted(self, req) -> None:
        """First admission observes queue time; a re-admission after
        preemption keeps the original queue-time sample (the request
        queued once — recompute is decode-side churn) and only marks the
        trace."""
        if getattr(req, "shadow", False):
            return
        now = time.monotonic()
        # Close any open requeue mark (preemption / failover wait books
        # to its own phase in the request's critical-path breakdown).
        note_readmitted(req)
        if req.admitted_time is None:
            req.admitted_time = now
            self.queue_time.observe(now - req.arrival_time)
            self.tracer.complete(
                "request/queued", req.arrival_time, now, cat="request",
                tid=_req_tid(req.request_id), id=req.request_id,
                trace=getattr(req, "trace_id", ""))
        else:
            self.tracer.instant("request/readmitted", cat="request",
                                tid=_req_tid(req.request_id),
                                id=req.request_id,
                                preemptions=req.num_preemptions)

    def on_first_token(self, req) -> None:
        if getattr(req, "shadow", False):
            return
        self.ttft.observe(req.first_token_time - req.arrival_time)
        start = (req.admitted_time if req.admitted_time is not None
                 else req.arrival_time)
        self.tracer.complete(
            "request/prefill", start, req.first_token_time, cat="request",
            tid=_req_tid(req.request_id), id=req.request_id,
            trace=getattr(req, "trace_id", ""),
            prompt_tokens=len(req.prompt_token_ids))

    def on_finished(self, req) -> None:
        if getattr(req, "shadow", False):
            return
        n_out = len(req.output_token_ids)
        first = req.first_token_time
        finish = req.finish_time if req.finish_time is not None \
            else time.monotonic()
        if first is not None and n_out > 1:
            self.tpot.observe((finish - first) / (n_out - 1))
        self.tracer.complete(
            "request/decode",
            first if first is not None else req.arrival_time, finish,
            cat="request", tid=_req_tid(req.request_id), id=req.request_id,
            trace=getattr(req, "trace_id", ""),
            output_tokens=n_out, finish_reason=req.finish_reason,
            preemptions=req.num_preemptions)
        # Phase attribution last: the breakdown reads the timestamps the
        # spans above were emitted from (per request, never per token).
        self.critical_path.observe(req)

    def on_preempted(self, req) -> None:
        note_requeue(req, "preempt")
        self.tracer.instant("request/preempted", cat="request",
                            tid=_req_tid(req.request_id), id=req.request_id)
