"""Goodput ledger & critical-path attribution: account every training
second and every request millisecond.

MegaScale's observability thesis (echoed in ``steplog.py`` /
``timeseries.py``) is that goodput at scale is *recovered by attribution*:
the framework itself must say where the time went, or recovery work
(restarts, rollbacks, tier restores, failovers) silently eats the wall
clock the throughput headline claims. This module is the two-sided
accounting layer:

**Training — :class:`GoodputLedger`, a phase clock.** At any instant the
run is in exactly one phase; every ``enter(phase)`` transition books the
elapsed interval to the *previous* phase's bucket, so bucket totals sum to
wall clock *by construction* (the conservation property is tier-1-tested,
not aspirational). The trainer transitions at the same sites its tracer
spans cover (data wait, host→device, step dispatch, device sync,
eval, checkpoint save/restore, sentinel rollback, SDC probe); after a
sentinel rollback the re-executed steps book to ``replay`` instead of
``step_compute`` (``begin_replay``/``end_replay``), so a drill that
converges still shows what fraction of the run was productive. The
elastic supervisor stitches per-generation worker ledgers across restarts
and adds the buckets only it can see: ``restart_downtime`` (teardown +
backoff + respawn gaps) and shrunk-world degradation
(:func:`stitch_ledgers`).

**Serving — per-request critical-path attribution.** The engine, gateway,
prefix tiers and failover paths already stamp monotonic timestamps on
each :class:`~dlti_tpu.serving.engine.Request`;
:func:`request_breakdown` assembles them into a phase breakdown
(``gateway_queue`` → ``queue`` → ``tier_restore`` → ``prefill`` →
``decode``, plus ``failover``/``preempt`` requeue stalls) that sums to
the client-observed latency. :class:`CriticalPathTracker` (one per
:class:`~dlti_tpu.telemetry.lifecycle.RequestTelemetry`, shared across
replicas) folds every finished request into the
``dlti_request_phase_seconds_total{phase=}`` exposition and retains the K
worst requests with their full timelines for ``GET /debug/slow`` — the
answer to "why was this p99 request slow: queue, prefill, tier restore,
or failover?".

Cost contract (same as the tracer): a *disabled* ledger's ``enter()`` is
one attribute read + an early return — no clock read, no lock, no dict —
so the per-step instrumentation can stay in the trainer unconditionally.

Metric names are a scrape contract (pinned in
``tests/test_bench_contract.py``); bucket and phase label sets are
parsing contracts for the same reason.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional

from dlti_tpu.telemetry.registry import Counter, Gauge

# ----------------------------------------------------------------------
# Bucket / phase catalogs (label contracts — postmortem, dashboards and
# the steplog parse these; pinned in tests/test_bench_contract.py)
# ----------------------------------------------------------------------

# Training wall-clock buckets a worker books itself. "step_compute" is
# the host-side dispatch of the compiled step; "device_sync" is the
# blocking wait for its results (where the device work actually
# surfaces); both count as PRODUCTIVE. "other" absorbs bookkeeping and
# anything not worth its own bucket — it must stay small, and because
# every second lands somewhere, a regression there is visible instead of
# invisible.
GOODPUT_BUCKETS = (
    "startup",            # init, compile, resume scan before first step
    "step_compute",       # compiled-step dispatch (host side)
    "device_sync",        # blocking wait on step results
    "data_wait",          # batch fetch stall (prefetch hides, not books)
    "host_to_device",     # global batch assembly / placement
    "eval",
    "checkpoint_save",
    "checkpoint_restore",  # verified resume at train start
    "rollback",           # sentinel rollback: restore + quarantine writes
    "replay",             # re-executing steps discarded by a rollback
    "sdc_probe",          # cross-rank param digest checks
    "shutdown",           # final saves / teardown
    "other",              # per-step bookkeeping, logging, residual host work
)

# Buckets only the elastic supervisor can book (stitched ledger).
SUPERVISOR_BUCKETS = ("restart_downtime",)

PRODUCTIVE_BUCKETS = ("step_compute", "device_sync")

# Serving per-request phases. A breakdown's values sum to the
# client-observed latency (enqueue-or-arrival → finish); "other" is the
# residual that keeps the sum exact when clamping eats sub-ms slivers.
REQUEST_PHASES = (
    "gateway_queue",   # admission-gateway queue (enqueue → engine submit)
    "queue",           # engine waiting deque (submit → slot admission)
    "tier_restore",    # host/disk prefix-block fetch + restore scatter
    "prefill",         # admission → first token, minus restore/stalls
    "failover",        # requeued after a replica fault, waiting again
    "preempt",         # preempted under memory pressure, waiting again
    "kv_handoff",      # disagg: prefill→decode paged-KV block migration
    "decode",          # first token → finish, minus requeue stalls
    "other",           # residual (clamp slivers; sum stays exact)
)

# Name-stability contracts (pinned in tests/test_bench_contract.py).
LEDGER_METRIC_NAMES = (
    "dlti_goodput_fraction",
    "dlti_goodput_seconds_total",
    "dlti_goodput_mfu_percent",
)
REQUEST_PHASE_METRIC_NAMES = (
    "dlti_request_phase_seconds_total",
    "dlti_request_phase_requests_total",
)

# Module-level metrics (the checkpoint-store/watchdog pattern: trainer
# sets them, the server registry registers them for /metrics).
goodput_fraction_gauge = Gauge(
    LEDGER_METRIC_NAMES[0],
    help="fraction of booked wall clock spent in productive step compute")
goodput_seconds_total = Counter(
    LEDGER_METRIC_NAMES[1],
    help="wall-clock seconds booked per goodput bucket (bucket label)")
goodput_mfu_gauge = Gauge(
    LEDGER_METRIC_NAMES[2],
    help="model FLOPs utilization of the most recent training step")
phase_seconds_total = Counter(
    REQUEST_PHASE_METRIC_NAMES[0],
    help="per-request critical-path seconds per phase (phase label)")
phase_requests_total = Counter(
    REQUEST_PHASE_METRIC_NAMES[1],
    help="finished requests folded into the phase attribution")


# ----------------------------------------------------------------------
# Training: the phase clock
# ----------------------------------------------------------------------

class GoodputLedger:
    """Wall-clock phase clock with conservation by construction.

    Thread-safety: ``enter`` is called from the trainer's step thread
    only; ``totals``/``scalars`` may be read concurrently by the
    time-series sampler thread, so transitions and reads share one lock.
    """

    def __init__(self, enabled: bool = True,
                 clock: Callable[[], float] = time.monotonic):
        self.enabled = enabled
        self._clock = clock
        self._lock = threading.Lock()
        self._totals: Dict[str, float] = {}
        self._deltas: Dict[str, float] = {}
        self._phase = "startup"
        now = clock() if enabled else 0.0
        self._t0 = now
        self._start = now
        # While replaying rolled-back steps, step buckets reclass to
        # "replay": set to the pre-rollback high-water step by
        # begin_replay, cleared by end_replay.
        self.replay_until: Optional[int] = None

    # -- transitions ----------------------------------------------------
    def enter(self, phase: str) -> None:
        """Book time since the last transition to the previous phase and
        make ``phase`` current. Disabled: one attribute read."""
        if not self.enabled:
            return
        now = self._clock()
        with self._lock:
            prev = self._phase
            if self.replay_until is not None and prev in PRODUCTIVE_BUCKETS:
                prev = "replay"
            dt = max(0.0, now - self._t0)
            self._totals[prev] = self._totals.get(prev, 0.0) + dt
            self._deltas[prev] = self._deltas.get(prev, 0.0) + dt
            self._phase = phase
            self._t0 = now

    def begin_replay(self, until_step: int) -> None:
        """Steps (re-)executed while the optimizer step stays at or below
        ``until_step`` are rollback replay, not fresh progress."""
        if self.enabled:
            self.replay_until = int(until_step)

    def end_replay(self) -> None:
        self.replay_until = None

    # -- reads ----------------------------------------------------------
    def wall(self) -> float:
        """Seconds since construction (0.0 disabled)."""
        return self._clock() - self._start if self.enabled else 0.0

    def totals(self) -> Dict[str, float]:
        """Bucket seconds including the still-open current phase; the
        values sum to :meth:`wall` exactly (float rounding aside)."""
        if not self.enabled:
            return {}
        now = self._clock()
        with self._lock:
            out = dict(self._totals)
            cur = self._phase
            if self.replay_until is not None and cur in PRODUCTIVE_BUCKETS:
                cur = "replay"
            out[cur] = out.get(cur, 0.0) + max(0.0, now - self._t0)
        return out

    def take_deltas(self) -> Dict[str, float]:
        """Bucket seconds accrued since the previous call (the per-step
        feed for the steplog fields and the ``dlti_goodput_seconds_total``
        counter). Does not close the open phase — sub-transition time
        rides into the next call."""
        if not self.enabled:
            return {}
        with self._lock:
            d, self._deltas = self._deltas, {}
        return d

    def goodput_fraction(self,
                         totals: Optional[Dict[str, float]] = None) -> float:
        t = self.totals() if totals is None else totals
        wall = sum(t.values())
        if wall <= 0:
            return 0.0
        return sum(t.get(b, 0.0) for b in PRODUCTIVE_BUCKETS) / wall

    def scalars(self) -> Dict[str, float]:
        """``goodput_*`` keys for the time-series ring / ``/debug/vars``
        (what the watchdog's goodput_collapse rule and the flight-dump
        metrics snapshot consume)."""
        if not self.enabled:
            return {}
        t = self.totals()
        out = {f"goodput_{k}_seconds": round(v, 6) for k, v in t.items()}
        out["goodput_wall_seconds"] = round(sum(t.values()), 6)
        out["goodput_fraction"] = round(self.goodput_fraction(t), 6)
        return out

    def to_dict(self) -> dict:
        t = self.totals()
        return {"buckets": {k: round(v, 6) for k, v in t.items()},
                "wall_s": round(sum(t.values()), 6),
                "goodput_fraction": round(self.goodput_fraction(t), 6)}

    def save(self, path: str, **extra) -> Optional[str]:
        """Atomic JSON write of :meth:`to_dict` + ``extra``; never raises
        (accounting must not kill the run it accounts). None disabled."""
        if not self.enabled:
            return None
        try:
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            tmp = f"{path}.tmp-{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump({**self.to_dict(), **extra}, f)
            os.replace(tmp, path)
            return path
        except OSError:
            return None


# ----------------------------------------------------------------------
# Elastic stitching: one ledger across restarts
# ----------------------------------------------------------------------

def load_generation_ledgers(elastic_dir: str) -> List[dict]:
    """Parse every ``ledger_g*_r*.json`` a worker saved into the elastic
    rendezvous dir (``training.elastic.save_generation_ledger``)."""
    out: List[dict] = []
    try:
        names = sorted(os.listdir(elastic_dir))
    except OSError:
        return out
    for name in names:
        if not (name.startswith("ledger_g") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(elastic_dir, name)) as f:
                out.append(json.load(f))
        except (OSError, ValueError):
            continue
    return out


def stitch_ledgers(worker_ledgers: List[dict], timeline: List[dict],
                   num_slots: int) -> dict:
    """Stitch per-generation worker ledgers + the supervisor's generation
    timeline into one run-level ledger.

    ``timeline`` entries: ``{"generation", "world_size", "start", "end",
    "outcome"}`` on the supervisor's clock. Only the supervisor sees the
    two buckets workers cannot: ``restart_downtime`` (the gap between one
    generation's end and the next one's start — teardown residue, backoff,
    respawn) and shrunk-world degradation (wall clock run at
    ``world_size < num_slots``, with the pro-rata capacity loss).

    Worker buckets are taken from ONE rank per generation (rank 0 when
    present): ranks run the same step-synchronous schedule in parallel,
    so summing across ranks would double-count wall clock.
    """
    per_gen: Dict[int, List[dict]] = {}
    for w in worker_ledgers:
        per_gen.setdefault(int(w.get("generation", 0)), []).append(w)
    buckets: Dict[str, float] = {}
    generations = []
    for gen in sorted(per_gen):
        ws = sorted(per_gen[gen], key=lambda w: int(w.get("rank", 0)))
        rep = ws[0]
        for k, v in (rep.get("buckets") or {}).items():
            buckets[k] = buckets.get(k, 0.0) + float(v)
        generations.append({
            "generation": gen, "rank": rep.get("rank"),
            "wall_s": rep.get("wall_s"),
            "goodput_fraction": rep.get("goodput_fraction"),
            "buckets": rep.get("buckets") or {},
            "num_rank_ledgers": len(ws),
        })
    segs = sorted(timeline, key=lambda s: s.get("start", 0.0))
    downtime = sum(max(0.0, b["start"] - a["end"])
                   for a, b in zip(segs, segs[1:]))
    shrunk_wall = 0.0
    shrunk_loss = 0.0
    for s in segs:
        wall = max(0.0, float(s.get("end", 0.0)) - float(s.get("start", 0.0)))
        world = int(s.get("world_size", num_slots))
        if 0 < world < num_slots:
            shrunk_wall += wall
            shrunk_loss += wall * (num_slots - world) / num_slots
    if downtime > 0:
        buckets["restart_downtime"] = round(
            buckets.get("restart_downtime", 0.0) + downtime, 6)
    total = sum(buckets.values())
    productive = sum(buckets.get(b, 0.0) for b in PRODUCTIVE_BUCKETS)
    return {
        "num_slots": num_slots,
        "num_generations": len(segs) or len(generations),
        "generations": generations,
        "buckets": {k: round(v, 6) for k, v in buckets.items()},
        "wall_s": round(total, 6),
        "restart_downtime_s": round(downtime, 6),
        "shrunk_world_s": round(shrunk_wall, 6),
        "shrunk_world_capacity_loss_s": round(shrunk_loss, 6),
        "goodput_fraction": round(productive / total, 6) if total else 0.0,
    }


# ----------------------------------------------------------------------
# Serving: per-request critical-path attribution
# ----------------------------------------------------------------------

def note_requeue(req, kind: str) -> None:
    """Mark a request leaving a slot back to a waiting queue (``kind`` in
    ``("failover", "preempt", "kv_handoff")``); the wait until
    re-admission books to that phase instead of inflating prefill/decode.

    A mark may already be open: a slot preempted mid-chunked-prefill whose
    replica then dies is requeued AGAIN (failover) before the preempt wait
    was ever closed by a re-admission. Fold the open window into its phase
    first — overwriting the mark would silently drop the elapsed wait and
    restart the charge window, and the lost time would book into prefill.
    """
    note_readmitted(req)
    req._requeue_mark = (kind, time.monotonic())


def note_readmitted(req) -> None:
    """Close an open requeue mark at (re-)admission time."""
    mark = getattr(req, "_requeue_mark", None)
    if not mark:
        return
    kind, t0 = mark
    req._requeue_mark = None
    dt = max(0.0, time.monotonic() - t0)
    req.stall_s[kind] = req.stall_s.get(kind, 0.0) + dt
    if req.first_token_time is None:
        req.stall_prefill_s += dt


def request_breakdown(req, end: Optional[float] = None) -> dict:
    """Assemble a request's recorded timestamps into a phase breakdown
    whose values sum to the client-observed latency (t0 = gateway enqueue
    when the request came through one, else engine arrival; end = finish).

    Returns ``{"total_s", "ttft_s", "phases": {phase: s}, "timeline":
    [(event, offset_s)]}``; ``phases`` keys come from
    :data:`REQUEST_PHASES` and always include the ``other`` residual that
    keeps the sum exact when clamping trims negative slivers.
    """
    gw_t = getattr(req, "gateway_enqueue_time", None)
    t0 = gw_t if gw_t is not None else req.arrival_time
    end = req.finish_time if req.finish_time is not None \
        else (end if end is not None else time.monotonic())
    first = req.first_token_time
    admitted = req.admitted_time
    restore = float(getattr(req, "restore_s", 0.0))
    stall = dict(getattr(req, "stall_s", {}) or {})
    stall_pre = float(getattr(req, "stall_prefill_s", 0.0))
    mark = getattr(req, "_requeue_mark", None)
    if mark:  # died waiting on a requeue (e.g. failover exhausted)
        dt = max(0.0, end - mark[1])
        stall[mark[0]] = stall.get(mark[0], 0.0) + dt
        if first is None:
            stall_pre += dt
    stall_total = sum(stall.values())
    stall_pre = min(stall_pre, stall_total)

    phases: Dict[str, float] = {}
    timeline: List[tuple] = [("submitted", max(0.0, req.arrival_time - t0))]
    if gw_t is not None:
        phases["gateway_queue"] = max(0.0, req.arrival_time - gw_t)
        timeline.insert(0, ("gateway_enqueue", 0.0))
    adm = admitted if admitted is not None else (first or end)
    phases["queue"] = max(0.0, adm - req.arrival_time)
    if admitted is not None:
        timeline.append(("admitted", max(0.0, admitted - t0)))
    if restore > 0:
        phases["tier_restore"] = restore
    pre_end = first if first is not None else end
    phases["prefill"] = max(0.0, (pre_end - adm) - restore - stall_pre)
    if first is not None:
        timeline.append(("first_token", max(0.0, first - t0)))
        phases["decode"] = max(0.0, (end - first)
                               - (stall_total - stall_pre))
    for kind, s in stall.items():
        if s > 0:
            phases[kind] = s
    timeline.append(("finish", max(0.0, end - t0)))
    total = round(max(0.0, end - t0), 6)
    # The residual is computed AGAINST THE ROUNDED values: the emitted
    # phases sum to the emitted total exactly (per-phase rounding would
    # otherwise leak a few microseconds of drift into consumers'
    # conservation checks).
    rounded = {k: round(v, 6) for k, v in phases.items()}
    residual = round(total - sum(rounded.values()), 6)
    rounded["other"] = max(0.0, residual)
    if residual < 0:
        # Per-phase round-ups can overshoot the rounded total by a few
        # microseconds; shave the excess off the largest phase so the
        # emitted numbers conserve exactly.
        top = max(rounded, key=lambda k: rounded[k])
        rounded[top] = round(rounded[top] + residual, 6)
    return {
        "total_s": total,
        "ttft_s": (round(first - t0, 6) if first is not None else None),
        "phases": rounded,
        "timeline": [(name, round(off, 6)) for name, off in timeline],
    }


class SlowLog:
    """Bounded retention of the K worst (slowest) finished requests with
    their full phase timelines — the ``GET /debug/slow`` payload."""

    def __init__(self, k: int = 32):
        self.k = max(1, int(k))
        self._lock = threading.Lock()
        self._entries: List[dict] = []

    def add(self, entry: dict) -> None:
        with self._lock:
            self._entries.append(entry)
            self._entries.sort(key=lambda e: -e.get("total_s", 0.0))
            del self._entries[self.k:]

    def worst(self, n: Optional[int] = None) -> List[dict]:
        with self._lock:
            out = list(self._entries)
        return out if n is None else out[:n]

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


class CriticalPathTracker:
    """Folds finished requests into the phase exposition + slow log.
    One per :class:`RequestTelemetry` (shared across replicas). Per
    REQUEST, not per token — and ``enabled = False`` reduces
    ``observe()`` to one attribute read."""

    def __init__(self, slow_k: int = 32):
        self.enabled = True
        self.slow = SlowLog(slow_k)

    def observe(self, req) -> Optional[dict]:
        if not self.enabled:
            return None
        if getattr(req, "_cp_observed", False):
            return None  # failover-errored requests can finish twice
        req._cp_observed = True
        b = request_breakdown(req)
        phase_requests_total.inc()
        for k, v in b["phases"].items():
            if v > 0:
                phase_seconds_total.labels(phase=k).inc(v)
        self.slow.add({
            "id": req.request_id,
            "trace_id": getattr(req, "trace_id", ""),
            "tenant": req.tenant,
            "priority": req.priority,
            "replica": req.replica,
            "finish_reason": req.finish_reason,
            "prompt_tokens": len(req.prompt_token_ids),
            "output_tokens": len(req.output_token_ids),
            "preemptions": req.num_preemptions,
            "retries": req.num_retries,
            "wall": time.time(),
            "total_s": b["total_s"],
            "ttft_s": b["ttft_s"],
            "phases": b["phases"],
            "timeline": b["timeline"],
        })
        return b
