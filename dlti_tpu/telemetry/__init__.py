"""Unified telemetry layer: metrics registry + structured span tracing.

One subsystem backing both planes' observability (previously scattered
across a hand-rolled Prometheus emitter in ``serving/server.py``, the
reference-parity CSV in ``utils/metrics.py``, ``StepTimer`` in
``utils/logging.py``, and raw ``jax.profiler`` windows in ``trainer.py``):

* :mod:`~dlti_tpu.telemetry.registry` — labeled counters / gauges /
  histograms + Prometheus text exposition; the single backing store for
  the server's ``/stats`` and ``/metrics`` endpoints.
* :mod:`~dlti_tpu.telemetry.tracer` — bounded-ring host-side span tracer
  (near-zero cost when disabled) exporting Chrome-trace JSON viewable in
  Perfetto.
* :mod:`~dlti_tpu.telemetry.lifecycle` — per-request lifecycle telemetry
  for the serving engine (TTFT/TPOT/queue-time histograms + spans).
* :mod:`~dlti_tpu.telemetry.steplog` — per-step JSONL stream for training
  (superset of the reference CSV schema).
* :mod:`~dlti_tpu.telemetry.heartbeat` — multi-host per-process
  last-seen-step gauge (straggler visibility).
* :mod:`~dlti_tpu.telemetry.timeseries` — bounded in-process time-series
  ring behind ``GET /debug/vars`` and the self-contained ``/dashboard``.
* :mod:`~dlti_tpu.telemetry.watchdog` — anomaly rule engine (hung step,
  throughput collapse, queue buildup, heartbeat staleness, checkpoint
  retry storms) with log/dump/abort escalation.
* :mod:`~dlti_tpu.telemetry.flightrecorder` — black-box ``flight-*/``
  dumps (span tail + metrics + time-series tail + live context) on
  faults, rendered by ``scripts/postmortem.py``.
* :mod:`~dlti_tpu.telemetry.ledger` — goodput ledger (every training
  second booked to one bucket, conservation-tested) + per-request
  critical-path attribution (phase breakdowns summing to client-observed
  latency, ``GET /debug/slow``), stitched across elastic restarts.
* :mod:`~dlti_tpu.telemetry.memledger` — HBM memory ledger (every
  device byte attributed to a named owner, conservation-tested against
  ``jax.live_arrays()``/``memory_stats()``), feeding ``GET
  /debug/memory``, ``memory.json`` OOM forensics, the watchdog's
  hbm_pressure rule, and the engine's headroom-aware admission.
* :mod:`~dlti_tpu.telemetry.slo` — declarative SLO engine: objectives
  over the SLIs above (latency histograms, gateway admission counters,
  goodput fraction), rolling error budgets per (objective, tenant
  class), multi-window multi-burn-rate alerting feeding the watchdog's
  slo_burn rule, ``GET /debug/slo``, and ``slo.json`` flight forensics.
"""

from dlti_tpu.telemetry.registry import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    LATENCY_BUCKETS,
    MetricsRegistry,
    TPOT_BUCKETS,
)
from dlti_tpu.telemetry.tracer import (  # noqa: F401
    SpanTracer,
    configure_tracer,
    get_tracer,
)
from dlti_tpu.telemetry.lifecycle import RequestTelemetry  # noqa: F401
from dlti_tpu.telemetry.steplog import (  # noqa: F401
    StepLogWriter,
    jsonl_stream_columns,
    metrics_csv_columns,
    schedule_lr,
)
from dlti_tpu.telemetry.heartbeat import Heartbeat  # noqa: F401
from dlti_tpu.telemetry.timeseries import (  # noqa: F401
    TimeSeriesSampler,
    render_dashboard_html,
)
from dlti_tpu.telemetry.watchdog import (  # noqa: F401
    AnomalyWatchdog,
    WATCHDOG_METRIC_NAMES,
)
from dlti_tpu.telemetry.flightrecorder import (  # noqa: F401
    FLIGHT_METRIC_NAMES,
    FlightRecorder,
    get_recorder,
    install as install_recorder,
)
from dlti_tpu.telemetry.ledger import (  # noqa: F401
    CriticalPathTracker,
    GOODPUT_BUCKETS,
    GoodputLedger,
    LEDGER_METRIC_NAMES,
    REQUEST_PHASE_METRIC_NAMES,
    REQUEST_PHASES,
    request_breakdown,
    stitch_ledgers,
)
from dlti_tpu.telemetry.slo import (  # noqa: F401
    Objective,
    SLO_METRIC_NAMES,
    SLOTracker,
    availability_objective,
    build_tracker as build_slo_tracker,
    goodput_objective,
    histogram_objective,
    parse_burn_tiers,
    standard_objectives,
)
from dlti_tpu.telemetry.memledger import (  # noqa: F401
    MEMLEDGER_METRIC_NAMES,
    MEMORY_OWNERS,
    MemoryBalloon,
    MemoryLedger,
    executable_memory_analysis,
    is_oom_error,
    tree_nbytes,
)
