"""Bounded in-process time-series ring: the black-box tape behind
``GET /debug/vars`` and the ``/dashboard`` page.

MegaScale's core observability claim is that goodput recovery comes from
*in-framework* instrumentation — the framework itself keeps enough recent
history to localize a straggler or a collapse without an external metrics
stack having been set up in advance. The PR 1 registry gives point-in-time
values; this module gives them a (bounded) past:

* :class:`TimeSeriesSampler` snapshots every numeric value its sources
  produce — typically a :class:`~dlti_tpu.telemetry.registry.MetricsRegistry`
  ``stats_dict()`` plus ad-hoc callbacks — into a ring of
  ``{"ts": monotonic, "wall": epoch, "values": {name: float}}`` samples,
  either on a daemon thread (``start()``) or on demand (``sample_now()``).
* Derived **rates** (``rate(name)``) turn cumulative counters into
  per-second series between ring samples — what the watchdog's
  collapse/buildup rules consume.
* ``snapshot()`` is the ``GET /debug/vars`` JSON payload; ``tail(n)`` is
  what a flight record embeds; :func:`render_dashboard_html` is a fully
  self-contained HTML page that polls ``/debug/vars`` — watching a live
  run needs a browser, not a Prometheus deployment.

Memory is strictly bounded: ``capacity`` samples, oldest evicted.
Sampling never raises out of a source — a broken callback loses its keys
for that sample (and is counted in ``source_errors``), never the run.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple


def flatten_numeric(d: dict, prefix: str = "") -> Dict[str, float]:
    """Numeric leaves of a (possibly nested) dict, dotted keys for nests
    (histogram summaries flatten to ``name.count`` / ``name.mean`` / ...).
    Bools and non-numerics are skipped; lists are opaque (skipped)."""
    out: Dict[str, float] = {}
    for k, v in d.items():
        key = f"{prefix}{k}"
        if isinstance(v, bool):
            continue
        if isinstance(v, (int, float)):
            out[key] = float(v)
        elif isinstance(v, dict):
            out.update(flatten_numeric(v, prefix=key + "."))
    return out


class TimeSeriesSampler:
    """Periodic snapshots of every source into a bounded ring."""

    def __init__(self, interval_s: float = 1.0, capacity: int = 600,
                 registry=None):
        self.interval_s = max(0.05, float(interval_s))
        self.capacity = max(2, int(capacity))
        self._sources: List[Callable[[], dict]] = []
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.source_errors = 0
        if registry is not None:
            self.add_source(registry.stats_dict)

    # -- sources --------------------------------------------------------
    def add_source(self, fn: Callable[[], dict]) -> None:
        """Register a callback producing ``{name: number-or-nested-dict}``;
        its numeric leaves join every subsequent sample."""
        self._sources.append(fn)

    # -- sampling -------------------------------------------------------
    def sample_now(self) -> dict:
        values: Dict[str, float] = {}
        for fn in self._sources:
            try:
                values.update(flatten_numeric(fn()))
            except Exception:
                # A broken source loses its keys for this sample; the ring
                # (and the run) survives. Counted so it cannot rot silently.
                self.source_errors += 1
        sample = {"ts": time.monotonic(), "wall": time.time(),
                  "values": values}
        with self._lock:
            self._ring.append(sample)
        return sample

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="dlti-ts-sampler")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2 * self.interval_s + 1)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.sample_now()

    # -- reads ----------------------------------------------------------
    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def tail(self, n: Optional[int] = None) -> List[dict]:
        with self._lock:
            items = list(self._ring)
        return items if n is None else items[-n:]

    def latest(self) -> Optional[dict]:
        with self._lock:
            return self._ring[-1] if self._ring else None

    def series(self, name: str) -> List[Tuple[float, float]]:
        """[(monotonic_ts, value)] for one metric across the ring."""
        return [(s["ts"], s["values"][name]) for s in self.tail()
                if name in s["values"]]

    def rate(self, name: str, window_s: Optional[float] = None,
             ) -> Optional[float]:
        """Per-second delta of ``name`` over the ring tail (counter →
        rate). ``None`` with < 2 observations; clamped at 0 so a process
        restart (counter reset) reads as quiet, not negative."""
        pts = self.series(name)
        if window_s is not None and pts:
            t_end = pts[-1][0]
            pts = [p for p in pts if t_end - p[0] <= window_s]
        if len(pts) < 2:
            return None
        dt = pts[-1][0] - pts[0][0]
        if dt <= 0:
            return None
        return max(0.0, (pts[-1][1] - pts[0][1]) / dt)

    def peak(self, name: str) -> Optional[float]:
        pts = self.series(name)
        return max(v for _, v in pts) if pts else None

    def snapshot(self, tail: Optional[int] = None) -> dict:
        """The ``GET /debug/vars`` payload."""
        samples = self.tail(tail)
        return {
            "now": time.time(),
            "interval_s": self.interval_s,
            "capacity": self.capacity,
            "num_samples": len(samples),
            "source_errors": self.source_errors,
            "latest": samples[-1]["values"] if samples else {},
            "samples": samples,
        }


# ----------------------------------------------------------------------
# /dashboard — one self-contained HTML page, zero external assets.
# ----------------------------------------------------------------------

# Series the dashboard promotes to sparkline rows when present (everything
# else lives in the collapsible all-values table). One series per
# sparkline (its row label names it — no legend needed); rate-suffixed
# entries are derived client-side from the counter samples.
_DASH_PREFERRED = (
    "generated_tokens", "requests", "active_seqs", "waiting", "free_blocks",
    "gateway_queue_depth", "gateway_queued_tokens", "gateway_inflight",
    "train_step", "train_loss", "train_tokens_per_s", "train_step_time_s",
    "goodput_fraction", "train_mfu_percent",
    # "where the memory lives" panel (telemetry.memledger scalars).
    "hbm_bytes_in_use", "hbm_headroom_bytes",
    "hbm_tracked_bytes", "hbm_untracked_bytes",
    # SLO panel (telemetry.slo scalars): objectives breaching, the worst
    # burn rate across every (objective, class, window), the tightest
    # remaining error budget.
    "slo_breaching", "slo_worst_burn_rate", "slo_min_budget_remaining",
)

_DASHBOARD_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>dlti live dashboard</title>
<style>
  :root {
    color-scheme: light;
    --surface-1: #fcfcfb; --surface-2: #f2f1ee;
    --text-primary: #0b0b0b; --text-secondary: #52514e;
    --series-1: #2a78d6; --status-bad: #e34948; --grid: #dddcd7;
  }
  @media (prefers-color-scheme: dark) {
    :root {
      color-scheme: dark;
      --surface-1: #1a1a19; --surface-2: #242423;
      --text-primary: #ffffff; --text-secondary: #c3c2b7;
      --series-1: #3987e5; --status-bad: #e66767; --grid: #3a3a38;
    }
  }
  body { margin: 0; padding: 16px 20px; background: var(--surface-1);
         color: var(--text-primary);
         font: 13px/1.45 ui-monospace, SFMono-Regular, Menlo, monospace; }
  h1 { font-size: 15px; margin: 0 0 2px; }
  .sub { color: var(--text-secondary); margin-bottom: 14px; }
  .alerts { border-left: 3px solid var(--status-bad); background:
            var(--surface-2); padding: 6px 10px; margin: 0 0 14px;
            display: none; }
  .alerts.on { display: block; }
  .grid { display: grid; gap: 10px 18px;
          grid-template-columns: repeat(auto-fill, minmax(340px, 1fr)); }
  .card { background: var(--surface-2); border-radius: 6px;
          padding: 8px 12px 6px; }
  .card .name { color: var(--text-secondary); font-size: 12px;
                overflow: hidden; text-overflow: ellipsis;
                white-space: nowrap; }
  .card .val { font-size: 17px; font-weight: 600; }
  .card svg { display: block; width: 100%; height: 36px; margin-top: 2px; }
  .spark { fill: none; stroke: var(--series-1); stroke-width: 2;
           stroke-linejoin: round; stroke-linecap: round; }
  .axis { stroke: var(--grid); stroke-width: 1; }
  details { margin-top: 18px; }
  summary { cursor: pointer; color: var(--text-secondary); }
  table { border-collapse: collapse; margin-top: 8px; }
  td { padding: 1px 14px 1px 0; color: var(--text-secondary); }
  td.v { color: var(--text-primary); text-align: right; }
  .err { color: var(--status-bad); }
</style></head><body>
<h1>dlti live dashboard</h1>
<div class="sub">polling <code>/debug/vars</code> every <span id="iv">2</span>s
  &middot; <span id="stamp">connecting&hellip;</span></div>
<div class="alerts" id="alerts"></div>
<div class="grid" id="cards"></div>
<details open><summary>all values</summary>
  <table id="all"></table></details>
<script>
const PREFERRED = __PREFERRED__;
const POLL_MS = 2000;
document.getElementById('iv').textContent = POLL_MS / 1000;
function fmt(v) {
  if (!isFinite(v)) return String(v);
  if (Math.abs(v) >= 1000) return Math.round(v).toLocaleString();
  return Math.abs(v - Math.round(v)) < 1e-9 ? String(Math.round(v))
       : v.toPrecision(4);
}
function sparkline(pts) {
  const W = 320, H = 36, P = 2;
  if (pts.length < 2) return '<svg viewBox="0 0 ' + W + ' ' + H + '"></svg>';
  const lo = Math.min(...pts), hi = Math.max(...pts), span = (hi - lo) || 1;
  const step = (W - 2 * P) / (pts.length - 1);
  const d = pts.map((v, i) =>
    (i ? 'L' : 'M') + (P + i * step).toFixed(1) + ',' +
    (H - P - (v - lo) / span * (H - 2 * P)).toFixed(1)).join('');
  return '<svg viewBox="0 0 ' + W + ' ' + H + '" preserveAspectRatio="none">' +
    '<line class="axis" x1="0" y1="' + (H - 1) + '" x2="' + W +
    '" y2="' + (H - 1) + '"/><path class="spark" d="' + d + '"/></svg>';
}
function seriesOf(samples, key) {
  const out = [];
  for (const s of samples) if (key in s.values) out.push(s.values[key]);
  return out;
}
async function tick() {
  let d;
  try {
    d = await (await fetch('/debug/vars')).json();
  } catch (e) {
    document.getElementById('stamp').innerHTML =
      '<span class="err">fetch failed: ' + e + '</span>';
    return;
  }
  const latest = d.latest || {}, samples = d.samples || [];
  document.getElementById('stamp').textContent =
    new Date(d.now * 1000).toLocaleTimeString() + ' \\u00b7 ' +
    d.num_samples + ' samples \\u00b7 ' + Object.keys(latest).length +
    ' series';
  // Watchdog alerts get the status treatment: icon + counts, never
  // color alone.
  const alertKeys = Object.keys(latest)
    .filter(k => k.startsWith('dlti_watchdog_alerts_total') && latest[k] > 0);
  const alertBox = document.getElementById('alerts');
  if (alertKeys.length) {
    alertBox.className = 'alerts on';
    alertBox.innerHTML = '&#9888; watchdog alerts: ' + alertKeys.map(k =>
      k.replace('dlti_watchdog_alerts_total', '') + ' = ' +
      fmt(latest[k])).join(' \\u00b7 ');
  } else { alertBox.className = 'alerts'; }
  const keys = PREFERRED.filter(k => k in latest);
  for (const k of Object.keys(latest).sort()) {
    if (!keys.includes(k) && keys.length < 18 &&
        /(_seconds\\.mean|_queue_depth|tokens_per_s)$/.test(k)) keys.push(k);
  }
  document.getElementById('cards').innerHTML = keys.map(k => {
    return '<div class="card"><div class="name">' + k + '</div>' +
      '<div class="val">' + fmt(latest[k]) + '</div>' +
      sparkline(seriesOf(samples, k)) + '</div>';
  }).join('');
  document.getElementById('all').innerHTML = Object.keys(latest).sort()
    .map(k => '<tr><td>' + k + '</td><td class="v">' + fmt(latest[k]) +
              '</td></tr>').join('');
}
tick();
setInterval(tick, POLL_MS);
</script></body></html>
"""


def render_dashboard_html() -> str:
    """The ``GET /dashboard`` body: a self-contained page (inline CSS/JS,
    no external assets) that polls ``/debug/vars`` and renders the
    preferred series as single-series sparklines plus a full value table
    — light/dark via ``prefers-color-scheme``."""
    import json as _json

    return _DASHBOARD_HTML.replace("__PREFERRED__",
                                   _json.dumps(list(_DASH_PREFERRED)))
