"""Metrics registry: labeled counters / gauges / histograms + Prometheus
text exposition.

The single backing store for the server's ``/stats`` and ``/metrics``
endpoints (previously a hand-rolled exposition loop inlined in
``serving/server.py``). Design constraints, in priority order:

1. **Name stability.** The pre-existing ``/metrics`` names
   (``dlti_requests``, ``dlti_free_blocks``, ...) are scraped by external
   dashboards; the registry's scalar exposition reproduces them
   byte-for-byte (``# TYPE`` line + ``name value`` line, sorted by name).
   Engine counters stay owned by the engine (its ``stats`` dict is the
   source of truth, registered here as a *scalar source* callback) so the
   hot decode path never takes a registry lock.
2. **Histograms for request-lifecycle latencies.** TTFT / TPOT /
   queue-time distributions are observed on-engine and exposed in the
   standard Prometheus histogram format (``_bucket{le=...}`` cumulative
   counts + ``_sum`` + ``_count``), so external loadgen percentiles can be
   cross-checked against the engine's own view.
3. **Thread safety.** ``observe``/``inc``/``set`` are called from the
   engine stepper thread while HTTP handler threads render; every mutation
   and snapshot is lock-protected (one lock per metric — contention is
   per-scrape, not per-token).
"""

from __future__ import annotations

import math
import threading
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

# Latency buckets (seconds) sized for LLM serving: sub-ms host paths up to
# multi-minute stragglers. Used for TTFT and queue time.
LATENCY_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)
# Per-output-token latency: decode steps are ms-scale on-chip, seconds
# over a relay link.
TPOT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                0.5, 1.0, 2.5, 5.0)
# Host-side prep work per dispatch (batch assembly, decode-state sync):
# tens of microseconds when clean, low milliseconds when rebuilding.
HOST_PREP_BUCKETS = (1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 0.001,
                     0.0025, 0.005, 0.01, 0.025, 0.05, 0.1)


def _fmt_labels(labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return "{" + inner + "}"


class _Metric:
    """Shared label-child machinery: a metric with no labels uses its
    default child; ``.labels(k=v)`` returns (creating on first use) the
    child for that label set."""

    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._children: Dict[Tuple[Tuple[str, str], ...], object] = {}

    def _new_child(self):
        raise NotImplementedError

    def labels(self, **labels: str):
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._new_child()
            return child

    def _default(self):
        return self.labels()

    def samples(self) -> List[Tuple[str, str, object]]:
        """[(name_with_labels, labels_str, value_snapshot)] under lock."""
        with self._lock:
            return [(self.name, _fmt_labels(key), child)
                    for key, child in sorted(self._children.items())]


class _CounterChild:
    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value


class Counter(_Metric):
    kind = "counter"

    def _new_child(self):
        return _CounterChild()

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    @property
    def value(self) -> float:
        return self._default().value


class _GaugeChild(_CounterChild):
    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)


class Gauge(_Metric):
    kind = "gauge"

    def _new_child(self):
        return _GaugeChild()

    def set(self, value: float) -> None:
        self._default().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default().inc(-amount)

    @property
    def value(self) -> float:
        return self._default().value


class Histogram:
    """Fixed-bucket histogram (Prometheus semantics: ``le`` upper bounds,
    cumulative on exposition). Unlabeled — one instance per series is all
    the engine needs, and it keeps ``observe()`` a couple of adds."""

    kind = "histogram"

    def __init__(self, name: str, buckets: Sequence[float] = LATENCY_BUCKETS,
                 help: str = "", stats_key: Optional[str] = None):
        self.name = name
        self.help = help
        # ``/stats`` key for the summary dict (default: the metric name).
        self.stats_key = stats_key or name
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self._counts = [0] * (len(self.buckets) + 1)  # [+Inf] is last
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        v = float(value)
        i = 0
        for b in self.buckets:  # tiny linear scan beats bisect at n<=16
            if v <= b:
                break
            i += 1
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    def snapshot(self) -> Tuple[List[int], float, int]:
        with self._lock:
            return list(self._counts), self._sum, self._count

    def percentile(self, p: float) -> float:
        """Bucket-interpolated percentile estimate (p in [0, 100])."""
        counts, _, total = self.snapshot()
        if total == 0:
            return 0.0
        target = (p / 100.0) * total
        cum = 0
        lo = 0.0
        for i, c in enumerate(counts):
            prev = cum
            cum += c
            if cum >= target:
                hi = self.buckets[i] if i < len(self.buckets) else lo
                if c == 0 or hi <= lo:
                    return hi
                return lo + (hi - lo) * (target - prev) / c
            lo = self.buckets[i] if i < len(self.buckets) else lo
        return self.buckets[-1] if self.buckets else 0.0

    def summary(self) -> dict:
        """Compact ``/stats`` view of the distribution."""
        _, s, n = self.snapshot()
        return {
            "count": n,
            "sum": round(s, 6),
            "mean": round(s / n, 6) if n else 0.0,
            "p50": round(self.percentile(50), 6),
            "p90": round(self.percentile(90), 6),
            "p99": round(self.percentile(99), 6),
        }

    def render(self) -> List[str]:
        counts, s, n = self.snapshot()
        lines = [f"# TYPE {self.name} {self.kind}"]
        cum = 0
        for b, c in zip(self.buckets, counts):
            cum += c
            le = format(b, "g")
            lines.append(f'{self.name}_bucket{{le="{le}"}} {cum}')
        lines.append(f'{self.name}_bucket{{le="+Inf"}} {n}')
        lines.append(f"{self.name}_sum {s}")
        lines.append(f"{self.name}_count {n}")
        return lines


class _ScalarSource:
    """A callback yielding a dict of raw scalars (e.g. the engine's
    ``stats`` dict plus derived gauges), exposed under ``prefix``."""

    def __init__(self, fn: Callable[[], dict], gauge_keys: Sequence[str],
                 prefix: str):
        self.fn = fn
        self.gauge_keys = frozenset(gauge_keys)
        self.prefix = prefix


class MetricsRegistry:
    """Registry of metrics + scalar sources; renders Prometheus text and a
    raw ``/stats`` dict from one shared store."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}
        self._sources: List[_ScalarSource] = []

    # -- registration ---------------------------------------------------
    def _get_or_create(self, cls, name: str, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, **kwargs)
            elif not isinstance(m, cls):
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help=help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help=help)

    def histogram(self, name: str, buckets: Sequence[float] = LATENCY_BUCKETS,
                  help: str = "") -> Histogram:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = Histogram(name, buckets, help=help)
            elif not isinstance(m, Histogram):
                raise ValueError(f"metric {name!r} is not a histogram")
            return m

    def register(self, metric) -> None:
        """Attach an externally created metric (e.g. the engine's
        request-lifecycle histograms) for exposition."""
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None and existing is not metric:
                raise ValueError(f"metric {metric.name!r} already registered")
            self._metrics[metric.name] = metric

    def add_scalar_source(self, fn: Callable[[], dict],
                          gauge_keys: Sequence[str] = (),
                          prefix: str = "") -> None:
        """Register a callback producing ``{key: number}``; keys in
        ``gauge_keys`` expose as gauges, the rest as counters. Non-numeric
        and bool values are skipped on exposition (kept verbatim in
        :meth:`stats_dict`)."""
        self._sources.append(_ScalarSource(fn, gauge_keys, prefix))

    def metric_names(self) -> List[str]:
        """Every exposition name this registry serves: registered metric
        objects plus the (prefixed) scalar-source keys. The naming-
        convention guard (``tests/test_metric_naming.py``) walks this."""
        with self._lock:
            names = set(self._metrics)
        for name, _, _ in self._scalar_samples():
            names.add(name)
        return sorted(names)

    # -- collection -----------------------------------------------------
    def _scalar_samples(self) -> List[Tuple[str, str, float]]:
        """[(exposition_name, kind, value)] from every scalar source."""
        out = []
        for src in self._sources:
            for k, v in src.fn().items():
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    continue
                kind = "gauge" if k in src.gauge_keys else "counter"
                out.append((f"{src.prefix}{k}", kind, v))
        return out

    def stats_dict(self) -> dict:
        """Raw (unprefixed) scalars + per-histogram summaries — the
        ``/stats`` payload."""
        out: dict = {}
        for src in self._sources:
            for k, v in src.fn().items():
                out[k] = v
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            if isinstance(m, Histogram):
                if m.stats_key not in out:
                    out[m.stats_key] = m.summary()
            elif isinstance(m, (Counter, Gauge)):
                for name, labels, child in m.samples():
                    key = name + labels
                    if key not in out:
                        out[key] = child.value
        return out

    def render_prometheus(self) -> str:
        """Full text exposition (version 0.0.4), sorted by metric name.

        Scalar-source lines reproduce the legacy inline exposition
        byte-for-byte: ``# TYPE <name> <kind>`` then ``<name> <value>``
        with Python's default int/float formatting."""
        blocks: List[Tuple[str, List[str]]] = []
        for name, kind, v in self._scalar_samples():
            blocks.append((name, [f"# TYPE {name} {kind}", f"{name} {v}"]))
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            if isinstance(m, Histogram):
                blocks.append((m.name, m.render()))
            else:
                lines = [f"# TYPE {m.name} {m.kind}"]
                for name, labels, child in m.samples():
                    val = child.value
                    lines.append(f"{name}{labels} {val}")
                if len(lines) > 1:
                    blocks.append((m.name, lines))
        blocks.sort(key=lambda b: b[0])
        lines = [line for _, blk in blocks for line in blk]
        return "\n".join(lines) + "\n"
