"""Fleet-wide distributed tracing: span federation with clock alignment.

The span tracer (``telemetry.tracer``) is strictly per-process — a bounded
ring of Chrome trace events stamped with that process's ``time.monotonic()``
clock. Once serving went multi-process (fleet workers over the wire
protocol, disagg pools, drain migrations, failover resubmits) no single
ring could follow a request gateway → prefill worker → KV handoff → decode
worker → completion. This module federates those rings, Dapper-style:

* **Trace context** — every ``Request`` carries a ``trace_id`` minted at
  the gateway (or at ``submit`` for direct clients) that rides the
  FT_SUBMIT descriptor, handoff envelopes, drain migrations, disagg
  staging, failover resubmits, and shadow-tap replays, so spans emitted in
  any process for any leg of one request share an id
  (:func:`mint_trace_id`).
* **Clock alignment** — ``time.monotonic()`` is per-process: worker span
  timestamps are meaningless on the supervisor's axis until rebased. The
  supervisor samples each RPC round trip (send ``t0``, receive ``t1``,
  worker-reported clock ``tw``) and estimates the worker's clock offset
  NTP-style: ``offset ≈ (t0 + t1)/2 − tw`` with uncertainty ``(t1 − t0)/2``
  (the classic bound — the true offset lies within half the round trip of
  the midpoint estimate), EWMA-smoothed per worker
  (:class:`ClockOffsetEstimator`).
* **Federation** — workers ship bounded span-ring tails in FT_STEP /
  FT_HEALTH replies; the supervisor rebases them onto its own clock and
  merges them into a :class:`TraceFederator` ring tagged with one pid per
  source process (plus ``"ph": "M"`` process_name metadata), so the
  Perfetto export renders one coherent multi-process timeline and
  ``GET /debug/trace?request_id=`` can reconstruct a single request's
  cross-process span tree (:func:`request_timeline`).

Caveat: alignment is an *estimate*. Offsets are only as good as the RPC
round trips that produced them (uncertainty = smoothed half-RTT, exposed
per worker in ``dlti_trace_clock_offset_seconds``); sub-uncertainty
orderings between spans from *different* processes are not trustworthy,
which is why :func:`request_timeline` reports per-leg durations (intra-
process, exact) separately from cross-process wall span.
"""

from __future__ import annotations

import threading
import uuid
from collections import deque
from typing import Dict, Iterable, List, Optional

from dlti_tpu.telemetry.registry import Counter, Gauge

# Scrape contract (pinned in tests/test_bench_contract.py and walked by
# tests/test_metric_naming.py).
TRACE_METRIC_NAMES = (
    "dlti_trace_federated_spans_total",
    "dlti_trace_unparented_spans_total",
    "dlti_trace_clock_offset_seconds",
)

# Module-level like the watchdog/flight counters: every federator in the
# process (serving fleet + both disagg pools) shares one series.
federated_spans_total = Counter(
    TRACE_METRIC_NAMES[0],
    help="remote spans ingested and rebased onto the local clock")
unparented_spans_total = Counter(
    TRACE_METRIC_NAMES[1],
    help="federated spans carrying no request/trace linkage (cannot be "
         "joined into any per-request timeline)")
clock_offset_gauge = Gauge(
    TRACE_METRIC_NAMES[2],
    help="EWMA-smoothed clock offset per worker (local ≈ remote + offset)")


def mint_trace_id() -> str:
    """One trace id per client request — minted once (at the gateway, or
    at ``submit`` for direct clients) and *propagated*, never re-derived,
    so every process that touches any leg of the request agrees on it."""
    return uuid.uuid4().hex[:16]


class ClockOffsetEstimator:
    """NTP-style offset estimator for one remote clock.

    ``sample(t0, t1, remote_time)`` takes the local send/receive
    timestamps around one RPC and the remote ``time.monotonic()`` reading
    taken while serving it. The midpoint estimate ``(t0+t1)/2 − tw`` is
    wrong by at most half the round trip (however asymmetric the two legs
    were, the remote stamp was taken somewhere inside the window), so
    half-RTT is the per-sample uncertainty. Both are EWMA-smoothed; the
    uncertainty term also absorbs observed drift (|raw − smoothed|), so a
    clock that is *moving* reports a wide bound rather than a confident
    stale one.

    Invariant (fixed true offset): ``|offset − true| ≤ uncertainty`` —
    each raw sample is within its half-RTT of the truth, and the
    uncertainty EWMA dominates the error EWMA term-by-term.
    """

    __slots__ = ("alpha", "offset", "uncertainty", "samples", "last_rtt")

    def __init__(self, alpha: float = 0.25):
        self.alpha = alpha
        self.offset = 0.0              # local ≈ remote + offset (seconds)
        self.uncertainty = float("inf")
        self.samples = 0
        self.last_rtt = 0.0

    def sample(self, t0: float, t1: float, remote_time: float) -> None:
        if t1 < t0:                    # clock went backwards locally; skip
            return
        raw = 0.5 * (t0 + t1) - remote_time
        half_rtt = 0.5 * (t1 - t0)
        self.last_rtt = t1 - t0
        if self.samples == 0:
            self.offset = raw
            self.uncertainty = half_rtt
        else:
            a = self.alpha
            drift = abs(raw - self.offset)
            self.offset += a * (raw - self.offset)
            self.uncertainty += a * (max(half_rtt, drift) - self.uncertainty)
        self.samples += 1

    def rebase(self, remote_s: float) -> float:
        """Map a remote ``time.monotonic()`` reading onto the local axis."""
        return remote_s + self.offset

    def to_dict(self) -> dict:
        return {"offset_s": self.offset,
                "uncertainty_s":
                    self.uncertainty if self.samples else None,
                "samples": self.samples,
                "last_rtt_s": self.last_rtt}


class TraceFederator:
    """Supervisor-side merged span ring: remote span tails rebased onto
    the local clock, one synthetic pid per source process.

    Sources are registered by a stable key (worker index). Real pids are
    recorded when known, but the *render* pid is synthetic and stable
    across respawns (``100001 + key``) so a respawned worker keeps its
    Perfetto row; the real pid/generation ride in the process_name
    metadata instead.
    """

    SYNTHETIC_PID_BASE = 100001

    def __init__(self, capacity: int = 65536, alpha: float = 0.25):
        self.capacity = capacity
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=capacity)
        self._dropped = 0              # evicted here (remote drops separate)
        self._remote_dropped = 0       # spans the workers evicted pre-ship
        self._sources: Dict[object, dict] = {}
        self._alpha = alpha

    # -- sources & clocks ----------------------------------------------
    def source(self, key, *, pid: Optional[int] = None,
               label: Optional[str] = None) -> dict:
        """Get-or-create the bookkeeping record for one remote process."""
        with self._lock:
            src = self._sources.get(key)
            if src is None:
                src = self._sources[key] = {
                    "estimator": ClockOffsetEstimator(self._alpha),
                    "pid": None, "label": f"worker{key}",
                    "render_pid": self.SYNTHETIC_PID_BASE + (
                        key if isinstance(key, int) else
                        abs(hash(key)) % 10000),
                }
            if pid is not None:
                src["pid"] = pid
            if label is not None:
                src["label"] = label
            return src

    def estimator(self, key) -> ClockOffsetEstimator:
        return self.source(key)["estimator"]

    def observe_rpc(self, key, t0: float, t1: float,
                    remote_time) -> None:
        """Feed one RPC round trip into the source's clock estimator and
        refresh the per-worker offset gauge."""
        if not isinstance(remote_time, (int, float)):
            return
        est = self.estimator(key)
        est.sample(t0, t1, float(remote_time))
        clock_offset_gauge.labels(worker=str(key)).set(est.offset)

    def offsets(self) -> Dict[str, dict]:
        """Per-source offset estimates (persisted into flight-dump
        context so postmortem --all can rebase dump span tails)."""
        with self._lock:
            items = list(self._sources.items())
        return {str(k): {"label": s["label"], "pid": s["pid"],
                         **s["estimator"].to_dict()}
                for k, s in items}

    # -- ingestion ------------------------------------------------------
    def ingest(self, key, events: Iterable[dict], *,
               remote_dropped: int = 0) -> int:
        """Rebase a shipped span tail onto the local clock and merge it.

        Events arrive as raw Chrome trace dicts on the *remote* clock;
        each is copied (never mutated in place), shifted by the source's
        estimated offset, and re-tagged with the source's render pid.
        """
        src = self.source(key)
        off_us = src["estimator"].offset * 1e6
        n = unparented = 0
        ingested = []
        for ev in events or ():
            if not isinstance(ev, dict):
                continue
            ev = dict(ev)
            ev["ts"] = float(ev.get("ts", 0.0)) + off_us
            ev["pid"] = src["render_pid"]
            args = ev.get("args")
            if not (isinstance(args, dict)
                    and (args.get("id") or args.get("trace"))):
                unparented += 1
            ingested.append(ev)
            n += 1
        if not n and not remote_dropped:
            return 0
        with self._lock:
            for ev in ingested:
                if (self._events.maxlen is not None
                        and len(self._events) == self._events.maxlen):
                    self._dropped += 1
                self._events.append(ev)
            self._remote_dropped += int(remote_dropped)
        if n:
            federated_spans_total.inc(n)
        if unparented:
            unparented_spans_total.inc(unparented)
        return n

    # -- export ---------------------------------------------------------
    @property
    def dropped_events(self) -> int:
        with self._lock:
            return self._dropped + self._remote_dropped

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def metadata_events(self) -> List[dict]:
        """``"ph": "M"`` process_name events — one per source — so
        Perfetto renders each remote process as its own labeled row."""
        with self._lock:
            items = sorted(self._sources.items(), key=lambda kv: str(kv[0]))
        out = []
        for key, src in items:
            name = src["label"]
            if src["pid"]:
                name = f"{name} (pid {src['pid']})"
            out.append({"ph": "M", "name": "process_name", "cat": "__meta",
                        "ts": 0.0, "pid": src["render_pid"], "tid": 0,
                        "args": {"name": name}})
        return out

    def merged_dict(self, local_tracer=None,
                    local_label: str = "supervisor") -> dict:
        """One Perfetto-loadable timeline: local ring + every federated
        remote tail, already on one clock, with per-process metadata."""
        events = self.metadata_events()
        dropped = self.dropped_events
        if local_tracer is not None:
            events.append({"ph": "M", "name": "process_name",
                           "cat": "__meta", "ts": 0.0,
                           "pid": local_tracer._pid,
                           "tid": 0, "args": {"name": local_label}})
            events.extend(local_tracer.events())
            dropped += local_tracer.dropped_events
        events.extend(self.events())
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "droppedEvents": dropped,
                "clockOffsets": self.offsets()}


# ----------------------------------------------------------------------
# Per-request reconstruction
# ----------------------------------------------------------------------

# Lifecycle legs that tile the request's life (gateway queue → engine
# queue → prefill → decode): the union of their intervals is compared
# against client-observed latency. Other legs (kv_handoff staging, retry
# stalls) overlap these and are reported but never counted toward it.
SEQUENTIAL_LEGS = ("gateway/queued", "request/queued",
                   "request/prefill", "request/decode")


def _union_s(intervals: List[tuple]) -> float:
    """Total measure of a union of [start, end] µs intervals, in seconds.

    Union, not sum: a fleet request is observed TWICE per leg — the
    supervisor's mirror and the owning worker each emit e.g.
    ``request/prefill`` for the same request — and after rebasing the two
    observations overlap almost exactly. Summing would double-count;
    the union keeps 'time covered by this leg' exact."""
    if not intervals:
        return 0.0
    intervals = sorted(intervals)
    total = 0.0
    cur_s, cur_e = intervals[0]
    for s, e in intervals[1:]:
        if s > cur_e:
            total += cur_e - cur_s
            cur_s, cur_e = s, e
        else:
            cur_e = max(cur_e, e)
    total += cur_e - cur_s
    return total / 1e6


def _span_matches(ev: dict, request_id: str, trace_id: str) -> bool:
    args = ev.get("args")
    if not isinstance(args, dict):
        return False
    if request_id and args.get("id") == request_id:
        return True
    return bool(trace_id) and args.get("trace") == trace_id


def request_timeline(events: Iterable[dict], request_id: str, *,
                     trace_id: str = "",
                     client_latency_s: Optional[float] = None) -> dict:
    """Assemble one request's merged, clock-aligned span tree.

    ``events`` is any already-rebased event iterable (federator + local
    tracer). Spans join on ``args.id == request_id`` or ``args.trace ==
    trace_id``. Returns the causally-sorted spans, per-leg durations
    (interval *union* per span name — the supervisor mirror and the
    owning worker both observe each lifecycle leg, and the union
    de-duplicates them), the set of source pids, the cross-process wall
    span, and the residual: client-observed latency (when given; else the
    wall span) minus the time covered by the sequential lifecycle legs.
    """
    events = list(events)
    if not trace_id:
        # Allow lookup by trace id alone: pick it up from the first
        # matching span so the caller can pass either handle.
        for ev in events:
            args = ev.get("args")
            if isinstance(args, dict) and args.get("id") == request_id \
                    and args.get("trace"):
                trace_id = str(args["trace"])
                break
    spans = [ev for ev in events
             if ev.get("ph") in ("X", "i")
             and _span_matches(ev, request_id, trace_id)]
    spans.sort(key=lambda ev: (ev.get("ts", 0.0),
                               ev.get("ts", 0.0) + ev.get("dur", 0.0)))
    legs: Dict[str, dict] = {}
    intervals: Dict[str, List[tuple]] = {}
    for ev in spans:
        if ev.get("ph") != "X":
            continue
        name = ev.get("name", "?")
        leg = legs.setdefault(name, {"dur_s": 0.0, "count": 0, "pids": []})
        ts = float(ev.get("ts", 0.0))
        intervals.setdefault(name, []).append(
            (ts, ts + float(ev.get("dur", 0.0))))
        leg["count"] += 1
        pid = ev.get("pid")
        if pid not in leg["pids"]:
            leg["pids"].append(pid)
    for name, leg in legs.items():
        leg["dur_s"] = _union_s(intervals[name])
    t0 = min((ev.get("ts", 0.0) for ev in spans), default=0.0)
    t1 = max((ev.get("ts", 0.0) + ev.get("dur", 0.0) for ev in spans),
             default=0.0)
    wall_s = max(0.0, (t1 - t0) / 1e6)
    # One combined union across the sequential legs: their intervals tile
    # enqueue → finish, and unioning (rather than summing per-leg
    # durations) keeps small cross-process overlaps — a worker's queued
    # leg inside the mirror's prefill window — from double-counting.
    seq_sum = _union_s([iv for name in SEQUENTIAL_LEGS
                        for iv in intervals.get(name, ())])
    baseline = client_latency_s if client_latency_s is not None else wall_s
    return {
        "request_id": request_id,
        "trace_id": trace_id,
        "spans": spans,
        "legs": {name: leg for name, leg in legs.items()},
        "sequential_legs": [n for n in SEQUENTIAL_LEGS if n in legs],
        "sequential_sum_s": seq_sum,
        "processes": sorted({ev.get("pid") for ev in spans
                             if ev.get("pid") is not None}),
        "wall_s": wall_s,
        "client_latency_s": client_latency_s,
        "residual_s": baseline - seq_sum,
    }


# ----------------------------------------------------------------------
# Flight-dump merging (postmortem --all)
# ----------------------------------------------------------------------

def merge_dump_tails(dumps: Iterable[dict]) -> dict:
    """Merge per-process flight-dump span tails onto one clock.

    Each entry: ``{"label", "pid", "offset_s", "uncertainty_s", "events",
    "dropped"}`` where ``offset_s`` maps that process's clock onto the
    reference (supervisor) clock — the value the worker persisted into its
    dump context from the supervisor's estimator (0 for the supervisor's
    own dump). Returns a Perfetto-loadable dict; distinct pids per source
    keep each process on its own row even when thread-fleet fakes share
    one real pid.
    """
    events: List[dict] = []
    meta: List[dict] = []
    sources: List[dict] = []
    dropped = 0
    for i, d in enumerate(sorted(dumps, key=lambda d: str(d.get("label")))):
        pid = d.get("pid") or (TraceFederator.SYNTHETIC_PID_BASE + i)
        label = str(d.get("label") or f"process{i}")
        off = d.get("offset_s") or 0.0
        unc = d.get("uncertainty_s")
        name = label if unc is None else f"{label} (±{unc * 1e3:.2f}ms)"
        meta.append({"ph": "M", "name": "process_name", "cat": "__meta",
                     "ts": 0.0, "pid": pid, "tid": 0,
                     "args": {"name": name}})
        sources.append({"label": label, "pid": pid, "offset_s": off,
                        "uncertainty_s": unc})
        dropped += int(d.get("dropped") or 0)
        for ev in d.get("events") or ():
            if not isinstance(ev, dict):
                continue
            ev = dict(ev)
            ev["ts"] = float(ev.get("ts", 0.0)) + off * 1e6
            ev["pid"] = pid
            events.append(ev)
    events.sort(key=lambda ev: ev.get("ts", 0.0))
    return {"traceEvents": meta + events, "displayTimeUnit": "ms",
            "droppedEvents": dropped, "sources": sources}
