"""Per-step JSONL telemetry stream (rank-0) for training runs.

MegaScale attributes large-scale training goodput recovery chiefly to
in-framework per-step instrumentation; this is the stream that makes that
possible here. Three record types, one JSON object per line:

* ``{"type": "run", ...}``   — run-level metadata, written once at start
  (experiment name, chip count, strategy — the identifying half of the
  reference CSV schema).
* ``{"type": "step", ...}``  — one per optimizer step: step, loss,
  grad_norm, lr, tokens/s/chip, MFU, HBM peak (+ its source) and the
  measured step wall time.
* ``{"type": "final", ...}`` — the full :class:`MetricsRecord` dict at run
  end, which makes the stream a strict superset of the reference CSV
  columns by construction (guarded by ``tests/test_telemetry.py``).

Lines are flushed per write so a preempted run's stream is readable up to
the last completed step. Writes go through the durable writer's
drop-and-count stream (``path_class="steplog"``): an EIO/ENOSPC on the
telemetry disk costs log lines (counted in ``dlti_disk_write_errors_total``
and the writer's ``dropped``), never a training step.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
from typing import Optional

from dlti_tpu.config import OptimizerConfig
from dlti_tpu.utils import durable_io
from dlti_tpu.utils.metrics import MetricsRecord

# Keys every "step" record carries (the per-step contract; the schema test
# asserts run ∪ step ∪ final covers the reference CSV columns). The
# sentinel fields (PR 8): `anomaly` is "" for a clean step or the verdict
# kind (nonfinite | loss_spike | grad_spike), `skipped_update` marks
# optimizer updates the in-step nonfinite gate skipped, and
# `rollbacks_total` is the run's cumulative automatic-rollback count —
# the triple an incident reader greps first. The goodput-ledger fields
# (PR 9, telemetry.ledger): per-phase wall clock accrued around this
# step — data/prefetch stall, device sync, checkpoint save+restore, and
# rollback+replay — divided evenly across a steps_per_sync window's
# records (checkpoint time issued after a record books to the next one).
# All 0.0 when the ledger is disabled. The memory-ledger fields (PR 11,
# telemetry.memledger): device bytes in use and the remaining headroom at
# this step's bookkeeping boundary — the per-step twins of the goodput
# phase fields, on the bytes axis. hbm_headroom_bytes is -1 when
# capacity is unknown (CPU runs without a configured budget); both are 0
# when the memory ledger is disabled.
STEP_RECORD_FIELDS = (
    "type", "step", "loss", "grad_norm", "lr",
    "tokens_per_second_per_chip", "mfu_percent",
    "peak_memory_gb", "peak_memory_source", "step_time_s",
    "anomaly", "skipped_update", "rollbacks_total",
    "data_wait_s", "sync_s", "ckpt_s", "rollback_s",
    "hbm_bytes_in_use", "hbm_headroom_bytes",
)

RUN_RECORD_FIELDS = ("type", "experiment", "num_gpus", "zero_stage",
                     "strategy")


def metrics_csv_columns() -> tuple:
    """The reference-parity CSV schema (``utils.metrics.MetricsRecord``)."""
    return tuple(f.name for f in dataclasses.fields(MetricsRecord))


def jsonl_stream_columns() -> frozenset:
    """Union of keys the writer can emit across record types."""
    return frozenset(STEP_RECORD_FIELDS) | frozenset(RUN_RECORD_FIELDS) \
        | frozenset(metrics_csv_columns())


def schedule_lr(cfg: OptimizerConfig, step: int) -> float:
    """Host-side mirror of ``training.optimizer.build_schedule`` — the lr
    at ``step`` without a device round trip per logged step."""
    lr, w = cfg.learning_rate, max(cfg.warmup_steps, 1)
    if cfg.schedule == "warmup_constant":
        if cfg.warmup_steps <= 0:
            return lr
        return lr * min(1.0, step / w)
    if cfg.schedule == "warmup_cosine":
        total = max(cfg.total_steps, cfg.warmup_steps + 1)
        if step < w:
            return lr * step / w
        frac = min(1.0, (step - w) / max(1, total - w))
        return lr * 0.5 * (1.0 + math.cos(math.pi * frac))
    raise ValueError(f"unknown schedule {cfg.schedule!r}")


class StepLogWriter:
    """Append-mode JSONL writer; one instance per (rank-0) training run.

    Telemetry criticality: a failed write is dropped and counted, never
    raised — the step loop must survive a sick telemetry disk."""

    def __init__(self, path: str, run_meta: Optional[dict] = None):
        self.path = path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._w = durable_io.LineWriter(path, path_class="steplog")
        if run_meta is not None:
            self._write({"type": "run", **run_meta})

    def _write(self, obj: dict) -> None:
        self._w.write_line(json.dumps(obj))

    @property
    def dropped(self) -> int:
        """Lines lost to I/O errors (drop-and-count contract)."""
        return self._w.dropped

    def log_step(self, step: int, **fields) -> None:
        self._write({"type": "step", "step": step, **fields})

    def log_final(self, record: "MetricsRecord | dict") -> None:
        row = record.to_dict() if isinstance(record, MetricsRecord) \
            else dict(record)
        self._write({"type": "final", **row})

    def close(self) -> None:
        self._w.close()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False
