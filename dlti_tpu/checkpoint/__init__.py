"""Checkpoint / resume / export (SURVEY.md §5.4).

Crash-consistent in-tree checkpoint store (``checkpoint.store``): atomic
finalize (per-array SHA-256 manifest + commit marker written last, then a
directory rename), async saves with bounded retry/backoff, digest-verified
resume with quarantine-and-fall-back for incomplete or corrupt
checkpoints, and a sidecar carrying the data-pipeline cursor + rng
schedule so a resumed run replays a bit-identical loss trajectory.
Rotation (``save_steps=100, save_total_limit=3`` parity,
``train_deepspeed_zero1.py:243-245``), scan-latest resume
(``train_deepspeed_zero1.py:267-279``), and consolidated merged-LoRA
export (the ``stage3_gather_16bit_weights_on_model_save`` + PEFT-merge
capability, ``configs/ds_config_zero3.json:36``) carry over from the
earlier Orbax backend, which this store replaced (its tensorstore restore
corrupts the heap under the persistent XLA compilation cache, and its
OCDBT format is opaque to content verification).
"""

from dlti_tpu.checkpoint.store import (  # noqa: F401
    CKPT_METRIC_NAMES,
    CheckpointCorruptError,
    latest_step,
    latest_verified_step,
    list_checkpoint_steps,
    load_train_meta,
    quarantine_step,
    manifest_digest,
    restore_latest_verified,
    restore_train_state,
    save_train_state,
    verify_checkpoint,
    verify_pytree_dir,
    wait_for_saves,
)
from dlti_tpu.checkpoint.export import (  # noqa: F401
    export_merged_model,
    export_params_host,
    load_exported_model,
)
