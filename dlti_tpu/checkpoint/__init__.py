"""Checkpoint / resume / export (SURVEY.md §5.4).

Orbax-backed async sharded checkpointing with rotation (the reference's
``save_steps=100, save_total_limit=3`` contract,
``train_deepspeed_zero1.py:243-245``), scan-latest resume
(``train_deepspeed_zero1.py:267-279``), and consolidated merged-LoRA export
(the ``stage3_gather_16bit_weights_on_model_save`` + PEFT-merge capability,
``configs/ds_config_zero3.json:36``).
"""

from dlti_tpu.checkpoint.orbax_io import (  # noqa: F401
    latest_step,
    list_checkpoint_steps,
    restore_train_state,
    save_train_state,
    wait_for_saves,
)
from dlti_tpu.checkpoint.export import (  # noqa: F401
    export_merged_model,
    load_exported_model,
)
