"""Orbax-backed sharded train-state checkpointing.

Replaces the DeepSpeed/HF checkpoint dirs the reference relies on:

* step-keyed directories with rotation (``save_total_limit`` parity)
* async save (preemption-friendly; the reference's "save more frequently for
  cluster resilience" intent, ``train_deepspeed_zero1.py:242-245``)
* sharded-aware restore: arrays come back with the *current* state's
  shardings, so a run can resume onto a different mesh shape than it saved
  from (capability the reference lacks entirely).
"""

from __future__ import annotations

import os
from typing import List, Optional

import jax
import orbax.checkpoint as ocp

from dlti_tpu.training.state import TrainState

# directory -> (manager, (keep, async_save) it was created with)
_managers: dict = {}


def _manager(directory: str, keep: Optional[int] = None,
             async_save: bool = True, for_save: bool = False) -> ocp.CheckpointManager:
    """One CheckpointManager per directory.

    Keyed by directory only: two live managers with different retention on
    the same directory race each other's rotation bookkeeping during async
    saves. Read-only callers (restore) reuse whatever exists; a *save* with
    different options closes and recreates the manager, so a read-only
    manager created first (the resume-scan path) cannot silently disable
    ``save_total_limit`` rotation.
    """
    directory = os.path.abspath(directory)
    cached = _managers.get(directory)
    if cached is not None:
        mgr, opts = cached
        if not for_save or opts == (keep, async_save):
            return mgr
        mgr.wait_until_finished()
        mgr.close()
        del _managers[directory]
    options = ocp.CheckpointManagerOptions(
        max_to_keep=keep,
        enable_async_checkpointing=async_save,
        create=True,
    )
    mgr = ocp.CheckpointManager(directory, options=options)
    _managers[directory] = (mgr, (keep, async_save))
    return mgr


def save_train_state(directory: str, step: int, state: TrainState,
                     keep: Optional[int] = 3, async_save: bool = True) -> None:
    mgr = _manager(directory, keep, async_save, for_save=True)
    mgr.save(step, args=ocp.args.StandardSave(state))


def wait_for_saves(directory: str) -> None:
    cached = _managers.get(os.path.abspath(directory))
    if cached is not None:
        cached[0].wait_until_finished()


def list_checkpoint_steps(directory: str) -> List[int]:
    """Enumerate completed checkpoint steps by scanning the directory —
    no CheckpointManager is constructed for read-only introspection."""
    if not os.path.isdir(directory):
        return []
    cached = _managers.get(os.path.abspath(directory))
    if cached is not None:
        return sorted(cached[0].all_steps())
    steps = []
    for name in os.listdir(directory):
        path = os.path.join(directory, name)
        # Completed Orbax step dirs are bare integers; in-flight saves live
        # in "<step>.orbax-checkpoint-tmp-*" dirs, which isdigit filters.
        if name.isdigit() and os.path.isdir(path):
            steps.append(int(name))
    return sorted(steps)


def latest_step(directory: str) -> Optional[int]:
    """Scan for the newest checkpoint (``train_deepspeed_zero1.py:267-279``
    contract: highest-numbered checkpoint dir, None if none)."""
    steps = list_checkpoint_steps(directory)
    return steps[-1] if steps else None


def restore_train_state(directory: str, step: int, target: TrainState) -> TrainState:
    """Restore into the structure/shardings of ``target``.

    ``target`` is a live (possibly sharded) TrainState template — typically
    a freshly initialized one; restored arrays adopt its shardings, which is
    what makes cross-mesh-shape resume work.
    """
    mgr = _manager(directory)
    abstract = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=getattr(x, "sharding", None))
        if hasattr(x, "shape") else x,
        target,
    )
    return mgr.restore(step, args=ocp.args.StandardRestore(abstract))
