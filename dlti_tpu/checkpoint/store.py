"""Crash-consistent train-state checkpoint store.

Replaces the Orbax/tensorstore backend for *train-state* checkpoints with
an in-tree store built around an explicit atomic-finalize protocol, so
every failure mode has a defined, tested recovery:

* **Atomic commit.** A save writes everything into a ``.tmp-<step>-*``
  staging dir (array shards, sidecar, ``MANIFEST.json`` with per-file
  SHA-256 digests, then a ``COMMIT`` marker carrying the manifest's own
  digest, in that order, each fsynced), and only then renames the staging
  dir to the bare-integer step dir. A reader can never observe a
  half-written committed checkpoint: a kill mid-save leaves a ``.tmp-*``
  dir that the resume scan quarantines.
* **Verified resume.** ``latest_verified_step`` / ``restore_latest_verified``
  walk committed steps newest-first, re-hash every file against the
  manifest, and *quarantine* (rename into ``_quarantine/``, count, log)
  anything incomplete or corrupt — truncated files, bit flips, missing
  commit markers — falling back to the newest checkpoint that proves out
  instead of crashing.
* **Bounded retry.** Transient write failures retry with exponential
  backoff (``dlti_ckpt_save_retries``); a save that exhausts its retries
  logs loudly and training continues (a failed save must not kill the
  run that would produce the next one).
* **Async by default.** The device→host snapshot happens on the caller's
  thread (the state may be donated by the very next step); file I/O,
  hashing, and the commit rename run on a per-directory writer thread.
  ``wait_for_saves`` joins the queue — the Trainer calls it on every exit
  path.

Why not Orbax here: on this image the tensorstore restore path corrupts
the process heap when the XLA persistent compilation cache is enabled
(the long-standing train→resume segfault in ``tests/test_e2e.py``), and
its OCDBT on-disk format is opaque to content verification. Arrays are
stored as raw little-endian buffers (``train_state/l<idx>.bin``) named in
``MANIFEST.json`` with their pytree path, shape, and dtype — every byte
on disk is hashable and attributable. Restore reads host-side and places
onto the *target* state's shardings, which preserves the cross-mesh-shape
resume capability the Orbax path had.

Checkpoint layout (``<dir>/<step>/``)::

    train_state/l00000.bin ...   raw array bytes (little-endian, C order)
    train_meta.json              sidecar: data cursor, rng schedule, seeds
    MANIFEST.json                {leaves: [{name, shape, dtype, file,
                                 size, sha256}], meta_files: {...}}
    COMMIT                       {"manifest_sha256": ...} — written last

Telemetry (names pinned in ``tests/test_bench_contract.py``):
save/restore duration histograms, corrupt-skipped + save-retry counters,
and a last-verified-step gauge.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import queue
import re
import shutil
import threading
import time
from typing import Any, List, Optional, Tuple

import jax
import numpy as np

from dlti_tpu.telemetry.registry import Counter, Gauge, Histogram
from dlti_tpu.utils import durable_io
from dlti_tpu.utils.logging import get_logger

_FORMAT_VERSION = 1
_MANIFEST = "MANIFEST.json"
_COMMIT = "COMMIT"
_SIDECAR = "train_meta.json"
_ARRAY_DIR = "train_state"
_TMP_PREFIX = ".tmp-"
_QUARANTINE_DIR = "_quarantine"

# Checkpoint I/O spans milliseconds (tiny test states) to minutes (7B
# trees on network filesystems).
CKPT_IO_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                   5.0, 10.0, 30.0, 60.0, 120.0, 300.0)

# Exposition-name contract (pinned in tests/test_bench_contract.py, like
# the gateway and prefetch metric sets).
CKPT_METRIC_NAMES = (
    "dlti_ckpt_save_seconds",
    "dlti_ckpt_restore_seconds",
    "dlti_ckpt_corrupt_skipped",
    "dlti_ckpt_save_retries",
    "dlti_ckpt_last_verified_step",
)

save_seconds = Histogram(
    CKPT_METRIC_NAMES[0], CKPT_IO_BUCKETS,
    help="checkpoint write+commit duration (writer thread)",
    stats_key="ckpt_save_seconds")
restore_seconds = Histogram(
    CKPT_METRIC_NAMES[1], CKPT_IO_BUCKETS,
    help="checkpoint read+place duration",
    stats_key="ckpt_restore_seconds")
corrupt_skipped = Counter(
    CKPT_METRIC_NAMES[2],
    help="checkpoints quarantined as incomplete or corrupt")
save_retries = Counter(
    CKPT_METRIC_NAMES[3],
    help="checkpoint save attempts retried after an I/O failure")
last_verified_step = Gauge(
    CKPT_METRIC_NAMES[4],
    help="newest checkpoint step that passed digest verification")


class CheckpointCorruptError(RuntimeError):
    """A checkpoint failed integrity verification (truncated / bit-flipped
    / missing commit marker). Resume paths quarantine and fall back."""


# ----------------------------------------------------------------------
# Leaf codec: jax/np array <-> raw bytes + (name, shape, dtype) metadata
# ----------------------------------------------------------------------

def _leaf_entries(state: Any) -> Tuple[List[dict], List[bytes]]:
    """Snapshot every array leaf to host bytes NOW (the caller may donate
    the device buffers to the next step immediately after)."""
    leaves_with_path, _ = jax.tree_util.tree_flatten_with_path(state)
    # Multi-host: consolidate every cross-process leaf to a full host
    # array in ONE jitted replicate launch (collective — every process
    # participates; rank 0 alone writes files). One launch, not one
    # process_allgather per leaf: on the gloo CPU backend, dozens of
    # tiny back-to-back cross-process launches intermittently wedge or
    # abort ("op.preamble.length <= op.nbytes") when one rank enters
    # launch n+1 while its peer still drains launch n's socket buffers —
    # a single launch gives XLA one rendezvous and per-op channel ids.
    # Consolidated checkpoints also make resume onto a different process
    # count trivial.
    cross = [i for i, (_, leaf) in enumerate(leaves_with_path)
             if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable]
    consolidated: dict = {}
    if cross:
        from jax.sharding import GSPMDSharding

        ins = [leaves_with_path[i][1] for i in cross]
        reps = [GSPMDSharding.get_replicated(x.sharding._device_assignment)
                for x in ins]
        outs = jax.jit(lambda xs: xs, out_shardings=reps)(ins)
        for i, out in zip(cross, outs):
            consolidated[i] = np.asarray(out.addressable_data(0))
    metas, payloads = [], []
    for i, (path, leaf) in enumerate(leaves_with_path):
        if i in consolidated:
            host = consolidated[i]
        else:
            host = np.asarray(jax.device_get(leaf))
        if not host.flags["C_CONTIGUOUS"]:
            # Note: ascontiguousarray promotes 0-d to 1-d, hence the guard
            # (0-d is always contiguous).
            host = np.ascontiguousarray(host)
        metas.append({
            "name": jax.tree_util.keystr(path),
            "shape": list(host.shape),
            "dtype": host.dtype.name,
            "file": f"{_ARRAY_DIR}/l{i:05d}.bin",
        })
        payloads.append(host.tobytes())
    return metas, payloads


def _decode_leaf(raw: bytes, meta: dict) -> np.ndarray:
    # np.dtype resolves ml_dtypes names (bfloat16, ...) once jax is
    # imported, which registers them.
    dtype = np.dtype(meta["dtype"])
    arr = np.frombuffer(raw, dtype=dtype)
    return arr.reshape(tuple(meta["shape"]))


def _sha256_bytes(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _sha256_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def _fsync_write(path: str, data: bytes,
                 path_class: str = "checkpoint") -> None:
    # Durable-writer policy (dlti_tpu.utils.durable_io): transient errnos
    # retry with backoff, ENOSPC reclaims quarantine/dump/cold-tier space
    # then retries, persistent failure re-raises for the caller's
    # skip-and-alert / degrade fallback.
    durable_io.write_bytes(path, data, path_class=path_class, fsync=True)


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # some filesystems refuse O_RDONLY on dirs; best effort
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


# ----------------------------------------------------------------------
# Async writer: one thread + FIFO queue per checkpoint directory
# ----------------------------------------------------------------------

@dataclasses.dataclass
class _PendingSave:
    step: int
    leaf_metas: List[dict]
    payloads: List[bytes]
    train_meta: Optional[dict]
    keep: Optional[int]
    retries: int
    retry_backoff_s: float
    # Durable-writer criticality class: "checkpoint" for train state,
    # "adapter" / "prefix_tier" when save_pytree serves those callers.
    path_class: str = "checkpoint"


class _Writer:
    def __init__(self, directory: str):
        self.directory = directory
        self._q: "queue.Queue[Optional[_PendingSave]]" = queue.Queue()
        self._idle = threading.Event()
        self._idle.set()
        self.last_error: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._run, name="dlti-ckpt-writer", daemon=True)
        self._thread.start()

    def submit(self, pending: _PendingSave) -> None:
        self._idle.clear()
        self._q.put(pending)

    def wait(self) -> None:
        self._q.join()
        self._idle.wait()

    @property
    def busy(self) -> bool:
        return not self._idle.is_set()

    def _run(self) -> None:
        while True:
            pending = self._q.get()
            try:
                if pending is not None:
                    _write_and_commit(self.directory, pending)
            except BaseException as e:  # noqa: BLE001 — logged, not fatal
                self.last_error = e
                get_logger().error(
                    "checkpoint save at step %s FAILED after retries: %s",
                    getattr(pending, "step", "?"), e)
            finally:
                self._q.task_done()
                if self._q.unfinished_tasks == 0:
                    self._idle.set()


_writers: dict = {}
_writers_lock = threading.Lock()


def _writer(directory: str) -> _Writer:
    directory = os.path.abspath(directory)
    with _writers_lock:
        w = _writers.get(directory)
        if w is None:
            w = _writers[directory] = _Writer(directory)
            # ENOSPC escape hatch: this directory's quarantined wreckage
            # is the first thing a reclaim pass quota-evicts.
            durable_io.register_reclaimer(
                f"ckpt-quarantine:{directory}",
                durable_io.quarantine_reclaimer(directory))
        return w


def _write_and_commit(directory: str, p: _PendingSave) -> None:
    """Full atomic-finalize protocol, with bounded retry/backoff."""
    t0 = time.perf_counter()
    final = os.path.join(directory, str(p.step))
    attempt = 0
    while True:
        tmp = os.path.join(
            directory, f"{_TMP_PREFIX}{p.step}-{os.getpid()}-{attempt}")
        try:
            if os.path.isdir(final):
                return  # idempotent: this step is already committed
            _write_staging(tmp, p)
            durable_io.replace(tmp, final, path_class=p.path_class)
            _fsync_dir(directory)
            break
        except Exception:
            shutil.rmtree(tmp, ignore_errors=True)
            attempt += 1
            if attempt > max(0, p.retries):
                raise
            save_retries.inc()
            time.sleep(p.retry_backoff_s * (2 ** (attempt - 1)))
    if p.keep:
        _rotate(directory, p.keep)
    last_verified_step.set(p.step)
    save_seconds.observe(time.perf_counter() - t0)


def _write_staging(tmp: str, p: _PendingSave) -> None:
    os.makedirs(os.path.join(tmp, _ARRAY_DIR), exist_ok=True)
    manifest: dict = {
        "format": _FORMAT_VERSION,
        "step": p.step,
        "leaves": [],
        "meta_files": {},
    }
    for meta, payload in zip(p.leaf_metas, p.payloads):
        _fsync_write(os.path.join(tmp, meta["file"]), payload,
                     p.path_class)
        entry = dict(meta)
        entry["size"] = len(payload)
        entry["sha256"] = _sha256_bytes(payload)
        manifest["leaves"].append(entry)
    if p.train_meta is not None:
        data = json.dumps(p.train_meta, indent=2, sort_keys=True).encode()
        _fsync_write(os.path.join(tmp, _SIDECAR), data, p.path_class)
        manifest["meta_files"][_SIDECAR] = {
            "size": len(data), "sha256": _sha256_bytes(data)}
    mbytes = json.dumps(manifest, indent=2, sort_keys=True).encode()
    _fsync_write(os.path.join(tmp, _MANIFEST), mbytes, p.path_class)
    # The commit marker is written LAST and names the manifest's digest:
    # a torn copy of this directory (e.g. a partial rsync, or a non-atomic
    # rename on an exotic filesystem) cannot present a valid COMMIT over a
    # mismatched manifest.
    _fsync_write(os.path.join(tmp, _COMMIT), json.dumps(
        {"manifest_sha256": _sha256_bytes(mbytes)}).encode(),
        p.path_class)
    _fsync_dir(os.path.join(tmp, _ARRAY_DIR))
    _fsync_dir(tmp)


def _rotate(directory: str, keep: int) -> None:
    steps = list_checkpoint_steps(directory)
    for step in steps[:-keep] if keep > 0 else []:
        shutil.rmtree(os.path.join(directory, str(step)),
                      ignore_errors=True)


# ----------------------------------------------------------------------
# Public API (same surface the Orbax backend exposed, plus verification)
# ----------------------------------------------------------------------

def save_train_state(directory: str, step: int, state: Any,
                     keep: Optional[int] = 3, async_save: bool = True,
                     train_meta: Optional[dict] = None,
                     retries: int = 3,
                     retry_backoff_s: float = 0.2) -> None:
    """Checkpoint ``state`` under ``directory/step`` atomically.

    The device→host snapshot is taken synchronously (the caller may donate
    the state to the next step right after this returns); writing,
    hashing, and the commit rename happen on the directory's writer thread
    when ``async_save`` (call :func:`wait_for_saves` to settle them).
    """
    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    leaf_metas, payloads = _leaf_entries(state)  # collective multi-host
    if jax.process_count() > 1 and jax.process_index() != 0:
        return  # rank 0 writes the consolidated checkpoint
    pending = _PendingSave(
        step=int(step), leaf_metas=leaf_metas, payloads=payloads,
        train_meta=train_meta, keep=keep, retries=retries,
        retry_backoff_s=retry_backoff_s)
    if async_save:
        _writer(directory).submit(pending)
    else:
        _write_and_commit(directory, pending)


def wait_for_saves(directory: str) -> None:
    """Block until every queued async save for ``directory`` has committed
    (or exhausted its retries — failures are logged, not raised, so exit
    paths can settle saves without masking the original exception)."""
    w = _writers.get(os.path.abspath(directory))
    if w is not None:
        w.wait()


def list_checkpoint_steps(directory: str) -> List[int]:
    """Committed (renamed-into-place) checkpoint steps, ascending. Staging
    (``.tmp-*``) and quarantined dirs are never listed."""
    if not os.path.isdir(directory):
        return []
    steps = []
    for name in os.listdir(directory):
        if name.isdigit() and os.path.isdir(os.path.join(directory, name)):
            steps.append(int(name))
    return sorted(steps)


def latest_step(directory: str) -> Optional[int]:
    """Newest committed step (no content verification — see
    :func:`latest_verified_step` for the resume-grade scan)."""
    steps = list_checkpoint_steps(directory)
    return steps[-1] if steps else None


def verify_checkpoint(directory: str, step: int) -> Tuple[bool, str]:
    """Deep integrity check: commit marker present, manifest digest
    matches the marker, and every listed file exists with the recorded
    size and SHA-256. Returns (ok, reason)."""
    return _verify_root(os.path.join(os.path.abspath(directory), str(step)))


def verify_pytree_dir(directory: str) -> Tuple[bool, str]:
    """The same deep integrity check for a :func:`save_pytree` artifact
    (an export dir rather than a numbered step dir) — the re-verify the
    rolling-reload path runs immediately before each per-replica swap, so
    an export corrupted mid-roll aborts the roll instead of canary-failing
    halfway through it. Returns (ok, reason)."""
    return _verify_root(os.path.abspath(directory))


def manifest_digest(directory: str) -> Optional[str]:
    """The committed manifest's SHA-256 for a checkpoint step dir or a
    :func:`save_pytree` export dir — the identity deploy/promote paths pin
    ("which bytes is the fleet serving"). None when the dir has no commit
    marker or it is unreadable."""
    try:
        with open(os.path.join(os.path.abspath(directory), _COMMIT),
                  "rb") as f:
            return json.loads(f.read()).get("manifest_sha256")
    except (OSError, ValueError):
        return None


def _verify_root(root: str) -> Tuple[bool, str]:
    commit_path = os.path.join(root, _COMMIT)
    manifest_path = os.path.join(root, _MANIFEST)
    if not os.path.isfile(commit_path):
        return False, "missing-commit"
    if not os.path.isfile(manifest_path):
        return False, "missing-manifest"
    try:
        with open(manifest_path, "rb") as f:
            mbytes = f.read()
        commit = json.loads(open(commit_path, "rb").read())
        if commit.get("manifest_sha256") != _sha256_bytes(mbytes):
            return False, "manifest-digest-mismatch"
        manifest = json.loads(mbytes)
    except (ValueError, OSError):
        return False, "bad-manifest"
    entries = list(manifest.get("leaves", []))
    entries += [dict(v, file=k)
                for k, v in manifest.get("meta_files", {}).items()]
    for entry in entries:
        path = os.path.join(root, entry["file"])
        if not os.path.isfile(path):
            return False, f"missing-file:{entry['file']}"
        if os.path.getsize(path) != entry["size"]:
            return False, f"size-mismatch:{entry['file']}"
        if _sha256_file(path) != entry["sha256"]:
            return False, f"digest-mismatch:{entry['file']}"
    return True, "ok"


def quarantine_step(directory: str, name: str, reason: str) -> Optional[str]:
    """Move a checkpoint (or staging dir) aside instead of deleting it —
    the bytes stay available for forensics; the resume scan stops seeing
    it. Returns the quarantine path."""
    directory = os.path.abspath(directory)
    src = os.path.join(directory, name)
    if not os.path.exists(src):
        return None
    qdir = os.path.join(directory, _QUARANTINE_DIR)
    os.makedirs(qdir, exist_ok=True)
    safe_reason = "".join(c if c.isalnum() or c in "-_." else "-"
                          for c in reason)
    k = 0
    while True:
        dst = os.path.join(qdir, f"{name.lstrip('.')}__{safe_reason}__{k}")
        if not os.path.exists(dst):
            break
        k += 1
    durable_io.replace(src, dst, path_class="checkpoint")
    # Quarantined wreckage is reclaimable the moment it exists (the
    # async-writer path registers this too; save_pytree-only directories
    # — adapters, tier blocks — get their hatch here).
    durable_io.register_reclaimer(
        f"ckpt-quarantine:{directory}",
        durable_io.quarantine_reclaimer(directory))
    corrupt_skipped.inc()
    get_logger().warning(
        "quarantined checkpoint %s (%s) -> %s", src, reason, dst)
    return dst


def latest_verified_step(directory: str) -> Optional[int]:
    """Newest step that passes :func:`verify_checkpoint`. Anything newer
    that fails is quarantined (renamed, counted, logged) so the next scan
    does not re-pay its verification cost. Stale staging dirs from killed
    saves are quarantined too."""
    directory = os.path.abspath(directory)
    if not os.path.isdir(directory):
        return None
    w = _writers.get(directory)
    if w is None or not w.busy:
        # A kill mid-async-save leaves a .tmp-* staging dir; with no
        # writer active it can only be stale.
        for name in sorted(os.listdir(directory)):
            if name.startswith(_TMP_PREFIX):
                quarantine_step(directory, name, "incomplete-save")
    for step in reversed(list_checkpoint_steps(directory)):
        ok, reason = verify_checkpoint(directory, step)
        if ok:
            last_verified_step.set(step)
            return step
        quarantine_step(directory, str(step), reason)
    return None


def load_train_meta(directory: str, step: int) -> Optional[dict]:
    """The sidecar written alongside the arrays (data-pipeline cursor, rng
    schedule, seeds). None for checkpoints saved without one."""
    path = os.path.join(os.path.abspath(directory), str(step), _SIDECAR)
    if not os.path.isfile(path):
        return None
    with open(path) as f:
        return json.load(f)


def restore_train_state(directory: str, step: int, target: Any) -> Any:
    """Restore into the structure/shardings of ``target``.

    ``target`` is a live (possibly sharded) state template — typically a
    freshly initialized one; arrays are read host-side and placed with the
    template's shardings, so a run can resume onto a different mesh shape
    than it saved from. Raises :class:`CheckpointCorruptError` on
    unreadable/corrupt data and ``ValueError`` on a genuine structure
    mismatch (different model/optimizer config)."""
    t0 = time.perf_counter()
    root = os.path.join(os.path.abspath(directory), str(step))
    manifest_path = os.path.join(root, _MANIFEST)
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointCorruptError(
            f"unreadable manifest for step {step} under {directory}: {e}"
        ) from e
    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(target)
    entries = manifest.get("leaves", [])
    if len(entries) != len(leaves_with_path):
        raise ValueError(
            f"checkpoint step {step} has {len(entries)} array leaves but "
            f"the target state has {len(leaves_with_path)} — the run "
            "config (model/optimizer/LoRA/fp16) does not match the "
            "checkpoint")
    placed = []
    for entry, (path, leaf) in zip(entries, leaves_with_path):
        name = jax.tree_util.keystr(path)
        if entry["name"] != name:
            raise ValueError(
                f"checkpoint leaf {entry['name']!r} does not line up with "
                f"target leaf {name!r} (structure mismatch)")
        want_shape = tuple(entry["shape"])
        want_dtype = entry["dtype"]
        t_shape = tuple(getattr(leaf, "shape", ()))
        t_dtype = getattr(getattr(leaf, "dtype", None), "name", None)
        if t_shape != want_shape or (t_dtype and t_dtype != want_dtype):
            raise ValueError(
                f"checkpoint leaf {name} is {want_dtype}{list(want_shape)} "
                f"but the target expects {t_dtype}{list(t_shape)}")
        fpath = os.path.join(root, entry["file"])
        try:
            with open(fpath, "rb") as f:
                raw = f.read()
        except OSError as e:
            raise CheckpointCorruptError(
                f"unreadable array file {entry['file']} for step {step}: "
                f"{e}") from e
        if len(raw) != entry["size"]:
            raise CheckpointCorruptError(
                f"array file {entry['file']} is {len(raw)} bytes, manifest "
                f"says {entry['size']} (truncated?)")
        host = _decode_leaf(raw, entry)
        placed.append(_place_like(host, leaf))
    restored = _launder(jax.tree_util.tree_unflatten(treedef, placed))
    restore_seconds.observe(time.perf_counter() - t0)
    return restored


def _place_like(host: np.ndarray, template: Any):
    """Put a host array onto the template leaf's sharding (cross-mesh
    resume: the restored value adopts the *current* run's layout)."""
    sharding = getattr(template, "sharding", None)
    if sharding is None:
        return jax.device_put(host)
    if jax.process_count() > 1:
        # Multi-host: each process materializes only its addressable
        # shards from the (shared-filesystem) full array.
        return jax.make_array_from_callback(
            host.shape, sharding, lambda idx: host[idx])
    return jax.device_put(host, sharding)


def save_pytree(directory: str, tree: Any, *,
                path_class: str = "checkpoint") -> str:
    """Write an arbitrary pytree (e.g. an export's params dict) with the
    same manifest+commit protocol as a step checkpoint, synchronously and
    atomically (staging dir + rename). Returns ``directory``.

    ``path_class`` selects the durable-writer criticality (``"adapter"``
    for LoRA exports, ``"prefix_tier"`` for KV-block demotions). A save
    that fails mid-staging quarantines its partial staging dir (never a
    stray ``.tmp-*``, never a torn committed dir) and re-raises."""
    directory = os.path.abspath(directory)
    parent = os.path.dirname(directory) or "."
    os.makedirs(parent, exist_ok=True)
    leaf_metas, payloads = _leaf_entries(tree)
    pending = _PendingSave(
        step=0, leaf_metas=leaf_metas, payloads=payloads, train_meta=None,
        keep=None, retries=3, retry_backoff_s=0.2, path_class=path_class)
    tmp = f"{directory}{_TMP_PREFIX}{os.getpid()}"
    shutil.rmtree(tmp, ignore_errors=True)
    try:
        _write_staging(tmp, pending)
        if os.path.isdir(directory):
            shutil.rmtree(directory)
        durable_io.replace(tmp, directory, path_class=path_class)
    except BaseException:
        # Torn/failed staging: quarantine the partial bytes for forensics
        # (falling back to plain removal when even the rename is sick).
        try:
            quarantine_step(parent, os.path.basename(tmp), "save-failed")
        except OSError:
            shutil.rmtree(tmp, ignore_errors=True)
        raise
    _fsync_dir(parent)
    return directory


_KEY_RE = re.compile(r"\['([^']*)'\]")


def load_pytree(directory: str, verify: bool = False) -> Any:
    """Load a :func:`save_pytree` artifact back into nested dicts (leaf
    names are parsed from the manifest's pytree paths — dict-keyed trees
    only, which covers params exports)."""
    directory = os.path.abspath(directory)
    manifest_path = os.path.join(directory, _MANIFEST)
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointCorruptError(
            f"unreadable manifest under {directory}: {e}") from e
    out: dict = {}
    for entry in manifest.get("leaves", []):
        keys = _KEY_RE.findall(entry["name"])
        if not keys or "".join(f"['{k}']" for k in keys) != entry["name"]:
            raise ValueError(
                f"leaf {entry['name']!r} is not a dict-keyed path; "
                "load_pytree only handles nested-dict trees")
        path = os.path.join(directory, entry["file"])
        with open(path, "rb") as f:
            raw = f.read()
        if len(raw) != entry["size"] or (
                verify and _sha256_bytes(raw) != entry["sha256"]):
            raise CheckpointCorruptError(
                f"array file {entry['file']} under {directory} failed "
                "integrity check")
        node = out
        for k in keys[:-1]:
            node = node.setdefault(k, {})
        node[keys[-1]] = _decode_leaf(raw, entry)
    return out


def _launderable(x: Any) -> bool:
    if not hasattr(x, "dtype") or not hasattr(x, "sharding"):
        return False
    # Host-pinned leaves (optimizer offload) stay as transfer products: an
    # elementwise op on pinned_host operands may not lower. Everything
    # else launders — note the CPU backend names its *default* memory
    # space "unpinned_host", so the test must be pinned-host-only, not
    # device-only.
    return getattr(x.sharding, "memory_kind", None) != "pinned_host"


def _launder(tree: Any) -> Any:
    """Pass restored arrays through a jitted elementwise copy.

    On this image's CPU jaxlib, *donating* a transfer-created array (a
    ``jax.device_put`` of host numpy — which may alias the Python-owned
    buffer zero-copy) into the compiled train step corrupts the process
    heap: the historical train→resume segfault in ``tests/test_e2e.py``,
    reproduced with transfer-created arrays alone, no checkpoint I/O
    involved. Executable *outputs* are immune (the runs that crashed on a
    restored state always continued fine from a live one). The training
    step donates its state, so restored states must be executable
    outputs, not transfer products. The copy is NOT donated — donation is
    the hazard being laundered away — costing one transient extra
    state-size allocation during restore.
    """
    import jax.numpy as jnp

    flags = [_launderable(x) for x in jax.tree_util.tree_leaves(tree)]
    if not any(flags):
        return tree

    def copy_tree(t):
        def copy_leaf(x):
            if not hasattr(x, "dtype"):
                return x
            if jnp.issubdtype(x.dtype, jnp.bool_):
                return jnp.logical_and(x, True)
            # +0 (not identity): jit(lambda x: x) returns the input
            # array object untouched, which would defeat the laundering.
            return x + jnp.zeros((), x.dtype)
        return jax.tree_util.tree_map(copy_leaf, t)

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    to_copy = [x for x, f in zip(leaves, flags) if f]
    copied = iter(jax.jit(copy_tree)(to_copy))
    out = [next(copied) if f else x for x, f in zip(leaves, flags)]
    return jax.tree_util.tree_unflatten(treedef, out)


def restore_latest_verified(directory: str, target: Any,
                            ) -> Optional[Tuple[Any, int, Optional[dict]]]:
    """Resume entry point: restore the newest checkpoint that verifies,
    quarantining and falling back past any that turn out corrupt even
    after passing the scan (TOCTOU / read errors). Returns
    ``(state, step, sidecar_meta)`` or None when nothing restorable
    exists. ``ValueError`` (structure mismatch) propagates — that is a
    config error, not corruption."""
    while True:
        step = latest_verified_step(directory)
        if step is None:
            return None
        try:
            state = restore_train_state(directory, step, target)
            return state, step, load_train_meta(directory, step)
        except CheckpointCorruptError as e:
            get_logger().warning(
                "verified checkpoint step %d failed on restore (%s); "
                "falling back", step, e)
            quarantine_step(directory, str(step), "restore-failed")
