"""Consolidated model export: sharded training state → portable artifact.

The capability chain the reference assembles from DeepSpeed + PEFT:
gather sharded weights on save (``stage3_gather_16bit_weights_on_model_save``,
``configs/ds_config_zero3.json:36``) then merge LoRA into the base model for
serving (vLLM leg, ``README.md:10``). Here: fold LoRA factors into base
kernels (:func:`~dlti_tpu.models.lora.merge_lora_params`), gather to host,
and write a single manifest-verified pytree artifact
(:func:`~dlti_tpu.checkpoint.store.save_pytree`) + config JSON that the
serving engine loads directly.
"""

from __future__ import annotations

import json
import os
from typing import Tuple

import jax

from dlti_tpu.checkpoint.store import load_pytree, save_pytree
from dlti_tpu.config import Config
from dlti_tpu.models.lora import merge_lora_params


def export_merged_model(directory: str, params, cfg: Config,
                        merge_lora: bool = True) -> str:
    """Write ``directory/model`` (manifest-verified pytree) +
    ``directory/config.json``.

    ``params`` may be sharded; leaves are gathered to host first (the
    16-bit-gather-on-save analog). Returns the export directory.
    """
    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    host_params = jax.device_get(params)
    if merge_lora and cfg.lora.enabled:
        host_params = merge_lora_params(host_params, alpha=cfg.lora.alpha)

    save_pytree(os.path.join(directory, "model"), host_params)

    meta = cfg.to_dict()
    meta["lora"]["enabled"] = False if merge_lora else meta["lora"]["enabled"]
    with open(os.path.join(directory, "config.json"), "w") as f:
        json.dump(meta, f, indent=2, sort_keys=True)
    return directory


def load_exported_model(directory: str) -> Tuple[dict, Config]:
    """Load a consolidated export → (params, config). Used by serving."""
    directory = os.path.abspath(directory)
    with open(os.path.join(directory, "config.json")) as f:
        cfg = Config.from_dict(json.load(f))
    model_dir = os.path.join(directory, "model")
    if not os.path.isfile(os.path.join(model_dir, "MANIFEST.json")):
        # Legacy export written by the old Orbax backend.
        import orbax.checkpoint as ocp

        return ocp.StandardCheckpointer().restore(model_dir), cfg
    params = load_pytree(model_dir)
    return params, cfg
