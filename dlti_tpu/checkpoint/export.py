"""Consolidated model export: sharded training state → portable artifact.

The capability chain the reference assembles from DeepSpeed + PEFT:
gather sharded weights on save (``stage3_gather_16bit_weights_on_model_save``,
``configs/ds_config_zero3.json:36``) then merge LoRA into the base model for
serving (vLLM leg, ``README.md:10``). Here: fold LoRA factors into base
kernels (:func:`~dlti_tpu.models.lora.merge_lora_params`), gather to host,
and write a single manifest-verified pytree artifact
(:func:`~dlti_tpu.checkpoint.store.save_pytree`) + config JSON that the
serving engine loads directly.
"""

from __future__ import annotations

import json
import os
from typing import Tuple

import jax

from dlti_tpu.checkpoint.store import load_pytree, save_pytree
from dlti_tpu.config import Config
from dlti_tpu.models.lora import merge_lora_params


def export_merged_model(directory: str, params, cfg: Config,
                        merge_lora: bool = True) -> str:
    """Write ``directory/model`` (manifest-verified pytree) +
    ``directory/config.json``.

    ``params`` may be sharded; leaves are gathered to host first (the
    16-bit-gather-on-save analog). Returns the export directory.
    """
    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    host_params = jax.device_get(params)
    if merge_lora and cfg.lora.enabled:
        host_params = merge_lora_params(host_params, alpha=cfg.lora.alpha)

    save_pytree(os.path.join(directory, "model"), host_params)

    meta = cfg.to_dict()
    meta["lora"]["enabled"] = False if merge_lora else meta["lora"]["enabled"]
    with open(os.path.join(directory, "config.json"), "w") as f:
        json.dump(meta, f, indent=2, sort_keys=True)
    return directory


def export_params_host(checkpoint_dir: str, step: int,
                       out_dir: str) -> str:
    """Host-side candidate export for the deployment controller: extract
    the ``.params`` subtree of a committed train-state checkpoint straight
    from its manifest — no model init, no optimizer state read, no device
    memory — and re-write it as a digest-verified :func:`save_pytree`
    artifact (the exact shape ``POST /v1/reload`` and ``request_reload``
    consume). Every leaf's SHA-256 is checked against the manifest while
    reading, so a corrupt checkpoint raises instead of exporting garbage.
    Returns the export's manifest SHA-256.
    """
    from dlti_tpu.checkpoint import store as _store

    root = os.path.join(os.path.abspath(checkpoint_dir), str(step))
    try:
        with open(os.path.join(root, "MANIFEST.json")) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise _store.CheckpointCorruptError(
            f"unreadable manifest for step {step} under {checkpoint_dir}: "
            f"{e}") from e
    prefix = ".params"
    params: dict = {}
    n = 0
    for entry in manifest.get("leaves", []):
        name = entry["name"]
        if not name.startswith(prefix + "["):
            continue
        keys = _store._KEY_RE.findall(name[len(prefix):])
        if not keys or prefix + "".join(
                f"['{k}']" for k in keys) != name:
            raise ValueError(
                f"checkpoint leaf {name!r} is not a dict-keyed params "
                "path; host-side export only handles nested-dict params")
        path = os.path.join(root, entry["file"])
        with open(path, "rb") as f:
            raw = f.read()
        if len(raw) != entry["size"] or \
                _store._sha256_bytes(raw) != entry["sha256"]:
            raise _store.CheckpointCorruptError(
                f"array file {entry['file']} for step {step} failed "
                "integrity check during export")
        node = params
        for k in keys[:-1]:
            node = node.setdefault(k, {})
        node[keys[-1]] = _store._decode_leaf(raw, entry)
        n += 1
    if n == 0:
        raise ValueError(
            f"checkpoint step {step} under {checkpoint_dir} has no "
            ".params leaves — not a train-state checkpoint?")
    out_dir = os.path.abspath(out_dir)
    save_pytree(out_dir, params)
    digest = _store.manifest_digest(out_dir)
    if digest is None:
        raise _store.CheckpointCorruptError(
            f"export {out_dir} has no committed manifest digest")
    return digest


def load_exported_model(directory: str) -> Tuple[dict, Config]:
    """Load a consolidated export → (params, config). Used by serving."""
    directory = os.path.abspath(directory)
    with open(os.path.join(directory, "config.json")) as f:
        cfg = Config.from_dict(json.load(f))
    model_dir = os.path.join(directory, "model")
    if not os.path.isfile(os.path.join(model_dir, "MANIFEST.json")):
        # Legacy export written by the old Orbax backend.
        import orbax.checkpoint as ocp

        return ocp.StandardCheckpointer().restore(model_dir), cfg
    params = load_pytree(model_dir)
    return params, cfg
