"""Checkpoint corruption + I/O fault injection for chaos tests.

Deterministic ways to damage an on-disk checkpoint the way real failures
do — a kill mid-save (stale staging dir), a truncated write, a bit flip
from a bad disk/NIC — so tier-1 tests can prove the verified-resume path
quarantines the damage and falls back instead of crashing. Used by
``tests/test_crash_consistency.py``; importable by operators for fire
drills.

:class:`FaultyIO` is the *live* counterpart: instead of damaging bytes
after the fact, it injects ENOSPC / EIO / slow writes / torn writes at
the file boundary while the system runs, via the durable writer's
injector hook (:func:`dlti_tpu.utils.durable_io.set_fault_injector`).
Spec syntax (same colon-separated shape as ``DLTI_TRAIN_FAULT_INJECT``):

    DLTI_IO_FAULT=PATH_GLOB:errno[:count|rate][:delay_s][;more-rules]

* ``PATH_GLOB`` — fnmatch glob, tried against the full path and its
  basename (``*ckpt*``, ``MANIFEST.json``, ``*/flight/*``).
* ``errno`` — an errno name (``ENOSPC``, ``EIO``, ``ESTALE``, ...), or
  ``torn`` (write half the bytes, then raise ``EIO``), or ``slow``
  (sleep ``delay_s``, then succeed).
* ``count|rate`` — an integer fires the rule that many times then
  clears it (recovery drills); a float in (0, 1] fires probabilistically
  (seeded — deterministic per injector instance). Empty = every match.
* ``delay_s`` — seconds to sleep before the op (stalling-NFS drills).
"""

from __future__ import annotations

import dataclasses
import errno as _errno_mod
import fnmatch
import json
import os
import random
import shutil
from typing import List, Optional

from dlti_tpu.checkpoint.store import (
    _ARRAY_DIR,
    _COMMIT,
    _MANIFEST,
    _TMP_PREFIX,
)
from dlti_tpu.utils.durable_io import IO_FAULT_ENV, set_fault_injector

CORRUPT_MODES = (
    "bitflip-array",      # flip one bit in the middle of an array file
    "truncate-array",     # cut an array file to half its size
    "truncate-manifest",  # cut MANIFEST.json short (unparseable)
    "drop-commit",        # delete the COMMIT marker (looks mid-finalize)
    "stale-tmp",          # demote the committed dir to a .tmp-* staging
                          # dir — byte-for-byte what a kill mid-async-save
                          # leaves behind
)


def bit_flip_file(path: str, offset: Optional[int] = None,
                  bit: int = 0) -> None:
    """Flip one bit in ``path`` (default: the middle byte) in place."""
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"cannot bit-flip empty file {path}")
    pos = size // 2 if offset is None else offset
    with open(path, "r+b") as f:
        f.seek(pos)
        byte = f.read(1)
        f.seek(pos)
        f.write(bytes([byte[0] ^ (1 << bit)]))


def truncate_file(path: str, keep_bytes: Optional[int] = None) -> None:
    """Truncate ``path`` to ``keep_bytes`` (default: half)."""
    size = os.path.getsize(path)
    keep = size // 2 if keep_bytes is None else keep_bytes
    with open(path, "r+b") as f:
        f.truncate(keep)


def _largest_array_file(step_dir: str) -> str:
    adir = os.path.join(step_dir, _ARRAY_DIR)
    files = [os.path.join(adir, n) for n in sorted(os.listdir(adir))]
    files = [f for f in files if os.path.getsize(f) > 0]
    if not files:
        raise FileNotFoundError(f"no non-empty array files under {adir}")
    return max(files, key=os.path.getsize)


def corrupt_checkpoint(directory: str, step: int, mode: str) -> str:
    """Damage the committed checkpoint ``directory/step`` per ``mode``
    (one of :data:`CORRUPT_MODES`). Returns the path that was damaged."""
    step_dir = os.path.join(os.path.abspath(directory), str(step))
    if not os.path.isdir(step_dir):
        raise FileNotFoundError(f"no committed checkpoint at {step_dir}")
    if mode == "bitflip-array":
        path = _largest_array_file(step_dir)
        bit_flip_file(path)
        return path
    if mode == "truncate-array":
        path = _largest_array_file(step_dir)
        truncate_file(path)
        return path
    if mode == "truncate-manifest":
        path = os.path.join(step_dir, _MANIFEST)
        truncate_file(path)
        return path
    if mode == "drop-commit":
        path = os.path.join(step_dir, _COMMIT)
        os.remove(path)
        return path
    if mode == "stale-tmp":
        dst = os.path.join(os.path.dirname(step_dir),
                           f"{_TMP_PREFIX}{step}-chaos")
        os.rename(step_dir, dst)
        # A real mid-save kill also never wrote the commit marker.
        commit = os.path.join(dst, _COMMIT)
        if os.path.exists(commit):
            os.remove(commit)
        return dst
    raise ValueError(f"unknown corruption mode {mode!r}; "
                     f"expected one of {CORRUPT_MODES}")


def make_torn_save(directory: str, step: int,
                   source_step: Optional[int] = None) -> str:
    """Fabricate the wreckage of a save killed mid-write: a ``.tmp-*``
    staging dir holding a partial copy (arrays but no manifest/commit).
    ``source_step`` supplies the bytes (default: any committed step)."""
    directory = os.path.abspath(directory)
    if source_step is None:
        from dlti_tpu.checkpoint.store import list_checkpoint_steps

        steps = list_checkpoint_steps(directory)
        if not steps:
            raise FileNotFoundError(f"no committed checkpoint in {directory}")
        source_step = steps[-1]
    src = os.path.join(directory, str(source_step))
    dst = os.path.join(directory, f"{_TMP_PREFIX}{step}-torn")
    shutil.copytree(src, dst)
    for name in (_MANIFEST, _COMMIT):
        path = os.path.join(dst, name)
        if os.path.exists(path):
            os.remove(path)
    return dst


def read_manifest(directory: str, step: int) -> dict:
    with open(os.path.join(os.path.abspath(directory), str(step),
                           _MANIFEST)) as f:
        return json.load(f)


# ----------------------------------------------------------------------
# Live I/O fault injection (the durable writer's chaos hook)
# ----------------------------------------------------------------------

# The special (non-errno) fault kinds. "torn" writes half the payload
# before raising EIO — the wreckage a power cut mid-flush leaves; "slow"
# only sleeps (a stalling NFS mount that eventually answers).
IO_FAULT_KINDS = ("torn", "slow")


@dataclasses.dataclass
class IOFault:
    """One parsed ``DLTI_IO_FAULT`` rule."""
    glob: str
    kind: str                      # errno name (lowercased), "torn", "slow"
    err: Optional[int]             # errno to raise; None for pure slow
    remaining: Optional[int] = None  # count budget; None = unlimited
    rate: Optional[float] = None   # fire probability; None = always
    delay_s: float = 0.0
    fired: int = 0

    def matches(self, path: str) -> bool:
        return (fnmatch.fnmatch(path, self.glob)
                or fnmatch.fnmatch(os.path.basename(path), self.glob))


class FaultyIO:
    """Monkeypatchable I/O fault injector for the durable writer.

    Install with :meth:`install` (or as a context manager) for
    in-process tests, or export ``DLTI_IO_FAULT=<spec>`` — the durable
    writer parses the env spec lazily, so subprocess drills need no
    code. ``plan(op, path)`` is the hook the writer calls before every
    raw write/append/replace; it returns the matching rule (consuming
    one count) or None.
    """

    def __init__(self, faults: List[IOFault], seed: int = 0xD170):
        self.faults = list(faults)
        self._rng = random.Random(seed)

    # -- spec parsing ---------------------------------------------------
    @staticmethod
    def parse_rule(text: str) -> IOFault:
        parts = text.split(":")
        if len(parts) < 2 or not parts[0] or not parts[1]:
            raise ValueError(
                f"bad {IO_FAULT_ENV} rule {text!r}; expected "
                "PATH_GLOB:errno[:count|rate][:delay_s]")
        glob_pat, kind = parts[0], parts[1].lower()
        if kind == "torn":
            err: Optional[int] = _errno_mod.EIO
        elif kind == "slow":
            err = None
        else:
            err = getattr(_errno_mod, kind.upper(), None)
            if not isinstance(err, int):
                raise ValueError(
                    f"unknown errno/kind {parts[1]!r} in {IO_FAULT_ENV} "
                    f"rule {text!r} (errno name, 'torn', or 'slow')")
        remaining: Optional[int] = None
        rate: Optional[float] = None
        if len(parts) > 2 and parts[2]:
            if "." in parts[2]:
                rate = float(parts[2])
                if not 0.0 < rate <= 1.0:
                    raise ValueError(
                        f"rate {parts[2]} out of (0, 1] in rule {text!r}")
            else:
                remaining = int(parts[2])
                if remaining <= 0:
                    raise ValueError(
                        f"count {parts[2]} must be positive in {text!r}")
        delay_s = float(parts[3]) if len(parts) > 3 and parts[3] else 0.0
        if kind == "slow" and delay_s <= 0.0:
            delay_s = 0.05  # a "slow" rule with no delay still stalls
        return IOFault(glob=glob_pat, kind=kind, err=err,
                       remaining=remaining, rate=rate, delay_s=delay_s)

    @classmethod
    def from_spec(cls, spec: str) -> "Optional[FaultyIO]":
        rules = [cls.parse_rule(part) for part in spec.split(";")
                 if part.strip()]
        return cls(rules) if rules else None

    @classmethod
    def from_env(cls) -> "Optional[FaultyIO]":
        spec = os.environ.get(IO_FAULT_ENV, "")
        return cls.from_spec(spec) if spec else None

    # -- the hook -------------------------------------------------------
    def plan(self, op: str, path: str) -> Optional[IOFault]:
        """First armed rule matching ``path`` (consumes one count)."""
        del op  # all write-side ops are fair game today
        for rule in self.faults:
            if rule.remaining is not None and rule.remaining <= 0:
                continue
            if not rule.matches(path):
                continue
            if rule.rate is not None and self._rng.random() >= rule.rate:
                continue
            if rule.remaining is not None:
                rule.remaining -= 1
            rule.fired += 1
            return rule
        return None

    @property
    def total_fired(self) -> int:
        return sum(r.fired for r in self.faults)

    # -- install / uninstall --------------------------------------------
    def install(self) -> "FaultyIO":
        set_fault_injector(self)
        return self

    def uninstall(self) -> None:
        set_fault_injector(None)

    def __enter__(self) -> "FaultyIO":
        return self.install()

    def __exit__(self, exc_type, exc, tb):
        self.uninstall()
        return False
