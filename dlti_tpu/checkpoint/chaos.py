"""Checkpoint corruption helpers for chaos tests.

Deterministic ways to damage an on-disk checkpoint the way real failures
do — a kill mid-save (stale staging dir), a truncated write, a bit flip
from a bad disk/NIC — so tier-1 tests can prove the verified-resume path
quarantines the damage and falls back instead of crashing. Used by
``tests/test_crash_consistency.py``; importable by operators for fire
drills.
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Optional

from dlti_tpu.checkpoint.store import (
    _ARRAY_DIR,
    _COMMIT,
    _MANIFEST,
    _TMP_PREFIX,
)

CORRUPT_MODES = (
    "bitflip-array",      # flip one bit in the middle of an array file
    "truncate-array",     # cut an array file to half its size
    "truncate-manifest",  # cut MANIFEST.json short (unparseable)
    "drop-commit",        # delete the COMMIT marker (looks mid-finalize)
    "stale-tmp",          # demote the committed dir to a .tmp-* staging
                          # dir — byte-for-byte what a kill mid-async-save
                          # leaves behind
)


def bit_flip_file(path: str, offset: Optional[int] = None,
                  bit: int = 0) -> None:
    """Flip one bit in ``path`` (default: the middle byte) in place."""
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"cannot bit-flip empty file {path}")
    pos = size // 2 if offset is None else offset
    with open(path, "r+b") as f:
        f.seek(pos)
        byte = f.read(1)
        f.seek(pos)
        f.write(bytes([byte[0] ^ (1 << bit)]))


def truncate_file(path: str, keep_bytes: Optional[int] = None) -> None:
    """Truncate ``path`` to ``keep_bytes`` (default: half)."""
    size = os.path.getsize(path)
    keep = size // 2 if keep_bytes is None else keep_bytes
    with open(path, "r+b") as f:
        f.truncate(keep)


def _largest_array_file(step_dir: str) -> str:
    adir = os.path.join(step_dir, _ARRAY_DIR)
    files = [os.path.join(adir, n) for n in sorted(os.listdir(adir))]
    files = [f for f in files if os.path.getsize(f) > 0]
    if not files:
        raise FileNotFoundError(f"no non-empty array files under {adir}")
    return max(files, key=os.path.getsize)


def corrupt_checkpoint(directory: str, step: int, mode: str) -> str:
    """Damage the committed checkpoint ``directory/step`` per ``mode``
    (one of :data:`CORRUPT_MODES`). Returns the path that was damaged."""
    step_dir = os.path.join(os.path.abspath(directory), str(step))
    if not os.path.isdir(step_dir):
        raise FileNotFoundError(f"no committed checkpoint at {step_dir}")
    if mode == "bitflip-array":
        path = _largest_array_file(step_dir)
        bit_flip_file(path)
        return path
    if mode == "truncate-array":
        path = _largest_array_file(step_dir)
        truncate_file(path)
        return path
    if mode == "truncate-manifest":
        path = os.path.join(step_dir, _MANIFEST)
        truncate_file(path)
        return path
    if mode == "drop-commit":
        path = os.path.join(step_dir, _COMMIT)
        os.remove(path)
        return path
    if mode == "stale-tmp":
        dst = os.path.join(os.path.dirname(step_dir),
                           f"{_TMP_PREFIX}{step}-chaos")
        os.rename(step_dir, dst)
        # A real mid-save kill also never wrote the commit marker.
        commit = os.path.join(dst, _COMMIT)
        if os.path.exists(commit):
            os.remove(commit)
        return dst
    raise ValueError(f"unknown corruption mode {mode!r}; "
                     f"expected one of {CORRUPT_MODES}")


def make_torn_save(directory: str, step: int,
                   source_step: Optional[int] = None) -> str:
    """Fabricate the wreckage of a save killed mid-write: a ``.tmp-*``
    staging dir holding a partial copy (arrays but no manifest/commit).
    ``source_step`` supplies the bytes (default: any committed step)."""
    directory = os.path.abspath(directory)
    if source_step is None:
        from dlti_tpu.checkpoint.store import list_checkpoint_steps

        steps = list_checkpoint_steps(directory)
        if not steps:
            raise FileNotFoundError(f"no committed checkpoint in {directory}")
        source_step = steps[-1]
    src = os.path.join(directory, str(source_step))
    dst = os.path.join(directory, f"{_TMP_PREFIX}{step}-torn")
    shutil.copytree(src, dst)
    for name in (_MANIFEST, _COMMIT):
        path = os.path.join(dst, name)
        if os.path.exists(path):
            os.remove(path)
    return dst


def read_manifest(directory: str, step: int) -> dict:
    with open(os.path.join(os.path.abspath(directory), str(step),
                           _MANIFEST)) as f:
        return json.load(f)
