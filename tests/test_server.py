"""HTTP server + load generator tests (tiny model, ephemeral port, CPU).

End-to-end over real sockets: OpenAI-compatible routes, streaming SSE,
chat templating, the async engine facade, and the Locust-equivalent load
generator driving the live server.
"""

import http.client
import json
import subprocess
import threading

import jax
import jax.numpy as jnp
import pytest

from dlti_tpu.config import MODEL_PRESETS
from dlti_tpu.data.tokenizer import ByteTokenizer
from dlti_tpu.models import LlamaForCausalLM
from dlti_tpu.serving import EngineConfig, InferenceEngine, SamplingParams
from dlti_tpu.serving.server import ServerConfig, llama2_chat_prompt, make_server

CFG = MODEL_PRESETS["llama_tiny"]


@pytest.fixture(scope="module")
def live_server():
    model = LlamaForCausalLM(CFG, None)
    rng = jax.random.PRNGKey(0)
    params = model.init(rng, jnp.zeros((1, 8), jnp.int32))["params"]
    ec = EngineConfig(max_seqs=4, block_size=8, num_blocks=128, max_model_len=128,
                      cache_dtype="float32", eos_token_id=-1)
    engine = InferenceEngine(CFG, params, ec)
    tok = ByteTokenizer()
    httpd, async_engine = make_server(
        engine, tok, ServerConfig(host="127.0.0.1", port=0,
                                  default_params=SamplingParams(max_tokens=8)))
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield "127.0.0.1", port
    httpd.shutdown()
    async_engine.shutdown()
    httpd.server_close()


def _post(host, port, path, body):
    conn = http.client.HTTPConnection(host, port, timeout=120)
    conn.request("POST", path, json.dumps(body),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


def _get(host, port, path):
    conn = http.client.HTTPConnection(host, port, timeout=30)
    conn.request("GET", path)
    resp = conn.getresponse()
    data = json.loads(resp.read())
    conn.close()
    return resp.status, data


def test_health_models_stats(live_server):
    host, port = live_server
    assert _get(host, port, "/health") == (200, {"status": "ok"})
    status, models = _get(host, port, "/v1/models")
    assert status == 200 and models["data"][0]["id"] == "dlti-tpu-model"
    status, stats = _get(host, port, "/stats")
    assert status == 200 and "free_blocks" in stats


def test_debug_slo_404_when_disabled(live_server):
    # This server was started without TelemetryConfig.slo — the route
    # must say so instead of returning an empty objectives dict (the
    # live-agreement path in test_traces.py covers the enabled side).
    host, port = live_server
    status, body = _get(host, port, "/debug/slo")
    assert status == 404
    assert "slo" in body.get("error", {}).get("message", "").lower()


def test_metrics_prometheus_exposition(live_server):
    """GET /metrics renders the /stats counters in Prometheus text
    format (vLLM-parity observability): TYPE lines + numeric samples,
    scrapeable without an adapter."""
    host, port = live_server
    conn = http.client.HTTPConnection(host, port, timeout=30)
    conn.request("GET", "/metrics")
    resp = conn.getresponse()
    assert resp.status == 200
    assert resp.getheader("Content-Type", "").startswith("text/plain")
    text = resp.read().decode()
    conn.close()
    assert "# TYPE dlti_free_blocks gauge" in text
    assert "# TYPE dlti_requests counter" in text
    for line in text.strip().splitlines():
        if line.startswith("#"):
            continue
        name, value = line.split()
        assert name.startswith("dlti_")
        float(value)  # every sample parses as a number


def test_completions_roundtrip(live_server):
    host, port = live_server
    status, data = _post(host, port, "/v1/completions", {
        "prompt": "hello", "max_tokens": 6, "temperature": 0.0,
    })
    assert status == 200, data
    obj = json.loads(data)
    assert obj["object"] == "text_completion"
    assert obj["usage"]["completion_tokens"] == 6
    assert obj["choices"][0]["finish_reason"] == "length"
    assert isinstance(obj["choices"][0]["text"], str)


def test_completions_deterministic_greedy(live_server):
    host, port = live_server
    body = {"prompt": "abc", "max_tokens": 5, "temperature": 0.0}
    _, d1 = _post(host, port, "/v1/completions", body)
    _, d2 = _post(host, port, "/v1/completions", body)
    assert json.loads(d1)["choices"][0]["text"] == json.loads(d2)["choices"][0]["text"]


def test_stop_matcher_invariants():
    """Property test for the windowed stop scanner (no server needed):
    over randomized stops and incremental text feeds, the emitted prefix
    never contains a stop string, the cut always equals the earliest
    full-text match, and the safe boundary never retracts emitted
    text."""
    import random

    from dlti_tpu.serving.server import _Handler

    rng = random.Random(7)
    alphabet = "abc"
    for _ in range(300):
        stops = tuple(
            "".join(rng.choice(alphabet) for _ in range(rng.randint(1, 3)))
            for _ in range(rng.randint(1, 3)))
        full = "".join(rng.choice(alphabet) for _ in range(rng.randint(1, 30)))
        matcher = _Handler._StopMatcher(stops)
        text, emitted = "", 0
        cut = None
        while len(text) < len(full) and cut is None:
            text = full[: len(text) + rng.randint(1, 4)]
            cut, safe = matcher.feed(text)
            if cut is not None:
                break
            assert safe >= emitted, (full, stops, text, safe, emitted)
            for s in stops:
                assert s not in text[:safe], (full, stops, text, safe)
            emitted = safe
        expected = min((i for i in (full[: len(text)].find(s)
                                    for s in stops) if i != -1),
                       default=None)
        assert cut == expected, (full, stops, text, cut, expected)


def _pick_stop(host, port):
    """(full_text, stop, request_body): a per-request-seeded sampled
    completion (reproducible by the engine's seed contract) and an inner
    2-gram whose FIRST occurrence is past index 0, so truncation is
    non-trivial; falls back to index 0 if the tiny model's output is too
    repetitive."""
    base = {"prompt": "abcdef", "max_tokens": 12, "temperature": 1.0,
            "seed": 11}
    _, d = _post(host, port, "/v1/completions", base)
    full = json.loads(d)["choices"][0]["text"]
    assert len(full) >= 2, f"output too short to test stops: {full!r}"
    stop = full[0:2]
    for i in range(1, len(full) - 1):
        cand = full[i:i + 2]
        if full.find(cand) == i:
            stop = cand
            break
    return full, stop, base


def test_stop_strings_full_response(live_server):
    """OpenAI `stop` strings (token-boundary-agnostic, matched on
    detokenized text): the response truncates BEFORE the match, excludes
    the stop string, reports finish_reason stop, and the engine is
    early-cancelled instead of decoding to max_tokens."""
    host, port = live_server
    full, stop, base = _pick_stop(host, port)
    _, d = _post(host, port, "/v1/completions", {**base, "stop": stop})
    obj = json.loads(d)
    got = obj["choices"][0]["text"]
    assert got == full[: full.find(stop)], (full, stop, got)
    assert stop not in got
    assert obj["choices"][0]["finish_reason"] == "stop"
    # invalid stop values are a 400, not a crashed stepper
    status, d = _post(host, port, "/v1/completions",
                      {**base, "stop": ["a", "b", "c", "d", "e"]})
    assert status == 400


def test_stop_strings_streaming(live_server):
    """Streaming with `stop`: the stop string is never emitted in any
    delta (held back across token boundaries), and the final chunk
    carries finish_reason stop."""
    host, port = live_server
    full, stop, base = _pick_stop(host, port)

    conn = http.client.HTTPConnection(host, port, timeout=120)
    conn.request("POST", "/v1/completions",
                 json.dumps({**base, "stop": stop, "stream": True}),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200
    raw = resp.read().decode()
    conn.close()
    deltas, finish = [], None
    for line in raw.splitlines():
        if not line.startswith("data: ") or line == "data: [DONE]":
            continue
        ev = json.loads(line[len("data: "):])
        ch = ev["choices"][0]
        if ch.get("text"):
            deltas.append(ch["text"])
        if ch.get("finish_reason"):
            finish = ch["finish_reason"]
    text = "".join(deltas)
    assert text == full[: full.find(stop)], (full, stop, text)
    assert stop not in text
    assert finish == "stop"


def test_stop_strings_streaming_tail_flush(live_server):
    """A stop string that never matches but whose PREFIX ends the output
    engages the hold-back; the done-event flush must still deliver the
    held tail so streaming equals non-streaming."""
    host, port = live_server
    full, _, base = _pick_stop(host, port)
    stop = full[-1] + "\x00"  # prefix = final char; full match impossible

    conn = http.client.HTTPConnection(host, port, timeout=120)
    conn.request("POST", "/v1/completions",
                 json.dumps({**base, "stop": stop, "stream": True}),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    raw = resp.read().decode()
    conn.close()
    deltas, finish = [], None
    for line in raw.splitlines():
        if not line.startswith("data: ") or line == "data: [DONE]":
            continue
        ev = json.loads(line[len("data: "):])
        ch = ev["choices"][0]
        if ch.get("text"):
            deltas.append(ch["text"])
        if ch.get("finish_reason"):
            finish = ch["finish_reason"]
    assert "".join(deltas) == full, (full, deltas)
    assert finish == "length"


def test_n_choices(live_server):
    """OpenAI `n`: n concurrent engine requests -> n indexed choices;
    a user seed derives per-choice seeds so choices differ but the whole
    response reproduces; guards reject stream+n and greedy+n."""
    host, port = live_server
    body = {"prompt": "abcdef", "max_tokens": 6, "temperature": 1.0,
            "seed": 3, "n": 3}
    status, d = _post(host, port, "/v1/completions", body)
    assert status == 200, d
    obj = json.loads(d)
    texts = [c["text"] for c in sorted(obj["choices"],
                                       key=lambda c: c["index"])]
    assert len(texts) == 3
    assert len(set(texts)) > 1, "per-choice seeds produced identical samples"
    assert obj["usage"]["completion_tokens"] == 18
    # Reproducible end to end.
    _, d2 = _post(host, port, "/v1/completions", body)
    assert [c["text"] for c in sorted(json.loads(d2)["choices"],
                                      key=lambda c: c["index"])] == texts
    # Loud rejections.
    status, _ = _post(host, port, "/v1/completions",
                      {**body, "stream": True})
    assert status == 400
    status, _ = _post(host, port, "/v1/completions",
                      {**body, "temperature": 0.0})
    assert status == 400


def test_n_choices_submit_fault_cancels_submitted(live_server):
    """ADVICE r05 orphan-burn fix: when a submit raises mid-loop for
    n > 1, every already-submitted choice gets cancel_requested set —
    they must not decode to max_tokens into queues nobody reads."""
    host, port = live_server
    # Reach the handler class and its AsyncEngine through the live server
    # (the BoundHandler type holds them as class attributes).
    import dlti_tpu.serving.server as server_mod

    # Fetch the async_engine via a throwaway request? Not needed: the
    # fixture's engine is reachable through the module-level make_server
    # wiring only, so patch at the AsyncEngine class level instead —
    # fail the SECOND submit of an n=3 request, then restore.
    orig_submit = server_mod.AsyncEngine.submit
    state = {"calls": 0, "submitted": []}

    def flaky_submit(self, prompt_ids, params, request_id=None):
        state["calls"] += 1
        if state["calls"] == 2:
            raise RuntimeError("injected: stepper parked mid-loop")
        req, q = orig_submit(self, prompt_ids, params, request_id)
        state["submitted"].append(req)
        return req, q

    server_mod.AsyncEngine.submit = flaky_submit
    try:
        status, d = _post(host, port, "/v1/completions",
                          {"prompt": "abcdef", "max_tokens": 64,
                           "temperature": 1.0, "n": 3})
    finally:
        server_mod.AsyncEngine.submit = orig_submit
    assert status == 503, d
    assert len(state["submitted"]) == 1
    assert state["submitted"][0].cancel_requested, \
        "already-submitted choice left decoding after mid-loop fault"


def test_chat_completions(live_server):
    host, port = live_server
    status, data = _post(host, port, "/v1/chat/completions", {
        "messages": [{"role": "system", "content": "Be brief."},
                     {"role": "user", "content": "hi"}],
        "max_tokens": 4, "temperature": 0.0,
    })
    assert status == 200, data
    obj = json.loads(data)
    assert obj["object"] == "chat.completion"
    assert obj["choices"][0]["message"]["role"] == "assistant"


def test_streaming_sse(live_server):
    host, port = live_server
    conn = http.client.HTTPConnection(*live_server, timeout=120)
    conn.request("POST", "/v1/completions", json.dumps({
        "prompt": "xy", "max_tokens": 5, "temperature": 0.0, "stream": True,
    }), {"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200
    assert resp.getheader("Content-Type").startswith("text/event-stream")
    raw = resp.read().decode()
    conn.close()
    events = [l[5:].strip() for l in raw.splitlines() if l.startswith("data:")]
    assert events[-1] == "[DONE]"
    finals = [json.loads(e) for e in events[:-1]]
    assert any(c["choices"][0]["finish_reason"] == "length" for c in finals)


def test_error_paths(live_server):
    host, port = live_server
    status, data = _post(host, port, "/v1/completions", {"prompt": ""})
    assert status == 400
    status, _ = _post(host, port, "/v1/chat/completions", {"messages": []})
    assert status == 400
    status, _ = _post(host, port, "/nope", {})
    assert status == 404
    # Prompt longer than max_model_len rejected cleanly.
    status, data = _post(host, port, "/v1/completions",
                         {"prompt": "z" * 500, "max_tokens": 2})
    assert status == 400
    assert b"max_model_len" in data
    # Malformed sampling params must 400 this request, not crash the
    # engine stepper thread (which would error out every in-flight stream).
    for bad in ({"seed": "abc"}, {"temperature": "hot"}, {"top_k": [1]}):
        status, data = _post(host, port, "/v1/completions",
                             {"prompt": "hi", **bad})
        assert status == 400, (bad, data)
    # Server still healthy after the bad requests.
    status, out = _post(host, port, "/v1/completions",
                        {"prompt": "hi", "max_tokens": 2, "seed": 1})
    assert status == 200


def test_llama2_chat_template():
    """Serve-time template must match the training format contract
    (scripts/prepare_dataset.py:12-25: "<s>[INST] q [/INST] a</s>")."""
    s = llama2_chat_prompt([{"role": "user", "content": "Q1"}])
    assert s == "[INST] Q1 [/INST]"
    s = llama2_chat_prompt([
        {"role": "system", "content": "SYS"},
        {"role": "user", "content": "Q1"},
        {"role": "assistant", "content": "A1"},
        {"role": "user", "content": "Q2"},
    ])
    assert s == "[INST] <<SYS>>\nSYS\n<</SYS>>\n\nQ1 [/INST] A1 [INST] Q2 [/INST]"


@pytest.fixture(scope="module")
def id_tok_server():
    """A server whose tokenizer renders EVERY sampled id as visible text
    (IdTokenizer — built for exactly this: a random-weight model's argmax
    ids exceed the byte tokenizer's printable range, so ByteTokenizer
    suppresses every SSE delta and zeroes streaming TTFT/TPOT)."""
    from dlti_tpu.data.tokenizer import IdTokenizer

    model = LlamaForCausalLM(CFG, None)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    ec = EngineConfig(max_seqs=4, block_size=8, num_blocks=128,
                      max_model_len=128, cache_dtype="float32",
                      eos_token_id=-1)
    engine = InferenceEngine(CFG, params, ec)
    httpd, async_engine = make_server(
        engine, IdTokenizer(vocab_size=CFG.vocab_size),
        ServerConfig(host="127.0.0.1", port=0,
                     default_params=SamplingParams(max_tokens=8)))
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield "127.0.0.1", port
    httpd.shutdown()
    async_engine.shutdown()
    httpd.server_close()


def test_loadgen_against_live_server(id_tok_server):
    from dlti_tpu.benchmarks import LoadGenConfig, run_load_test

    host, port = id_tok_server
    report = run_load_test(LoadGenConfig(
        host=host, port=port, num_requests=8, concurrency=4,
        max_tokens=4, stream=True, prompt="bench", timeout_s=120,
        scrape_server_metrics=True))
    assert report.num_ok == 8, report.errors
    assert report.output_tokens_per_s > 0
    assert report.ttft_p50_s > 0
    assert report.latency_p99_s >= report.latency_p50_s
    # On-engine histograms rode back with the report: the engine observed
    # every request's TTFT and queue time itself.
    ttft = report.server_histograms["dlti_request_ttft_seconds"]
    assert ttft["count"] >= 8 and ttft["mean"] > 0
    assert report.server_histograms["dlti_request_queue_time_seconds"][
        "count"] >= 8

    # Non-streaming path exercises usage-based token accounting.
    report = run_load_test(LoadGenConfig(
        host=host, port=port, num_requests=4, concurrency=2,
        max_tokens=4, stream=False, prompt="bench", timeout_s=120))
    assert report.num_ok == 4, report.errors
    assert report.output_tokens_per_s > 0


def test_native_allocator_contract(tmp_path):
    """C++ allocator obeys the same contract as the Python fallback."""
    import os
    from dlti_tpu.utils import native as native_mod

    so = native_mod._lib_path()
    if not os.path.exists(so):
        r = subprocess.run(["make", "-C", os.path.dirname(so)],
                           capture_output=True)
        if r.returncode != 0:
            pytest.skip("native toolchain unavailable")
    # Fresh load (bypass module cache).
    native_mod._TRIED = False
    native_mod._LIB = None
    lib = native_mod.load_native_runtime()
    assert lib is not None

    from dlti_tpu.serving import BlockManager

    bm = BlockManager(num_blocks=8, block_size=4)
    assert bm._native is not None
    assert bm.num_free == 7
    a = bm.allocate(3)
    assert a is not None and len(set(a)) == 3 and 0 not in a
    assert bm.allocate(5) is None
    assert bm.num_free == 4
    bm.free(a)
    assert bm.num_free == 7


def test_stepper_fault_aborts_cleanly():
    """A faulted engine.step() errors exactly the in-flight consumers and
    leaves the engine EMPTY (slots + waiting freed): no hot-loop on a
    persistent fault, no decoding into deleted queues after a transient
    one."""
    from dlti_tpu.serving.server import AsyncEngine

    model = LlamaForCausalLM(CFG, None)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    ec = EngineConfig(max_seqs=2, block_size=8, num_blocks=32,
                      max_model_len=32, cache_dtype="float32",
                      eos_token_id=-1)
    eng = InferenceEngine(CFG, params, ec)
    boom = {"n": 0}
    real_step = eng.step

    def flaky_step():
        boom["n"] += 1
        raise RuntimeError("injected device fault")

    eng.step = flaky_step
    aeng = AsyncEngine(eng)
    try:
        _, q = aeng.submit([3, 1, 4, 1, 5], SamplingParams(max_tokens=4))
        kind, payload = q.get(timeout=30)[:2]
        assert kind == "error" and "injected device fault" in payload
        # Engine drained: nothing left to step, stepper idles (no
        # unbounded retry of the failing program).
        assert not eng.has_work
        assert all(s.free for s in eng.slots) and not eng.waiting
        n_after_error = boom["n"]
        import time as _t
        _t.sleep(0.5)
        assert boom["n"] == n_after_error  # stepper is parked, not looping
        # Recovery: the engine works again for new requests.
        eng.step = real_step
        _, q2 = aeng.submit([2, 7, 1], SamplingParams(temperature=0.0,
                                                      max_tokens=3))
        events = [q2.get(timeout=60) for _ in range(4)]
        assert events[-1][0] == "done"
        assert sum(1 for e in events if e[0] == "token") == 3
    finally:
        aeng.shutdown()
