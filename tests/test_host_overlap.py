"""Host-latency-hiding layer: equivalence + zero-upload contracts.

Two hot paths, one invariant each:

* Training (``dlti_tpu.data.prefetch``): the background prefetcher must be
  *invisible* in the numbers — bit-identical loss trajectory vs. the
  synchronous path for every (preset, packing) combination — and safe to
  shut down mid-epoch (preemption).
* Serving (``dlti_tpu.serving.decode_state``): the device-resident
  decode-state cache must be byte-identical to the full re-upload path
  (including across preemption and re-admission), and a clean decode step
  — no admission/retire/preempt/growth since the last one — must issue
  ZERO host→device decode-state uploads (the acceptance criterion).
"""

import json

import numpy as np
import pytest

from dlti_tpu.config import (
    CheckpointConfig, Config, DataConfig, LoRAConfig, MODEL_PRESETS,
    OptimizerConfig, ParallelConfig, TelemetryConfig, TrainConfig, ZeROStage,
)
from dlti_tpu.data import TokenBatchDataset
from dlti_tpu.data.prefetch import HostPrefetcher
from dlti_tpu.serving import EngineConfig, InferenceEngine, SamplingParams

CFG = MODEL_PRESETS["llama_tiny"]


# ----------------------------------------------------------------------
# Prefetcher unit contracts
# ----------------------------------------------------------------------

def test_prefetcher_preserves_order_and_values():
    items = [{"x": np.full((2, 2), i)} for i in range(17)]
    got = [hb for hb, _ in HostPrefetcher(iter(items), depth=3)]
    assert len(got) == 17
    for want, have in zip(items, got):
        assert have is want  # the host batch object passes through untouched


def test_prefetcher_place_fn_pairs_host_and_placed():
    items = [{"x": np.arange(4) + i} for i in range(5)]
    pre = HostPrefetcher(iter(items), depth=2,
                         place_fn=lambda b: {k: v * 1 for k, v in b.items()})
    for hb, placed in pre:
        assert placed is not hb
        np.testing.assert_array_equal(placed["x"], hb["x"])
    assert pre.stats["fetches"] == 5


def test_prefetcher_close_unblocks_full_queue():
    """Preemption path: the worker is parked on a full queue; close() must
    join it promptly instead of leaking a daemon thread."""
    pre = HostPrefetcher(iter([{"x": np.zeros(1)}] * 100), depth=1)
    next(iter(pre))  # ensure the worker is up and the queue cycles
    pre.close()
    assert not pre._thread.is_alive()
    pre.close()  # idempotent


def test_prefetcher_propagates_source_exception():
    def bad():
        yield {"x": np.zeros(1)}
        raise RuntimeError("dataset exploded")

    it = iter(HostPrefetcher(bad(), depth=2))
    next(it)
    with pytest.raises(RuntimeError, match="dataset exploded"):
        next(it)


def test_prefetcher_telemetry_names_and_stall_histogram():
    from dlti_tpu.data.prefetch import PREFETCH_METRIC_NAMES

    pre = HostPrefetcher(iter([{"x": np.zeros(1)}] * 3), depth=2)
    list(pre)
    assert pre.queue_depth.name == PREFETCH_METRIC_NAMES[0]
    assert pre.stall_time.name == PREFETCH_METRIC_NAMES[1]
    _, _, n = pre.stall_time.snapshot()
    assert n == 3  # one stall sample per consumed batch


# ----------------------------------------------------------------------
# Training: loss-trajectory equivalence, prefetch on vs off
# ----------------------------------------------------------------------

def _make_dataset(pack: bool, micro_bs: int, accum: int, seq_len: int = 32):
    # Enough tokens that even PACKED rows (several docs per row) cover >= 4
    # steps at every shape used below.
    rng = np.random.default_rng(7)
    chunk = micro_bs * accum
    seqs = [list(map(int, rng.integers(1, 500, size=int(rng.integers(8, 16)))))
            for _ in range(12 * chunk)]
    return TokenBatchDataset(
        sequences=seqs, seq_len=seq_len, pad_id=0,
        micro_batch_size=micro_bs, grad_accum_steps=accum, pack=pack)


def _train_losses(tmp_path, tag, par, pack, micro_bs, accum, prefetch_depth):
    from dlti_tpu.training.trainer import Trainer

    steplog = tmp_path / f"{tag}.jsonl"
    cfg = Config(
        model=CFG,
        lora=LoRAConfig(r=2, alpha=4, dropout=0.0),
        optimizer=OptimizerConfig(warmup_steps=2),
        parallel=par,
        data=DataConfig(max_seq_len=32, prefetch_depth=prefetch_depth),
        train=TrainConfig(num_epochs=1, max_steps=3, micro_batch_size=micro_bs,
                          grad_accum_steps=accum, logging_steps=100,
                          metrics_csv=str(tmp_path / f"{tag}.csv")),
        checkpoint=CheckpointConfig(save_strategy="no"),
        telemetry=TelemetryConfig(step_log_path=str(steplog)),
    )
    trainer = Trainer(cfg)
    trainer.train(dataset=_make_dataset(pack, micro_bs, accum))
    rows = [json.loads(line) for line in open(steplog)]
    losses = [r["loss"] for r in rows if r.get("type") == "step"]
    assert len(losses) == 3
    return losses


@pytest.mark.parametrize("preset_kind,pack", [
    ("baseline", False),
    ("baseline", True),
    pytest.param("zero3", False, marks=pytest.mark.slow),
    pytest.param("zero3", True, marks=pytest.mark.slow),
])
def test_prefetch_loss_trajectory_bit_identical(tmp_path, preset_kind, pack):
    """Prefetch on (default depth 2) vs off: same batches in the same
    order through the same rng schedule — the per-step losses must be
    bit-identical floats, not merely close."""
    if preset_kind == "baseline":
        par, micro_bs, accum = ParallelConfig(), 2, 2
    else:
        par, micro_bs, accum = \
            ParallelConfig(zero_stage=ZeROStage.ZERO3, fsdp=8), 8, 1
    on = _train_losses(tmp_path, f"{preset_kind}_{pack}_on", par, pack,
                       micro_bs, accum, prefetch_depth=2)
    off = _train_losses(tmp_path, f"{preset_kind}_{pack}_off", par, pack,
                        micro_bs, accum, prefetch_depth=0)
    assert on == off  # exact float equality


def test_prefetch_survives_request_stop(tmp_path):
    """Preemption mid-epoch with the worker buffering ahead: the loop must
    shut the prefetcher down cleanly (no leaked thread, no deadlock) and
    write the preemption checkpoint at an executed step."""
    import threading

    from dlti_tpu.checkpoint import latest_step
    from dlti_tpu.training.trainer import Trainer

    cfg = Config(
        model=CFG, lora=LoRAConfig(r=2, alpha=4, dropout=0.0),
        optimizer=OptimizerConfig(warmup_steps=1),
        parallel=ParallelConfig(),
        data=DataConfig(max_seq_len=16, prefetch_depth=2),
        train=TrainConfig(num_epochs=1, micro_batch_size=2,
                          grad_accum_steps=1, logging_steps=100,
                          metrics_csv=str(tmp_path / "m.csv")),
        checkpoint=CheckpointConfig(output_dir=str(tmp_path / "ckpt"),
                                    save_strategy="steps", save_steps=1000,
                                    save_total_limit=2, async_save=False),
    )
    ds = _make_dataset(False, 2, 1, seq_len=16)
    trainer = Trainer(cfg)

    class StopAfterThird:
        """Dataset proxy whose epoch generator requests a stop at the 3rd
        yield — the prefetch worker pulls it EARLY (ahead of the step
        thread), exercising the stop-while-buffered shutdown path."""

        def steps_per_epoch(self):
            return ds.steps_per_epoch()

        def epoch(self, epoch_idx=0, skip_steps=0):
            for i, b in enumerate(ds.epoch(epoch_idx, skip_steps)):
                if i == 2:
                    trainer.request_stop()
                yield b

    trainer.train(dataset=StopAfterThird())
    stopped_at = latest_step(cfg.checkpoint.output_dir)
    # At least one step ran (the loop observes the stop at a step
    # boundary) and the run never consumed the whole epoch.
    assert stopped_at is not None and 1 <= stopped_at < ds.steps_per_epoch()
    # The worker is joined on exit — no prefetch thread may outlive
    # train() (checkpoint/backend helpers may, hence the name filter).
    assert not [t for t in threading.enumerate()
                if t.name.startswith("dlti-prefetch")]


# ----------------------------------------------------------------------
# drop_remainder (satellite): honored instead of silently ignored
# ----------------------------------------------------------------------

def test_drop_remainder_false_pads_final_step():
    rng = np.random.default_rng(0)
    seqs = [list(map(int, rng.integers(1, 500, size=6))) for _ in range(7)]
    kw = dict(sequences=seqs, seq_len=8, pad_id=0, micro_batch_size=2,
              grad_accum_steps=1, shuffle_seed=None, shard_by_host=False)
    drop = TokenBatchDataset(drop_remainder=True, **kw)
    keep = TokenBatchDataset(drop_remainder=False, **kw)
    assert drop.steps_per_epoch() == 3
    assert keep.steps_per_epoch() == 4
    dropped = list(drop.epoch(0))
    kept = list(keep.epoch(0))
    assert len(dropped) == 3 and len(kept) == 4
    for a, b in zip(dropped, kept):  # shared full steps are identical
        np.testing.assert_array_equal(a["input_ids"], b["input_ids"])
    tail = kept[-1]
    assert tail["input_ids"].shape == kept[0]["input_ids"].shape
    # Row 0 is the real 7th sequence; row 1 is padding: pad_id tokens,
    # zero loss mask — no loss or gradient contribution.
    assert tail["loss_mask"][0, 0].sum() > 0
    assert (tail["input_ids"][0, 1] == 0).all()
    assert (tail["loss_mask"][0, 1] == 0).all()


def test_drop_remainder_padded_step_trains(tmp_path):
    """The padded final step must run through the Trainer without shape
    errors or NaNs (all-pad rows carry zero loss mask)."""
    from dlti_tpu.training.trainer import Trainer

    rng = np.random.default_rng(3)
    seqs = [list(map(int, rng.integers(1, 500, size=7))) for _ in range(5)]
    ds = TokenBatchDataset(sequences=seqs, seq_len=16, pad_id=0,
                           micro_batch_size=2, grad_accum_steps=1,
                           drop_remainder=False)
    cfg = Config(
        model=CFG, lora=LoRAConfig(r=2, alpha=4, dropout=0.0),
        optimizer=OptimizerConfig(warmup_steps=1),
        parallel=ParallelConfig(),
        data=DataConfig(max_seq_len=16),
        train=TrainConfig(num_epochs=1, micro_batch_size=2,
                          grad_accum_steps=1, logging_steps=100,
                          metrics_csv=str(tmp_path / "m.csv")),
        checkpoint=CheckpointConfig(save_strategy="no"),
    )
    _, record = Trainer(cfg).train(dataset=ds)
    assert np.isfinite(record.final_loss)


# ----------------------------------------------------------------------
# Serving: decode-state cache equivalence + zero-upload clean steps
# ----------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny_params():
    import jax
    import jax.numpy as jnp

    from dlti_tpu.models import LlamaForCausalLM

    model = LlamaForCausalLM(CFG, None)
    return model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]


def _engine(params, cache_on: bool, **over):
    kw = dict(max_seqs=3, block_size=8, num_blocks=64, max_model_len=64,
              cache_dtype="float32", eos_token_id=-1,
              decode_state_cache=cache_on)
    kw.update(over)
    return InferenceEngine(CFG, params, EngineConfig(**kw))


def _tokens(results):
    return [(r.request_id, r.output_token_ids, r.finish_reason)
            for r in results]


def test_decode_state_cache_matches_reupload(tiny_params):
    """Byte-identical outputs, greedy and seeded-sampled, cache on vs off."""
    prompts = [[1, 2, 3, 4, 5], [6, 7, 8], [9, 10, 11, 12]]
    for sp in (SamplingParams(temperature=0.0, max_tokens=10),
               SamplingParams(temperature=0.9, top_k=7, seed=11,
                              max_tokens=10)):
        want = _engine(tiny_params, False).generate(prompts, sp)
        got = _engine(tiny_params, True).generate(prompts, sp)
        assert _tokens(got) == _tokens(want)


def test_decode_state_cache_matches_across_preemption(tiny_params):
    """A pool small enough to force preempt → re-admission (recompute)
    must still be byte-identical to the re-upload path, seeded sampling
    included (gen counts resume mid-stream on re-admission)."""
    prompts = [[1, 2, 3, 4, 5, 6, 7], [8, 9, 10, 11, 12, 13],
               [14, 15, 16, 17, 18]]
    sp = SamplingParams(temperature=0.7, seed=5, max_tokens=12)
    kw = dict(max_seqs=3, num_blocks=8, max_model_len=48)
    want = _engine(tiny_params, False, **kw)
    got = _engine(tiny_params, True, **kw)
    rw = want.generate(prompts, sp)
    rg = got.generate(prompts, sp)
    assert want.stats["preemptions"] >= 1  # the scenario actually engaged
    assert got.stats["preemptions"] == want.stats["preemptions"]
    assert _tokens(rg) == _tokens(rw)


def test_decode_state_cache_matches_multi_step(tiny_params):
    prompts = [[1, 2, 3, 4], [5, 6, 7]]
    sp = SamplingParams(temperature=0.0, max_tokens=9)
    want = _engine(tiny_params, False, max_seqs=2, steps_per_sync=4)
    got = _engine(tiny_params, True, max_seqs=2, steps_per_sync=4)
    assert _tokens(got.generate(prompts, sp)) == \
        _tokens(want.generate(prompts, sp))


def test_clean_decode_step_issues_zero_uploads(tiny_params):
    """THE acceptance criterion: once the batch composition settles, every
    further decode step reuses the resident device state — zero
    host→device decode-state uploads, while decode_steps keeps advancing."""
    # One 64-token block per sequence: no block-table growth inside the
    # observation window (growth is a legitimately dirty event).
    eng = _engine(tiny_params, True, block_size=64, num_blocks=8)
    eng.submit([1, 2, 3, 4], SamplingParams(temperature=0.0, max_tokens=30))
    eng.step()   # admission + prefill
    eng.step()   # first decode: uploads the admitted row
    settled = eng.stats["decode_state_uploads"]
    clean_before = eng.stats["decode_state_clean_syncs"]
    steps_before = eng.stats["decode_steps"]
    for _ in range(6):
        eng.step()
    assert eng.stats["decode_steps"] == steps_before + 6
    assert eng.stats["decode_state_uploads"] == settled  # ZERO new uploads
    assert eng.stats["decode_state_clean_syncs"] >= clean_before + 6
    # Host-prep histogram observed every dispatch.
    _, _, n = eng.telemetry.host_prep.snapshot()
    assert n >= 7


def test_decode_state_upload_counters_exposed(tiny_params):
    """The counters ride the engine stats dict (the /metrics scalar
    source), present even with the cache disabled."""
    for on in (True, False):
        eng = _engine(tiny_params, on)
        for k in ("decode_state_uploads", "decode_state_rows",
                  "decode_state_clean_syncs"):
            assert k in eng.stats


# ----------------------------------------------------------------------
# BlockManager double-free guard (satellite)
# ----------------------------------------------------------------------

def test_block_manager_double_free_raises():
    from dlti_tpu.serving.block_manager import BlockManager
    from dlti_tpu.utils.native import load_native_runtime

    native = load_native_runtime()
    if native is not None and not hasattr(native,
                                          "dlti_allocator_free_checked"):
        pytest.skip("prebuilt native runtime predates checked free")
    bm = BlockManager(num_blocks=16, block_size=8)
    blocks = bm.allocate(4)
    bm.free(blocks[:2])
    with pytest.raises(ValueError, match="free"):
        bm.free(blocks[:2])  # double free
    # All-or-nothing: the rejected call freed nothing, the pool is intact
    # and the still-live blocks free cleanly.
    assert bm.num_free == 15 - 2
    bm.free(blocks[2:])
    assert bm.num_free == 15


def test_block_manager_double_free_raises_python(monkeypatch):
    import dlti_tpu.serving.block_manager as bmod

    monkeypatch.setattr(bmod, "load_native_runtime", lambda: None)
    bm = bmod.BlockManager(num_blocks=8, block_size=8)
    got = bm.allocate(2)
    bm.free(got)
    with pytest.raises(ValueError, match="double free"):
        bm.free([got[0]])
    with pytest.raises(ValueError, match="freeing invalid block"):
        bm.free([0])
    # Duplicate ids within one batch are a double free too.
    more = bm.allocate(1)
    with pytest.raises(ValueError, match="double free"):
        bm.free([more[0], more[0]])
    assert more[0] not in bm._free  # rejected call freed nothing
