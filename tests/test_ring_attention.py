"""Ring attention (sequence parallelism) correctness on the virtual mesh.

The reference has no sequence/context parallelism — it truncates to 512
tokens (``train_baseline.py:155``; SURVEY.md §5.7). These tests prove the
first-class SP path: ring attention over the 'sequence' axis matches the
dense reference attention exactly (forward and gradient), composes with TP,
and a fully sequence-parallel train step matches the single-device step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dlti_tpu.config import (
    Config,
    LoRAConfig,
    MODEL_PRESETS,
    OptimizerConfig,
    ParallelConfig,
    TrainConfig,
    ZeROStage,
)
from dlti_tpu.models import LlamaForCausalLM
from dlti_tpu.ops.attention import reference_attention
from dlti_tpu.parallel import build_mesh, make_sharded_train_step, shard_train_state
from dlti_tpu.parallel.ring_attention import ring_attention
from conftest import make_packed_segments
from dlti_tpu.training import build_optimizer, create_train_state, make_train_step


def _qkv(rng, b=2, s=64, h=4, hk=2, d=8, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(rng, 3)
    q = jax.random.normal(kq, (b, s, h, d), dtype)
    k = jax.random.normal(kk, (b, s, hk, d), dtype)
    v = jax.random.normal(kv, (b, s, hk, d), dtype)
    return q, k, v


def _mesh(data=1, fsdp=1, tensor=1, sequence=8):
    return build_mesh(ParallelConfig(data=data, fsdp=fsdp, tensor=tensor,
                                     sequence=sequence))


@pytest.mark.parametrize("causal", [True, False], ids=["causal", "full"])
def test_ring_matches_reference(rng, causal):
    q, k, v = _qkv(rng)
    mesh = _mesh(sequence=8)
    ref = reference_attention(q, k, v, causal=causal)
    out = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh, causal=causal))(
        q, k, v
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ring_composes_with_tp(rng):
    """Heads sharded over 'tensor' while seq rides the ring."""
    q, k, v = _qkv(rng, h=4, hk=2)
    mesh = _mesh(tensor=2, sequence=4)
    ref = reference_attention(q, k, v, causal=True)
    out = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_ring_with_batch_sharding(rng):
    """Batch over data, sequence over the ring — the training layout."""
    q, k, v = _qkv(rng, b=4, s=32)
    mesh = _mesh(data=2, sequence=4)
    ref = reference_attention(q, k, v, causal=True)
    out = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_ring_gradients_match(rng):
    """d/dq,k,v of a scalar readout must match the dense path (ppermute
    transposition runs the reverse ring)."""
    q, k, v = _qkv(rng, s=32)
    mesh = _mesh(sequence=8)
    w = jax.random.normal(jax.random.fold_in(rng, 9), q.shape, jnp.float32)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh) * w)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) * w)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for gr, gd, name in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gr), np.asarray(gd), rtol=1e-4, atol=1e-4,
            err_msg=f"grad wrt {name} diverged",
        )


@pytest.mark.slow
def test_ring_custom_positions_match_reference(rng):
    """Explicit (shifted) positions: ring mask must follow the positions
    RoPE used, not reconstructed shard indices."""
    q, k, v = _qkv(rng, s=32)
    b, s = q.shape[0], q.shape[1]
    positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None, :] + 7,
                                 (b, s))
    mesh = _mesh(sequence=8)
    ref = reference_attention(q, k, v, causal=True,
                              q_positions=positions, kv_positions=positions)
    out = jax.jit(
        lambda q, k, v: ring_attention(q, k, v, mesh, positions=positions)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ring_packed_segments_match_reference(rng):
    """Packed batches ride the ring: segment ids travel with K/V and the
    mask matches the dense reference (padding rows output zero)."""
    q, k, v = _qkv(rng, s=64)
    segs = make_packed_segments(2, 64)
    mesh = _mesh(sequence=8)
    ref = reference_attention(q, k, v, causal=True, segment_ids=segs)
    out = jax.jit(
        lambda q, k, v: ring_attention(q, k, v, mesh, segment_ids=segs)
    )(q, k, v)
    valid = np.asarray(segs != 0)[:, :, None, None]
    np.testing.assert_allclose(np.asarray(out) * valid,
                               np.asarray(ref) * valid,
                               rtol=1e-5, atol=1e-5)


def test_ring_sliding_window_matches_reference(rng):
    q, k, v = _qkv(rng, s=64)
    mesh = _mesh(sequence=8)
    ref = reference_attention(q, k, v, causal=True, window=24)
    out = jax.jit(
        lambda q, k, v: ring_attention(q, k, v, mesh, window=24)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ring_window_plus_segments_match_reference(rng):
    q, k, v = _qkv(rng, s=64)
    segs = make_packed_segments(2, 64, n_docs=2, seed=3)
    mesh = _mesh(sequence=4)
    ref = reference_attention(q, k, v, causal=True, window=16,
                              segment_ids=segs)
    out = jax.jit(
        lambda q, k, v: ring_attention(q, k, v, mesh, window=16,
                                       segment_ids=segs)
    )(q, k, v)
    valid = np.asarray(segs != 0)[:, :, None, None]
    np.testing.assert_allclose(np.asarray(out) * valid,
                               np.asarray(ref) * valid,
                               rtol=1e-5, atol=1e-5)


def test_ring_seq_not_divisible_raises(rng):
    q, k, v = _qkv(rng, s=60)
    mesh = _mesh(sequence=8)
    with pytest.raises(ValueError, match="not divisible"):
        ring_attention(q, k, v, mesh)


@pytest.mark.slow
def test_sp_train_step_matches_single_device(rng):
    """Full train step with sequence=8 (pure SP) == single-device step."""
    model_cfg = MODEL_PRESETS["llama_tiny"]
    batch = {
        "input_ids": jax.random.randint(
            jax.random.PRNGKey(7), (2, 2, 64), 0, model_cfg.vocab_size),
        "loss_mask": jnp.ones((2, 2, 64), jnp.int32),
    }

    def mk(parallel, mesh=None):
        cfg = Config(
            model=model_cfg,
            lora=LoRAConfig(r=4, alpha=8, dropout=0.0),
            optimizer=OptimizerConfig(warmup_steps=2),
            parallel=parallel,
            train=TrainConfig(micro_batch_size=2, grad_accum_steps=2),
        )
        model = LlamaForCausalLM(cfg.model, cfg.lora, mesh)
        tx = build_optimizer(cfg.optimizer)
        state = create_train_state(rng, model, tx, (2, 64), lora_enabled=True)
        return cfg, model, state

    # Single-device ground truth.
    _, ref_model, ref_state = mk(ParallelConfig())
    ref_step = jax.jit(make_train_step(ref_model, accum_steps=2))
    for i in range(2):
        ref_state, ref_metrics = ref_step(ref_state, batch,
                                          jax.random.fold_in(rng, i))

    parallel = ParallelConfig(zero_stage=ZeROStage.ZERO1, sequence=8)
    mesh = build_mesh(parallel)
    cfg, model, state = mk(parallel, mesh)
    state = shard_train_state(state, cfg, mesh)
    step = make_sharded_train_step(model, state, cfg, mesh, accum_steps=2,
                                   donate=False)
    for i in range(2):
        state, metrics = step(state, batch, jax.random.fold_in(rng, i))

    np.testing.assert_allclose(float(metrics["loss"]),
                               float(ref_metrics["loss"]), rtol=2e-4)
    ref_t, _ = ref_state.trainable_and_frozen()
    sp_t, _ = state.trainable_and_frozen()
    for key in ref_t:
        np.testing.assert_allclose(
            np.asarray(jax.device_get(sp_t[key])), np.asarray(ref_t[key]),
            atol=2e-4, err_msg=f"param {key} diverged under SP",
        )
