"""CLI surface tests: prepare -> train -> compare, serve args.

The reference's user surface is CLI scripts driven by a notebook
(SURVEY.md §1 L2/L4); these tests pin our equivalents end-to-end in fresh
interpreters (subprocess) exactly as a user would invoke them.
"""

import json
import os
import subprocess
import sys

import pytest

# Heavy jit-compile tier: excluded from the fast pre-commit gate
# (`pytest -m 'not slow'`); the full suite runs them.
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(args, timeout=420, env_extra=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    env.update(env_extra or {})
    return subprocess.run(
        [sys.executable] + args, cwd=REPO, env=env,
        capture_output=True, text=True, timeout=timeout,
    )


@pytest.fixture(scope="module")
def workdir(tmp_path_factory):
    return tmp_path_factory.mktemp("cli")


@pytest.fixture(scope="module")
def prepared_data(workdir):
    out = workdir / "data"
    proc = _run(["scripts/prepare_dataset.py", "--synthetic", "48",
                 "--output-dir", str(out)])
    assert proc.returncode == 0, proc.stderr[-2000:]
    return out


def test_prepare_dataset_format_contract(prepared_data):
    """Rows must follow the Llama-2 chat contract byte-for-byte."""
    from datasets import load_from_disk

    ds = load_from_disk(str(prepared_data))
    assert len(ds) == 48
    t = ds[0]["text"]
    assert t.startswith("<s>[INST] ") and " [/INST] " in t and t.endswith("</s>")


def test_prepare_dataset_from_jsonl(workdir):
    src = workdir / "pairs.jsonl"
    with open(src, "w") as f:
        f.write(json.dumps({"question": " q1 ", "answer": " a1 "}) + "\n")
    out = workdir / "from_jsonl"
    proc = _run(["scripts/prepare_dataset.py", "--input-json", str(src),
                 "--output-dir", str(out)])
    assert proc.returncode == 0, proc.stderr[-2000:]
    from datasets import load_from_disk

    assert load_from_disk(str(out))[0]["text"] == "<s>[INST] q1 [/INST] a1</s>"


@pytest.fixture(scope="module")
def trained_csv(workdir, prepared_data):
    csv = workdir / "metrics.csv"
    for preset, ndev in (("baseline", "1"), ("zero1", "8")):
        proc = _run([
            "scripts/train.py", "--preset", preset, "--num-devices", ndev,
            "--model", "llama_tiny", "--tokenizer", "byte",
            "--dataset-path", str(prepared_data),
            "--max-steps", "2", "--max-seq-len", "64", "--lora-r", "4",
            "--gradient-accumulation-steps", "1", "--warmup-steps", "1",
            "--save-strategy", "no", "--metrics-csv", str(csv),
            "--output-dir", str(workdir / f"ckpt_{preset}"),
        ])
        assert proc.returncode == 0, proc.stderr[-3000:]
    return csv


def test_train_cli_pipe_composes_with_zero_preset(workdir, prepared_data):
    """r05: --pipe composes with ZeRO presets from the CLI — --data sets
    the batch-row extent (zero1: 'data' axis) alongside the pipe stages."""
    proc = _run([
        "scripts/train.py", "--preset", "zero1", "--pipe", "2",
        "--data", "2",
        "--model", "llama_tiny", "--tokenizer", "byte",
        "--dataset-path", str(prepared_data),
        "--max-steps", "2", "--max-seq-len", "64", "--lora-r", "4",
        "--per-device-batch-size", "1",
        "--gradient-accumulation-steps", "2", "--warmup-steps", "1",
        "--save-strategy", "no",
        "--metrics-csv", str(workdir / "pipe_zero1.csv"),
        "--output-dir", str(workdir / "ckpt_pipe_zero1"),
    ])
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert (workdir / "pipe_zero1.csv").exists()


def test_train_cli_writes_reference_schema(trained_csv):
    import pandas as pd

    df = pd.read_csv(trained_csv)
    assert len(df) == 2
    for col in ("experiment", "num_gpus", "zero_stage", "strategy",
                "training_time_hours", "samples_per_second",
                "peak_memory_gb", "final_loss"):
        assert col in df.columns, f"reference CSV column {col} missing"
    assert set(df["experiment"]) == {"baseline", "zero1_8dev"}
    assert df["final_loss"].notna().all()


def test_train_cli_eval_loop(workdir, prepared_data):
    """--eval-dataset/--eval-steps reach Trainer._run_eval and the metrics
    CSV carries the eval_loss column (VERDICT r02 weak #7)."""
    csv = workdir / "metrics_eval.csv"
    proc = _run([
        "scripts/train.py", "--preset", "baseline", "--num-devices", "1",
        "--model", "llama_tiny", "--tokenizer", "byte",
        "--dataset-path", str(prepared_data),
        "--eval-dataset", str(prepared_data), "--eval-steps", "2",
        "--max-steps", "2", "--max-seq-len", "64", "--lora-r", "4",
        "--gradient-accumulation-steps", "1", "--warmup-steps", "1",
        "--save-strategy", "no", "--metrics-csv", str(csv),
        "--output-dir", str(workdir / "ckpt_eval"),
    ])
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "eval @ step 2" in proc.stderr + proc.stdout
    import pandas as pd

    df = pd.read_csv(csv)
    assert "eval_loss" in df.columns and df["eval_loss"].notna().all()
    assert "peak_memory_source" in df.columns
    assert df["peak_memory_source"].isin(["device", "host_rss", "none"]).all()


def test_compare_cli(workdir, trained_csv):
    plot = workdir / "plots" / "cmp.png"
    proc = _run(["scripts/compare_training.py", "--csv", str(trained_csv),
                 "--plot-out", str(plot)], timeout=180)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "TRAINING COMPARISON" in proc.stdout
    assert "KEY FINDINGS" in proc.stdout
    assert plot.is_file()


def test_serve_cli_rejects_missing_model():
    proc = _run(["scripts/serve.py", "--tokenizer", "byte"], timeout=120)
    assert proc.returncode != 0
    assert "--model-dir or --random-init" in proc.stderr
