"""Unit tests: the durable writer's classified retry/reclaim/degrade
policy and the FaultyIO chaos injector it pairs with.

Everything here runs on the real filesystem with *injected* faults (the
``DLTI_IO_FAULT`` spec / an installed ``FaultyIO``) — no monkeypatched
builtins — because the injection point (durable_io's raw ops) is exactly
the boundary production writes cross.
"""

import errno
import json
import os

import pytest

from dlti_tpu.checkpoint.chaos import FaultyIO, IOFault
from dlti_tpu.utils import durable_io


@pytest.fixture(autouse=True)
def _clean_durable_io_state():
    durable_io.reset_for_tests()
    yield
    durable_io.reset_for_tests()


# ----------------------------------------------------------------------
# Errno classification
# ----------------------------------------------------------------------

def test_classify_errno():
    assert durable_io.classify_errno(OSError(errno.EIO, "x")) == "transient"
    assert durable_io.classify_errno(OSError(errno.EAGAIN, "x")) == "transient"
    assert durable_io.classify_errno(OSError(errno.ESTALE, "x")) == "transient"
    assert durable_io.classify_errno(OSError(errno.ENOSPC, "x")) == "reclaim"
    assert durable_io.classify_errno(OSError(errno.EDQUOT, "x")) == "reclaim"
    assert durable_io.classify_errno(OSError(errno.EACCES, "x")) == "persistent"
    assert durable_io.classify_errno(ValueError("x")) == "persistent"


# ----------------------------------------------------------------------
# FaultyIO spec parsing
# ----------------------------------------------------------------------

def test_parse_rule_errno_count_delay():
    r = FaultyIO.parse_rule("*ckpt*:ENOSPC:3:0.5")
    assert (r.glob, r.kind, r.err, r.remaining, r.rate, r.delay_s) == \
        ("*ckpt*", "enospc", errno.ENOSPC, 3, None, 0.5)


def test_parse_rule_rate_and_torn_and_slow():
    r = FaultyIO.parse_rule("MANIFEST.json:EIO:0.5")
    assert r.rate == 0.5 and r.remaining is None
    t = FaultyIO.parse_rule("*:torn")
    assert t.err == errno.EIO and t.kind == "torn"
    s = FaultyIO.parse_rule("*:slow")
    assert s.err is None and s.delay_s > 0


@pytest.mark.parametrize("bad", [
    "no-errno-part", ":EIO", "*:NOTANERRNO", "*:EIO:0", "*:EIO:-2",
    "*:EIO:1.5",
])
def test_parse_rule_rejects_bad_specs(bad):
    with pytest.raises(ValueError):
        FaultyIO.parse_rule(bad)


def test_from_spec_multi_rule_and_empty():
    inj = FaultyIO.from_spec("*a*:EIO:1;*b*:ENOSPC")
    assert len(inj.faults) == 2
    assert FaultyIO.from_spec("  ;  ") is None


def test_fault_matching_full_path_and_basename():
    f = IOFault(glob="hb_*.json", kind="eio", err=errno.EIO)
    assert f.matches("/any/where/hb_g0_r1.json")
    assert not f.matches("/any/where/ledger_g0_r1.json")


def test_count_budget_consumed_then_clears():
    inj = FaultyIO.from_spec("*:EIO:2")
    assert inj.plan("write", "/x") is not None
    assert inj.plan("write", "/x") is not None
    assert inj.plan("write", "/x") is None  # budget spent: fault cleared
    assert inj.total_fired == 2


# ----------------------------------------------------------------------
# write_bytes: retry / degrade / recover
# ----------------------------------------------------------------------

def test_transient_eio_is_retried_away(tmp_path):
    path = tmp_path / "ckpt.bin"
    with FaultyIO.from_spec("*ckpt.bin:EIO:2"):
        assert durable_io.write_bytes(str(path), b"payload",
                                      path_class="checkpoint",
                                      backoff_s=0.001)
    assert path.read_bytes() == b"payload"
    led = durable_io.disk_ledger()["checkpoint"]
    assert led["errors"] == 2 and led["writes"] == 1
    assert not durable_io.is_degraded("checkpoint")


def test_raising_class_reraises_after_budget(tmp_path):
    path = tmp_path / "ckpt.bin"
    with FaultyIO.from_spec("*ckpt.bin:EACCES"):
        with pytest.raises(OSError) as ei:
            durable_io.write_bytes(str(path), b"x", path_class="checkpoint",
                                   backoff_s=0.001)
    assert ei.value.errno == errno.EACCES
    assert durable_io.is_degraded("checkpoint")


def test_drop_class_returns_false_and_counts(tmp_path):
    path = tmp_path / "log.jsonl"
    with FaultyIO.from_spec("*log.jsonl:EIO"):
        assert durable_io.append_line(str(path), "line",
                                      path_class="steplog",
                                      backoff_s=0.001) is False
    led = durable_io.disk_ledger()["steplog"]
    assert led["drops"] == 1
    assert durable_io.is_degraded("steplog")
    assert durable_io.degraded_classes() == ("steplog",)


def test_first_success_clears_degraded(tmp_path):
    path = tmp_path / "log.jsonl"
    with FaultyIO.from_spec("*log.jsonl:EIO:1"):
        durable_io.append_line(str(path), "dropped", path_class="steplog")
    assert durable_io.is_degraded("steplog")
    assert durable_io.append_line(str(path), "kept", path_class="steplog")
    assert not durable_io.is_degraded("steplog")
    assert path.read_text() == "kept\n"


def test_torn_write_leaves_half_payload(tmp_path):
    path = tmp_path / "blob.bin"
    with FaultyIO.from_spec("*blob.bin:torn"):
        with pytest.raises(OSError):
            durable_io.write_bytes(str(path), b"0123456789",
                                   path_class="checkpoint", retries=0)
    assert path.read_bytes() == b"01234"  # the wreckage is on disk


def test_slow_write_succeeds(tmp_path):
    path = tmp_path / "s.bin"
    with FaultyIO.from_spec("*s.bin:slow::0.01"):
        assert durable_io.write_bytes(str(path), b"x",
                                      path_class="checkpoint")
    assert path.read_bytes() == b"x"


# ----------------------------------------------------------------------
# ENOSPC reclaim
# ----------------------------------------------------------------------

def test_enospc_runs_reclaimers_then_retries(tmp_path):
    junk = tmp_path / "_quarantine" / "old"
    junk.mkdir(parents=True)
    (junk / "w.bin").write_bytes(b"z" * 4096)
    durable_io.register_reclaimer(
        "q", durable_io.quarantine_reclaimer(str(tmp_path)))
    path = tmp_path / "data.bin"
    # One ENOSPC: the reclaim pass frees quarantine bytes, then the free
    # retry (no budget burned) succeeds.
    with FaultyIO.from_spec("*data.bin:ENOSPC:1"):
        assert durable_io.write_bytes(str(path), b"x" * 16,
                                      path_class="checkpoint", retries=0)
    assert not junk.exists()
    led = durable_io.disk_ledger()["checkpoint"]
    assert led["reclaims"] == 1 and led["reclaimed_bytes"] >= 4096
    assert path.read_bytes() == b"x" * 16


def test_persistent_enospc_degrades_after_budget(tmp_path):
    path = tmp_path / "data.bin"
    with FaultyIO.from_spec("*data.bin:ENOSPC"):
        with pytest.raises(OSError) as ei:
            durable_io.write_bytes(str(path), b"x", path_class="checkpoint",
                                   retries=1, backoff_s=0.001)
    assert ei.value.errno == errno.ENOSPC
    assert durable_io.is_degraded("checkpoint")


def test_sweep_oldest_keeps_newest(tmp_path):
    d = tmp_path / "dumps"
    d.mkdir()
    for i in range(4):
        p = d / f"f{i}"
        p.write_bytes(b"x" * 10)
        os.utime(p, (i, i))  # deterministic mtime order
    freed = durable_io.sweep_oldest(str(d), keep=1)
    assert freed == 30
    assert sorted(os.listdir(d)) == ["f3"]


# ----------------------------------------------------------------------
# write_json_atomic / LineWriter
# ----------------------------------------------------------------------

def test_write_json_atomic_roundtrip_and_no_tmp_left(tmp_path):
    path = tmp_path / "hb.json"
    assert durable_io.write_json_atomic(str(path), {"step": 3},
                                        path_class="elastic")
    assert json.loads(path.read_text()) == {"step": 3}
    assert os.listdir(tmp_path) == ["hb.json"]  # staging tmp cleaned up


def test_write_json_atomic_drop_class_failure_keeps_old_file(tmp_path):
    path = tmp_path / "hb.json"
    path.write_text('{"step": 1}')
    with FaultyIO.from_spec("*:EIO"):
        assert durable_io.write_json_atomic(str(path), {"step": 2},
                                            path_class="elastic",
                                            retries=0) is False
    # The previous atomic write survives a failed refresh intact.
    assert json.loads(path.read_text()) == {"step": 1}


def test_linewriter_drops_and_self_heals(tmp_path):
    path = tmp_path / "stream.jsonl"
    w = durable_io.LineWriter(str(path), path_class="steplog")
    assert w.write_line("a")
    with FaultyIO.from_spec("*stream.jsonl:EIO"):
        assert w.write_line("b") is False
        assert w.write_line("c") is False
    assert w.dropped == 2
    assert w.write_line("d")  # fault cleared: stream reopens and heals
    w.close()
    assert path.read_text().splitlines() == ["a", "d"]
    assert not durable_io.is_degraded("steplog")


# ----------------------------------------------------------------------
# Env-spec activation + scalars
# ----------------------------------------------------------------------

def test_env_spec_injects_without_install(tmp_path, monkeypatch):
    monkeypatch.setenv(durable_io.IO_FAULT_ENV, "*env.bin:EIO")
    assert durable_io.write_bytes(str(tmp_path / "env.bin"), b"x",
                                  path_class="steplog", retries=0) is False
    monkeypatch.delenv(durable_io.IO_FAULT_ENV)
    # Spec change (removal) re-parses: writes work again.
    assert durable_io.write_bytes(str(tmp_path / "env.bin"), b"x",
                                  path_class="steplog")


def test_scalars_report_errors_and_degraded(tmp_path):
    with FaultyIO.from_spec("*:EIO"):
        durable_io.append_line(str(tmp_path / "l"), "x",
                               path_class="steplog")
    s = durable_io.scalars()
    assert s["disk_write_errors"] >= 1
    assert s["disk_write_drops"] == 1
    assert s["disk_degraded"] == 1
    assert s["disk_free_bytes"] > 0


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-q"]))
