"""Unified telemetry layer tests (tier-1).

Covers the four contracts the subsystem makes:

* **Exposition stability** — the registry-backed ``/metrics`` keeps every
  pre-existing ``dlti_<stat>`` name and TYPE byte-for-byte (golden test
  against the legacy inline renderer), and adds the TTFT/TPOT/queue-time
  histograms.
* **Tracer bounds + format** — the span ring buffer never exceeds its
  capacity, and exports load as valid Chrome-trace JSON (``ph``/``ts``/
  ``name`` on every event) viewable in Perfetto.
* **Engine lifecycle ordering** — a served request's spans appear in
  submitted → queued → prefill → decode order with matching histogram
  observations.
* **Disabled-path overhead** — a disabled tracer's span site costs an
  attribute read (bounded well under the noise floor of a decode step).

Plus the training-side stream: the per-step JSONL schema stays a superset
of the reference CSV columns (the parity contract in
``dlti_tpu/utils/metrics.py``), verified both statically and from a real
tiny training run that also exercises ``--trace-dir``'s per-step phase
spans.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlti_tpu.config import (
    CheckpointConfig, Config, DataConfig, LoRAConfig, MODEL_PRESETS,
    TelemetryConfig, TrainConfig,
)
from dlti_tpu.models import LlamaForCausalLM
from dlti_tpu.serving import EngineConfig, InferenceEngine, SamplingParams
from dlti_tpu.telemetry import (
    Heartbeat, MetricsRegistry, SpanTracer, configure_tracer, get_tracer,
    jsonl_stream_columns, metrics_csv_columns, schedule_lr,
)
from dlti_tpu.telemetry.registry import Histogram
from dlti_tpu.utils.metrics import REFERENCE_CSV_COLUMNS

CFG = MODEL_PRESETS["llama_tiny"]


# ----------------------------------------------------------------------
# Registry
# ----------------------------------------------------------------------

# A stats dict shaped like the engine's (every key the legacy inline
# exposition rendered), with the derived gauges the server adds.
FAKE_STATS = {
    "requests": 3, "generated_tokens": 12, "prefill_tokens": 9,
    "preemptions": 0, "decode_steps": 4, "decode_slot_steps": 7,
    "prefix_cached_tokens": 0, "spec_proposed": 0, "spec_accepted": 0,
    "spec_paused_rounds": 0,
    "active_seqs": 1, "waiting": 2, "free_blocks": 100,
}
GAUGE_KEYS = ("active_seqs", "waiting", "free_blocks")


def _legacy_exposition(stats: dict) -> str:
    """The exact renderer serving/server.py inlined before the registry."""
    lines = []
    for k, v in sorted(stats.items()):
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        name = f"dlti_{k}"
        kind = "gauge" if k in GAUGE_KEYS else "counter"
        lines += [f"# TYPE {name} {kind}", f"{name} {v}"]
    return "\n".join(lines) + "\n"


def test_registry_exposition_matches_legacy_renderer():
    """Golden: with only the scalar source registered, the registry
    reproduces the legacy /metrics output byte-for-byte."""
    reg = MetricsRegistry()
    reg.add_scalar_source(lambda: dict(FAKE_STATS), gauge_keys=GAUGE_KEYS,
                          prefix="dlti_")
    assert reg.render_prometheus() == _legacy_exposition(FAKE_STATS)


def test_registry_exposition_with_histograms_keeps_legacy_lines():
    """Adding histograms must not rename or retype any legacy series."""
    reg = MetricsRegistry()
    reg.add_scalar_source(lambda: dict(FAKE_STATS), gauge_keys=GAUGE_KEYS,
                          prefix="dlti_")
    h = Histogram("dlti_request_ttft_seconds", (0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    reg.register(h)
    text = reg.render_prometheus()
    legacy_lines = _legacy_exposition(FAKE_STATS).strip().splitlines()
    new_lines = text.strip().splitlines()
    # Every legacy line survives verbatim, in the same relative order.
    it = iter(new_lines)
    for want in legacy_lines:
        for got in it:
            if got == want:
                break
        else:
            pytest.fail(f"legacy exposition line missing/reordered: {want}")
    # Histogram series render in Prometheus histogram format, cumulative.
    assert "# TYPE dlti_request_ttft_seconds histogram" in text
    assert 'dlti_request_ttft_seconds_bucket{le="0.1"} 1' in text
    assert 'dlti_request_ttft_seconds_bucket{le="1"} 2' in text
    assert 'dlti_request_ttft_seconds_bucket{le="+Inf"} 3' in text
    assert "dlti_request_ttft_seconds_count 3" in text


def test_registry_stats_dict_merges_sources_and_summaries():
    reg = MetricsRegistry()
    reg.add_scalar_source(lambda: dict(FAKE_STATS), gauge_keys=GAUGE_KEYS,
                          prefix="dlti_")
    h = Histogram("dlti_request_ttft_seconds", (0.1, 1.0),
                  stats_key="request_ttft_seconds")
    h.observe(0.2)
    reg.register(h)
    d = reg.stats_dict()
    assert d["requests"] == 3 and d["free_blocks"] == 100
    s = d["request_ttft_seconds"]
    assert s["count"] == 1 and s["mean"] == pytest.approx(0.2)
    assert set(s) >= {"count", "sum", "mean", "p50", "p90", "p99"}


def test_histogram_percentiles_and_labels():
    h = Histogram("h", (1.0, 2.0, 4.0))
    for v in (0.5, 1.5, 1.5, 3.0):
        h.observe(v)
    assert 0.0 < h.percentile(50) <= 2.0
    assert h.percentile(99) <= 4.0
    reg = MetricsRegistry()
    g = reg.gauge("dlti_heartbeat_last_step")
    g.labels(process="0").set(7)
    g.labels(process="1").set(5)
    text = reg.render_prometheus()
    assert 'dlti_heartbeat_last_step{process="0"} 7' in text
    assert 'dlti_heartbeat_last_step{process="1"} 5' in text


# ----------------------------------------------------------------------
# Tracer
# ----------------------------------------------------------------------

def test_tracer_ring_buffer_bounded(tmp_path):
    tr = SpanTracer(capacity=100, enabled=True)
    for i in range(250):
        tr.instant(f"e{i}")
    assert len(tr) == 100
    # Oldest dropped: the survivors are the most recent 100.
    names = [e["name"] for e in tr.events()]
    assert names[0] == "e150" and names[-1] == "e249"


def test_tracer_chrome_export_valid(tmp_path):
    tr = SpanTracer(capacity=64, enabled=True)
    with tr.span("phase_a", cat="test", step=1):
        pass
    tr.complete("phase_b", 1.0, 2.0, cat="test")
    tr.instant("mark")
    path = tr.export(str(tmp_path / "trace.json"))
    with open(path) as f:
        d = json.load(f)  # must be valid JSON
    evs = d["traceEvents"]
    assert len(evs) == 3
    for ev in evs:
        assert {"ph", "ts", "name", "pid", "tid"} <= set(ev)
    spans = [e for e in evs if e["ph"] == "X"]
    assert all("dur" in e and e["dur"] >= 0 for e in spans)
    b = next(e for e in evs if e["name"] == "phase_b")
    assert b["ts"] == pytest.approx(1.0e6) and b["dur"] == pytest.approx(1.0e6)


def test_tracer_disabled_overhead_smoke():
    """The disabled span site must be unmeasurable against a decode step:
    bound the per-call cost at 20 µs (measured ~0.3 µs; the bound only
    exists to catch an accidental dict/lock/clock on the disabled path)."""
    tr = SpanTracer(enabled=False)
    n = 20000
    t0 = time.perf_counter()
    for _ in range(n):
        with tr.span("hot"):
            pass
        tr.instant("hot")
        tr.complete("hot", 0.0, 1.0)
    dt = time.perf_counter() - t0
    assert len(tr) == 0  # nothing recorded
    assert dt / n < 20e-6, f"disabled-path cost {dt / n * 1e6:.2f} us/site"


def test_configure_tracer_resizes_and_toggles():
    tr = configure_tracer(enabled=True, capacity=8)
    try:
        assert tr is get_tracer()
        for i in range(20):
            tr.instant(f"x{i}")
        assert len(tr) == 8
    finally:
        configure_tracer(enabled=False)
        tr.clear()


# ----------------------------------------------------------------------
# Engine request lifecycle
# ----------------------------------------------------------------------

@pytest.fixture(scope="module")
def traced_engine():
    """Tiny engine driven through a few requests with tracing enabled."""
    model = LlamaForCausalLM(CFG, None)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    ec = EngineConfig(max_seqs=2, block_size=8, num_blocks=64,
                      max_model_len=64, cache_dtype="float32",
                      eos_token_id=-1)
    tracer = configure_tracer(enabled=True, capacity=4096)
    tracer.clear()
    engine = InferenceEngine(CFG, params, ec)
    prompts = [[5, 6, 7], [9, 10], [11, 12, 13, 14]]
    results = engine.generate(prompts,
                              SamplingParams(max_tokens=4, temperature=0.0))
    yield engine, results, tracer.events()
    configure_tracer(enabled=False)
    tracer.clear()


def test_request_lifecycle_span_ordering(traced_engine):
    engine, results, events = traced_engine
    assert all(r.finish_reason == "length" for r in results)
    for r in results:
        mine = [e for e in events
                if e.get("args", {}).get("id") == r.request_id]
        by_name = {e["name"]: e for e in mine}
        assert {"request/submitted", "request/queued", "request/prefill",
                "request/decode"} <= set(by_name), by_name.keys()
        sub = by_name["request/submitted"]
        q, p, d = (by_name["request/queued"], by_name["request/prefill"],
                   by_name["request/decode"])
        # Phase ordering: each phase starts no earlier than the previous
        # one began, and spans chain start -> end -> next start.
        assert sub["ts"] <= q["ts"] + q["dur"]
        assert q["ts"] <= p["ts"] and p["ts"] <= d["ts"]
        assert q["ts"] + q["dur"] <= p["ts"] + p["dur"] + 1e-3
        assert d["args"]["output_tokens"] == 4
        assert d["args"]["finish_reason"] == "length"


def test_engine_step_phase_spans_present(traced_engine):
    _, _, events = traced_engine
    names = {e["name"] for e in events}
    assert "engine/decode_dispatch" in names
    assert "engine/admit" in names
    assert "engine/decode_sync" in names


def test_lifecycle_histograms_observed(traced_engine):
    engine, results, _ = traced_engine
    tel = engine.telemetry
    n = len(results)
    assert tel.ttft.snapshot()[2] == n
    assert tel.queue_time.snapshot()[2] == n
    assert tel.tpot.snapshot()[2] == n  # every request emitted > 1 token
    # max_tokens=4 -> 3 inter-token gaps per request, all positive.
    assert tel.tpot.summary()["mean"] > 0


def test_server_registry_backing(traced_engine):
    """build_registry over a live engine: legacy names + histograms in one
    exposition, /stats served from the same store."""
    engine, _, _ = traced_engine

    class _FakeAsync:  # build_registry only reads .engine
        pass

    fake = _FakeAsync()
    fake.engine = engine
    from dlti_tpu.serving.server import build_registry

    reg = build_registry(fake)
    text = reg.render_prometheus()
    assert "# TYPE dlti_requests counter" in text
    assert "# TYPE dlti_free_blocks gauge" in text
    assert "# TYPE dlti_request_ttft_seconds histogram" in text
    assert "# TYPE dlti_request_tpot_seconds histogram" in text
    assert "# TYPE dlti_request_queue_time_seconds histogram" in text
    d = reg.stats_dict()
    assert d["requests"] == engine.stats["requests"]
    assert d["request_ttft_seconds"]["count"] == 3


# ----------------------------------------------------------------------
# Heartbeat
# ----------------------------------------------------------------------

def test_heartbeat_single_process_and_gauges():
    reg = MetricsRegistry()
    hb = Heartbeat(registry=reg)
    hb.beat(10)
    assert hb.last_seen[0][0] == 10
    assert hb.lag() == 0 and hb.straggler_report() is None
    # Straggler arithmetic on an injected multi-process view.
    hb.last_seen[1] = (7, time.time())
    assert hb.lag() == 3
    assert "proc 1: -3" in hb.straggler_report()
    text = reg.render_prometheus()
    assert 'dlti_heartbeat_last_step{process="0"} 10' in text


def test_heartbeat_straggler_report_and_lag_gauge():
    """straggler_report() had no unit test (log-only until the lag
    gauge); pin its text + the per-rank lags()/gauge surface."""
    reg = MetricsRegistry()
    hb = Heartbeat(registry=reg)
    # Lockstep fleet: no report, zero lags.
    now = time.time()
    hb.last_seen = {0: (12, now), 1: (12, now)}
    assert hb.straggler_report() is None
    assert hb.lags() == {0: 0, 1: 0}
    # Two stragglers at different depths: the report names each with its
    # deficit, sorted by rank; lags() is the gauge form of the same view.
    hb.last_seen = {0: (12, now), 1: (9, now), 2: (5, now)}
    report = hb.straggler_report()
    assert "behind step 12" in report
    assert "proc 1: -3" in report and "proc 2: -7" in report
    assert hb.lags() == {0: 0, 1: 3, 2: 7}
    # beat() refreshes both gauges; per-rank lag is exposed for scrape.
    hb.beat(12)
    text = reg.render_prometheus()
    assert 'dlti_heartbeat_lag_steps{process="0"} 0' in text
    assert 'dlti_heartbeat_lag_steps{process="2"} 7' in text
    # Empty map degrades cleanly.
    hb.last_seen = {}
    assert hb.lags() == {} and hb.lag() == 0
    assert hb.straggler_report() is None


# ----------------------------------------------------------------------
# Per-step JSONL stream: schema superset of the reference CSV
# ----------------------------------------------------------------------

def test_jsonl_schema_superset_of_reference_csv():
    cols = jsonl_stream_columns()
    assert set(REFERENCE_CSV_COLUMNS) <= cols
    # ... and of the extended CSV (MetricsRecord) too.
    assert set(metrics_csv_columns()) <= cols


def test_schedule_lr_matches_optax():
    import dataclasses

    from dlti_tpu.config import OptimizerConfig
    from dlti_tpu.training.optimizer import build_schedule

    for kwargs in ({"schedule": "warmup_constant", "warmup_steps": 10},
                   {"schedule": "warmup_cosine", "warmup_steps": 5,
                    "total_steps": 50}):
        cfg = OptimizerConfig(learning_rate=3e-4, **kwargs)
        sched = build_schedule(cfg)
        for step in (0, 1, 5, 10, 25, 50, 80):
            assert schedule_lr(cfg, step) == pytest.approx(
                float(sched(step)), rel=1e-5), (kwargs, step)


def test_training_smoke_writes_stream_and_trace(tmp_path):
    """Tiny end-to-end train with telemetry on: the JSONL stream has
    run/step/final records (final ⊇ reference CSV columns) and the trace
    dir gets a Perfetto-loadable Chrome trace with per-step phase spans —
    the acceptance criterion for ``--trace-dir``."""
    from dlti_tpu.training import Trainer

    cfg = Config(
        model=CFG,
        lora=LoRAConfig(enabled=False),
        data=DataConfig(max_seq_len=16),
        checkpoint=CheckpointConfig(save_strategy="no"),
        train=TrainConfig(num_epochs=1, micro_batch_size=2,
                          grad_accum_steps=1, max_steps=2, logging_steps=1),
        telemetry=TelemetryConfig(
            trace_dir=str(tmp_path / "traces"),
            step_log_path=str(tmp_path / "steps.jsonl"),
            heartbeat_interval_steps=1),
    )
    rng = np.random.default_rng(0)
    ids = [rng.integers(1, 500, (1, 2, 16), dtype=np.int32)
           for _ in range(3)]
    batches = [{"input_ids": a, "labels": a} for a in ids]
    try:
        trainer = Trainer(cfg)
        _, record = trainer.train(batches_per_epoch=batches)
    finally:
        configure_tracer(enabled=False)
        get_tracer().clear()

    lines = [json.loads(l) for l in open(tmp_path / "steps.jsonl")]
    assert [l["type"] for l in lines] == ["run", "step", "step", "final"]
    from dlti_tpu.telemetry.steplog import STEP_RECORD_FIELDS

    for step_rec in lines[1:-1]:
        assert set(STEP_RECORD_FIELDS) <= set(step_rec)
        assert step_rec["loss"] > 0
    final = lines[-1]
    assert set(REFERENCE_CSV_COLUMNS) <= set(final)
    assert final["final_loss"] == pytest.approx(record.final_loss)

    traces = list((tmp_path / "traces").glob("*.json"))
    assert len(traces) == 1
    with open(traces[0]) as f:
        d = json.load(f)
    names = {e["name"] for e in d["traceEvents"]}
    assert {"train/batch_fetch", "train/step_dispatch",
            "train/device_sync"} <= names
    for ev in d["traceEvents"]:
        assert {"ph", "ts", "name"} <= set(ev)
