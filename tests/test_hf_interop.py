"""HF checkpoint interop: logits parity with transformers, safetensors IO
roundtrips, and PEFT adapter import/export.

This is the "switch from the reference" contract: the reference's artifacts
are HF hub checkpoints (``training/train_baseline.py:122-126``) and PEFT
LoRA adapters (``training/train_baseline.py:226-228``); both must map onto
our param tree losslessly and produce the same function.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlti_tpu.config import LoRAConfig, ModelConfig
from dlti_tpu.models import (
    LlamaForCausalLM,
    config_from_hf,
    config_to_hf,
    hf_state_dict_from_params,
    load_hf_checkpoint,
    load_peft_adapter,
    merge_lora_params,
    params_from_hf_state_dict,
    save_hf_checkpoint,
    save_peft_adapter,
)

# Heavy jit-compile tier: excluded from the fast pre-commit gate
# (`pytest -m 'not slow'`); the full suite runs them.
pytestmark = pytest.mark.slow

# fp32 everywhere so the parity check is numerically meaningful.
TINY = ModelConfig(
    vocab_size=256, hidden_size=64, intermediate_size=128, num_layers=2,
    num_heads=4, num_kv_heads=2, max_seq_len=64, dtype="float32",
    param_dtype="float32", remat=False, attention_impl="reference",
)


def _hf_tiny_model():
    torch = pytest.importorskip("torch")
    from transformers import LlamaConfig
    from transformers import LlamaForCausalLM as HFLlama

    hf_cfg = LlamaConfig(
        vocab_size=TINY.vocab_size, hidden_size=TINY.hidden_size,
        intermediate_size=TINY.intermediate_size,
        num_hidden_layers=TINY.num_layers,
        num_attention_heads=TINY.num_heads,
        num_key_value_heads=TINY.num_kv_heads,
        max_position_embeddings=TINY.max_seq_len,
        rms_norm_eps=TINY.rms_norm_eps, rope_theta=TINY.rope_theta,
        tie_word_embeddings=False, attention_bias=False,
    )
    torch.manual_seed(0)
    model = HFLlama(hf_cfg).eval()
    return model


def _hf_state_dict_numpy(model):
    return {k: v.detach().cpu().numpy() for k, v in model.state_dict().items()}


def test_logits_match_transformers():
    """Converted weights produce the same logits as the HF torch model."""
    torch = pytest.importorskip("torch")
    hf_model = _hf_tiny_model()
    params = params_from_hf_state_dict(_hf_state_dict_numpy(hf_model), TINY)

    ids = np.random.default_rng(0).integers(0, TINY.vocab_size, (2, 16))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()

    ours = LlamaForCausalLM(TINY)
    logits, _ = ours.apply({"params": params}, jnp.asarray(ids, jnp.int32))
    np.testing.assert_allclose(np.asarray(logits), hf_logits, rtol=2e-4, atol=2e-4)


def test_state_dict_roundtrip():
    hf_model = _hf_tiny_model()
    sd = _hf_state_dict_numpy(hf_model)
    params = params_from_hf_state_dict(sd, TINY)
    back = hf_state_dict_from_params(params, TINY)
    sd_keys = {k for k in sd if "rotary_emb" not in k}
    assert sd_keys == set(back)
    for k in back:
        np.testing.assert_array_equal(np.asarray(back[k]), sd[k])


def test_unconsumed_keys_rejected():
    hf_model = _hf_tiny_model()
    sd = _hf_state_dict_numpy(hf_model)
    sd["model.layers.7.self_attn.q_proj.weight"] = sd[
        "model.layers.0.self_attn.q_proj.weight"]
    with pytest.raises(ValueError, match="unconsumed"):
        params_from_hf_state_dict(sd, TINY)


def test_checkpoint_dir_roundtrip(tmp_path):
    """save_hf_checkpoint -> load_hf_checkpoint is lossless, incl. sharding."""
    rng = jax.random.PRNGKey(0)
    model = LlamaForCausalLM(TINY)
    params = model.init(rng, jnp.zeros((1, 8), jnp.int32))["params"]

    # Tiny shard budget to force the multi-file + index path.
    save_hf_checkpoint(str(tmp_path), params, TINY, max_shard_bytes=200_000)
    assert os.path.exists(tmp_path / "model.safetensors.index.json")
    loaded, cfg = load_hf_checkpoint(str(tmp_path))
    assert cfg.hidden_size == TINY.hidden_size
    flat_a = jax.tree_util.tree_leaves_with_path(params)
    flat_b = jax.tree_util.tree_leaves_with_path(loaded)
    assert [p for p, _ in flat_a] == [p for p, _ in flat_b]
    for (_, a), (_, b) in zip(flat_a, flat_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_config_mapping_roundtrip():
    hf = config_to_hf(TINY)
    cfg = config_from_hf(hf, dtype="float32", param_dtype="float32",
                         remat=False, attention_impl="reference")
    assert cfg.vocab_size == TINY.vocab_size
    assert cfg.num_kv_heads == TINY.num_kv_heads
    assert cfg.resolved_head_dim == TINY.resolved_head_dim
    assert cfg.tie_embeddings == TINY.tie_embeddings


def test_peft_adapter_roundtrip(tmp_path):
    """Export LoRA factors as a PEFT adapter, reload into fresh params."""
    lora = LoRAConfig(r=4, alpha=8, dropout=0.0)
    model = LlamaForCausalLM(TINY, lora)
    rng = jax.random.PRNGKey(1)
    params = model.init(rng, jnp.zeros((1, 8), jnp.int32))["params"]

    # Give lora_b nonzero values so the roundtrip is observable.
    params = jax.tree_util.tree_map_with_path(
        lambda path, x: jax.random.normal(
            jax.random.PRNGKey(hash(str(path)) % (2**31)), x.shape, x.dtype)
        if any(getattr(k, "key", "") in ("lora_a", "lora_b") for k in path) else x,
        params,
    )
    save_peft_adapter(str(tmp_path), params, lora)
    assert os.path.exists(tmp_path / "adapter_model.safetensors")
    with open(tmp_path / "adapter_config.json") as f:
        acfg = json.load(f)
    assert acfg["r"] == 4 and acfg["peft_type"] == "LORA"

    fresh = model.init(jax.random.PRNGKey(2), jnp.zeros((1, 8), jnp.int32))["params"]
    loaded = load_peft_adapter(str(tmp_path), fresh)
    a = jax.tree_util.tree_leaves_with_path(params)
    b = jax.tree_util.tree_leaves_with_path(loaded)
    for (path, va), (_, vb) in zip(a, b):
        if any(getattr(k, "key", "") in ("lora_a", "lora_b") for k in path):
            np.testing.assert_array_equal(np.asarray(va), np.asarray(vb))


def test_peft_adapter_loads_into_peft_library(tmp_path):
    """The exported adapter parses with the actual peft library against the
    matching HF base model, and the merged outputs agree with ours."""
    torch = pytest.importorskip("torch")
    peft = pytest.importorskip("peft")

    hf_model = _hf_tiny_model()
    params = params_from_hf_state_dict(_hf_state_dict_numpy(hf_model), TINY)

    lora = LoRAConfig(r=4, alpha=8, dropout=0.0)
    ours = LlamaForCausalLM(TINY, lora)
    lora_params = ours.init(jax.random.PRNGKey(3),
                            jnp.zeros((1, 8), jnp.int32))["params"]

    # Graft the HF base weights under our randomly-initialized LoRA factors.
    def graft(lp, base):
        if isinstance(lp, dict):
            return {k: graft(v, base[k]) if k in base else lp[k] for k, v in lp.items()}
        return base
    merged_tree = graft(lora_params, params)

    save_peft_adapter(str(tmp_path), merged_tree, lora)

    peft_model = peft.PeftModel.from_pretrained(hf_model, str(tmp_path))
    peft_model = peft_model.merge_and_unload()

    ids = np.random.default_rng(1).integers(0, TINY.vocab_size, (2, 12))
    with torch.no_grad():
        hf_logits = peft_model(torch.tensor(ids)).logits.numpy()

    merged_params = merge_lora_params(merged_tree, alpha=lora.alpha)
    logits, _ = LlamaForCausalLM(TINY).apply(
        {"params": merged_params}, jnp.asarray(ids, jnp.int32))
    np.testing.assert_allclose(np.asarray(logits), hf_logits, rtol=5e-4, atol=5e-4)


def test_trainer_init_from_hf_base_params(tmp_path):
    """Trainer(base_params=...) grafts HF weights under fresh LoRA factors."""
    from dlti_tpu.config import (CheckpointConfig, Config, DataConfig,
                                 LoRAConfig as LC, OptimizerConfig,
                                 ParallelConfig, TrainConfig)
    from dlti_tpu.training import Trainer

    hf_model = _hf_tiny_model()
    base = params_from_hf_state_dict(_hf_state_dict_numpy(hf_model), TINY)
    cfg = Config(
        model=TINY, lora=LC(r=4, alpha=8, dropout=0.0),
        optimizer=OptimizerConfig(warmup_steps=1),
        parallel=ParallelConfig(),
        data=DataConfig(max_seq_len=16),
        train=TrainConfig(micro_batch_size=2, grad_accum_steps=1),
        checkpoint=CheckpointConfig(output_dir=str(tmp_path), save_strategy="no"),
    )
    trainer = Trainer(cfg, base_params=base)
    state = trainer.init_state()
    got = np.asarray(
        state.params["model"]["layers_0"]["attn"]["q_proj"]["kernel"])
    want = np.asarray(base["model"]["layers_0"]["attn"]["q_proj"]["kernel"])
    np.testing.assert_array_equal(got, want)
    # LoRA factors exist and lora_b starts at zero (PEFT semantics).
    lb = np.asarray(state.params["model"]["layers_0"]["attn"]["q_proj"]["lora_b"])
    assert (lb == 0).all()


def test_graft_shape_mismatch_rejected():
    from dlti_tpu.models import graft_base_params

    hf_model = _hf_tiny_model()
    base = params_from_hf_state_dict(_hf_state_dict_numpy(hf_model), TINY)
    model = LlamaForCausalLM(TINY)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    base["model"]["embed_tokens"] = base["model"]["embed_tokens"][:, :32]
    with pytest.raises(ValueError, match="shape"):
        graft_base_params(params, base)
