"""Fleet wire-protocol tests: serializer round-trips, frame integrity,
and malformed-input robustness (ISSUE 17 satellite).

Every failure mode gets a dedicated exception so the supervisor can tell
"peer died mid-frame" (fail over) from "peer spoke garbage" (evict); these
tests pin that taxonomy and the byte-exactness of the serializer the
paged-KV handoff envelope rides on.
"""

import hashlib
import socket
import struct
import threading
import time

import numpy as np
import pytest

from dlti_tpu.serving import wire
from dlti_tpu.serving.engine import Request
from dlti_tpu.serving.sampling import SamplingParams


def _pair():
    a, b = socket.socketpair()
    return a, b


# -- tagged serializer -------------------------------------------------------

@pytest.mark.parametrize("obj", [
    None,
    True,
    False,
    0,
    -1,
    (1 << 62),
    -(1 << 63),            # int64 min boundary
    (1 << 63) - 1,         # int64 max boundary
    (1 << 80),             # bigint path
    -(1 << 100),
    3.25,
    float("inf"),
    "",
    "héllo wörld",
    b"",
    b"\x00\xff raw",
    [],
    [1, "two", 3.0, None],
    (4, 5, (6,)),
    {},
    {"k": [1, 2], "nested": {"t": (True, False), "b": b"x"}},
])
def test_pack_obj_roundtrip(obj):
    out = wire.unpack_obj(wire.pack_obj(obj))
    assert out == obj
    assert type(out) is type(obj)


def test_pack_obj_nan_roundtrip():
    out = wire.unpack_obj(wire.pack_obj(float("nan")))
    assert isinstance(out, float) and out != out


@pytest.mark.parametrize("dtype", ["float32", "bfloat16", "int8", "uint32",
                                   "float64", "int64"])
def test_ndarray_roundtrip_byte_exact(dtype):
    try:
        dt = np.dtype(dtype)
    except TypeError:
        import jax.numpy as jnp  # bfloat16 registers via ml_dtypes

        dt = jnp.bfloat16
    rng = np.random.default_rng(0)
    arr = rng.integers(0, 100, size=(3, 4, 5)).astype(dt)
    out = wire.unpack_obj(wire.pack_obj(arr))
    assert isinstance(out, np.ndarray)
    assert out.dtype == arr.dtype
    assert out.shape == arr.shape
    assert out.tobytes() == arr.tobytes()


def test_ndarray_zero_dim_and_empty():
    scalar = np.float32(7.5)  # np.generic packs as its python item
    assert wire.unpack_obj(wire.pack_obj(scalar)) == 7.5
    empty = np.zeros((0, 4), np.int32)
    out = wire.unpack_obj(wire.pack_obj(empty))
    assert out.shape == (0, 4) and out.dtype == np.int32


def test_ndarray_noncontiguous_packs_c_order():
    arr = np.arange(24, dtype=np.int32).reshape(4, 6)[:, ::2]
    out = wire.unpack_obj(wire.pack_obj(arr))
    assert np.array_equal(out, arr)


def test_pack_obj_rejects_unserializable():
    with pytest.raises(TypeError):
        wire.pack_obj(object())
    with pytest.raises(TypeError):
        wire.pack_obj({1, 2, 3})


def test_unpack_obj_unknown_tag():
    with pytest.raises(wire.WireError, match="unknown tag"):
        wire.unpack_obj(b"Z")


def test_unpack_obj_trailing_bytes():
    data = wire.pack_obj(42) + b"junk"
    with pytest.raises(wire.WireError, match="trailing"):
        wire.unpack_obj(data)


def test_unpack_obj_truncated_payload():
    data = wire.pack_obj("hello world")
    with pytest.raises(wire.WireError):
        wire.unpack_obj(data[:4])


# -- frame I/O ---------------------------------------------------------------

def test_frame_roundtrip():
    a, b = _pair()
    try:
        payload = wire.pack_obj({"x": [1, 2, 3], "arr": np.arange(8)})
        wire.send_frame(a, wire.FT_STEP, payload)
        ftype, got = wire.recv_frame(b)
        assert ftype == wire.FT_STEP
        assert got == payload
    finally:
        a.close()
        b.close()


def test_frame_empty_payload():
    a, b = _pair()
    try:
        wire.send_frame(a, wire.FT_HEALTH)
        ftype, got = wire.recv_frame(b)
        assert ftype == wire.FT_HEALTH and got == b""
    finally:
        a.close()
        b.close()


def test_recv_bad_magic():
    a, b = _pair()
    try:
        a.sendall(b"HTTP" + wire._HEADER.pack(
            wire.MAGIC, wire.WIRE_VERSION, wire.FT_OK, 0)[4:])
        with pytest.raises(wire.WireBadMagic):
            wire.recv_frame(b)
    finally:
        a.close()
        b.close()


def test_recv_version_mismatch():
    a, b = _pair()
    try:
        a.sendall(wire._HEADER.pack(wire.MAGIC, wire.WIRE_VERSION + 1,
                                    wire.FT_OK, 0))
        with pytest.raises(wire.WireVersionMismatch):
            wire.recv_frame(b)
    finally:
        a.close()
        b.close()


def test_recv_frame_too_large():
    a, b = _pair()
    try:
        a.sendall(wire._HEADER.pack(wire.MAGIC, wire.WIRE_VERSION,
                                    wire.FT_OK, 1 << 30))
        with pytest.raises(wire.WireFrameTooLarge):
            wire.recv_frame(b, max_frame_bytes=1024)
    finally:
        a.close()
        b.close()


def test_recv_digest_mismatch():
    a, b = _pair()
    try:
        payload = wire.pack_obj({"adopt": "me"})
        digest = hashlib.sha256(payload).digest()[:wire._DIGEST_BYTES]
        corrupted = bytearray(payload)
        corrupted[0] ^= 0xFF  # bit-flip after the digest was computed
        a.sendall(wire._HEADER.pack(wire.MAGIC, wire.WIRE_VERSION,
                                    wire.FT_ADOPT, len(payload))
                  + bytes(corrupted) + digest)
        with pytest.raises(wire.WireDigestMismatch):
            wire.recv_frame(b)
    finally:
        a.close()
        b.close()


def test_recv_closed_at_boundary():
    a, b = _pair()
    a.close()
    try:
        with pytest.raises(wire.WireClosed):
            wire.recv_frame(b)
    finally:
        b.close()


def test_recv_peer_death_mid_frame():
    a, b = _pair()
    try:
        payload = wire.pack_obj([1] * 100)
        frame = (wire._HEADER.pack(wire.MAGIC, wire.WIRE_VERSION,
                                   wire.FT_STEP, len(payload))
                 + payload)
        a.sendall(frame[:len(frame) // 2])  # half a frame, then die
        a.close()
        with pytest.raises(wire.WireTruncated):
            wire.recv_frame(b)
    finally:
        b.close()


def test_recv_truncated_header():
    a, b = _pair()
    try:
        a.sendall(b"DLT")  # less than one header
        a.close()
        with pytest.raises(wire.WireTruncated):
            wire.recv_frame(b)
    finally:
        b.close()


def test_send_frame_on_dead_socket():
    a, b = _pair()
    b.close()
    try:
        with pytest.raises(wire.WireTruncated):
            # Loopback buffering may swallow one send; a big payload and a
            # second attempt guarantee the broken pipe surfaces.
            payload = b"x" * (1 << 22)
            wire.send_frame(a, wire.FT_STEP, payload)
            wire.send_frame(a, wire.FT_STEP, payload)
    finally:
        a.close()


def test_request_reply_ok_and_remote_error():
    a, b = _pair()

    def peer():
        ftype, payload = wire.recv_frame(b)
        assert ftype == wire.FT_HEALTH
        wire.send_frame(b, wire.FT_OK, wire.pack_obj({"ok": True}))
        wire.recv_frame(b)
        wire.send_frame(b, wire.FT_ERROR,
                        wire.pack_obj({"error": "handler exploded"}))

    t = threading.Thread(target=peer, daemon=True)
    t.start()
    try:
        assert wire.request_reply(a, wire.FT_HEALTH, None) == {"ok": True}
        with pytest.raises(wire.WireRemoteError, match="handler exploded"):
            wire.request_reply(a, wire.FT_STEP, {"cancels": []})
    finally:
        t.join(timeout=5)
        a.close()
        b.close()


def test_wire_metrics_count_frames():
    def frames_sum():
        return sum(c.value for _, _, c in wire.frames_total.samples())

    base_frames = frames_sum()
    base_health = wire.frames_total.labels(kind="health").value
    base_bytes = wire.wire_bytes_total.value
    a, b = _pair()
    try:
        wire.send_frame(a, wire.FT_HEALTH, b"abc")
        wire.recv_frame(b)
    finally:
        a.close()
        b.close()
    assert frames_sum() == base_frames + 1
    assert wire.frames_total.labels(kind="health").value == base_health + 1
    assert (wire.wire_bytes_total.value - base_bytes
            == wire._HEADER.size + 3 + wire._DIGEST_BYTES)


# -- request descriptor ------------------------------------------------------

def _mk_request():
    req = Request(
        request_id="req-42",
        prompt_token_ids=[5, 6, 7],
        params=SamplingParams(max_tokens=16, temperature=0.5, top_k=10,
                              top_p=0.9, seed=123, logprobs=True,
                              stop_token_ids=(99,)),
        arrival_time=time.monotonic(),
    )
    req.output_token_ids = [8, 9]
    req.output_logprobs = [-0.5, -1.25]
    req.num_preemptions = 1
    req.num_retries = 2
    req.num_migrations = 3
    req.tenant = "acme"
    req.adapter = "lora-a"
    return req


def test_request_descriptor_roundtrip():
    req = _mk_request()
    out = wire.request_from_wire(wire.request_to_wire(req))
    assert out.request_id == req.request_id
    assert out.prompt_token_ids == req.prompt_token_ids
    assert out.output_token_ids == req.output_token_ids
    assert out.output_logprobs == req.output_logprobs
    for f in wire._PARAM_FIELDS:
        assert getattr(out.params, f) == getattr(req.params, f), f
    assert out.params.stop_token_ids == (99,)
    assert out.num_preemptions == 1
    assert out.num_retries == 2
    assert out.num_migrations == 3
    assert out.tenant == "acme"
    assert out.adapter == "lora-a"
    assert not out.done


def test_request_descriptor_survives_wire_serialization():
    d = wire.request_to_wire(_mk_request())
    out = wire.request_from_wire(wire.unpack_obj(wire.pack_obj(d)))
    assert out.output_token_ids == [8, 9]
    assert out.params.seed == 123


# -- handoff envelope --------------------------------------------------------

def _mk_snap():
    return {
        "request": _mk_request(),
        "payloads": [{"l00000": {"k": np.ones((2, 3), np.float32),
                                 "v": np.zeros((2, 3), np.float32)}}],
        "seq_len": 5,
        "last_token": 9,
        "slot_key": np.array([11, 22], np.uint32),
        "gen_count": 2,
    }


def test_handoff_roundtrip_byte_exact():
    snap = _mk_snap()
    out = wire.unpack_handoff(wire.pack_handoff(snap))
    assert out["seq_len"] == 5 and out["last_token"] == 9
    assert out["gen_count"] == 2
    assert out["slot_key"].tobytes() == snap["slot_key"].tobytes()
    kv = out["payloads"][0]["l00000"]
    assert kv["k"].tobytes() == snap["payloads"][0]["l00000"]["k"].tobytes()
    assert out["request"].request_id == "req-42"
    assert out["request"].output_token_ids == [8, 9]


def test_handoff_version_mismatch():
    env = wire.pack_obj({"v": wire.HANDOFF_VERSION + 1, "kind": "kv-handoff",
                         "snap": {}})
    with pytest.raises(wire.WireVersionMismatch):
        wire.unpack_handoff(env)


def test_handoff_wrong_kind_or_shape():
    with pytest.raises(wire.WireError):
        wire.unpack_handoff(wire.pack_obj({"v": 1, "kind": "weights"}))
    with pytest.raises(wire.WireError):
        wire.unpack_handoff(wire.pack_obj([1, 2, 3]))


# -- shared helpers ----------------------------------------------------------

def test_ephemeral_port_is_bindable():
    port = wire.ephemeral_port()
    assert 1024 <= port <= 65535
    with socket.socket() as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", port))


def test_connect_with_retry_times_out_cleanly():
    port = wire.ephemeral_port()
    t0 = time.monotonic()
    with pytest.raises(wire.WireError, match="could not connect"):
        wire.connect_with_retry("127.0.0.1", port, timeout_s=0.3,
                                interval_s=0.05)
    assert time.monotonic() - t0 < 5.0


def test_connect_with_retry_waits_for_listener():
    port = wire.ephemeral_port()
    accepted = []

    def late_listener():
        time.sleep(0.2)
        srv = socket.socket()
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", port))
        srv.listen(1)
        conn, _ = srv.accept()
        accepted.append(True)
        conn.close()
        srv.close()

    t = threading.Thread(target=late_listener, daemon=True)
    t.start()
    sock = wire.connect_with_retry("127.0.0.1", port, timeout_s=5.0)
    sock.close()
    t.join(timeout=5)
    assert accepted == [True]
