"""Analysis module: reference compare_training.py derivation parity."""

import numpy as np
import pandas as pd
import pytest

from dlti_tpu.analysis import create_plots, load_and_calculate
from dlti_tpu.utils.metrics import MetricsRecord, save_training_metrics


def _write_csv(path, rows):
    for r in rows:
        save_training_metrics(r, csv_path=str(path))


def _record(exp, n, stage, hours, mem=10.0):
    return MetricsRecord(
        experiment=exp, num_gpus=n, zero_stage=stage,
        strategy="baseline" if stage == 0 else f"zero{stage}",
        training_time_hours=hours, samples_per_second=1.0 / hours,
        peak_memory_gb=mem, final_loss=0.7,
    )


def test_speedup_and_efficiency_derivations(tmp_path):
    """speedup = baseline_time/time; efficiency = speedup/chips*100
    (compare_training.py:46-47)."""
    csv = tmp_path / "m.csv"
    _write_csv(csv, [
        _record("baseline", 1, 0, 10.0),
        _record("zero2_4dev", 4, 2, 3.0),
    ])
    df = load_and_calculate(str(csv))
    row = df[df["experiment"] == "zero2_4dev"].iloc[0]
    np.testing.assert_allclose(row["speedup"], 10.0 / 3.0)
    np.testing.assert_allclose(row["efficiency_percent"], 10.0 / 3.0 / 4 * 100)
    base = df[df["experiment"] == "baseline"].iloc[0]
    np.testing.assert_allclose(base["speedup"], 1.0)


def test_missing_baseline_falls_back_to_first_row(tmp_path):
    """Reference fallback (compare_training.py:37-42)."""
    csv = tmp_path / "m.csv"
    _write_csv(csv, [
        _record("zero1_2dev", 2, 1, 6.0),
        _record("zero3_4dev", 4, 3, 3.0),
    ])
    df = load_and_calculate(str(csv))
    np.testing.assert_allclose(
        df[df["experiment"] == "zero3_4dev"].iloc[0]["speedup"], 2.0
    )


def test_empty_csv_raises(tmp_path):
    csv = tmp_path / "m.csv"
    pd.DataFrame(columns=["experiment", "num_gpus", "training_time_hours"]).to_csv(
        csv, index=False
    )
    with pytest.raises(ValueError, match="no rows"):
        load_and_calculate(str(csv))


def test_create_plots_writes_png(tmp_path):
    csv = tmp_path / "m.csv"
    _write_csv(csv, [
        _record("baseline", 1, 0, 10.0),
        _record("zero1_2dev", 2, 1, 6.0),
        _record("zero3_4dev", 4, 3, 3.0),
    ])
    df = load_and_calculate(str(csv))
    out = create_plots(df, str(tmp_path / "plots" / "cmp.png"))
    import os

    assert os.path.isfile(out) and os.path.getsize(out) > 10_000
