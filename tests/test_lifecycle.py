"""Serving fleet self-healing (dlti_tpu.serving.lifecycle + replicas).

Layers, mirroring the subsystem's own structure:

* **State-machine units** (fake clock, no engines): quarantine → probe
  pass/fail → reinstate, exponential probation backoff, the flap
  breaker's permanent eviction, window pruning, and the legacy
  healing-off death that must NOT book a flap.
* **Watchdog rule**: ``replica_flap`` fires on growth of the flaps
  counter in the ring, once per eviction episode, and stays silent with
  ``replica_flap_limit=0``.
* **Gateway**: drain-window-derived Retry-After on 503 refusals.
* **End-to-end heal drill**: a chaos-killed replica is quarantined,
  rebuilt, canaried against the pinned digest, reinstated, and serves
  round-2 traffic — zero client errors throughout.
* **Byte-identity**: a request live-migrated off a preempted replica
  mid-decode finishes with EXACTLY the tokens of an unmigrated run —
  greedy and seeded-sampled, bf16 and int8 KV — because the paged-KV
  handoff carries generated-so-far tokens and the slot's rng stream.
* **Rolling reload**: a multi-replica fleet hot-swaps weights one
  replica at a time under in-flight load with zero errors; same-weight
  reloads are additionally byte-identical end to end.
* **Attribution pin**: migrated/failed-over requests book the stall in
  ``stall_s``/``request_breakdown()`` as ``preempt``/``failover``, not
  as inflated decode.

The slow drill (3-replica fleet under loadgen + rolling reload + chaos
preemption) lives at the bottom under ``@pytest.mark.slow``.
"""

import threading
import time
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlti_tpu.config import (
    Config, GatewayConfig, MODEL_PRESETS, ReplicaLifecycleConfig,
    WatchdogConfig,
)
from dlti_tpu.models import LlamaForCausalLM
from dlti_tpu.serving import (
    AdmissionError, EngineConfig, InferenceEngine, ReplicatedEngine,
    SamplingParams,
)
from dlti_tpu.serving.gateway import AdmissionGateway
from dlti_tpu.serving.lifecycle import (
    ReplicaLifecycle, STATES, canary_digest,
)
from dlti_tpu.telemetry import (
    AnomalyWatchdog, RequestTelemetry, SpanTracer, TimeSeriesSampler,
)
from dlti_tpu.telemetry.ledger import request_breakdown

CFG = MODEL_PRESETS["llama_tiny"]

PROMPTS = [[1, 2, 3, 4, 5], [6, 7, 8], [9, 10, 11, 12], [13, 14]]


@pytest.fixture(scope="module")
def tiny_params():
    model = LlamaForCausalLM(CFG, None)
    return model.init(jax.random.PRNGKey(0),
                      jnp.zeros((1, 8), jnp.int32))["params"]


def _ec(**over):
    base = dict(max_seqs=4, block_size=8, num_blocks=64, max_model_len=128,
                cache_dtype="float32", eos_token_id=-1)
    base.update(over)
    return EngineConfig(**base)


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ----------------------------------------------------------------------
# State-machine units (fake clock, no engines)
# ----------------------------------------------------------------------

def test_quarantine_probe_reinstate_cycle():
    clock = _Clock()
    lc = ReplicaLifecycle(
        ReplicaLifecycleConfig(enabled=True, probation_initial_s=2.0),
        2, clock=clock)
    assert lc.state(0) == "live" and lc.state(1) == "live"
    assert lc.on_fault(1) == "quarantined"
    assert lc.due_probes() == []  # probation not yet elapsed
    clock.advance(2.0)
    assert lc.due_probes() == [1]
    lc.begin_probe(1)
    assert lc.state(1) == "probing"
    assert lc.due_probes() == []  # probing replicas are not re-offered
    assert lc.on_probe_result(1, True) == "live"
    assert lc.counters["quarantines"] == 1
    assert lc.counters["reinstates"] == 1
    assert lc.counts()["live"] == 2


def test_probation_backs_off_exponentially_and_resets_on_pass():
    clock = _Clock()
    lc = ReplicaLifecycle(
        ReplicaLifecycleConfig(probation_initial_s=1.0,
                               probation_backoff=2.0, probation_max_s=5.0,
                               flap_window_s=1e9, flap_max_cycles=100),
        1, clock=clock)
    lc.on_fault(0)
    clock.advance(1.0)
    assert lc.due_probes() == [0]
    for expect_wait in (2.0, 4.0, 5.0):  # 1 * 2**n, capped at max_s
        lc.begin_probe(0)
        lc.on_probe_result(0, False)
        clock.advance(expect_wait - 0.1)
        assert lc.due_probes() == [], expect_wait
        clock.advance(0.1)
        assert lc.due_probes() == [0], expect_wait
    lc.begin_probe(0)
    assert lc.on_probe_result(0, True) == "live"
    # A pass resets the backoff: next fault waits only the initial again.
    lc.on_fault(0)
    clock.advance(1.0)
    assert lc.due_probes() == [0]


def test_flap_breaker_evicts_after_repeated_cycles():
    clock = _Clock()
    lc = ReplicaLifecycle(
        ReplicaLifecycleConfig(probation_initial_s=0.0,
                               flap_window_s=100.0, flap_max_cycles=2),
        2, clock=clock)
    for _ in range(2):
        assert lc.on_fault(0) == "quarantined"
        lc.begin_probe(0)
        lc.on_probe_result(0, True)
        clock.advance(1.0)
    assert lc.on_fault(0) == "evicted"  # 3rd cycle inside the window
    assert lc.counters["flaps"] == 1
    assert lc.on_fault(0) == "evicted"  # terminal: no double accounting
    assert lc.counters["flaps"] == 1
    assert lc.counts()["evicted"] == 1
    assert lc.state(1) == "live"  # neighbor untouched


def test_flap_window_prunes_old_cycles():
    clock = _Clock()
    lc = ReplicaLifecycle(
        ReplicaLifecycleConfig(probation_initial_s=0.0,
                               flap_window_s=10.0, flap_max_cycles=2),
        1, clock=clock)
    for _ in range(5):  # each fault leaves the window before the next
        assert lc.on_fault(0) == "quarantined"
        lc.begin_probe(0)
        lc.on_probe_result(0, True)
        clock.advance(11.0)
    assert lc.counters["flaps"] == 0


def test_mark_dead_books_no_flap_but_evict_does():
    lc = ReplicaLifecycle(ReplicaLifecycleConfig(), 2, clock=_Clock())
    lc.mark_dead(0)  # legacy healing-off death
    assert lc.state(0) == "evicted"
    assert lc.counters["flaps"] == 0
    lc.evict(1)  # deliberate permanent removal
    assert lc.counters["flaps"] == 1


def test_canary_digest_is_stable_and_order_length_sign_sensitive():
    d = canary_digest([1, 2, 3])
    assert d == canary_digest([1, 2, 3])
    assert d != canary_digest([1, 2, 4])
    assert d != canary_digest([3, 2, 1])
    assert d != canary_digest([1, 2])
    assert canary_digest([-1]) != canary_digest([1])


def test_scalars_snapshot_keys():
    lc = ReplicaLifecycle(ReplicaLifecycleConfig(enabled=True), 3,
                          clock=_Clock())
    lc.on_fault(1)
    lc.mark_dead(2)
    s = lc.scalars()
    assert s["replica_lifecycle_quarantines_total"] == 1
    assert s["replica_lifecycle_live"] == 1
    assert s["replica_lifecycle_quarantined"] == 1
    assert s["replica_lifecycle_evicted"] == 1
    for state in STATES:
        assert f"replica_lifecycle_{state}" in s


def test_lifecycle_config_roundtrips_through_json():
    cfg = Config.from_dict({"serving": {"lifecycle": {
        "enabled": True, "flap_max_cycles": 5, "probation_initial_s": 7.5}}})
    assert cfg.serving.lifecycle.enabled
    assert cfg.serving.lifecycle.flap_max_cycles == 5
    assert cfg.serving.lifecycle.probation_initial_s == 7.5
    again = Config.from_dict(cfg.to_dict())
    assert again.serving.lifecycle == cfg.serving.lifecycle


# ----------------------------------------------------------------------
# Watchdog replica_flap rule
# ----------------------------------------------------------------------

def _watchdog(sampler, **over):
    kw = dict(enabled=True, interval_s=0.05, hung_step_min_s=30.0)
    kw.update(over)
    return AnomalyWatchdog(WatchdogConfig(**kw), sampler,
                           tracer=SpanTracer(enabled=False),
                           clock=time.monotonic)


def test_replica_flap_rule_fires_on_eviction_growth():
    s = TimeSeriesSampler(capacity=16)
    state = {"flaps": 0.0}
    s.add_source(lambda: {"dlti_replica_lifecycle_flaps_total":
                          state["flaps"]})
    wd = _watchdog(s, replica_flap_limit=1)
    s.sample_now()
    assert wd.check_now() == []  # watermark established, no alert
    state["flaps"] = 1.0
    s.sample_now()
    fired = wd.check_now()
    assert [a["rule"] for a in fired] == ["replica_flap"]
    assert "evicted" in fired[0]["message"]
    s.sample_now()
    assert wd.check_now() == []  # flat since last check: re-armed quietly
    state["flaps"] = 2.0
    s.sample_now()
    assert [a["rule"] for a in wd.check_now()] == ["replica_flap"]


def test_replica_flap_rule_disabled_by_zero_limit():
    s = TimeSeriesSampler(capacity=16)
    state = {"flaps": 0.0}
    s.add_source(lambda: {"dlti_replica_lifecycle_flaps_total":
                          state["flaps"]})
    wd = _watchdog(s, replica_flap_limit=0)
    s.sample_now()
    wd.check_now()
    state["flaps"] = 5.0
    s.sample_now()
    assert wd.check_now() == []


# ----------------------------------------------------------------------
# Gateway: drain 503 carries a drain-window-derived Retry-After
# ----------------------------------------------------------------------

class _FakeAsyncEngine:
    def __init__(self, room: int = 0):
        self.engine = types.SimpleNamespace(
            cfg=types.SimpleNamespace(max_seqs=room),
            num_active=0, waiting=[], has_work=False,
            telemetry=RequestTelemetry(), stats={}, num_free_blocks=0)
        self.submitted = []


def test_drain_503_retry_after_derived_from_drain_window():
    gw = AdmissionGateway(_FakeAsyncEngine(),
                          GatewayConfig(enabled=True, drain_grace_s=30.0,
                                        retry_after_s=1.0), None)
    try:
        gw.drain()
        with pytest.raises(AdmissionError) as ei:
            gw.submit([1], SamplingParams(), "r0")
        assert ei.value.status == 503
        # Remaining grace window, not the static 1 s backoff: a client
        # that honors it lands on the replacement process.
        assert 25.0 < ei.value.retry_after <= 30.0
    finally:
        gw.shutdown()


# ----------------------------------------------------------------------
# End-to-end: chaos-killed replica heals and serves again
# ----------------------------------------------------------------------

def _run_fleet(rep, reqs, max_steps=600):
    for _ in range(max_steps):
        if not rep.has_work:
            break
        rep.step()
    assert not rep.has_work, "fleet failed to drain its work"
    return reqs


def test_chaos_killed_replica_is_reinstated_and_serves_again(tiny_params):
    rep = ReplicatedEngine(
        CFG, tiny_params, _ec(), replicas=2, tensor=1,
        devices=jax.devices()[:2], fault_inject_step="1:3",
        lifecycle_cfg=ReplicaLifecycleConfig(enabled=True,
                                             probation_initial_s=0.0))
    sp = SamplingParams(temperature=0.0, max_tokens=8)
    reqs = _run_fleet(rep, [rep.submit(p, sp) for p in PROMPTS])
    # Zero client errors: every round-1 request finished normally even
    # though replica 1 died mid-run (failover resubmit covered it).
    assert all(r.finish_reason == "length" for r in reqs), \
        [(r.request_id, r.finish_reason) for r in reqs]
    # Failed-over requests book the wait as "failover", not decode.
    failed_over = [r for r in reqs if r.num_retries > 0]
    assert failed_over
    for r in failed_over:
        assert r.stall_s.get("failover", 0.0) > 0.0
        assert request_breakdown(r)["phases"].get("failover", 0.0) > 0.0
    # Heal: probation 0 → the probe runs on subsequent ticks; the rebuilt
    # replica must match the pinned canary digest and come back live.
    for _ in range(10):
        if rep.lifecycle.state(1) == "live":
            break
        rep.step()
    assert rep.lifecycle.state(1) == "live"
    assert rep.lifecycle.counters["quarantines"] == 1
    assert rep.lifecycle.counters["reinstates"] == 1
    assert not rep._dead
    # Round 2: the healed replica takes traffic again.
    before = rep.engines[1].stats["requests"]
    reqs2 = _run_fleet(rep, [rep.submit(p, sp) for p in PROMPTS])
    assert all(r.finish_reason == "length" for r in reqs2)
    assert rep.engines[1].stats["requests"] > before
    assert rep.lifecycle_counts() == {
        "live": 2, "quarantined": 0, "draining": 0, "dead": 0}


# ----------------------------------------------------------------------
# Byte-identity: live migration on preemption drain
# ----------------------------------------------------------------------

@pytest.mark.parametrize("kv_dtype", ["bfloat16", "int8"])
@pytest.mark.parametrize("sp", [
    SamplingParams(max_tokens=8, temperature=0.0),           # greedy
    SamplingParams(max_tokens=8, temperature=0.9, seed=7),   # sampled
], ids=["greedy", "seeded-sampled"])
def test_migrated_outputs_byte_identical(tiny_params, kv_dtype, sp):
    """A decode live-migrated off a preempted replica mid-flight must
    finish with exactly the unmigrated run's tokens: the KV handoff
    carries generated-so-far tokens and the slot's rng stream, so not
    even a seeded sampling draw diverges."""
    ec = _ec(cache_dtype=kv_dtype)
    base = ReplicatedEngine(CFG, tiny_params, ec, replicas=2, tensor=1,
                            devices=jax.devices()[:2])
    expect = [r.output_token_ids for r in base.generate(PROMPTS, sp)]

    rep = ReplicatedEngine(CFG, tiny_params, ec, replicas=2, tensor=1,
                           devices=jax.devices()[:2],
                           fault_inject_step="1:4:preempt")
    reqs = _run_fleet(rep, [rep.submit(p, sp) for p in PROMPTS])
    assert [r.output_token_ids for r in reqs] == expect
    assert all(r.finish_reason == "length" for r in reqs)
    # The preemption actually migrated mid-decode work (not a vacuous
    # pass where the replica was idle at the chaos step).
    migrated = [r for r in reqs if r.num_migrations > 0]
    assert migrated
    assert rep.lifecycle.counters["migrations"] >= len(migrated)
    # Attribution pin: the handoff window books as "preempt" stall.
    for r in migrated:
        assert r.stall_s.get("preempt", 0.0) > 0.0
        assert request_breakdown(r)["phases"].get("preempt", 0.0) > 0.0


# ----------------------------------------------------------------------
# Rolling weight reload under live load
# ----------------------------------------------------------------------

def _drain_and_roll(rep, max_steps=2000):
    for _ in range(max_steps):
        if not rep.has_work and rep._reload is None:
            break
        rep.step()
    assert rep._reload is None, "rolling reload never completed"


def test_rolling_reload_same_weights_is_byte_identical(tiny_params):
    """Reloading the SAME weights mid-flight (re-verify + hot-swap) is a
    pure migration exercise: zero errors AND byte-identical outputs for
    every request, migrated or not."""
    sp = SamplingParams(temperature=0.0, max_tokens=16)
    prompts = [[i + 1, i + 2, i + 3] for i in range(6)]
    base = ReplicatedEngine(CFG, tiny_params, _ec(), replicas=3, tensor=1,
                            devices=jax.devices()[:3])
    expect = [r.output_token_ids for r in base.generate(prompts, sp)]

    rep = ReplicatedEngine(
        CFG, tiny_params, _ec(), replicas=3, tensor=1,
        devices=jax.devices()[:3],
        lifecycle_cfg=ReplicaLifecycleConfig(enabled=True,
                                             probation_initial_s=0.0))
    reqs = [rep.submit(p, sp) for p in prompts]
    for _ in range(3):  # get decodes in flight before the roll starts
        rep.step()
    host = jax.device_get(tiny_params)
    assert rep.request_reload(lambda: host)
    assert not rep.request_reload(lambda: host)  # roll already in progress
    _drain_and_roll(rep)
    assert all(r.finish_reason == "length" for r in reqs)
    assert [r.output_token_ids for r in reqs] == expect
    assert rep.lifecycle.counters["reinstates"] == 3
    assert rep.lifecycle.counts()["live"] == 3
    assert not rep._dead


def test_rolling_reload_swaps_new_weights_with_zero_errors(tiny_params):
    rep = ReplicatedEngine(
        CFG, tiny_params, _ec(), replicas=3, tensor=1,
        devices=jax.devices()[:3],
        lifecycle_cfg=ReplicaLifecycleConfig(enabled=True,
                                             probation_initial_s=0.0))
    sp = SamplingParams(temperature=0.0, max_tokens=12)
    reqs = [rep.submit([i + 1, i + 2, i + 3], sp) for i in range(6)]
    for _ in range(3):
        rep.step()
    new_host = jax.tree_util.tree_map(
        lambda x: np.asarray(x) * np.float32(1.01),
        jax.device_get(tiny_params))
    old_digest = rep._canary_digest
    assert rep.request_reload(lambda: new_host)
    _drain_and_roll(rep)
    # Zero client errors across the whole roll.
    assert all(r.finish_reason == "length" for r in reqs), \
        [(r.request_id, r.finish_reason) for r in reqs]
    # Every replica actually holds the new weights now.
    want = jax.tree_util.tree_leaves(new_host)[0]
    for e in rep.engines:
        got = np.asarray(jax.tree_util.tree_leaves(e.params)[0])
        np.testing.assert_allclose(got, want, rtol=1e-6)
    # The canary digest was re-pinned against the new weights.
    assert rep._canary_digest is not None
    assert rep._canary_digest != old_digest
    # Fleet fully live; post-reload traffic serves normally.
    assert rep.lifecycle.counts()["live"] == 3
    out = rep.generate([[1, 2, 3]], sp)
    assert len(out[0].output_token_ids) == 12


# ----------------------------------------------------------------------
# Slow drill: 3-replica fleet under loadgen, rolling reload + chaos
# preemption, zero client errors, warm sessions stay warm
# ----------------------------------------------------------------------

@pytest.mark.slow
def test_fleet_drill_loadgen_reload_and_preempt(tiny_params, tmp_path):
    from dlti_tpu.benchmarks import LoadGenConfig, run_load_test
    from dlti_tpu.checkpoint.store import save_pytree
    from dlti_tpu.data.tokenizer import IdTokenizer
    from dlti_tpu.serving.server import ServerConfig, make_server

    rep = ReplicatedEngine(
        CFG, tiny_params, _ec(enable_prefix_caching=True, num_blocks=128),
        replicas=3, tensor=1, devices=jax.devices()[:3],
        fault_inject_step="2:30:preempt",
        lifecycle_cfg=ReplicaLifecycleConfig(enabled=True,
                                             probation_initial_s=0.0))
    export_dir = str(tmp_path / "weights")
    save_pytree(export_dir, jax.device_get(tiny_params))
    httpd, async_engine = make_server(
        rep, IdTokenizer(vocab_size=CFG.vocab_size),
        ServerConfig(host="127.0.0.1", port=0,
                     default_params=SamplingParams(max_tokens=8),
                     gateway=GatewayConfig(enabled=True)))
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        def _kick_reload():
            import http.client
            import json as _json

            time.sleep(1.0)  # let the load build up first
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
            conn.request("POST", "/v1/reload",
                         _json.dumps({"directory": export_dir}),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            body = resp.read()
            conn.close()
            assert resp.status == 200, body

        kicker = threading.Thread(target=_kick_reload, daemon=True)
        kicker.start()
        report = run_load_test(LoadGenConfig(
            host="127.0.0.1", port=port, sessions=4, turns=4,
            max_tokens=8, stream=True, timeout_s=300,
            concurrency=4, num_requests=16))
        kicker.join(timeout=60)
        # Zero client errors: sheds (backpressure) would be tolerable,
        # hard errors are not — and there should be none of either here.
        assert not report.errors, report.errors
        assert report.num_ok == report.num_requests, \
            (report.num_ok, report.num_requests, report.errors)
        # Warm sessions stayed warm: repeat turns kept completing.
        assert report.num_warm > 0
        # Let the roll (and any preempt heal) finish, then check the
        # fleet recovered fully: all three replicas live.
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if rep._reload is None and not rep.lifecycle_pending:
                break
            time.sleep(0.2)
        assert rep._reload is None
        assert rep.lifecycle.counters["reinstates"] >= 3
        assert rep.lifecycle_counts()["live"] == 3
        assert rep.lifecycle_counts()["dead"] == 0
        # The new-fields contract rode through loadgen end to end.
        assert report.migrations_total >= 0
        assert report.ttft_p999_s >= report.ttft_p99_s
    finally:
        httpd.shutdown()
        async_engine.shutdown()
        httpd.server_close()
