"""CI smoke for the host-overlap microbench (satellite of the
host-latency-hiding PR): the artifact generator must stay runnable and its
two headline claims must hold on a cold CPU run — prefetch stall strictly
below the no-prefetch stall, and zero decode-state uploads across a clean
steady-state decode window."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "benchmarks_dev", "host_overlap.py")


@pytest.mark.slow
def test_host_overlap_bench_smoke(tmp_path):
    out = tmp_path / "host_overlap.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run([sys.executable, BENCH, str(out)], env=env,
                          capture_output=True, text=True, timeout=600,
                          cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-1000:]
    report = json.loads(out.read_text())

    tr = report["train"]
    # Prefetch hides the synthetic gather delay: strictly less stall, and
    # the loss trajectory is untouched (bit-identical final loss).
    assert tr["prefetch_on"]["host_stall_s"] < tr["prefetch_off"]["host_stall_s"]
    assert tr["prefetch_on"]["final_loss"] == tr["prefetch_off"]["final_loss"]

    sv = report["serving"]["dirty_tracking"]
    # A clean steady-state decode step uploads nothing.
    assert sv["clean_window_uploads"] == 0
    assert sv["decode_state_clean_syncs"] > 0
    # Dirty tracking ships rows only on scheduling events — orders of
    # magnitude below one-full-state-per-step.
    assert sv["decode_state_uploads"] < sv["decode_steps"]
