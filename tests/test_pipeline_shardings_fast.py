"""Fast-tier pin of the pipeline placement rules (no jit, milliseconds).

The full PP-composition equivalence family is slow-tier
(tests/test_pipeline.py); this keeps the DEFAULT pre-commit gate
covering the r05 sharding rules — one spec-level assertion per axis —
so a placement regression cannot ship between full-suite runs.
"""

import numpy as np
import pytest

import jax

jax.config.update("jax_platforms", "cpu")

from jax.sharding import PartitionSpec as P

import dlti_tpu.parallel.sharding as sh_mod
from dlti_tpu.config import ParallelConfig, ZeROStage
from dlti_tpu.parallel.mesh import build_mesh
from dlti_tpu.parallel.pipeline import pipeline_param_shardings


def _pparams():
    return {
        "embed_tokens": np.zeros((64, 16), np.float32),
        "lm_head": np.zeros((16, 64), np.float32),
        "final_norm": {"scale": np.zeros((16,), np.float32)},
        "layers": {
            "attn": {"q_proj": {"kernel": np.zeros((2, 16, 16), np.float32)}},
            "mlp": {"w1": np.zeros((2, 4, 16, 32), np.float32)},
        },
    }


def test_pipe_tp_fsdp_expert_specs(monkeypatch):
    """One placement check per axis: pipe on the layer dim, tensor on the
    TP-rule dim, fsdp on the largest free dim, expert on the (shifted)
    expert dim, vocab rules on embed/head, norm replicated."""
    mesh = build_mesh(ParallelConfig(pipe=2, tensor=2, fsdp=2,
                                     zero_stage=ZeROStage.ZERO3))
    # Production floor: tiny leaves (norm scales) stay replicated even
    # though divisible — the all-gather latency isn't worth it.
    sh = pipeline_param_shardings(_pparams(), mesh)
    assert sh["final_norm"]["scale"].spec == P(None,)
    assert "fsdp" not in sh["layers"]["attn"]["q_proj"]["kernel"].spec

    # Floor lowered (test scale): every axis lands where the rule says.
    monkeypatch.setattr(sh_mod, "_MIN_FSDP_DIM", 8)
    sh = pipeline_param_shardings(_pparams(), mesh)
    assert sh["layers"]["attn"]["q_proj"]["kernel"].spec == \
        P("pipe", "fsdp", "tensor")
    assert sh["embed_tokens"].spec[0] == "tensor"   # vocab rows
    assert sh["lm_head"].spec[1] == "tensor"        # vocab cols


def test_pipe_expert_spec():
    mesh = build_mesh(ParallelConfig(pipe=2, expert=4))
    sh = pipeline_param_shardings(_pparams(), mesh)
    w1_spec = sh["layers"]["mlp"]["w1"].spec
    assert w1_spec[0] == "pipe" and w1_spec[1] == "expert", w1_spec


def test_trainer_pipe_legality_fast():
    """The legality list's r05 shape, without building any step: every
    mesh axis composes; param offload without LoRA and SP x loss_chunk
    stay rejected."""
    from dlti_tpu.config import (
        Config, LoRAConfig, ModelConfig, ParallelConfig, TrainConfig,
    )
    from dlti_tpu.training.trainer import _validate_pipeline_config

    cfg_model = ModelConfig(vocab_size=64, hidden_size=16,
                            intermediate_size=32, num_layers=2,
                            num_heads=2, num_kv_heads=2, max_seq_len=16,
                            remat=False)

    def cfg_with(par, lora=None, **train_kw):
        return Config(model=cfg_model,
                      lora=lora or LoRAConfig(r=2, alpha=4),
                      parallel=par, train=TrainConfig(**train_kw))

    # Every axis at once passes validation.
    _validate_pipeline_config(cfg_with(ParallelConfig(
        pipe=2, tensor=2, data=2, sequence=2, expert=2,
        fsdp=2, zero_stage=ZeROStage.ZERO3)))
    # Offload (both kinds, boundary-transfer mode) passes with LoRA...
    _validate_pipeline_config(cfg_with(ParallelConfig(
        pipe=2, data=2, offload_optimizer=True, offload_params=True)))
    # ...and rejections stay loud.
    with pytest.raises(ValueError, match="does not compose"):
        _validate_pipeline_config(cfg_with(
            ParallelConfig(pipe=2, data=2, offload_params=True),
            lora=LoRAConfig(enabled=False)))
    with pytest.raises(ValueError, match="does not compose"):
        _validate_pipeline_config(cfg_with(
            ParallelConfig(pipe=2, sequence=2), loss_chunk=8))
