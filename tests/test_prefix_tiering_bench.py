"""CI smoke for the prefix-tiering microbench (satellite of the tiered
prefix-cache PR), mirroring tests/test_host_overlap_bench.py: the artifact
generator must stay runnable and its headline claims must hold on a cold
CPU run — byte-identical outputs with tiering on vs off, prefill tokens
saved by tier restores under an HBM budget too small for the session set,
and the end-to-end serving run's warm-turn TTFT strictly below cold with
the affinity router keeping sessions sticky."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "benchmarks_dev", "prefix_tiering.py")


@pytest.mark.slow
def test_prefix_tiering_bench_smoke(tmp_path):
    out = tmp_path / "prefix_tiering.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)  # the bench sets its own 2-device flag
    proc = subprocess.run([sys.executable, BENCH, str(out)], env=env,
                          capture_output=True, text=True, timeout=600,
                          cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-1500:]
    report = json.loads(out.read_text())

    ab = report["engine_ab"]
    # Equivalence: tiering must never change a single sampled token.
    assert ab["outputs_equal"] is True
    # The headline: restores replaced re-prefill on the measured path,
    # under real eviction pressure (the pool forced demotions).
    assert ab["prefill_tokens_saved"] > 0
    assert ab["prefix_restored_tokens"] > 0
    assert ab["demotions"] > 0
    assert ab["tier_traffic"]["disk_hits"] + ab["tier_traffic"]["host_hits"] > 0

    sv = report["serving"]
    # End-to-end: every recurring-session request completed, warm turns
    # beat cold turns on TTFT, sessions stuck to their replica, and tier
    # restores happened on the served path too.
    assert sv["num_ok"] == sv["num_cold"] + sv["num_warm"]
    assert not sv["errors"]
    assert sv["warm_ttft_p50_s"] < sv["cold_ttft_p50_s"]
    assert sv["affinity"]["sticky"] > 0
    assert sv["prefix_restored_tokens"] > 0
    assert sv["cache_hit_rate"] > 0
