"""Elastic self-healing training: supervisor state machine + drill.

Tier-1 here is deliberately JAX-free on the worker side: the launcher
rendezvous contract (``worker_env``/``slurm_env`` defaulting), the
reshape math (``rescale_batch_schedule`` / ``fit_parallel_to_devices``),
and the :class:`~dlti_tpu.training.elastic.ElasticLauncher`
restart-budget/backoff/rejoin state machine driven by fake subprocess
workers that fail, hang, or drain on cue in well under a second each.

The slow tier runs the real drill the ISSUE's acceptance names: two gloo
``jax.distributed`` processes training llama_tiny through
``scripts/train.py``, a supervisor-side ``host-kill`` of worker 1
mid-epoch, reshape to world 1, verified resume, rejoin to world 2 at the
next checkpoint boundary, and a step-for-step loss match against an
uninterrupted run.
"""

import json
import os
import subprocess
import sys
import textwrap
import time

import pytest

from dlti_tpu.launcher import (
    DEFAULT_PORT, ENV_COORDINATOR, ENV_NUM_PROCESSES, ENV_PROCESS_ID,
    slurm_env, worker_env,
)
from dlti_tpu.training import elastic
from dlti_tpu.training.elastic import (
    ENV_ELASTIC_DIR, ENV_GENERATION, ENV_NUM_SLOTS, ElasticLauncher,
    HostKillSpec, latest_committed_step, rescale_batch_schedule,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _events(launcher):
    path = os.path.join(launcher.elastic_dir, "elastic_events.jsonl")
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


# ------------------------------------------------------- rendezvous env

def test_worker_env_contract_and_base_isolation():
    base = {"HOME": "/h"}
    env = worker_env("10.0.0.1:29400", 4, 2, base=base)
    assert env[ENV_COORDINATOR] == "10.0.0.1:29400"
    assert env[ENV_NUM_PROCESSES] == "4"
    assert env[ENV_PROCESS_ID] == "2"
    assert env["HOME"] == "/h"
    assert ENV_COORDINATOR not in base  # base dict is never mutated


def test_slurm_env_port_defaulting_and_id_fallbacks():
    # Default port comes from the launcher contract, not SLURM.
    env = slurm_env({"SLURM_NODELIST": "h[01-04]", "SLURM_NNODES": "4",
                     "SLURM_NODEID": "3"})
    assert env[ENV_COORDINATOR] == f"h01:{DEFAULT_PORT}"
    # NNODES/NODEID are the fallback when NTASKS/PROCID are absent.
    assert env[ENV_NUM_PROCESSES] == "4"
    assert env[ENV_PROCESS_ID] == "3"
    # Explicit NTASKS/PROCID win over the node-level vars.
    env = slurm_env({"SLURM_JOB_NODELIST": "a,b", "SLURM_NNODES": "2",
                     "SLURM_NTASKS": "8", "SLURM_NODEID": "1",
                     "SLURM_PROCID": "5"}, port=1234)
    assert env[ENV_COORDINATOR] == "a:1234"
    assert (env[ENV_NUM_PROCESSES], env[ENV_PROCESS_ID]) == ("8", "5")


# ------------------------------------------------------- reshape math

def test_rescale_batch_schedule_preserves_rows_per_step():
    for micro, accum, full, live in ((8, 2, 2, 1), (8, 16, 4, 2),
                                     (8, 2, 2, 2), (6, 4, 3, 1)):
        m, a = rescale_batch_schedule(micro, accum, full, live)
        assert m * a == micro * accum  # the global schedule invariant
        assert m == micro * live // full
    with pytest.raises(ValueError, match="integral"):
        rescale_batch_schedule(3, 2, 2, 1)
    with pytest.raises(ValueError, match="positive"):
        rescale_batch_schedule(8, 2, 0, 1)


def test_fit_parallel_to_devices():
    from dlti_tpu.config import ParallelConfig, ZeROStage
    from dlti_tpu.parallel.mesh import fit_parallel_to_devices

    z3 = ParallelConfig(zero_stage=ZeROStage.ZERO3, fsdp=8)
    assert fit_parallel_to_devices(z3, 8) is z3          # already fits
    assert fit_parallel_to_devices(z3, 4).fsdp == 4      # shrink fsdp
    dp = ParallelConfig(data=4, tensor=2)
    got = fit_parallel_to_devices(dp, 4)
    assert (got.data, got.tensor) == (2, 2)              # TP extent kept
    with pytest.raises(ValueError, match="model-parallel"):
        fit_parallel_to_devices(ParallelConfig(tensor=8), 4)
    with pytest.raises(ValueError, match="mixed"):
        fit_parallel_to_devices(ParallelConfig(data=2, fsdp=4), 4)


# ------------------------------------------------------- chaos spec

def test_host_kill_spec_is_supervisor_owned():
    from dlti_tpu.training.chaos import TrainFaultInjector

    spec = HostKillSpec.parse("3:host-kill")
    assert (spec.step, spec.rank) == (3, 1)
    assert HostKillSpec.parse("5:host-kill:0").rank == 0
    assert HostKillSpec.parse("4:kill") is None          # in-process mode
    assert HostKillSpec.parse("") is None
    # ...and the in-process injector ignores the supervisor-owned mode,
    # so DLTI_TRAIN_FAULT_INJECT can ride the launch env into workers.
    assert TrainFaultInjector.from_spec("3:host-kill") is None
    assert TrainFaultInjector.from_spec("3:host-kill:0") is None
    assert TrainFaultInjector.from_spec("3:kill") is not None


def test_latest_committed_step_requires_commit_marker(tmp_path):
    assert latest_committed_step(None) is None
    assert latest_committed_step(str(tmp_path / "nope")) is None
    (tmp_path / "3").mkdir()                 # no COMMIT: not committed
    assert latest_committed_step(str(tmp_path)) is None
    (tmp_path / "3" / "COMMIT").write_text("{}")
    (tmp_path / "7").mkdir()
    (tmp_path / "7" / "COMMIT").write_text("{}")
    (tmp_path / ".tmp-9-x").mkdir()          # staging dirs never count
    assert latest_committed_step(str(tmp_path)) == 7


# ------------------------------------------------- supervisor state machine
#
# Fake workers: tiny non-JAX python scripts that exercise exactly one
# behavior each; the supervisor under test is the real one.

def _script(tmp_path, name, body):
    p = tmp_path / name
    p.write_text(textwrap.dedent(body))
    return [sys.executable, str(p)]


def _launcher(cmd, n, tmp_path, **kw):
    sleeps = []
    kw.setdefault("poll_s", 0.05)
    kw.setdefault("term_grace_s", 2.0)
    kw.setdefault("elastic_dir", str(tmp_path / "elastic"))
    kw.setdefault("log_dir", str(tmp_path / "logs"))
    real_sleep = time.sleep

    def sleep(s):
        sleeps.append(s)
        real_sleep(min(s, 0.05))  # backoffs recorded, not waited out

    lau = ElasticLauncher(cmd, n, sleep=sleep, **kw)
    lau._test_sleeps = sleeps
    return lau


def test_clean_run_supervises_to_zero(tmp_path):
    cmd = _script(tmp_path, "ok.py", """\
        import os, sys
        assert os.environ["DLTI_GENERATION"] == "0"
        assert os.environ["DLTI_ELASTIC_DIR"]
        assert os.environ["DLTI_ELASTIC_NUM_SLOTS"] == "2"
        sys.exit(0)
    """)
    lau = _launcher(cmd, 2, tmp_path)
    assert lau.run() == 0
    assert lau.restarts == 0
    assert [e["event"] for e in _events(lau)][-2:] == [
        "done", "supervisor_exit"]


def test_failure_shrinks_world_and_charges_budget(tmp_path):
    # rank 1 dies in generation 0; the survivor relaunches as a 1-process
    # generation 1 and completes.
    cmd = _script(tmp_path, "flaky.py", """\
        import os, sys, time
        if (os.environ["DLTI_NUM_PROCESSES"] == "2"
                and os.environ["DLTI_PROCESS_ID"] == "1"):
            sys.exit(7)
        time.sleep(0.3)
        sys.exit(0)
    """)
    lau = _launcher(cmd, 2, tmp_path, restart_budget=2, backoff_s=0.5)
    assert lau.run() == 0
    assert lau.restarts == 1
    ev = _events(lau)
    kinds = [e["event"] for e in ev]
    assert "failure" in kinds and "backoff" in kinds
    fail = next(e for e in ev if e["event"] == "failure")
    assert (fail["slot"], fail["rc"]) == (1, 7)
    spawns = [e for e in ev if e["event"] == "spawn"]
    assert [s["world_size"] for s in spawns] == [2, 1]
    assert spawns[1]["world"] == [0]          # survivor renumbered to rank 0
    assert [e["seconds"] for e in ev if e["event"] == "backoff"] == [0.5]
    assert 0.5 in lau._test_sleeps            # backoff actually slept


def test_budget_exhaustion_gives_up_with_failure_rc(tmp_path):
    cmd = _script(tmp_path, "doomed.py", "import sys; sys.exit(5)\n")
    lau = _launcher(cmd, 2, tmp_path, restart_budget=2, backoff_s=1.0,
                    rejoin=False)
    assert lau.run() == 5
    assert lau.restarts == 2
    ev = _events(lau)
    assert ev[-1]["event"] == "give_up" and ev[-1]["rc"] == 5
    # Exponential backoff: 1.0 then 2.0 (the third failure exhausts the
    # budget before another backoff).
    assert [e["seconds"] for e in ev if e["event"] == "backoff"] == [1.0, 2.0]
    # rejoin=False: every relaunch is full-size.
    assert [e["world_size"] for e in ev if e["event"] == "spawn"] == [2, 2, 2]


def test_rejoin_at_next_checkpoint_boundary(tmp_path):
    # gen 0: rank 1 dies -> shrink to world 1. gen 1: the survivor loops
    # (SIGTERM-aware, exits 0 on drain). When a checkpoint commits, the
    # supervisor drains gen 1 and relaunches at full size; gen 2 exits
    # clean.
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    cmd = _script(tmp_path, "worker.py", f"""\
        import json, os, signal, sys, time
        gen = int(os.environ["DLTI_GENERATION"])
        if gen == 0 and os.environ["DLTI_PROCESS_ID"] == "1":
            sys.exit(3)
        if gen >= 2:
            sys.exit(0)
        stop = []
        signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
        t0 = time.time()
        while not stop and time.time() - t0 < 30:
            if time.time() - t0 > 0.4:
                # the shrunk generation 'reaches a save boundary'
                d = os.path.join({str(ckpt)!r}, "4")
                os.makedirs(d, exist_ok=True)
                open(os.path.join(d, "COMMIT"), "w").write("{{}}")
            time.sleep(0.05)
        sys.exit(0)
    """)
    lau = _launcher(cmd, 2, tmp_path, restart_budget=3, backoff_s=0.2,
                    ckpt_dir=str(ckpt))
    assert lau.run() == 0
    ev = _events(lau)
    kinds = [e["event"] for e in ev]
    assert "rejoin_drain" in kinds and "rejoin" in kinds
    spawns = [e["world_size"] for e in ev if e["event"] == "spawn"]
    assert spawns == [2, 1, 2]                # shrink, then full-size rejoin
    rejoin = next(e for e in ev if e["event"] == "rejoin")
    assert rejoin["world"] == [0, 1]
    # The rejoin drain was triggered by the committed boundary.
    drain = next(e for e in ev if e["event"] == "rejoin_drain")
    assert drain["checkpoint_step"] == 4


def test_host_kill_chaos_fires_once_on_observed_step(tmp_path):
    # Workers write heartbeat files like the trainer does; the supervisor
    # SIGKILLs rank 1 once step 3 is observed, then recovers to world 1.
    cmd = _script(tmp_path, "beater.py", """\
        import json, os, sys, time
        d = os.environ["DLTI_ELASTIC_DIR"]
        gen = os.environ["DLTI_GENERATION"]
        rank = os.environ["DLTI_PROCESS_ID"]
        if os.environ["DLTI_NUM_PROCESSES"] == "1":
            sys.exit(0)   # recovered generation completes immediately
        for step in range(1, 100):
            path = os.path.join(d, f"hb_g{gen}_r{rank}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"step": step, "wall": time.time()}, f)
            os.replace(tmp, path)
            time.sleep(0.05)
        sys.exit(0)
    """)
    lau = _launcher(cmd, 2, tmp_path, restart_budget=1, backoff_s=0.2,
                    fault_spec="3:host-kill")
    assert lau.run() == 0
    ev = _events(lau)
    kills = [e for e in ev if e["event"] == "host_kill"]
    assert len(kills) == 1 and kills[0]["rank"] == 1
    assert kills[0]["step"] >= 3
    assert lau.fault.fired
    fail = next(e for e in ev if e["event"] == "failure")
    assert fail["slot"] == 1                  # the SIGKILL books as failure
    assert [e["world_size"] for e in ev if e["event"] == "spawn"] == [2, 1]


def test_stale_heartbeat_triggers_targeted_ladder(tmp_path):
    # One beat, then silence: the supervisor declares the worker stale,
    # writes a supervisor incident, ladders it (SIGTERM->SIGKILL), and —
    # with no budget — gives up nonzero.
    cmd = _script(tmp_path, "hung.py", """\
        import json, os, time
        d = os.environ["DLTI_ELASTIC_DIR"]
        path = os.path.join(d, "hb_g0_r0.json")
        with open(path, "w") as f:
            json.dump({"step": 1, "wall": time.time()}, f)
        time.sleep(60)
    """)
    lau = _launcher(cmd, 1, tmp_path, restart_budget=0,
                    heartbeat_stale_s=0.5, startup_grace_s=5.0,
                    term_grace_s=0.3)
    t0 = time.monotonic()
    rc = lau.run()
    assert rc != 0
    assert time.monotonic() - t0 < 30         # did not wait out sleep(60)
    ev = _events(lau)
    assert any(e["event"] == "stale" for e in ev)
    incident = json.load(open(os.path.join(
        lau.elastic_dir, "supervisor_incident_g0.json")))
    assert incident["rank"] == 0 and incident["heartbeat"]["step"] == 1


def test_watchdog_stale_alert_drives_targeted_kill(tmp_path):
    # Rank 0's in-worker watchdog aggregates collective heartbeats and
    # fires heartbeat_stale naming the straggler; the mirrored alert file
    # makes the supervisor ladder THAT rank instead of aborting the job.
    cmd = _script(tmp_path, "quiet.py", """\
        import os, time
        time.sleep(60)
    """)
    lau = _launcher(cmd, 2, tmp_path, restart_budget=0, term_grace_s=0.3)
    # Pre-plant the mirrored alert (what elastic.mirror_alert writes).
    os.makedirs(lau.elastic_dir, exist_ok=True)
    with open(os.path.join(lau.elastic_dir,
                           "watchdog_alerts_g0_r0.jsonl"), "w") as f:
        f.write(json.dumps({"rule": "heartbeat_stale",
                            "stale": {"1": 42.0}}) + "\n")
    t0 = time.monotonic()
    rc = lau.run()
    assert rc != 0
    assert time.monotonic() - t0 < 30
    ev = _events(lau)
    stale = next(e for e in ev if e["event"] == "watchdog_stale")
    assert stale["rank"] == 1                 # targeted, not whole-job


# ------------------------------------------------- worker-side helpers

def test_beat_and_mirror_alert_write_into_elastic_dir(tmp_path, monkeypatch):
    monkeypatch.setenv(ENV_ELASTIC_DIR, str(tmp_path))
    monkeypatch.setenv(ENV_GENERATION, "2")
    monkeypatch.setenv("DLTI_PROCESS_ID", "1")
    monkeypatch.setenv(ENV_NUM_SLOTS, "2")
    elastic._last_beat[0] = 0.0
    elastic.beat(7)
    hb = json.load(open(tmp_path / "hb_g2_r1.json"))
    assert (hb["step"], hb["generation"], hb["rank"]) == (7, 2, 1)
    elastic.mirror_alert({"rule": "heartbeat_stale", "stale": {"0": 9.0}})
    lines = open(tmp_path / "watchdog_alerts_g2_r1.jsonl").readlines()
    assert json.loads(lines[0])["rule"] == "heartbeat_stale"


def test_beat_noop_without_env(tmp_path, monkeypatch):
    monkeypatch.delenv(ENV_ELASTIC_DIR, raising=False)
    elastic.beat(1)        # must not raise or write anywhere
    elastic.mirror_alert({"rule": "x"})
    assert elastic.elastic_info() is None


def test_flight_dump_tagged_with_rank_and_generation(tmp_path, monkeypatch):
    from dlti_tpu.telemetry.flightrecorder import FlightRecorder, verify_dump

    monkeypatch.setenv("DLTI_PROCESS_ID", "1")
    monkeypatch.setenv(ENV_GENERATION, "2")
    rec = FlightRecorder(str(tmp_path))
    rec.note(step=10)
    path = rec.dump(reason="test", force=True)
    assert os.path.basename(path).endswith("-g2-r1")
    assert verify_dump(path) == []
    ctx = json.load(open(os.path.join(path, "context.json")))
    assert (ctx["process_id"], ctx["generation"]) == (1, 2)


def test_postmortem_incident_mode_over_per_rank_dumps(tmp_path, monkeypatch):
    from dlti_tpu.telemetry.flightrecorder import FlightRecorder

    monkeypatch.setenv(ENV_GENERATION, "0")
    monkeypatch.setenv("DLTI_PROCESS_ID", "1")
    rec = FlightRecorder(str(tmp_path))
    rec.note(step=3, role="training")
    rec.dump(reason="chaos_kill", force=True)
    monkeypatch.setenv(ENV_GENERATION, "1")
    monkeypatch.setenv("DLTI_PROCESS_ID", "0")
    rec2 = FlightRecorder(str(tmp_path))
    rec2.note(step=5, role="training")
    rec2.dump(reason="preemption_stop", force=True)

    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "postmortem.py"),
         str(tmp_path), "--all", "--json"],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr[-800:]
    incident = json.loads(out.stdout)
    assert incident["num_dumps"] == 2
    assert set(incident["generations"]) == {"0", "1"}
    # Root cause is the earliest non-preemption death: the gen-0 chaos
    # kill on rank 1, not the later drain.
    assert incident["root_cause"]["reason"] == "chaos_kill"
    assert incident["root_cause"]["process_id"] == 1
    # Human rendering works too.
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "postmortem.py"),
         str(tmp_path), "--all"],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr[-800:]
    assert "INCIDENT" in out.stdout and "root cause" in out.stdout


def test_maybe_reshape_from_env(tmp_path, monkeypatch):
    from dlti_tpu.config import (
        Config, MODEL_PRESETS, ParallelConfig, TrainConfig, ZeROStage,
    )
    from dlti_tpu.training.elastic import maybe_reshape_from_env

    cfg = Config(model=MODEL_PRESETS["llama_tiny"],
                 parallel=ParallelConfig(zero_stage=ZeROStage.ZERO3, fsdp=4),
                 train=TrainConfig(micro_batch_size=4, grad_accum_steps=2))
    # Outside an elastic launch: untouched.
    monkeypatch.delenv(ENV_ELASTIC_DIR, raising=False)
    assert maybe_reshape_from_env(cfg) is cfg
    # Live world 1 of 2 slots (this test process IS world 1): grad accum
    # doubles, mesh/microbatch stay at what the live device count built.
    monkeypatch.setenv(ENV_ELASTIC_DIR, str(tmp_path))
    monkeypatch.setenv(ENV_GENERATION, "1")
    monkeypatch.setenv(ENV_NUM_SLOTS, "2")
    monkeypatch.setenv("DLTI_PROCESS_ID", "0")
    got = maybe_reshape_from_env(cfg)
    assert got.train.micro_batch_size == 4
    assert got.train.grad_accum_steps == 4
    assert (got.train.micro_batch_size * got.train.grad_accum_steps
            == 4 * 2 * 2 // 2 * 2)  # rows/step of the full-world schedule
    # At full size: untouched.
    monkeypatch.setenv(ENV_NUM_SLOTS, "1")
    assert maybe_reshape_from_env(cfg) is cfg


# ------------------------------------------------------------ the drill
#
# The acceptance drill: 2 real gloo processes under the elastic
# supervisor, worker 1 host-killed mid-epoch, reshape to world 1 +
# verified resume, rejoin to world 2 at the next checkpoint boundary, and
# the final loss trajectory matches an uninterrupted run step-for-step.

@pytest.mark.slow
def test_elastic_drill_host_kill_reshape_resume_rejoin(tmp_path):
    import numpy as np

    n_rows, seq = 128, 32
    # Fixed-length rows (every line truncates to seq tokens): uniform
    # loss masks make the grad-accum regrouping of the shrunk world
    # mathematically identical, not just approximately so.
    data = tmp_path / "data.txt"
    data.write_text("".join(
        f"row {i:04d} " + "x" * 64 + "\n" for i in range(n_rows)))

    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_DEFAULT_MATMUL_PRECISION"] = "highest"

    def train_cmd(out_dir, steplog):
        return [
            sys.executable, os.path.join(REPO, "scripts", "train.py"),
            "--preset", "zero3", "--model", "llama_tiny",
            "--tokenizer", "byte", "--dataset-path", str(data),
            "--output-dir", str(out_dir), "--max-seq-len", str(seq),
            "--per-device-batch-size", "1",
            "--gradient-accumulation-steps", "2",
            "--num-train-epochs", "1", "--save-steps", "2",
            "--save-total-limit", "10", "--warmup-steps", "2",
            "--logging-steps", "1", "--prefetch-depth", "0",
            "--step-log", str(steplog),
            "--metrics-csv", str(tmp_path / "m.csv"),
        ]

    def losses_from(steplog):
        out = {}
        order = []
        with open(steplog) as f:
            for line in f:
                rec = json.loads(line)
                if rec.get("type") == "step":
                    out[rec["step"]] = rec["loss"]
                    order.append(rec["step"])
        return out, order

    # Uninterrupted reference: ONE process, 8 devices — the same global
    # mesh extent and batch schedule the elastic job defines.
    ref_env = dict(env)
    ref_env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    ref_log = tmp_path / "ref_steps.jsonl"
    proc = subprocess.run(
        train_cmd(tmp_path / "ref_ckpt", ref_log), env=ref_env,
        capture_output=True, text=True, timeout=600, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-3000:]
    ref_losses, _ = losses_from(ref_log)
    assert len(ref_losses) == n_rows // (8 * 2)  # 8 steps/epoch

    # Elastic run: 2 processes x 4 devices under the supervisor; the
    # supervisor SIGKILLs worker 1 once heartbeats reach step 3.
    el_env = dict(env)
    el_env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    el_env["DLTI_TRAIN_FAULT_INJECT"] = "3:host-kill"
    ckpt = tmp_path / "ckpt"
    el_log = tmp_path / "el_steps.jsonl"
    elastic_dir = tmp_path / "elastic"
    # Budget 4, not the 1 the drill strictly needs: this image's gloo CPU
    # collectives are intrinsically flaky under contention (a rank can
    # SIGABRT in a collective through no fault of the code under test),
    # and absorbing such a failure with a spare recovery cycle is the
    # supervisor's PURPOSE — the assertions below verify the mandated
    # recovery invariants rather than a noise-free restart history.
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "launch.py"),
         "--num-processes", "2", "--elastic",
         "--restart-budget", "4", "--backoff", "0.5",
         "--ckpt-dir", str(ckpt), "--elastic-dir", str(elastic_dir),
         "--log-dir", str(tmp_path / "logs"), "--term-grace", "30", "--",
         *train_cmd(ckpt, el_log)],
        env=el_env, capture_output=True, text=True, timeout=900, cwd=REPO)
    logs = ""
    logdir = tmp_path / "logs"
    if logdir.is_dir():
        for p in sorted(logdir.iterdir()):
            if p.suffix == ".err":
                logs += f"--- {p.name} ---\n" + p.read_text()[-1500:]
    assert proc.returncode == 0, (
        f"supervisor rc={proc.returncode}\n{proc.stderr[-2000:]}\n{logs}")

    events = [json.loads(line) for line in
              open(elastic_dir / "elastic_events.jsonl")]
    kinds = [e["event"] for e in events]
    spawns = [e for e in events if e["event"] == "spawn"]
    # The advertised sequence: full size -> host-kill -> reshape to the
    # survivor -> rejoin at the next checkpoint boundary -> full size.
    # (Spurious environment failures may add recovery cycles around it —
    # absorbed by the spare budget — so assert the invariants, not an
    # exact restart history.)
    assert "host_kill" in kinds, kinds
    assert "rejoin_drain" in kinds and "rejoin" in kinds, kinds
    assert [k for k in kinds if k == "host_kill"] == ["host_kill"]
    assert spawns[0]["world_size"] == 2
    # The failure booked for the host-kill blames the killed slot, and
    # the generation spawned right after it is the reshaped survivor.
    hk = kinds.index("host_kill")
    hk_fail = next(e for e in events[hk:] if e["event"] == "failure")
    assert hk_fail["slot"] == 1, hk_fail
    post_kill_spawn = next(e for e in events
                           if e["event"] == "spawn"
                           and e["generation"] > events[hk]["generation"])
    assert post_kill_spawn["world_size"] == 1, post_kill_spawn
    # A rejoin (after the post-kill shrink) grew the world back to 2.
    rejoin = next(e for e in events[hk:] if e["event"] == "rejoin")
    assert rejoin["world"] == [0, 1]
    assert spawns[-1]["world_size"] == 2, spawns

    # The post-kill generation resumed from the last VERIFIED step: its
    # first re-logged step is watermark+1 (the supervisor recorded the
    # watermark at every spawn). Every other resume in the log also
    # restarts at some spawn's watermark+1 — nothing resumes from an
    # unverified or uncommitted step.
    el_losses, order = losses_from(el_log)
    watermark = post_kill_spawn["ckpt_watermark"]
    assert watermark is not None and watermark >= 2
    restarts = [order[i] for i in range(1, len(order))
                if order[i] <= order[i - 1]]
    assert restarts, "step log shows no resume"
    assert watermark + 1 in restarts
    valid_resume_points = {(s["ckpt_watermark"] or 0) + 1 for s in spawns}
    assert set(restarts) <= valid_resume_points, (restarts, spawns)

    # Step-for-step loss match with the uninterrupted run — before the
    # kill, through the shrunk generation (regrouped grad accum), and
    # after the rejoin.
    assert set(el_losses) == set(ref_losses)
    for step in sorted(ref_losses):
        np.testing.assert_allclose(
            el_losses[step], ref_losses[step], rtol=2e-4,
            err_msg=f"loss diverged at step {step} "
                    f"(elastic {el_losses[step]} vs ref {ref_losses[step]})")

    # Heartbeats respect the advertised worlds: no (generation, rank)
    # beat outside what its spawn announced (the reshape really shrank
    # the world), and the generations the drill hinges on — the one the
    # host-kill hit, the shrunk survivor, and the rejoined full-size one
    # — all have a beat from every advertised rank.
    hb_files = {p.name for p in elastic_dir.glob("hb_g*_r*.json")}
    allowed = {f"hb_g{s['generation']}_r{r}.json"
               for s in spawns for r in range(s["world_size"])}
    assert hb_files <= allowed, (hb_files, allowed)
    for spawn in (next(s for s in spawns
                       if s["generation"] == events[hk]["generation"]),
                  post_kill_spawn, spawns[-1]):
        for r in range(spawn["world_size"]):
            assert f"hb_g{spawn['generation']}_r{r}.json" in hb_files, (
                spawn, sorted(hb_files))
