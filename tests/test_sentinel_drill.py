"""Numeric-fault chaos drills against the real CLIs (slow tier).

The honest versions of what ``tests/test_sentinel.py`` proves in-process,
with no human in the loop anywhere:

* ``nan-grad`` through ``scripts/train.py``: the injected NaN batch runs
  the genuine compiled step, the in-step gate skips the update, the
  steplog records the anomaly, and the run completes.
* ``poison-batch``: a deterministically-corrupt data window spikes the
  loss; the sentinel rolls back to the last verified checkpoint, replays
  (the window re-poisons, like real bad data), rolls back again,
  quarantines the window permanently — and the final loss trajectory is
  step-for-step identical to a clean run over the surviving data.
* ``param-flip`` on rank 1 of a REAL 2-process gloo run under the
  elastic supervisor: the cross-rank digest probe flags rank 1 as the
  SDC suspect, rank 1 writes a flight dump and exits with the
  distinctive code, the supervisor evicts it, reshapes to the survivor,
  resumes from the last verified step, rejoins at the next checkpoint
  boundary — and the post-recovery losses match an uninterrupted run
  step for step.
"""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRAIN = os.path.join(REPO, "scripts", "train.py")
LAUNCH = os.path.join(REPO, "scripts", "launch.py")


def _losses(steplog):
    """{step: loss} with LAST occurrence winning (rollbacks/resumes
    re-log replayed steps) + the raw step order."""
    out, order = {}, []
    with open(steplog) as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("type") == "step":
                out[rec["step"]] = rec["loss"]
                order.append(rec["step"])
    return out, order


def _steplog_records(steplog):
    return [json.loads(l) for l in open(steplog)
            if json.loads(l).get("type") == "step"]


# ----------------------------------------------------------------------
# Single-process drills: nan-grad skip, poison-batch rollback+quarantine
# ----------------------------------------------------------------------

def _write_learnable_corpus(path, n=64):
    # Identical rows of DISTINCT bytes: a full fine-tune at lr 1e-2
    # learns the order within a few steps (loss -> ~0), so a permuted
    # (poisoned) batch is a genuine, large relative loss spike — and
    # permutation actually changes it (an all-'x' row would be
    # permutation-invariant).
    row = "abcdefghijklmnopqrstuvwxyz012345"
    with open(path, "w") as f:
        for _ in range(n):
            f.write(row + "\n")


def _run_train(tmp_path, tag, out_dir, extra, timeout=420):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_backend_optimization_level=0"
    cmd = [
        sys.executable, TRAIN,
        "--preset", "baseline", "--model", "llama_tiny",
        "--tokenizer", "byte",
        "--dataset-path", str(tmp_path / "corpus.txt"),
        "--output-dir", str(out_dir),
        "--max-seq-len", "32", "--per-device-batch-size", "2",
        "--gradient-accumulation-steps", "1",
        "--lora-r", "0", "--learning-rate", "0.01",
        "--warmup-steps", "2", "--max-steps", "14", "--save-steps", "2",
        "--save-total-limit", "10", "--logging-steps", "1000",
        "--sentinel-rollback-after", "1", "--sentinel-window", "4",
        "--sentinel-min-samples", "4",
        "--sentinel-loss-spike-factor", "1.5",
        "--metrics-csv", str(tmp_path / f"{tag}.csv"),
        "--step-log", str(tmp_path / f"{tag}.jsonl"),
    ] + extra
    return subprocess.run(cmd, env=env, cwd=REPO, capture_output=True,
                          text=True, timeout=timeout)


def test_nan_grad_then_poison_batch_full_recovery_loop(tmp_path):
    _write_learnable_corpus(tmp_path / "corpus.txt")

    # Phase 1 — nan-grad: a transient NaN batch skips its update (the
    # bf16 gate), books one anomaly, and the run completes on its own.
    nan = _run_train(tmp_path, "nan", tmp_path / "ck_nan",
                     ["--fault-inject-step", "3:nan-grad"])
    assert nan.returncode == 0, nan.stderr[-3000:]
    recs = _steplog_records(tmp_path / "nan.jsonl")
    by_step = {}
    for r in recs:
        by_step[r["step"]] = r  # last occurrence wins (rollback replays)
    first_log = {}
    for r in recs:
        first_log.setdefault(r["step"], r)
    assert first_log[3]["anomaly"] == "nonfinite"
    assert first_log[3]["skipped_update"] == 1
    # rollback_after=1: even the transient NaN triggers one rollback and
    # a clean replay — the replayed step 3 is normal.
    assert by_step[3]["anomaly"] == ""
    assert by_step[14]["rollbacks_total"] >= 1

    # Phase 2 — poison-batch at data position 10: the window re-poisons
    # on replay (deterministic bad data), so rollback #1 replays it,
    # rollback #2 quarantines it permanently.
    poi = _run_train(tmp_path, "poi", tmp_path / "ck_poi",
                     ["--fault-inject-step", "10:poison-batch"])
    assert poi.returncode == 0, poi.stderr[-3000:]
    poi_losses, poi_order = _losses(tmp_path / "poi.jsonl")
    assert poi_losses, "poisoned run logged no steps"
    assert max(poi_losses) == 14
    # The rollbacks are visible in the steplog...
    assert any(r["rollbacks_total"] >= 2
               for r in _steplog_records(tmp_path / "poi.jsonl"))
    # ...and the quarantine persisted.
    skip = json.load(open(tmp_path / "ck_poi" / "sentinel_skiplist.json"))
    quarantined = [w["pos"] for w in skip["windows"] if w["quarantined"]]
    assert quarantined == [10], skip

    # Phase 3 — the acceptance bar: the recovered trajectory equals a
    # CLEAN run over the surviving data (same quarantine pre-seeded, no
    # chaos), step for step, exactly.
    ck_ref = tmp_path / "ck_ref"
    ck_ref.mkdir()
    (ck_ref / "sentinel_skiplist.json").write_text(json.dumps(skip))
    ref = _run_train(tmp_path, "ref", ck_ref, [])
    assert ref.returncode == 0, ref.stderr[-3000:]
    ref_losses, _ = _losses(tmp_path / "ref.jsonl")
    assert set(ref_losses) == set(poi_losses)
    for step, loss in ref_losses.items():
        assert poi_losses[step] == loss, (step, poi_losses[step], loss)


# ----------------------------------------------------------------------
# 2-process gloo drill: param-flip SDC -> attribute -> evict -> resume
# ----------------------------------------------------------------------

def test_sdc_param_flip_attributed_evicted_and_recovered(tmp_path):
    n_rows, seq = 128, 32
    # Fixed-length rows (every line truncates to seq tokens): the same
    # mesh/schedule shape as the PR-6 elastic drill, whose world-2 -> 1
    # grad-accum regrouping is proven bit-identical — the trajectory
    # assertion below needs that exactness through the post-evict
    # replay. (Under ZeRO-3 llama_tiny's params all sit below the FSDP
    # size floor, so every param leaf stays cross-process replicated and
    # the digest probe covers the whole tree.)
    data = tmp_path / "data.txt"
    data.write_text("".join(
        f"row {i:04d} " + "x" * 64 + "\n" for i in range(n_rows)))

    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_DEFAULT_MATMUL_PRECISION"] = "highest"

    def train_cmd(out_dir, steplog):
        return [
            sys.executable, TRAIN,
            "--preset", "zero3", "--model", "llama_tiny",
            "--tokenizer", "byte",
            "--dataset-path", str(data), "--output-dir", str(out_dir),
            "--max-seq-len", str(seq), "--per-device-batch-size", "1",
            "--gradient-accumulation-steps", "2",
            "--num-train-epochs", "1", "--save-steps", "2",
            "--save-total-limit", "10", "--warmup-steps", "2",
            "--logging-steps", "1", "--prefetch-depth", "0",
            "--sdc-check-interval", "2",
            "--step-log", str(steplog),
            "--metrics-csv", str(tmp_path / "m.csv"),
            "--flight-dir", str(tmp_path / "flight"),
        ]

    # Uninterrupted reference: ONE process, 8 virtual devices — the same
    # global mesh extent and (world-size-invariant) batch schedule.
    ref_env = dict(env)
    ref_env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    ref_log = tmp_path / "ref_steps.jsonl"
    proc = subprocess.run(train_cmd(tmp_path / "ref_ckpt", ref_log),
                          env=ref_env, capture_output=True, text=True,
                          timeout=600, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-3000:]
    ref_losses, _ = _losses(ref_log)
    assert len(ref_losses) == n_rows // (8 * 2)  # 8 steps/epoch

    # SDC run: 2 gloo processes x 4 devices under the elastic
    # supervisor; rank 1 flips one mantissa bit in a replicated param at
    # step 3; the digest probe (every 2 steps) must flag rank 1 at
    # step 4.
    el_env = dict(env)
    el_env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    el_env["DLTI_TRAIN_FAULT_INJECT"] = "3:param-flip:1"
    ckpt = tmp_path / "ckpt"
    el_log = tmp_path / "el_steps.jsonl"
    elastic_dir = tmp_path / "elastic"
    proc = subprocess.run(
        [sys.executable, LAUNCH, "--num-processes", "2", "--elastic",
         "--restart-budget", "4", "--backoff", "0.5",
         "--ckpt-dir", str(ckpt), "--elastic-dir", str(elastic_dir),
         "--log-dir", str(tmp_path / "logs"), "--term-grace", "30", "--",
         *train_cmd(ckpt, el_log)],
        env=el_env, capture_output=True, text=True, timeout=900, cwd=REPO)
    logs = ""
    logdir = tmp_path / "logs"
    if logdir.is_dir():
        for p in sorted(logdir.iterdir()):
            if p.suffix == ".err":
                logs += f"--- {p.name} ---\n" + p.read_text()[-1500:]
    assert proc.returncode == 0, (
        f"supervisor rc={proc.returncode}\n{proc.stderr[-2000:]}\n{logs}")

    events = [json.loads(line) for line in
              open(elastic_dir / "elastic_events.jsonl")]
    kinds = [e["event"] for e in events]
    # The suspect rank exited with the SDC code and the supervisor
    # booked exactly that slot as the failure (healthy ranks exit 0, so
    # attribution is unambiguous).
    from dlti_tpu.training.sentinel import SDC_EXIT_CODE

    sdc_failures = [e for e in events if e["event"] == "failure"
                    and e.get("rc") == SDC_EXIT_CODE]
    assert sdc_failures, events
    assert all(e["slot"] == 1 for e in sdc_failures), sdc_failures
    # Evict -> reshape to the survivor -> resume -> rejoin full size.
    first_fail = kinds.index("failure")
    post = next(e for e in events[first_fail:] if e["event"] == "spawn")
    assert post["world_size"] == 1, post
    assert "rejoin" in kinds, kinds
    spawns = [e for e in events if e["event"] == "spawn"]
    assert spawns[-1]["world_size"] == 2, spawns

    # The suspect wrote its black box before evicting itself, tagged
    # with its rank, carrying the SDC verdict.
    import glob

    dumps = sorted(glob.glob(str(tmp_path / "flight" / "flight-*-r1*")))
    assert dumps, os.listdir(tmp_path / "flight")
    contexts = [json.load(open(os.path.join(d, "context.json")))
                for d in dumps]
    # The flip itself left the chaos pre-fire dump; the PROBE's verdict
    # dump names this rank as the suspect.
    ctx = next(c for c in contexts if c["reason"] == "sdc_mismatch")
    assert ctx["suspect_self"] is True
    assert ctx["alert"]["suspects"] == [1]

    # And the recovered trajectory matches the uninterrupted run step
    # for step (contaminated steps were re-executed from the verified
    # checkpoint; the step log's final value per step is the replay's).
    # Same tolerance as the PR-6 elastic drill: steps replayed by the
    # SHRUNK world regroup the grad-accum reductions, which reorders
    # floating-point sums (allclose, not bitwise); within a fixed world
    # size the replay IS bit-exact (the single-process drills above
    # assert strict equality).
    import numpy as np

    el_losses, order = _losses(el_log)
    assert set(el_losses) == set(ref_losses)
    for step in sorted(ref_losses):
        np.testing.assert_allclose(
            el_losses[step], ref_losses[step], rtol=2e-4,
            err_msg=f"loss diverged at step {step} "
                    f"(elastic {el_losses[step]} vs ref "
                    f"{ref_losses[step]})")
    restarts = [order[i] for i in range(1, len(order))
                if order[i] <= order[i - 1]]
    assert restarts, "step log shows no resume after eviction"
