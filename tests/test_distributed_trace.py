"""Distributed-tracing units (telemetry.distributed_trace): fake-clock
clock-offset estimation, span federation, per-request timeline
reconstruction, trace-context wire round-trips, and flight-dump merging.

All deterministic — RPC round trips are *simulated* with explicit fake
clocks (a true offset we control), so the estimator's invariant
(|estimate − truth| ≤ uncertainty) is checked against ground truth rather
than wall time. The cross-process integration drill (real fleet, real
spans over the wire protocol) lives in tests/test_fleet.py.
"""

import math
import random

import pytest

from dlti_tpu.serving.engine import Request
from dlti_tpu.serving.sampling import SamplingParams
from dlti_tpu.serving.wire import request_from_wire, request_to_wire
from dlti_tpu.telemetry.distributed_trace import (
    SEQUENTIAL_LEGS, ClockOffsetEstimator, TraceFederator, merge_dump_tails,
    mint_trace_id, request_timeline,
)
from dlti_tpu.telemetry.tracer import SpanTracer


# ----------------------------------------------------------------------
# Fake-clock RPC simulation
# ----------------------------------------------------------------------

def _simulate_rpcs(est, true_offset, rtts, *, local_t0=100.0,
                   asymmetry=0.5, drift_per_rpc=0.0):
    """Feed simulated round trips into ``est`` against a worker whose
    clock reads ``local − true_offset`` (optionally drifting). Returns
    the final true offset (it moves when drift_per_rpc != 0)."""
    t = local_t0
    off = true_offset
    for i, rtt in enumerate(rtts):
        t0 = t
        t1 = t + rtt
        # The worker stamps its clock somewhere inside the window; the
        # asymmetry knob places it (0.5 = symmetric legs).
        remote_stamp = (t0 + asymmetry * rtt) - off
        est.sample(t0, t1, remote_stamp)
        t = t1 + 0.01
        off += drift_per_rpc
    return off


def test_estimator_converges_on_skewed_worker():
    """A worker whose clock is 3.5s behind: the estimate lands within
    half-RTT of the truth and the invariant holds after every sample."""
    est = ClockOffsetEstimator()
    true = 3.5
    rtts = [0.004, 0.002, 0.003, 0.005, 0.002, 0.004, 0.003, 0.002]
    _simulate_rpcs(est, true, rtts)
    assert est.samples == len(rtts)
    assert abs(est.offset - true) <= est.uncertainty
    assert abs(est.offset - true) < 0.01
    assert est.to_dict()["uncertainty_s"] == pytest.approx(est.uncertainty)


def test_estimator_invariant_under_asymmetric_legs():
    """However asymmetric the two legs of each RPC are, the remote stamp
    was taken inside the [t0, t1] window — so |estimate − truth| stays
    within the (smoothed half-RTT) uncertainty, sample by sample."""
    rng = random.Random(7)
    est = ClockOffsetEstimator()
    true = -1.25                     # worker clock AHEAD of supervisor
    t = 50.0
    for _ in range(64):
        rtt = rng.uniform(0.001, 0.030)
        asym = rng.uniform(0.0, 1.0)
        t0, t1 = t, t + rtt
        est.sample(t0, t1, (t0 + asym * rtt) - true)
        assert abs(est.offset - true) <= est.uncertainty + 1e-12
        t = t1 + rng.uniform(0.0, 0.1)


def test_estimator_drifting_worker_widens_uncertainty():
    """A *moving* clock must report a wide bound, not a confident stale
    one: the drift term (|raw − smoothed|) feeds the uncertainty EWMA."""
    fixed = ClockOffsetEstimator()
    drifting = ClockOffsetEstimator()
    rtts = [0.002] * 40
    _simulate_rpcs(fixed, 2.0, rtts)
    final_true = _simulate_rpcs(drifting, 2.0, rtts, drift_per_rpc=0.005)
    assert drifting.uncertainty > fixed.uncertainty * 3
    # The smoothed estimate trails the moving truth, but stays within
    # the widened bound of the *recent* true offsets.
    assert abs(drifting.offset - final_true) < 0.25


def test_estimator_first_sample_and_backwards_clock():
    est = ClockOffsetEstimator()
    assert est.samples == 0 and math.isinf(est.uncertainty)
    assert est.to_dict()["uncertainty_s"] is None   # no samples yet
    est.sample(10.0, 9.0, 5.0)       # t1 < t0: skipped
    assert est.samples == 0
    est.sample(10.0, 10.004, 8.002)
    assert est.samples == 1
    assert est.offset == pytest.approx(10.002 - 8.002)
    assert est.uncertainty == pytest.approx(0.002)
    assert est.rebase(8.002) == pytest.approx(10.002)


def test_rebased_spans_never_reorder_causal_legs():
    """Causal order survives rebasing: the supervisor hands off at its
    T, the worker's decode leg starts (on the worker clock) strictly
    after receipt — after rebasing with a converged estimator the decode
    leg must not appear to start before the handoff ended, beyond the
    estimator's own uncertainty."""
    est = ClockOffsetEstimator()
    true = 7.75
    _simulate_rpcs(est, true, [0.002] * 10)
    handoff_end_local = 200.0
    # Worker-side decode starts 1ms after the handoff lands (worker clock).
    decode_start_remote = (handoff_end_local - true) + 0.001
    rebased = est.rebase(decode_start_remote)
    assert rebased >= handoff_end_local - est.uncertainty
    # And intra-worker ordering is preserved exactly (constant shift).
    remote_ts = [1.0, 1.5, 2.0, 2.25]
    rebased_ts = [est.rebase(ts) for ts in remote_ts]
    assert rebased_ts == sorted(rebased_ts)


# ----------------------------------------------------------------------
# TraceFederator
# ----------------------------------------------------------------------

def _span(name, ts_us, dur_us, *, pid=1, rid=None, trace=None, ph="X"):
    ev = {"ph": ph, "name": name, "cat": "test", "ts": float(ts_us),
          "pid": pid, "tid": 1}
    if ph == "X":
        ev["dur"] = float(dur_us)
    args = {}
    if rid:
        args["id"] = rid
    if trace:
        args["trace"] = trace
    if args:
        ev["args"] = args
    return ev


def test_federator_rebases_and_retags_pids():
    fed = TraceFederator()
    fed.source(0, pid=4242, label="worker0 gen1")
    # A converged 2s offset: worker spans land 2s later on our axis.
    fed.observe_rpc(0, 10.0, 10.002, 8.001)
    n = fed.ingest(0, [_span("request/decode", 1_000_000, 500, rid="r1")])
    assert n == 1 and len(fed) == 1
    ev = fed.events()[0]
    assert ev["ts"] == pytest.approx(1_000_000 + 2.0 * 1e6)
    assert ev["pid"] == TraceFederator.SYNTHETIC_PID_BASE + 0
    # Respawn: same key, new real pid — the render pid (Perfetto row)
    # stays stable; only the metadata label changes.
    fed.source(0, pid=5555, label="worker0 gen2")
    meta = fed.metadata_events()
    assert len(meta) == 1 and meta[0]["ph"] == "M"
    assert meta[0]["pid"] == TraceFederator.SYNTHETIC_PID_BASE + 0
    assert "worker0 gen2" in meta[0]["args"]["name"]
    assert "5555" in meta[0]["args"]["name"]


def test_federator_counts_unparented_and_dropped():
    from dlti_tpu.telemetry.distributed_trace import (
        federated_spans_total, unparented_spans_total,
    )

    fed = TraceFederator(capacity=4)
    base_fed = federated_spans_total.value
    base_unp = unparented_spans_total.value
    spans = [_span("engine/decode_dispatch", i * 100, 10) for i in range(6)]
    spans.append(_span("request/prefill", 999, 10, trace="t1"))
    fed.ingest(3, spans, remote_dropped=5)
    assert len(fed) == 4                       # ring bound holds
    assert fed.dropped_events == 3 + 5         # local evictions + remote
    assert federated_spans_total.value - base_fed == 7
    # Engine-step spans carry no request/trace linkage: unparented.
    assert unparented_spans_total.value - base_unp == 6


def test_federator_merged_dict_includes_local_and_offsets():
    fed = TraceFederator()
    fed.observe_rpc("w1", 0.0, 0.004, -2.998)  # worker ~3s behind
    fed.ingest("w1", [_span("request/decode", 0, 100, rid="r9")])
    local = SpanTracer(capacity=16, enabled=True)
    local.complete("gateway/queued", 0.0, 0.001, cat="gateway", id="r9")
    out = fed.merged_dict(local, local_label="supervisor")
    phs = [e["ph"] for e in out["traceEvents"]]
    assert phs.count("M") == 2                 # one per process
    names = {e["name"] for e in out["traceEvents"] if e["ph"] != "M"}
    assert {"gateway/queued", "request/decode"} <= names
    assert "w1" in out["clockOffsets"]
    assert out["clockOffsets"]["w1"]["offset_s"] == pytest.approx(
        3.0, abs=0.01)


# ----------------------------------------------------------------------
# Span-ring shipping cursor (SpanTracer.events_since)
# ----------------------------------------------------------------------

def test_events_since_walks_ring_without_duplicates():
    tr = SpanTracer(capacity=8, enabled=True)
    for i in range(5):
        tr.instant(f"request/submitted", id=f"r{i}")
    evs, dropped, cur = tr.events_since(0, limit=3)
    assert [e["args"]["id"] for e in evs] == ["r0", "r1", "r2"]
    assert dropped == 0
    evs2, dropped2, cur2 = tr.events_since(cur, limit=512)
    assert [e["args"]["id"] for e in evs2] == ["r3", "r4"]
    assert dropped2 == 0
    assert tr.events_since(cur2, limit=512) == ([], 0, cur2)


def test_events_since_reports_ring_evictions():
    tr = SpanTracer(capacity=4, enabled=True)
    for i in range(10):                        # 6 evicted before any ship
        tr.instant("request/submitted", id=f"r{i}")
    evs, dropped, cur = tr.events_since(0, limit=512)
    assert dropped == 6
    assert [e["args"]["id"] for e in evs] == ["r6", "r7", "r8", "r9"]
    assert cur == tr.total_events
    # A slow consumer that lags behind keeps honest accounting too.
    for i in range(10, 16):
        tr.instant("request/submitted", id=f"r{i}")
    evs, dropped, cur = tr.events_since(cur, limit=2)
    assert dropped == 2                        # r10, r11 already evicted
    assert [e["args"]["id"] for e in evs] == ["r12", "r13"]


# ----------------------------------------------------------------------
# Trace-context wire round trip
# ----------------------------------------------------------------------

def test_wire_round_trips_trace_id():
    req = Request(request_id="req-1", prompt_token_ids=[1, 2, 3],
                  params=SamplingParams(max_tokens=4),
                  trace_id=mint_trace_id())
    d = request_to_wire(req)
    assert d["trace_id"] == req.trace_id
    back = request_from_wire(d)
    assert back.trace_id == req.trace_id


def test_wire_old_frames_without_trace_id_still_parse():
    """A peer from before this change omits the field: the request
    arrives untraced ("") — never re-minted here, which would fork the
    id between processes."""
    req = Request(request_id="req-2", prompt_token_ids=[1],
                  params=SamplingParams(max_tokens=2))
    d = request_to_wire(req)
    d.pop("trace_id")
    back = request_from_wire(d)
    assert back.trace_id == ""


def test_mint_trace_id_is_unique_and_compact():
    ids = {mint_trace_id() for _ in range(256)}
    assert len(ids) == 256
    assert all(len(i) == 16 for i in ids)


# ----------------------------------------------------------------------
# Per-request timeline reconstruction
# ----------------------------------------------------------------------

def _request_events(rid="r1", trace="t-abc"):
    """A two-process request: gateway + queue on pid 1 (supervisor),
    prefill/decode on pid 100001 (worker, already rebased), with a
    kv_handoff overlapping the decode leg."""
    return [
        _span("gateway/queued", 0, 10_000, pid=1, rid=rid, trace=trace),
        _span("request/queued", 10_000, 5_000, pid=1, rid=rid, trace=trace),
        _span("request/prefill", 15_000, 30_000, pid=100001, rid=rid,
              trace=trace),
        _span("engine/kv_handoff", 45_000, 2_000, pid=1, rid=rid,
              trace=trace),
        _span("request/decode", 45_000, 55_000, pid=100001, rid=rid,
              trace=trace),
        # Unrelated request: must not leak into the timeline.
        _span("request/decode", 0, 99_000, pid=1, rid="other"),
    ]


def test_request_timeline_merges_across_processes():
    tl = request_timeline(_request_events(), "r1")
    assert tl["trace_id"] == "t-abc"           # picked up from the spans
    assert len(tl["spans"]) == 5
    assert tl["processes"] == [1, 100001]
    # Causally ordered by rebased start time.
    starts = [ev["ts"] for ev in tl["spans"]]
    assert starts == sorted(starts)
    assert tl["sequential_legs"] == list(SEQUENTIAL_LEGS)
    # Sequential legs tile the life: 10 + 5 + 30 + 55 ms; the handoff
    # overlaps decode and is reported but never summed.
    assert tl["sequential_sum_s"] == pytest.approx(0.100)
    assert "engine/kv_handoff" in tl["legs"]
    assert tl["wall_s"] == pytest.approx(0.100)
    assert tl["residual_s"] == pytest.approx(0.0, abs=1e-9)


def test_request_timeline_residual_vs_client_latency():
    tl = request_timeline(_request_events(), "r1", client_latency_s=0.112)
    assert tl["client_latency_s"] == pytest.approx(0.112)
    # 12ms the server never saw (client-side network / connect time).
    assert tl["residual_s"] == pytest.approx(0.012)


def test_request_timeline_joins_on_trace_id_alone():
    """Failover resubmits re-enter under a new engine request id but the
    SAME trace id: spans that only share args.trace still join."""
    events = _request_events(rid="r1", trace="t-abc")
    events.append(_span("request/decode", 110_000, 1_000, pid=100002,
                        rid="r1-retry1", trace="t-abc"))
    tl = request_timeline(events, "r1")
    assert len(tl["spans"]) == 6
    assert 100002 in tl["processes"]


def test_request_timeline_unions_mirror_and_worker_observations():
    """A fleet request is observed twice per leg — the supervisor mirror
    and the owning worker each emit request/prefill + request/decode for
    the same request. Leg durations are interval UNIONS, so the doubled
    observation must not double the sequential coverage."""
    rid, trace = "r1", "t-abc"
    events = [
        _span("gateway/queued", 0, 10_000, pid=1, rid=rid, trace=trace),
        # Supervisor mirror: prefill covers dispatch -> first token,
        # decode covers first token -> finish.
        _span("request/prefill", 10_000, 40_000, pid=1, rid=rid,
              trace=trace),
        _span("request/decode", 50_000, 50_000, pid=1, rid=rid,
              trace=trace),
        # Worker (rebased): queue leg inside the mirror's prefill
        # window, then near-identical prefill/decode observations.
        _span("request/queued", 11_000, 4_000, pid=100001, rid=rid,
              trace=trace),
        _span("request/prefill", 15_000, 34_000, pid=100001, rid=rid,
              trace=trace),
        _span("request/decode", 49_500, 50_000, pid=100001, rid=rid,
              trace=trace),
    ]
    tl = request_timeline(events, rid)
    assert tl["legs"]["request/prefill"]["count"] == 2
    assert tl["legs"]["request/prefill"]["pids"] == [1, 100001]
    # Union, not sum: mirror [10,50]ms dominates worker [15,49]ms.
    assert tl["legs"]["request/prefill"]["dur_s"] == pytest.approx(0.040)
    # Coverage = enqueue -> finish, despite 6 overlapping spans.
    assert tl["sequential_sum_s"] == pytest.approx(0.100)


def test_request_timeline_accepts_generator_input():
    tl = request_timeline(iter(_request_events()), "r1")
    assert len(tl["spans"]) == 5


# ----------------------------------------------------------------------
# Flight-dump merging (postmortem --all)
# ----------------------------------------------------------------------

def test_merge_dump_tails_rebases_onto_one_clock():
    sup = [_span("engine/kv_handoff", 5_000_000, 1_000, rid="r1")]
    # Worker clock 2s behind: its raw ts are 2s too small on our axis.
    wrk = [_span("request/decode", 3_000_500, 900, rid="r1")]
    out = merge_dump_tails([
        {"label": "supervisor flight-x", "pid": 100, "offset_s": 0.0,
         "uncertainty_s": None, "events": sup, "dropped": 0},
        {"label": "worker0 flight-y", "pid": 200, "offset_s": 2.0,
         "uncertainty_s": 0.0015, "events": wrk, "dropped": 3},
    ])
    evs = [e for e in out["traceEvents"] if e["ph"] != "M"]
    by_name = {e["name"]: e for e in evs}
    assert by_name["request/decode"]["ts"] == pytest.approx(5_000_500)
    assert by_name["request/decode"]["pid"] == 200
    # Sorted onto one axis: handoff (5.000s) precedes decode (5.0005s).
    assert [e["name"] for e in evs] == ["engine/kv_handoff",
                                       "request/decode"]
    meta = [e for e in out["traceEvents"] if e["ph"] == "M"]
    assert len(meta) == 2
    worker_meta = next(m for m in meta if "worker0" in m["args"]["name"])
    assert "±1.50ms" in worker_meta["args"]["name"]
    assert out["droppedEvents"] == 3
    assert {s["pid"] for s in out["sources"]} == {100, 200}


def test_merge_dump_tails_synthesizes_missing_pids():
    out = merge_dump_tails([
        {"label": "a", "events": [_span("request/decode", 0, 1, rid="r")]},
        {"label": "b", "events": [_span("request/prefill", 0, 1, rid="r")]},
    ])
    pids = {e["pid"] for e in out["traceEvents"]}
    assert len(pids) == 2
    assert all(p >= TraceFederator.SYNTHETIC_PID_BASE for p in pids)
