"""Pallas flash attention vs XLA reference (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import make_packed_segments
from dlti_tpu.ops.attention import reference_attention
from dlti_tpu.ops.pallas.flash_attention import flash_attention


def _qkv(rng, b=2, s=256, h=4, hkv=4, d=64):
    q = jax.random.normal(rng, (b, s, h, d))
    k = jax.random.normal(jax.random.fold_in(rng, 1), (b, s, hkv, d))
    v = jax.random.normal(jax.random.fold_in(rng, 2), (b, s, hkv, d))
    return q, k, v


@pytest.mark.parametrize("block_q,block_kv", [
    (128, 128),
    (64, 128),   # block_kv > block_q: rows with fully-masked blocks
    (128, 64),
    (256, 256),  # single block
])
def test_flash_matches_reference(rng, block_q, block_kv):
    q, k, v = _qkv(rng)
    out_ref = reference_attention(q, k, v, causal=True)
    out_fa = flash_attention(q, k, v, causal=True, block_q=block_q,
                             block_kv=block_kv, interpret=True)
    np.testing.assert_allclose(np.asarray(out_fa), np.asarray(out_ref),
                               atol=2e-5, rtol=1e-3)


def test_flash_gqa(rng):
    q, k, v = _qkv(rng, h=8, hkv=2)
    out_ref = reference_attention(q, k, v, causal=True)
    out_fa = flash_attention(q, k, v, causal=True, block_q=128, block_kv=128,
                             interpret=True)
    np.testing.assert_allclose(np.asarray(out_fa), np.asarray(out_ref),
                               atol=2e-5, rtol=1e-3)


def test_flash_grads_match_reference(rng):
    q, k, v = _qkv(rng, b=1, s=128, h=2, hkv=2, d=64)

    def loss_fa(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, block_q=64,
                                       block_kv=64, interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    g_fa = jax.grad(loss_fa, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_fa, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_flash_noncausal(rng):
    q, k, v = _qkv(rng, s=128)
    out_ref = reference_attention(q, k, v, causal=False)
    out_fa = flash_attention(q, k, v, causal=False, block_q=64, block_kv=64,
                             interpret=True)
    np.testing.assert_allclose(np.asarray(out_fa), np.asarray(out_ref),
                               atol=2e-5, rtol=1e-3)


@pytest.mark.parametrize("h,hkv", [(4, 4), (8, 2)])
def test_flash_segments_match_reference(rng, h, hkv):
    q, k, v = _qkv(rng, b=2, s=256, h=h, hkv=hkv)
    segs = make_packed_segments(2, 256)
    out_ref = reference_attention(q, k, v, causal=True, segment_ids=segs)
    out_fa = flash_attention(q, k, v, causal=True, segment_ids=segs,
                             block_q=64, block_kv=64, interpret=True)
    # Padding rows (seg 0) diverge by design: reference yields a uniform
    # softmax over all-masked scores, flash yields exact zeros. Both are
    # garbage excluded from the loss — compare real tokens only.
    valid = np.asarray(segs != 0)[:, :, None, None]
    np.testing.assert_allclose(np.asarray(out_fa) * valid,
                               np.asarray(out_ref) * valid,
                               atol=2e-5, rtol=1e-3)


@pytest.mark.slow
def test_flash_segments_grads_match_reference(rng):
    q, k, v = _qkv(rng, b=1, s=128, h=4, hkv=2, d=64)
    segs = make_packed_segments(1, 128, n_docs=2)
    valid = (segs != 0).astype(q.dtype)[:, :, None, None]

    def loss_fa(q, k, v):
        out = flash_attention(q, k, v, causal=True, segment_ids=segs,
                              block_q=64, block_kv=64, interpret=True)
        return jnp.sum((out * valid) ** 2)

    def loss_ref(q, k, v):
        out = reference_attention(q, k, v, causal=True, segment_ids=segs)
        return jnp.sum((out * valid) ** 2)

    g_fa = jax.grad(loss_fa, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_fa, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_flash_window(rng):
    q, k, v = _qkv(rng, b=1, s=256, h=2, hkv=2)
    out_ref = reference_attention(q, k, v, causal=True, window=96)
    out_fa = flash_attention(q, k, v, causal=True, window=96,
                             block_q=64, block_kv=64, interpret=True)
    np.testing.assert_allclose(np.asarray(out_fa), np.asarray(out_ref),
                               atol=2e-5, rtol=1e-3)


def test_flash_window_plus_segments(rng):
    q, k, v = _qkv(rng, b=1, s=256, h=2, hkv=2)
    segs = make_packed_segments(1, 256)
    valid = np.asarray(segs != 0)[:, :, None, None]
    out_ref = reference_attention(q, k, v, causal=True, window=64,
                                  segment_ids=segs)
    out_fa = flash_attention(q, k, v, causal=True, window=64,
                             segment_ids=segs, block_q=64, block_kv=64,
                             interpret=True)
    np.testing.assert_allclose(np.asarray(out_fa) * valid,
                               np.asarray(out_ref) * valid,
                               atol=2e-5, rtol=1e-3)


def test_flash_segments_unaligned_seq(rng):
    """seq not a multiple of the block: bounds masking composes with segs."""
    q, k, v = _qkv(rng, b=1, s=192, h=2, hkv=2)
    segs = make_packed_segments(1, 192, n_docs=2)
    valid = np.asarray(segs != 0)[:, :, None, None]
    out_ref = reference_attention(q, k, v, causal=True, segment_ids=segs)
    out_fa = flash_attention(q, k, v, causal=True, segment_ids=segs,
                             block_q=128, block_kv=128, interpret=True)
    np.testing.assert_allclose(np.asarray(out_fa) * valid,
                               np.asarray(out_ref) * valid,
                               atol=2e-5, rtol=1e-3)


@pytest.mark.parametrize("seq,window,block", [
    (512, 96, 64),    # windowed grid engaged (3-4 visits of 8 blocks)
    (512, 100, 64),   # window not a multiple of the block
    (448, 96, 64),    # unaligned seq + windowed grid
    (512, 64, 128),   # window smaller than one block
])
def test_flash_windowed_grid_matches_reference(rng, seq, window, block):
    """The restricted kv sweep (only blocks inside the band are visited —
    or DMA'd) must be exact for every window/block alignment."""
    q, k, v = _qkv(rng, b=1, s=seq, h=2, hkv=2)
    out_ref = reference_attention(q, k, v, causal=True, window=window)
    out_fa = flash_attention(q, k, v, causal=True, window=window,
                             block_q=block, block_kv=block, interpret=True)
    np.testing.assert_allclose(np.asarray(out_fa), np.asarray(out_ref),
                               atol=2e-5, rtol=1e-3)


def test_flash_windowed_grid_grads_match_reference(rng):
    q, k, v = _qkv(rng, b=1, s=256, h=2, hkv=2, d=64)

    def loss_fa(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, window=96,
                                       block_q=64, block_kv=64,
                                       interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True,
                                           window=96) ** 2)

    g_fa = jax.grad(loss_fa, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_fa, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


@pytest.mark.slow
def test_flash_windowed_grid_with_segments_and_gqa(rng):
    """window + packing + GQA on the restricted sweep."""
    q, k, v = _qkv(rng, b=2, s=256, h=8, hkv=2)
    segs = make_packed_segments(2, 256)
    valid = np.asarray(segs != 0)[:, :, None, None]

    def loss_fa(q, k, v):
        out = flash_attention(q, k, v, causal=True, window=80,
                              segment_ids=segs, block_q=64, block_kv=64,
                              interpret=True)
        return jnp.sum((out * valid) ** 2)

    def loss_ref(q, k, v):
        out = reference_attention(q, k, v, causal=True, window=80,
                                  segment_ids=segs)
        return jnp.sum((out * valid) ** 2)

    np.testing.assert_allclose(float(loss_fa(q, k, v)),
                               float(loss_ref(q, k, v)), rtol=1e-4)
    g_fa = jax.grad(loss_fa, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_fa, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)
