"""Flight recorder, anomaly watchdog, and time-series ring tests (tier-1).

The self-monitoring contracts:

* **Sampler** — bounded ring, nested-dict flattening, counter→rate
  derivation, broken sources counted not fatal.
* **Watchdog rules** — each rule fires on synthetic ring data, exactly
  once per condition episode (edge-triggered), re-arming when the
  condition clears; hung-step detection trips on a stalled fake trainer
  within the configured deadline and increments
  ``dlti_watchdog_alerts_total{rule="hung_step"}``.
* **Flight recorder** — a dump is an atomically-visible, digest-verified
  directory carrying span tail (with the ring's dropped-event count),
  metrics, time-series tail, and live context; rotation and throttling
  hold; a chaos-injected trainer fault leaves a dump whose context names
  the last completed step and the phase at death; the postmortem CLI
  round-trips it.
* **Server surface** — ``GET /debug/vars`` serves the ring, ``GET
  /dashboard`` serves the self-contained page, ``POST /debug/profile``
  captures once and 409s a concurrent capture, and an engine step fault
  dumps a flight record.
"""

import http.client
import json
import os
import signal
import subprocess
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlti_tpu.config import (
    CheckpointConfig, Config, DataConfig, FlightRecorderConfig, LoRAConfig,
    MODEL_PRESETS, TelemetryConfig, TrainConfig, WatchdogConfig,
)
from dlti_tpu.telemetry import (
    AnomalyWatchdog, FlightRecorder, SpanTracer, TimeSeriesSampler,
    configure_tracer, get_tracer,
)
from dlti_tpu.telemetry.flightrecorder import (
    list_dumps, load_dump, verify_dump,
)
from dlti_tpu.telemetry.watchdog import alerts_total

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CFG = MODEL_PRESETS["llama_tiny"]


def _alert_count(rule: str) -> float:
    return alerts_total.labels(rule=rule).value


# ----------------------------------------------------------------------
# Time-series sampler
# ----------------------------------------------------------------------

def test_sampler_ring_bounded_and_flattened():
    s = TimeSeriesSampler(interval_s=0.1, capacity=5)
    vals = {"x": 0}
    s.add_source(lambda: {"x": vals["x"],
                          "hist": {"count": 2, "mean": 0.25},
                          "skip": "text", "flag": True})
    for i in range(9):
        vals["x"] = i
        s.sample_now()
    assert len(s) == 5  # ring bound
    latest = s.latest()["values"]
    assert latest == {"x": 8.0, "hist.count": 2.0, "hist.mean": 0.25}
    assert [v for _, v in s.series("x")] == [4.0, 5.0, 6.0, 7.0, 8.0]
    snap = s.snapshot(tail=2)
    assert snap["num_samples"] == 2 and snap["latest"]["x"] == 8.0


def test_sampler_rate_and_broken_source():
    s = TimeSeriesSampler(interval_s=0.1, capacity=16)
    state = {"c": 0.0, "t": 100.0}
    s.add_source(lambda: {"c": state["c"]})
    s.add_source(lambda: 1 / 0)  # broken source must not kill sampling
    for _ in range(4):
        s.sample_now()
        state["c"] += 10.0
        time.sleep(0.01)
    assert s.source_errors == 4
    r = s.rate("c")
    assert r is not None and r > 0
    # Counter reset (process restart) clamps to 0, never negative.
    state["c"] = 0.0
    s.sample_now()
    assert s.rate("c") == 0.0
    assert s.peak("c") == 30.0


# ----------------------------------------------------------------------
# Watchdog rules on synthetic ring data
# ----------------------------------------------------------------------

def _watchdog(sampler, tracer=None, heartbeat=None, clock=None, **over):
    kw = dict(enabled=True, interval_s=0.05, hung_step_min_s=30.0)
    kw.update(over)
    return AnomalyWatchdog(
        WatchdogConfig(**kw), sampler, heartbeat=heartbeat,
        # NB `tracer or ...` would misfire: an empty SpanTracer is falsy
        # (it defines __len__).
        tracer=tracer if tracer is not None else SpanTracer(enabled=False),
        clock=clock or time.monotonic)


def test_throughput_collapse_fires_once_and_rearms():
    s = TimeSeriesSampler(capacity=32)
    state = {"tps": 100.0}
    s.add_source(lambda: {"train_tokens_per_s": state["tps"]})
    wd = _watchdog(s, throughput_min_samples=5, throughput_floor_frac=0.25)
    for _ in range(6):
        s.sample_now()
    assert wd.check_now() == []  # healthy: no alert
    state["tps"] = 5.0  # < 0.25 x median(100)
    s.sample_now()
    fired = wd.check_now()
    assert [a["rule"] for a in fired] == ["throughput_collapse"]
    assert wd.check_now() == []  # edge-triggered: same episode, one alert
    state["tps"] = 100.0  # recovery re-arms ...
    s.sample_now()
    assert wd.check_now() == []
    state["tps"] = 3.0    # ... so a second collapse fires again
    s.sample_now()
    assert [a["rule"] for a in wd.check_now()] == ["throughput_collapse"]
    assert wd.alert_counts() == {"throughput_collapse": 2}


def test_queue_and_shed_buildup_rules():
    s = TimeSeriesSampler(capacity=32)
    state = {"depth": 0.0, "shed": 0.0}
    s.add_source(lambda: {"gateway_queue_depth": state["depth"],
                          "dlti_gateway_shed_total": state["shed"]})
    wd = _watchdog(s, queue_depth_limit=8, shed_rate_limit=2.0)
    for depth in (2, 9, 9):  # only 2 consecutive samples at/over the limit
        state["depth"] = depth
        s.sample_now()
    assert wd.check_now() == []
    state["depth"] = 10
    s.sample_now()  # third consecutive sample over the limit
    rules = [a["rule"] for a in wd.check_now()]
    assert rules == ["queue_buildup"]
    # Shed counter jumping across samples -> rate over the limit.
    state["shed"] = 500.0
    s.sample_now()
    rules = [a["rule"] for a in wd.check_now()]
    assert rules == ["shed_buildup"]


def test_ckpt_retry_storm_rule():
    s = TimeSeriesSampler(capacity=32)
    state = {"r": 0.0}
    s.add_source(lambda: {"ckpt_save_retries": state["r"]})
    wd = _watchdog(s, ckpt_retry_limit=3)
    s.sample_now()
    state["r"] = 1.0
    s.sample_now()
    assert wd.check_now() == []  # 1 retry: below the storm threshold
    state["r"] = 5.0
    s.sample_now()
    assert [a["rule"] for a in wd.check_now()] == ["ckpt_retry_storm"]


def test_heartbeat_stale_rule():
    class FakeHeartbeat:
        last_seen = {0: (10, time.time()), 1: (7, time.time() - 120.0)}

    wd = _watchdog(TimeSeriesSampler(), heartbeat=FakeHeartbeat(),
                   heartbeat_stale_s=60.0)
    fired = wd.check_now()
    assert [a["rule"] for a in fired] == ["heartbeat_stale"]
    assert "proc 1" in fired[0]["message"]


def test_hung_step_on_stalled_fake_trainer(tmp_path):
    """A trainer that completes steps then stalls trips hung_step within
    the deadline (k x rolling-median step time, floored), increments the
    pinned counter, writes the JSONL event log, and emits a tracer
    instant. New progress re-arms the rule."""
    now = [0.0]
    tr = SpanTracer(capacity=64, enabled=True)
    log = tmp_path / "alerts.jsonl"
    wd = _watchdog(TimeSeriesSampler(), tracer=tr, clock=lambda: now[0],
                   hung_step_min_s=1.0, hung_step_factor=10.0,
                   alert_log_path=str(log))
    before = _alert_count("hung_step")
    for step in range(1, 5):  # steps 0.1s apart -> median 0.1s
        now[0] += 0.1
        wd.notify_step(step)
    assert wd.check_now() == []  # just stepped: healthy
    assert wd.hung_step_deadline_s() == pytest.approx(1.0)  # floor wins
    now[0] += 1.5  # stall past the deadline
    fired = wd.check_now()
    assert [a["rule"] for a in fired] == ["hung_step"]
    assert fired[0]["last_step"] == 4
    assert _alert_count("hung_step") == before + 1
    assert wd.check_now() == []  # one alert per hang episode
    rows = [json.loads(l) for l in open(log)]
    assert rows[-1]["rule"] == "hung_step"
    assert any(e["name"] == "watchdog/alert" for e in tr.events())
    # Progress re-arms; a second stall fires a second alert.
    now[0] += 0.1
    wd.notify_step(5)
    assert wd.check_now() == []
    now[0] += 2.0
    assert [a["rule"] for a in wd.check_now()] == ["hung_step"]
    assert _alert_count("hung_step") == before + 2


def test_dump_escalation_invokes_flight_dump():
    calls = []
    s = TimeSeriesSampler()
    state = {"tps": 50.0}
    s.add_source(lambda: {"train_tokens_per_s": state["tps"]})
    wd = _watchdog(s, action="dump", throughput_min_samples=3,
                   throughput_floor_frac=0.5)
    wd._on_dump = calls.append
    for _ in range(4):
        s.sample_now()
    state["tps"] = 1.0
    s.sample_now()
    fired = wd.check_now()
    assert [a["rule"] for a in fired] == ["throughput_collapse"]
    assert len(calls) == 1 and calls[0]["rule"] == "throughput_collapse"


# ----------------------------------------------------------------------
# Flight recorder dumps
# ----------------------------------------------------------------------

def test_dump_complete_verified_and_rotated(tmp_path):
    tr = SpanTracer(capacity=4, enabled=True)
    for i in range(9):  # overflow the ring: droppedEvents must report 5
        tr.instant(f"e{i}")
    s = TimeSeriesSampler(capacity=8)
    s.add_source(lambda: {"v": 1.0})
    s.sample_now()
    cfg = Config()
    rec = FlightRecorder(str(tmp_path / "fr"), tracer=tr, sampler=s,
                         config=cfg, keep=2, min_interval_s=0.0)
    rec.add_metrics_source(lambda: {"m": 7})
    rec.note(phase="decode", step=41)
    rec.note(step=42, last_completed_step=42)
    paths = [rec.dump(reason=f"test_{i}") for i in range(3)]
    assert all(p is not None for p in paths)
    dumps = list_dumps(str(tmp_path / "fr"))
    assert len(dumps) == 2  # keep=2 rotated the oldest away
    assert verify_dump(dumps[-1]) == []
    data = load_dump(dumps[-1])
    ctx = data["context.json"]
    assert ctx["reason"] == "test_2"
    assert ctx["context"]["phase"] == "decode"  # later note kept earlier key
    assert ctx["context"]["step"] == 42
    assert ctx["config_fingerprint"]
    spans = data["spans.json"]
    assert spans["droppedEvents"] == 5
    assert [e["name"] for e in spans["traceEvents"]] == \
        ["e5", "e6", "e7", "e8"]
    assert data["metrics.json"]["m"] == 7
    assert data["timeseries.json"]["samples"][0]["values"] == {"v": 1.0}
    assert data["config.json"]["train"]["seed"] == cfg.train.seed
    # Dump-dir naming carries the step (flight-step<NNN>).
    assert os.path.basename(dumps[-1]).startswith("flight-step00000042")


def test_dump_throttles_but_force_wins(tmp_path):
    rec = FlightRecorder(str(tmp_path), tracer=SpanTracer(),
                         min_interval_s=60.0)
    assert rec.dump(reason="first") is not None
    assert rec.dump(reason="second") is None         # throttled
    assert rec.dump(reason="third", force=True) is not None
    # A damaged dump is detected (self-announcing forensics).
    target = list_dumps(str(tmp_path))[-1]
    with open(os.path.join(target, "metrics.json"), "a") as f:
        f.write(" ")
    assert any("metrics.json" in p for p in verify_dump(target))


def test_dump_never_raises(tmp_path):
    rec = FlightRecorder(str(tmp_path / "nope"), tracer=SpanTracer())
    rec.add_metrics_source(lambda: 1 / 0)  # broken source: counted, not fatal
    path = rec.dump(reason="broken_source")
    assert path is not None
    assert load_dump(path)["metrics.json"]["metrics_source_errors"] == 1
    # Unwritable directory: dump returns None instead of masking a fault.
    rec2 = FlightRecorder("/proc/definitely-not-writable/x",
                          tracer=SpanTracer())
    assert rec2.dump(reason="nowhere") is None


# ----------------------------------------------------------------------
# Chaos-fault dump through the real Trainer + postmortem round trip
# ----------------------------------------------------------------------

@pytest.fixture(scope="module")
def chaos_dump_dir(tmp_path_factory):
    """Tiny training run with the flight recorder + watchdog on, killed by
    the chaos injector (raise mode — in-process; the kill mode's SIGKILL
    drill lives in the slow subprocess test below)."""
    from dlti_tpu.training import Trainer
    from dlti_tpu.training.chaos import TrainFault

    tmp = tmp_path_factory.mktemp("flight")
    cfg = Config(
        model=CFG, lora=LoRAConfig(enabled=False),
        data=DataConfig(max_seq_len=16),
        checkpoint=CheckpointConfig(save_strategy="no"),
        train=TrainConfig(num_epochs=1, micro_batch_size=2,
                          grad_accum_steps=1, max_steps=4, logging_steps=100,
                          fault_inject_step="2:raise"),
        telemetry=TelemetryConfig(
            watchdog=WatchdogConfig(enabled=True, interval_s=0.05),
            flight_recorder=FlightRecorderConfig(dir=str(tmp))),
    )
    rng = np.random.default_rng(0)
    ids = [rng.integers(1, 500, (1, 2, 16), dtype=np.int32)
           for _ in range(5)]
    batches = [{"input_ids": a, "labels": a} for a in ids]
    try:
        with pytest.raises(TrainFault):
            Trainer(cfg).train(batches_per_epoch=batches)
    finally:
        configure_tracer(enabled=False)
        get_tracer().clear()
    return str(tmp)


def test_chaos_fault_leaves_complete_dump(chaos_dump_dir):
    dumps = list_dumps(chaos_dump_dir)
    assert len(dumps) == 1, dumps  # one incident, one dump (throttled)
    assert verify_dump(dumps[0]) == []
    data = load_dump(dumps[0])
    ctx = data["context.json"]
    assert ctx["reason"] == "chaos_raise"
    assert ctx["context"]["last_completed_step"] == 2
    assert ctx["context"]["phase"]
    assert ctx["context"]["role"] == "training"
    assert ctx["injected_at_step"] == 2
    # The span tail captured the real step phases (tracer force-enabled
    # by the recorder even without --trace-dir).
    names = {e["name"] for e in data["spans.json"]["traceEvents"]}
    assert {"train/batch_fetch", "train/step_dispatch",
            "train/device_sync"} <= names
    # Metrics + time series rode along.
    assert data["metrics.json"]["train_step"] == 2
    assert data["timeseries.json"]["samples"]


def test_postmortem_cli_round_trips_dump(chaos_dump_dir):
    dumps = list_dumps(chaos_dump_dir)
    env = dict(os.environ)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "postmortem.py"),
         chaos_dump_dir],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=60)
    assert r.returncode == 0, r.stderr[-1000:]
    out = r.stdout
    assert os.path.basename(dumps[0]) in out
    assert "chaos_raise" in out
    assert "last step:     2" in out
    assert "phase:" in out and "active at death" in out
    # Machine-readable mode parses and names the same facts.
    rj = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "postmortem.py"),
         dumps[0], "--json"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=60)
    assert rj.returncode == 0, rj.stderr[-1000:]
    summary = json.loads(rj.stdout)
    assert summary["last_completed_step"] == 2
    assert summary["reason"] == "chaos_raise"
    assert summary["phase_at_death"]
    assert summary["integrity_problems"] == []


# ----------------------------------------------------------------------
# Server surface: /debug/vars, /dashboard, /debug/profile, fault dump
# ----------------------------------------------------------------------

@pytest.fixture(scope="module")
def monitored_server(tmp_path_factory):
    from dlti_tpu.data.tokenizer import ByteTokenizer
    from dlti_tpu.models import LlamaForCausalLM
    from dlti_tpu.serving import EngineConfig, InferenceEngine, SamplingParams
    from dlti_tpu.serving.server import ServerConfig, make_server

    tmp = tmp_path_factory.mktemp("srv")
    model = LlamaForCausalLM(CFG, None)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    ec = EngineConfig(max_seqs=2, block_size=8, num_blocks=64,
                      max_model_len=64, cache_dtype="float32",
                      eos_token_id=-1)
    engine = InferenceEngine(CFG, params, ec)
    tel = TelemetryConfig(
        trace_dir=str(tmp / "traces"),
        watchdog=WatchdogConfig(enabled=True, interval_s=0.1),
        flight_recorder=FlightRecorderConfig(dir=str(tmp / "fr")))
    httpd, aeng = make_server(
        engine, ByteTokenizer(),
        ServerConfig(host="127.0.0.1", port=0,
                     default_params=SamplingParams(max_tokens=4),
                     telemetry=tel))
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield "127.0.0.1", port, httpd, engine, str(tmp)
    httpd.watchdog.stop()
    httpd.sampler.stop()
    httpd.shutdown()
    aeng.shutdown()
    httpd.server_close()
    from dlti_tpu.telemetry import install_recorder

    install_recorder(None)
    configure_tracer(enabled=False)
    get_tracer().clear()


def _get(host, port, path, timeout=60):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    conn.request("GET", path)
    resp = conn.getresponse()
    data = resp.read()
    ctype = resp.getheader("Content-Type", "")
    conn.close()
    return resp.status, data, ctype


def _post(host, port, path, body, timeout=120):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    conn.request("POST", path, json.dumps(body),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


def test_debug_vars_and_dashboard(monitored_server):
    host, port, httpd, engine, _ = monitored_server
    _post(host, port, "/v1/completions",
          {"prompt": "hi", "max_tokens": 3, "temperature": 0.0})
    # The ring samples on a cadence: wait until a sample *after* the
    # completion landed (latest can be one interval stale).
    deadline = time.time() + 10
    while time.time() < deadline and (
            len(httpd.sampler) < 2
            or (httpd.sampler.latest()["values"]
                .get("generated_tokens", 0)) < 3):
        time.sleep(0.05)
    st, data, ctype = _get(host, port, "/debug/vars")
    assert st == 200 and ctype.startswith("application/json")
    obj = json.loads(data)
    assert obj["num_samples"] >= 2
    assert obj["latest"]["generated_tokens"] >= 3
    assert "trace_dropped_events" in obj["latest"]
    st, data, _ = _get(host, port, "/debug/vars?tail=1")
    assert st == 200 and json.loads(data)["num_samples"] == 1
    st, data, ctype = _get(host, port, "/dashboard")
    assert st == 200 and ctype.startswith("text/html")
    page = data.decode()
    assert "/debug/vars" in page and "sparkline" in page
    assert "dlti_watchdog_alerts_total" in page  # alert banner wiring


def test_debug_trace_reports_dropped_events(monitored_server):
    host, port, *_ = monitored_server
    st, data, _ = _get(host, port, "/debug/trace")
    assert st == 200
    obj = json.loads(data)
    assert "droppedEvents" in obj and "traceEvents" in obj


def test_profile_capture_and_concurrent_409(monitored_server):
    host, port, _, _, tmp = monitored_server
    results = {}

    def long_capture():
        results["first"] = _post(host, port, "/debug/profile",
                                 {"seconds": 1.5})

    t = threading.Thread(target=long_capture)
    t.start()
    time.sleep(0.4)  # the first capture is mid-flight now
    st, data = _post(host, port, "/debug/profile", {"seconds": 0.1})
    assert st == 409, data
    t.join(timeout=120)
    st, data = results["first"]
    assert st == 200, data
    out = json.loads(data)
    assert out["status"] == "ok"
    assert os.path.isdir(out["trace_dir"])  # jax.profiler wrote here
    assert any(os.scandir(out["trace_dir"]))
    # Bad inputs: non-numeric and out-of-range both 400.
    assert _post(host, port, "/debug/profile", {"seconds": "x"})[0] == 400
    assert _post(host, port, "/debug/profile", {"seconds": 0})[0] == 400


def test_engine_step_fault_dumps_flight_record(monitored_server):
    from dlti_tpu.serving.sampling import SamplingParams

    host, port, httpd, engine, tmp = monitored_server
    before = len(list_dumps(os.path.join(tmp, "fr")))
    real_step = engine.step

    def flaky_step():
        raise RuntimeError("injected device fault")

    engine.step = flaky_step
    try:
        st, data = _post(host, port, "/v1/completions",
                         {"prompt": "zz", "max_tokens": 4})
        assert st == 500
    finally:
        engine.step = real_step
    dumps = list_dumps(os.path.join(tmp, "fr"))
    assert len(dumps) == before + 1
    data = load_dump(dumps[-1])
    assert data["context.json"]["reason"] == "engine_step_fault"
    assert "injected device fault" in data["context.json"]["exception"]
    assert data["context.json"]["context"]["role"] == "serving"
    assert verify_dump(dumps[-1]) == []


# ----------------------------------------------------------------------
# The honest drill: scripts/train.py kills ITSELF (SIGKILL, no Python
# teardown) and the pre-fire hook must still leave the black box.
# ----------------------------------------------------------------------

@pytest.mark.slow
def test_kill_chaos_leaves_dump_postmortem_renders(tmp_path):
    rng = np.random.default_rng(5)
    with open(tmp_path / "corpus.txt", "w") as f:
        for i in range(160):
            words = " ".join(f"w{int(w)}" for w in rng.integers(0, 50, 6))
            f.write(f"sample {i}: {words}\n")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_backend_optimization_level=0"
    flight = tmp_path / "fr"
    cmd = [
        sys.executable, os.path.join(REPO, "scripts", "train.py"),
        "--preset", "baseline", "--model", "llama_tiny",
        "--tokenizer", "byte",
        "--dataset-path", str(tmp_path / "corpus.txt"),
        "--output-dir", str(tmp_path / "ckpt"),
        "--max-seq-len", "32", "--per-device-batch-size", "2",
        "--gradient-accumulation-steps", "1", "--lora-r", "2",
        "--warmup-steps", "2", "--max-steps", "6", "--save-steps", "2",
        "--logging-steps", "1000",
        "--metrics-csv", str(tmp_path / "m.csv"),
        "--fault-inject-step", "3:kill",
        "--flight-dir", str(flight), "--watchdog",
    ]
    r = subprocess.run(cmd, env=env, cwd=REPO, capture_output=True,
                       text=True, timeout=420)
    assert r.returncode == -signal.SIGKILL, (r.returncode, r.stderr[-2000:])
    dumps = list_dumps(str(flight))
    assert dumps, "SIGKILL chaos left no flight record"
    assert verify_dump(dumps[-1]) == []
    data = load_dump(dumps[-1])
    assert data["context.json"]["reason"] == "chaos_kill"
    assert data["context.json"]["context"]["last_completed_step"] == 3
    pm = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "postmortem.py"),
         str(flight)],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=60)
    assert pm.returncode == 0, pm.stderr[-1000:]
    assert "chaos_kill" in pm.stdout
    assert "last step:     3" in pm.stdout
