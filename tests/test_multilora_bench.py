"""CI smoke for the multi-LoRA A/B microbench (satellite of the
multi-LoRA serving PR), mirroring tests/test_disagg_bench.py: the
artifact generator behind ``results/multilora_cpu.json`` must stay
runnable, and its equivalence claim must hold on a cold run — every
request's tokens byte-identical between the shared-base engine and the
per-adapter merged engines, with a genuinely heterogeneous batch on the
measured path. Throughput numbers are properties of the committed
artifact (quiet machine), not of this noisy smoke run, so the smoke pins
shape + equivalence + the weight-bytes arithmetic, not the margins."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "benchmarks_dev", "multilora_ab.py")


@pytest.mark.slow
def test_multilora_ab_bench_smoke(tmp_path):
    out = tmp_path / "multilora_cpu.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, BENCH, "--adapters", "4", "--requests", "12",
         "--max-tokens", "8", "--json-out", str(out)],
        env=env, capture_output=True, text=True, timeout=600, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-1500:]
    report = json.loads(out.read_text())

    # The bench itself asserts equivalence before writing; the report
    # must record it, and the batch must have been truly heterogeneous.
    assert report["outputs_equal"] is True
    assert report["max_concurrent_adapters"] >= 4
    # The consolidation arithmetic: one base + a small pool beats N full
    # merged copies, and the ledger-visible numbers are self-consistent.
    sw, mw = report["shared"]["weight_bytes"], report["merged"]["weight_bytes"]
    assert sw["total"] == sw["base"] + sw["adapter_pool"]
    assert mw["total"] == mw["per_replica"] * report["adapters"]
    assert sw["total"] < mw["total"]
    assert report["shared"]["pool"]["loads"] == report["adapters"]
    for key in ("benchmark", "platform", "adapters", "rank",
                "weight_bytes_saving_frac", "shared", "merged"):
        assert key in report, key


def test_committed_artifact_meets_the_bar():
    """The checked-in results/multilora_cpu.json is the PR's evidence;
    pin the acceptance bar (≥4 adapters concurrent on one engine,
    outputs_equal, lower total weight bytes) so a regenerated artifact
    that misses it fails CI instead of silently shipping."""
    path = os.path.join(REPO, "results", "multilora_cpu.json")
    report = json.loads(open(path).read())
    assert report["outputs_equal"] is True
    assert report["adapters"] >= 8
    assert report["max_concurrent_adapters"] >= 4
    assert (report["shared"]["weight_bytes"]["total"]
            < report["merged"]["weight_bytes"]["total"])
    assert report["weight_bytes_saving_frac"] >= 0.5
