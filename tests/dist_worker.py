"""Worker process for the REAL 2-process ``jax.distributed`` test.

Launched by ``scripts/launch.py --num-processes 2`` (the torchrun analog —
the capability the reference exercised with real multi-rank jobs,
``train.ipynb:640-653``). Each process owns 4 virtual CPU devices; the two
rendezvous over the DLTI_* env contract into one 8-device ZeRO-3 mesh and
train llama_tiny for a few steps on the SAME global batches a
single-process 8-device run consumes, so the test can assert loss
equality.

Data contract: every process builds the full deterministic global batch
and feeds its process-local row slice through
:func:`dlti_tpu.parallel.sharding.make_global_batch` (the production
multi-host assembly path). The committed host-shard *schedule*
(``HostShardedSchedule``) deliberately assigns different rows per host for
scalability, so this worker bypasses the dataset and slices the global
batch directly — the point here is numerical equivalence of the
distributed step, not the data schedule.

Usage: ``python tests/dist_worker.py OUT_JSON [n_steps] [strategy]``
(strategy: ``zero3`` (default), ``tp`` — ZeRO-3 fsdp=8, or fsdp=4 x
tensor=2 with the tensor axis spanning both processes, so TP's
row/column-parallel collectives really cross a process boundary — or
``pipe`` — data=4 x pipe=2 through the production Trainer: pipe stages
process-local (the ICI-like placement), batch rows sharded across the
hosts, the multi-host GPipe configuration r05 legalized.)
"""

import json
import os
import sys

_repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if os.path.isdir(os.path.join(_repo_root, "dlti_tpu")):
    sys.path.insert(0, _repo_root)

N_LOCAL_DEVICES = 4  # per process; 2 processes -> 8-device global mesh


def main() -> None:
    out_path = sys.argv[1]
    n_steps = int(sys.argv[2]) if len(sys.argv) > 2 else 4
    strategy = sys.argv[3] if len(sys.argv) > 3 else "zero3"

    flags = os.environ.get("XLA_FLAGS", "")
    os.environ["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count={N_LOCAL_DEVICES}")
    import jax

    jax.config.update("jax_platforms", "cpu")  # env alone loses to site hook
    jax.config.update("jax_default_matmul_precision", "highest")

    from dlti_tpu.launcher import maybe_initialize_from_env

    assert maybe_initialize_from_env(), "launcher env missing"
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 2 * N_LOCAL_DEVICES, jax.device_count()

    import numpy as np

    from dlti_tpu.config import (
        Config, DataConfig, LoRAConfig, MODEL_PRESETS, OptimizerConfig,
        ParallelConfig, TrainConfig, ZeROStage,
    )
    from dlti_tpu.models import LlamaForCausalLM
    from dlti_tpu.parallel import (
        build_mesh, make_sharded_train_step, shard_train_state,
    )
    from dlti_tpu.parallel.sharding import make_global_batch
    from dlti_tpu.training import build_optimizer, create_train_state

    parallel = {
        "zero3": ParallelConfig(zero_stage=ZeROStage.ZERO3, fsdp=8),
        # fsdp=4 x tensor=2: with (fsdp, tensor)-major device order the
        # tensor pairs are process-local while the fsdp axis spans both
        # processes — a mixed TP x FSDP mesh whose cross-process
        # collectives (param all-gathers / grad reduce-scatters) compose
        # with TP-sharded kernels. The pure-fsdp mode already proves
        # cross-process collectives; this mode proves the composition.
        "tp": ParallelConfig(zero_stage=ZeROStage.ZERO3, fsdp=4, tensor=2),
        # data=4 x pipe=2: data-major order keeps each pipe pair
        # process-local (the natural deployment: GPipe over ICI within a
        # host, DP across hosts) while batch rows shard across the two
        # processes — the multi-host pipeline configuration.
        "pipe": ParallelConfig(data=4, pipe=2),
    }[strategy]
    cfg = Config(
        model=MODEL_PRESETS["llama_tiny"],
        lora=LoRAConfig(r=4, alpha=8, dropout=0.0),
        optimizer=OptimizerConfig(warmup_steps=2),
        parallel=parallel,
        data=DataConfig(max_seq_len=32),
        train=TrainConfig(micro_batch_size=8, grad_accum_steps=2),
    )
    rng = jax.random.PRNGKey(0)
    if strategy == "pipe":
        # The production Trainer path: init_state converts to the stacked
        # pipe layout + shards it; _build_step routes to the GPipe step.
        from dlti_tpu.training.trainer import Trainer

        trainer = Trainer(cfg)
        mesh = trainer.mesh
        state = trainer.init_state(rng)
        step = trainer._build_step(state)
    else:
        model = LlamaForCausalLM(cfg.model, cfg.lora)
        tx = build_optimizer(cfg.optimizer)
        state = create_train_state(rng, model, tx, (2, 32), lora_enabled=True)
        mesh = build_mesh(cfg.parallel)
        state = shard_train_state(state, cfg, mesh)
        step = make_sharded_train_step(model, state, cfg, mesh, accum_steps=2,
                                       donate=False)

    # Deterministic global batch, identical on every process AND in the
    # single-process reference run (tests/test_distributed.py).
    accum, bs, seq = 2, 8, 32
    np_rng = np.random.default_rng(7)
    global_ids = np_rng.integers(
        0, cfg.model.vocab_size, (accum, bs, seq)).astype(np.int32)
    rows_per_proc = bs // jax.process_count()
    lo = jax.process_index() * rows_per_proc
    local = {
        "input_ids": global_ids[:, lo:lo + rows_per_proc],
        "loss_mask": np.ones((accum, rows_per_proc, seq), np.int32),
    }
    batch = make_global_batch(local, cfg, mesh)

    losses = []
    for i in range(n_steps):
        state, metrics = step(state, batch, jax.random.fold_in(rng, i))
        losses.append(float(jax.device_get(metrics["loss"])))

    if jax.process_index() == 0:
        with open(out_path, "w") as f:
            json.dump({"losses": losses,
                       "process_count": jax.process_count(),
                       "device_count": jax.device_count()}, f)
    # All ranks participate in a final barrier-ish sync so rank 1 doesn't
    # exit while rank 0 still owns in-flight collectives.
    jax.block_until_ready(jax.tree_util.tree_leaves(state.params)[0])


if __name__ == "__main__":
    main()
