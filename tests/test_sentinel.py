"""Numeric fault tolerance (``dlti_tpu.training.sentinel``) — tier 1.

Three layers, mirroring the subsystem's own split:

* **Detector units** — spike-window math (cold start, re-arm), streak
  accounting, skip-list strike/quarantine semantics and persistence,
  SDC digest + majority attribution, chaos-spec parsing and injection.
* **Step-level** — the bf16 nonfinite gate: a NaN batch through the real
  compiled step must skip the optimizer update (params/opt state
  unchanged) while the step counter (and so the lr/rng schedule)
  advances — the fp16 scaler's skip semantics, extended.
* **Trainer-level** — a transient NaN skips and the run continues;
  with rollback armed, the run restores the last verified checkpoint
  and finishes with a loss trajectory bit-identical to a clean run; a
  pre-quarantined window is skipped by the data feed.

The serving guard (nonfinite decode output → replica quarantine) is
tested here too; the full CLI/gloo drills live in
``tests/test_sentinel_drill.py`` (slow tier).
"""

import json
import math
import threading

import numpy as np
import pytest

from dlti_tpu.config import (
    CheckpointConfig, Config, DataConfig, LoRAConfig, MODEL_PRESETS,
    OptimizerConfig, SentinelConfig, TrainConfig,
)
from dlti_tpu.training.chaos import TrainFaultInjector
from dlti_tpu.training.sentinel import (
    DataSkipList, NumericSentinel, SDC_EXIT_CODE, SpikeDetector,
    attribute_suspects, replicated_param_digest,
)

CFG = MODEL_PRESETS["llama_tiny"]


# ----------------------------------------------------------------------
# Spike detector
# ----------------------------------------------------------------------

def test_spike_detector_cold_start():
    d = SpikeDetector(window=8, min_samples=4, factor=2.0)
    # Nothing fires before min_samples normal readings — even wild values.
    assert not d.update(1.0)
    assert not d.update(100.0)  # admitted: no baseline to judge it by
    assert not d.update(1.0)
    assert not d.ready          # 3 admitted < min_samples=4
    assert not d.update(1.0)
    assert d.ready


def test_spike_detector_window_math_and_rearm():
    d = SpikeDetector(window=8, min_samples=4, factor=2.0)
    for v in (1.0, 1.1, 0.9, 1.0):
        assert not d.update(v)
    assert d.update(2.5)      # > 2 x median(~1.0): spike
    # Re-arm semantics: the spike was NOT admitted, so the baseline is
    # intact — a consecutive spike still fires, and a normal value does
    # not.
    assert d.update(2.5)
    assert not d.update(1.05)
    assert math.isclose(d.median, 1.0, abs_tol=0.1)


def test_spike_detector_min_delta_floors_noise():
    d = SpikeDetector(window=8, min_samples=2, factor=2.0, min_delta=1.0)
    for v in (0.01, 0.012, 0.011):
        d.update(v)
    # 3x the median but the absolute move is microscopic: not a spike.
    assert not d.update(0.03)


def test_spike_detector_ignores_nonfinite():
    d = SpikeDetector(window=4, min_samples=2, factor=2.0)
    d.update(1.0)
    d.update(1.0)
    assert not d.update(float("nan"))
    assert not d.update(float("inf"))
    assert d.median == 1.0  # nonfinite never entered the window


# ----------------------------------------------------------------------
# Sentinel streaks
# ----------------------------------------------------------------------

def test_numeric_sentinel_streak_and_rollback_due():
    s = NumericSentinel(SentinelConfig(rollback_after=2, min_samples=2,
                                       window=4))
    v = s.observe(1, float("nan"), 1.0, skipped_update=True)
    assert v["kind"] == "nonfinite" and not v["rollback_due"]
    v = s.observe(2, 1.0, float("inf"), skipped_update=True)
    assert v["kind"] == "nonfinite" and v["rollback_due"]
    assert v["streak"] == [(1, "nonfinite"), (2, "nonfinite")]
    # A clean step resets the streak.
    v = s.observe(3, 1.0, 1.0, skipped_update=False)
    assert v["kind"] == "" and not v["rollback_due"] and s.streak == []
    assert s.counts["nonfinite"] == 2
    assert s.counts["skipped_updates"] == 2
    s.note_rollback()
    assert s.rollbacks == 1
    assert "sentinel_rollbacks" in s.scalars()


def test_numeric_sentinel_rollback_budget():
    s = NumericSentinel(SentinelConfig(max_rollbacks=2))
    assert not s.over_budget()
    s.note_rollback()
    s.note_rollback()
    assert s.over_budget()


# ----------------------------------------------------------------------
# Skip-list
# ----------------------------------------------------------------------

def test_skiplist_strike_quarantine_and_roundtrip():
    sl = DataSkipList(quarantine_after=2)
    assert sl.strike([5, 7], step=10) == []         # first strike: replay
    assert sl.quarantined() == set()
    assert sl.strike([7], step=12) == [7]           # second strike: out
    assert sl.quarantined() == {7}
    meta = sl.to_meta()
    sl2 = DataSkipList(quarantine_after=2)
    sl2.merge_meta(meta)
    assert sl2.quarantined() == {7}
    assert sl2.windows[5]["strikes"] == 1
    # Merge keeps max strikes and sticky quarantine.
    sl2.merge_meta([{"pos": 5, "strikes": 0, "quarantined": False}])
    assert sl2.windows[5]["strikes"] == 1
    sl2.merge_meta([{"pos": 9, "quarantined": True}])
    assert 9 in sl2.quarantined()


def test_skiplist_file_persistence(tmp_path):
    sl = DataSkipList(quarantine_after=1)
    sl.strike([3], step=4)
    sl.save(str(tmp_path))
    raw = json.load(open(tmp_path / DataSkipList.FILENAME))
    assert raw["windows"][0]["pos"] == 3
    sl2 = DataSkipList(quarantine_after=1)
    sl2.load(str(tmp_path))
    assert sl2.quarantined() == {3}
    # A missing/corrupt file is a silent no-op (best-effort persistence).
    sl3 = DataSkipList()
    sl3.load(str(tmp_path / "nope"))
    (tmp_path / "bad" ).mkdir()
    (tmp_path / "bad" / DataSkipList.FILENAME).write_text("{not json")
    sl3.load(str(tmp_path / "bad"))
    assert len(sl3) == 0


# ----------------------------------------------------------------------
# SDC digest + attribution
# ----------------------------------------------------------------------

def test_attribute_suspects_majority_and_tiebreak():
    a, b = b"A" * 32, b"B" * 32
    assert attribute_suspects([a, a, a]) == []
    assert attribute_suspects([a, a, b]) == [2]
    assert attribute_suspects([b, a, a]) == [0]
    # 2-rank split: no majority — rank 0 is the reference, rank 1 the
    # suspect (the documented blind spot: a corrupt rank 0 in a 2-rank
    # world misattributes; 3+ ranks vote it out).
    assert attribute_suspects([a, b]) == [1]
    # All distinct: rank 0 stays the reference.
    assert attribute_suspects([a, b, b"C" * 32]) == [1, 2]
    assert attribute_suspects([]) == []


def test_replicated_param_digest_detects_bit_flip():
    import jax
    import jax.numpy as jnp

    tree = {"w": jnp.arange(8, dtype=jnp.float32),
            "b": jnp.ones((4,), jnp.float32)}
    d1, n1 = replicated_param_digest(tree)
    assert n1 == 2
    d2, _ = replicated_param_digest(
        jax.tree_util.tree_map(lambda x: x + 0, tree))
    assert d1 == d2  # value-identical trees hash identically
    host = np.array(tree["w"])
    host.view(np.uint32)[0] ^= 1  # one mantissa bit
    d3, _ = replicated_param_digest({"w": jnp.asarray(host),
                                     "b": tree["b"]})
    assert d3 != d1


# ----------------------------------------------------------------------
# Chaos injectors
# ----------------------------------------------------------------------

def test_chaos_spec_parsing_numeric_modes():
    inj = TrainFaultInjector.from_spec("4:nan-grad")
    assert (inj.step, inj.mode) == (4, "nan-grad")
    inj = TrainFaultInjector.from_spec("10:poison-batch")
    assert (inj.step, inj.mode) == (10, "poison-batch")
    inj = TrainFaultInjector.from_spec("3:param-flip:1")
    assert (inj.step, inj.mode, inj.rank) == (3, "param-flip", 1)
    assert TrainFaultInjector.from_spec("3:param-flip").rank == 1
    # host-kill stays supervisor-owned; a RANK field on other modes is a
    # spec error, not a silent drop.
    assert TrainFaultInjector.from_spec("3:host-kill:1") is None
    with pytest.raises(ValueError):
        TrainFaultInjector.from_spec("3:nan-grad:1")
    with pytest.raises(ValueError):
        TrainFaultInjector.from_spec("3:frob")


def test_chaos_nan_grad_fires_once_and_copies():
    inj = TrainFaultInjector.from_spec("4:nan-grad")
    batch = {"input_ids": np.ones((1, 2, 8), np.int32),
             "loss_mask": np.ones((1, 2, 8), np.int32)}
    assert inj.maybe_corrupt_batch(2, 3, batch) is None  # step 3 < 4
    out = inj.maybe_corrupt_batch(3, 4, batch)
    assert out is not None
    assert np.isnan(out["loss_mask"]).all()
    assert (batch["loss_mask"] == 1).all()  # original never mutated
    assert inj.maybe_corrupt_batch(4, 5, batch) is None  # fires once


def test_chaos_poison_batch_keyed_by_position_and_refires():
    inj = TrainFaultInjector.from_spec("7:poison-batch")
    ids = np.arange(16, dtype=np.int32).reshape(1, 2, 8)
    batch = {"input_ids": ids, "loss_mask": np.ones_like(ids)}
    assert inj.maybe_corrupt_batch(6, 7, batch) is None   # wrong position
    p1 = inj.maybe_corrupt_batch(7, 8, batch)
    p2 = inj.maybe_corrupt_batch(7, 12, batch)  # REPLAY: re-poisons,
    assert p1 is not None and p2 is not None    # deterministically
    assert (p1["input_ids"] == p2["input_ids"]).all()
    assert not (p1["input_ids"] == ids).all()
    assert sorted(p1["input_ids"].ravel()) == sorted(ids.ravel())
    assert (batch["input_ids"] == ids).all()  # original never mutated


def test_chaos_param_flip_rank_gated_single_process():
    import jax.numpy as jnp

    from dlti_tpu.training.state import TrainState

    class _S:
        params = {"w": jnp.ones((4,), jnp.float32)}

        def replace(self, **kw):
            out = _S()
            out.params = kw.get("params", self.params)
            return out

    # rank defaults to 1; this process is rank 0 -> no flip, but the
    # injector still retires (one corruption event per spec).
    inj = TrainFaultInjector.from_spec("2:param-flip")
    assert inj.maybe_corrupt_state(2, _S()) is None
    assert inj.fired
    inj0 = TrainFaultInjector.from_spec("2:param-flip:0")
    flipped = inj0.maybe_corrupt_state(2, _S())
    assert flipped is not None
    d_before, _ = replicated_param_digest(_S().params)
    d_after, _ = replicated_param_digest(flipped.params)
    assert d_before != d_after
    # One mantissa bit: the numeric delta is tiny, the digest delta total.
    assert np.allclose(np.array(flipped.params["w"]), 1.0, atol=1e-5)


# ----------------------------------------------------------------------
# Step-level: the bf16 nonfinite gate
# ----------------------------------------------------------------------

def test_bf16_step_skips_nonfinite_update():
    import jax
    import jax.numpy as jnp

    from dlti_tpu.models import LlamaForCausalLM
    from dlti_tpu.training import build_optimizer, create_train_state
    from dlti_tpu.training.step import make_train_step

    model = LlamaForCausalLM(CFG, None)
    tx = build_optimizer(OptimizerConfig(warmup_steps=1))
    state = create_train_state(jax.random.PRNGKey(0), model, tx, (2, 16),
                               lora_enabled=False)
    step = jax.jit(make_train_step(model, accum_steps=1))
    rng = jax.random.PRNGKey(1)
    ids = np.random.default_rng(0).integers(
        1, CFG.vocab_size, (1, 2, 16)).astype(np.int32)
    good = {"input_ids": ids, "loss_mask": np.ones_like(ids)}
    nan_mask = np.full(ids.shape, np.nan, np.float32)
    bad = {"input_ids": ids, "loss_mask": nan_mask}

    state1, m1 = step(state, good, jax.random.fold_in(rng, 1))
    assert float(m1["nonfinite"]) == 0.0
    assert float(m1["skipped_update"]) == 0.0

    before = jax.device_get(state1.params)
    opt_before = jax.device_get(state1.opt_state)
    state2, m2 = step(state1, bad, jax.random.fold_in(rng, 2))
    assert float(m2["nonfinite"]) == 1.0
    assert float(m2["skipped_update"]) == 1.0
    assert not math.isfinite(float(m2["loss"]))
    # The update was SKIPPED: params and optimizer state are bit-equal.
    after = jax.device_get(state2.params)
    for a, b in zip(jax.tree_util.tree_leaves(before),
                    jax.tree_util.tree_leaves(after)):
        assert (np.asarray(a) == np.asarray(b)).all()
    for a, b in zip(jax.tree_util.tree_leaves(opt_before),
                    jax.tree_util.tree_leaves(jax.device_get(
                        state2.opt_state))):
        assert (np.asarray(a) == np.asarray(b)).all()
    # ...but the step counter advanced: the lr/rng schedule is a pure
    # function of the step index (skip is schedule-invariant).
    assert int(state2.step) == int(state1.step) + 1
    # And the next good step proceeds normally from the unpoisoned state.
    state3, m3 = step(state2, good, jax.random.fold_in(rng, 3))
    assert math.isfinite(float(m3["loss"]))
    assert float(m3["nonfinite"]) == 0.0


# ----------------------------------------------------------------------
# Trainer-level: skip, rollback, quarantine honoring
# ----------------------------------------------------------------------

def _train_cfg(tmp, fault="", sent=None, max_steps=8, step_log=""):
    from dlti_tpu.config import TelemetryConfig

    return Config(
        model=CFG, lora=LoRAConfig(r=2, alpha=4, dropout=0.0),
        optimizer=OptimizerConfig(warmup_steps=2),
        data=DataConfig(max_seq_len=32, prefetch_depth=0),
        checkpoint=CheckpointConfig(output_dir=str(tmp / "ck"),
                                    save_steps=2, save_total_limit=10),
        telemetry=TelemetryConfig(step_log_path=step_log),
        train=TrainConfig(num_epochs=1, max_steps=max_steps,
                          micro_batch_size=2, grad_accum_steps=1,
                          logging_steps=1000, fault_inject_step=fault,
                          sentinel=sent or SentinelConfig()),
    )


def _dataset():
    from dlti_tpu.data.pipeline import TokenBatchDataset

    rng = np.random.default_rng(0)
    seqs = [list(map(int, rng.integers(1, 500, 24))) for _ in range(32)]
    return TokenBatchDataset(sequences=seqs, seq_len=32, pad_id=0,
                             micro_batch_size=2, grad_accum_steps=1,
                             shuffle_seed=0, shard_by_host=False)


def _run(tmp, **kw):
    from dlti_tpu.training.trainer import Trainer

    t = Trainer(_train_cfg(tmp, **kw))
    state, rec = t.train(dataset=_dataset())
    return t, rec


@pytest.fixture(scope="module")
def clean_final_loss(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("clean")
    _, rec = _run(tmp)
    return rec.final_loss


def test_nan_grad_skips_update_and_steplog_records(tmp_path,
                                                  clean_final_loss):
    log = tmp_path / "steps.jsonl"
    t, rec = _run(tmp_path, fault="4:nan-grad", step_log=str(log))
    # Default rollback_after=3 > the single-step streak: no rollback —
    # the transient NaN cost one skipped update, nothing else.
    assert t._sentinel.rollbacks == 0
    assert t._sentinel.counts["nonfinite"] == 1
    assert t._sentinel.counts["skipped_updates"] == 1
    assert math.isfinite(rec.final_loss)
    rows = [json.loads(l) for l in open(log)]
    steps = {r["step"]: r for r in rows if r.get("type") == "step"}
    assert steps[4]["anomaly"] == "nonfinite"
    assert steps[4]["skipped_update"] == 1
    assert not math.isfinite(steps[4]["loss"])  # honest reporting
    assert steps[5]["anomaly"] == "" and steps[5]["skipped_update"] == 0
    assert steps[8]["rollbacks_total"] == 0


def test_nan_grad_rollback_matches_clean_run(tmp_path, clean_final_loss):
    t, rec = _run(tmp_path, fault="4:nan-grad",
                  sent=SentinelConfig(rollback_after=1))
    # One anomaly -> rollback to the verified step-2 checkpoint; the
    # replayed window is clean (transient fault), so the final loss is
    # BIT-IDENTICAL to a run that never faulted.
    assert t._sentinel.rollbacks == 1
    assert rec.final_loss == clean_final_loss
    # The implicated window got a strike but was NOT quarantined
    # (quarantine_after=2): transient faults replay.
    assert len(t._skiplist) == 1
    assert t._skiplist.quarantined() == set()
    # The skip-list persisted for crash recovery between saves.
    assert (tmp_path / "ck" / DataSkipList.FILENAME).exists()


def test_quarantined_window_is_skipped_on_resume(tmp_path):
    # Pre-seed the persistent skip-list (what a prior run's double
    # rollback would have written) and verify the data feed honors it:
    # the quarantined window never feeds a step, the feed moves on.
    ck = tmp_path / "ck"
    ck.mkdir()
    (ck / DataSkipList.FILENAME).write_text(json.dumps(
        {"format": 1, "windows": [{"pos": 2, "strikes": 2,
                                   "quarantined": True, "last_step": 9}]}))
    t, rec = _run(tmp_path, max_steps=6)
    assert t._live.get("sentinel_windows_skipped") == 1
    # All 6 steps executed (the feed substituted the next windows) and
    # the data cursor leads the step count by the skipped window.
    assert t._live["train_step"] == 6
    # Sidecar of the newest checkpoint carries the skip-list + cursor.
    from dlti_tpu.checkpoint import latest_verified_step, load_train_meta

    step = latest_verified_step(str(ck))
    meta = load_train_meta(str(ck), step)
    assert meta["data_pos"] == step + 1
    assert any(w["pos"] == 2 and w["quarantined"]
               for w in meta["skip_list"])


# ----------------------------------------------------------------------
# Watchdog rules
# ----------------------------------------------------------------------

def test_watchdog_sentinel_rules_fire_on_counter_growth():
    from dlti_tpu.config import WatchdogConfig
    from dlti_tpu.telemetry import AnomalyWatchdog, TimeSeriesSampler

    vals = {"sentinel_nonfinite_steps": 0, "sentinel_loss_spikes": 0,
            "sentinel_grad_spikes": 0, "sdc_mismatches": 0}
    sampler = TimeSeriesSampler(interval_s=60)
    sampler.add_source(lambda: dict(vals))
    wd = AnomalyWatchdog(WatchdogConfig(enabled=True), sampler)

    sampler.sample_now()
    assert wd.check_now() == []  # watermark init: no spurious alert
    vals["sentinel_nonfinite_steps"] = 2
    vals["sentinel_loss_spikes"] = 1
    sampler.sample_now()
    fired = wd.check_now()
    assert {a["rule"] for a in fired} == {"nonfinite_step", "loss_spike"}
    # Edge semantics: no growth -> no refire, and the rule re-arms.
    sampler.sample_now()
    assert wd.check_now() == []
    vals["sdc_mismatches"] = 1
    sampler.sample_now()
    assert {a["rule"] for a in wd.check_now()} == {"sdc_mismatch"}


# ----------------------------------------------------------------------
# Serving guard
# ----------------------------------------------------------------------

def _tiny_params():
    import jax
    import jax.numpy as jnp

    from dlti_tpu.models import LlamaForCausalLM

    model = LlamaForCausalLM(CFG, None)
    return model.init(jax.random.PRNGKey(0),
                      jnp.zeros((1, 8), jnp.int32))["params"]


def _nan_params(params):
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda x: jnp.full_like(x, jnp.nan)
        if jnp.issubdtype(x.dtype, jnp.inexact) else x, params)


def test_engine_guard_trips_on_nan_params_before_streaming():
    from dlti_tpu.serving import (
        EngineConfig, InferenceEngine, NumericFault, SamplingParams,
    )

    ec = EngineConfig(max_seqs=2, block_size=8, num_blocks=32,
                      max_model_len=64, cache_dtype="float32",
                      eos_token_id=-1)
    eng = InferenceEngine(CFG, _tiny_params(), ec)
    req = eng.submit([1, 2, 3], SamplingParams(max_tokens=6,
                                               temperature=0.0))
    eng.step()  # prefill + first token
    eng.step()  # a decode step
    n_before = len(req.output_token_ids)
    assert n_before >= 1
    eng.params = _nan_params(eng.params)
    with pytest.raises(NumericFault):
        for _ in range(4):
            eng.step()
    # No garbage token was appended after the poison.
    assert len(req.output_token_ids) <= n_before + 1
    assert all(math.isfinite(lp) for lp in req.output_logprobs)
    assert eng.stats["numeric_faults"] >= 1


def test_engine_guard_trips_on_nan_prefill():
    from dlti_tpu.serving import (
        EngineConfig, InferenceEngine, NumericFault, SamplingParams,
    )

    ec = EngineConfig(max_seqs=2, block_size=8, num_blocks=32,
                      max_model_len=64, cache_dtype="float32",
                      eos_token_id=-1)
    eng = InferenceEngine(CFG, _nan_params(_tiny_params()), ec)
    req = eng.submit([1, 2, 3], SamplingParams(max_tokens=4))
    with pytest.raises(NumericFault):
        eng.step()
    assert req.output_token_ids == []  # the garbage first token never landed


def test_nan_logits_replica_quarantined_zero_client_errors():
    """Serving acceptance: nonfinite logits on one replica of a 2-replica
    gateway fleet -> that replica is quarantined, clients see zero
    errors, and every streamed token matches a clean single-engine
    reference (no garbage reached a user)."""
    import jax

    from dlti_tpu.config import GatewayConfig
    from dlti_tpu.data.tokenizer import IdTokenizer
    from dlti_tpu.serving import (
        EngineConfig, InferenceEngine, ReplicatedEngine, SamplingParams,
    )
    from dlti_tpu.serving.server import ServerConfig, make_server

    devices = jax.devices()
    if len(devices) < 2:
        devices = [devices[0], devices[0]]
    ec = EngineConfig(max_seqs=4, block_size=8, num_blocks=128,
                      max_model_len=128, cache_dtype="float32",
                      eos_token_id=-1)
    params = _tiny_params()
    # Replica 0's params go NaN at its 3rd step: the engine's numeric
    # guard (not a synthetic raise) must detect and fail it over.
    rep = ReplicatedEngine(CFG, params, ec, replicas=2, tensor=1,
                           devices=devices[:2], max_retries=2,
                           fault_inject_step="0:3:nan-logits")
    httpd, aeng = make_server(
        rep, IdTokenizer(vocab_size=CFG.vocab_size),
        ServerConfig(host="127.0.0.1", port=0, request_timeout_s=120,
                     default_params=SamplingParams(max_tokens=8),
                     gateway=GatewayConfig(enabled=True,
                                           max_queued_requests=64)))
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    port = httpd.server_address[1]

    import http.client

    def post(body):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
        conn.request("POST", "/v1/completions", json.dumps(body),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        data = resp.read()
        conn.close()
        return resp.status, json.loads(data)

    try:
        prompts = [f"req {i}" for i in range(6)]
        results = [None] * len(prompts)

        def one(i):
            results[i] = post({"prompt": prompts[i], "max_tokens": 12,
                               "temperature": 0.0})

        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(len(prompts))]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=120)

        # Zero client-visible errors, full completions.
        for i, r in enumerate(results):
            assert r is not None and r[0] == 200, (i, r)
            assert r[1]["usage"]["completion_tokens"] == 12, r[1]

        # The poisoned replica was quarantined by the NUMERIC guard.
        assert rep.num_live == 1
        assert rep.failover["replica_faults"] == 1
        assert rep.stats["numeric_faults"] >= 1
        assert rep.failover["retries"] >= 1

        # No garbage tokens streamed: every completion is byte-identical
        # to a clean single-engine greedy reference.
        clean = InferenceEngine(CFG, params, ec)
        tok = IdTokenizer(vocab_size=CFG.vocab_size)
        for i, r in enumerate(results):
            ref = clean.generate([tok.encode(prompts[i], add_bos=True)],
                                 SamplingParams(max_tokens=12,
                                                temperature=0.0))[0]
            assert r[1]["choices"][0]["text"] == tok.decode(
                ref.output_token_ids), i
    finally:
        httpd.shutdown()
        if httpd.gateway is not None:
            httpd.gateway.shutdown()
        aeng.shutdown()
        httpd.server_close()


def test_replica_fault_spec_parsing():
    from dlti_tpu.serving.replicas import _parse_fault_inject

    assert _parse_fault_inject("") is None
    assert _parse_fault_inject("0:3") == (0, 3, "raise")
    assert _parse_fault_inject("1:5:nan-logits") == (1, 5, "nan-logits")
    with pytest.raises(ValueError):
        _parse_fault_inject("1:5:frob")


def test_sdc_exit_code_is_distinctive():
    from dlti_tpu.telemetry.watchdog import ABORT_EXIT_CODE

    assert SDC_EXIT_CODE not in (0, 1, 2, ABORT_EXIT_CODE)
    assert SDC_EXIT_CODE < 128  # clear of shell signal-death encodings
