"""bench.py driver contract (the r03 postmortem, pinned).

The driver parses bench.py's LAST stdout line as JSON and records the
exit code. Whatever happens — unreachable backend, bad env config, a
wedged relay — there must be exactly ONE JSON line and a meaningful rc,
within a bounded time. r03 lost its round's perf verification to a
silent rc=124; these tests keep that failure mode dead.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _run(env_extra, timeout=120):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.update(env_extra)
    proc = subprocess.run([sys.executable, BENCH], env=env,
                          capture_output=True, text=True, timeout=timeout,
                          cwd=REPO)
    lines = [l for l in proc.stdout.strip().splitlines() if l.strip()]
    return proc, lines


def test_unreachable_backend_fails_with_json_by_deadline():
    """Backend init failure -> error JSON + nonzero exit by the deadline
    (r05: the probe retries until DEADLINE_S - MIN_SLACK_S so a mid-window
    relay recovery is caught; a dead backend still ends in rc=3 + JSON,
    never the r03 silent 50-minute burn)."""
    proc, lines = _run({"JAX_PLATFORMS": "bogus",
                        "BENCH_PROBE_TIMEOUT": "30",
                        "BENCH_DEADLINE_S": "60",
                        "BENCH_MIN_SLACK_S": "10"})
    assert proc.returncode == 3, proc.stderr[-500:]
    assert len(lines) == 1, lines
    out = json.loads(lines[0])
    assert out["value"] == 0.0
    assert "probe failed" in out["error"]


def test_bad_env_config_emits_json():
    """A config typo must not burn candidates or exit silently."""
    proc, lines = _run({"JAX_PLATFORMS": "cpu", "BENCH_MODEL": "llama_tiny",
                        "BENCH_QUANT": "int4"})
    assert proc.returncode == 2, proc.stderr[-500:]
    out = json.loads(lines[-1])
    assert "BENCH_QUANT" in out["error"]


@pytest.mark.slow
def test_happy_path_single_json_line():
    """CPU run on the tiny preset: rc=0 and exactly one parseable JSON
    line with the driver-contract keys."""
    proc, lines = _run({"JAX_PLATFORMS": "cpu", "BENCH_MODEL": "llama_tiny",
                        "BENCH_BS": "2", "BENCH_SEQ": "64",
                        "BENCH_STEPS": "2"}, timeout=300)
    assert proc.returncode == 0, proc.stderr[-800:]
    assert len(lines) == 1, lines
    out = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in out
    assert out["value"] > 0


@pytest.mark.slow
def test_watchdog_deadline_emits_json():
    """A deadline hit mid-run still produces one JSON line and a
    diagnosable error instead of rc=124."""
    proc, lines = _run({"JAX_PLATFORMS": "cpu", "BENCH_SKIP_PROBE": "1",
                        "BENCH_DEADLINE_S": "5", "BENCH_MODEL": "llama_tiny",
                        "BENCH_BS": "2", "BENCH_SEQ": "64"}, timeout=180)
    assert proc.returncode in (4, 5), (proc.returncode, proc.stderr[-500:])
    out = json.loads(lines[-1])
    assert "error" in out


def test_gateway_metric_names_are_schema_stable():
    """The dlti_gateway_* exposition names are a scrape contract like the
    legacy dlti_<stat> names: renaming one silently breaks external
    dashboards, so the full set is pinned here."""
    from dlti_tpu.serving.gateway import GATEWAY_METRIC_NAMES

    assert GATEWAY_METRIC_NAMES == (
        "dlti_gateway_queue_depth",
        "dlti_gateway_queued_tokens",
        "dlti_gateway_inflight",
        "dlti_gateway_replicas_alive",
        "dlti_gateway_admitted_total",
        "dlti_gateway_rejected_total",
        "dlti_gateway_shed_total",
        "dlti_gateway_retries_total",
        "dlti_gateway_replica_faults_total",
        "dlti_gateway_affinity_sticky_total",
        "dlti_gateway_affinity_spill_total",
    )


def test_prefix_cache_metric_names_are_schema_stable():
    """Tiered prefix-cache telemetry names are a scrape contract like the
    gateway set: per-tier (tier="hbm" | "host" | "disk") hit / miss /
    eviction / promotion / demotion counters plus the per-tier block
    gauge, all registered by the server registry."""
    from dlti_tpu.serving import prefix_cache as pc

    assert pc.PREFIX_CACHE_METRIC_NAMES == (
        "dlti_prefix_cache_hits_total",
        "dlti_prefix_cache_misses_total",
        "dlti_prefix_cache_evictions_total",
        "dlti_prefix_cache_promotions_total",
        "dlti_prefix_cache_demotions_total",
        "dlti_prefix_cache_blocks",
    )
    assert pc.hits_total.name == pc.PREFIX_CACHE_METRIC_NAMES[0]
    assert pc.misses_total.name == pc.PREFIX_CACHE_METRIC_NAMES[1]
    assert pc.evictions_total.name == pc.PREFIX_CACHE_METRIC_NAMES[2]
    assert pc.promotions_total.name == pc.PREFIX_CACHE_METRIC_NAMES[3]
    assert pc.demotions_total.name == pc.PREFIX_CACHE_METRIC_NAMES[4]
    assert pc.blocks_gauge.name == pc.PREFIX_CACHE_METRIC_NAMES[5]


def test_host_overlap_metric_names_are_schema_stable():
    """Host-latency-hiding telemetry names are a scrape contract like the
    gateway set: the training prefetcher's gauge/histogram, the engine's
    decode host-prep histogram, and the decode-state upload counters
    (exposed via the engine stats scalar source as dlti_<key>)."""
    from dlti_tpu.data.prefetch import PREFETCH_METRIC_NAMES

    assert PREFETCH_METRIC_NAMES == (
        "dlti_train_prefetch_queue_depth",
        "dlti_train_prefetch_stall_seconds",
    )

    from dlti_tpu.telemetry import RequestTelemetry

    tel = RequestTelemetry()
    assert [h.name for h in tel.histograms()] == [
        "dlti_request_ttft_seconds",
        "dlti_request_tpot_seconds",
        "dlti_request_queue_time_seconds",
        "dlti_decode_host_prep_seconds",
    ]

    # Engine stats keys ride the /metrics scalar source (dlti_ prefix):
    # dlti_decode_state_uploads / _rows / _clean_syncs.
    from dlti_tpu.serving.decode_state import DecodeStateCache

    stats: dict = {}
    DecodeStateCache(2, stats=stats)
    assert set(stats) == {"decode_state_uploads", "decode_state_rows",
                          "decode_state_clean_syncs"}


def test_ckpt_metric_names_are_schema_stable():
    """Checkpoint-robustness telemetry names are a scrape contract like
    the gateway and prefetch sets: save/restore duration histograms, the
    corrupt-quarantine and save-retry counters, and the
    last-verified-step gauge."""
    from dlti_tpu.checkpoint import CKPT_METRIC_NAMES
    from dlti_tpu.checkpoint import store

    assert CKPT_METRIC_NAMES == (
        "dlti_ckpt_save_seconds",
        "dlti_ckpt_restore_seconds",
        "dlti_ckpt_corrupt_skipped",
        "dlti_ckpt_save_retries",
        "dlti_ckpt_last_verified_step",
    )
    assert store.save_seconds.name == CKPT_METRIC_NAMES[0]
    assert store.restore_seconds.name == CKPT_METRIC_NAMES[1]
    assert store.corrupt_skipped.name == CKPT_METRIC_NAMES[2]
    assert store.save_retries.name == CKPT_METRIC_NAMES[3]
    assert store.last_verified_step.name == CKPT_METRIC_NAMES[4]


def test_watchdog_and_flight_metric_names_are_schema_stable():
    """Self-monitoring telemetry names are a scrape contract like the
    gateway/prefetch/ckpt sets: the watchdog's per-rule alert counter,
    the flight recorder's dump counter, and the tracer's ring-eviction
    counter exposed by the server registry."""
    from dlti_tpu.telemetry import FLIGHT_METRIC_NAMES, WATCHDOG_METRIC_NAMES
    from dlti_tpu.telemetry import flightrecorder, watchdog

    assert WATCHDOG_METRIC_NAMES == ("dlti_watchdog_alerts_total",)
    assert FLIGHT_METRIC_NAMES == ("dlti_flight_dumps_total",)
    assert watchdog.alerts_total.name == WATCHDOG_METRIC_NAMES[0]
    assert flightrecorder.dumps_total.name == FLIGHT_METRIC_NAMES[0]
    # The watchdog rule set is part of the alert-counter label contract
    # (dashboards filter by rule=...).
    assert watchdog.RULES == (
        "hung_step", "throughput_collapse", "queue_buildup",
        "shed_buildup", "heartbeat_stale", "ckpt_retry_storm",
        "nonfinite_step", "loss_spike", "sdc_mismatch",
        "goodput_collapse", "hbm_pressure", "disk_pressure",
        "replica_flap", "slo_burn", "canary_regression",
    )


def test_slo_metric_names_are_schema_stable():
    """SLO gauge names are a scrape contract like the watchdog/gateway
    sets: compliance, error-budget-remaining, and windowed burn rate,
    all (objective, class)-labeled and registered by the server
    registry."""
    from dlti_tpu.telemetry import SLO_METRIC_NAMES
    from dlti_tpu.telemetry import slo

    assert SLO_METRIC_NAMES == (
        "dlti_slo_compliance",
        "dlti_slo_error_budget_remaining",
        "dlti_slo_burn_rate",
    )
    assert slo.compliance_gauge.name == SLO_METRIC_NAMES[0]
    assert slo.budget_remaining_gauge.name == SLO_METRIC_NAMES[1]
    assert slo.burn_rate_gauge.name == SLO_METRIC_NAMES[2]
    # The default burn tiers are the SRE fast/slow pairing dashboards
    # and runbooks key on; changing them re-tunes every deployment.
    assert slo.DEFAULT_BURN_TIERS == "14:60:5,6:300:30"
    assert slo.parse_burn_tiers(slo.DEFAULT_BURN_TIERS) == (
        (14.0, 60.0, 5.0), (6.0, 300.0, 30.0))


def test_disk_metric_names_are_schema_stable():
    """Durable-writer health names are a scrape contract like the
    watchdog/ckpt sets: the free-bytes gauge plus the path_class-labeled
    write-error counter and degraded gauge, all registered by the server
    registry and watched by the disk_pressure rule."""
    from dlti_tpu.utils import durable_io

    assert durable_io.DISK_METRIC_NAMES == (
        "dlti_disk_free_bytes",
        "dlti_disk_write_errors_total",
        "dlti_disk_degraded",
    )
    assert durable_io.free_bytes_gauge.name == \
        durable_io.DISK_METRIC_NAMES[0]
    assert durable_io.write_errors_total.name == \
        durable_io.DISK_METRIC_NAMES[1]
    assert durable_io.degraded_gauge.name == durable_io.DISK_METRIC_NAMES[2]
    # The path-class set is the degradation-policy contract (the README
    # criticality table and the AST guard's covered modules key on it).
    assert durable_io.PATH_CLASSES == (
        "checkpoint", "adapter", "prefix_tier", "flight", "fleet_runtime",
        "steplog", "elastic", "sentinel", "watchdog",
    )


def test_lifecycle_metric_names_are_schema_stable():
    """Replica-lifecycle telemetry names are a scrape contract like the
    watchdog/disk sets: the self-healing counters (quarantine, reinstate,
    flap eviction, live migration + fallback) and the per-replica state
    gauge, all registered by the server registry and watched by the
    replica_flap rule."""
    from dlti_tpu.serving import lifecycle

    assert lifecycle.LIFECYCLE_METRIC_NAMES == (
        "dlti_replica_lifecycle_quarantines_total",
        "dlti_replica_lifecycle_reinstates_total",
        "dlti_replica_lifecycle_flaps_total",
        "dlti_replica_lifecycle_migrations_total",
        "dlti_replica_lifecycle_migration_fallbacks_total",
        "dlti_replica_state",
    )
    assert lifecycle.quarantines_total.name == \
        lifecycle.LIFECYCLE_METRIC_NAMES[0]
    assert lifecycle.reinstates_total.name == \
        lifecycle.LIFECYCLE_METRIC_NAMES[1]
    assert lifecycle.flaps_total.name == lifecycle.LIFECYCLE_METRIC_NAMES[2]
    assert lifecycle.migrations_total.name == \
        lifecycle.LIFECYCLE_METRIC_NAMES[3]
    assert lifecycle.migration_fallbacks_total.name == \
        lifecycle.LIFECYCLE_METRIC_NAMES[4]
    assert lifecycle.replica_state_gauge.name == \
        lifecycle.LIFECYCLE_METRIC_NAMES[5]
    # The state set is the replica_state gauge's value contract
    # (dashboards map code -> label via STATES order).
    assert lifecycle.STATES == (
        "live", "quarantined", "probing", "draining", "evicted",
    )


def test_deploy_metric_names_are_schema_stable():
    """Continuous-delivery telemetry names are a scrape contract like
    the lifecycle/watchdog sets: the candidate/canary/promote/rollback/
    refuse counters the canary_regression rule and release dashboards
    key on, plus the incumbent-step gauge, all registered by the server
    registry."""
    from dlti_tpu.serving import deploy

    assert deploy.DEPLOY_METRIC_NAMES == (
        "dlti_deploy_candidates_total",
        "dlti_deploy_canaries_total",
        "dlti_deploy_promotions_total",
        "dlti_deploy_rollbacks_total",
        "dlti_deploy_rejected_total",
        "dlti_deploy_incumbent_step",
    )
    assert deploy.candidates_total.name == deploy.DEPLOY_METRIC_NAMES[0]
    assert deploy.canaries_total.name == deploy.DEPLOY_METRIC_NAMES[1]
    assert deploy.promotions_total.name == deploy.DEPLOY_METRIC_NAMES[2]
    assert deploy.rollbacks_total.name == deploy.DEPLOY_METRIC_NAMES[3]
    assert deploy.rejected_total.name == deploy.DEPLOY_METRIC_NAMES[4]
    assert deploy.incumbent_step_gauge.name == \
        deploy.DEPLOY_METRIC_NAMES[5]


def test_fleet_metric_names_are_schema_stable():
    """Multi-process fleet telemetry names are a scrape contract: the
    wire-layer frame/byte counters (labeled by frame kind) and the
    supervisor's live-worker gauge + respawn counter, all federated into
    the serving registry and cross-checked by loadgen's federation
    report."""
    from dlti_tpu.serving import fleet, wire

    assert wire.WIRE_METRIC_NAMES == (
        "dlti_fleet_frames_total",
        "dlti_fleet_wire_bytes_total",
    )
    assert wire.frames_total.name == wire.WIRE_METRIC_NAMES[0]
    assert wire.wire_bytes_total.name == wire.WIRE_METRIC_NAMES[1]

    assert fleet.FLEET_METRIC_NAMES == (
        "dlti_fleet_workers_alive",
        "dlti_fleet_respawns_total",
    )
    assert fleet.workers_alive_gauge.name == fleet.FLEET_METRIC_NAMES[0]
    assert fleet.respawns_total.name == fleet.FLEET_METRIC_NAMES[1]
    # The per-worker key sets are the federation contract: counter keys
    # must sum across workers to the fleet-level dlti_{key} totals
    # (loadgen's federation report asserts this at scrape time).
    assert fleet.WORKER_COUNTER_KEYS == (
        "requests", "generated_tokens", "prefill_tokens",
        "preemptions", "decode_steps",
    )
    assert fleet.WORKER_GAUGE_KEYS == (
        "up", "active", "waiting", "free_blocks",
    )


def test_trace_metric_names_are_schema_stable():
    """Distributed-tracing federation names are a scrape contract:
    spans adopted from fleet workers, spans arriving without request or
    trace parentage, and the per-worker clock-offset gauge the rebasing
    used — registered unconditionally by build_registry so the series
    exist (at zero) even on single-process engines."""
    from dlti_tpu.telemetry import distributed_trace as dt

    assert dt.TRACE_METRIC_NAMES == (
        "dlti_trace_federated_spans_total",
        "dlti_trace_unparented_spans_total",
        "dlti_trace_clock_offset_seconds",
    )
    assert dt.federated_spans_total.name == dt.TRACE_METRIC_NAMES[0]
    assert dt.unparented_spans_total.name == dt.TRACE_METRIC_NAMES[1]
    assert dt.clock_offset_gauge.name == dt.TRACE_METRIC_NAMES[2]


def test_spec_metric_names_are_schema_stable():
    """Speculative-decode telemetry names are a scrape contract: raw
    draft-economics counters (proposed/accepted draft tokens, paused
    slot-rounds) plus the derived acceptance-rate and adaptive
    draft-length gauges, registered by build_registry's spec scalar
    source and scraped into LoadReport.spec by loadgen."""
    from dlti_tpu.serving.engine import SPEC_METRIC_NAMES

    assert SPEC_METRIC_NAMES == (
        "dlti_spec_proposed_total",
        "dlti_spec_accepted_total",
        "dlti_spec_paused_rounds_total",
        "dlti_spec_acceptance_rate",
        "dlti_spec_draft_len",
    )


def test_sentinel_metric_names_are_schema_stable():
    """Numeric-fault-sentinel telemetry names are a scrape contract like
    the watchdog/ckpt sets: anomaly/skip/rollback/quarantine counters and
    the cross-rank SDC probe counters, all registered by the server
    registry for /dashboard."""
    from dlti_tpu.training import sentinel

    assert sentinel.SENTINEL_METRIC_NAMES == (
        "dlti_sentinel_anomalies_total",
        "dlti_sentinel_skipped_updates_total",
        "dlti_sentinel_rollbacks_total",
        "dlti_sentinel_quarantined_windows_total",
    )
    assert sentinel.SDC_METRIC_NAMES == (
        "dlti_sdc_probes_total",
        "dlti_sdc_mismatches_total",
    )
    assert sentinel.anomalies_total.name == sentinel.SENTINEL_METRIC_NAMES[0]
    assert sentinel.skipped_updates_total.name == \
        sentinel.SENTINEL_METRIC_NAMES[1]
    assert sentinel.rollbacks_total.name == sentinel.SENTINEL_METRIC_NAMES[2]
    assert sentinel.quarantined_windows_total.name == \
        sentinel.SENTINEL_METRIC_NAMES[3]
    assert sentinel.sdc_probes_total.name == sentinel.SDC_METRIC_NAMES[0]
    assert sentinel.sdc_mismatches_total.name == sentinel.SDC_METRIC_NAMES[1]
    # The suspect-rank exit code is a supervisor-attribution contract
    # (clear of shell/signal codes and the watchdog's abort 86).
    assert sentinel.SDC_EXIT_CODE == 87


def test_steplog_sentinel_fields_are_schema_stable():
    """The per-step JSONL stream's sentinel triple (what an incident
    reader greps first) is part of the step-record contract."""
    from dlti_tpu.telemetry.steplog import STEP_RECORD_FIELDS

    assert {"anomaly", "skipped_update", "rollbacks_total"} <= set(
        STEP_RECORD_FIELDS)


def test_steplog_goodput_fields_are_schema_stable():
    """The goodput-ledger per-phase durations (data/prefetch stall,
    device sync, checkpoint, rollback+replay) are part of the step-record
    contract: trajectory tooling attributes slow steps by these keys."""
    from dlti_tpu.telemetry.steplog import STEP_RECORD_FIELDS

    assert {"data_wait_s", "sync_s", "ckpt_s", "rollback_s"} <= set(
        STEP_RECORD_FIELDS)


def test_ledger_metric_names_are_schema_stable():
    """Goodput-ledger + critical-path attribution names are a scrape
    contract like the watchdog/ckpt sets; the bucket and phase label
    sets are parsing contracts (postmortem, steplog, /debug/slow)."""
    from dlti_tpu.telemetry import ledger

    assert ledger.LEDGER_METRIC_NAMES == (
        "dlti_goodput_fraction",
        "dlti_goodput_seconds_total",
        "dlti_goodput_mfu_percent",
    )
    assert ledger.REQUEST_PHASE_METRIC_NAMES == (
        "dlti_request_phase_seconds_total",
        "dlti_request_phase_requests_total",
    )
    assert ledger.goodput_fraction_gauge.name == \
        ledger.LEDGER_METRIC_NAMES[0]
    assert ledger.goodput_seconds_total.name == \
        ledger.LEDGER_METRIC_NAMES[1]
    assert ledger.goodput_mfu_gauge.name == ledger.LEDGER_METRIC_NAMES[2]
    assert ledger.phase_seconds_total.name == \
        ledger.REQUEST_PHASE_METRIC_NAMES[0]
    assert ledger.phase_requests_total.name == \
        ledger.REQUEST_PHASE_METRIC_NAMES[1]
    assert ledger.GOODPUT_BUCKETS == (
        "startup", "step_compute", "device_sync", "data_wait",
        "host_to_device", "eval", "checkpoint_save", "checkpoint_restore",
        "rollback", "replay", "sdc_probe", "shutdown", "other",
    )
    assert ledger.SUPERVISOR_BUCKETS == ("restart_downtime",)
    assert ledger.PRODUCTIVE_BUCKETS == ("step_compute", "device_sync")
    assert ledger.REQUEST_PHASES == (
        "gateway_queue", "queue", "tier_restore", "prefill",
        "failover", "preempt", "kv_handoff", "decode", "other",
    )


def test_memledger_metric_names_are_schema_stable():
    """HBM memory-ledger names are a scrape contract like the
    watchdog/ckpt sets: the per-owner bytes gauge (label owner=...) plus
    the peak / headroom / untracked gauges, all registered by the server
    registry; the owner set is the attribution-label contract
    (dashboards and scripts/memory_plan.py key on it)."""
    from dlti_tpu.telemetry import memledger

    assert memledger.MEMLEDGER_METRIC_NAMES == (
        "dlti_hbm_bytes",
        "dlti_hbm_peak_bytes",
        "dlti_hbm_headroom_bytes",
        "dlti_hbm_untracked_bytes",
    )
    assert memledger.hbm_bytes_gauge.name == \
        memledger.MEMLEDGER_METRIC_NAMES[0]
    assert memledger.hbm_peak_gauge.name == \
        memledger.MEMLEDGER_METRIC_NAMES[1]
    assert memledger.hbm_headroom_gauge.name == \
        memledger.MEMLEDGER_METRIC_NAMES[2]
    assert memledger.hbm_untracked_gauge.name == \
        memledger.MEMLEDGER_METRIC_NAMES[3]
    assert memledger.MEMORY_OWNERS == (
        "params", "optimizer_state", "grad_buffers", "kv_block_pool",
        "prefix_cache_hbm", "decode_state_cache", "prefetch_buffers",
        "kv_handoff_staging", "lora_adapters", "chaos_balloon",
    )


def test_adapter_metric_names_are_schema_stable():
    """Multi-LoRA serving telemetry names are a scrape contract like the
    prefix-cache set: adapter load/evict counters, pool hit/miss
    counters, and the pool slot/byte gauges, all registered by the
    server registry."""
    from dlti_tpu.serving import adapters

    assert adapters.ADAPTER_METRIC_NAMES == (
        "dlti_adapter_loads_total",
        "dlti_adapter_evictions_total",
        "dlti_adapter_pool_hits_total",
        "dlti_adapter_pool_misses_total",
        "dlti_adapter_pool_slots",
        "dlti_adapter_pool_bytes",
    )
    assert adapters.loads_total.name == adapters.ADAPTER_METRIC_NAMES[0]
    assert adapters.evictions_total.name == adapters.ADAPTER_METRIC_NAMES[1]
    assert adapters.pool_hits_total.name == adapters.ADAPTER_METRIC_NAMES[2]
    assert adapters.pool_misses_total.name == \
        adapters.ADAPTER_METRIC_NAMES[3]
    assert adapters.pool_slots_gauge.name == adapters.ADAPTER_METRIC_NAMES[4]
    assert adapters.pool_bytes_gauge.name == adapters.ADAPTER_METRIC_NAMES[5]


def test_disagg_metric_names_are_schema_stable():
    """Disaggregated-serving names are a scrape contract like the gateway
    set: per-pool liveness/queue/active gauges plus the KV-handoff
    counters and latency histogram (registered by the server registry
    when the engine is a DisaggController)."""
    from dlti_tpu.serving import disagg

    assert disagg.POOL_METRIC_NAMES == (
        "dlti_pool_prefill_replicas_alive",
        "dlti_pool_decode_replicas_alive",
        "dlti_pool_prefill_waiting",
        "dlti_pool_decode_waiting",
        "dlti_pool_prefill_active",
        "dlti_pool_decode_active",
    )
    assert disagg.KV_HANDOFF_METRIC_NAMES == (
        "dlti_kv_handoff_total",
        "dlti_kv_handoff_bytes_total",
        "dlti_kv_handoff_staged",
        "dlti_kv_handoff_fallbacks_total",
        "dlti_kv_handoff_sheds_total",
        "dlti_kv_handoff_seconds",
    )
    assert disagg.handoff_seconds.name == disagg.KV_HANDOFF_METRIC_NAMES[5]
    # Every pool_scalars key must expose as one of the pinned names.
    exposed = {f"dlti_{k}" for k in disagg.POOL_GAUGE_KEYS} | {
        "dlti_kv_handoff_total", "dlti_kv_handoff_bytes_total",
        "dlti_kv_handoff_fallbacks_total", "dlti_kv_handoff_sheds_total"}
    assert exposed == set(disagg.POOL_METRIC_NAMES
                          + disagg.KV_HANDOFF_METRIC_NAMES) - {
        "dlti_kv_handoff_seconds"}


def test_steplog_hbm_fields_are_schema_stable():
    """The per-step JSONL stream's memory pair (what an OOM incident
    reader greps first) is part of the step-record contract."""
    from dlti_tpu.telemetry.steplog import STEP_RECORD_FIELDS

    assert {"hbm_bytes_in_use", "hbm_headroom_bytes"} <= set(
        STEP_RECORD_FIELDS)


def test_heartbeat_metric_names_are_schema_stable():
    """The per-rank last-step and straggler-lag gauges are a scrape
    contract (dashboards plot which rank trails by how much)."""
    from dlti_tpu.telemetry.heartbeat import HEARTBEAT_METRIC_NAMES

    assert HEARTBEAT_METRIC_NAMES == (
        "dlti_heartbeat_last_step",
        "dlti_heartbeat_lag_steps",
    )


def test_elastic_metric_names_are_schema_stable():
    """Elastic-training telemetry names are a scrape contract like the
    watchdog/ckpt sets: the supervisor's restart counter and the
    generation / live-world gauges every generation's workers re-set."""
    from dlti_tpu.training import elastic

    assert elastic.ELASTIC_METRIC_NAMES == (
        "dlti_elastic_restarts_total",
        "dlti_elastic_generation",
        "dlti_elastic_world_size",
    )
    assert elastic.restarts_total.name == elastic.ELASTIC_METRIC_NAMES[0]
    assert elastic.generation_gauge.name == elastic.ELASTIC_METRIC_NAMES[1]
    assert elastic.world_size_gauge.name == elastic.ELASTIC_METRIC_NAMES[2]
    # The rendezvous env extension is part of the launcher contract too.
    assert elastic.ENV_GENERATION == "DLTI_GENERATION"
    assert elastic.ENV_ELASTIC_DIR == "DLTI_ELASTIC_DIR"
    assert elastic.ENV_NUM_SLOTS == "DLTI_ELASTIC_NUM_SLOTS"


def test_debug_vars_and_dump_surface_contract():
    """Keys consumers parse: the /debug/vars envelope (loadgen end-of-run
    scrape, the dashboard page) and the flight-dump file set
    (scripts/postmortem.py)."""
    from dlti_tpu.telemetry import TimeSeriesSampler
    from dlti_tpu.telemetry.flightrecorder import DUMP_FILES, MANIFEST

    snap = TimeSeriesSampler().snapshot()
    assert {"now", "interval_s", "capacity", "num_samples",
            "source_errors", "latest", "samples"} <= set(snap)
    assert DUMP_FILES == ("context.json", "spans.json", "metrics.json",
                          "timeseries.json", "config.json", "memory.json",
                          "slo.json", "deploy.json")
    assert MANIFEST == "MANIFEST.json"


def test_load_report_schema_includes_gateway_fields():
    """scripts/benchmark_serving.py consumers parse the report JSON by
    key; the multi-tenant/priority additions are part of that schema."""
    import dataclasses

    from dlti_tpu.benchmarks.loadgen import LoadReport

    fields = {f.name for f in dataclasses.fields(LoadReport)}
    required = {
        # Legacy report contract.
        "num_requests", "num_ok", "duration_s", "requests_per_s",
        "output_tokens_per_s", "latency_p50_s", "latency_p90_s",
        "latency_p99_s", "ttft_p50_s", "ttft_p90_s", "ttft_p99_s",
        "tpot_mean_ms", "errors", "server_histograms",
        # Gateway-era additions: shed accounting + per-class breakdown.
        "num_shed", "shed_rate", "per_class",
        # Watchdog-era additions: the server's own anomaly verdict from
        # the end-of-run /debug/vars scrape.
        "watchdog_alerts", "peak_queue_depth",
        # Recurring-session (prefix-tiering) additions: cold-vs-warm TTFT
        # split + the server-scraped cache hit rate.
        "num_cold", "num_warm", "cold_ttft_p50_s", "cold_ttft_p90_s",
        "warm_ttft_p50_s", "warm_ttft_p90_s", "cache_hit_rate",
        # Goodput-ledger era: server-reported critical-path phase means,
        # overall and decomposed cold-vs-warm (TTFT by phase).
        "phase_means", "cold_phases", "warm_phases",
        # Memory-ledger era: end-of-run /debug/memory scrape (owner
        # attribution + headroom).
        "memory",
        # Disaggregation era: mixed-interference mode's decode-TPOT split
        # by concurrent-long-prefill overlap.
        "interference",
        # Multi-LoRA era: per-adapter latency breakdown + the
        # server-scraped adapter-pool hit rate.
        "per_adapter", "adapter_pool_hit_rate",
        # Replica-lifecycle era: tail-of-the-tail percentiles plus the
        # per-run migration/retry disturbance totals.
        "ttft_p999_s", "tpot_p999_ms", "migrations_total", "retries_total",
        # SLO era: the /debug/slo scrape cross-checked against the
        # client's own records (server/client/agreement sections).
        "slo",
        # Adaptive-spec era: end-of-run speculative-decode economics
        # (proposed/accepted/paused totals + acceptance-rate and
        # draft-length gauges) from the /metrics scrape.
        "spec",
        # Distributed-tracing era: fraction of sampled ok requests whose
        # merged /debug/trace?request_id= timeline carries the
        # gateway + prefill + decode legs.
        "trace_coverage",
    }
    missing = required - fields
    assert not missing, f"LoadReport lost contract fields: {missing}"


def test_percentile_linear_interpolation():
    """_percentile interpolates between closest ranks (numpy's default
    method) — nearest-rank rounding snapped p99 and p99.9 to the same
    max sample at bench-sized n, hiding tail regressions."""
    from dlti_tpu.benchmarks.loadgen import _percentile

    xs = [1.0, 2.0, 3.0, 4.0]
    assert _percentile(xs, 0) == 1.0
    assert _percentile(xs, 100) == 4.0
    assert _percentile(xs, 50) == 2.5
    assert _percentile(xs, 25) == 1.75
    hundred = [float(i) for i in range(1, 101)]
    assert abs(_percentile(hundred, 99) - 99.01) < 1e-9
    assert abs(_percentile(hundred, 99.9) - 99.901) < 1e-9
    # p99 and p99.9 must now be distinguishable at n=100.
    assert _percentile(hundred, 99.9) > _percentile(hundred, 99)
    # Degenerate cases: single sample (any p) and empty.
    assert _percentile([0.25], 50) == 0.25
    assert _percentile([], 99) == 0.0


def test_per_class_summary_keys():
    """Per-priority-class breakdown keys (consumed by report tooling)."""
    from dlti_tpu.benchmarks.loadgen import RequestRecord, _class_summary

    rec = RequestRecord(start=0.0, end=1.0, first_token=0.25,
                        output_tokens=8, ok=True, status=200,
                        priority="interactive")
    shed = RequestRecord(start=0.0, end=0.1, ok=False, status=429,
                         priority="interactive", error="HTTP 429")
    summary = _class_summary([rec, shed])
    assert set(summary) == {
        "count", "ok", "shed", "latency_p50_s", "latency_p99_s",
        "ttft_p50_s", "ttft_p90_s", "ttft_p99_s", "tpot_mean_ms",
        "tpot_p99_ms",
    }
    assert summary["count"] == 2 and summary["ok"] == 1
    assert summary["shed"] == 1
    assert summary["ttft_p50_s"] == 0.25
