"""Orchestration (experiment matrix, SLURM emission) + launcher tests.

Reference analog: the L4 notebook matrix (``train.ipynb`` cells 5-33) and
the torchrun/deepspeed launcher contract (SURVEY.md §2d) — which the
reference never tests at all (§4).
"""

import os
import stat
import subprocess
import sys
import time

import pytest

from dlti_tpu.launcher import (
    ENV_COORDINATOR, ENV_NUM_PROCESSES, ENV_PROCESS_ID,
    first_slurm_node, launch_local, slurm_env,
)
from dlti_tpu.orchestration import (
    ExperimentSpec, build_command, emit_slurm, plan_matrix, run_matrix,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Heavy jit-compile tier: excluded from the fast pre-commit gate
# (`pytest -m 'not slow'`); the full suite runs them.
pytestmark = pytest.mark.slow


# ---------------------------------------------------------------- matrix plan

def test_plan_matrix_baseline_is_single_device():
    specs = plan_matrix(["baseline", "zero2"], [1, 2, 4])
    names = [s.name for s in specs]
    # baseline appears once (reference train_baseline.py is 1-GPU only);
    # zero2 fans out over every device count (the notebook's --num_gpus loop).
    assert names == ["baseline", "zero2_1dev", "zero2_2dev", "zero2_4dev"]


def test_plan_matrix_rejects_unknown_strategy():
    with pytest.raises(ValueError, match="unknown strategy"):
        plan_matrix(["zero9"], [1])


def test_build_command_flag_mapping():
    cmd = build_command(
        ExperimentSpec("zero3", 4, tensor=2),
        {"max_steps": 3, "pack": True, "no_resume": False, "model": "llama_tiny"},
        python="PY", train_script="train.py")
    assert cmd[:2] == ["PY", "train.py"]
    assert ("--preset", "zero3") == (cmd[2], cmd[3])
    assert ("--num-devices", "4") == (cmd[4], cmd[5])
    assert "--tensor" in cmd and cmd[cmd.index("--tensor") + 1] == "2"
    assert "--sequence" not in cmd          # extent 1 is elided
    assert cmd[cmd.index("--max-steps") + 1] == "3"
    assert "--pack" in cmd                  # true boolean -> bare flag
    assert "--no-resume" not in cmd         # false boolean -> omitted
    assert cmd[cmd.index("--model") + 1] == "llama_tiny"


def test_run_matrix_dry_run_executes_nothing(tmp_path, capsys):
    specs = plan_matrix(["zero1"], [2])
    results = run_matrix(specs, {"model": "llama_tiny"}, dry_run=True,
                         metrics_csv=str(tmp_path / "m.csv"),
                         output_root=str(tmp_path), log_dir=None)
    assert results[0]["returncode"] is None
    assert "zero1" in capsys.readouterr().out
    assert not (tmp_path / "m.csv").exists()


def test_run_matrix_records_failure_and_continues(tmp_path):
    """A crashed cell is recorded and the matrix keeps going — the
    notebook's own semantics (its 2-GPU NCCL crash is preserved in-tree,
    train.ipynb:794-838, and later cells still ran)."""
    ok = tmp_path / "ok.py"
    ok.write_text("import sys; sys.exit(0)\n")
    specs = [ExperimentSpec("zero1", 1), ExperimentSpec("zero2", 1)]

    # The fake trainer crashes only for the zero1 run (sniffs --preset).
    script = tmp_path / "fake_train.py"
    script.write_text(
        "import sys\n"
        "sys.exit(7 if 'zero1' in sys.argv[sys.argv.index('--preset')+1] else 0)\n")
    results = run_matrix(specs, {}, metrics_csv=str(tmp_path / "m.csv"),
                         output_root=str(tmp_path / "ckpt"),
                         log_dir=str(tmp_path / "logs"), analyze=False,
                         train_script=str(script))
    assert [r["returncode"] for r in results] == [7, 0]
    # per-run log files in the reference's logs/*.out|err layout
    assert (tmp_path / "logs" / "zero1_1dev.out").exists()
    assert (tmp_path / "logs" / "zero2_1dev.err").exists()


# ---------------------------------------------------------------- slurm emit

def test_emit_slurm_writes_sbatch_and_submit(tmp_path):
    specs = plan_matrix(["zero3"], [8])
    paths = emit_slurm(specs, {"model": "llama2_7b"},
                       out_dir=str(tmp_path / "slurm"), hosts_per_pod=4,
                       partition="tpu", time_limit="04:00:00")
    assert len(paths) == 1
    body = open(paths[0]).read()
    assert "#SBATCH --job-name=zero3_8dev" in body
    assert "#SBATCH --nodes=4" in body
    assert "#SBATCH --partition=tpu" in body
    assert "#SBATCH --time=04:00:00" in body
    assert "srun" in body and "--coordinator-from-slurm" in body
    assert "--preset zero3" in body and "--num-devices 8" in body
    submit = tmp_path / "slurm" / "submit_all.sh"
    assert submit.exists()
    assert stat.S_IXUSR & os.stat(submit).st_mode
    assert "sbatch zero3_8dev.sbatch" in submit.read_text()


# ------------------------------------------------------------------ launcher

def test_launch_local_env_contract(tmp_path):
    """Every rank sees the rendezvous env (the LOCAL_RANK/WORLD_SIZE analog)."""
    probe = tmp_path / "probe.py"
    probe.write_text(
        "import os, pathlib\n"
        f"d = {str(tmp_path)!r}\n"
        "pid = os.environ['DLTI_PROCESS_ID']\n"
        "pathlib.Path(d, 'rank'+pid).write_text(\n"
        "    os.environ['DLTI_COORDINATOR'] + ' ' + os.environ['DLTI_NUM_PROCESSES'])\n")
    rc = launch_local([sys.executable, str(probe)], 3, port=29555)
    assert rc == 0
    for i in range(3):
        assert (tmp_path / f"rank{i}").read_text() == "127.0.0.1:29555 3"


def test_launch_local_failure_kills_stragglers(tmp_path):
    """First failing rank terminates the rest (torchrun sigkill semantics)."""
    script = tmp_path / "worker.py"
    script.write_text(
        "import os, sys, time\n"
        "if os.environ['DLTI_PROCESS_ID'] == '1':\n"
        "    sys.exit(3)\n"
        "time.sleep(60)\n")
    t0 = time.perf_counter()
    rc = launch_local([sys.executable, str(script)], 2)
    assert rc == 3
    assert time.perf_counter() - t0 < 30  # did not wait out the sleep(60)


def test_first_slurm_node_parsing():
    assert first_slurm_node("hosta,hostb") == "hosta"
    assert first_slurm_node("tpu-host[003-006,009]") == "tpu-host003"
    assert first_slurm_node("nid[07,09-12]") == "nid07"
    assert first_slurm_node("single") == "single"


def test_slurm_env_mapping():
    env = slurm_env({"SLURM_JOB_NODELIST": "tpu[01-04]", "SLURM_NTASKS": "4",
                     "SLURM_PROCID": "2"}, port=1234)
    assert env[ENV_COORDINATOR] == "tpu01:1234"
    assert env[ENV_NUM_PROCESSES] == "4"
    assert env[ENV_PROCESS_ID] == "2"


def test_run_experiments_cli_dry_run():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "run_experiments.py"),
         "--dry-run", "--strategies", "baseline,zero3", "--device-counts", "2",
         "--model", "llama_tiny", "--tokenizer", "byte",
         "--dataset-path", "ds", "--max-steps", "2", "--log-dir", ""],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    assert "--preset baseline" in out.stdout
    assert "--preset zero3" in out.stdout and "--num-devices 2" in out.stdout
