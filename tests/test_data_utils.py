"""Data pipeline + utils tests: golden format strings, tokenize/pack
determinism, metrics CSV schema, experiment naming."""

import csv
import os

import numpy as np
import pytest

from dlti_tpu.data import (
    ByteTokenizer,
    format_conversation_for_llama2,
    make_batches,
    tokenize_and_truncate,
)
from dlti_tpu.data.pipeline import pack_sequences, pad_to_batch
from dlti_tpu.utils import (
    MetricsRecord,
    create_experiment_name,
    get_zero_stage_from_config,
    print_metrics_summary,
    save_training_metrics,
)
from dlti_tpu.utils.metrics import compute_mfu


def test_llama2_format_golden():
    """Byte-exact parity with scripts/prepare_dataset.py:12-25."""
    out = format_conversation_for_llama2(
        {"question": "  How do I sort a list? ", "answer": " Use sorted(). "}
    )
    assert out == {"text": "<s>[INST] How do I sort a list? [/INST] Use sorted().</s>"}


def test_byte_tokenizer_roundtrip():
    tok = ByteTokenizer()
    text = "hello wörld"
    ids = tok.encode(text, add_bos=True, add_eos=True)
    assert ids[0] == tok.bos_id and ids[-1] == tok.eos_id
    assert tok.decode(ids) == text


def test_tokenize_truncates_at_512():
    tok = ByteTokenizer()
    seqs = tokenize_and_truncate(["x" * 1000], tok, max_seq_len=512)
    assert len(seqs[0]) == 512


def test_pad_to_batch_masks():
    ids, mask = pad_to_batch([[5, 6], [7, 8, 9]], seq_len=4, pad_id=0)
    np.testing.assert_array_equal(ids, [[5, 6, 0, 0], [7, 8, 9, 0]])
    np.testing.assert_array_equal(mask, [[1, 1, 0, 0], [1, 1, 1, 0]])


def test_pack_sequences_segments():
    ids, mask, segs = pack_sequences([[1, 2], [3, 4], [5, 6, 7, 8, 9]], seq_len=5, pad_id=0)
    assert ids.shape[1] == 5
    # Docs 1 and 2 pack into one row with distinct segment ids.
    assert segs[0].tolist() == [1, 1, 2, 2, 0]
    assert ids[1].tolist() == [5, 6, 7, 8, 9]
    assert mask[0].tolist() == [1, 1, 1, 1, 0]


def test_batches_shape_and_determinism():
    tok = ByteTokenizer()
    texts = [f"sample number {i}" for i in range(20)]
    ds = make_batches(texts, tok, seq_len=16, micro_batch_size=2,
                      grad_accum_steps=2, shard_by_host=False)
    batches1 = list(ds.epoch(0))
    batches2 = list(ds.epoch(0))
    assert len(batches1) == ds.steps_per_epoch() == 5
    assert batches1[0]["input_ids"].shape == (2, 2, 16)
    np.testing.assert_array_equal(batches1[0]["input_ids"], batches2[0]["input_ids"])
    # Different epoch -> different order.
    batches3 = list(ds.epoch(1))
    assert not all(
        np.array_equal(a["input_ids"], b["input_ids"])
        for a, b in zip(batches1, batches3)
    )


def test_experiment_name_parity():
    """Doctest cases from training/utils.py:22-28 (dev for device)."""
    assert create_experiment_name(1, None) == "baseline"
    assert create_experiment_name(1, 0) == "baseline"
    assert create_experiment_name(2, 1) == "zero1_2dev"
    assert create_experiment_name(4, 3) == "zero3_4dev"


def test_zero_stage_from_config(tmp_path):
    ds_style = tmp_path / "ds.json"
    ds_style.write_text('{"zero_optimization": {"stage": 2}}')
    assert get_zero_stage_from_config(str(ds_style)) == 2
    ours = tmp_path / "ours.json"
    from dlti_tpu.config import preset

    ours.write_text(preset("zero3_8dev").to_json())
    assert get_zero_stage_from_config(str(ours)) == 3


def test_metrics_csv_schema(tmp_path):
    """CSV columns match the reference schema (train_baseline.py:246-255)
    plus the TPU additions."""
    path = str(tmp_path / "m.csv")
    rec = MetricsRecord(
        experiment="zero2_8dev", num_gpus=8, zero_stage=2, strategy="zero2",
        training_time_hours=0.5, samples_per_second=12.0, peak_memory_gb=3.2,
        final_loss=0.71, tokens_per_second_per_chip=800.0, mfu_percent=41.0,
    )
    save_training_metrics(rec, path)
    save_training_metrics(rec, path)
    with open(path) as f:
        rows = list(csv.DictReader(f))
    assert len(rows) == 2
    ref_cols = ["experiment", "num_gpus", "zero_stage", "strategy",
                "training_time_hours", "samples_per_second", "peak_memory_gb",
                "final_loss"]
    assert list(rows[0].keys())[: len(ref_cols)] == ref_cols
    print_metrics_summary(rec)  # smoke


def test_mfu_formula():
    # 1000 tok/s/chip on a 7e9-param LoRA model at 197 TFLOP/s:
    # 4*7e9*1000 / 197e12 = 14.2%
    mfu = compute_mfu(1000, 7_000_000_000, 197e12, trainable_params=17_000_000)
    np.testing.assert_allclose(mfu, 100 * 4 * 7e9 * 1000 / 197e12, rtol=1e-6)


def test_config_roundtrip():
    from dlti_tpu.config import Config, preset

    cfg = preset("zero2_8dev", model="llama_debug")
    back = Config.from_json(cfg.to_json())
    assert back == cfg


def test_native_packer_matches_python_oracle(monkeypatch):
    """C++ pack assignment == pure-Python packing, bit for bit."""
    import os
    import numpy as np

    from dlti_tpu.data.pipeline import pack_sequences
    from dlti_tpu.utils import native as native_mod

    if native_mod.load_native_runtime() is None or not hasattr(
            native_mod.load_native_runtime(), "dlti_pack_assign"):
        import pytest
        pytest.skip("native runtime not built")

    rng = np.random.default_rng(0)
    seqs = [list(map(int, rng.integers(1, 100, rng.integers(1, 40))))
            for _ in range(300)]
    got = pack_sequences(seqs, seq_len=64, pad_id=0, open_rows=8)

    # Force the Python path for the oracle.
    monkeypatch.setenv("DLTI_DISABLE_NATIVE", "1")
    native_mod._TRIED = False
    native_mod._LIB = None
    try:
        want = pack_sequences(seqs, seq_len=64, pad_id=0, open_rows=8)
    finally:
        monkeypatch.delenv("DLTI_DISABLE_NATIVE")
        native_mod._TRIED = False
        native_mod._LIB = None
    for g, w in zip(got, want):
        np.testing.assert_array_equal(g, w)


def test_pack_sequences_drops_empty_docs():
    import numpy as np

    from dlti_tpu.data.pipeline import pack_sequences

    ids, mask, segs = pack_sequences([[5], [], [7]], seq_len=4, pad_id=0)
    np.testing.assert_array_equal(ids[0, :2], [5, 7])
    np.testing.assert_array_equal(segs[0, :2], [1, 2])
